"""Sparse/CTR training benchmark (BASELINE.json flagship config #4:
DeepFM / wide-deep CTR with high-dim sparse tables — the workload the
reference served with SparseRemoteParameterUpdater + SparseRowMatrix
(RemoteParameterUpdater.h:265, math/SparseRowMatrix.h:206); here the
embedding is a vocab-shardable jax table, gathers ride XLA, and the
question is what actually bounds a step at 10M-row scale).

Measures rows/s for wide_deep with a 10M-row embedding table (plus
1M/100k/10k auxiliary fields, criteo-ish 13 dense features) under three
optimizers that isolate the suspected bottleneck — the dense optimizer
moment sweep over the big tables:

  sgd        — no optimizer state: the only table traffic is gather +
               scatter-add grads (update touches rows... but XLA applies
               dense w - lr*g over the full table: still a full sweep)
  adam       — dense fused sweep: reads w,m,v + writes w,m,v every step
  adam_lazy  — Adam(lazy_mode=True): gather/scatter moment update on the
               touched rows only (re-validating the round-4 negative
               result at 10M-row scale, where the dense sweep costs
               ~2 GB/step of HBM traffic and lazy SHOULD win)

Methodology: pinned compiled-window form — one `Executor.run_steps(K)`
dispatch per timed window, feeds staged on device once, median of 3
windows, completion forced by a scalar fetch (axon block_until_ready
returns early).  Writes benchmark/ctr_results.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt                      # noqa: E402
from paddle_tpu import layers, models        # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "ctr_results.json")

VOCABS = [10_000_000, 1_000_000, 100_000, 10_000]
EMB_DIM = 16
DENSE_D = 13
BATCH = 4096


def _build(optimizer):
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    ids = [layers.data(f"id{i}", shape=[1], dtype="int64")
           for i in range(len(VOCABS))]
    dense = layers.data("dense", shape=[DENSE_D], dtype="float32")
    label = layers.data("label", shape=[1], dtype="float32")
    pred = models.wide_deep(ids, dense, VOCABS, emb_dim=EMB_DIM)
    loss = layers.mean(layers.log_loss(pred, label))
    optimizer.minimize(loss)
    return loss


def _feeds(rng):
    f = {f"id{i}": rng.randint(0, v, (BATCH, 1))
         for i, v in enumerate(VOCABS)}
    f["dense"] = rng.rand(BATCH, DENSE_D).astype("float32")
    f["label"] = (rng.rand(BATCH, 1) < 0.3).astype("float32")
    return f


def bench_variant(name, optimizer, iters=100, reps=3):
    import jax

    rng = np.random.RandomState(0)
    loss = _build(optimizer)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {k: jax.device_put(v) for k, v in _feeds(rng).items()}
    # warmup compiles the SAME scan length as the timed windows
    (lv,) = exe.run_steps(iters, feed=feeds, fetch_list=[loss],
                          return_numpy=False)
    if not np.isfinite(float(np.asarray(lv)[-1])):
        raise FloatingPointError(f"{name}: non-finite warmup loss")
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        (lv,) = exe.run_steps(iters, feed=feeds, fetch_list=[loss],
                              return_numpy=False)
        last = float(np.asarray(lv)[-1])     # completion barrier
        times.append(time.perf_counter() - t0)
    if not np.isfinite(last):
        raise FloatingPointError(f"{name}: non-finite timed loss")
    med = float(np.median(times)) / iters
    row = {"variant": name, "ms_per_step": round(med * 1e3, 3),
           "rows_per_sec": round(BATCH / med),
           "spread_pct": round(100 * (max(times) - min(times))
                               / np.median(times), 2)}
    print(json.dumps(row), flush=True)
    return row


def main():
    import jax

    # analytic accounting for the expected regimes, printed next to data:
    # dense Adam sweep traffic/step = 3 reads + 3 writes of every table
    table_bytes = 4 * sum(v * (EMB_DIM + 1) for v in VOCABS)
    rows = {"device": str(jax.devices()[0]),
            "batch": BATCH, "vocabs": VOCABS, "emb_dim": EMB_DIM,
            "table_bytes": table_bytes,
            "expected_dense_sweep_ms_at_675GBps":
                round(6 * table_bytes / 675e9 * 1e3, 2),
            "variants": []}
    for name, opt in [
        ("sgd", pt.optimizer.SGD(learning_rate=0.1)),
        ("adam_dense", pt.optimizer.Adam(learning_rate=1e-3)),
        ("adam_lazy", pt.optimizer.Adam(learning_rate=1e-3,
                                        lazy_mode=True)),
    ]:
        rows["variants"].append(bench_variant(name, opt))
    with open(OUT, "w") as f:
        json.dump(rows, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
