#!/usr/bin/env python
"""Giant-embedding CTR benchmark: host-resident sparse parameter server
vs a dense device-resident embedding (paddle_tpu.sparse; ROADMAP item
4(a); the reference capability is the pserver sparse-row path —
SparseRemoteParameterUpdater.h:265, math/SparseRowMatrix.h:206).

The configuration declares a **device HBM embedding budget** and a vocab
whose full dense table EXCEEDS it (the giant-embedding regime: the table
cannot live on one device, so it lives on the host and each step pulls
only the rows a batch touches).  Measured rows, all REAL and in-container
(CPU; the TPU row is a pending-hardware stub per the PR 1 convention):

* ``examples_per_sec`` — wide&deep-style CTR training throughput,
  host-sparse table vs the dense-embedding control (same model, same
  feed stream, pinned window form: median of K-step windows);
* ``lookup_latency_ms`` — p50/p99 of per-batch deduped row pulls;
* ``push_rows_per_sec`` — sparse-update throughput (host-side per-row
  Adagrad applied to the pushed gradient rows);
* ``cache`` — hot-rows cache hit rate under a zipfian id distribution
  (read-only serving-style traffic);
* ``doctor`` — the PR 10 measured-vs-modeled step budget attached to
  the sparse arm, so the host-bound-vs-compute-bound claim is measured,
  not asserted;
* ``vectorization_ab`` — ISSUE 15: paired alternating scalar-vs-
  vectorized A/B of the host hot path (``SparseTable(impl=...)``), per
  the PR 9 measurement discipline (median of per-pair ratios, noise
  gate, raw windows committed).  Three arms: ``steady`` (the PR 14 CTR
  training workload end to end, gate at the 1.5x acceptance bar),
  ``cold_init`` (fresh-table pulls, the init-dominated regime the
  batched Philox kernel targets), and ``overlap`` (vectorized sync rim
  vs pull-ahead prefetch + bounded async push — on this ~1-effective-
  core container an honest refusal is an expected outcome).

Writes benchmark/ctr_results.json.  The round-4 dense-optimizer-moment
sweep this file used to hold (a REAL TPU v5lite measurement from before
the sparse subsystem existed) is preserved under
``legacy_r04_dense_optimizer_sweep``.

Usage::

    python benchmark/ctr.py [--smoke] [--out PATH]
    python benchmark/run.py --model ctr [--smoke]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "ctr_results.json")

# -- configuration -----------------------------------------------------------
# The benchmark's premise, stated up front: a per-device HBM slice
# budgeted for embeddings (a v5e-lite slice share).  The big table's
# dense form must NOT fit it.
HBM_EMBEDDING_BUDGET_MB = 64

FULL = {
    "batch": 512,
    "emb_dim": 16,
    "vocab_big": 2_000_000,      # dense: 2e6*16*4 = 122 MiB > budget
    "vocab_small": 100_000,
    "dense_features": 13,
    "hidden": 64,
    "warmup_steps": 3,
    "window_steps": 10,
    "windows": 3,
    "cache_rows": 65_536,
    "cache_batches": 60,
    "zipf_a": 1.2,
    "ab_pairs": 5,
    "ab_window_steps": 8,
    "cold_rows": 200_000,
    "cold_chunk": 8192,
}
SMOKE = {
    "batch": 64,
    "emb_dim": 8,
    "vocab_big": 20_000,
    "vocab_small": 2_000,
    "dense_features": 4,
    "hidden": 16,
    "warmup_steps": 1,
    "window_steps": 3,
    "windows": 2,
    "cache_rows": 1024,
    "cache_batches": 8,
    "zipf_a": 1.2,
    "ab_pairs": 2,
    "ab_window_steps": 3,
    "cold_rows": 2_000,
    "cold_chunk": 512,
}


def _build_model(cfg, sparse: bool):
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    pt.default_main_program().random_seed = 42
    pt.default_startup_program().random_seed = 42
    ids_big = layers.data("ids_big", shape=[1], dtype="int64")
    ids_small = layers.data("ids_small", shape=[1], dtype="int64")
    dense = layers.data("dense", shape=[cfg["dense_features"]],
                        dtype="float32")
    label = layers.data("label", shape=[1], dtype="float32")
    kw = {"sparse": True} if sparse else {}
    e_big = layers.embedding(ids_big, size=[cfg["vocab_big"],
                                            cfg["emb_dim"]],
                             name="ctr_big", **kw)
    e_small = layers.embedding(ids_small, size=[cfg["vocab_small"],
                                                cfg["emb_dim"]],
                               name="ctr_small", **kw)
    x = layers.concat([e_big, e_small, dense], axis=1)
    x = layers.fc(x, size=cfg["hidden"], act="relu")
    pred = layers.fc(x, size=1, act="sigmoid")
    loss = layers.mean(layers.square(pred - label))
    pt.optimizer.Adagrad(learning_rate=0.05).minimize(loss)
    return loss


def _zipf_ids(rng, a, vocab, size):
    """Zipfian ids over [0, vocab): heavy head at small ids — the CTR
    id-frequency shape the hot-rows cache is built for."""
    draws = rng.zipf(a, size=size).astype(np.int64)
    return (draws - 1) % vocab


def _feed_stream(cfg, n, seed=0):
    rng = np.random.RandomState(seed)
    B = cfg["batch"]
    for _ in range(n):
        yield {
            "ids_big": _zipf_ids(rng, cfg["zipf_a"], cfg["vocab_big"],
                                 (B, 1)),
            "ids_small": _zipf_ids(rng, cfg["zipf_a"],
                                   cfg["vocab_small"], (B, 1)),
            "dense": rng.rand(B, cfg["dense_features"]).astype(
                np.float32),
            "label": (rng.rand(B, 1) < 0.3).astype(np.float32),
        }


def _pctl(xs, q):
    if not xs:
        return None
    return float(np.percentile(np.asarray(xs, np.float64), q))


def _sparse_tables(cfg, storage="memory", storage_dir=None,
                   impl="vectorized"):
    from paddle_tpu.sparse import SparseTable
    kw = dict(optimizer="adagrad", learning_rate=0.05,
              storage=storage, storage_dir=storage_dir, impl=impl)
    return {
        "ctr_big": SparseTable("ctr_big", cfg["vocab_big"],
                               cfg["emb_dim"], num_shards=8, seed=1,
                               **kw),
        "ctr_small": SparseTable("ctr_small", cfg["vocab_small"],
                                 cfg["emb_dim"], num_shards=4, seed=2,
                                 **kw),
    }


def run_sparse_arm(cfg, quiet=False):
    """Sparse-table training throughput + lookup/push micro-metrics."""
    import paddle_tpu as pt
    from paddle_tpu.sparse import SparseSession

    loss = _build_model(cfg, sparse=True)
    tables = _sparse_tables(cfg)
    # bucket pinned to the batch size: ONE compiled variant regardless
    # of per-batch unique counts (the production config; the default
    # power-of-two laddering is for workloads with wild unique-count
    # variance that cannot afford max-size pulls)
    sess = SparseSession(tables, bucket_floor=cfg["batch"])
    sess.bind(pt.default_main_program())
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    steps = cfg["warmup_steps"] + cfg["windows"] * cfg["window_steps"]
    feeds = list(_feed_stream(cfg, steps))
    pull_ms, push_rows, push_ms = [], 0, 0.0
    windows, last_window_pulls = [], []
    k = 0
    for w in range(-1, cfg["windows"]):      # window -1 = warmup
        n = cfg["warmup_steps"] if w < 0 else cfg["window_steps"]
        t0 = time.perf_counter()
        for _ in range(n):
            feed = feeds[k]
            k += 1
            s0 = dict(sess.stats)
            out = sess.run(exe, pt.default_main_program(), feed, [loss])
            float(out[0])                    # force completion
            if w >= 0:
                dt = sess.stats["pull_ms"] - s0["pull_ms"]
                pull_ms.append(dt)
                if w == cfg["windows"] - 1:
                    last_window_pulls.append(dt)
                push_rows += sess.stats["pushed_rows"] \
                    - s0["pushed_rows"]
                push_ms += sess.stats["push_ms"] - s0["push_ms"]
        if w >= 0:
            windows.append(cfg["batch"] * n
                           / (time.perf_counter() - t0))
    row = {
        "examples_per_sec": round(float(np.median(windows)), 1),
        "examples_per_sec_windows": [round(x, 1) for x in windows],
        # all-windows latency includes the lazy cold-row initialization
        # of the zipf tail (real CTR behavior); the warm row is the
        # last window alone, where most pulls hit resident rows
        "lookup_latency_ms": {"p50": round(_pctl(pull_ms, 50), 3),
                              "p99": round(_pctl(pull_ms, 99), 3)},
        "lookup_latency_warm_ms": {
            "p50": round(_pctl(last_window_pulls, 50), 3),
            "p99": round(_pctl(last_window_pulls, 99), 3)},
        "push_rows_per_sec": round(push_rows / (push_ms / 1e3), 1)
        if push_ms else None,
        "pushed_rows": int(push_rows),
        "live_rows": {n: t.live_rows for n, t in tables.items()},
        "host_table_mb": round(sum(t.host_bytes()
                                   for t in tables.values()) / 2**20, 2),
    }
    if not quiet:
        print(json.dumps({"arm": "sparse", **row}), flush=True)
    return row, sess, exe, loss


def run_dense_control(cfg, quiet=False):
    """Dense device-resident embedding control: same model, same feeds.
    This is the arm the HBM budget rules out at real scale — on CPU it
    is merely slow (every step materializes and sweeps the full dense
    gradient of each table)."""
    import paddle_tpu as pt

    loss = _build_model(cfg, sparse=False)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    steps = cfg["warmup_steps"] + cfg["windows"] * cfg["window_steps"]
    feeds = list(_feed_stream(cfg, steps))
    windows, k = [], 0
    for w in range(-1, cfg["windows"]):
        n = cfg["warmup_steps"] if w < 0 else cfg["window_steps"]
        t0 = time.perf_counter()
        for _ in range(n):
            out = exe.run(pt.default_main_program(), feed=feeds[k],
                          fetch_list=[loss])
            float(out[0])
            k += 1
        if w >= 0:
            windows.append(cfg["batch"] * n
                           / (time.perf_counter() - t0))
    row = {"examples_per_sec": round(float(np.median(windows)), 1),
           "examples_per_sec_windows": [round(x, 1) for x in windows]}
    if not quiet:
        print(json.dumps({"arm": "dense_control", **row}), flush=True)
    return row


def _train_window(sess, exe, prog, loss_name, feeds, scope):
    """One timed window: pull (possibly prefetched) -> dispatch -> push
    for every feed, then the flush barrier — host+device work complete
    when it returns."""
    it = sess.prefetch_feeds(iter(feeds))
    try:
        for feed in it:
            out = exe.run(prog, feed=feed,
                          fetch_list=[loss_name] + sess.grad_fetch_list,
                          scope=scope)
            float(np.asarray(out[0]).reshape(-1)[0])
            sess.complete(out[1:])
    finally:
        it.close()
    sess.flush()


def _impl_arm(cfg, impl, session_kw=None):
    """A self-contained training arm (own program, scope, executor,
    tables) whose window cursor walks a shared feed schedule."""
    import paddle_tpu as pt
    from paddle_tpu.sparse import SparseSession

    loss = _build_model(cfg, sparse=True)
    prog = pt.default_main_program()
    startup = pt.default_startup_program()
    scope = pt.core.scope.Scope()
    exe = pt.Executor()
    exe.run(startup, feed={}, fetch_list=[], scope=scope)
    sess = SparseSession(_sparse_tables(cfg, impl=impl),
                         bucket_floor=cfg["batch"],
                         **(session_kw or {}))
    sess.bind(prog)
    return {"sess": sess, "exe": exe, "prog": prog, "scope": scope,
            "loss_name": loss.name, "cursor": 0}


def run_vectorization_ab(cfg, quiet=False):
    """ISSUE 15 leg 4: paired alternating scalar-vs-vectorized A/B on
    the PR 14 CTR workload (PR 9 discipline: median of per-pair ratios
    + noise gate + raw windows committed).  Steady arm gates at the
    1.5x acceptance bar; both arms of every pair consume the SAME feed
    windows, so drift cancels pair-wise."""
    from paddle_tpu.tuning.search import paired_ab

    W = cfg["ab_window_steps"]
    pairs = cfg["ab_pairs"]
    # paired_ab runs max(2, pairs) measured pairs + 1 warmup pair; the
    # schedule must cover every window or a short slice would time a
    # no-op loop and fabricate a ratio — _next_window asserts it
    n_windows = (max(2, pairs) + 1) * W
    feeds = list(_feed_stream(cfg, n_windows, seed=11))

    def _next_window(arm):
        lo = arm["cursor"]
        arm["cursor"] += W
        window = feeds[lo:lo + W]
        assert len(window) == W, \
            f"feed schedule exhausted at {lo} (have {len(feeds)})"
        return window

    # -- steady arm: end-to-end training throughput ----------------------
    arms = {"reference": _impl_arm(cfg, "reference"),
            "vectorized": _impl_arm(cfg, "vectorized")}

    def measure_steady(config):
        arm = arms[config["impl"]]
        _train_window(arm["sess"], arm["exe"], arm["prog"],
                      arm["loss_name"], _next_window(arm), arm["scope"])

    steady = paired_ab(measure_steady, {"impl": "reference"},
                       {"impl": "vectorized"}, pairs=pairs, warmup=1,
                       min_speedup=1.5)
    steady["examples_per_window"] = cfg["batch"] * W
    # byte-identity of the two arms' final table state: the A/B compares
    # THE SAME training run, not two different ones
    sv = arms["vectorized"]["sess"].export_state_vars()
    sr = arms["reference"]["sess"].export_state_vars()
    steady["arms_bit_identical"] = sorted(sv) == sorted(sr) and all(
        sv[k].tobytes() == sr[k].tobytes() for k in sv)

    # -- cold-init arm: fresh tables, pure pull (init-dominated) ---------
    rng = np.random.RandomState(5)
    cold_ids = np.unique(rng.randint(
        0, cfg["vocab_big"], int(cfg["cold_rows"] * 1.2)
    ).astype(np.int64))[:cfg["cold_rows"]]

    def measure_cold(config):
        t = _sparse_tables(cfg, impl=config["impl"])["ctr_big"]
        for lo in range(0, len(cold_ids), cfg["cold_chunk"]):
            t.pull(cold_ids[lo:lo + cfg["cold_chunk"]])

    cold = paired_ab(measure_cold, {"impl": "reference"},
                     {"impl": "vectorized"}, pairs=pairs, warmup=1)
    cold["rows_per_window"] = int(len(cold_ids))

    # -- overlap arm: vectorized sync rim vs prefetch + async push -------
    over_arm = {
        "sync": _impl_arm(cfg, "vectorized"),
        "overlap": _impl_arm(cfg, "vectorized",
                             {"prefetch_depth": 2, "async_push": 2,
                              "push_flush_batch": 2}),
    }

    def measure_overlap(config):
        arm = over_arm[config["mode"]]
        _train_window(arm["sess"], arm["exe"], arm["prog"],
                      arm["loss_name"], _next_window(arm), arm["scope"])

    overlap = paired_ab(measure_overlap, {"mode": "sync"},
                        {"mode": "overlap"}, pairs=pairs, warmup=1)
    prefetch_stats = over_arm["overlap"]["sess"].stats
    overlap["prefetch_hits"] = prefetch_stats["prefetch_hits"]
    overlap["prefetch_misses"] = prefetch_stats["prefetch_misses"]

    row = {"steady": steady, "cold_init": cold, "overlap": overlap}
    if not quiet:
        print(json.dumps({"arm": "vectorization_ab", **{
            k: {"speedup": v["speedup"], "accepted": v["accepted"]}
            for k, v in row.items()}}), flush=True)
    return row


def run_cache_arm(cfg, quiet=False):
    """Hot-rows cache hit rate under zipfian read-only traffic (the
    serving path: pull-only, cache-first)."""
    import paddle_tpu as pt
    from paddle_tpu.sparse import SparseSession

    _build_model(cfg, sparse=True)
    sess = SparseSession(_sparse_tables(cfg),
                         cache_rows=cfg["cache_rows"])
    sess.bind(pt.default_main_program())
    for feed in _feed_stream(cfg, cfg["cache_batches"], seed=7):
        sess.prepare_feed(feed, is_test=True)
    cs = sess.cache_stats()
    row = {"cache_rows": cfg["cache_rows"],
           "batches": cfg["cache_batches"],
           "zipf_a": cfg["zipf_a"],
           "hits": cs["hits"], "misses": cs["misses"],
           "hit_rate": round(cs["hit_rate"], 4)}
    if not quiet:
        print(json.dumps({"arm": "cache", **row}), flush=True)
    return row


def run_doctor_pass(cfg, quiet=False):
    """One EXTRA observed sparse pass AFTER the timed windows (the
    instrumentation never touches the A/B): the PR 10 step budget must
    reconcile measured wall within BUDGET_TOLERANCE, and the sparse
    pull/push spans ride the same log."""
    import tempfile

    import paddle_tpu as pt
    from paddle_tpu import flags
    from paddle_tpu.observability import attribution
    from paddle_tpu.sparse import SparseSession

    log = os.path.join(tempfile.gettempdir(),
                       f"pt_doctor_ctr_{os.getpid()}.jsonl")
    try:
        os.remove(log)
    except OSError:
        pass
    loss = _build_model(cfg, sparse=True)
    sess = SparseSession(_sparse_tables(cfg), observe=True,
                         bucket_floor=cfg["batch"])
    sess.bind(pt.default_main_program())
    exe = pt.Executor(observe=True)
    exe.run(pt.default_startup_program())
    feeds = list(_feed_stream(cfg, cfg["window_steps"] + 1, seed=3))
    # one UNOBSERVED warmup step: the first-trace compile belongs to
    # startup cost, not to the steady-state budget being doctored
    float(sess.run(exe, pt.default_main_program(), feeds[0], [loss])[0])
    prev_obs = flags.get_flag("observe")
    prev_log = flags.get_flag("metrics_log")
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", log)
    try:
        for feed in feeds[1:]:
            out = sess.run(exe, pt.default_main_program(), feed, [loss])
            float(out[0])
    finally:
        flags.set_flag("observe", prev_obs)
        flags.set_flag("metrics_log", prev_log or "")
    report = attribution.doctor_report(
        [log], program=pt.default_main_program(),
        assume_batch=cfg["batch"])
    row = {"doctor": report.get("training")}
    if not quiet:
        print(json.dumps({"arm": "doctor", **row}), flush=True)
    return row


def run_all(cfg=None, smoke=False, quiet=False):
    cfg = cfg or (SMOKE if smoke else FULL)
    dense_mb = (cfg["vocab_big"] + cfg["vocab_small"]) \
        * cfg["emb_dim"] * 4 / 2**20
    sparse_row, sess, exe, loss = run_sparse_arm(cfg, quiet=quiet)
    dense_row = run_dense_control(cfg, quiet=quiet)
    vect_ab = run_vectorization_ab(cfg, quiet=quiet)
    cache_row = run_cache_arm(cfg, quiet=quiet)
    try:
        doctor_row = run_doctor_pass(cfg, quiet=quiet)
    except Exception as e:   # A/B rows must survive a doctor failure
        doctor_row = {"doctor": {"error": f"{type(e).__name__}: {e}"}}
    speedup = None
    if dense_row["examples_per_sec"]:
        speedup = round(sparse_row["examples_per_sec"]
                        / dense_row["examples_per_sec"], 3)
    return {
        "config": {**cfg,
                   "hbm_embedding_budget_mb": HBM_EMBEDDING_BUDGET_MB,
                   "dense_tables_mb": round(dense_mb, 1),
                   "dense_exceeds_budget":
                       dense_mb > HBM_EMBEDDING_BUDGET_MB},
        "sparse": sparse_row,
        "dense_control": dense_row,
        "sparse_vs_dense_speedup": speedup,
        "vectorization_ab": vect_ab,
        "cache": cache_row,
        **doctor_row,
        "smoke": bool(smoke),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-fast path check (tiny sizes); does "
                         "not overwrite the committed results file")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    row = run_all(smoke=args.smoke)
    print(json.dumps(row, indent=1))
    if args.smoke:
        return
    result = {
        "benchmark": "ctr_sparse_parameter_server",
        "device": "cpu (in-container; no TPU reachable)",
        "cpu": row,
        "tpu": {
            "status": "pending-hardware",
            "plan": "re-run benchmark/ctr.py on a chip host: the "
                    "sparse arm's device step is the same compiled "
                    "gather+train step (rows feed [n_unique, dim]); "
                    "the dense control either OOMs (the budget claim "
                    "made real) or pays the full-table optimizer "
                    "sweep the round-4 legacy row below measured",
            "rows": [],
        },
    }
    legacy_path = os.path.join(os.path.dirname(args.out),
                               "ctr_results.json")
    try:
        with open(legacy_path) as fh:
            old = json.load(fh)
        if "variants" in old:    # the pre-rewrite round-4 study
            result["legacy_r04_dense_optimizer_sweep"] = old
        elif "legacy_r04_dense_optimizer_sweep" in old:
            result["legacy_r04_dense_optimizer_sweep"] = \
                old["legacy_r04_dense_optimizer_sweep"]
    except (OSError, ValueError):
        pass
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
