#!/usr/bin/env python
"""Continuous-batching incremental decode benchmark: the KV-cache slot
pool (paddle_tpu.serving.decode) vs a static-batch control (ROADMAP
item 5; the serving analog of the Orca iteration-level scheduler).

Both arms run the SAME two compiled step functions (batch-1 prefill +
batch-S one-token decode over donated cache slabs) on the SAME
mixed-length request trace; the only difference is the scheduler:

* ``continuous`` — at every token-step boundary, finished sequences
  (max-len here; EOS in general) are evicted and completed immediately
  and queued requests are admitted into the freed slots;
* ``static`` — requests are admitted only into an EMPTY pool, and the
  whole batch then runs until its slowest member finishes (pad to the
  longest: the classic request-batcher behavior a generate workload
  degrades to).

Measured rows, all REAL and in-container (CPU; the TPU row is a
pending-hardware stub per the PR 1 convention):

* ``decode tokens/s`` — generated-token throughput per arm;
* ``ttft_ms`` — p50/p99 time to first token (admission -> prefill);
* ``inter_token_ms`` — p50/p99 gap between consecutive tokens of one
  sequence (the streaming cadence continuous batching bounds);
* ``slot_occupancy`` — live slots over total at decode steps (the
  padded-compute complement);
* ``ab`` — paired alternating static-vs-continuous A/B per the PR 9
  discipline (median of per-pair ratios, noise gate, raw windows
  committed), acceptance bar 1.3x decode tokens/s;
* ``arms_tokens_identical`` — every request's generated tokens must be
  BIT-identical across the two schedulers (per-row bit independence of
  ``attention_with_cache`` + the recompute oracle in
  tests/test_decode.py make scheduling invisible to the math);
* ``doctor`` — the decode section of the PR 10 measured-vs-modeled
  budget, attached from one extra observed window.

Writes benchmark/decode_results.json.

Usage::

    python benchmark/decode.py [--smoke] [--out PATH]
    python benchmark/run.py --model decode [--smoke]
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

_DOCTOR_SEQ = itertools.count()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "decode_results.json")

FULL = {
    "vocab": 256,
    "hidden": 64,
    "n_layers": 2,
    "slots": 8,
    "max_len": 64,
    "n_requests": 24,
    "prompt_lens": (4, 6, 8, 12),
    "max_news": (4, 8, 8, 48),      # long-tail mix: the static arm pads
                                    # every round to its slowest member,
                                    # continuous streams the short ones
                                    # through the freed slots
    "ab_pairs": 5,
    "warmup": 1,
    "min_speedup": 1.3,
}
SMOKE = {
    "vocab": 64,
    "hidden": 32,
    "n_layers": 1,
    "slots": 4,
    "max_len": 32,
    "n_requests": 6,
    "prompt_lens": (3, 5, 7),
    "max_news": (2, 4, 8),
    "ab_pairs": 2,
    "warmup": 1,
    "min_speedup": 1.3,
}


def _trace(cfg, seed=0):
    """The shared mixed-length request trace: (prompt, max_new) pairs.
    eos_id is None, so every request generates exactly max_new tokens —
    deterministic work per window by construction."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(cfg["n_requests"]):
        plen = cfg["prompt_lens"][i % len(cfg["prompt_lens"])]
        prompt = [int(t) for t in rng.randint(1, cfg["vocab"], plen)]
        out.append((prompt, cfg["max_news"][i % len(cfg["max_news"])]))
    return out


def _build_pool(cfg, mode):
    from paddle_tpu.serving.decode import DecodeEngine, DecodeRuntime

    eng = DecodeEngine(
        vocab_size=cfg["vocab"], hidden_dim=cfg["hidden"],
        n_layers=cfg["n_layers"], slots=cfg["slots"],
        max_len=cfg["max_len"], eos_id=None, seed=7,
        name=f"bench-{mode}")
    rt = DecodeRuntime(eng, mode=mode, step_wait_ms=0.5,
                       default_deadline_ms=None)
    rt.start(warmup=True)
    return rt


def _run_window(rt, trace):
    """Submit the whole trace (closed queue of offered load), wait for
    every completion; returns (wall_s, outputs)."""
    t0 = time.perf_counter()
    reqs = [rt.submit(p, m) for p, m in trace]
    outs = [r.result(timeout=600.0) for r in reqs]
    return time.perf_counter() - t0, outs


def _arm_row(rt, trace, outs, wall_s, h0, h1):
    tokens = sum(len(o["tokens"]) for o in outs)
    ttfts = sorted(o["ttft_ms"] for o in outs if o["ttft_ms"] is not None)
    inter = sorted(g for o in outs for g in o["inter_token_ms"])
    steps = h1["steps"] - h0["steps"]
    # decode-step tokens = all generated minus the prefill-emitted firsts
    step_tokens = (h1["tokens"] - h0["tokens"]) - len(outs)

    def pctl(xs, q):
        return round(float(np.percentile(np.asarray(xs, np.float64), q)),
                     3) if xs else None

    return {
        "mode": rt.mode,
        "tokens": tokens,
        "decode_tokens_per_s": round(tokens / wall_s, 1),
        "wall_s": round(wall_s, 3),
        "ttft_ms": {"p50": pctl(ttfts, 50), "p99": pctl(ttfts, 99)},
        "inter_token_ms": {"p50": pctl(inter, 50), "p99": pctl(inter, 99)},
        "decode_steps": steps,
        "slot_occupancy": round(step_tokens / (steps * rt.engine.slots), 4)
        if steps else None,
    }


def run_ab(cfg, quiet=False):
    """The headline A/B: one persistent pool per mode (engines compiled
    once, outside every timed window), alternating windows over the same
    trace, PR 9 paired discipline at the 1.3x bar."""
    from paddle_tpu.tuning.search import paired_ab

    trace = _trace(cfg)
    pools = {m: _build_pool(cfg, m) for m in ("static", "continuous")}
    try:
        last_outs = {}

        def measure(config):
            rt = pools[config["mode"]]
            _, outs = _run_window(rt, trace)
            last_outs[config["mode"]] = outs

        ab = paired_ab(measure, {"mode": "static"},
                       {"mode": "continuous"}, pairs=cfg["ab_pairs"],
                       warmup=cfg["warmup"],
                       min_speedup=cfg["min_speedup"])

        # per-arm detail rows from one more (untimed-by-the-AB) window
        rows = {}
        for mode, rt in pools.items():
            h0 = rt.health()
            wall, outs = _run_window(rt, trace)
            rows[mode] = _arm_row(rt, trace, outs, wall, h0, rt.health())
            last_outs[mode] = outs

        # the integrity bar: scheduling must be invisible to the math —
        # every request's token ids bitwise equal across schedulers
        identical = all(
            a["tokens"] == b["tokens"]
            for a, b in zip(last_outs["static"], last_outs["continuous"]))
    finally:
        for rt in pools.values():
            rt.shutdown(drain=True, timeout=60.0)
    row = {"ab": ab, "static": rows["static"],
           "continuous": rows["continuous"],
           "arms_tokens_identical": bool(identical)}
    if not quiet:
        print(json.dumps({
            "arm": "decode_ab", "speedup": ab["speedup"],
            "accepted": ab["accepted"],
            "static_tokens_per_s": rows["static"]["decode_tokens_per_s"],
            "continuous_tokens_per_s":
                rows["continuous"]["decode_tokens_per_s"],
            "arms_tokens_identical": bool(identical)}), flush=True)
    return row


def run_doctor_pass(cfg, quiet=False):
    """One extra OBSERVED continuous window (instrumentation never
    touches the A/B): the decode section of the stats summary + the
    doctor's token-step budget ride a JSONL log."""
    import tempfile

    from paddle_tpu import flags
    from paddle_tpu.observability import attribution
    from paddle_tpu.observability.export import summarize_logs

    # unique path per pass: the JSONL writer keeps a same-path handle
    # open across calls, so a removed-and-reused name would stream to an
    # unlinked inode
    log = os.path.join(
        tempfile.gettempdir(),
        f"pt_doctor_decode_{os.getpid()}_{next(_DOCTOR_SEQ)}.jsonl")
    try:
        os.remove(log)
    except OSError:
        pass
    rt = _build_pool(cfg, "continuous")
    prev_obs = flags.get_flag("observe")
    prev_log = flags.get_flag("metrics_log")
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", log)
    try:
        _run_window(rt, _trace(cfg))
    finally:
        flags.set_flag("observe", prev_obs)
        flags.set_flag("metrics_log", prev_log or "")
        rt.shutdown(drain=True, timeout=60.0)
    summary = summarize_logs([log])
    report = attribution.doctor_report([log])
    row = {"doctor": report.get("decode"),
           "stats_decode": summary.get("decode")}
    if not quiet:
        print(json.dumps({"arm": "doctor", **row}), flush=True)
    return row


def run_all(cfg=None, smoke=False, quiet=False):
    cfg = cfg or (SMOKE if smoke else FULL)
    row = run_ab(cfg, quiet=quiet)
    try:
        doctor_row = run_doctor_pass(cfg, quiet=quiet)
    except Exception as e:   # A/B rows must survive a doctor failure
        doctor_row = {"doctor": {"error": f"{type(e).__name__}: {e}"}}
    return {"config": dict(cfg), **row, **doctor_row,
            "smoke": bool(smoke)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-fast path check (tiny sizes); does "
                         "not overwrite the committed results file")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    row = run_all(smoke=args.smoke)
    print(json.dumps(row, indent=1))
    if args.smoke:
        return
    from paddle_tpu.tuning.search import pending_stub
    from paddle_tpu.tuning.targets import ensure_registered
    ensure_registered("pallas/paged_kv_gather")
    result = {
        "benchmark": "decode_continuous_batching",
        "device": "cpu (in-container; no TPU reachable)",
        "cpu": row,
        "tpu": {
            "status": "pending-hardware",
            "plan": "re-run benchmark/decode.py on a chip host: the "
                    "decode step is the same compiled one-token program "
                    "(donated [S, Tmax, D] cache slabs in HBM); on-chip "
                    "the per-step dispatch shrinks and the padded-"
                    "compute fraction static batching wastes grows with "
                    "the matmul width, so the continuous win should "
                    "widen — commit real rows, never extrapolate these",
            "rows": [],
            "paged_kv_gather": pending_stub("pallas/paged_kv_gather"),
        },
    }
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
