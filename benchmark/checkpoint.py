#!/usr/bin/env python
"""Incremental-checkpoint benchmark (ISSUE 18): dirty-row sparse deltas
and chunked dense diffs vs the full-save control, per the PR 9 paired-
alternating discipline (median of per-pair ratios, noise gate, raw
windows committed, refusals honest).  All rows REAL and in-container
(CPU; the TPU row is a pending-hardware stub per the PR 1 convention).

Arms:

* ``commit_ab`` — the tentpole gate: per-task delta commit vs full-save
  control at a 2M-row vocab with ~0.5% of the resident working set
  touched per task.  Both arms train the SAME feed schedule on
  identically-seeded tables, each committing blocking (wall includes
  serialization + write + fsync).  Gates: wall ``min_speedup=5.0`` via
  the paired A/B, plus ``bytes_ratio >= 10`` from the committed
  manifests.  After the timed windows BOTH tips are restored and
  asserted bit-identical (rows, Adagrad moment, export bytes) to each
  other and to the live tables — the delta chain is fast because it
  writes less, not because it drops state.
* ``elastic_tasks`` — the task-boundary loop the elastic worker runs:
  per task push + async commit through the REAL ``Checkpointer``
  (``DeltaPolicy`` off vs on), durability barrier (``manager.wait()``)
  at the window edge where task_finished reports.  Reported as tasks/s
  per arm; the delta arm includes its periodic rebases (max_chain=8).
* ``restore_chain`` — recovery cost: restore wall for a base+K-delta
  chain vs a single full save of the SAME final state, shas asserted
  equal.  Chain replay is expected to cost MORE than a full restore —
  this row prices the durability win, it does not gate on it.

Writes benchmark/checkpoint_results.json.

Usage::

    python benchmark/checkpoint.py [--smoke] [--out PATH]
    python benchmark/run.py --model checkpoint [--smoke]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "checkpoint_results.json")

FULL = {
    "vocab": 2_000_000,
    "dim": 16,
    "num_shards": 4,
    "resident_rows": 400_000,     # warm working set (rows on disk)
    "touched_per_task": 2_000,    # 0.5% of resident per task
    "ab_pairs": 4,
    "elastic_tasks_per_window": 3,
    "elastic_pairs": 3,
    "dense_param_floats": 1_000_000,   # 4 MB dense rider (chunk-diffed)
    "chain_k": 8,
}
SMOKE = {
    "vocab": 50_000,
    "dim": 8,
    "num_shards": 3,
    "resident_rows": 4_000,
    "touched_per_task": 40,
    "ab_pairs": 2,
    "elastic_tasks_per_window": 2,
    "elastic_pairs": 2,
    "dense_param_floats": 20_000,
    "chain_k": 3,
}


# -- plumbing ----------------------------------------------------------------

def _mk_table(cfg, name="emb"):
    from paddle_tpu.sparse import SparseTable
    return SparseTable(name, cfg["vocab"], cfg["dim"],
                       optimizer="adagrad", learning_rate=0.05,
                       num_shards=cfg["num_shards"], seed=3)


def _warm(cfg, t):
    ids = np.arange(cfg["resident_rows"], dtype=np.int64)
    g = np.random.RandomState(7).standard_normal(
        (len(ids), cfg["dim"])).astype(np.float32)
    t.push(ids, g)


def _feed(cfg, n_tasks, seed):
    """Per-task (ids, grads) touching ~0.5% of the resident set."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_tasks):
        ids = rng.choice(cfg["resident_rows"], size=cfg["touched_per_task"],
                         replace=False).astype(np.int64)
        out.append((ids, rng.standard_normal(
            (len(ids), cfg["dim"])).astype(np.float32)))
    return out


def _scope(state, **dense):
    import paddle_tpu as pt
    sc = pt.Scope()
    for k, v in state.items():
        sc.set(k, v)
    for k, v in dense.items():
        sc.set(k, v)
    return sc


def _sha(state, extra=None):
    h = hashlib.sha256()
    for k in sorted(state):
        a = np.ascontiguousarray(np.asarray(state[k]))
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    if extra is not None:
        h.update(np.asarray(extra, np.float32).tobytes())
    return h.hexdigest()


def _restore_sha(cfg, root):
    """Restore the newest commit and reduce it to the canonical table
    export sha (+ dense vars hashed alongside)."""
    import paddle_tpu as pt
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    sc = pt.Scope()
    cm = CheckpointManager(root, async_save=False)
    step = cm.restore(scope=sc)
    state = {k: np.asarray(sc.get(k)) for k in sc.keys()
             if k.startswith("__sparse__/")}
    t = _mk_table(cfg)
    t.restore_state_vars(state)
    dense = [np.asarray(sc.get(k), np.float32)
             for k in sorted(sc.keys())
             if not k.startswith("__sparse__/")
             and not k.startswith("__train_state__")]
    h = hashlib.sha256(_sha(t.export_state_vars()).encode())
    for a in dense:
        h.update(a.tobytes())
    return step, h.hexdigest()


# -- arms --------------------------------------------------------------------

def run_commit_ab(cfg, workdir, quiet=False):
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.tuning.search import paired_ab

    arms = {}
    for mode in ("full", "delta"):
        t = _mk_table(cfg)
        _warm(cfg, t)
        cm = CheckpointManager(os.path.join(workdir, f"ab_{mode}"),
                               async_save=False, max_to_keep=3)
        # both arms start from the SAME committed base so the delta arm
        # chains and the full arm's windows measure steady-state saves
        tok, st = t.export_full()
        cm.save(0, _scope(st), blocking=True, kind="full",
                on_commit=lambda info, tk=tok, tt=t: tt.commit_delta(tk))
        arms[mode] = {"t": t, "cm": cm, "cursor": 0, "step": 0,
                      "bytes": []}
    n_windows = max(2, cfg["ab_pairs"]) + 1          # + warmup
    feeds = _feed(cfg, n_windows, seed=11)

    def measure(config):
        arm = arms[config["mode"]]
        ids, g = feeds[arm["cursor"]]
        arm["cursor"] += 1
        arm["t"].push(ids, g)
        arm["step"] += 1
        kind = config["mode"]
        tok, st = (arm["t"].export_full() if kind == "full"
                   else arm["t"].export_delta())
        box = {}
        arm["cm"].save(arm["step"], _scope(st), blocking=True, kind=kind,
                       on_commit=lambda info, tk=tok, a=arm:
                           (a["t"].commit_delta(tk), box.update(info)),
                       on_fail=lambda exc, tk=tok, a=arm:
                           a["t"].retract_delta(tk))
        arm["bytes"].append(int(box["bytes"]))

    ab = paired_ab(measure, {"mode": "full"}, {"mode": "delta"},
                   pairs=cfg["ab_pairs"], warmup=1, min_speedup=5.0)
    # bytes gate from the manifests of the TIMED windows (skip warmup)
    fb = [float(b) for b in arms["full"]["bytes"][1:]]
    db = [float(b) for b in arms["delta"]["bytes"][1:]]
    bytes_ratio = float(np.median(fb) / max(1.0, np.median(db)))
    ab["full_bytes_per_commit"] = fb
    ab["delta_bytes_per_commit"] = db
    ab["bytes_ratio"] = round(bytes_ratio, 2)
    ab["min_bytes_ratio"] = 10.0
    ab["bytes_accepted"] = bool(bytes_ratio >= 10.0)
    ab["touched_fraction"] = cfg["touched_per_task"] / cfg["resident_rows"]
    # bit-identity: both arms trained the same schedule, so the restored
    # delta tip must equal the restored full tip AND the live tables
    live = _sha(arms["full"]["t"].export_state_vars())
    assert _sha(arms["delta"]["t"].export_state_vars()) == live, \
        "arms diverged: the A/B compared two different runs"
    _, full_sha = _restore_sha(cfg, os.path.join(workdir, "ab_full"))
    _, delta_sha = _restore_sha(cfg, os.path.join(workdir, "ab_delta"))
    ab["restore_bit_identical"] = bool(full_sha == delta_sha)
    assert ab["restore_bit_identical"], \
        "delta-chain restore diverged from the full-save oracle"
    if not quiet:
        print(json.dumps({"arm": "commit_ab", "speedup": ab["speedup"],
                          "accepted": ab["accepted"],
                          "bytes_ratio": ab["bytes_ratio"],
                          "bytes_accepted": ab["bytes_accepted"]}),
              flush=True)
    return ab


def run_elastic_tasks(cfg, workdir, quiet=False):
    """The elastic task-boundary loop through the real Checkpointer:
    async commit per task, durable barrier at the window edge."""
    import paddle_tpu as pt
    from paddle_tpu.sparse import SparseSession
    from paddle_tpu.train_state import Checkpointer, DeltaPolicy
    from paddle_tpu.tuning.search import paired_ab

    class _Exe:
        _step = 0

    arms = {}
    for mode in ("full", "delta"):
        t = _mk_table(cfg)
        _warm(cfg, t)
        sess = SparseSession(t)
        scope = pt.Scope()
        scope.set("w", np.zeros(cfg["dense_param_floats"], np.float32))
        ck = Checkpointer(os.path.join(workdir, f"el_{mode}"), _Exe(),
                          handle_signals=False, delta_source=sess,
                          delta=DeltaPolicy(enabled=(mode == "delta")))
        ck.begin(scope, None, 0, {})
        arms[mode] = {"t": t, "ck": ck, "cursor": 0}
    per_win = cfg["elastic_tasks_per_window"]
    n_tasks = (max(2, cfg["elastic_pairs"]) + 1) * per_win
    feeds = _feed(cfg, n_tasks, seed=13)

    def measure(config):
        arm = arms[config["mode"]]
        ck = arm["ck"]
        for _ in range(per_win):
            ids, g = feeds[arm["cursor"]]
            arm["cursor"] += 1
            arm["t"].push(ids, g)
            ck.emitted += 1
            ck._save(0, 0)                      # async commit pipeline
        ck.manager.wait()                       # task_finished barrier

    ab = paired_ab(measure, {"mode": "full"}, {"mode": "delta"},
                   pairs=cfg["elastic_pairs"], warmup=1)
    ab["tasks_per_window"] = per_win
    ab["tasks_per_s"] = {
        m: round(per_win / float(np.median(w)), 3)
        for m, w in (("full", ab["default_windows"]),
                     ("delta", ab["candidate_windows"]))}
    for arm in arms.values():                   # drain before teardown
        arm["ck"].manager.wait()
    if not quiet:
        print(json.dumps({"arm": "elastic_tasks",
                          "speedup": ab["speedup"],
                          "accepted": ab["accepted"],
                          "tasks_per_s": ab["tasks_per_s"]}), flush=True)
    return ab


def run_restore_chain(cfg, workdir, quiet=False):
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    t = _mk_table(cfg)
    _warm(cfg, t)
    chain = CheckpointManager(os.path.join(workdir, "chain"),
                              async_save=False, max_to_keep=cfg["chain_k"] + 2)
    tok, st = t.export_full()
    chain.save(0, _scope(st), blocking=True, kind="full",
               on_commit=lambda info, tk=tok: t.commit_delta(tk))
    for k, (ids, g) in enumerate(_feed(cfg, cfg["chain_k"], seed=17), 1):
        t.push(ids, g)
        tok, st = t.export_delta()
        chain.save(k, _scope(st), blocking=True, kind="delta",
                   on_commit=lambda info, tk=tok: t.commit_delta(tk))
    # a single full save of the SAME final state is the control
    ctrl = CheckpointManager(os.path.join(workdir, "ctrl"),
                             async_save=False)
    tok, st = t.export_full()
    ctrl.save(cfg["chain_k"], _scope(st), blocking=True, kind="full",
              on_commit=lambda info, tk=tok: t.commit_delta(tk))

    t0 = time.perf_counter()
    step_c, sha_c = _restore_sha(cfg, os.path.join(workdir, "chain"))
    chain_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    step_f, sha_f = _restore_sha(cfg, os.path.join(workdir, "ctrl"))
    full_ms = (time.perf_counter() - t0) * 1e3
    assert step_c == step_f == cfg["chain_k"]
    row = {
        "chain_len": cfg["chain_k"],
        "chain_restore_ms": round(chain_ms, 1),
        "full_restore_ms": round(full_ms, 1),
        "replay_overhead_x": round(chain_ms / max(1e-9, full_ms), 2),
        "bit_identical": bool(sha_c == sha_f),
    }
    assert row["bit_identical"], \
        "base+K-delta replay diverged from the full-save oracle"
    if not quiet:
        print(json.dumps({"arm": "restore_chain", **row}), flush=True)
    return row


def run_all(cfg=None, smoke=False, quiet=False):
    cfg = cfg or (SMOKE if smoke else FULL)
    with tempfile.TemporaryDirectory(prefix="pt-ckpt-bench-") as workdir:
        commit_ab = run_commit_ab(cfg, workdir, quiet=quiet)
        elastic = run_elastic_tasks(cfg, workdir, quiet=quiet)
        restore = run_restore_chain(cfg, workdir, quiet=quiet)
    return {
        "config": dict(cfg),
        "commit_ab": commit_ab,
        "elastic_tasks": elastic,
        "restore_chain": restore,
        "smoke": bool(smoke),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast path check (tiny sizes); does not "
                         "overwrite the committed results file")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    row = run_all(smoke=args.smoke)
    print(json.dumps(row, indent=1))
    if args.smoke:
        return
    result = {
        "benchmark": "incremental_checkpoint",
        "device": "cpu (in-container; no TPU reachable)",
        "cpu": row,
        "tpu": {
            "status": "pending-hardware",
            "plan": "re-run benchmark/checkpoint.py on a chip host: the "
                    "commit path is host-side (serialize + fsync) and "
                    "the gates should hold as-is; the interesting chip "
                    "row is elastic_tasks with real training steps "
                    "overlapping the async writer",
            "rows": [],
        },
    }
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
