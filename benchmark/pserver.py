#!/usr/bin/env python
"""Multi-host sparse parameter-server wire benchmark (ISSUE 17).

The shard servers run as REAL ``python -m paddle_tpu pserver``
subprocesses (their own interpreters: server-side kernel time never
shares the GIL with the timed client), and every row is measured
in-container per the PR 1/9 discipline — paired alternating windows,
median of per-pair ratios, noise gate, raw windows committed, refusals
honest.  Arms:

* ``wire_ab`` — the tentpole gate: ONE batched zero-copy binary frame
  per request vs the naive per-row JSON arm (the reference-impl RPC
  cost shape), same server, same feed schedule, ``min_speedup=3.0``;
* ``remote_pull_latency`` — p50/p99 of warm remote batched pulls, next
  to the SAME workload against an in-process ``SparseTable`` measured
  in the same run (the PR 15 vectorized hot path; its committed CTR
  ledger put warm in-process pulls at single-digit ms — the wire tier
  must stay in that regime, not multiply it);
* ``trace_overhead_ab`` — context-propagation cost (ISSUE 20): the same
  schedule through an observing client (ctx in every frame, server span
  + srv timing piggyback in every reply) vs an observe-off client,
  verdict against a pre-registered 5% overhead budget;
* ``shard_pipelining_ab`` — 1-shard fleet vs 2-shard fleet, pipelined
  rounds (write both frames before reading either).  Wire latency =
  max-not-sum holds anywhere, but shard THROUGHPUT gains need two cores
  to run two kernels at once — on this ~1-effective-core container an
  honest refusal is the expected verdict and is committed as such.

Writes benchmark/pserver_results.json (cpu: real rows; tpu:
pending-hardware per the PR 1 convention).

Usage::

    python benchmark/pserver.py [--smoke] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "pserver_results.json")
HOST = "127.0.0.1"
READY_TIMEOUT = 180

FULL = {
    "vocab": 200_000,
    "dim": 16,
    "warm_rows": 16_384,         # resident working set (warmed up front)
    "pull_batch": 1024,          # ids per batched round
    "latency_reps": 200,
    "ab_batch": 256,             # rows per round in the naive-arm A/B
    "ab_rounds": 3,              # rounds per timed window
    "ab_pairs": 4,
    "pipe_batch": 2048,
    "pipe_rounds": 4,
    "pipe_pairs": 4,
    # the trace-overhead question is "is it within 5%", not "is it 3x":
    # it needs far more rounds per window than the coarse wire gates
    "trace_batch": 256,
    "trace_rounds": 12,
    "trace_pairs": 8,
}
SMOKE = {
    "vocab": 4_000,
    "dim": 8,
    "warm_rows": 512,
    "pull_batch": 128,
    "latency_reps": 20,
    "ab_batch": 32,
    "ab_rounds": 2,
    "ab_pairs": 2,
    "pipe_batch": 256,
    "pipe_rounds": 2,
    "pipe_pairs": 2,
    "trace_batch": 32,
    "trace_rounds": 2,
    "trace_pairs": 2,
}


# -- fleet plumbing ----------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind((HOST, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.pop("PADDLE_TPU_METRICS_LOG", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get(
        "PYTHONPATH", "")
    return env


def _launch_fleet(n_shards):
    """Start an n-shard subprocess fleet; returns (procs, addrs)."""
    ports = [_free_port() for _ in range(n_shards)]
    procs = []
    for k in range(n_shards):
        argv = [sys.executable, "-m", "paddle_tpu", "pserver",
                "--shard", f"{k}/{n_shards}", "--host", HOST,
                "--port", str(ports[k])]
        procs.append(subprocess.Popen(
            argv, env=_env(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True))
    for p in procs:
        deadline = time.monotonic() + READY_TIMEOUT
        while True:
            line = p.stdout.readline()
            if '"pserver"' in line:
                break
            if p.poll() is not None or time.monotonic() > deadline:
                raise RuntimeError("pserver failed to start")
    return procs, [(HOST, port) for port in ports]


def _stop_fleet(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()


def _remote(name, cfg, addrs, **kw):
    from paddle_tpu.sparse.client import RemoteSparseTable
    return RemoteSparseTable(name, cfg["vocab"], cfg["dim"], addrs=addrs,
                             optimizer="adagrad", learning_rate=0.05,
                             seed=3, **kw)


def _feed(cfg, rounds, batch, seed):
    """(ids, grads) rounds drawn from the warm working set."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(rounds):
        ids = rng.choice(cfg["warm_rows"], size=batch,
                         replace=False).astype(np.int64)
        out.append((ids, rng.standard_normal(
            (batch, cfg["dim"])).astype(np.float32)))
    return out


def _pctl(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q))


# -- arms --------------------------------------------------------------------

def run_wire_ab(cfg, addrs, quiet=False):
    """Batched zero-copy binary frames vs the naive per-row JSON arm.
    Same server process, same feed schedule; separate tables so state
    never crosses arms.  The tentpole gate: min_speedup=3.0."""
    from paddle_tpu.tuning.search import paired_ab

    arms = {}
    for mode in ("naive", "binary"):
        rt = _remote(f"ab_{mode}", cfg, addrs, wire_mode=mode)
        rt.pull(np.arange(cfg["warm_rows"], dtype=np.int64))  # warm init
        arms[mode] = {"rt": rt, "cursor": 0}
    n_windows = (max(2, cfg["ab_pairs"]) + 1) * cfg["ab_rounds"]
    feeds = _feed(cfg, n_windows, cfg["ab_batch"], seed=1)

    def measure(config):
        arm = arms[config["wire"]]
        lo = arm["cursor"]
        arm["cursor"] += cfg["ab_rounds"]
        window = feeds[lo:lo + cfg["ab_rounds"]]
        assert len(window) == cfg["ab_rounds"], "feed schedule exhausted"
        for ids, g in window:
            arm["rt"].pull(ids)
            arm["rt"].push(ids, g)

    ab = paired_ab(measure, {"wire": "naive"}, {"wire": "binary"},
                   pairs=cfg["ab_pairs"], warmup=1, min_speedup=3.0)
    ab["rows_per_window"] = cfg["ab_batch"] * cfg["ab_rounds"]
    # both arms trained the same schedule: the fleet must hold
    # bit-identical rows for them (the naive arm is slow, not wrong)
    a = arms["naive"]["rt"].export_state_vars()
    b = arms["binary"]["rt"].export_state_vars()
    ab["arms_bit_identical"] = all(
        a[k.replace("ab_binary", "ab_naive")].tobytes() == b[k].tobytes()
        for k in b if not k.endswith("/meta"))
    for arm in arms.values():
        arm["rt"].close()
    if not quiet:
        print(json.dumps({"arm": "wire_ab", "speedup": ab["speedup"],
                          "accepted": ab["accepted"]}), flush=True)
    return ab


def run_trace_overhead_ab(cfg, addrs, quiet=False):
    """Context-propagation cost (ISSUE 20): the SAME pull/push schedule
    through an observing client (client spans built, ctx injected into
    every frame header, server-side span + srv timing piggyback in every
    reply) vs an observe-off client (byte-identical pre-tracing wire).
    Paired alternating windows; no metrics_log in either arm, so this
    isolates the propagation machinery from JSONL disk writes.

    The verdict field is ``overhead_frac`` (median on/off ratio - 1)
    against the pre-registered ``overhead_budget`` of 5% — committed
    honestly either way (``paired_ab``'s ``accepted`` is NOT the verdict
    here: the A/B harness is reused for its windowing + raw evidence)."""
    from paddle_tpu.tuning.search import paired_ab

    arms = {}
    for observe in (True, False):
        rt = _remote(f"trace_{'on' if observe else 'off'}", cfg, addrs,
                     observe=observe)
        rt.pull(np.arange(cfg["warm_rows"], dtype=np.int64))  # warm init
        arms[observe] = {"rt": rt, "cursor": 0}
    n_windows = (max(2, cfg["trace_pairs"]) + 1) * cfg["trace_rounds"]
    feeds = _feed(cfg, n_windows, cfg["trace_batch"], seed=7)

    def measure(config):
        arm = arms[config["observe"]]
        lo = arm["cursor"]
        arm["cursor"] += cfg["trace_rounds"]
        window = feeds[lo:lo + cfg["trace_rounds"]]
        assert len(window) == cfg["trace_rounds"], "schedule exhausted"
        for ids, g in window:
            arm["rt"].pull(ids)
            arm["rt"].push(ids, g)

    # default = observe ON, candidate = OFF: the median pair ratio IS
    # on/off, so overhead_frac falls straight out of the windows
    ab = paired_ab(measure, {"observe": True}, {"observe": False},
                   pairs=cfg["trace_pairs"], warmup=1)
    for arm in arms.values():
        arm["rt"].close()
    overhead = ab["speedup"] - 1.0
    row = {
        "rows_per_window": cfg["trace_batch"] * cfg["trace_rounds"],
        "overhead_frac": round(overhead, 4),
        "overhead_budget": 0.05,
        "within_budget": bool(overhead <= 0.05),
        "pair_ratios_on_over_off": ab["pair_ratios"],
        "observe_on_windows": ab["default_windows"],
        "observe_off_windows": ab["candidate_windows"],
        # pre-registered context for an over-budget verdict: the ON arm
        # pays the ENTIRE observe-enabled client path (PR 10 wire
        # timers + histograms + spans), not just this PR's ctx/srv
        # fields, and loopback RPCs on a 1-core container are
        # sub-millisecond, so fixed per-RPC Python cost inflates the
        # relative number far beyond what a network-bound fleet sees
        "note": ("on-arm = full observe-enabled client (spans + wire "
                 "timers + ctx + srv absorb) vs observe-off; loopback "
                 "sub-ms RPCs make fixed per-RPC cost dominate"),
    }
    if not quiet:
        print(json.dumps({"arm": "trace_overhead_ab",
                          "overhead_frac": row["overhead_frac"],
                          "within_budget": row["within_budget"]}),
              flush=True)
    return row


def run_remote_pull_latency(cfg, addrs, quiet=False):
    """p50/p99 of warm batched remote pulls, next to the identical
    workload against an in-process vectorized SparseTable (the PR 15
    hot path this tier serves)."""
    from paddle_tpu.sparse import SparseTable

    rt = _remote("lat", cfg, addrs)
    local = SparseTable("lat_local", cfg["vocab"], cfg["dim"],
                        optimizer="adagrad", learning_rate=0.05, seed=3,
                        impl="vectorized")
    warm = np.arange(cfg["warm_rows"], dtype=np.int64)
    rt.pull(warm)
    local.pull(warm)
    rng = np.random.RandomState(2)
    remote_ms, local_ms = [], []
    for _ in range(cfg["latency_reps"]):
        ids = rng.choice(cfg["warm_rows"], size=cfg["pull_batch"],
                         replace=False).astype(np.int64)
        t0 = time.perf_counter()
        rt.pull(ids)
        remote_ms.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        local.pull(ids)
        local_ms.append((time.perf_counter() - t0) * 1e3)
    rt.close()
    row = {
        "pull_batch": cfg["pull_batch"],
        "reps": cfg["latency_reps"],
        "remote_ms": {"p50": round(_pctl(remote_ms, 50), 3),
                      "p99": round(_pctl(remote_ms, 99), 3)},
        "in_process_ms": {"p50": round(_pctl(local_ms, 50), 3),
                          "p99": round(_pctl(local_ms, 99), 3)},
        "wire_overhead_p50_ms": round(
            _pctl(remote_ms, 50) - _pctl(local_ms, 50), 3),
    }
    if not quiet:
        print(json.dumps({"arm": "remote_pull_latency", **row}),
              flush=True)
    return row


def run_shard_pipelining_ab(cfg, quiet=False):
    """1-shard vs 2-shard fleet under pipelined rounds.  Per-round wire
    latency is max-not-sum by construction; kernel throughput gains
    need real parallel cores — the verdict on this box is committed
    either way."""
    from paddle_tpu.tuning.search import paired_ab

    fleets, procs = {}, []
    for n in (1, 2):
        ps, addrs = _launch_fleet(n)
        procs += ps
        rt = _remote("pipe", cfg, addrs)
        rt.pull(np.arange(cfg["warm_rows"], dtype=np.int64))
        fleets[n] = {"rt": rt, "cursor": 0}
    n_windows = (max(2, cfg["pipe_pairs"]) + 1) * cfg["pipe_rounds"]
    feeds = _feed(cfg, n_windows, cfg["pipe_batch"], seed=4)

    def measure(config):
        arm = fleets[config["shards"]]
        lo = arm["cursor"]
        arm["cursor"] += cfg["pipe_rounds"]
        window = feeds[lo:lo + cfg["pipe_rounds"]]
        assert len(window) == cfg["pipe_rounds"], "schedule exhausted"
        for ids, g in window:
            arm["rt"].pull(ids)
            arm["rt"].push(ids, g)

    ab = paired_ab(measure, {"shards": 1}, {"shards": 2},
                   pairs=cfg["pipe_pairs"], warmup=1)
    ab["rows_per_window"] = cfg["pipe_batch"] * cfg["pipe_rounds"]
    ab["effective_cores"] = len(os.sched_getaffinity(0)) \
        if hasattr(os, "sched_getaffinity") else os.cpu_count()
    for arm in fleets.values():
        arm["rt"].close()
    _stop_fleet(procs)
    if not quiet:
        print(json.dumps({"arm": "shard_pipelining_ab",
                          "speedup": ab["speedup"],
                          "accepted": ab["accepted"]}), flush=True)
    return ab


def run_all(cfg=None, smoke=False, quiet=False):
    cfg = cfg or (SMOKE if smoke else FULL)
    procs, addrs = _launch_fleet(1)
    try:
        wire_ab = run_wire_ab(cfg, addrs, quiet=quiet)
        latency = run_remote_pull_latency(cfg, addrs, quiet=quiet)
        trace_overhead = run_trace_overhead_ab(cfg, addrs, quiet=quiet)
    finally:
        _stop_fleet(procs)
    pipelining = run_shard_pipelining_ab(cfg, quiet=quiet)
    return {
        "config": dict(cfg),
        "wire_ab": wire_ab,
        "remote_pull_latency": latency,
        "trace_overhead_ab": trace_overhead,
        "shard_pipelining_ab": pipelining,
        "smoke": bool(smoke),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="fast path check (tiny sizes); does not "
                         "overwrite the committed results file")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    row = run_all(smoke=args.smoke)
    print(json.dumps(row, indent=1))
    if args.smoke:
        return
    result = {
        "benchmark": "pserver_wire",
        "device": "cpu (in-container; no TPU reachable)",
        "cpu": row,
        "tpu": {
            "status": "pending-hardware",
            "plan": "re-run benchmark/pserver.py on a chip-host fleet: "
                    "shard servers on separate hosts give the "
                    "pipelining arm real parallel kernels and NIC-level "
                    "scatter-gather; the wire_ab gate is host-side and "
                    "should hold as-is",
            "rows": [],
        },
    }
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
