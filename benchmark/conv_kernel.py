"""Per-op A/B microbenchmark: XLA's conv emitter vs the hand-written
Pallas 1x1-conv kernels (ops/pallas_conv.py) on ResNet-50's eligible
1x1 shapes — the workload RESULTS.md round 5 identified as the binding
constraint (1x1/gradient convs at ~51 TFLOP/s against a 57-115 TFLOP/s
corrected-roofline ceiling).

Per (shape, pass) row both implementations run the identical math:

    fwd    out = conv1x1(x, w)
    dgrad  dx  = d/dx sum(conv1x1(x, w) * g)     (isolated via jax.grad)
    wgrad  dw  = d/dw sum(conv1x1(x, w) * g)     (the worst measured pass)
    wgrad_fused  Pallas: wgrad + per-channel gout sum fused in the K
                 stream; XLA: wgrad conv + the separate reduction XLA
                 emits for the bias/BN-beta gradient

Methodology: the pinned compiled-window scheme (RESULTS.md round 4) —
each timed window is ONE dispatch of a lax.scan over ``--steps``
iterations whose carry perturbs the weight by a data-dependent ~0 so no
iteration hoists; median of ``--reps`` windows, spread reported.

Run:    python benchmark/conv_kernel.py               (TPU, bf16)
        python benchmark/conv_kernel.py --interpret   (CPU correctness
                                                       pass, tiny shapes)
Writes: benchmark/conv_kernel_results.json
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                   # noqa: E402
import jax.numpy as jnp                      # noqa: E402
from jax import lax                          # noqa: E402

from paddle_tpu.ops.pallas_conv import (  # noqa: E402
    _from_pixel_major, _to_pixel_major, pallas_matmul)
# the shared measurement harness (paddle_tpu.tuning.search): warmup
# discard, median of windows, spread — this benchmark is a thin driver
# over it since the autotuner PR
from paddle_tpu.tuning.search import time_windows  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "conv_kernel_results.json")
DN = ("NCHW", "OIHW", "NCHW")

# ResNet-50 bs128: every 1x1 shape the routing gate accepts (the
# 64-channel stage-1/2 blocks stay on XLA and are not measured)
SHAPES = [
    # (name, N, C, H, W, M, stride)
    ("c512_m128_hw28", 128, 512, 28, 28, 128, 1),
    ("c128_m512_hw28", 128, 128, 28, 28, 512, 1),
    ("c1024_m256_hw14", 128, 1024, 14, 14, 256, 1),
    ("c256_m1024_hw14", 128, 256, 14, 14, 1024, 1),
    ("c2048_m512_hw7", 128, 2048, 7, 7, 512, 1),
    ("c512_m2048_hw7", 128, 512, 7, 7, 2048, 1),
    ("c1024_m2048_s2_hw14", 128, 1024, 14, 14, 2048, 2),
]
INTERPRET_SHAPES = [("tiny_c128_m256_hw16", 2, 128, 16, 16, 256, 1)]


def _xla_conv(x, w, stride):
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(0, 0), (0, 0)], dimension_numbers=DN)


def _pallas_conv(x, w, stride, interpret):
    from paddle_tpu.ops.pallas_conv import conv2d_1x1
    return conv2d_1x1(x, w, (stride, stride), interpret=interpret)


def _views(x, g, w, stride):
    """The matmul views the Pallas per-pass rows operate on — via the
    kernel module's own layout helpers, so the benchmark times exactly
    the relayouts the shipped path pays (input relayouts here; the
    dgrad row also pays the output relayout + stride scatter below)."""
    xs = x[:, :, ::stride, ::stride] if stride != 1 else x
    xm, dims = _to_pixel_major(xs)
    gm, _ = _to_pixel_major(g)
    return xm, gm, w.reshape(w.shape[0], w.shape[1]), dims


def make_step(impl, pas, stride, interpret):
    """(x, w, g) -> scalar the scan carry chains on; one op per step."""
    if impl == "xla":
        if pas == "fwd":
            def f(x, w, g):
                return jnp.sum(_xla_conv(x, w, stride) * g)
        elif pas == "dgrad":
            def f(x, w, g):
                dx = jax.grad(lambda x_: jnp.sum(
                    _xla_conv(x_, w, stride) * g))(x)
                return jnp.sum(dx * dx[..., :1, :1])
        elif pas == "wgrad":
            def f(x, w, g):
                dw = jax.grad(lambda w_: jnp.sum(
                    _xla_conv(x, w_, stride) * g))(w)
                return jnp.sum(dw * dw[..., :1, :, :])
        else:                                   # wgrad_fused A/B partner:
            def f(x, w, g):                     # wgrad + separate bias sum
                dw = jax.grad(lambda w_: jnp.sum(
                    _xla_conv(x, w_, stride) * g))(w)
                dsum = jnp.sum(g, axis=(0, 2, 3))
                return jnp.sum(dw * dw[..., :1, :, :]) + jnp.sum(dsum)
        return f

    from paddle_tpu.ops.pallas_conv import _mm
    if pas == "fwd":
        def f(x, w, g):
            return jnp.sum(_pallas_conv(x, w, stride, interpret) * g)
    elif pas == "dgrad":
        def f(x, w, g):
            # pay everything the shipped VJP pays: the dot, the
            # pixel-major -> NCHW output relayout, and (stride > 1) the
            # zero-scatter back to the input grid — the XLA row's dx has
            # all three baked into its conv, so omitting them here would
            # bias pallas_speedup upward
            _, gm, wm, dims = _views(x, g, w, stride)
            dxm = pallas_matmul(gm, wm, False, False, 512, 512, 1024,
                                interpret)
            dx = _from_pixel_major(dxm, dims, w.shape[1])
            if stride != 1:
                dx = jnp.zeros(x.shape, x.dtype) \
                    .at[:, :, ::stride, ::stride].set(dx)
            return jnp.sum(dx * dx[..., :1, :1])
    elif pas == "wgrad":
        def f(x, w, g):
            xm, gm, _, _ = _views(x, g, w, stride)
            dw = _mm(gm, xm, True, False, 512, 512, 1024, interpret)
            return jnp.sum(dw * dw[:1])
    else:                                       # wgrad + fused dsum epilogue
        def f(x, w, g):
            xm, gm, _, _ = _views(x, g, w, stride)
            dw, dsum = _mm(gm, xm, True, False, 512, 512, 1024, interpret,
                           a_colsum=True)
            return jnp.sum(dw * dw[:1]) + jnp.sum(dsum)
    return f


def run_row(name, N, C, H, W, M, stride, steps, reps, dtype, interpret):
    OH, OW = (H - 1) // stride + 1, (W - 1) // stride + 1
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, C, H, W), dtype)
    w = jnp.asarray(rng.randn(M, C, 1, 1) * 0.05, dtype)
    g = jnp.asarray(rng.randn(N, M, OH, OW), dtype)
    P = N * OH * OW
    flops = 2.0 * P * C * M                       # per pass per step
    row = {"shape": name, "P": P, "C": C, "M": M, "stride": stride,
           "steps": steps, "passes": {}}
    for pas in ("fwd", "dgrad", "wgrad", "wgrad_fused"):
        times = {}
        for impl in ("xla", "pallas"):
            step = make_step(impl, pas, stride, interpret)

            @functools.partial(jax.jit, static_argnames=("n",))
            def window(x, w, g, n):
                def body(carry, _):
                    xc, wc, gc = carry
                    s = step(xc, wc, gc)
                    # data-dependent ~0 perturbation on EVERY operand so
                    # no pass's op is loop-invariant (dgrad reads only
                    # (w, g), wgrad only (x, g) — perturbing w alone
                    # would let XLA hoist those out of the scan)
                    f = (1.0 - 1e-12 * s)
                    return tuple(t * f.astype(t.dtype) for t in carry), s
                _, ss = lax.scan(body, (x, w, g), None, length=n)
                return ss[-1]

            # engine harness: warmup window pays the compile, timed
            # windows materialize the scalar (the completion barrier)
            tw = time_windows(lambda: float(window(x, w, g, steps)),
                              reps=reps, warmup=1, unit=steps)
            med = tw["seconds"]
            times[impl] = {
                "ms": round(med * 1e3, 3),
                "tflops": round(flops / med / 1e12, 1),
                "spread_pct": tw["spread_pct"]}
        times["pallas_speedup"] = round(
            times["xla"]["ms"] / times["pallas"]["ms"], 3)
        row["passes"][pas] = times
        print(json.dumps({"shape": name, "pass": pas, **times}),
              flush=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU correctness pass on a tiny shape (timings "
                         "meaningless; asserts nothing crashes end-to-end)")
    args = ap.parse_args()
    shapes = INTERPRET_SHAPES if args.interpret else SHAPES
    steps = 2 if args.interpret else args.steps
    reps = 1 if args.interpret else args.reps
    dtype = jnp.dtype(args.dtype)
    results = {"device": str(jax.devices()[0]), "dtype": str(dtype),
               "steps": steps, "rows": []}
    for spec in shapes:
        results["rows"].append(
            run_row(*spec, steps=steps, reps=reps, dtype=dtype,
                    interpret=args.interpret))
    if not args.interpret:
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
