"""Inference benchmark on the AOT StableHLO deploy path (VERDICT r4
Missing #5): the reference PUBLISHED inference throughput for ResNet-50
bs1/4/16 (benchmark/IntelOptimizedPaddle.md:81-85 — 107.8 / 182.7 / 217.7
img/s on 2x Skylake 6148); this measures the same metric for the exported
artifact (export_model.py) on the real chip, plus the seq2seq beam
decoder, and writes benchmark/inference_results.json.

Methodology: the artifact is loaded fresh via ``load_compiled_model`` (the
deploy-ABI binding — parameters baked in, no Program/Scope), then M calls
are dispatched back-to-back and only the LAST output is fetched; devices
queue async dispatches, so total/M approximates device step time with the
host/tunnel round trip paid once (measured separately as ``latency_s``,
which on this tunneled setup is ~0.1 s and would otherwise swamp bs1).
Single-call round-trip latency is reported alongside — that is what an
on-host server without pipelining would see.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers, models  # noqa: E402

# artifact-loading/feed-synthesis shared with benchmark/serving.py — the
# deploy-ABI benchmark and the serving benchmark measure ONE model/
# manifest path (ISSUE 8 satellite: no drift between the two)
from benchmark.serving_common import (closed_loop,  # noqa: E402
                                      feeds_from_manifest, load_artifact,
                                      percentile, single_example)

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "inference_results.json")


def _force(x):
    return np.asarray(x[0]).ravel()[:1]


def _time_pipelined(run, feeds, out_count_per_call, windows=5, target_s=2.0):
    import jax
    feeds = jax.device_put(feeds)       # stage once; calls then enqueue
    out = run(feeds)
    _force(out)
    t0 = time.perf_counter()
    _force(run(feeds))
    per_call_rt = time.perf_counter() - t0          # incl. tunnel round trip
    M = max(10, int(target_s / max(per_call_rt, 1e-4)))
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(M - 1):
            out = run(feeds)
        out = run(feeds)
        _force(out)
        times.append((time.perf_counter() - t0) / M)
    med = float(np.median(times))
    return {"per_call_s": med,
            "throughput_per_s": out_count_per_call / med,
            "latency_roundtrip_s": per_call_rt, "calls_per_window": M,
            "spread_pct": 100.0 * (max(times) - min(times)) / med}


def _time_device_scan(run, feeds, out_count_per_call, est_call_s,
                      windows=5):
    """True device step time: K chained calls inside ONE jit dispatch (a
    lax.scan whose carry is a data-dependent ~0 perturbation of the feed,
    so XLA cannot hoist or elide iterations) — the inference analog of the
    training benches' run_steps methodology.  Removes host dispatch and
    tunnel latency entirely."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    feeds = jax.device_put(feeds)
    name = next(n for n, v in feeds.items())
    float_feed = jnp.issubdtype(feeds[name].dtype, jnp.floating)

    @functools.partial(jax.jit, static_argnames=("k",))
    def runk(feeds, k):
        def body(c, _):
            f = dict(feeds)
            f[name] = f[name] + c.astype(f[name].dtype)
            outs = run(f)
            dep = next(o for o in outs
                       if jnp.issubdtype(o.dtype, jnp.floating))
            d = dep.ravel()[0] * 1e-30      # data-dependent, ~0 numerically
            return (d if float_feed else d.astype(jnp.int64)), None
        c, _ = lax.scan(body, jnp.zeros((), jnp.float32)
                        if float_feed else jnp.zeros((), jnp.int64),
                        None, length=k)
        return c

    warmed = set()

    def window(k, n=1):
        if k not in warmed:                 # compile/warm once per k
            _force([runk(feeds, k)])
            warmed.add(k)
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            _force([runk(feeds, k)])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    lat = window(1, n=3)                    # round-trip floor
    # adaptive k: the device step can be orders of magnitude under the
    # ~0.1 s tunnel round trip (bs1 ResNet fwd is sub-millisecond), so
    # probe and scale until the scan body dominates the window
    k = int(np.clip(1.5 / max(est_call_s, 1e-3), 64, 512))
    probe = window(k)
    est = max((probe - lat) / k, 2e-7)
    k = int(np.clip(1.0 / est, k, 20000))
    times = [window(k) for _ in range(windows)]
    med = float(np.median(times))
    eff = max((med - lat) / k, 1e-9)
    return {"device_step_s": eff,
            "device_throughput_per_s": out_count_per_call / eff,
            "k": k, "latency_floor_s": lat,
            "device_spread_pct": 100.0 * (max(times) - min(times)) / med}


def export_resnet50(tmpdir="/tmp/pt_infer_resnet"):
    """Export the ResNet-50 inference artifact (shared by the throughput
    benches below and by ``--server`` mode)."""
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    img = layers.data("img", shape=[3, 224, 224], dtype="float32")
    pred = models.resnet50(img, num_classes=1000)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    pt.export_compiled_model(tmpdir, {"img": ((-1, 3, 224, 224), "float32")},
                             [pred])
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    return tmpdir


def bench_resnet50(batches=(1, 4, 16, 64, 128), tmpdir="/tmp/pt_infer_resnet"):
    run, manifest = load_artifact(export_resnet50(tmpdir))
    rows = {}
    rng = np.random.RandomState(0)
    for b in batches:
        feeds = feeds_from_manifest(manifest, b, rng)
        r = _time_pipelined(run, feeds, out_count_per_call=b)
        r.update(_time_device_scan(run, feeds, out_count_per_call=b,
                                   est_call_s=r["per_call_s"]))
        rows[f"bs{b}"] = r
        print(json.dumps({"resnet50_infer": f"bs{b}", **r}), flush=True)
    return rows


def bench_seq2seq_decode(batches=(1, 16, 64), tmpdir="/tmp/pt_infer_s2s"):
    """Beam-4 decoding, src len 30, max 30 generated tokens, d512,
    vocab 30k — the training benchmark's config on the generation path."""
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    V, T = 30000, 30
    src = layers.data("src", shape=[T], dtype="int64")
    ids, scores, lens = models.seq2seq_infer(
        src, src_vocab_size=V, tgt_vocab_size=V, emb_dim=512,
        hidden_dim=512, beam_size=4, max_len=T)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    pt.export_compiled_model(tmpdir, {"src": ((-1, T), "int64")},
                             [ids, scores, lens])
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    run, _ = pt.load_compiled_model(tmpdir)
    rows = {}
    rng = np.random.RandomState(0)
    for b in batches:
        feeds = {"src": rng.randint(2, V, (b, T)).astype("int64")}
        # tokens/s accounting: B x max_len best-hypothesis tokens out.
        # No device-scan variant here: a beam decode call is tens of ms,
        # far above the dispatch floor, and each extra scan length costs
        # another multi-minute decoder compile
        r = _time_pipelined(run, feeds, out_count_per_call=b * T)
        rows[f"bs{b}"] = r
        print(json.dumps({"seq2seq_beam4_decode": f"bs{b}", **r}),
              flush=True)
    return rows


def bench_server(tmpdir="/tmp/pt_infer_resnet", duration_s=4.0,
                 workers=32, max_batch=16, max_wait_ms=5.0,
                 model_name="resnet50"):
    """``--server`` mode: drive the SAME exported artifact through the
    serving runtime (paddle_tpu.serving.Server) instead of raw
    ``load_compiled_model`` calls — the deploy-ABI benchmark and the
    serving benchmark share one model/manifest path, and this row is the
    server-mediated counterpart of the raw per-call rows above (the
    delta is the batching/admission layer's cost and win)."""
    from paddle_tpu.serving import Model, Server
    from paddle_tpu.serving.server import _buckets

    if not os.path.exists(os.path.join(tmpdir, "manifest.json")):
        if model_name != "resnet50":
            raise SystemExit(f"--artifact {tmpdir!r}: no manifest.json")
        export_resnet50(tmpdir)
    _, manifest = load_artifact(tmpdir)
    rng = np.random.RandomState(0)
    example = single_example(manifest, rng)

    # warm EVERY bucket (same fidelity rule as benchmark/serving.py's
    # _make_server): a mid-window compile would smear seconds of one-off
    # cost into the p50/p99 this row is compared on
    srv = Server(max_batch=max_batch, max_wait_ms=max_wait_ms,
                 deadline_ms=None, queue_capacity=max(256, 4 * workers),
                 warmup_buckets=_buckets(max_batch))
    srv.add_model(Model.from_artifact(tmpdir, name=model_name))
    srv.start()
    try:
        lat, loop_row = closed_loop(srv, example, workers=workers,
                                    duration_s=duration_s)
        health = srv.health()["models"][model_name]
    finally:
        srv.shutdown(drain=True)
    lat_ms = [v * 1e3 for v in lat]
    row = {
        "model": model_name, "artifact": tmpdir,
        "max_batch": max_batch, "max_wait_ms": max_wait_ms,
        **loop_row,
        "latency_ms_p50": round(percentile(lat_ms, 0.50), 2)
        if lat_ms else None,
        "latency_ms_p99": round(percentile(lat_ms, 0.99), 2)
        if lat_ms else None,
        "batches": health["batches"],
        "mean_batch": round(health["served"] / health["batches"], 2)
        if health["batches"] else None,
    }
    print(json.dumps({"server": row}), flush=True)
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description="deploy-ABI inference "
                                 "benchmark (see module docstring)")
    ap.add_argument("which", nargs="*", default=["resnet50", "seq2seq"],
                    help="benches to run (resnet50, seq2seq)")
    ap.add_argument("--server", action="store_true",
                    help="drive the exported artifact through the "
                         "serving runtime (paddle_tpu serve engine) "
                         "instead of raw artifact calls")
    ap.add_argument("--duration-s", type=float, default=4.0)
    ap.add_argument("--workers", type=int, default=32)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--artifact", default=None,
                    help="serve this exported dir instead of the "
                         "resnet50 artifact (--server mode only)")
    args = ap.parse_args(argv)

    import jax
    results = {"device": str(jax.devices()[0])}
    if os.path.exists(OUT):                 # merge partial runs (keeps
        with open(OUT) as f:                # the committed rows' device
            results.update(json.load(f))    # provenance intact)
    if args.server:
        kw = {}
        if args.artifact:
            kw = {"tmpdir": args.artifact,
                  "model_name": os.path.basename(
                      os.path.normpath(args.artifact))}
        results["server"] = {
            "device": str(jax.devices()[0]),
            **bench_server(duration_s=args.duration_s,
                           workers=args.workers,
                           max_batch=args.max_batch, **kw)}
    else:
        if "resnet50" in args.which:
            results["resnet50"] = bench_resnet50()
        if "seq2seq" in args.which:
            results["seq2seq_beam4"] = bench_seq2seq_decode()
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
