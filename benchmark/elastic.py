"""Elastic training service benchmark: the K=8 -> 4 -> 8 resize round.

Runs one real elastic job on this host (CPU workers; each is a full
training process over the master's slot-sharded exactly-once streams)
and commits the ROADMAP item 3 acceptance evidence to
``elastic_results.json``:

* a committed resize-boundary record per membership change, each with a
  planner re-plan for the surviving world size validating with ZERO
  PT030/PT031 findings;
* training-loss continuation across both boundaries: the first batches
  after a resize continue from the merged replicas' level (no reset to
  the cold-start loss) and the global step counter never rewinds;
* exactly-once task accounting (every chunk trained once per committed
  state, no loss, no double-count at any world size);
* drain/merge/re-plan wall times per boundary.

The TPU row is a pending-hardware stub per the PR 1 convention: on a
chip host the same boundary re-plans the real mesh (the committed plan's
GSPMD specs drive ``ShardedExecutor`` there) — re-run this driver and
commit the filled row.

Usage: python benchmark/elastic.py [--workers 8] [--smoke]
"""
import argparse
import glob
import json
import os
import pickle
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

CONF = """
settings(batch_size=8, learning_rate=0.1,
         learning_method=MomentumOptimizer(momentum=0.9))
x = data_layer('x', 32)
y = data_layer('label', 5)
h = fc_layer(input=x, size=64, act=ReluActivation())
h2 = fc_layer(input=h, size=64, act=ReluActivation())
out = fc_layer(input=h2, size=5, act=SoftmaxActivation())
outputs(classification_cost(input=out, label=y))
"""


def _make_data(root, n_chunks, recs_per_chunk, seed=7):
    rng = np.random.RandomState(seed)
    os.makedirs(root, exist_ok=True)
    # a learnable synthetic task (fixed random teacher): loss must FALL,
    # or the continuation claim would be vacuous
    w = rng.rand(32, 5)
    for i in range(n_chunks):
        recs = []
        for _ in range(recs_per_chunk):
            x = rng.rand(32).astype("float32")
            label = np.array([int(np.argmax(x @ w))], dtype="int64")
            recs.append((x, label))
        with open(os.path.join(root, f"part-{i:03d}.pickle"), "wb") as f:
            pickle.dump(recs, f)


def _load_events(events_dir):
    """[(resize_epoch, slot, stream_index, cost)] time-ordered by file
    append order per slot, replay-deduped by key."""
    rows = {}
    for p in sorted(glob.glob(os.path.join(events_dir, "slot-*.jsonl"))):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rows[(e["epoch"], e["slot"], e["e"])] = float.fromhex(e["c"])
    return [(k[0], k[1], k[2], v) for k, v in sorted(rows.items())]


def _phase_losses(events, epoch):
    return [c for ep, _s, _e, c in events if ep == epoch]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--shrink-to", type=int, default=4)
    ap.add_argument("--chunks", type=int, default=96)
    ap.add_argument("--recs", type=int, default=64)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes, result NOT committed")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "elastic_results.json"))
    args = ap.parse_args(argv)
    if args.smoke:
        args.workers, args.shrink_to = 2, 1
        args.chunks, args.recs = 6, 16

    from paddle_tpu.distributed.elastic import (ElasticConfig, ElasticJob,
                                                _worker_argv_for_config)
    from paddle_tpu.trainer_config_helpers import load_v1_config

    work = tempfile.mkdtemp(prefix="pt-elastic-bench-")
    conf = os.path.join(work, "conf.py")
    with open(conf, "w") as f:
        f.write(CONF)
    data = os.path.join(work, "data")
    _make_data(data, args.chunks, args.recs)
    chunks = sorted(glob.glob(os.path.join(data, "part-*.pickle")))
    events_dir = os.path.join(work, "events")
    os.makedirs(events_dir)
    root = os.path.join(work, "job")

    cfg = load_v1_config(conf)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    job = ElasticJob(ElasticConfig(
        workers=args.workers, data=chunks, root=root,
        worker_cmd=_worker_argv_for_config(conf, 8,
                                           events_dir=events_dir,
                                           heartbeat_interval_s=0.05),
        program=cfg.main_program, task_timeout_s=120.0,
        heartbeat_lease_s=60.0, drain_timeout_s=300.0,
        assume_batch=8, poll_s=0.05, env=env))
    job.start()

    n = len(chunks)
    milestones = [max(2, n // 6), max(4, n // 2)]

    def watcher():
        while job.master.stats()["done"] < milestones[0]:
            time.sleep(0.02)
        job.request_scale(args.shrink_to)          # shrink on "loss"
        while job.resize_epoch < 1 or \
                job.master.stats()["done"] < milestones[1]:
            time.sleep(0.02)
        job.request_scale(args.workers)            # regrow on rejoin

    t0 = time.time()
    threading.Thread(target=watcher, daemon=True).start()
    summary = job.run()
    wall = time.time() - t0

    events = _load_events(events_dir)
    records = [json.loads(line)
               for line in open(os.path.join(root, "records.jsonl"))]
    resizes = [r for r in records if r["event"] == "resize"]

    phases = []
    for ep in sorted({e[0] for e in events}):
        losses = _phase_losses(events, ep)
        world = next((r["world"] for r in records
                      if r["resize_epoch"] == ep), None)
        phases.append({
            "resize_epoch": ep, "world": world, "batches": len(losses),
            "first_losses": [round(v, 5) for v in losses[:4]],
            "last_losses": [round(v, 5) for v in losses[-4:]],
            "mean_loss_first_quarter": round(
                float(np.mean(losses[:max(1, len(losses) // 4)])), 5),
            "mean_loss_last_quarter": round(
                float(np.mean(losses[-max(1, len(losses) // 4):])), 5),
        })

    # continuation check: the first post-resize quarter must sit at or
    # below the pre-resize FIRST quarter (i.e. nothing reset to cold
    # start); strict monotone mean decrease is asserted end to end
    continuation = []
    for a, b in zip(phases, phases[1:]):
        continuation.append({
            "boundary": f"{a['world']}->{b['world']}",
            "pre_last_quarter": a["mean_loss_last_quarter"],
            "post_first_quarter": b["mean_loss_first_quarter"],
            "cold_start_first_quarter": phases[0][
                "mean_loss_first_quarter"],
            "continues": b["mean_loss_first_quarter"] <
            phases[0]["mean_loss_first_quarter"],
        })

    doc = {
        "host": {"cpu_count": os.cpu_count(),
                 "python": sys.version.split()[0]},
        "config": {"workers": args.workers, "shrink_to": args.shrink_to,
                   "chunks": n, "recs_per_chunk": args.recs,
                   "batch_size": 8, "smoke": bool(args.smoke)},
        "summary": summary,
        "wall_s": round(wall, 2),
        "resize_rounds": [{
            "reason": r["reason"], "world": r["world"],
            "resize_epoch": r["resize_epoch"],
            "replicas_merged": len(r["merged"]["merged_from"]),
            "plan_candidate": (r.get("plan") or {}).get("candidate"),
            "pt030_pt031_findings": (r.get("plan") or {}).get(
                "lint_findings"),
        } for r in resizes],
        "phases": phases,
        "loss_continuation": continuation,
        "exactly_once": {
            "tasks": n, "done": summary["task_stats"]["done"],
            "unique_batches_trained": len(events),
            "expected_batches": n * args.recs // 8,
        },
        "acceptance": {
            "resize_round": f"{args.workers}->{args.shrink_to}->"
                            f"{args.workers}",
            "all_replans_lint_clean": all(
                not r["plan"]["lint_findings"] for r in resizes
                if r.get("plan")),
            "committed_resize_records": len(resizes),
            "completed": summary["completed"],
            "zero_task_loss": summary["task_stats"]["done"] == n,
            "loss_continues_across_boundaries": all(
                c["continues"] for c in continuation),
        },
    }

    print(json.dumps(doc["acceptance"], indent=1))
    if not args.smoke:
        full = {
            "cpu": doc,
            "tpu": {
                "status": "pending hardware",
                "note": "re-run python benchmark/elastic.py on a chip "
                        "host and commit the filled row (PR 1 "
                        "convention); there the committed resize "
                        "plans' GSPMD specs drive ShardedExecutor "
                        "meshes of the surviving chip count instead "
                        "of worker-pool data parallelism alone",
                "rows": [],
            },
        }
        with open(args.out, "w") as f:
            json.dump(full, f, indent=1, sort_keys=True)
        print(f"wrote {args.out}")
    shutil.rmtree(work, ignore_errors=True)
    return 0 if all(doc["acceptance"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
