#!/usr/bin/env python
"""ISSUE 12 acceptance run: the eager per-op profiler over three real
training programs (wide_deep CTR, one CIFAR resnet basic block, a small
LSTM classifier), committed as ``benchmark/opprof_results.json``.

Each row is one ``observability.opprof.profile_program`` report reduced
to the acceptance facts:

* the per-op measured table sums to the eager-replay total within the
  pinned tolerance (``opprof.TOLERANCE`` = ``BUDGET_TOLERANCE`` = 15%),
* the top-3 ops by measured time are NAMED (with phase + roofline
  verdict where the static model joined),
* the ranked XLA-loses-here op classes, carrying the pre-registered
  Pallas-candidate rule IDs where one matches,
* the measured-vs-modeled peak-HBM position from the liveness walk.

The merged per-op-class calibration table (the format-2
``attribution.save_op_class_calibration`` document) is embedded under
``"calibration"`` — ``attribution.load_op_class_ratios`` reads it
directly and ``paddle_tpu plan --calibration benchmark/opprof_results...``
is NOT the supported spelling (the table is nested); use

    python -m paddle_tpu profile prog.json --calibration-out table.json
    python -m paddle_tpu plan prog.json --mesh dp=8 --calibration table.json

for the live workflow.  Run:

    python benchmark/opprof.py [--smoke] [--out PATH]

Prints one JSON line per model, then writes the results document.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "opprof_results.json")


# ---------------------------------------------------------------------------
# Model builders (fixed shapes, seeded feeds — reruns profile the same
# program on the same data)
# ---------------------------------------------------------------------------
def build_wide_deep(rng):
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    B, nsparse, vocab, dense_d = 64, 8, 1000, 13
    sparse = [layers.data(f"s{i}", shape=[1], dtype="int64")
              for i in range(nsparse)]
    dense = layers.data("dense", shape=[dense_d], dtype="float32")
    label = layers.data("label", shape=[1], dtype="float32")
    ctr = models.wide_deep(sparse, dense, [vocab] * nsparse)
    loss = layers.mean(layers.log_loss(ctr, label))
    pt.optimizer.Adam(1e-3).minimize(loss)
    feeds = {f"s{i}": rng.randint(0, vocab, (B, 1)) for i in range(nsparse)}
    feeds["dense"] = rng.rand(B, dense_d).astype("float32")
    feeds["label"] = rng.randint(0, 2, (B, 1)).astype("float32")
    return feeds, B


def build_resnet_block(rng):
    """One CIFAR basic block (conv-bn-relu x2 + residual add) + head —
    the conv/batch_norm op-class row without resnet-20's 60+ op walk."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.models.resnet import basic_block

    B = 16
    img = layers.data("img", shape=[16, 16, 16], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    block = basic_block(img, 16, 16, 1)
    pool = layers.pool2d(block, pool_type="avg", global_pooling=True)
    pred = layers.fc(pool, size=10, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    feeds = {"img": rng.rand(B, 16, 16, 16).astype("float32"),
             "label": rng.randint(0, 10, (B, 1))}
    return feeds, B


def build_lstm(rng):
    import paddle_tpu as pt
    from paddle_tpu import layers, models

    B, T, vocab = 16, 24, 2000
    words = layers.data("words", shape=[], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")
    pred = models.lstm_text_classification(
        words, vocab_size=vocab, num_classes=2, emb_dim=32,
        hidden_size=64, lstm_num=1)
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Adam(1e-3).minimize(loss)
    feeds = {"words": rng.randint(0, vocab, (B, T)),
             "words@LEN": np.full(B, T),
             "label": rng.randint(0, 2, (B, 1))}
    return feeds, B


MODELS = {"wide_deep": build_wide_deep,
          "resnet_block": build_resnet_block,
          "lstm": build_lstm}


# ---------------------------------------------------------------------------
def profile_model(name, *, reps, warmup):
    import paddle_tpu as pt
    from paddle_tpu.observability import opprof

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    rng = np.random.RandomState(7)
    feeds, batch = MODELS[name](rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    report = opprof.profile_program(
        pt.default_main_program(), executor=exe, feed=feeds,
        batch=batch, reps=reps, warmup=warmup)

    def top_row(r):
        out = {"op_type": r["op_type"], "index": r["index"],
               "phase": r["phase"], "wall_ms": r["wall_ms"],
               "share": round(r["wall_ms"] / report["per_op_sum_ms"], 4)
               if report["per_op_sum_ms"] else 0.0}
        m = r.get("modeled")
        if m:
            out["roofline"] = m["roofline"]
            out["ratio"] = r.get("ratio")
        return out

    mem = report["memory"]
    row = {
        "model": name, "program": report["program"],
        "batch": batch, "reps": reps, "warmup": warmup,
        "ops": report["ops"],
        "eager_total_ms": report["eager_total_ms"],
        "per_op_sum_ms": report["per_op_sum_ms"],
        "sum_gap_frac": report["sum_gap_frac"],
        "tolerance": report["tolerance"],
        "within_tolerance": report["within_tolerance"],
        "top3": [top_row(r) for r in report["top"][:3]],
        "xla_loses_here": report["xla_loses_here"][:5],
        "memory": {k: mem[k] for k in
                   ("state_bytes", "peak_bytes", "peak_index", "peak_op",
                    "modeled_peak_bytes", "peak_ratio") if k in mem},
    }
    return row, report["op_classes"]


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="reps=1/warmup=1 sanity pass; does not rewrite "
                         "the committed results unless --out is given")
    ap.add_argument("--reps", type=int, default=5,
                    help="timed windows per op (median; default 5)")
    ap.add_argument("--warmup", type=int, default=2,
                    help="discarded warmup windows per op (default 2)")
    ap.add_argument("--out", default=None,
                    help=f"results path (default {RESULTS_PATH}; "
                         f"--smoke without --out prints only)")
    args = ap.parse_args()
    reps, warmup = (1, 1) if args.smoke else (args.reps, args.warmup)

    rows = []
    op_classes = {}
    for name in MODELS:
        row, classes = profile_model(name, reps=reps, warmup=warmup)
        print(json.dumps(row), flush=True)
        rows.append(row)
        for c in classes:
            op_classes[f"{c['program']}:{c['op_type']}"] = c

    doc = {
        "description":
            "ISSUE 12 acceptance artifact: eager per-op profiles "
            "(observability.opprof) of three REAL in-container training "
            "steps — per-op measured table vs the one-shot eager-replay "
            "total (must reconcile within opprof.TOLERANCE=0.15), top-3 "
            "ops named with phase + roofline verdict, ranked "
            "XLA-loses-here op classes carrying the pre-registered "
            "Pallas-candidate rule IDs, and the liveness walk's "
            "measured-vs-modeled peak HBM.  'calibration' is the "
            "format-2 attribution calibration document whose op_classes "
            "section analysis.planner.plan(op_class_ratios=...) "
            "consumes via attribution.load_op_class_ratios.",
        "platform": "cpu (no TPU reachable this session; ~1 effective "
                    "host core — eager per-op walls are HOST-dominated "
                    "dispatch costs, so the measured/predicted ratios "
                    "calibrate the CPU fallback, not chip silicon; "
                    "rerun on hardware to commit chip ratios)",
        "rows": rows,
        "calibration": {"format": 2, "programs": {},
                        "op_classes": op_classes},
    }
    out = args.out or (None if args.smoke else RESULTS_PATH)
    if out:
        with open(out, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        print(json.dumps({"wrote": out,
                          "models": [r["model"] for r in rows],
                          "all_within_tolerance":
                          all(r["within_tolerance"] for r in rows)}),
              flush=True)


if __name__ == "__main__":
    main()
