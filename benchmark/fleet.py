"""Fleet-vs-single saturation + chaos benchmark (ISSUE 11 acceptance).

Measures the serving FLEET (N `paddle_tpu serve` replica processes
behind the queue-depth-aware router, paddle_tpu/serving/fleet.py)
against itself at N=1 — same artifact, same per-replica resources: each
replica is CPU-PINNED to one core (``sched_setaffinity``), so "add a
replica" means "add a core's worth of capacity", the horizontal-scaling
claim a fleet exists to make.  On this 2-core container that is N=1 vs
N=2; a chip host raises the sweep (replica-per-chip assignment replaces
core pinning).

Methodology (this box's external contention swings wall time 1.3-1.4x
run to run — PR 9/10 budget notes — so one-shot sequential comparisons
are junk):

* ``saturation`` — an escalating-rate open-loop ladder on the full
  fleet finds the saturating offered rate; the fleet-rim backlog shed
  keeps past-saturation arms from thrashing (replica-side shed pays
  wire+parse on a serving core first — measured ~40% throughput loss).
* ``capacity`` — fleet-of-1 vs fleet-of-2 as PAIRED ALTERNATING arms on
  the SAME running fleet: the r1 half CORDONS the second replica
  (administratively unroutable, process untouched) so the pair flips
  fleet size in milliseconds and both halves see the same contention
  regime.  Headline = median of per-pair r2/r1 ratios (PR 2/9
  convention).
* ``overload`` — open-loop at 1x and 2x measured capacity with
  deadlines + fleet-rim shedding: admitted p99 must stay bounded
  FLEET-WIDE, the PR 8 claim at fleet scope.
* ``chaos_sigkill`` — closed-loop load, one replica SIGKILLed mid-run:
  ZERO admitted requests dropped fleet-wide (in-flight work fails over
  to the survivor), and the victim relaunches through the supervisor
  gate back to ready.

Results land under the ``fleet`` key of benchmark/serving_results.json
(the single-server rows stay untouched); TPU rows follow the PR 1
pending-hardware convention.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmark.serving_common import (closed_loop, export_mlp,  # noqa: E402
                                      load_artifact, percentile,
                                      single_example)

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "serving_results.json")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    return dict(os.environ, JAX_PLATFORMS="cpu",
                PYTHONPATH=REPO + os.pathsep
                + os.environ.get("PYTHONPATH", ""))


def host_parallel_probe(duration_s: float = 3.0) -> dict:
    """The container's REAL parallel capacity, measured the PR 2 way
    (host_parallel_efficiency): GEMM throughput of one core-pinned
    process vs two pinned to different cores.  On this container the
    pair delivers ~1.2x of the single — the hypervisor hands out ~1.2
    effective cores regardless of the nominal count — which is the hard
    ceiling on ANY 2-replica speedup.  The fleet row is judged against
    this measured ceiling, not against an imaginary 2.0x."""
    import subprocess

    code = ("import os,sys,time;import numpy as np;"
            "os.sched_setaffinity(0,{int(sys.argv[1])});"
            "a=np.random.rand(1024,1024).astype('float32');b=a.copy();"
            "n=0;t0=time.perf_counter()\n"
            f"while time.perf_counter()-t0<{duration_s}: a@b; n+=1\n"
            "print(n/(time.perf_counter()-t0))")

    def run_one(core):
        return subprocess.Popen([sys.executable, "-c", code, str(core)],
                                stdout=subprocess.PIPE, text=True)

    p = run_one(0)
    single = float(p.communicate(timeout=duration_s * 10)[0])
    ps = [run_one(0), run_one(1)]
    pair = sum(float(q.communicate(timeout=duration_s * 10)[0])
               for q in ps)
    return {"single_gemms_per_s": round(single, 1),
            "pair_gemms_per_s": round(pair, 1),
            "pair_over_single": round(pair / max(1e-9, single), 3)}


def _make_router(model_dir, n, *, deadline_ms, queue, max_batch,
                 max_wait_ms, ncores, backlog_limit=None):
    from paddle_tpu.serving.fleet import (FleetRouter, ProcessReplica,
                                          serve_argv)

    argv = serve_argv([f"m={model_dir}"], max_batch=max_batch,
                      max_wait_ms=max_wait_ms, deadline_ms=deadline_ms,
                      queue=queue, warmup_all=True)

    def factory(i):
        return ProcessReplica(argv, name=f"replica{i}", env=_env(),
                              cpu_affinity=[i % ncores])

    return FleetRouter(factory, replicas=n, poll_interval_s=0.1,
                       max_restarts=3, backlog_limit=backlog_limit,
                       restart_backoff_base_s=0.1).start(
                           ready_timeout_s=600)


def open_loop(router, example, *, rate, duration_s, deadline_ms):
    """Fixed-rate submission against a RUNNING fleet; returns the
    admitted-latency row (the fleet analog of serving.py's arms)."""
    lock = threading.Lock()
    lat, errors = [], {}
    offered = served = 0
    interval = 1.0 / rate
    t_start = time.monotonic()
    t_last = t_start
    stop = t_start + duration_s
    pendings = []

    def on_done(fp):
        nonlocal served, t_last
        with lock:
            if fp.error is None:
                lat.append((time.monotonic() - fp.t_admit))
                served += 1
                t_last = time.monotonic()
            else:
                k = type(fp.error).__name__
                errors[k] = errors.get(k, 0) + 1

    next_t = time.monotonic()
    while time.monotonic() < stop:
        now = time.monotonic()
        if now < next_t:
            time.sleep(min(interval, next_t - now))
            continue
        next_t += interval
        offered += 1
        try:
            fp = router.submit(example, deadline_ms=deadline_ms)
        except BaseException as e:      # typed admission rejection
            with lock:
                k = type(e).__name__
                errors[k] = errors.get(k, 0) + 1
            continue
        fp.add_done_callback(on_done)
        pendings.append(fp)
    deadline = time.monotonic() + 60
    for fp in pendings:
        if not fp.done() and time.monotonic() < deadline:
            try:
                fp.result(timeout=max(0.1, deadline - time.monotonic()))
            except BaseException:
                pass
    with lock:
        lat.sort()
        # throughput over admit-to-last-completion wall: requests
        # admitted in the window but completed just past it count at
        # their true cost instead of vanishing
        wall = max(duration_s, t_last - t_start)
        row = {"offered_per_s": round(rate, 1), "offered": offered,
               "offered_actual_per_s": round(offered / duration_s, 1),
               "served": served,
               "served_per_s": round(served / wall, 1),
               "errors": dict(errors)}
        if lat:
            row["latency_ms_p50"] = round(percentile(lat, 0.50) * 1e3, 2)
            row["latency_ms_p99"] = round(percentile(lat, 0.99) * 1e3, 2)
        return row


def saturation_ladder(router, example, *, duration_s, deadline_ms,
                      start_rate):
    """Climb open-loop arms until served_per_s stops improving — keep
    climbing while an arm is visibly unsaturated (no rejections, served
    ~= offered) — and return (best_arm, ladder)."""
    best, ladder = None, []
    rate = start_rate
    for _step in range(7):
        arm = open_loop(router, example, rate=rate,
                        duration_s=duration_s, deadline_ms=deadline_ms)
        ladder.append({"offered_per_s": arm["offered_per_s"],
                       "served_per_s": arm["served_per_s"],
                       "shed": arm["errors"].get("Overloaded", 0)})
        unsaturated = (not arm["errors"]
                       and arm["served_per_s"]
                       >= 0.92 * arm["offered_actual_per_s"])
        if best is None or arm["served_per_s"] > \
                best["served_per_s"] * 1.05:
            best = arm
            rate *= 2.0 if unsaturated else 1.5
            continue
        if unsaturated:
            rate *= 2.0                 # not saturated yet: keep going
            continue
        break                           # plateaued: done
    return best, ladder


def paired_capacity(router, example, spare_name, *, pairs, duration_s,
                    deadline_ms, rate):
    """Fleet-of-1 vs fleet-of-2 as PAIRED ALTERNATING arms on the SAME
    running fleet: the r1 half cordons the second replica so the pair
    flips fleet size in milliseconds and both halves sit in the same
    contention regime.  Headline = median of per-pair r2/r1 ratios."""
    rows = []
    for k in range(pairs):
        router.cordon(spare_name)
        try:
            r1 = open_loop(router, example, rate=rate,
                           duration_s=duration_s,
                           deadline_ms=deadline_ms)
        finally:
            router.cordon(spare_name, cordoned=False)
        r2 = open_loop(router, example, rate=rate,
                       duration_s=duration_s, deadline_ms=deadline_ms)
        rows.append({
            "pair": k,
            "r1_served_per_s": r1["served_per_s"],
            "r2_served_per_s": r2["served_per_s"],
            "ratio": round(r2["served_per_s"]
                           / max(1e-9, r1["served_per_s"]), 3),
            "r1_p99_ms": r1.get("latency_ms_p99"),
            "r2_p99_ms": r2.get("latency_ms_p99"),
        })
        print(json.dumps({"pair": rows[-1]}), flush=True)
    ratios = sorted(r["ratio"] for r in rows)
    return {
        "pairs": rows,
        "r1_served_per_s_median": sorted(
            r["r1_served_per_s"] for r in rows)[len(rows) // 2],
        "r2_served_per_s_median": sorted(
            r["r2_served_per_s"] for r in rows)[len(rows) // 2],
        "speedup_median_of_pair_ratios": ratios[len(ratios) // 2],
        "pairs_favoring_r2": sum(1 for r in rows if r["ratio"] > 1.0),
    }


def chaos_arm(model_dir, example, *, duration_s, ncores, max_batch,
              max_wait_ms, workers=8):
    """SIGKILL one of two replicas under closed-loop load: zero admitted
    drops fleet-wide + supervisor relaunch back to ready."""
    import paddle_tpu as pt

    router = _make_router(model_dir, 2, deadline_ms=0, queue=4096,
                          max_batch=max_batch, max_wait_ms=max_wait_ms,
                          ncores=ncores)
    try:
        failovers0 = pt.observability.registry().snapshot()[
            "fleet/failovers"]["value"]
        victim = router.replicas[0]
        kill_at = time.monotonic() + duration_s / 3.0

        def killer():
            time.sleep(max(0.0, kill_at - time.monotonic()))
            victim.kill()

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        lat, row = closed_loop(router, example, workers=workers,
                               duration_s=duration_s, timeout_s=120.0)
        kt.join(timeout=30)
        relaunched = False
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if victim.state == "ready":
                relaunched = True
                break
            time.sleep(0.5)
        failovers = pt.observability.registry().snapshot()[
            "fleet/failovers"]["value"] - failovers0
        return {
            "replicas": 2, "sigkill_at_s": round(duration_s / 3.0, 2),
            "served": row["served"],
            "dropped": row["worker_errors"],   # closed_loop counts every
            # raised error; with shedding/deadlines off any error IS a
            # dropped admitted request
            "failovers": int(failovers),
            "victim_relaunched_ready": relaunched,
            "victim_restarts": getattr(victim, "restarts", 0),
            "latency_ms_p99": round(percentile(lat, 0.99) * 1e3, 2),
            "zero_admitted_drops": row["worker_errors"] == 0,
        }
    finally:
        router.shutdown(timeout_s=120)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny durations (CI smoke, numbers meaningless)")
    ap.add_argument("--duration-s", type=float, default=5.0)
    ap.add_argument("--pairs", type=int, default=4)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-ms", type=float, default=10.0)
    ap.add_argument("--deadline-ms", type=float, default=4000.0)
    ap.add_argument("--queue", type=int, default=64)
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args(argv)
    if args.smoke:
        args.duration_s, args.pairs = 1.5, 1

    ncores = os.cpu_count() or 1
    # sized for two constraints: (a) replica-bound — per-request model
    # time must dominate the ~0.2 ms routing/JSON-wire cost or the
    # router (one Python process sharing this 2-core host) is what
    # gets measured; (b) COMPUTE-bound, not bandwidth-bound — batch 32
    # over 50 MB of weights gives ~11 flops/byte, while a 4096-wide
    # model at batch 8 streams 200 MB/dispatch and saturates the
    # SHARED memory bus, which no replica count can scale
    model_dir = export_mlp("/tmp/pt_fleet_bench_mlp6", in_dim=64,
                           hidden=(2048,) * 6, classes=16)
    _, manifest = load_artifact(model_dir)
    rng = np.random.RandomState(0)
    example = single_example(manifest, rng)
    # pre-serialized wire form: the open-loop scheduler must not pay a
    # tolist() per submission
    example_wire = {k: v.tolist() for k, v in example.items()}

    result = {
        "engine": "process-replica fleet (paddle_tpu.serving.fleet): "
                  "N `paddle_tpu serve` subprocesses behind the "
                  "queue-depth router",
        "model": "mlp 64->2048x6->16 (symbolic-batch StableHLO "
                 "artifact, ~34 MFLOP/request; sized so (a) COMPUTE-"
                 "bound at batch 32 — a bandwidth-bound model cannot "
                 "scale with replicas on shared-memory-bus cores — and "
                 "(b) per-replica capacity sits well under the ~570/s "
                 "ceiling of the single-process Python load generator, "
                 "so offered load can actually exceed 2x one replica)",
        "host_cores": ncores,
        "replica_pinning": "sched_setaffinity: replica i -> core "
                           "i % ncores (identical per-replica "
                           "resources; the scaling claim is capacity "
                           "per added core)",
        "note": "router + load generator share the same host as the "
                "replicas on this container — fleet capacity is net of "
                "routing/JSON-wire overhead; capacity pairs alternate "
                "r1/r2 via cordon to cancel this box's 1.3-1.4x "
                "contention swings",
    }
    print(json.dumps({"phase": "host_parallel_probe"}), flush=True)
    probe = host_parallel_probe()
    result["host_parallel_probe"] = probe
    print(json.dumps({"host_parallel_probe": probe}), flush=True)

    router = _make_router(model_dir, 2, deadline_ms=args.deadline_ms,
                          queue=args.queue, max_batch=args.max_batch,
                          max_wait_ms=args.max_wait_ms, ncores=ncores,
                          backlog_limit=args.queue)
    try:
        for _ in range(6):              # settle both replicas
            router.infer(example, deadline_ms=None, timeout=120)
        print(json.dumps({"phase": "saturation_ladder"}), flush=True)
        sat, ladder = saturation_ladder(
            router, example_wire, duration_s=args.duration_s,
            deadline_ms=args.deadline_ms, start_rate=150.0)
        result["saturation"] = {**sat, "ladder": ladder}
        print(json.dumps({"saturation": result["saturation"]}),
              flush=True)
        sat_rate = max(sat["served_per_s"] * 1.3, 30.0)

        print(json.dumps({"phase": "paired_capacity",
                          "rate": round(sat_rate, 1)}), flush=True)
        cap = paired_capacity(
            router, example_wire, "replica1", pairs=args.pairs,
            duration_s=args.duration_s, deadline_ms=args.deadline_ms,
            rate=sat_rate)
        result["capacity_pairs"] = cap
        result["scaling"] = {
            "replicas": [1, 2],
            "req_per_s_median": [cap["r1_served_per_s_median"],
                                 cap["r2_served_per_s_median"]],
            "speedup": cap["speedup_median_of_pair_ratios"],
        }

        # overload envelope fleet-wide: 1x vs 2x of measured capacity
        cap2 = cap["r2_served_per_s_median"]
        arms = {}
        for factor in (1.0, 2.0):
            print(json.dumps({"phase": f"open_loop_{factor}x"}),
                  flush=True)
            arms[f"{factor}x"] = open_loop(
                router, example_wire, rate=max(1.0, cap2 * factor),
                duration_s=args.duration_s,
                deadline_ms=args.deadline_ms)
            print(json.dumps({f"{factor}x": arms[f"{factor}x"]}),
                  flush=True)
        result["overload"] = arms
    finally:
        router.shutdown(timeout_s=120)

    p99_1x = arms["1.0x"].get("latency_ms_p99")
    p99_2x = arms["2.0x"].get("latency_ms_p99")
    speedup = result["scaling"]["speedup"]
    ceiling = probe["pair_over_single"]
    # two ways to pass: the absolute claim (a real multi-core host), or
    # reaching >=85% of THIS host's measured 2-process ceiling — on this
    # container the hypervisor delivers ~1.2 effective cores no matter
    # what nominal count /proc advertises, so 1.2x IS perfect scaling
    # here and the absolute sweep belongs to the TPU-host pending row
    result["acceptance"] = {
        "host_parallel_ceiling_2proc": ceiling,
        "fleet_speedup": speedup,
        "fleet_over_ceiling": round(speedup / max(1e-9, ceiling), 3),
        "capacity_scales_with_replicas":
            (speedup > 1.2
             and cap["pairs_favoring_r2"] >= (args.pairs + 1) // 2)
            or speedup >= 0.85 * ceiling,
        "p99_1x_ms": p99_1x, "p99_2x_ms": p99_2x,
        "p99_ratio_2x_over_1x": (round(p99_2x / p99_1x, 3)
                                 if p99_1x and p99_2x else None),
        "bounded_under_overload": (bool(p99_1x and p99_2x
                                        and p99_2x < 5.0 * p99_1x)),
    }

    print(json.dumps({"phase": "chaos_sigkill"}), flush=True)
    result["chaos_sigkill"] = chaos_arm(
        model_dir, example, duration_s=max(4.0, args.duration_s),
        ncores=ncores, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms)
    print(json.dumps({"chaos_sigkill": result["chaos_sigkill"]}),
          flush=True)

    result["tpu"] = {
        "status": "pending hardware",
        "note": "re-run python benchmark/fleet.py on a chip host and "
                "commit the filled rows (PR 1 convention); replica "
                "pinning becomes per-chip assignment there",
        "rows": [],
    }

    if not args.smoke:
        existing = {}
        if os.path.exists(args.out):
            with open(args.out) as fh:
                existing = json.load(fh)
        existing["fleet"] = result
        tmp = args.out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(existing, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, args.out)
        print(json.dumps({"wrote": args.out}), flush=True)
    return result


if __name__ == "__main__":
    main()
