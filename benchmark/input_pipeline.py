#!/usr/bin/env python
"""Input-pipeline A/B: naive synchronous Trainer.train loop vs the
asynchronous pipelined path (``pipeline=`` -> ``Executor.run_pipelined``).

Two input-bound workloads, both trained on REAL decoded rows (synthetic
raw records generated once with a fixed seed; decode is genuine Python
parsing work of the kind the reference's readers did):

* ``wide_deep`` — Criteo-shaped CTR rows: ``label,dense...,field:value...``
  lines decoded by split + float parsing + feature hashing into 26 sparse
  ids + 13 dense floats (models/wide_deep).  Fixed shapes, so the
  pipelined arm chunks K batches per compiled-scan dispatch.
* ``lstm`` — imdb-shaped text classification: space-separated token
  strings decoded by tokenize + vocab lookup, padded to one bucket
  (models/lstm_textcls).

Methodology (same median-of-windows discipline as benchmark/RESULTS.md):
each measurement is a WINDOW of ``batches`` end-to-end training steps
through ``trainer.SGD.train``; the two arms alternate naive/pipelined
window pairs ``reps`` times so machine noise hits both arms equally, and
the per-arm MEDIAN with (max-min)/median spread is reported.  Warmup
windows (compiles) precede timing.  Numbers printed are measured in this
container on this run — never projected.

Usage:
    python benchmark/input_pipeline.py                  # full A/B, writes
                                                        # input_pipeline_results.json
    python benchmark/input_pipeline.py --smoke          # seconds-fast path check
    python benchmark/input_pipeline.py --workload wide_deep
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "input_pipeline_results.json")

NF_DENSE, NF_SPARSE = 13, 26


def host_parallel_efficiency():
    """Measured usable host-thread parallelism: two concurrent GIL-free
    numpy workloads vs one, ideal 2.0.  ~1.0 means the container delivers
    ONE effective core no matter what os.cpu_count() claims — then the
    pipeline's overlap cannot pay and only its serial savings (chunked
    scan dispatch, vectorized staging) show up in the A/B.  Recorded in
    the results JSON so every committed number carries the host context
    it was measured under."""
    import threading
    A = np.random.rand(1024, 1024).astype(np.float32)

    def work(out):
        t0 = time.perf_counter()
        for _ in range(3):
            (A @ A).sum()
        out.append(time.perf_counter() - t0)

    a = []
    work(a)          # warm
    a = []
    work(a)
    outs = [[], []]
    ts = [threading.Thread(target=work, args=(o,)) for o in outs]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    return round(2 * a[0] / wall, 2)


# ---------------------------------------------------------------------------
# synthetic raw data + decoders (the honest host-side work)
# ---------------------------------------------------------------------------
def make_ctr_lines(n, seed=0):
    """Criteo-shaped raw lines: 'label,i1 .. i13,f0:v f1:v ..' with the
    dense slots carrying RAW integer counts, ~45% of them missing (empty
    slot), and ~25% of the categorical field:value tokens absent — the
    shape of the actual Criteo logs."""
    r = np.random.RandomState(seed)
    lines = []
    for _ in range(n):
        label = r.randint(0, 2)
        dense = " ".join("" if r.rand() < 0.45 else "%d"
                         % r.randint(0, 65536) for _ in range(NF_DENSE))
        sparse = " ".join("f%d:%d" % (f, r.randint(0, 100000))
                          for f in range(NF_SPARSE) if r.rand() > 0.25)
        lines.append("%d,%s,%s" % (label, dense, sparse))
    return lines


def make_ctr_decoder(vocab):
    from math import log1p
    from zlib import crc32

    def decode(line):
        # the standard Criteo recipe: log1p-normalize the integer dense
        # features (missing -> 0), feature-hash the categorical
        # field:value tokens into per-field id slots (absent -> id 0).
        # crc32, not Python's hash(): feature hashing must be
        # deterministic across processes (train/serve skew otherwise —
        # hash() is randomized per process by PYTHONHASHSEED).
        lab, dense_s, sparse_s = line.split(",")
        dense = np.array([log1p(float(t)) if t else 0.0
                          for t in dense_s.split(" ")], np.float32)
        ids = [0] * NF_SPARSE
        for kv in sparse_s.split():
            f, _ = kv.split(":")
            ids[int(f[1:])] = crc32(kv.encode()) % vocab
        return tuple(ids) + (dense, np.float32(int(lab)))
    return decode


def make_text_lines(n, vocab, max_len, seed=0):
    """imdb-shaped raw docs: space-separated word tokens + label."""
    r = np.random.RandomState(seed)
    words = ["w%d" % i for i in range(vocab)]
    lines = []
    for _ in range(n):
        L = r.randint(max_len // 4, max_len + 1)
        toks = " ".join(words[i] for i in r.randint(0, vocab, L))
        lines.append((toks, int(r.randint(0, 2))))
    return lines


def make_text_decoder(vocab, max_len):
    word_idx = {"w%d" % i: i for i in range(vocab)}
    unk = vocab - 1

    def decode(sample):
        text, label = sample
        ids = [word_idx.get(t, unk) for t in text.split()][:max_len]
        return ids, label
    return decode


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------
def build_wide_deep(cfg):
    import paddle_tpu as pt
    from paddle_tpu import layers, models, trainer

    sparse = [layers.data("s%d" % i, shape=[1], dtype="int64")
              for i in range(NF_SPARSE)]
    dense = layers.data("dense", shape=[NF_DENSE], dtype="float32")
    label = layers.data("label", shape=[1], dtype="float32")
    pred = models.wide_deep(sparse, dense, [cfg["vocab"]] * NF_SPARSE,
                            emb_dim=cfg["emb"], deep_hidden=(32, 16))
    cost = layers.mean(layers.square_error_cost(pred, label))
    # plain SGD, in the FTRL/AdaGrad spirit of Wide&Deep-era CTR training;
    # a double-moment optimizer would make the tiny tables' optimizer
    # memory traffic, not ingestion, the bottleneck
    sgd = trainer.SGD(cost, update_equation=pt.optimizer.SGD(
        learning_rate=0.05))
    return sgd, sparse + [dense, label]


def build_lstm(cfg):
    import paddle_tpu as pt
    from paddle_tpu import layers, models, trainer

    words = layers.data("words", shape=[], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")
    pred = models.lstm_text_classification(
        words, vocab_size=cfg["vocab"], num_classes=2,
        emb_dim=cfg["emb"], hidden_size=cfg["hidden"])
    cost = layers.mean(layers.cross_entropy(pred, label))
    sgd = trainer.SGD(cost, update_equation=pt.optimizer.Adam(1e-3))
    return sgd, [words, label]


WORKLOADS = {
    "wide_deep": {
        "build": build_wide_deep,
        "full": {"vocab": 1000, "emb": 8, "batch": 256, "batches": 60,
                 "reps": 8, "pipeline": {"steps_per_dispatch": 10,
                                         "num_workers": 1}},
        "smoke": {"vocab": 100, "emb": 8, "batch": 32, "batches": 6,
                  "reps": 1, "pipeline": {"steps_per_dispatch": 3,
                                          "num_workers": 1}},
    },
    "lstm": {
        "build": build_lstm,
        "full": {"vocab": 10000, "emb": 64, "hidden": 64, "batch": 64,
                 "max_len": 64, "batches": 30, "reps": 6,
                 "pipeline": {"steps_per_dispatch": 8, "num_workers": 1}},
        "smoke": {"vocab": 200, "emb": 8, "hidden": 8, "batch": 8,
                  "max_len": 16, "batches": 4, "reps": 1,
                  "pipeline": {"steps_per_dispatch": 2, "num_workers": 1}},
    },
}


def _make_reader(workload, cfg):
    """Zero-arg batched reader re-decoding the raw records every pass —
    the decode cost is the point of the benchmark."""
    from paddle_tpu import reader as rd
    n = cfg["batch"] * cfg["batches"]
    if workload == "wide_deep":
        lines = make_ctr_lines(n)
        decode = make_ctr_decoder(cfg["vocab"])
    else:
        lines = make_text_lines(n, cfg["vocab"], cfg["max_len"])
        decode = make_text_decoder(cfg["vocab"], cfg["max_len"])
    return rd.batch(rd.map_readers(decode, lambda: iter(lines)),
                    cfg["batch"], drop_last=True)


def run_workload(workload, smoke=False, quiet=False):
    import paddle_tpu as pt

    spec = WORKLOADS[workload]
    cfg = dict(spec["smoke" if smoke else "full"])
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    sgd, feed_list = spec["build"](cfg)
    bucket = cfg.get("max_len", 0)
    if bucket:
        # one padding bucket: every batch pads to max_len
        # (seq_bucket_multiple = max_len) — fixed shapes, one compile, and
        # the pipelined arm's same-signature scan chunking engages
        # (standard bucketed batching)
        orig_feeder = sgd._feeder

        def bucket_feeder(feeding, fl, staging_slots=0):
            f = orig_feeder(feeding, fl, staging_slots=staging_slots)
            f.seq_bucket_multiple = bucket
            return f

        sgd._feeder = bucket_feeder
    reader = _make_reader(workload, cfg)
    losses = []

    def handler(e):
        from paddle_tpu.trainer import events
        if isinstance(e, events.EndIteration):
            losses.append(e.cost)

    def one_pass(pipeline):
        t0 = time.perf_counter()
        sgd.train(reader, num_passes=1, event_handler=handler,
                  feed_list=feed_list, pipeline=pipeline)
        return cfg["batches"] / (time.perf_counter() - t0)

    pipe_cfg = dict(cfg["pipeline"])

    # warmup: compile both arms' executables outside the timed windows
    one_pass(False)
    one_pass(pipe_cfg)

    # Paired windows: each rep times naive then pipelined back-to-back and
    # the headline speedup is the MEDIAN OF PER-PAIR RATIOS — this
    # container's throughput drifts on multi-minute timescales (external
    # contention), which a paired design cancels and independent medians
    # do not.
    naive, pipelined = [], []
    for _ in range(cfg["reps"]):
        naive.append(one_pass(False))
        pipelined.append(one_pass(pipe_cfg))
    assert np.isfinite(losses).all(), "non-finite training loss"

    def stats(xs):
        med = statistics.median(xs)
        return med, (max(xs) - min(xs)) / med if len(xs) > 1 else 0.0

    ratios = [p / n for n, p in zip(naive, pipelined)]
    n_med, n_spread = stats(naive)
    p_med, p_spread = stats(pipelined)
    r_med, r_spread = stats(ratios)
    row = {
        "workload": workload,
        "batch": cfg["batch"],
        "batches_per_window": cfg["batches"],
        "reps": cfg["reps"],
        "pipeline_config": pipe_cfg,
        "naive_steps_per_s": round(n_med, 2),
        "naive_spread": round(n_spread, 3),
        "pipelined_steps_per_s": round(p_med, 2),
        "pipelined_spread": round(p_spread, 3),
        "speedup": round(r_med, 3),
        "speedup_spread": round(r_spread, 3),
        "speedup_pairs": [round(r, 3) for r in ratios],
        "smoke": smoke,
    }
    try:
        row.update(_doctor_pass(workload, one_pass, pipe_cfg, cfg))
    except Exception as e:   # the A/B rows must survive a doctor failure
        row["doctor"] = {"error": f"{type(e).__name__}: {e}"}
    if not quiet:
        print(json.dumps(row), flush=True)
    return row


def _doctor_pass(workload, one_pass, pipe_cfg, cfg):
    """One EXTRA pipelined pass with observe + a fresh JSONL log, AFTER
    the timed windows (instrumentation cost never touches the A/B):
    the measured step-time budget and the static-cost-model calibration
    row ride the committed result row (`python -m paddle_tpu doctor`
    is the CLI form of the same attribution).  The log path is unique
    per workload — the JSONL writer only reopens on a path change."""
    import tempfile

    import paddle_tpu as pt
    from paddle_tpu import flags
    from paddle_tpu.observability import attribution

    log = os.path.join(tempfile.gettempdir(),
                       f"pt_doctor_pipe_{workload}_{os.getpid()}.jsonl")
    try:
        os.remove(log)
    except OSError:
        pass
    prev_obs = flags.get_flag("observe")
    prev_log = flags.get_flag("metrics_log")
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", log)
    try:
        one_pass(pipe_cfg)
    finally:
        flags.set_flag("observe", prev_obs)
        flags.set_flag("metrics_log", prev_log or "")
    report = attribution.doctor_report([log],
                                       program=pt.default_main_program(),
                                       assume_batch=cfg["batch"])
    out = {"doctor": report.get("training")}
    if "calibration" in report:
        out["calibration"] = report["calibration"]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="all",
                    choices=["all"] + sorted(WORKLOADS))
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-fast path check (tiny sizes, 1 rep); "
                         "does not overwrite the committed results file")
    ap.add_argument("--json", default=None,
                    help="results path (default: benchmark/"
                         "input_pipeline_results.json; smoke runs only "
                         "write when given explicitly)")
    args = ap.parse_args()

    names = sorted(WORKLOADS) if args.workload == "all" else [args.workload]
    rows = [run_workload(w, smoke=args.smoke) for w in names]

    out_path = args.json or (None if args.smoke else RESULTS_PATH)
    if out_path:
        doc = {
            "description": "naive Trainer.train loop vs pipeline= "
                           "(Executor.run_pipelined): end-to-end training "
                           "steps/s, median of alternating windows",
            "platform": __import__("jax").devices()[0].platform,
            "cpu_count": os.cpu_count(),
            "host_parallel_efficiency": host_parallel_efficiency(),
            "rows": rows,
        }
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"# wrote {out_path}", file=sys.stderr)
    return rows


if __name__ == "__main__":
    main()
