"""Shared deploy-artifact helpers for the serving + inference benchmarks.

One model/manifest path for both: ``benchmark/inference.py`` (deploy-ABI
throughput, ``--server`` mode) and ``benchmark/serving.py`` (load
generator) export with :func:`export_mlp` / the inference benches'
exporters, then load through :func:`load_artifact` and synthesize wire
feeds with :func:`feeds_from_manifest` — so the two benchmarks can never
drift onto different artifact conventions.
"""
from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def export_mlp(dirname: str, in_dim: int = 784, hidden=(2048, 2048, 2048),
               classes: int = 10, seed: int = 0) -> str:
    """Export a dense classifier MLP as a symbolic-batch StableHLO
    artifact (the serving benchmark's standard tenant: heavy enough that
    CPU capacity is a few hundred req/s, so an open-loop Python load
    generator can genuinely overload it)."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    x = layers.data("x", shape=[in_dim], dtype="float32")
    h = x
    for width in hidden:
        h = layers.fc(h, size=width, act="relu")
    pred = layers.fc(h, size=classes, act="softmax")
    pt.default_main_program().random_seed = seed
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    pt.export_compiled_model(dirname, {"x": ((-1, in_dim), "float32")},
                             [pred])
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    return dirname


def load_artifact(dirname: str):
    """(run, manifest) for an exported artifact — the deploy-ABI binding
    both benchmarks measure through."""
    import paddle_tpu as pt
    return pt.load_compiled_model(dirname)


def feeds_from_manifest(manifest: dict, batch: int, rng,
                        int_high: int = 2):
    """Synthesize a stacked feed dict from an artifact manifest's input
    specs: floats U(0,1), ints U(0, int_high) — the generic fake-data
    provider for any exported model."""
    feeds = {}
    for name, spec in manifest["inputs"].items():
        shape = list(spec["shape"])
        if shape and (shape[0] is None or int(shape[0]) < 0):
            # symbolic batch: instantiate at the requested size
            shape = [batch] + [int(d) for d in shape[1:]]
        else:
            # fixed-shape input: serve it as exported
            shape = [int(d) for d in shape]
        dtype = np.dtype(spec["dtype"])
        if dtype.kind in "iu":
            feeds[name] = rng.randint(0, int_high, shape).astype(dtype)
        else:
            feeds[name] = rng.rand(*shape).astype(dtype)
    return feeds


def single_example(manifest: dict, rng, int_high: int = 2):
    """One per-request example (no batch axis) from a manifest.

    Serving submits per-example feeds, so every input must carry a
    SYMBOLIC leading batch dim — a fixed-shape input has no batch axis
    to strip, and silently dropping its first real dim would feed the
    server mis-shaped examples."""
    for name, spec in manifest["inputs"].items():
        shape = list(spec["shape"])
        if not shape or not (shape[0] is None or int(shape[0]) < 0):
            raise ValueError(
                f"artifact input {name!r} has fixed shape {shape}; "
                f"serving needs a symbolic batch dim (export with a "
                f"-1/None leading dim)")
    stacked = feeds_from_manifest(manifest, 1, rng, int_high=int_high)
    return {k: v[0] for k, v in stacked.items()}


def closed_loop(srv, example, *, workers: int, duration_s: float,
                timeout_s: float = 120.0):
    """Closed-loop load shared by both benchmarks: N worker threads
    issue back-to-back sync infers against an already-started server
    for ``duration_s``.  Returns ``(sorted_latencies_s, row)``; worker
    exceptions are counted (not silently fatal to the thread) and a
    zero-served run raises loudly instead of yielding a garbage row."""
    import threading
    import time

    lat, errors = [], []
    lock = threading.Lock()
    stop = time.monotonic() + duration_s

    def worker():
        while time.monotonic() < stop:
            t0 = time.monotonic()
            try:
                srv.infer(example, deadline_ms=None, timeout=timeout_s)
            except Exception as e:      # noqa: BLE001 — counted, surfaced
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                lat.append(time.monotonic() - t0)

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    if not lat and errors:
        raise RuntimeError(
            f"closed_loop: every worker failed; first error: {errors[0]}")
    lat.sort()
    row = {"workers": workers, "duration_s": round(wall, 3),
           "served": len(lat), "req_per_s": round(len(lat) / wall, 1),
           "worker_errors": len(errors)}
    return lat, row


def percentile(sorted_vals, q: float):
    """Shared rank-based percentile over an ASCENDING-sorted list (the
    one statistic both benchmarks and the serving tests quote — one
    convention, no drift).  None on empty input."""
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]
