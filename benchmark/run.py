#!/usr/bin/env python
"""Benchmark runner mirroring the reference's benchmark/paddle suite
(benchmark/paddle/image/run.sh configs + benchmark/paddle/rnn/run.sh), plus
the seq2seq tokens/s metric BASELINE.json asks for.

Usage:
    python benchmark/run.py --model resnet50 --batch 64 --amp
    python benchmark/run.py --all            # every headline config

Prints one JSON line per config:
    {"model", "batch", "ms_per_batch", "throughput", "unit", "ref", "speedup"}
``ref`` is the reference's published number for that config (BASELINE.md),
converted to the same unit; null when the reference published none.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# reference numbers (BASELINE.md): config -> (ms/batch, source)
REF_MS = {
    ("alexnet", 64): 195.0, ("alexnet", 128): 334.0,
    ("alexnet", 256): 602.0, ("alexnet", 512): 1629.0,
    ("googlenet", 64): 613.0, ("googlenet", 128): 1149.0,
    ("googlenet", 256): 2348.0,
    ("smallnet", 64): 10.463,
    ("lstm_h256", 64): 83.0, ("lstm_h512", 64): 184.0,
    ("lstm_h1280", 64): 641.0, ("lstm_h512", 128): 261.0,
    ("lstm_h512", 256): 414.0,
}
# img/s references (CPU MKL-DNN table, best published for these models)
REF_IMG_S = {("resnet50", 64): 81.69, ("resnet50", 128): 82.35,
             ("vgg19", 64): 28.46, ("vgg19", 128): 29.83}


def _build_image(model, batch):
    import paddle_tpu as pt
    from paddle_tpu import layers, models
    size = {"alexnet": 224, "googlenet": 224, "resnet50": 224,
            "vgg19": 224, "smallnet": 32}[model]
    img = layers.data("img", shape=[3, size, size], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    num_classes = 10 if model == "smallnet" else 1000
    if model == "alexnet":
        pred = models.alexnet(img, num_classes)
    elif model == "googlenet":
        pred = models.googlenet(img, num_classes)
    elif model == "resnet50":
        pred = models.resnet50(img, num_classes)
    elif model == "vgg19":
        pred = models.vgg19(img, num_classes)
    else:
        pred = models.vgg_cifar(img, num_classes)
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Momentum(learning_rate=0.01 / batch, momentum=0.9) \
        .minimize(loss)
    rng = np.random.RandomState(0)
    feeds = {"img": rng.rand(batch, 3, size, size).astype("float32"),
             "label": rng.randint(0, num_classes, (batch, 1))}
    return loss, feeds, batch


def _build_lstm(hidden, batch, seq_len=100, vocab=30000, emb=128,
                lstm_num=2):
    """benchmark/paddle/rnn/rnn.py: emb -> N stacked LSTM -> last -> fc2."""
    import paddle_tpu as pt
    from paddle_tpu import layers, models
    words = layers.data("words", shape=[], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")
    pred = models.lstm_text_classification(
        words, vocab_size=vocab, num_classes=2, emb_dim=emb,
        hidden_size=hidden, lstm_num=lstm_num)
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Adam(2e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feeds = {"words": rng.randint(0, vocab, (batch, seq_len)),
             "words@LEN": np.full(batch, seq_len),
             "label": rng.randint(0, 2, (batch, 1))}
    return loss, feeds, batch


def _build_seq2seq(batch, src_len=30, tgt_len=30, vocab=30000, dim=512,
                   lazy_adam=False):
    import paddle_tpu as pt
    from paddle_tpu import layers, models
    src = layers.data("src", shape=[], dtype="int64", lod_level=1)
    tgt = layers.data("tgt", shape=[], dtype="int64", lod_level=1)
    lbl = layers.data("lbl", shape=[], dtype="int64", lod_level=1)
    probs = models.seq2seq_attention(src, tgt, vocab, vocab, emb_dim=dim,
                                     hidden_dim=dim)
    flat = layers.reshape(probs, [-1, vocab])
    loss = layers.mean(layers.cross_entropy(
        flat, layers.reshape(lbl, [-1, 1])))
    pt.optimizer.Adam(1e-3, lazy_mode=lazy_adam).minimize(loss)
    rng = np.random.RandomState(0)
    feeds = {"src": rng.randint(0, vocab, (batch, src_len)),
             "src@LEN": np.full(batch, src_len),
             "tgt": rng.randint(0, vocab, (batch, tgt_len)),
             "tgt@LEN": np.full(batch, tgt_len),
             "lbl": rng.randint(0, vocab, (batch, tgt_len)),
             "lbl@LEN": np.full(batch, tgt_len)}
    # tokens processed per batch = batch * (src + tgt)
    return loss, feeds, batch * (src_len + tgt_len)


def run_config(name, batch, amp=True, iters=None, reps=3,
               conv1x1_pallas=None):
    import statistics

    import jax
    import paddle_tpu as pt

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()

    if name.startswith("lstm_h"):
        loss, feeds, units = _build_lstm(int(name[6:]), batch)
        unit = "samples/s"
    elif name == "seq2seq":
        loss, feeds, units = _build_seq2seq(batch)
        unit = "tokens/s"
    else:
        loss, feeds, units = _build_image(name, batch)
        unit = "img/s"

    exe = pt.Executor(amp=amp, conv1x1_pallas=conv1x1_pallas)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {k: jax.device_put(v) for k, v in feeds.items()}
    prog = pt.default_main_program()
    # Pinned methodology (round 4, see RESULTS.md): each window is ONE
    # compiled dispatch of `iters` steps (Executor.run_steps — device-side
    # lax.scan with donated state), so host dispatch rate and tunnel
    # latency are out of the measurement; first call = compile + warmup.
    # Fixed window sizes (no probe compiles): big CNNs 60 steps, small
    # models 300.
    if iters is None:
        iters = 60 if name in ("alexnet", "googlenet", "resnet50",
                               "vgg19") else 300
    (lv,) = exe.run_steps(iters, prog, feed=feeds, fetch_list=[loss],
                          return_numpy=False)
    assert np.isfinite(np.asarray(lv)[-1])
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        (lv,) = exe.run_steps(iters, prog, feed=feeds, fetch_list=[loss],
                              return_numpy=False)
        assert np.isfinite(np.asarray(lv)[-1])
        rates.append(units * iters / (time.perf_counter() - t0))
    thr = statistics.median(rates)
    spread = (max(rates) - min(rates)) / thr
    dt = units / thr
    ref_ms = REF_MS.get((name, batch))
    ref_thr = REF_IMG_S.get((name, batch))
    if ref_thr is None and ref_ms is not None:
        ref_thr = units / (ref_ms / 1e3)
    out = {"model": name, "batch": batch,
           "ms_per_batch": round(dt * 1e3, 2),
           "throughput": round(thr, 1), "unit": unit,
           "ref": ref_thr, "amp": amp,
           "speedup": round(thr / ref_thr, 2) if ref_thr else None,
           "window_spread": round(spread, 4)}
    print(json.dumps(out), flush=True)
    return out


HEADLINE = [("alexnet", 128), ("googlenet", 128), ("smallnet", 64),
            ("resnet50", 64), ("vgg19", 64),
            ("lstm_h512", 64), ("lstm_h512", 128), ("seq2seq", 64)]


def run_input_pipeline(smoke=False):
    """Delegate to benchmark/input_pipeline.py (naive vs pipelined
    Trainer.train A/B); one JSON line per workload, same as run_config."""
    from benchmark.input_pipeline import WORKLOADS, run_workload
    return [run_workload(w, smoke=smoke) for w in sorted(WORKLOADS)]


def run_compile_cache(smoke=False):
    """Delegate to benchmark/compile_cache.py (cold vs warm
    startup-to-first-step across two subprocesses); --smoke is the
    seconds-fast tiny-model correctness gate wired into tier-1."""
    from benchmark.compile_cache import MODELS, run_model, run_smoke
    if smoke:
        return [run_smoke()]
    return [run_model(m) for m in MODELS]


def run_autotune(smoke=False):
    """Delegate to benchmark/autotune.py (tuned-vs-default A/B per
    host-side tunable through the real search path); one JSON summary
    line per tunable, same shape as the committed rows."""
    import tempfile

    from benchmark.autotune import HOST_TUNABLES, run_one
    with tempfile.TemporaryDirectory(prefix="pt-autotune-") as store:
        return [run_one(n, store, smoke=smoke)
                for n in sorted(HOST_TUNABLES)]


def run_ctr(smoke=False):
    """Delegate to benchmark/ctr.py (host-resident sparse parameter
    server vs dense-embedding control, lookup latency, push throughput,
    zipfian cache hit rate, doctor budget)."""
    from benchmark.ctr import run_all
    return [run_all(smoke=smoke)]


def run_decode(smoke=False):
    """Delegate to benchmark/decode.py (continuous-batching KV-cache
    decode slot pool vs static-batch control: decode tokens/s paired
    A/B, TTFT/inter-token percentiles, slot occupancy, doctor budget)."""
    from benchmark.decode import run_all
    return [run_all(smoke=smoke)]


def run_pserver(smoke=False):
    """Delegate to benchmark/pserver.py (multi-host sparse parameter
    server: batched binary wire vs naive JSON A/B, remote pull latency
    vs in-process, shard pipelining A/B over a real process fleet)."""
    from benchmark.pserver import run_all
    return [run_all(smoke=smoke)]


def run_checkpoint(smoke=False):
    """Delegate to benchmark/checkpoint.py (incremental checkpointing:
    delta-commit vs full-save wall/bytes A/B, elastic task-boundary
    commit throughput, base+K-delta chain restore cost)."""
    from benchmark.checkpoint import run_all
    return [run_all(smoke=smoke)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None,
                    help="model config, 'input_pipeline' for the "
                         "naive-vs-pipelined input A/B, 'compile_cache' "
                         "for the cold-vs-warm startup A/B, 'autotune' "
                         "for the tuned-vs-default autotuner A/B, "
                         "'ctr' for the sparse-parameter-server CTR A/B, "
                         "'decode' for the continuous-batching "
                         "incremental-decode A/B, 'pserver' for the "
                         "multi-host sparse parameter-server wire A/B, "
                         "or 'checkpoint' for the incremental-"
                         "checkpoint delta-vs-full A/B")
    ap.add_argument("--smoke", action="store_true",
                    help="input_pipeline/compile_cache/autotune/ctr/"
                         "decode/pserver/checkpoint only: seconds-fast "
                         "path check")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=None,
                    help="steps per timed window (default: 60 for the "
                         "big CNNs, 300 otherwise)")
    ap.add_argument("--amp", action="store_true", default=True)
    ap.add_argument("--no-amp", dest="amp", action="store_false")
    ap.add_argument("--conv1x1-pallas", dest="conv1x1_pallas",
                    action="store_true", default=None,
                    help="route eligible 1x1 convs to the hand-written "
                         "Pallas kernels (ops/pallas_conv.py; per-op A/B: "
                         "benchmark/conv_kernel.py)")
    ap.add_argument("--no-conv1x1-pallas", dest="conv1x1_pallas",
                    action="store_false")
    ap.add_argument("--all", action="store_true")
    args = ap.parse_args()
    if args.model == "input_pipeline":
        run_input_pipeline(smoke=args.smoke)
        return
    if args.model == "compile_cache":
        run_compile_cache(smoke=args.smoke)
        return
    if args.model == "autotune":
        run_autotune(smoke=args.smoke)
        return
    if args.model == "ctr":
        run_ctr(smoke=args.smoke)
        return
    if args.model == "decode":
        run_decode(smoke=args.smoke)
        return
    if args.model == "pserver":
        run_pserver(smoke=args.smoke)
        return
    if args.model == "checkpoint":
        run_checkpoint(smoke=args.smoke)
        return
    if args.all:
        for name, batch in HEADLINE:
            try:
                run_config(name, batch, amp=args.amp, iters=args.iters,
                           conv1x1_pallas=args.conv1x1_pallas)
            except Exception as e:
                print(json.dumps({"model": name, "batch": batch,
                                  "error": str(e)[:200]}), flush=True)
    else:
        run_config(args.model, args.batch, amp=args.amp, iters=args.iters,
                   conv1x1_pallas=args.conv1x1_pallas)


if __name__ == "__main__":
    main()
