#!/usr/bin/env python
"""Roofline accounting experiments for the LSTM and seq2seq benchmarks
(the RESULTS.md ResNet section's method applied to the RNN rows): an
analytic FLOP/byte model per config plus on-device controls that vary one
factor at a time (batch, sequence length, vocab) to identify the binding
resource.  Run on the real chip:

    python benchmark/roofline_rnn.py [--quick]

Prints one JSON line per experiment; the RESULTS.md "Where the RNN time
goes" section quotes these numbers.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmark.run import run_config  # noqa: E402


def lstm_model(hidden, batch, seq_len=100, emb=128, lstm_num=2,
               bytes_per_el=2):
    """Analytic per-batch cost of the stacked-LSTM classifier.

    FLOPs: the 4-gate input+recurrent matmuls, fwd + ~2x for backward.
    Weight-stream bytes: under lax.scan the gate weights are re-read from
    HBM every timestep (they cannot stay resident across the sequential
    chain), fwd and again bwd, plus the dW accumulator carried through the
    backward scan (read+write per step).
    """
    per_step_flops = 0
    per_step_wbytes = 0
    for li in range(lstm_num):
        d_in = emb if li == 0 else hidden
        n_w = (d_in + hidden) * 4 * hidden
        per_step_flops += 2 * n_w          # MACs*2, per sample
        per_step_wbytes += n_w * bytes_per_el
    flops = 3 * batch * seq_len * per_step_flops          # fwd + 2x bwd
    # fwd weight reads + bwd weight reads + dW accumulator read+write
    wbytes = seq_len * per_step_wbytes * (1 + 1 + 2)
    # activation traffic: h,c per layer per step, write fwd + read bwd
    abytes = 3 * batch * seq_len * lstm_num * 2 * hidden * bytes_per_el
    return {"gflops": flops / 1e9, "weight_gb": wbytes / 1e9,
            "act_gb": abytes / 1e9}


def seq2seq_model(batch, src_len=30, tgt_len=30, vocab=30000, dim=512,
                  bytes_per_el=2):
    """Analytic per-batch cost split: vocab head vs recurrent/attention."""
    n_tok = batch * tgt_len
    head_flops = 3 * n_tok * 2 * dim * vocab              # fwd+bwd matmul
    # softmax+CE traffic: logits [n_tok, vocab] written fwd, read for
    # softmax, read+write for dlogits in bwd (fp32 master in AMP loss)
    head_bytes = 4 * n_tok * vocab * 4
    # encoder GRU/LSTM + decoder step matmuls + attention projections
    rec_flops = 3 * batch * (src_len + tgt_len) * 2 * (
        (dim + dim) * 4 * dim + 3 * dim * dim)
    rec_wbytes = (src_len + tgt_len) * ((dim + dim) * 4 * dim +
                                        3 * dim * dim) * bytes_per_el * 4
    return {"head_gflops": head_flops / 1e9,
            "head_gb": head_bytes / 1e9,
            "rec_gflops": rec_flops / 1e9,
            "rec_weight_gb": rec_wbytes / 1e9}


def vocab_head_control(batch_tokens=1920, dim=512, vocab=30000,
                       reps=3, iters=40):
    """Isolated vocab projection + softmax-CE training step, same shapes
    as the seq2seq head ([B*T, dim] @ [dim, vocab] -> CE), bf16 matmul."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch_tokens, dim).astype("float32") - 0.5,
                    dtype=jnp.bfloat16)
    w = jnp.asarray(rng.rand(dim, vocab).astype("float32") * 0.02,
                    dtype=jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, vocab, batch_tokens))

    def step(w, _):
        def loss_fn(w):
            logits = (x @ w).astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
            return jnp.mean(lse - picked)
        l, g = jax.value_and_grad(loss_fn)(w)
        return (w - 0.001 * g).astype(jnp.bfloat16), l

    @jax.jit
    def window(w):
        # device-side loop: same dispatch-free methodology as run_steps
        return jax.lax.scan(step, w, None, length=iters)

    w, ls = window(w)
    float(ls[-1])
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        w, ls = window(w)
        float(ls[-1])
        rates.append((time.perf_counter() - t0) / iters)
    ms = sorted(rates)[len(rates) // 2] * 1e3
    return {"experiment": "vocab_head_control",
            "tokens": batch_tokens, "dim": dim, "vocab": vocab,
            "ms_per_batch": round(ms, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewer reps/windows")
    args = ap.parse_args()
    reps = 2 if args.quick else 3

    out = []

    # --- LSTM: batch scaling (weight-bound => ms/batch ~flat in B) ------
    for bs in (64, 128, 256):
        r = run_config("lstm_h512", bs, reps=reps)
        r["experiment"] = f"lstm_h512_bs{bs}"
        out.append(r)
    # model
    for bs in (64, 128, 256):
        m = lstm_model(512, bs)
        m["experiment"] = f"lstm_model_bs{bs}"
        print(json.dumps(m), flush=True)
        out.append(m)

    # --- seq2seq: batch scaling, vocab-head control, small-vocab,
    # dense-vs-lazy Adam A/B -------------------------------------------
    for bs in (64, 128, 256):
        r = run_config("seq2seq", bs, reps=reps)
        r["experiment"] = f"seq2seq_full_v30000_bs{bs}"
        out.append(r)
    c = vocab_head_control()
    print(json.dumps(c), flush=True)
    out.append(c)
    m = seq2seq_model(64)
    m["experiment"] = "seq2seq_model"
    print(json.dumps(m), flush=True)
    out.append(m)

    import benchmark.run as br
    orig = br._build_seq2seq

    # small-vocab control: same recurrent work, 1/10 head
    def small_vocab(batch, **kw):
        return orig(batch, vocab=3000)
    br._build_seq2seq = small_vocab
    try:
        r = run_config("seq2seq", 64, reps=reps)
        r["experiment"] = "seq2seq_full_v3000"
        out.append(r)
    finally:
        br._build_seq2seq = orig

    # lazy (row-sparse) Adam A/B at bs64: same conditions as the dense
    # run above; see RESULTS.md for the (negative) verdict
    def lazy(batch, **kw):
        return orig(batch, lazy_adam=True)
    br._build_seq2seq = lazy
    try:
        r = run_config("seq2seq", 64, reps=reps)
        r["experiment"] = "seq2seq_full_v30000_lazy_adam"
        out.append(r)
    finally:
        br._build_seq2seq = orig

    with open(os.path.join(os.path.dirname(__file__),
                           "roofline_rnn_results.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
