#!/usr/bin/env python
"""Compile-cache A/B: cold vs warm startup-to-first-step across two
subprocesses.

Unlike kernel throughput (TPU-gated), compile time is fully measurable in a
CPU-only container: each arm is a FRESH python process that builds a real
model, runs the startup program and executes train steps with
``PADDLE_TPU_CACHE_DIR`` pointing at a shared directory.  The first (cold)
process populates the persistent cache (serialized step executables +
JAX's HLO-keyed compilation cache, core/compile_cache.py); the second
(warm) process loads them, skipping trace, lower AND compile.

Measured columns per arm (all wall-clock in the child, never projected):

* ``engine_s``         — startup-program run + first train step: the span
                         the compile cache can shorten.  The headline
                         speedup is ``cold.engine_s / warm.engine_s``.
* ``total_s``          — python-process start to first step done (includes
                         the jax+framework import tax, identical in both
                         arms; reported so the end-to-end picture is
                         honest).
* ``steps_digest``     — sha256 over every fetch of ``--steps`` train
                         steps; cold and warm must be BIT-IDENTICAL (the
                         deserialized executable is the same program).
* ``counters``         — compile_stats() snapshot (traces / disk hits /
                         stores); a correct warm arm has ZERO traces.

Models: ``wide_deep`` (CTR embeddings + MLP), ``resnet`` (CIFAR resnet-20),
``lstm`` (embedding -> dynamic_lstm -> fc) — the three
benchmark-representative graph shapes — plus ``tiny`` for the --smoke
seconds-fast path (tmpdir cache, asserts warm-run disk hit + bit-identical
fetches) wired into tier-1.

Usage:
    python benchmark/compile_cache.py              # full A/B, writes
                                                   # compile_cache_results.json
    python benchmark/compile_cache.py --smoke      # tiny model, seconds
    python benchmark/compile_cache.py --model lstm
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "compile_cache_results.json")
MODELS = ("wide_deep", "resnet", "lstm")


# ---------------------------------------------------------------------------
# child: one measured arm in a fresh process
# ---------------------------------------------------------------------------
def _build_model(model, rng):
    """Build (loss, feeds) for one model; fixed shapes + seeded data so the
    cold and warm arms run bit-identical programs on bit-identical inputs."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers, models

    if model == "wide_deep":
        B, nsparse, vocab, dense_d = 32, 8, 1000, 13
        sparse = [layers.data(f"s{i}", shape=[1], dtype="int64")
                  for i in range(nsparse)]
        dense = layers.data("dense", shape=[dense_d], dtype="float32")
        label = layers.data("label", shape=[1], dtype="float32")
        ctr = models.wide_deep(sparse, dense, [vocab] * nsparse)
        loss = layers.mean(layers.log_loss(ctr, label))
        pt.optimizer.Adam(1e-3).minimize(loss)
        feeds = {f"s{i}": rng.randint(0, vocab, (B, 1))
                 for i in range(nsparse)}
        feeds["dense"] = rng.rand(B, dense_d).astype("float32")
        feeds["label"] = rng.randint(0, 2, (B, 1)).astype("float32")
    elif model == "resnet":
        B = 8
        img = layers.data("img", shape=[3, 32, 32], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        pred = models.resnet_cifar(img, num_classes=10, depth=20)
        loss = layers.mean(layers.cross_entropy(pred, label))
        pt.optimizer.Momentum(learning_rate=0.01, momentum=0.9) \
            .minimize(loss)
        feeds = {"img": rng.rand(B, 3, 32, 32).astype("float32"),
                 "label": rng.randint(0, 10, (B, 1))}
    elif model == "lstm":
        B, T, vocab = 16, 32, 2000
        words = layers.data("words", shape=[], dtype="int64", lod_level=1)
        label = layers.data("label", shape=[1], dtype="int64")
        pred = models.lstm_text_classification(
            words, vocab_size=vocab, num_classes=2, emb_dim=32,
            hidden_size=64, lstm_num=1)
        loss = layers.mean(layers.cross_entropy(pred, label))
        pt.optimizer.Adam(1e-3).minimize(loss)
        feeds = {"words": rng.randint(0, vocab, (B, T)),
                 "words@LEN": np.full(B, T),
                 "label": rng.randint(0, 2, (B, 1))}
    elif model == "tiny":
        B = 8
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        pred = layers.fc(layers.fc(x, size=32, act="relu"), size=4,
                         act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, y))
        pt.optimizer.SGD(0.1).minimize(loss)
        feeds = {"x": rng.rand(B, 16).astype("float32"),
                 "y": rng.randint(0, 4, (B, 1))}
    else:
        raise ValueError(f"unknown model {model!r}")
    return loss, feeds


def child_main(model: str, steps: int):
    """One arm: build, startup, ``steps`` train steps; print ONE JSON
    line.  PADDLE_TPU_CACHE_DIR (and JAX_PLATFORMS) come from the
    environment set by the parent."""
    t_proc = time.perf_counter()
    import hashlib

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.core import compile_cache

    t_import = time.perf_counter()
    rng = np.random.RandomState(0)
    loss, feeds = _build_model(model, rng)
    t_build = time.perf_counter()

    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    t_startup = time.perf_counter()
    outs = [exe.run(feed=feeds, fetch_list=[loss])]
    t_first = time.perf_counter()
    for _ in range(steps - 1):
        outs.append(exe.run(feed=feeds, fetch_list=[loss]))
    digest = hashlib.sha256(
        b"".join(np.ascontiguousarray(o[0]).tobytes() for o in outs)
    ).hexdigest()

    stats = compile_cache.stats()
    print(json.dumps({
        "model": model,
        "import_s": round(t_import - t_proc, 4),
        "build_s": round(t_build - t_import, 4),
        "startup_run_s": round(t_startup - t_build, 4),
        "first_step_s": round(t_first - t_startup, 4),
        "engine_s": round(t_first - t_build, 4),
        "total_s": round(t_first - t_proc, 4),
        "first_loss": float(np.asarray(outs[0][0])),
        "steps_digest": digest,
        "counters": stats.snapshot(),
    }), flush=True)


# ---------------------------------------------------------------------------
# parent: cold/warm pairs
# ---------------------------------------------------------------------------
def _run_arm(model: str, cache_dir: str, steps: int) -> dict:
    env = dict(os.environ, PADDLE_TPU_CACHE_DIR=cache_dir,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--model", model, "--steps", str(steps)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if r.returncode != 0:
        raise RuntimeError(
            f"compile_cache child ({model}) failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_model(model: str, steps: int = 3, cache_dir: str = None,
              quiet: bool = False) -> dict:
    """One cold/warm pair in a fresh cache dir; returns the result row."""
    d = cache_dir or tempfile.mkdtemp(prefix=f"ptcc_{model}_")
    owns = cache_dir is None
    try:
        cold = _run_arm(model, d, steps)
        warm = _run_arm(model, d, steps)
    finally:
        if owns:
            shutil.rmtree(d, ignore_errors=True)
    row = {
        "model": model,
        "cold_engine_s": cold["engine_s"], "warm_engine_s": warm["engine_s"],
        "speedup_engine": round(cold["engine_s"] / warm["engine_s"], 2),
        "cold_total_s": cold["total_s"], "warm_total_s": warm["total_s"],
        "speedup_total": round(cold["total_s"] / warm["total_s"], 2),
        "bit_identical": cold["steps_digest"] == warm["steps_digest"],
        "warm_traces": warm["counters"].get("traces", 0),
        "warm_disk_hits": warm["counters"].get("disk_hits", 0),
        "cold_counters": cold["counters"], "warm_counters": warm["counters"],
        "cold": cold, "warm": warm,
    }
    if not quiet:
        print(json.dumps({k: row[k] for k in (
            "model", "cold_engine_s", "warm_engine_s", "speedup_engine",
            "cold_total_s", "warm_total_s", "speedup_total",
            "bit_identical", "warm_traces", "warm_disk_hits")}),
            flush=True)
    return row


def run_smoke(steps: int = 3) -> dict:
    """Seconds-fast correctness path (tier-1): tiny model, tmpdir cache.
    Asserts the warm arm hit the persistent cache without a single trace
    and produced bit-identical fetches.  Timing columns are reported but
    NOT asserted — smoke is a correctness gate, not a perf gate."""
    row = run_model("tiny", steps=steps, quiet=True)
    assert row["bit_identical"], (
        "warm-run fetches differ from cold run:\n"
        f"cold {row['cold']['steps_digest']} warm {row['warm']['steps_digest']}")
    assert row["warm_disk_hits"] >= 2, (
        "warm run did not hit the persistent executable cache: "
        f"{row['warm_counters']}")
    assert row["warm_traces"] == 0, (
        "warm run re-traced despite persistent cache: "
        f"{row['warm_counters']}")
    print(json.dumps({"model": "compile_cache_smoke", "ok": True,
                      "speedup_engine": row["speedup_engine"],
                      "warm_counters": row["warm_counters"]}), flush=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true",
                    help="internal: run one measured arm in this process")
    ap.add_argument("--model", default=None,
                    help=f"one of {MODELS + ('tiny',)} (default: all three)")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + assertions, seconds-fast")
    args = ap.parse_args()

    if args.child:
        child_main(args.model, args.steps)
        return
    if args.smoke:
        run_smoke(steps=args.steps)
        return

    models = [args.model] if args.model else list(MODELS)
    rows = [run_model(m, steps=args.steps) for m in models]
    import multiprocessing

    import jax
    payload = {
        "benchmark": "compile_cache_cold_vs_warm",
        "note": ("two fresh subprocesses sharing one PADDLE_TPU_CACHE_DIR; "
                 "engine_s = startup-program run + first train step (the "
                 "span compile caching can shorten); measured in-container "
                 "on CPU, never projected"),
        "host": {"jax": jax.__version__,
                 "backend": jax.default_backend(),
                 "cpu_count": multiprocessing.cpu_count()},
        "rows": [{k: v for k, v in r.items()
                  if k not in ("cold", "warm")} for r in rows],
        "detail": [{"model": r["model"], "cold": r["cold"],
                    "warm": r["warm"]} for r in rows],
    }
    with open(RESULTS_PATH, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    print(f"wrote {RESULTS_PATH}", file=sys.stderr)


if __name__ == "__main__":
    main()
