#!/usr/bin/env python
"""Serving-runtime load benchmark: throughput-vs-latency, shedding vs
collapse (ISSUE 8 acceptance; ROADMAP item 1's load-generator gate).

Two generators over the in-process :class:`paddle_tpu.serving.Server`
on an exported MLP artifact (the deploy-ABI path, shared with
benchmark/inference.py via benchmark/serving_common.py):

* **closed loop** — C worker threads submit back-to-back; measures
  saturation capacity (req/s) with batching at work.
* **open loop** — a tick generator offers load at a FIXED rate
  (fractions/multiples of measured capacity), which is what real traffic
  does: arrival rate does not slow down because the server is behind.
  Per-arm rows record offered/admitted/served rates, admitted-request
  latency p50/p99, shed + deadline-expired counts.

The demonstration row pair (acceptance): at 2x offered overload the
SHEDDING arm's admitted p99 stays within 2x of the 1x arm's p99 —
admission control bounds queue wait at queue_capacity/throughput — while
the CONTROL arm (no shedding, unbounded queue, no deadlines) shows the
collapse: queue depth grows without bound for the whole run and admitted
p99 blows up to seconds (every request eventually "succeeds", far past
any useful deadline).

CPU rows are REAL in-container measurements (this box is ~1 effective
core — see RESULTS.md round 7 — so absolute capacity is small; the
CURVES are the result).  TPU rows follow the PR 1 pending-hardware-stub
convention: run ``python benchmark/serving.py`` on a chip host and
commit the filled rows.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmark.serving_common import (closed_loop, export_mlp,  # noqa: E402
                                      load_artifact, percentile,
                                      single_example)

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "serving_results.json")


class _Collector:
    """Thread-safe terminal-outcome recorder for open-loop arms."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latency_ms = []          # admitted AND served
        self.errors = {}              # typed error name -> count
        self.shed_at_admission = 0

    def cb(self, pending):
        ms = (time.monotonic() - pending.t_admit) * 1e3
        with self.lock:
            if pending.error is None:
                self.latency_ms.append(ms)
            else:
                name = type(pending.error).__name__
                self.errors[name] = self.errors.get(name, 0) + 1

    def note_admission_reject(self, exc):
        with self.lock:
            name = type(exc).__name__
            self.errors[name] = self.errors.get(name, 0) + 1
            self.shed_at_admission += 1


def _make_server(model_dir, *, shed, queue, deadline_ms, max_batch,
                 max_wait_ms):
    from paddle_tpu.serving import Model, Server
    from paddle_tpu.serving.server import _buckets
    # warm EVERY bucket: the arms measure steady-state queueing, and a
    # mid-arm compile would smear seconds of one-off cost into the
    # latency distribution (the runtime itself tags those cold)
    srv = Server(max_batch=max_batch, max_wait_ms=max_wait_ms,
                 deadline_ms=deadline_ms, queue_capacity=queue, shed=shed,
                 warmup_buckets=_buckets(max_batch))
    srv.add_model(Model.from_artifact(model_dir, name="mlp"))
    srv.start()
    return srv


def closed_loop_capacity(model_dir, example, *, workers, duration_s,
                         max_batch, max_wait_ms):
    """Saturation req/s: C workers, back-to-back sync infers (shared
    generator: serving_common.closed_loop)."""
    srv = _make_server(model_dir, shed=True, queue=max(256, 4 * workers),
                      deadline_ms=None, max_batch=max_batch,
                      max_wait_ms=max_wait_ms)
    try:
        _lat, row = closed_loop(srv, example, workers=workers,
                                duration_s=duration_s)
    finally:
        srv.shutdown(drain=True)
    return row


def open_loop_arm(model_dir, example, *, rate, duration_s, shed, queue,
                  deadline_ms, max_batch, max_wait_ms, tick_s=0.005,
                  label="", sample_queue=False):
    """Offer `rate` req/s for `duration_s`; return the arm's row.

    Each arm writes its own JSONL span log, and the committed row
    carries the per-request budget (queue+batch wait vs model dispatch)
    the doctor derives from it — `python -m paddle_tpu doctor` over the
    same log reproduces the breakdown."""
    import re
    import tempfile

    from paddle_tpu import faults, flags
    # one log PER ARM (unique path: the JSONL writer only reopens on a
    # path CHANGE, so reusing one name across arms would keep writing
    # into the first arm's unlinked inode)
    slug = re.sub(r"[^A-Za-z0-9_.-]", "_", label or f"rate{rate:g}")
    log = os.path.join(tempfile.gettempdir(),
                       f"pt_serving_arm_{os.getpid()}_{slug}.jsonl")
    try:
        os.remove(log)
    except OSError:
        pass
    prev_log = flags.get_flag("metrics_log")
    flags.set_flag("metrics_log", log)
    try:
        srv = _make_server(model_dir, shed=shed, queue=queue,
                           deadline_ms=deadline_ms, max_batch=max_batch,
                           max_wait_ms=max_wait_ms)
        col = _Collector()
        offered = 0
        queue_samples = []
        t0 = time.monotonic()
        next_sample = t0
        end = t0 + duration_s
        while True:
            now = time.monotonic()
            if now >= end:
                break
            # offer every request whose arrival time has passed (burst
            # ticks: open-loop arrivals never slow down with the server)
            due = int((now - t0) * rate) - offered
            for _ in range(due):
                offered += 1
                try:
                    pending = srv.submit(example, deadline_ms=deadline_ms)
                except (faults.Overloaded, faults.ServerClosed,
                        faults.ModelUnavailable) as e:
                    col.note_admission_reject(e)
                    continue
                pending.add_done_callback(col.cb)
            if sample_queue and now >= next_sample:
                queue_samples.append(
                    (round(now - t0, 2),
                     srv.health()["models"]["mlp"]["queue_depth"]))
                next_sample = now + 0.5
            time.sleep(tick_s)
        gen_wall = time.monotonic() - t0
        pending_at_stop = srv.health()["models"]["mlp"]["queue_depth"]
        if sample_queue:
            queue_samples.append((round(gen_wall, 2), pending_at_stop))
        # control arm: do NOT drain the unbounded backlog through the
        # model (it would take rate/capacity * duration longer); abort it
        # and let the completed set speak.  Shedding arms drain in
        # bounded time.
        srv.shutdown(drain=shed, timeout=60)
    finally:
        # restore even when the arm dies mid-flight — leaking the arm's
        # temp path would permanently clobber a user-set metrics log
        flags.set_flag("metrics_log", prev_log or "")
    with col.lock:
        lat = sorted(col.latency_ms)
        errors = dict(col.errors)
    served = len(lat)
    row = {
        "label": label, "offered_per_s": rate,
        "duration_s": round(gen_wall, 3), "offered": offered,
        "served": served,
        "served_per_s": round(served / gen_wall, 1),
        "latency_ms_p50": round(percentile(lat, 0.50), 2) if lat else None,
        "latency_ms_p90": round(percentile(lat, 0.90), 2) if lat else None,
        "latency_ms_p99": round(percentile(lat, 0.99), 2) if lat else None,
        "errors": errors,
        "shed": errors.get("Overloaded", 0),
        "deadline_expired": errors.get("DeadlineExceeded", 0),
        "shed_rate": round(errors.get("Overloaded", 0) / offered, 4)
        if offered else None,
        "config": {"shed": shed, "queue": queue,
                   "deadline_ms": deadline_ms, "max_batch": max_batch,
                   "max_wait_ms": max_wait_ms},
    }
    if sample_queue:
        row["queue_depth_samples"] = queue_samples
        row["pending_at_stop"] = pending_at_stop
        row["aborted_at_stop"] = errors.get("ServerClosed", 0)
    try:
        from paddle_tpu.observability import attribution
        row["doctor"] = attribution.doctor_report([log]).get("serving")
    except OSError:
        row["doctor"] = None       # log unreadable: the arm row stands
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny durations (CI smoke, numbers meaningless)")
    ap.add_argument("--duration-s", type=float, default=4.0)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--queue", type=int, default=32,
                    help="admission queue capacity (the shed arms' "
                         "latency bound is ~queue/throughput)")
    ap.add_argument("--deadline-ms", type=float, default=1000.0)
    ap.add_argument("--workers", type=int, default=64,
                    help="closed-loop capacity-probe concurrency")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args(argv)
    if args.smoke:
        args.duration_s = 1.0
        args.workers = 16

    import jax

    model_dir = export_mlp("/tmp/pt_serving_bench_mlp")
    _, manifest = load_artifact(model_dir)
    rng = np.random.RandomState(0)
    example = single_example(manifest, rng)

    print(json.dumps({"phase": "capacity_probe"}), flush=True)
    cap = closed_loop_capacity(
        model_dir, example, workers=args.workers,
        duration_s=args.duration_s, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms)
    print(json.dumps({"closed_loop": cap}), flush=True)
    # The closed loop UNDERESTIMATES capacity (workers wait out their own
    # round trips, so batches under-fill); saturation throughput under
    # heavy open-loop overload is the honest "1x" anchor — offered load
    # factors are relative to what the server can actually serve.
    sat = open_loop_arm(
        model_dir, example, rate=max(1.0, cap["req_per_s"] * 4.0),
        duration_s=args.duration_s, shed=True, queue=args.queue,
        deadline_ms=args.deadline_ms, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, label="saturation_probe")
    print(json.dumps({"saturation_probe": sat}), flush=True)
    capacity = max(cap["req_per_s"], sat["served_per_s"])

    arms = []
    for factor in (0.25, 0.5, 1.0, 2.0, 4.0):
        rate = max(1.0, capacity * factor)
        row = open_loop_arm(
            model_dir, example, rate=rate, duration_s=args.duration_s,
            shed=True, queue=args.queue, deadline_ms=args.deadline_ms,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            label=f"{factor:g}x_shed")
        row["load_factor"] = factor
        arms.append(row)
        print(json.dumps({"open_loop": row}), flush=True)

    # Control anchor: the FASTEST service rate demonstrated anywhere so
    # far (this ~1-core box's throughput swings 2-3x with neighbors; an
    # early low probe would leave the "overload" control under-loaded).
    # If the box speeds up mid-run and the queue still doesn't grow,
    # escalate the offered multiple until it demonstrably does.
    anchor = max([capacity] + [a["served_per_s"] for a in arms])
    control = None
    for mult in (2.0, 3.0, 4.0):
        control = open_loop_arm(
            model_dir, example, rate=max(1.0, anchor * mult),
            duration_s=args.duration_s, shed=False, queue=None,
            deadline_ms=None, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            label=f"{mult:g}x_control_no_shedding", sample_queue=True)
        control["load_factor"] = mult
        print(json.dumps({"open_loop": control}), flush=True)
        if control["pending_at_stop"] >= 4 * args.max_batch:
            break
        print(json.dumps({"note": "control arm not overloaded (box sped "
                          "up mid-run); escalating offered load"}),
              flush=True)

    p99_1x = next(a["latency_ms_p99"] for a in arms
                  if a["load_factor"] == 1.0)
    p99_2x = next(a["latency_ms_p99"] for a in arms
                  if a["load_factor"] == 2.0)
    # an arm that served nothing (every request shed/expired on a slow
    # enough box) reports p99 None — the acceptance fields must degrade
    # to None/False, not TypeError after every row was measured
    acceptance = {
        "p99_1x_ms": p99_1x, "p99_2x_shed_ms": p99_2x,
        "p99_2x_control_ms": control["latency_ms_p99"],
        "p99_ratio_2x_over_1x": round(p99_2x / p99_1x, 3)
        if p99_1x and p99_2x is not None else None,
        "bounded_under_overload": bool(
            p99_1x and p99_2x is not None and p99_2x <= 2.0 * p99_1x),
        "control_collapse_factor": round(
            control["latency_ms_p99"] / p99_1x, 1)
        if p99_1x and control["latency_ms_p99"] else None,
    }
    print(json.dumps({"acceptance": acceptance}), flush=True)

    results = {
        "engine": "in-process Server over exported StableHLO artifact "
                  "(benchmark/serving_common.export_mlp 784-2048x3-10)",
        "device": str(jax.devices()[0]),
        "note": "CPU in-container rows; ~1 effective host core "
                "(RESULTS.md round 7) bounds absolute capacity — the "
                "shed-vs-control CURVES are the result",
        "closed_loop": cap,
        "saturation_probe": sat,
        "capacity_req_per_s": capacity,
        "open_loop": arms,
        "control": control,
        "acceptance": acceptance,
        "tpu": {"status": "pending hardware",
                "note": "re-run python benchmark/serving.py on a chip "
                        "host and commit the filled rows (PR 1 stub "
                        "convention)", "rows": []},
    }
    if not args.smoke:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
