#!/usr/bin/env python
"""Autotuner A/B: tuned-vs-default on the host-side tunables, committed.

For each host-side tunable with a built-in target
(``paddle_tpu.tuning.targets``) this driver runs the REAL search path —
``tuning.search.tune``: grid over the declared space, then the paired
alternating default-vs-winner A/B whose headline is the MEDIAN OF
PER-PAIR RATIOS (the PR 2 discipline; this container's throughput drifts
2-3x on multi-minute timescales and pairing cancels what independent
medians cannot) — and commits the outcome VERBATIM: a winner only when
the noise gate accepts it, otherwise the gate's explicit refusal WITH
the raw windows.  Either is a valid committed row; a fabricated speedup
is not.

Winners are persisted to a store directory (default: a throwaway tmp
dir; pass ``--cache-dir`` to keep them for replay via
``PADDLE_TPU_AUTOTUNE=1``), proving the full search → persist → replay
loop in one run.

Device-side tunables cannot be searched in this container (no TPU);
their rows are pending-hardware stubs carrying the pre-registered
decision rules (the PR 1 convention) — the first chip session runs
``python -m paddle_tpu tune <target>`` and fills them.

Usage:
    python benchmark/autotune.py              # full A/B, writes
                                              # autotune_results.json
    python benchmark/autotune.py --smoke      # seconds-fast path check
    python benchmark/autotune.py --target serving/batcher
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

RESULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "autotune_results.json")

HOST_TUNABLES = ("executor/run_pipelined", "serving/batcher",
                 "reader/prefetch")
DEVICE_TUNABLES = ("pallas/flash_attention", "pallas/conv1x1_blocks",
                   "xla/scoped_vmem_limit_kib")


def run_one(name: str, store_dir: str, smoke: bool, quiet: bool = False):
    from paddle_tpu.tuning import search, targets

    targets.ensure_registered(name)
    measure = targets.build_target(name, smoke=smoke)

    def on_trial(t):
        if not quiet:
            print(json.dumps({"tunable": name, "trial": t.config,
                              "status": t.status, "seconds": t.seconds}),
                  flush=True)

    doc = search.tune(name, measure,
                      reps=2 if smoke else 3,
                      pairs=3 if smoke else 7,
                      budget=4 if smoke else None,
                      base=store_dir, save=True, on_trial=on_trial)
    trials = doc.get("search", {}).get("trials", [])
    row = {
        "tunable": name,
        "status": doc["status"],
        "default": doc.get("search", {}).get("default"),
        "winner": doc.get("winner"),
        "trials": [{"config": t["config"], "status": t["status"],
                    "seconds": t["seconds"]} for t in trials],
        "smoke": smoke,
    }
    ab = doc.get("ab")
    if ab is not None:
        # the verdict AND its evidence: raw alternating windows + pair
        # ratios, so a refusal is an auditable fact, not a missing row
        row["ab"] = {k: ab[k] for k in
                     ("speedup", "pair_ratios", "default_windows",
                      "candidate_windows", "min_speedup", "accepted",
                      "refusal_reason")}
    if doc.get("record_path"):
        row["record_committed"] = True
    if not quiet:
        print(json.dumps({k: row[k] for k in ("tunable", "status",
                                              "winner")}
                         | ({"speedup": ab["speedup"]} if ab else {}),
              ), flush=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", default="all",
                    choices=["all"] + sorted(HOST_TUNABLES))
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-fast path check (tiny sizes, capped "
                         "budget); does not overwrite the committed "
                         "results file")
    ap.add_argument("--cache-dir", default=None,
                    help="persist winners here for later replay "
                         "(default: throwaway tmp dir)")
    ap.add_argument("--json", default=None,
                    help="results path (default: benchmark/"
                         "autotune_results.json; smoke runs only write "
                         "when given explicitly)")
    args = ap.parse_args()

    names = sorted(HOST_TUNABLES) if args.target == "all" \
        else [args.target]
    tmp = None
    store_dir = args.cache_dir
    if store_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pt-autotune-")
        store_dir = tmp.name

    rows = [run_one(n, store_dir, smoke=args.smoke) for n in names]

    from paddle_tpu.tuning import search as _search
    from paddle_tpu.tuning import targets as _targets
    for n in DEVICE_TUNABLES:
        _targets.ensure_registered(n)
    pending = [_search.pending_stub(n) for n in DEVICE_TUNABLES]

    out_path = args.json or (None if args.smoke else RESULTS_PATH)
    if out_path:
        from input_pipeline import host_parallel_efficiency
        doc = {
            "description": "persistent-autotuner A/B: tuned-vs-default "
                           "per host-side tunable (search -> paired "
                           "alternating windows, median of per-pair "
                           "ratios, noise-gate verdicts committed "
                           "verbatim with raw windows)",
            "platform": __import__("jax").devices()[0].platform,
            "cpu_count": os.cpu_count(),
            "host_parallel_efficiency": host_parallel_efficiency(),
            "min_speedup_gate": 1.10,
            "rows": rows,
            "pending_hardware": pending,
        }
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"# wrote {out_path}", file=sys.stderr)
    if tmp is not None:
        tmp.cleanup()
    return rows


if __name__ == "__main__":
    main()
