"""HBM stream-bandwidth cross-check (VERDICT r4 'what's weak' #2).

The ResNet-50 roofline in RESULTS.md rests on a ~300 GB/s effective HBM
bandwidth figure that was measured only with jnp elementwise kernels.  If
the part actually streams faster and the jnp kernels are the limiter, the
"2650 img/s is the ceiling" claim is wrong.  This benchmark measures the
same quantity three independent ways:

  1. jnp    — the original method: elementwise copy/axpy lowered by XLA,
              K sequential repeats inside one lax.scan dispatch (carry
              evolves each step so nothing hoists out of the loop).
  2. pallas-grid — a Pallas kernel whose grid pipeline auto-double-buffers
              chunk DMAs HBM->VMEM->HBM around the VPU op.
  3. pallas-dma  — a hand-written double-buffered ``pltpu.make_async_copy``
              stream (explicit semaphores, 2 VMEM slots), the method the
              verdict prescribed; pure DMA, no VPU in the loop for copy.

Traffic accounting: copy moves 2N bytes per pass (read + write), axpy
(z = a*x + y) moves 3N.  Reported GB/s = traffic / median window time.

Run on the real chip (no env overrides):  python benchmark/bandwidth.py
Writes benchmark/bandwidth_results.json and prints a table.
"""
from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False

LANES = 512                      # f32 row = 2 KB
CHUNK_ROWS = 1024                # chunk = 2 MB (2 slots -> 4 MB VMEM)


# ---------------------------------------------------------------------------
# method 1: jnp elementwise, serialized by an evolving scan carry
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("k",))
def _jnp_copy_k(x, k):
    # c * 1.0 would fold; 1.0000001 keeps a real read+write per step
    return lax.scan(lambda c, _: (c * jnp.float32(1.0000001), None),
                    x, None, length=k)[0]


@functools.partial(jax.jit, static_argnames=("k",))
def _jnp_axpy_k(x, y, k):
    return lax.scan(lambda c, _: (jnp.float32(1.0000001) * x + c, None),
                    y, None, length=k)[0]


# ---------------------------------------------------------------------------
# method 2: Pallas grid pipeline (automatic double-buffered chunk DMA)
# ---------------------------------------------------------------------------
def _grid_copy(x):
    n = x.shape[0] // CHUNK_ROWS

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 1.0000001

    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[pl.BlockSpec((CHUNK_ROWS, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((CHUNK_ROWS, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def _grid_axpy(x, y):
    n = x.shape[0] // CHUNK_ROWS

    def kern(x_ref, y_ref, o_ref):
        o_ref[...] = 1.0000001 * x_ref[...] + y_ref[...]

    return pl.pallas_call(
        kern,
        grid=(n,),
        in_specs=[pl.BlockSpec((CHUNK_ROWS, LANES), lambda i: (i, 0))] * 2,
        out_specs=pl.BlockSpec((CHUNK_ROWS, LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x, y)


@functools.partial(jax.jit, static_argnames=("k",))
def _grid_copy_k(x, k):
    return lax.scan(lambda c, _: (_grid_copy(c), None), x, None,
                    length=k)[0]


@functools.partial(jax.jit, static_argnames=("k",))
def _grid_axpy_k(x, y, k):
    return lax.scan(lambda c, _: (_grid_axpy(x, c), None), y, None,
                    length=k)[0]


# ---------------------------------------------------------------------------
# method 3: hand-written double-buffered make_async_copy stream
# ---------------------------------------------------------------------------
def _dma_copy(x):
    """Pure-DMA copy: chunks stream HBM->VMEM slot->HBM, two slots, input
    DMA for chunk i+1 in flight while chunk i's output DMA drains."""
    n = x.shape[0] // CHUNK_ROWS

    def kern(x_hbm, o_hbm):
        def body(scratch, in_sems, out_sems):
            def in_dma(slot, i):
                return pltpu.make_async_copy(
                    x_hbm.at[pl.ds(i * CHUNK_ROWS, CHUNK_ROWS)],
                    scratch.at[slot], in_sems.at[slot])

            def out_dma(slot, i):
                return pltpu.make_async_copy(
                    scratch.at[slot],
                    o_hbm.at[pl.ds(i * CHUNK_ROWS, CHUNK_ROWS)],
                    out_sems.at[slot])

            in_dma(0, 0).start()

            def loop(i, _):
                slot = i % 2
                nxt = (i + 1) % 2

                # before refilling the other slot, its previous chunk's
                # output DMA must have drained
                @pl.when((i + 1 < n) & (i >= 1))
                def _():
                    out_dma(nxt, i - 1).wait()

                @pl.when(i + 1 < n)
                def _():
                    in_dma(nxt, i + 1).start()

                in_dma(slot, i).wait()
                out_dma(slot, i).start()
                return _

            lax.fori_loop(0, n, loop, None)
            out_dma((n - 1) % 2, n - 1).wait()

            @pl.when(n >= 2)
            def _():
                out_dma(n % 2, n - 2).wait()

        pl.run_scoped(
            body,
            scratch=pltpu.VMEM((2, CHUNK_ROWS, LANES), jnp.float32),
            in_sems=pltpu.SemaphoreType.DMA((2,)),
            out_sems=pltpu.SemaphoreType.DMA((2,)),
        )

    return pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


@functools.partial(jax.jit, static_argnames=("k",))
def _dma_copy_k(x, k):
    return lax.scan(lambda c, _: (_dma_copy(c), None), x, None,
                    length=k)[0]


# ---------------------------------------------------------------------------
def _force(x):
    """Force completion.  On the tunneled axon platform block_until_ready
    returns before the computation drains, so completion is forced by a
    data-dependent scalar fetch (~0.1 s tunnel round trip — measured and
    subtracted as the ``latency`` control)."""
    return float(jnp.ravel(x)[0])


def _time_fn(fn, *args, k, traffic_bytes, windows=5):
    out = fn(*args, k=k)                     # compile + warm
    _force(out)
    lat = []
    for _ in range(3):
        t0 = time.perf_counter()
        _force(out)
        lat.append(time.perf_counter() - t0)
    lat_med = float(np.median(lat))
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        o = fn(*args, k=k)
        _force(o)
        times.append(time.perf_counter() - t0)
    med = float(np.median(times))
    eff = max(med - lat_med, 1e-9)
    return {"gbps": traffic_bytes * k / eff / 1e9,
            "window_s": med, "fetch_latency_s": lat_med,
            "spread_pct": 100.0 * (max(times) - min(times)) / med}


def main():
    results = {"device": str(jax.devices()[0]),
               "chunk_mb": CHUNK_ROWS * LANES * 4 / 2**20, "rows": []}
    sizes_mb = [128, 512, 1024, 2048]
    for mb in sizes_mb:
        rows = mb * 2**20 // (LANES * 4)
        rows -= rows % CHUNK_ROWS
        nbytes = rows * LANES * 4
        # window >= ~2 s at an assumed 300 GB/s: the ~0.1 s completion-fetch
        # tunnel latency (subtracted, but noisy) must stay a small fraction
        k = min(4000, max(4, int(2.0 * 300e9 / (2 * nbytes))))
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (rows, LANES), jnp.float32)
        y = jax.random.normal(jax.random.PRNGKey(1), (rows, LANES),
                              jnp.float32)

        row = {"size_mb": nbytes / 2**20, "k": k}
        row["jnp_copy"] = _time_fn(_jnp_copy_k, x, k=k,
                                   traffic_bytes=2 * nbytes)
        row["jnp_axpy"] = _time_fn(_jnp_axpy_k, x, y, k=k,
                                   traffic_bytes=3 * nbytes)
        if _HAVE_PALLAS and jax.default_backend() == "tpu":
            row["pallas_grid_copy"] = _time_fn(_grid_copy_k, x, k=k,
                                               traffic_bytes=2 * nbytes)
            row["pallas_grid_axpy"] = _time_fn(_grid_axpy_k, x, y, k=k,
                                               traffic_bytes=3 * nbytes)
            row["pallas_dma_copy"] = _time_fn(_dma_copy_k, x, k=k,
                                              traffic_bytes=2 * nbytes)
        results["rows"].append(row)
        del x, y
        print(json.dumps(row))

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bandwidth_results.json")
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"\nwrote {out_path}")
    # summary table
    print(f"{'MB':>6} " + " ".join(f"{m:>16}" for m in
          ("jnp_copy", "jnp_axpy", "grid_copy", "grid_axpy", "dma_copy")))
    for r in results["rows"]:
        vals = [r.get(m, {}).get("gbps") for m in
                ("jnp_copy", "jnp_axpy", "pallas_grid_copy",
                 "pallas_grid_axpy", "pallas_dma_copy")]
        print(f"{r['size_mb']:>6.0f} " + " ".join(
            f"{v:>14.1f}GB" if v else f"{'-':>16}" for v in vals))


if __name__ == "__main__":
    main()
