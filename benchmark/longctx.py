"""Long-context training microbenchmark: causal flash-attention training
steps at 16k-64k tokens on ONE chip — the O(T)-memory capability the
2017 reference had no answer to (its longest sequences were LoD-packed
RNN batches; an O(T^2) attention at 64k would need a 32 GB score matrix
per head in f32, vs O(T) VMEM streaming here).

Per row: one fused step = forward + FlashAttention-2 backward through
``ops.pallas_kernels.flash_attention`` plus a trivial loss, timed as
compiled ``lax.scan`` windows with the pinned methodology
(scalar-fetch completion, median of windows).

Modes:
  python benchmark/longctx.py              default table (16k/32k/64k,
                                           1024x1024 blocks)
  python benchmark/longctx.py --sweep      32k/64k block sweep with
                                           ``xla_tpu_scoped_vmem_limit_kib``
                                           raised to 32/64 MB — unlocking
                                           the 2048-row blocks the 16 MB
                                           default rejects, plus deeper
                                           K-streaming (block_k 2048/4096
                                           at block_q 512) and a d=128
                                           head-dim control
  python benchmark/longctx.py --framework  the same 64k step through the
                                           FRAMEWORK path — a Program
                                           running ``layers.flash_attention``
                                           via ``Executor.run_steps`` —
                                           vs the raw-kernel number

Results merge into benchmark/longctx_results.json.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                   # noqa: E402
import jax.numpy as jnp                      # noqa: E402
from jax import lax                          # noqa: E402

from paddle_tpu.ops.pallas_kernels import flash_attention  # noqa: E402
# the shared measurement harness (paddle_tpu.tuning.search): warmup
# discard, median of windows, per-config fault containment — this
# benchmark is a thin driver over it since the autotuner PR
from paddle_tpu.tuning.search import run_trial, time_windows  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "longctx_results.json")

HEADS, DIM = 8, 64

# the sweep grid: (block_q, block_k).  1024x1024 is the shipped default;
# 2048-row blocks exceed the 16 MB default scoped VMEM (the round-5
# rejection) and need the 32/64 MB knob; 512x2048/512x4096 trade grid
# parallelism for deeper K streams.
SWEEP_BLOCKS = [(1024, 1024), (2048, 1024), (1024, 2048), (2048, 2048),
                (512, 2048), (512, 4096)]
SWEEP_VMEM_KIB = [None, 32 * 1024, 64 * 1024]     # None = 16 MB default


def make_step(T, block_q=1024, block_k=1024):
    def loss_fn(qkv):
        q, k, v = qkv
        o = flash_attention(q, k, v, causal=True, block_q=block_q,
                            block_k=block_k)
        return jnp.sum(o.astype(jnp.float32) ** 2) * 1e-6

    grad = jax.value_and_grad(loss_fn)

    @functools.partial(jax.jit, static_argnames=("steps",))
    def run(qkv, steps):
        def body(carry, _):
            l, g = grad(carry)
            # SGD-like touch so iterations chain (nothing hoists)
            new = tuple(x - 1e-6 * gx.astype(x.dtype)
                        for x, gx in zip(carry, g))
            return new, l

        qkv, losses = lax.scan(body, qkv, None, length=steps)
        return losses

    return run


def _qkv(T, dim=DIM):
    rng = np.random.RandomState(0)
    return tuple(jnp.asarray(rng.randn(HEADS, T, dim), jnp.bfloat16)
                 for _ in range(3))


def _steps_for(T):
    steps = max(2, int(2e9 // (T * T // 64)))   # ~few windows/s
    return int(np.clip(steps, 2, 30))


def _timed(call, steps, reps=3):
    """Median s/step + spread via the engine harness; ``call`` returns
    the loss stack, materialized here as the completion barrier."""
    tw = time_windows(lambda: float(call()[-1]), reps=reps, warmup=1,
                      unit=steps)
    return tw["seconds"], tw["spread_pct"]


def _attn_flops(T, dim=DIM):
    # attention-only FLOPs: fwd 2*2*BH*T^2/2*D (causal), bwd ~2.5x
    return 3.5 * 2 * HEADS * (T * T / 2) * dim * 2


def default_table(results):
    results["rows"] = []
    for T in (16384, 32768, 65536):
        qkv = _qkv(T)
        run = make_step(T)
        steps = _steps_for(T)
        med, spread = _timed(lambda: run(qkv, steps), steps)
        row = {"tokens": T, "ms_per_step": round(med * 1e3, 2),
               "tokens_per_sec": round(T / med),
               "attn_tflops": round(_attn_flops(T) / med / 1e12, 1),
               "spread_pct": spread}
        results["rows"].append(row)
        print(json.dumps(row), flush=True)


def _sweep_measure(T, bq, bk, kib, qkv, steps, d=DIM):
    """One-window measure closure for the search engine: compile lazily
    on the first (warmup-discarded) window, exactly where the bespoke
    loop compiled; a VMEM rejection therefore surfaces as the trial's
    recorded failure — which IS the sweep result for that config."""
    state = {}

    def measure(_cfg):
        if "comp" not in state:
            run = make_step(T, bq, bk)
            opts = ({"xla_tpu_scoped_vmem_limit_kib": str(kib)}
                    if kib else None)
            state["comp"] = jax.jit(run, static_argnames=("steps",)) \
                .lower(qkv, steps).compile(compiler_options=opts)
        float(state["comp"](qkv)[-1])        # completion barrier
    return measure


def _trial_row(trial, T, steps, base_row, d=DIM):
    """Map an engine Trial onto the committed sweep row format."""
    row = dict(base_row)
    if trial.status == "ok":
        med = trial.seconds / steps
        row.update(ms_per_step=round(med * 1e3, 2),
                   attn_tflops=round(_attn_flops(T, d) / med / 1e12, 1),
                   spread_pct=trial.spread_pct)
    else:
        row["error"] = (trial.error or trial.status)[:160]
    return row


def sweep(results):
    """32k/64k block sweep across scoped-VMEM limits — a thin driver over
    the autotuner search engine (`tuning.search.run_trial` provides the
    warmup-discard/median-of-windows harness AND the per-config fault
    containment: a config whose kernel VMEM footprint exceeds the limit
    records its compile error as the row, never kills the sweep)."""
    rows = []
    for T in (32768, 65536):
        steps = _steps_for(T)
        qkv = _qkv(T)            # one host-RNG + device_put per T, not per row
        for kib in SWEEP_VMEM_KIB:
            for bq, bk in SWEEP_BLOCKS:
                trial = run_trial(
                    _sweep_measure(T, bq, bk, kib, qkv, steps),
                    {"block_q": bq, "block_k": bk,
                     "scoped_vmem_kib": kib or 16 * 1024},
                    reps=3, warmup=1, trial_timeout_s=600.0)
                row = _trial_row(trial, T, steps,
                                 {"tokens": T, "block_q": bq,
                                  "block_k": bk,
                                  "scoped_vmem_mb":
                                      (kib or 16 * 1024) // 1024})
                rows.append(row)
                print(json.dumps(row), flush=True)
    # head-dim control: the same kernel at d=128 (2x the MXU lane fill of
    # the d=64 table rows) — isolates the structural head-dim cap from
    # any VMEM/block effect
    T, d = 32768, 128
    qkv = _qkv(T, d)                     # head dim comes from the arrays
    steps = _steps_for(T)
    trial = run_trial(
        _sweep_measure(T, 1024, 1024, 32 * 1024, qkv, steps, d=d),
        {"block_q": 1024, "block_k": 1024,
         "scoped_vmem_kib": 32 * 1024},
        reps=3, warmup=1, trial_timeout_s=600.0)
    ctrl = _trial_row(trial, T, steps,
                      {"tokens": T, "head_dim": d, "block_q": 1024,
                       "block_k": 1024, "scoped_vmem_mb": 32}, d=d)
    print(json.dumps(ctrl), flush=True)
    results["sweep"] = {"rows": rows, "head_dim_control": ctrl}


def framework_path(results, T=65536, interpret=False):
    """The 64k step through the framework: layers.flash_attention inside
    a Program, trained via Executor.run_steps — the number users get,
    to be within ~2% of the raw-kernel row."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()

    shape = [HEADS, T, DIM]
    q = pt.layer_helper.LayerHelper("lc").create_parameter(
        pt.ParamAttr(name="lc_q"), shape=shape, dtype="float32")
    k = pt.layer_helper.LayerHelper("lc").create_parameter(
        pt.ParamAttr(name="lc_k"), shape=shape, dtype="float32")
    v = pt.layer_helper.LayerHelper("lc").create_parameter(
        pt.ParamAttr(name="lc_v"), shape=shape, dtype="float32")
    o = layers.flash_attention(q, k, v, causal=True, block_q=1024,
                               block_k=1024, interpret=interpret)
    loss = layers.scale(layers.mean(layers.elementwise_mul(o, o)), 1e-3)
    pt.optimizer.SGD(learning_rate=1e-6).minimize(loss)

    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    prog = pt.default_main_program()
    steps = _steps_for(T)

    def call():
        (lv,) = exe.run_steps(steps, prog, feed={}, fetch_list=[loss],
                              return_numpy=False)
        # unconditional materialization = the completion barrier
        if not np.isfinite(np.asarray(lv)[-1]):
            raise FloatingPointError("non-finite loss in timed window")

    tw = time_windows(call, reps=3, warmup=1, unit=steps)
    row = {"tokens": T, "path": "framework(Executor.run_steps)",
           "ms_per_step": round(tw["seconds"] * 1e3, 2),
           "spread_pct": tw["spread_pct"]}
    print(json.dumps(row), flush=True)
    results["framework_path"] = row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--framework", action="store_true")
    ap.add_argument("--framework-tokens", type=int, default=65536)
    ap.add_argument("--interpret", action="store_true",
                    help="CPU shakeout (tiny T, interpret kernels)")
    args = ap.parse_args()

    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    results.update(device=str(jax.devices()[0]), heads=HEADS, dim=DIM)

    if args.interpret:
        framework_path(results, T=512, interpret=True)
        return                                    # shakeout only; no write
    if args.sweep:
        sweep(results)
    elif args.framework:
        framework_path(results, T=args.framework_tokens)
    else:
        default_table(results)
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
