"""Long-context training microbenchmark: causal flash-attention training
steps at 16k-64k tokens on ONE chip — the O(T)-memory capability the
2017 reference had no answer to (its longest sequences were LoD-packed
RNN batches; an O(T^2) attention at 64k would need a 32 GB score matrix
per head in f32, vs O(T) VMEM streaming here).

Per row: one fused step = forward + FlashAttention-2 backward through
``ops.pallas_kernels.flash_attention`` (blocks 1024x1024, swept) plus a
trivial loss, timed as compiled ``lax.scan`` windows with the pinned
methodology (scalar-fetch completion, median of windows).

Run: python benchmark/longctx.py  ->  benchmark/longctx_results.json
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                   # noqa: E402
import jax.numpy as jnp                      # noqa: E402
from jax import lax                          # noqa: E402

from paddle_tpu.ops.pallas_kernels import flash_attention  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "longctx_results.json")

HEADS, DIM = 8, 64


def make_step(T):
    def loss_fn(qkv):
        q, k, v = qkv
        o = flash_attention(q, k, v, causal=True, block_q=1024,
                            block_k=1024)
        return jnp.sum(o.astype(jnp.float32) ** 2) * 1e-6

    grad = jax.value_and_grad(loss_fn)

    @functools.partial(jax.jit, static_argnames=("steps",))
    def run(qkv, steps):
        def body(carry, _):
            l, g = grad(carry)
            # SGD-like touch so iterations chain (nothing hoists)
            new = tuple(x - 1e-6 * gx.astype(x.dtype)
                        for x, gx in zip(carry, g))
            return new, l

        qkv, losses = lax.scan(body, qkv, None, length=steps)
        return losses

    return run


def main():
    results = {"device": str(jax.devices()[0]), "heads": HEADS,
               "dim": DIM, "rows": []}
    rng = np.random.RandomState(0)
    for T in (16384, 32768, 65536):
        BH = HEADS                       # [BH, T, D] layout, batch 1
        qkv = tuple(jnp.asarray(rng.randn(BH, T, DIM), jnp.bfloat16)
                    for _ in range(3))
        run = make_step(T)
        steps = max(2, int(2e9 // (T * T // 64)))   # ~few windows/s
        steps = int(np.clip(steps, 2, 30))
        losses = run(qkv, steps)
        float(losses[-1])                # compile + warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            losses = run(qkv, steps)
            float(losses[-1])            # completion barrier
            times.append(time.perf_counter() - t0)
        med = float(np.median(times)) / steps
        # attention-only FLOPs: fwd 2*2*BH*T^2/2*D (causal), bwd ~2.5x
        flops = 3.5 * 2 * BH * (T * T / 2) * DIM * 2
        row = {"tokens": T, "ms_per_step": round(med * 1e3, 2),
               "tokens_per_sec": round(T / med),
               "attn_tflops": round(flops / med / 1e12, 1),
               "spread_pct": round(100 * (max(times) - min(times))
                                   / np.median(times), 2)}
        results["rows"].append(row)
        print(json.dumps(row), flush=True)
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
