"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's best published ResNet-50 *training* number is
82.35 img/s (batch 128) on a 2x20-core Skylake with MKL-DNN
(benchmark/IntelOptimizedPaddle.md:39-45 — no GPU ResNet-50 number exists
in-repo; BASELINE.md "Gaps").  vs_baseline = ours / 82.35.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMG_S = 82.35
BATCH = 128
WARMUP = 5
ITERS = 30


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu import layers, models

    img = layers.data("img", shape=[3, 224, 224], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    pred = models.resnet50(img, num_classes=1000)
    loss = layers.mean(layers.cross_entropy(pred, label))
    opt = pt.optimizer.Momentum(learning_rate=0.01 / BATCH, momentum=0.9)
    opt.minimize(loss)

    # bf16 compute + fp32 master weights + XLA-chosen parameter layouts:
    # the TPU-idiomatic training mode (auto_layout removes the per-step
    # layout-normalizing copies on every donated conv filter)
    exe = pt.Executor(amp=True, auto_layout=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])

    rng = np.random.RandomState(0)
    # feeds live on device: a real input pipeline overlaps transfers, and the
    # axon tunnel's host<->device hop would otherwise dominate the timing
    feeds = {"img": jax.device_put(
        rng.rand(BATCH, 3, 224, 224).astype("float32")),
        "label": jax.device_put(rng.randint(0, 1000, (BATCH, 1)))}

    # ONE compiled step variant (same fetch_list every call): fetch the loss
    # but keep it on device (return_numpy=False) — no per-step readback, and
    # auto_layout's pinned parameter layouts hold for the whole run
    prog = pt.default_main_program()
    for _ in range(WARMUP):
        (lv,) = exe.run(prog, feed=feeds, fetch_list=[loss],
                        return_numpy=False)
    assert np.isfinite(float(lv))   # block: warmup fully executed

    # enqueue all steps (the device serializes them through the donated
    # state dependency), then read ONE loss scalar: a single host readback
    # is a true execution barrier — block_until_ready is unreliable over the
    # tunnel, and a per-step readback would add ~70ms tunnel latency/step
    t0 = time.perf_counter()
    for _ in range(ITERS):
        (lv,) = exe.run(prog, feed=feeds, fetch_list=[loss],
                        return_numpy=False)
    assert np.isfinite(float(lv))
    elapsed = time.perf_counter() - t0

    img_s = BATCH * ITERS / elapsed
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # the driver records whatever line we print
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0, "unit": "img/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(1)
