"""Headline benchmark: ResNet-50 training throughput, images/sec/chip,
plus the seq2seq+attention tokens/s north-star (BASELINE.json).

``bench.py --mesh dp=8 [--simulate]`` runs the multi-chip leg instead: the
auto-sharding planner (paddle_tpu.analysis.planner) proposes specs for the
mesh, a ``ShardedExecutor(auto_shard=True)`` executes one training step
with them, and the fetches are checked against an unsharded step — the
planner-proposed-specs smoke row for MULTICHIP_*.json.  ``--simulate``
forces the 8-virtual-device CPU platform
(``--xla_force_host_platform_device_count``), so the row lands on a
chipless container; the throughput/scaling-efficiency measurement stays
pending-hardware until a session has a real multi-chip mesh (run the same
command there without ``--simulate``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
headline metric, with the seq2seq number carried in "extra_metrics" on the
same line (the driver records the whole object).

Methodology (pinned, round 4 — see benchmark/RESULTS.md "Methodology"):
- Each timed window is ONE compiled dispatch: Executor.run_steps(K)
  compiles lax.scan over K training steps with donated state, so host
  dispatch rate and axon-tunnel latency are out of the measurement (and
  out of the training loop — run_steps is the user-facing API).  Reading
  the stacked losses is the window barrier; the first call is
  compile + warmup.
- Median of N windows with the (max-min)/median spread reported: the
  tunnel can deliver slow windows under external contention; the median
  rejects them.

Baselines: the reference's best published ResNet-50 *training* number is
82.35 img/s (batch 128) on a 2x20-core Skylake with MKL-DNN
(benchmark/IntelOptimizedPaddle.md:39-45 — no GPU ResNet-50 number exists
in-repo; BASELINE.md "Gaps").  vs_baseline = ours / 82.35.  The reference
never published a seq2seq tokens/s number (BASELINE.md "Gaps"), so that
metric's vs_baseline is null — this framework's own measurement IS the
baseline going forward.
"""
from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np

BASELINE_IMG_S = 82.35
BATCH = 128


def _median_window_throughput(exe, prog, feeds, loss, units_per_step,
                              iters, reps):
    """Pinned timing core (round 4): each window is ONE compiled dispatch
    of ``iters`` steps (`Executor.run_steps` — a device-side lax.scan with
    donated state), so per-step host dispatch and tunnel latency are out
    of the measurement entirely; the first (untimed) call is the compile +
    warmup.  Median of `reps` windows; spread = (max-min)/median."""
    t0 = time.perf_counter()
    (lv,) = exe.run_steps(iters, prog, feed=feeds, fetch_list=[loss],
                          return_numpy=False)
    assert np.isfinite(np.asarray(lv)[-1])     # compile+warmup executed
    _median_window_throughput.last_warmup_s = time.perf_counter() - t0
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        (lv,) = exe.run_steps(iters, prog, feed=feeds, fetch_list=[loss],
                              return_numpy=False)
        assert np.isfinite(np.asarray(lv)[-1])   # barrier: window done
        rates.append(units_per_step * iters / (time.perf_counter() - t0))
    med = statistics.median(rates)
    return med, (max(rates) - min(rates)) / med


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu import layers, models, profiler

    # runtime observability ON for the whole driver run: every timed
    # dispatch lands in the step-time histograms and the pipeline leg
    # records its queue/stall numbers — snapshotted into the JSON line
    # below (headline fields unchanged; host-side only, zero retraces).
    # When no metrics_log is already configured, the headline leg writes
    # a temp JSONL so the doctor budget + cost-model calibration ride
    # the committed line (a user-set PADDLE_TPU_METRICS_LOG is used
    # as-is, never clobbered).
    pt.flags.set_flag("observe", True)
    own_log = not pt.flags.get_flag("metrics_log")
    if own_log:
        import os
        import tempfile
        resnet_log = os.path.join(tempfile.gettempdir(),
                                  f"pt_bench_resnet_{os.getpid()}.jsonl")
        try:
            os.remove(resnet_log)
        except OSError:
            pass
        pt.flags.set_flag("metrics_log", resnet_log)
    else:
        resnet_log = None          # user-owned log: never doctored here

    img = layers.data("img", shape=[3, 224, 224], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    pred = models.resnet50(img, num_classes=1000)
    loss = layers.mean(layers.cross_entropy(pred, label))
    opt = pt.optimizer.Momentum(learning_rate=0.01 / BATCH, momentum=0.9)
    opt.minimize(loss)

    # bf16 compute + fp32 master weights.  auto_layout is unnecessary
    # under run_steps: inside one scan executable XLA keeps parameters in
    # compute layouts across iterations (measured equal, 2648 vs 2652).
    # conv1x1_pallas stays OFF here: the Pallas 1x1 kernels
    # (ops/pallas_conv.py) are interpret-mode verified only — their
    # Mosaic/TPU lowering has never executed on hardware.  Flip it on in
    # the same commit as an on-chip per-op A/B (benchmark/conv_kernel.py)
    # showing >=1.2x, together with the re-measured driver number.
    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])

    rng = np.random.RandomState(0)
    # feeds live on device: a real input pipeline overlaps transfers, and the
    # axon tunnel's host<->device hop would otherwise dominate the timing
    feeds = {"img": jax.device_put(
        rng.rand(BATCH, 3, 224, 224).astype("float32")),
        "label": jax.device_put(rng.randint(0, 1000, (BATCH, 1)))}

    prog = pt.default_main_program()
    img_s, spread = _median_window_throughput(
        exe, prog, feeds, loss, units_per_step=BATCH, iters=80, reps=3)
    # snapshot NOW: the seq2seq/pipeline legs below reuse the timing core
    # and would overwrite last_warmup_s before the record is built
    resnet_warmup_s = getattr(_median_window_throughput, "last_warmup_s", 0.0)

    # doctor the headline leg from its own log window (before the other
    # legs write into it): measured budget + predicted-vs-measured
    # calibration row for the resnet program.  Only when the driver OWNS
    # a fresh temp log — a user-set PADDLE_TPU_METRICS_LOG appends
    # across runs, and a budget over earlier runs' events would attach a
    # wrong calibration ratio (run `paddle_tpu doctor` on such a log
    # directly instead).
    doctor_row = None
    if own_log:
        try:
            from paddle_tpu.observability import attribution
            report = attribution.doctor_report([resnet_log], program=prog,
                                               assume_batch=BATCH)
            doctor_row = {k: report.get(k)
                          for k in ("training", "calibration",
                                    "top_bottleneck") if k in report}
        except Exception:
            pass                   # headline metric still reports

    tok_s = tok_spread = None
    try:
        tok_s, tok_spread = _seq2seq_tokens_per_sec()
    except Exception:
        pass                       # headline metric still reports

    pipe_row = None
    try:
        pipe_row = _input_pipeline_speedup()
    except Exception:
        pass                       # headline metric still reports

    line = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "window_spread": round(spread, 4),
        # compile-time telemetry (core/compile_cache.py): how much of this
        # run went to trace/lower/compile, and whether the persistent
        # cache (PADDLE_TPU_CACHE_DIR) shortcut it — the cold-start axis
        # benchmark/compile_cache.py measures in isolation
        "compile_telemetry": {
            "first_dispatch_s": round(resnet_warmup_s, 3),
            "compile_phases_s": round(
                profiler.compile_stats().total_compile_seconds(), 3),
            "cache_counters": profiler.compile_stats().snapshot(),
        },
    }
    extra = []
    if tok_s is not None:
        extra.append({
            "metric": "seq2seq_attn_train_tokens_per_sec_per_chip",
            "value": round(tok_s, 1),
            "unit": "tokens/s",
            "vs_baseline": None,   # reference unpublished (BASELINE.md)
            "window_spread": round(tok_spread, 4),
        })
    if pipe_row is not None:
        extra.append({
            "metric": "input_pipeline_wide_deep_train_steps_per_sec",
            "value": pipe_row["pipelined_steps_per_s"],
            "unit": "steps/s",
            # vs the naive synchronous Trainer.train loop, same run
            "vs_baseline": pipe_row["speedup"],
            "window_spread": pipe_row["pipelined_spread"],
            # step-time budget + calibration from the extra doctored
            # pipelined pass (benchmark/input_pipeline.py _doctor_pass)
            "doctor": pipe_row.get("doctor"),
            "calibration": pipe_row.get("calibration"),
        })
    if extra:
        line["extra_metrics"] = extra
    if doctor_row is not None:
        line["doctor"] = doctor_row
    # full observability snapshot (step-time histograms, pipeline
    # queue-depth/stall numbers, compile counters, device memory where
    # the backend reports it) — BENCH_*.json gains these for free
    line["metrics_snapshot"] = profiler.metrics_snapshot()
    print(json.dumps(line))


def _input_pipeline_speedup():
    """End-to-end input-pipeline A/B on the wide_deep CTR ingestion
    workload (benchmark/input_pipeline.py): naive synchronous
    Trainer.train loop vs the pipelined run_pipelined path, median of
    paired alternating windows measured in THIS run."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmark.input_pipeline import WORKLOADS, run_workload

    WORKLOADS["wide_deep"]["full"]["reps"] = 4   # keep the driver fast
    return run_workload("wide_deep", quiet=True)  # ONE JSON line contract


def _seq2seq_tokens_per_sec(batch=64):
    """seq2seq+attention training tokens/s (benchmark/run.py seq2seq
    config; same pinned single-variant median-of-windows methodology as
    the headline metric)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import layers, models

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()

    vocab, dim, src_len, tgt_len = 30000, 512, 30, 30
    src = layers.data("src", shape=[], dtype="int64", lod_level=1)
    tgt = layers.data("tgt", shape=[], dtype="int64", lod_level=1)
    lbl = layers.data("lbl", shape=[], dtype="int64", lod_level=1)
    probs = models.seq2seq_attention(src, tgt, vocab, vocab, emb_dim=dim,
                                     hidden_dim=dim)
    flat = layers.reshape(probs, [-1, vocab])
    loss = layers.mean(layers.cross_entropy(
        flat, layers.reshape(lbl, [-1, 1])))
    pt.optimizer.Adam(1e-3).minimize(loss)

    rng = np.random.RandomState(0)
    feeds = {"src": rng.randint(0, vocab, (batch, src_len)),
             "src@LEN": np.full(batch, src_len),
             "tgt": rng.randint(0, vocab, (batch, tgt_len)),
             "tgt@LEN": np.full(batch, tgt_len),
             "lbl": rng.randint(0, vocab, (batch, tgt_len)),
             "lbl@LEN": np.full(batch, tgt_len)}
    feeds = {k: jax.device_put(v) for k, v in feeds.items()}

    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    prog = pt.default_main_program()
    return _median_window_throughput(
        exe, prog, feeds, loss,
        units_per_step=batch * (src_len + tgt_len), iters=150, reps=5)


def _mesh_main(mesh_str: str, simulate: bool):
    """Planner-proposed-specs smoke on a (possibly simulated) mesh."""
    import os

    from paddle_tpu.cli import _parse_mesh

    axes = _parse_mesh(mesh_str)
    n_devices = 1
    for s in axes.values():
        n_devices *= s
    if simulate:
        # must land before the backend initializes; conftest-style live
        # config update below covers an already-imported jax.  An
        # existing (possibly smaller) device-count flag is REPLACED with
        # the max of both — keeping a stale value would fail the run
        # with advice to pass the flag that was already passed
        import re
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
        count = max(n_devices, int(m.group(1)) if m else 0)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", flags)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}"
        ).strip()
    import jax
    if simulate:
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.parallel import ShardedExecutor, mesh_for_axes

    try:
        mesh = mesh_for_axes(axes)
    except RuntimeError as e:
        raise RuntimeError(f"{e} — or pass --simulate for the CPU path")

    # the smoke model: megatron-eligible widths (128-divisible) so a tp
    # axis actually exercises tensor splits, small enough for CPU
    batch = 64
    x = layers.data("x", shape=[256], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=512, act="relu")
    pred = layers.fc(h, size=128, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()

    rng = np.random.RandomState(0)
    feeds = {"x": rng.rand(batch, 256).astype("float32"),
             "label": rng.randint(0, 128, (batch, 1))}

    exe1 = pt.Executor()
    exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (ref,) = exe1.run(prog, feed=feeds, fetch_list=[loss])

    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=mesh, batch_axis=next(iter(axes)),
                          auto_shard=True, validate=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe._step = 0
    (sharded,) = exe.run(prog, feed=feeds, fetch_list=[loss])
    plan = exe.auto_plan
    rel_err = abs(float(sharded) - float(ref)) / max(1e-12, abs(float(ref)))

    on_chip = jax.default_backend() not in ("cpu",)
    line = {
        "metric": "multichip_planner_smoke",
        "mesh": mesh_str,
        "n_devices": n_devices,
        "simulated_cpu_mesh": not on_chip,
        "plan_candidate": plan.candidate,
        "planner_param_specs": {
            k: [list(e) if e else None for e in v]
            for k, v in sorted(plan.param_specs.items())},
        "planner_feeds_sharded": len(plan.feed_specs),
        "per_device_peak_hbm_mb": round(
            plan.cost.peak_hbm_bytes_per_device / 1e6, 3),
        "step_time_proxy_ms": round(plan.cost.step_time_proxy_s * 1e3, 4),
        "sharded_vs_unsharded_rel_err": rel_err,
        "ok": bool(rel_err < 2e-4),
        # the measured row is chip-only: CPU-simulated throughput says
        # nothing about ICI scaling, so it stays pending-hardware
        "scaling_efficiency": None if not on_chip else "MEASURE-ME",
        "note": ("planner-proposed-specs smoke on a simulated CPU mesh; "
                 "run `bench.py --mesh ... ` (no --simulate) first "
                 "session with a chip for the scaling-efficiency row"
                 if not on_chip else "on-chip run"),
    }
    print(json.dumps(line))
    if not line["ok"]:
        sys.exit(1)


if __name__ == "__main__":
    try:
        if "--mesh" in sys.argv:
            _mesh_main(sys.argv[sys.argv.index("--mesh") + 1],
                       simulate="--simulate" in sys.argv)
        else:
            main()
    except Exception as e:  # the driver records whatever line we print
        print(json.dumps({
            "metric": ("multichip_planner_smoke" if "--mesh" in sys.argv
                       else "resnet50_train_images_per_sec_per_chip"),
            "value": 0.0, "unit": "img/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(1)
