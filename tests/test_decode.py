"""Continuous-batching incremental decode tests (ISSUE 16 tentpole):

* the BIT-identity oracle — incremental cached decode through the
  compiled one-token program must equal a full recompute-per-token
  replay (reset slabs -> re-prefill -> re-decode the prefix) through the
  SAME compiled programs, bit-for-bit, under slot churn and relocation;
* the zero-retrace contract — steady-state serving with per-step
  admit/evict and mixed lengths compiles NOTHING after warmup
  (retrace_guard + CompileStats counter deltas);
* fault injection at ``serving.decode_step`` — transient retries leave
  the KV slabs clean (token-identical to an uninjected run), fatal
  fails the affected actives with typed errors, keeps queued requests
  alive, and feeds the circuit breaker;
* the Server front door (``add_decode_model``/``submit_decode``) and the
  benchmark gate (smoke arm in-process; full A/B @slow).
"""
import json
import os

import numpy as np
import pytest

from paddle_tpu.faults import InjectedFault, ModelUnavailable
from paddle_tpu.serving.decode import (DecodeEngine, DecodeRuntime,
                                       bucket_for_len)
from paddle_tpu.serving.server import ModelError

RESULTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmark", "decode_results.json")


def _engine(vocab=23, hidden=12, layers=2, slots=3, seed=5, name="t"):
    return DecodeEngine(vocab, hidden_dim=hidden, n_layers=layers,
                        slots=slots, max_len=16, len_buckets=(16,),
                        eos_id=None, seed=seed, name=name)


def _bits(a):
    return np.ascontiguousarray(np.asarray(a, np.float32)).view(np.uint32)


def _greedy(eng, slot, prompt, n_steps, cohab=None, churn_at=None,
            churn_prompt=None):
    """Drive the engine by hand: prefill ``prompt`` into ``slot``
    (plus optional co-resident prompts advanced in lockstep), greedy-
    decode ``n_steps``; optionally EVICT the first cohab slot at step
    ``churn_at`` and admit ``churn_prompt`` there mid-flight.  Returns
    (tokens, [n_steps+1, V] logit rows) for ``slot``."""
    S = eng.slots
    eng.reset()
    cur = np.zeros(S, np.int64)
    lens = np.zeros(S, np.int32)
    act = np.zeros(S, np.float32)
    tok, row = eng.prefill(slot, prompt)
    rows, toks = [row], [tok]
    cur[slot], lens[slot], act[slot] = tok, len(prompt), 1.0
    for s, p in (cohab or {}).items():
        t2, _ = eng.prefill(s, p)
        cur[s], lens[s], act[s] = t2, len(p), 1.0
    for k in range(n_steps):
        if churn_at is not None and k == churn_at:
            victim = next(iter(cohab))
            act[victim] = 0.0                      # evict mid-flight
            t3, _ = eng.prefill(victim, churn_prompt)
            cur[victim], lens[victim] = t3, len(churn_prompt)
            act[victim] = 1.0                      # admit into the hole
        logits = eng.decode_step(cur, lens, act)
        for s in range(S):
            if act[s]:
                nxt = int(np.asarray(logits[s, 0]).argmax())
                if s == slot:
                    rows.append(np.asarray(logits[s, 0], np.float32))
                    toks.append(nxt)
                cur[s] = nxt
                lens[s] += 1
    return toks, np.stack(rows)


def test_bucket_for_len():
    assert bucket_for_len(5, (32, 64)) == 32
    assert bucket_for_len(33, (32, 64)) == 64
    assert bucket_for_len(64, (32, 64)) == 64
    # overflow: one oversized engine beats a rejected workload
    assert bucket_for_len(65, (32, 64)) == 65


def test_incremental_decode_matches_recompute_oracle():
    """THE correctness pin: the incremental path reuses cache slabs
    across every step; the oracle rebuilds them from zero for each
    token (reset -> prefill -> replay the recorded prefix through the
    same compiled one-token program) and must land on bitwise-equal
    logits.  The incremental run additionally carries a co-resident
    sequence that is evicted and REPLACED mid-flight (slot churn), and
    the oracle replays in a DIFFERENT slot with different neighbors
    (relocation invariance) — per-row bits must not notice any of it."""
    eng = _engine(name="oracle")
    prompt, n = [3, 7, 1, 9], 6
    toks, rows = _greedy(eng, 0, prompt, n, cohab={1: [2, 5]},
                         churn_at=3, churn_prompt=[8, 8, 4])
    assert len(toks) == n + 1 and rows.shape == (n + 1, eng.vocab_size)
    # greedy chain really is the argmax chain
    assert toks == [int(r.argmax()) for r in rows]

    for t in range(n + 1):
        # full recompute of step t in another slot with another neighbor
        eng.reset()
        first, row = eng.prefill(2, prompt)
        eng.prefill(0, [6, 2, 2, 1, 5])
        assert first == toks[0]
        if t == 0:
            replay = row
        else:
            cur = np.zeros(eng.slots, np.int64)
            lens = np.zeros(eng.slots, np.int32)
            act = np.zeros(eng.slots, np.float32)
            lens[2], act[2] = len(prompt), 1.0
            for k in range(t):
                cur[2] = toks[k]
                logits = eng.decode_step(cur, lens, act)
                lens[2] += 1
            replay = np.asarray(logits[2, 0], np.float32)
        np.testing.assert_array_equal(
            _bits(replay), _bits(rows[t]),
            err_msg=f"recompute oracle diverged at token step {t}")


def test_decode_rows_independent_of_coresidents():
    """Same engine, same prompt: solo vs fully-packed pool produce
    bit-identical logit rows AND tokens (the property that makes
    continuous batching invisible to the math)."""
    eng = _engine(name="indep")
    prompt = [4, 11, 2]
    toks_solo, rows_solo = _greedy(eng, 1, prompt, 5)
    toks_full, rows_full = _greedy(eng, 1, prompt, 5,
                                   cohab={0: [9, 1], 2: [6, 6, 6, 3]})
    assert toks_solo == toks_full
    np.testing.assert_array_equal(_bits(rows_solo), _bits(rows_full))


def test_steady_state_decode_zero_retrace():
    """After warmup the pool serves mixed prompt lengths, mixed
    generation lengths, and per-step admit/evict churn through EXACTLY
    two compiled programs: no new trace, no new cache entry."""
    from paddle_tpu.core import compile_cache

    eng = _engine(vocab=13, hidden=8, layers=1, slots=2, name="zrt")
    rt = DecodeRuntime(eng, step_wait_ms=0.5, default_deadline_ms=None)
    rt.start(warmup=True)
    try:
        c0 = dict(compile_cache.stats().counters)
        with compile_cache.retrace_guard():
            reqs = [rt.submit([1 + (i % 7), 2, 3][: 1 + (i % 3)],
                              1 + (i % 5)) for i in range(9)]
            outs = [r.result(timeout=120.0) for r in reqs]
        c1 = dict(compile_cache.stats().counters)
    finally:
        rt.shutdown(drain=True, timeout=60.0)
    for i, o in enumerate(outs):
        assert len(o["tokens"]) == 1 + (i % 5)
        assert o["finish"] == "length"
    assert c1.get("traces", 0) == c0.get("traces", 0)
    assert c1.get("misses", 0) == c0.get("misses", 0)


def test_decode_step_transient_fault_is_invisible():
    """A transient injected INSIDE the retry rim (before the executor
    call: slabs untouched) retries per the pool's policy and the run's
    tokens stay identical to an uninjected run."""
    from paddle_tpu.testing import faultinject as fi

    eng = _engine(vocab=19, hidden=8, layers=1, slots=2, name="fit")
    rt = DecodeRuntime(eng, step_wait_ms=0.5, default_deadline_ms=None)
    rt.start(warmup=True)
    trace = [([2, 9], 4), ([5, 1, 7], 3), ([8], 5)]
    try:
        base = [r.result(timeout=60.0)["tokens"]
                for r in [rt.submit(p, m) for p, m in trace]]
        fi.configure("serving.decode_step@2=transient")
        inj = [r.result(timeout=60.0)["tokens"]
               for r in [rt.submit(p, m) for p, m in trace]]
        assert fi.fired("serving.decode_step") == 1
        assert inj == base
        assert rt.breaker_state() == "closed"
    finally:
        fi.clear()
        rt.shutdown(drain=True, timeout=60.0)


def test_decode_step_fatal_fault_breaker_and_recovery():
    """A fatal at the decode step fails the ACTIVE sequence with a typed
    error, leaves the queued request alive, opens the breaker at its
    threshold (admission refused with ModelUnavailable), and the
    cooldown probe recovers — all on one pool."""
    import time

    from paddle_tpu.testing import faultinject as fi

    eng = _engine(vocab=19, hidden=8, layers=1, slots=1, name="fif")
    rt = DecodeRuntime(eng, step_wait_ms=0.5, default_deadline_ms=None,
                       breaker_threshold=1, breaker_cooldown_s=0.3)
    rt.start(warmup=True)
    try:
        fi.configure("serving.decode_step@1=fatal")
        r1 = rt.submit([2, 9], 4)          # admitted into the only slot
        r2 = rt.submit([5, 1, 7], 3)       # queued behind it
        with pytest.raises(ModelError):
            r1.result(timeout=60.0)
        assert fi.fired("serving.decode_step") == 1
        fi.clear()
        # breaker open: admission rejects new work with the typed error
        assert rt.breaker_state() == "open"
        with pytest.raises(ModelUnavailable):
            rt.submit([3, 3], 2)
        # the queued request survives the incident: after cooldown the
        # probe admits it and it completes normally
        out = r2.result(timeout=60.0)
        assert len(out["tokens"]) == 3 and out["finish"] == "length"
        deadline = time.monotonic() + 5.0
        while rt.breaker_state() != "closed" \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rt.breaker_state() == "closed"
        assert len(rt.submit([4], 2).result(timeout=60.0)["tokens"]) == 2
    finally:
        fi.clear()
        rt.shutdown(drain=True, timeout=60.0)
    assert isinstance(r1.error, (ModelError, InjectedFault))


def test_server_decode_front_door():
    """add_decode_model / submit_decode: the Server owns the pool's
    lifecycle, surfaces its health, and rejects after shutdown."""
    from paddle_tpu.serving import Server

    eng = _engine(vocab=19, hidden=8, layers=1, slots=2, name="srv")
    srv = Server(deadline_ms=None)
    srv.add_decode_model(eng, name="gen")
    srv.start()
    try:
        outs = [srv.submit_decode([2, 9, 4], 3, model="gen")
                .result(timeout=60.0) for _ in range(3)]
        assert all(o["tokens"] == outs[0]["tokens"] for o in outs)
        h = srv.health()
        assert h["decode"]["gen"]["served"] >= 3
        assert h["decode"]["gen"]["mode"] == "continuous"
    finally:
        srv.shutdown(drain=True)
    from paddle_tpu.faults import ServerClosed
    with pytest.raises(ServerClosed):
        srv.submit_decode([1], 1, model="gen")


def test_decode_bench_smoke_row_complete():
    from benchmark.decode import run_all

    row = run_all(smoke=True, quiet=True)
    assert row["smoke"] is True
    ab = row["ab"]
    assert len(ab["pair_ratios"]) >= 2
    assert len(ab["default_windows"]) == len(ab["candidate_windows"])
    assert ab["accepted"] in (True, False)
    if not ab["accepted"]:
        assert ab["refusal_reason"]
    for arm in ("static", "continuous"):
        r = row[arm]
        assert r["mode"] == arm
        assert r["decode_tokens_per_s"] > 0
        assert r["ttft_ms"]["p99"] >= r["ttft_ms"]["p50"]
        assert r["inter_token_ms"]["p99"] >= r["inter_token_ms"]["p50"]
        assert 0 < r["slot_occupancy"] <= 1
    # the schedulers must be invisible to the math
    assert row["arms_tokens_identical"] is True
    doc = row["doctor"]
    assert doc and "error" not in doc, doc
    assert doc["steps"] > 0 and doc["top"] in ("dispatch", "scheduler")


def test_committed_decode_results_structure():
    """The committed JSON carries real CPU rows (accepted at the 1.3x
    bar or an explicit refusal WITH raw windows) + the pending-hardware
    TPU stub wired to the pre-registered paged-gather decision rule."""
    with open(RESULTS) as fh:
        data = json.load(fh)
    assert data["benchmark"] == "decode_continuous_batching"
    cpu = data["cpu"]
    ab = cpu["ab"]
    assert ab["min_speedup"] == 1.3
    assert ab["accepted"] or ab["refusal_reason"]
    assert ab["default_windows"] and ab["candidate_windows"]
    assert cpu["arms_tokens_identical"] is True
    assert cpu["continuous"]["decode_tokens_per_s"] > 0
    assert cpu["static"]["decode_tokens_per_s"] > 0
    assert cpu["doctor"]["steps"] > 0
    assert data["tpu"]["status"] == "pending-hardware"
    pg = data["tpu"]["paged_kv_gather"]
    assert pg["tunable"] == "pallas/paged_kv_gather"
    assert pg["status"] == "pending_hardware"
    assert "1.15x" in pg["decision_rule"]


@pytest.mark.slow
def test_decode_full_ab_runs():
    from benchmark.decode import run_all

    row = run_all(smoke=False, quiet=True)
    assert row["arms_tokens_identical"] is True
    assert row["doctor"]["steps"] > 0
