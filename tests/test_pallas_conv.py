"""Pallas 1x1-conv kernel tests (interpret mode on CPU; the same kernels
compile for the MXU on TPU).  Covers the generic blocked matmul with its
custom VJP, the conv wrapper (stride 1 and 2), the fused BN-stats /
bias-grad epilogues, eligibility gating, and the end-to-end Executor
routing behind the opt-in switch."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.ops.pallas_conv import (conv1x1_eligible, conv2d_1x1,
                                        conv2d_1x1_grad_fused,
                                        conv2d_1x1_with_bn_stats,
                                        pallas_matmul)

R = np.random.RandomState(7)
DN = ("NCHW", "OIHW", "NCHW")


def _xla_conv(x, w, strides=(1, 1)):
    return lax.conv_general_dilated(
        x, w, strides, [(0, 0), (0, 0)], dimension_numbers=DN)


@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_pallas_matmul_transposes(ta, tb):
    M, K, N = 256, 384, 128
    a = R.randn(M, K).astype("float32")
    b = R.randn(K, N).astype("float32")
    ref = a @ b
    aa = jnp.asarray(a.T if ta else a)
    bb = jnp.asarray(b.T if tb else b)
    out = pallas_matmul(aa, bb, ta, tb, 128, 128, 128, True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_pallas_matmul_vjp_matches_xla():
    M, K, N = 256, 256, 128
    a = jnp.asarray(R.randn(M, K).astype("float32"))
    bt = jnp.asarray(R.randn(N, K).astype("float32"))   # stored transposed

    def f(a, b):
        return jnp.sum(pallas_matmul(a, b, False, True, 128, 128, 128,
                                     True) ** 2)

    def f_ref(a, b):
        return jnp.sum((a @ b.T) ** 2)

    ga, gb = jax.grad(f, (0, 1))(a, bt)
    gar, gbr = jax.grad(f_ref, (0, 1))(a, bt)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gar),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gbr),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("stride", [1, 2])
def test_conv2d_1x1_forward_and_grads(stride):
    x = jnp.asarray(R.randn(2, 128, 16, 16).astype("float32"))
    w = jnp.asarray(R.randn(256, 128, 1, 1).astype("float32"))
    s = (stride, stride)
    ref = _xla_conv(x, w, s)
    out = conv2d_1x1(x, w, s, 128, 128, 128, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    g = jnp.asarray(R.randn(*ref.shape).astype("float32"))
    dxr, dwr = jax.grad(
        lambda x, w: jnp.sum(_xla_conv(x, w, s) * g), (0, 1))(x, w)
    # autodiff through the wrapper (the executor's append_backward path)
    dxa, dwa = jax.grad(
        lambda x, w: jnp.sum(conv2d_1x1(x, w, s, 128, 128, 128, True) * g),
        (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dxa), np.asarray(dxr),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dwa), np.asarray(dwr),
                               rtol=1e-3, atol=1e-2)
    # the explicit fused-gradient entry point (benchmark path)
    dx, dw, dsum = conv2d_1x1_grad_fused(x, w, g, s, 128, 128, 128, True)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dxr),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dwr),
                               rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(np.asarray(dsum),
                               np.asarray(g).sum((0, 2, 3)),
                               rtol=1e-3, atol=1e-3)


def test_conv2d_1x1_bn_stats_epilogue():
    x = jnp.asarray(R.randn(2, 128, 16, 16).astype("float32"))
    w = jnp.asarray(R.randn(128, 128, 1, 1).astype("float32"))
    ref = np.asarray(_xla_conv(x, w))
    out, csum, csq = conv2d_1x1_with_bn_stats(x, w, (1, 1), 128, 128, 128,
                                              True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(csum), ref.sum((0, 2, 3)),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(csq), (ref ** 2).sum((0, 2, 3)),
                               rtol=1e-3, atol=1e-2)


def test_eligibility_gate():
    ok = dict(strides=(1, 1), pads=(0, 0), dils=(1, 1), groups=1)
    assert conv1x1_eligible((128, 256, 14, 14), (512, 256, 1, 1), **ok)
    # 3x3 filter / groups / padding / dilation all fall back
    assert not conv1x1_eligible((128, 256, 14, 14), (512, 256, 3, 3), **ok)
    assert not conv1x1_eligible((128, 256, 14, 14), (512, 256, 1, 1),
                                strides=(1, 1), pads=(0, 0), dils=(1, 1),
                                groups=2)
    assert not conv1x1_eligible((128, 256, 14, 14), (512, 256, 1, 1),
                                strides=(1, 1), pads=(1, 1), dils=(1, 1),
                                groups=1)
    # non-128-divisible channels (ResNet stage-1 64-ch blocks) fall back
    assert not conv1x1_eligible((128, 64, 56, 56), (64, 64, 1, 1), **ok)
    # pixel count must tile too
    assert not conv1x1_eligible((2, 128, 4, 4), (128, 128, 1, 1), **ok)


def _bn_conv_program(use_pallas):
    img = layers.data("img", shape=[128, 8, 8], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.conv2d(img, 128, 1, bias_attr=False,
                      param_attr=pt.ParamAttr(name="cw"),
                      use_pallas=use_pallas)
    h = layers.batch_norm(h)
    h = layers.pool2d(h, pool_size=8, pool_type="avg")
    pred = layers.fc(h, size=10, act="softmax",
                     param_attr=pt.ParamAttr(name="fw"))
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_executor_routing_end_to_end(rng, monkeypatch):
    """Same program trained 3 steps through XLA's conv emitter and through
    the Pallas route (interpret mode): losses must track, proving the
    opt-in switch routes the forward AND the autodiff gradients.  A
    counting wrapper on ``conv2d_1x1`` proves the route was actually
    taken — nn_ops has four silent fall-through gates, and without the
    probe a routing regression would make this test pass vacuously
    (both runs on XLA, trivially equal losses)."""
    feeds = {"img": rng.rand(4, 128, 8, 8).astype("float32") * 0.1,
             "label": rng.randint(0, 10, (4, 1))}

    loss = _bn_conv_program(use_pallas=None)
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    base = [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
            for _ in range(3)]

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    loss = _bn_conv_program(use_pallas=True)
    prog = pt.default_main_program()
    for op in prog.global_block().ops:
        if op.type == "conv2d":
            op.attrs["pallas_interpret"] = True   # CPU test: interpret mode

    from paddle_tpu.ops import pallas_conv
    calls = []
    real = pallas_conv.conv2d_1x1
    monkeypatch.setattr(
        pallas_conv, "conv2d_1x1",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])
    exe = pt.Executor(conv1x1_pallas=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    pallas = [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
              for _ in range(3)]
    assert calls, "conv2d never routed to the Pallas kernel (silent " \
                  "fall-through in nn_ops._conv2d)"
    np.testing.assert_allclose(base, pallas, rtol=2e-4, atol=2e-5)


def test_executor_flag_off_is_default_path(rng):
    """conv1x1_pallas defaults OFF: without the opt-in nothing routes to
    Pallas (the attr-free program must not consult the kernel at all on a
    CPU backend — no interpret attr set, would raise if routed)."""
    feeds = {"img": rng.rand(4, 128, 8, 8).astype("float32") * 0.1,
             "label": rng.randint(0, 10, (4, 1))}
    loss = _bn_conv_program(use_pallas=None)
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    v = float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
    assert np.isfinite(v)
