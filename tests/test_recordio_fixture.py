"""Hermetic record-format contract test for ``reader.creator.recordio``:
golden part files are COMMITTED under tests/fixtures/recordio (pickle
protocol 2, generated once), so the chunked-record format the whole
cloud-reading stack shares — ``dataset.common.split`` writes it,
``recordio``/``cloud_reader``/``cluster_files_reader`` read it — is
pinned by bytes on disk, with no network and no generated-then-read
self-consistency blind spot."""
import glob
import hashlib
import os
import pickle

import paddle_tpu.reader.creator as creator
from paddle_tpu.dataset import common

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "recordio")

# the records the committed bytes MUST decode to (format contract)
GOLDEN = [
    (0, [1.0, 2.0, 3.0], "alpha"),
    (1, [4.0, 5.0, 6.0], "beta"),
    (2, [7.0, 8.0, 9.0], "gamma"),
    (3, [0.5, 1.5, 2.5], "delta"),
    (4, [3.5, 4.5, 5.5], "epsilon"),
]
SHA256 = {
    "part-00000.pickle":
        "c43ec8f83c9eb052cccfee115446661aa8f247a825590d5571b3063f45c2f9d6",
    "part-00001.pickle":
        "e25a3cbdc84d1269762965f79666bb658d31c44e6bf80115fb5fbb6bf5e68a89",
}


def test_fixture_bytes_unchanged():
    """The committed bytes themselves are the contract: a pickle-protocol
    or writer change that silently rewrites the format shows up here."""
    for name, want in SHA256.items():
        with open(os.path.join(FIXDIR, name), "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == want, name


def test_recordio_reads_golden_fixture():
    r = creator.recordio(os.path.join(FIXDIR, "part-*.pickle"))
    assert list(r()) == GOLDEN


def test_recordio_unbuffered_and_list_paths():
    paths = sorted(glob.glob(os.path.join(FIXDIR, "part-*.pickle")))
    r = creator.recordio(paths, buf_size=0)      # no prefetch thread
    assert list(r()) == GOLDEN
    # re-iterable: creators return fresh generators per call
    assert list(r()) == GOLDEN


def test_split_writes_the_same_format(tmp_path):
    """dataset.common.split output is byte-compatible with what recordio
    reads — the full write->read round trip of the shared format."""
    suffix = str(tmp_path / "rt-%05d.pickle")
    common.split(lambda: iter(GOLDEN), line_count=2, suffix=suffix)
    files = sorted(glob.glob(str(tmp_path / "rt-*.pickle")))
    assert len(files) == 3                        # 2+2+1 records
    assert list(creator.recordio(files, buf_size=0)()) == GOLDEN
    # each part is ONE pickled list (the _read_part contract)
    with open(files[0], "rb") as f:
        assert pickle.load(f) == GOLDEN[:2]
