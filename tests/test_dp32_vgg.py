"""32-way data-parallel VGG training on a 32-virtual-device CPU mesh
(BASELINE.json configs: 'VGG-16 distributed data-parallel (pserver →
ICI allreduce, 32 chips)').

Runs in a subprocess because the virtual device count is fixed at jax
init (the main test process pins 8).  Asserts the dp=32 run tracks a
single-device run on the same data — the pserver-parity guarantee,
delivered by GSPMD all-reduce instead of a parameter server."""
import json
import os
import subprocess
import sys

import pytest

# @slow (ISSUE 12 tier-1 budget audit): a ~12s fresh-interpreter round
# (32-virtual-device jax init + VGG compile); the sharded-vs-unsharded
# parity guarantee is tier-1-covered in-process by test_planner's
# 8-device mesh execution-parity subset.  Run with `-m slow`.
pytestmark = pytest.mark.slow

_WORKER = r'''
import json, os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh

def build():
    pt.core.reset_default_programs(); pt.core.reset_global_scope()
    pt.unique_name.reset()
    img = layers.data("img", shape=[3, 16, 16], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    # vgg-shaped: conv groups then fc head (tiny dims for CI)
    x = img
    for ch in (8, 16):
        x = layers.conv2d(x, num_filters=ch, filter_size=3, act="relu",
                          padding=1)
        x = layers.pool2d(x, pool_size=2, pool_type="max")
    pred = layers.fc(layers.fc(x, size=32, act="relu"), size=10,
                     act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    return loss

rng = np.random.RandomState(0)
feeds = {"img": rng.rand(64, 3, 16, 16).astype("float32"),
         "label": rng.randint(0, 10, (64, 1))}

loss = build()
exe1 = pt.Executor()
exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
single = [float(exe1.run(feed=feeds, fetch_list=[loss])[0])
          for _ in range(4)]

loss = build()
assert len(jax.devices()) == 32, jax.devices()
mesh = make_mesh(MeshConfig(dp=32))
exe = ShardedExecutor(mesh=mesh)
exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
exe.place_state(pt.default_main_program())
exe._step = 0
dp = [float(exe.run(pt.default_main_program(), feed=feeds,
                    fetch_list=[loss])[0]) for _ in range(4)]
# one run_steps window over the 32-way mesh too
(stacked,) = exe.run_steps(3, feed=feeds, fetch_list=[loss])
print("RESULT " + json.dumps({"single": single, "dp32": dp,
                              "scan": [float(x) for x in
                                       np.asarray(stacked).reshape(-1)]}))
'''


def test_vgg_dp32_matches_single_device(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, timeout=600, cwd=repo)
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    line = [ln for ln in out.stdout.decode().splitlines()
            if ln.startswith("RESULT ")]
    assert line, out.stdout.decode()
    r = json.loads(line[-1][len("RESULT "):])
    import numpy as np
    np.testing.assert_allclose(r["dp32"], r["single"], rtol=2e-2,
                               atol=1e-4)
    assert r["scan"][-1] < r["single"][0]      # keeps training under scan
