"""Sequence parallelism as a FIRST-CLASS framework feature: a Paddle-API
user writes ``layers.flash_attention`` / ``nets.scaled_dot_product_attention``
and, under a ShardedExecutor whose mesh has sp>1, the attention lowering
routes through ``parallel.ring_attention`` inside a partial-manual shard_map
over the sp axis (ops/pallas_kernels.py _flash_attention_op) — no raw
shard_map in user code.  Equivalence strategy matches the pipeline/MoE
first-class tests (test_pipeline_program.py): the sharded run must track
the plain single-device Executor numerically."""
import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, nets
from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh

T, D = 16, 8


def _attn_model(rng, batch=4, causal=True, via_nets=False,
                sequence_parallel=True, interpret=False):
    x = layers.data("x", shape=[D], dtype="float32", lod_level=1)
    y = layers.data("y", shape=[D], dtype="float32", lod_level=1)
    q = layers.fc(x, size=D, num_flatten_dims=2)
    k = layers.fc(x, size=D, num_flatten_dims=2)
    v = layers.fc(x, size=D, num_flatten_dims=2)
    if via_nets:
        att = nets.scaled_dot_product_attention(
            q, k, v, sequence_parallel=sequence_parallel)
    else:
        att = layers.flash_attention(q, k, v, causal=causal,
                                     sequence_parallel=sequence_parallel,
                                     interpret=interpret)
    out = layers.fc(att, size=D, num_flatten_dims=2)
    loss = layers.mean(layers.square_error_cost(out, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    lens = np.full(batch, T, dtype="int64")
    feeds = {"x": rng.randn(batch, T, D).astype("float32"), "x@LEN": lens,
             "y": rng.randn(batch, T, D).astype("float32"), "y@LEN": lens}
    return loss, feeds


def _train(exe, prog, feeds, loss, steps=3, place_state=False):
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    if place_state:
        exe.place_state(prog)
    exe._step = 0
    return [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
            for _ in range(steps)]


@pytest.mark.parametrize("mesh_cfg,causal,via_nets", [
    (MeshConfig(sp=4), True, False),          # pure sp ring, causal
    (MeshConfig(sp=4), False, False),         # non-causal ring
    (MeshConfig(dp=2, sp=4), True, False),    # dp x sp composition
    (MeshConfig(sp=4), False, True),          # the nets.* entry point
])
def test_sp_attention_training_matches_single_device(rng, mesh_cfg, causal,
                                                     via_nets):
    """An attention model trained through ShardedExecutor over sp (and
    dp x sp) must track the plain single-device Executor, which runs the
    same program with the device-global kernel."""
    loss, feeds = _attn_model(rng, causal=causal, via_nets=via_nets)
    prog = pt.default_main_program()

    single = _train(pt.Executor(), prog, feeds, loss)

    pt.core.reset_global_scope()
    mesh = make_mesh(mesh_cfg, devices=jax.devices()[:mesh_cfg.size])
    exe = ShardedExecutor(mesh=mesh)
    multi = _train(exe, prog, feeds, loss)

    assert single[-1] < single[0]          # it actually trains
    np.testing.assert_allclose(single, multi, rtol=2e-4, atol=1e-5)


def test_tp_x_sp_composition_matches(rng):
    """Megatron column/row-sharded projections (tp) composed with ring
    attention (sp) in ONE program: the partial-manual shard_map is over
    sp only, so the tp axis stays GSPMD-managed straight through the
    attention — trained losses match single-device."""
    x = layers.data("x", shape=[D], dtype="float32", lod_level=1)
    y = layers.data("y", shape=[D], dtype="float32", lod_level=1)
    q = layers.fc(x, size=D, num_flatten_dims=2,
                  param_attr=pt.ParamAttr(name="wq", sharding=(None, "tp")))
    k = layers.fc(x, size=D, num_flatten_dims=2,
                  param_attr=pt.ParamAttr(name="wk", sharding=(None, "tp")))
    v = layers.fc(x, size=D, num_flatten_dims=2,
                  param_attr=pt.ParamAttr(name="wv", sharding=(None, "tp")))
    att = layers.flash_attention(q, k, v, causal=True)
    out = layers.fc(att, size=D, num_flatten_dims=2,
                    param_attr=pt.ParamAttr(name="wo", sharding=("tp", None)))
    loss = layers.mean(layers.square_error_cost(out, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    lens = np.full(4, T, dtype="int64")
    feeds = {"x": rng.randn(4, T, D).astype("float32"), "x@LEN": lens,
             "y": rng.randn(4, T, D).astype("float32"), "y@LEN": lens}

    single = _train(pt.Executor(), prog, feeds, loss)
    pt.core.reset_global_scope()
    mesh = make_mesh(MeshConfig(tp=2, sp=4), devices=jax.devices()[:8])
    multi = _train(ShardedExecutor(mesh=mesh), prog, feeds, loss,
                   place_state=True)
    assert single[-1] < single[0]
    np.testing.assert_allclose(single, multi, rtol=2e-4, atol=1e-5)
    # the projection weights really are tp-distributed
    assert not pt.global_scope().get("wq").sharding.is_fully_replicated


def test_sp_flash_kernel_path_matches(rng):
    """interpret=True drives the EXACT fused-kernel ring variant (flash
    fwd/bwd + lse merges across ppermute hops) through the first-class
    lowering on the CPU mesh — the code path real multi-chip TPU runs
    take."""
    loss, feeds = _attn_model(rng, causal=True, interpret=True)
    prog = pt.default_main_program()
    single = _train(pt.Executor(), prog, feeds, loss)
    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(sp=4),
                                         devices=jax.devices()[:4]))
    multi = _train(exe, prog, feeds, loss)
    assert single[-1] < single[0]
    np.testing.assert_allclose(single, multi, rtol=2e-3, atol=1e-4)


def test_sp_opt_out_still_matches(rng):
    """sequence_parallel=False keeps the device-global GSPMD kernel under
    an sp mesh — the opt-out path stays numerically correct too."""
    loss, feeds = _attn_model(rng, sequence_parallel=False)
    prog = pt.default_main_program()
    single = _train(pt.Executor(), prog, feeds, loss)
    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(sp=4),
                                         devices=jax.devices()[:4]))
    multi = _train(exe, prog, feeds, loss)
    np.testing.assert_allclose(single, multi, rtol=2e-4, atol=1e-5)


def test_sp_inside_pipeline_stage_falls_back(rng):
    """flash_attention inside a pipeline_stage body on a pp x sp mesh must
    fall back to the device-global kernel (entering a second shard_map from
    the pp-manual region is illegal) and still match single-device."""
    x = layers.data("x", shape=[T, D], dtype="float32")
    y = layers.data("y", shape=[T, D], dtype="float32")
    with pt.pipeline_stage(0):
        h = layers.fc(x, size=D, num_flatten_dims=2, act="tanh")
    with pt.pipeline_stage(1):
        att = layers.flash_attention(h, h, h, causal=True)
    loss = layers.mean(layers.square_error_cost(att, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    feeds = {"x": rng.randn(4, T, D).astype("float32"),
             "y": rng.randn(4, T, D).astype("float32")}

    single = _train(pt.Executor(), prog, feeds, loss)
    pt.core.reset_global_scope()
    mesh = make_mesh(MeshConfig(pp=2, sp=4),
                     devices=jax.devices()[:8])
    multi = _train(ShardedExecutor(mesh=mesh), prog, feeds, loss)
    np.testing.assert_allclose(single, multi, rtol=2e-4, atol=1e-5)


def test_sp_ineligible_shape_falls_back(rng):
    """T not divisible by sp: the lowering statically falls back to the
    whole-array kernel instead of erroring."""
    x = layers.data("x", shape=[D], dtype="float32", lod_level=1)
    q = layers.fc(x, size=D, num_flatten_dims=2)
    att = layers.flash_attention(q, q, q)
    loss = layers.mean(att)
    prog = pt.default_main_program()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(sp=4),
                                         devices=jax.devices()[:4]))
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {"x": rng.randn(4, 10, D).astype("float32"),
             "x@LEN": np.full(4, 10, dtype="int64")}
    (lv,) = exe.run(prog, feed=feeds, fetch_list=[loss])
    assert np.isfinite(float(lv))


def test_sp_run_steps_compiled_loop(rng):
    """The compiled K-step training loop (run_steps — the pinned benchmark
    methodology) composes with first-class sp: one sharded lax.scan
    dispatch over an sp=4 mesh matches K sequential single-device steps."""
    loss, feeds = _attn_model(rng)
    prog = pt.default_main_program()

    exe_ref = pt.Executor()
    exe_ref.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe_ref._step = 0
    ref = [float(exe_ref.run(prog, feed=feeds, fetch_list=[loss])[0])
           for _ in range(4)]

    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(sp=4),
                                         devices=jax.devices()[:4]))
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe._step = 0
    (lvs,) = exe.run_steps(4, prog, feed=feeds, fetch_list=[loss])
    np.testing.assert_allclose(np.asarray(lvs).ravel(), ref, rtol=2e-4,
                               atol=1e-5)
