"""CTR sparse-parameter-server benchmark gate: the --smoke arm runs the
REAL code path in-process (tier-1, seconds); the full A/B is @slow per
the frozen fast-allowlist convention (it is also what commits
benchmark/ctr_results.json)."""
import json
import os

import numpy as np
import pytest

from benchmark.ctr import (HBM_EMBEDDING_BUDGET_MB, SMOKE, run_all,
                           _zipf_ids)

RESULTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmark", "ctr_results.json")


def test_zipf_ids_in_range_and_head_heavy():
    rng = np.random.RandomState(0)
    ids = _zipf_ids(rng, 1.2, 1000, 10_000)
    assert ids.min() >= 0 and ids.max() < 1000
    # zipf head: the most frequent id dwarfs the median frequency
    _, counts = np.unique(ids, return_counts=True)
    assert counts.max() > 10 * np.median(counts)


def test_ctr_smoke_row_complete():
    row = run_all(smoke=True, quiet=True)
    assert row["smoke"] is True
    cfg = row["config"]
    # the smoke config shrinks everything EXCEPT the claim structure
    assert set(SMOKE) <= set(cfg)
    assert cfg["hbm_embedding_budget_mb"] == HBM_EMBEDDING_BUDGET_MB
    sp = row["sparse"]
    assert sp["examples_per_sec"] > 0
    assert sp["lookup_latency_ms"]["p99"] >= sp["lookup_latency_ms"]["p50"]
    assert sp["push_rows_per_sec"] > 0
    assert sp["pushed_rows"] > 0
    assert all(v > 0 for v in sp["live_rows"].values())
    assert row["dense_control"]["examples_per_sec"] > 0
    assert row["sparse_vs_dense_speedup"] is not None
    cache = row["cache"]
    assert 0 <= cache["hit_rate"] <= 1
    assert cache["hits"] + cache["misses"] > 0
    doc = row["doctor"]
    assert doc and "error" not in doc, doc
    assert doc["within_tolerance"] is True
    # ISSUE 15: the scalar-vs-vectorized paired A/B rides the row with
    # raw windows (evidence committed whether accepted or refused) and
    # the steady arms must finish BYTE-identical — the A/B compares the
    # same training run, not two different ones
    ab = row["vectorization_ab"]
    for arm in ("steady", "cold_init", "overlap"):
        r = ab[arm]
        assert len(r["pair_ratios"]) >= 2
        assert len(r["default_windows"]) == len(r["candidate_windows"])
        assert r["accepted"] in (True, False)
        if not r["accepted"]:
            assert r["refusal_reason"]
    assert ab["steady"]["arms_bit_identical"] is True
    assert ab["steady"]["min_speedup"] == 1.5    # the acceptance bar


def test_committed_results_structure():
    """The committed JSON carries real CPU rows + the pending-hardware
    TPU stub (PR 1 convention) + the preserved round-4 legacy study,
    and its config's dense table genuinely exceeds the declared HBM
    embedding budget (the giant-embedding premise)."""
    with open(RESULTS) as fh:
        data = json.load(fh)
    assert data["benchmark"] == "ctr_sparse_parameter_server"
    cpu = data["cpu"]
    assert cpu["config"]["dense_exceeds_budget"] is True
    assert cpu["config"]["dense_tables_mb"] > \
        cpu["config"]["hbm_embedding_budget_mb"]
    assert cpu["sparse"]["examples_per_sec"] > 0
    assert cpu["dense_control"]["examples_per_sec"] > 0
    assert cpu["cache"]["hit_rate"] > 0
    assert cpu["doctor"]["within_tolerance"] is True
    assert data["tpu"]["status"] == "pending-hardware"
    assert "legacy_r04_dense_optimizer_sweep" in data
    # round-15 acceptance: the committed steady A/B either clears the
    # 1.5x bar or records an explicit noise-gate refusal WITH raw
    # windows; the committed doctor budget must reconcile
    ab = data["cpu"]["vectorization_ab"]
    steady = ab["steady"]
    assert steady["accepted"] or steady["refusal_reason"]
    assert steady["default_windows"] and steady["candidate_windows"]
    assert steady["arms_bit_identical"] is True
    assert ab["cold_init"]["pair_ratios"]
    assert data["cpu"]["doctor"]["budget_gap_frac"] <= 0.15


@pytest.mark.slow
def test_ctr_full_ab_runs():
    row = run_all(smoke=False, quiet=True)
    assert row["doctor"].get("within_tolerance") is True
    assert row["config"]["dense_exceeds_budget"] is True
