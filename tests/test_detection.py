"""Detection end-to-end tests: real roi_pool Argmax, ssd_loss
(MultiBoxLoss.cpp analog) matching/mining semantics + SSD training, and the
DetectionMAP evaluator (DetectionMAPEvaluator.cpp analog) against
hand-computed AP."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.evaluator import DetectionMAP


def test_roi_pool_argmax_is_real(rng):
    """Argmax carries the flat h*W+w index of each bin's max (roi_pool_op.h
    argmax semantics), verified against a numpy loop."""
    N, C, H, W = 1, 2, 8, 8
    xv = rng.rand(N, C, H, W).astype("float32")
    roisv = np.array([[0, 0, 0, 7, 7],
                      [0, 2, 2, 5, 5]], dtype="float32")
    x = layers.data("x", shape=[C, H, W], dtype="float32")
    rois = layers.data("rois", shape=[5], dtype="float32")
    helper = pt.layer_helper.LayerHelper("roi_pool")
    out_v = helper.create_variable_for_type_inference("float32")
    argmax_v = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="roi_pool", inputs={"X": [x], "ROIs": [rois]},
                     outputs={"Out": [out_v], "Argmax": [argmax_v]},
                     attrs={"pooled_height": 2, "pooled_width": 2,
                            "spatial_scale": 1.0})
    exe = pt.Executor()
    out, amax = exe.run(pt.default_main_program(),
                        feed={"x": xv, "rois": roisv},
                        fetch_list=[out_v, argmax_v])
    assert amax.shape == out.shape
    assert np.issubdtype(amax.dtype, np.integer)
    flat = xv[0].reshape(C, -1)
    for r in range(out.shape[0]):
        for c in range(C):
            for i in range(2):
                for j in range(2):
                    idx = int(amax[r, c, i, j])
                    assert idx >= 0
                    np.testing.assert_allclose(flat[c, idx], out[r, c, i, j],
                                               rtol=1e-6)


def _run_ssd_loss(rng, loc, conf, gtb, gtl, prior, **attrs):
    locv = layers.data("loc", shape=list(loc.shape[1:]), dtype="float32")
    confv = layers.data("conf", shape=list(conf.shape[1:]), dtype="float32")
    gtbv = layers.data("gtb", shape=list(gtb.shape[1:]), dtype="float32")
    gtlv = layers.data("gtl", shape=list(gtl.shape[1:]), dtype="int64")
    priorv = layers.data("prior", shape=list(prior.shape), dtype="float32",
                         append_batch_size=False)
    loss = layers.ssd_loss(locv, confv, gtbv, gtlv, priorv, **attrs)
    exe = pt.Executor()
    out, = exe.run(pt.default_main_program(),
                   feed={"loc": loc, "conf": conf, "gtb": gtb, "gtl": gtl,
                         "prior": prior}, fetch_list=[loss])
    return out


def test_ssd_loss_perfect_prediction_is_low(rng):
    """A prediction that encodes the gt box exactly and is confident in the
    right class must cost (much) less than a wrong one."""
    P, C, M = 4, 3, 1
    prior = np.array([[0.0, 0.0, 0.5, 0.5],
                      [0.5, 0.0, 1.0, 0.5],
                      [0.0, 0.5, 0.5, 1.0],
                      [0.5, 0.5, 1.0, 1.0]], dtype="float32")
    gtb = np.array([[[0.0, 0.0, 0.5, 0.5]]], dtype="float32")  # == prior 0
    gtl = np.array([[1]], dtype="int64")
    loc_good = np.zeros((1, P, 4), "float32")   # zero offsets = exact match
    conf_good = np.zeros((1, P, C), "float32")
    conf_good[0, 0, 1] = 8.0                    # right class on matched
    conf_good[0, 1:, 0] = 8.0                   # background on the rest
    good = _run_ssd_loss(rng, loc_good, conf_good, gtb, gtl, prior)

    pt.core.reset_default_programs()
    conf_bad = np.zeros((1, P, C), "float32")
    conf_bad[0, 0, 2] = 8.0                     # confidently WRONG class
    conf_bad[0, 1:, 1] = 8.0
    bad = _run_ssd_loss(rng, loc_good, conf_bad, gtb, gtl, prior)
    assert float(good[0]) < 0.1
    assert float(bad[0]) > float(good[0]) + 1.0


def test_ssd_loss_ignores_padding_rows(rng):
    """Padded gt rows (label < 0) must not change the loss."""
    P, C = 4, 3
    prior = np.array([[0.0, 0.0, 0.5, 0.5],
                      [0.5, 0.0, 1.0, 0.5],
                      [0.0, 0.5, 0.5, 1.0],
                      [0.5, 0.5, 1.0, 1.0]], dtype="float32")
    loc = rng.randn(1, P, 4).astype("float32") * 0.1
    conf = rng.randn(1, P, C).astype("float32")
    gtb1 = np.array([[[0.1, 0.1, 0.4, 0.4]]], dtype="float32")
    gtl1 = np.array([[2]], dtype="int64")
    a = _run_ssd_loss(rng, loc, conf, gtb1, gtl1, prior)

    pt.core.reset_default_programs()
    gtb2 = np.concatenate([gtb1, np.ones((1, 3, 4), "float32")], axis=1)
    gtl2 = np.concatenate([gtl1, -np.ones((1, 3), "int64")], axis=1)
    b = _run_ssd_loss(rng, loc, conf, gtb2, gtl2, prior)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_ssd_trains_end_to_end(rng):
    """Mini SSD: conv backbone -> loc/conf heads + prior_box; ssd_loss falls
    over training steps (the detection-training capability MultiBoxLoss
    provided)."""
    B, M = 2, 2
    img = layers.data("img", shape=[3, 32, 32], dtype="float32")
    gtb = layers.data("gtb", shape=[M, 4], dtype="float32")
    gtl = layers.data("gtl", shape=[M], dtype="int64")
    feat = layers.conv2d(img, num_filters=8, filter_size=3, stride=2,
                         padding=1, act="relu")          # [B,8,16,16]
    feat = layers.conv2d(feat, num_filters=8, filter_size=3, stride=2,
                         padding=1, act="relu")          # [B,8,8,8]
    boxes, variances = layers.prior_box(
        feat, img, min_sizes=[8.0], aspect_ratios=[1.0], flip=False)
    n_priors_per_cell = boxes.shape[2] if boxes.shape else 1
    loc_head = layers.conv2d(feat, num_filters=4, filter_size=3, padding=1)
    conf_head = layers.conv2d(feat, num_filters=3 * 1, filter_size=3,
                              padding=1)
    loc = layers.transpose(loc_head, [0, 2, 3, 1])
    loc = layers.reshape(loc, [-1, 8 * 8, 4])
    conf = layers.transpose(conf_head, [0, 2, 3, 1])
    conf = layers.reshape(conf, [-1, 8 * 8, 3])
    prior = layers.reshape(boxes, [-1, 4])
    pvar = layers.reshape(variances, [-1, 4])
    loss = layers.mean(layers.ssd_loss(loc, conf, gtb, gtl, prior,
                                       prior_box_var=pvar))
    pt.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {"img": rng.rand(B, 3, 32, 32).astype("float32"),
             "gtb": np.array([[[0.1, 0.1, 0.4, 0.5], [0.5, 0.5, 0.9, 0.9]],
                              [[0.2, 0.3, 0.6, 0.7], [0, 0, 0, 0]]],
                             dtype="float32"),
             "gtl": np.array([[1, 2], [1, -1]], dtype="int64")}
    vals = [float(exe.run(pt.default_main_program(), feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(15)]
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0] * 0.8


def test_detection_map_hand_computed():
    """mAP evaluator vs a hand-worked example: one class, two images,
    three detections (one duplicate -> FP)."""
    ev = DetectionMAP(overlap_threshold=0.5, ap_version="11point")
    # img0: gt at [0,0,1,1]; img1: gt at [0,0,1,1]
    gtb = np.array([[[0, 0, 1, 1]], [[0, 0, 1, 1]]], dtype="float32")
    gtl = np.array([[1], [1]], dtype="int64")
    # detections: img0 hit (score .9), img0 duplicate (score .8 -> FP),
    # img1 miss (iou<0.5, score .7 -> FP)
    det = np.full((2, 3, 6), -1.0, dtype="float32")
    det[0, 0] = [1, 0.9, 0, 0, 1, 1]
    det[0, 1] = [1, 0.8, 0.01, 0.01, 0.99, 0.99]
    det[1, 0] = [1, 0.7, 0.6, 0.6, 1.6, 1.6]
    ev.update(det, gtb, gtl)
    # ranked: tp, fp, fp over n_pos=2 -> precision 1, .5, 1/3; recall .5
    # at every point => 11-point AP = 6/11 * 1.0
    assert abs(ev.eval() - 6 / 11) < 1e-6
    # integral AP: p=1.0 at first recall step (0 -> .5), nothing after
    ev2 = DetectionMAP(overlap_threshold=0.5, ap_version="integral")
    ev2.update(det, gtb, gtl)
    assert abs(ev2.eval() - 0.5) < 1e-6


def test_detection_pipeline_train_then_eval(rng):
    """ssd_loss training output feeds detection_output + DetectionMAP: the
    full SSD train->decode->evaluate loop runs and produces a sane mAP."""
    ev = DetectionMAP()
    scores = np.zeros((1, 4, 2), "float32")
    scores[0, :, 1] = [0.9, 0.2, 0.1, 0.05]
    boxes = np.array([[[0, 0, .5, .5], [.5, 0, 1, .5],
                       [0, .5, .5, 1], [.5, .5, 1, 1]]], "float32")
    s = layers.data("s", shape=[4, 2], dtype="float32")
    b = layers.data("b", shape=[4, 4], dtype="float32")
    det = layers.detection_output(s, b, keep_top_k=4)
    exe = pt.Executor()
    out, = exe.run(pt.default_main_program(), feed={"s": scores, "b": boxes},
                   fetch_list=[det])
    ev.update(out, np.array([[[0, 0, .5, .5]]], "float32"),
              np.array([[1]], "int64"))
    assert abs(ev.eval() - 1.0) < 1e-9


def test_detection_map_difficult_ignored():
    """evaluate_difficult=False: a detection matched to a difficult gt is
    ignored (not a TP), per VOC / DetectionMAPEvaluator.cpp semantics."""
    ev = DetectionMAP(overlap_threshold=0.5, evaluate_difficult=False)
    gtb = np.array([[[0, 0, 1, 1], [2, 2, 3, 3]]], dtype="float32")
    gtl = np.array([[1, 1]], dtype="int64")
    diff = np.array([[True, False]])
    det = np.full((1, 1, 6), -1.0, dtype="float32")
    det[0, 0] = [1, 0.9, 0, 0, 1, 1]   # overlaps only the difficult gt
    ev.update(det, gtb, gtl, gt_difficult=diff)
    assert ev.eval() == 0.0
