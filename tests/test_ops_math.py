"""Per-op forward + numeric-gradient tests for math/elementwise/reduction/
transform ops (reference: fluid/tests/test_elementwise_*_op.py,
test_activation_op.py, test_reduce_op.py, test_matmul_op.py, ...)."""
import numpy as np
import pytest

from op_test import check_grad, check_output

R = np.random.RandomState(11)


def _away_from_kinks(a, kinks=(0.0,), margin=0.05):
    for k in kinks:
        a = np.where(np.abs(a - k) < margin, a + 2 * margin, a)
    return a


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------
ELTWISE = {
    "elementwise_add": np.add,
    "elementwise_sub": np.subtract,
    "elementwise_mul": np.multiply,
    "elementwise_div": np.divide,
    "elementwise_max": np.maximum,
    "elementwise_min": np.minimum,
    "elementwise_pow": np.power,
}


@pytest.mark.parametrize("op", sorted(ELTWISE))
def test_elementwise_forward(op):
    x = R.uniform(0.5, 2.0, (3, 4)).astype("float32")
    y = R.uniform(0.5, 2.0, (3, 4)).astype("float32")
    check_output(op, {"X": ("x", x), "Y": ("y", y)}, {},
                 {"Out": ELTWISE[op](x, y)})


@pytest.mark.parametrize("op", ["elementwise_add", "elementwise_sub",
                                "elementwise_mul", "elementwise_div"])
def test_elementwise_grad(op):
    x = R.uniform(0.5, 2.0, (3, 4)).astype("float32")
    y = R.uniform(0.5, 2.0, (3, 4)).astype("float32")
    check_grad(op, {"X": ("x", x), "Y": ("y", y)}, {}, wrt=["x", "y"])


def test_elementwise_add_broadcast_axis():
    """fluid broadcast: Y [C] added over axis=1 of X [N,C,H,W]."""
    x = R.rand(2, 3, 4, 5).astype("float32")
    y = R.rand(3).astype("float32")
    check_output("elementwise_add", {"X": ("x", x), "Y": ("y", y)},
                 {"axis": 1}, {"Out": x + y.reshape(1, 3, 1, 1)})


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
ACT = {
    "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
    "tanh": np.tanh,
    "relu": lambda x: np.maximum(x, 0),
    "exp": np.exp,
    "abs": np.abs,
    "square": np.square,
    "sqrt": np.sqrt,
    "reciprocal": lambda x: 1 / x,
    "log": np.log,
    "softplus": lambda x: np.log1p(np.exp(x)),
    "softsign": lambda x: x / (1 + np.abs(x)),
    "ceil": np.ceil,
    "floor": np.floor,
    "round": np.round,
}


@pytest.mark.parametrize("op", sorted(ACT))
def test_activation_forward(op):
    x = R.uniform(0.2, 2.0, (3, 5)).astype("float32")
    check_output(op, {"X": ("x", x)}, {}, {"Out": ACT[op](x)}, atol=1e-4)


@pytest.mark.parametrize("op", ["sigmoid", "tanh", "relu", "exp", "square",
                                "sqrt", "log", "softplus", "softsign"])
def test_activation_grad(op):
    x = _away_from_kinks(
        R.uniform(0.3, 1.5, (3, 4)).astype("float32"))
    check_grad(op, {"X": ("x", x)}, {}, wrt=["x"], max_relative_error=1e-2)


def test_leaky_relu_and_elu():
    x = _away_from_kinks(R.uniform(-2, 2, (3, 4)).astype("float32"))
    check_output("leaky_relu", {"X": ("x", x)}, {"alpha": 0.1},
                 {"Out": np.where(x > 0, x, 0.1 * x)})
    check_output("elu", {"X": ("x", x)}, {"alpha": 1.0},
                 {"Out": np.where(x > 0, x, np.expm1(x))})


def test_pow_scale_clip():
    x = R.uniform(0.5, 2.0, (3, 4)).astype("float32")
    check_output("pow", {"X": ("x", x)}, {"factor": 3.0}, {"Out": x ** 3})
    check_output("scale", {"X": ("x", x)}, {"scale": 2.5, "bias": 0.5},
                 {"Out": 2.5 * x + 0.5})
    check_output("clip", {"X": ("x", x)}, {"min": 0.8, "max": 1.5},
                 {"Out": np.clip(x, 0.8, 1.5)})
    check_grad("scale", {"X": ("x", x)}, {"scale": 2.5}, wrt=["x"])


def test_clip_by_norm():
    x = R.uniform(-1, 1, (4, 4)).astype("float32") * 3
    norm = np.sqrt((x ** 2).sum())
    expected = x * (1.0 / max(norm, 1.0)) if norm > 1.0 else x
    check_output("clip_by_norm", {"X": ("x", x)}, {"max_norm": 1.0},
                 {"Out": expected}, atol=1e-4)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
def test_mul_op_2d():
    x = R.rand(4, 6).astype("float32")
    y = R.rand(6, 3).astype("float32")
    check_output("mul", {"X": ("x", x), "Y": ("y", y)},
                 {"x_num_col_dims": 1, "y_num_col_dims": 1}, {"Out": x @ y})
    check_grad("mul", {"X": ("x", x), "Y": ("y", y)},
               {"x_num_col_dims": 1, "y_num_col_dims": 1}, wrt=["x", "y"])


def test_mul_op_flatten():
    x = R.rand(2, 3, 4).astype("float32")
    y = R.rand(12, 5).astype("float32")
    check_output("mul", {"X": ("x", x), "Y": ("y", y)},
                 {"x_num_col_dims": 1, "y_num_col_dims": 1},
                 {"Out": x.reshape(2, 12) @ y})


@pytest.mark.parametrize("tx,ty", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_matmul_transposes(tx, ty):
    a = R.rand(4, 5).astype("float32")
    b = R.rand(5, 3).astype("float32")
    x = a.T.copy() if tx else a
    y = b.T.copy() if ty else b
    check_output("matmul", {"X": ("x", x), "Y": ("y", y)},
                 {"transpose_X": tx, "transpose_Y": ty}, {"Out": a @ b})


def test_matmul_batched():
    x = R.rand(2, 4, 5).astype("float32")
    y = R.rand(2, 5, 3).astype("float32")
    check_output("matmul", {"X": ("x", x), "Y": ("y", y)}, {},
                 {"Out": np.matmul(x, y)})
    check_grad("matmul", {"X": ("x", x), "Y": ("y", y)}, {}, wrt=["x", "y"])


def test_sum_op():
    xs = [R.rand(3, 4).astype("float32") for _ in range(3)]
    check_output("sum", {"X": [("a", xs[0]), ("b", xs[1]), ("c", xs[2])]},
                 {}, {"Out": xs[0] + xs[1] + xs[2]})


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------
RED = {"reduce_sum": np.sum, "reduce_mean": np.mean,
       "reduce_max": np.max, "reduce_min": np.min, "reduce_prod": np.prod}


@pytest.mark.parametrize("op", sorted(RED))
@pytest.mark.parametrize("dim,keep", [([0], False), ([1], True),
                                      (None, False)])
def test_reduce_forward(op, dim, keep):
    x = R.uniform(0.5, 1.5, (3, 4)).astype("float32")
    attrs = {"keep_dim": keep}
    if dim is None:
        attrs["reduce_all"] = True
        exp = RED[op](x, keepdims=keep)
    else:
        attrs["dim"] = dim
        exp = RED[op](x, axis=tuple(dim), keepdims=keep)
    check_output(op, {"X": ("x", x)}, attrs, {"Out": np.asarray(exp)})


def test_reduce_sum_grad():
    x = R.rand(3, 4).astype("float32")
    check_grad("reduce_sum", {"X": ("x", x)}, {"dim": [1]}, wrt=["x"])
    check_grad("reduce_mean", {"X": ("x", x)}, {"reduce_all": True},
               wrt=["x"])


# ---------------------------------------------------------------------------
# shape transforms
# ---------------------------------------------------------------------------
def test_reshape_transpose_concat_split():
    x = R.rand(2, 6).astype("float32")
    check_output("reshape", {"X": ("x", x)}, {"shape": [3, 4]},
                 {"Out": x.reshape(3, 4)})
    check_output("transpose", {"X": ("x", x)}, {"axis": [1, 0]},
                 {"Out": x.T})
    y = R.rand(2, 6).astype("float32")
    check_output("concat", {"X": [("x", x), ("y", y)]}, {"axis": 0},
                 {"Out": np.concatenate([x, y], 0)})
    check_output("split", {"X": ("x", x)}, {"num": 2, "axis": 1},
                 {"Out~0": x[:, :3], "Out~1": x[:, 3:]})
    check_grad("transpose", {"X": ("x", x)}, {"axis": [1, 0]}, wrt=["x"])


def test_pad_and_crop():
    x = R.rand(2, 3).astype("float32")
    check_output("pad", {"X": ("x", x)},
                 {"paddings": [1, 0, 0, 2], "pad_value": 0.5},
                 {"Out": np.pad(x, ((1, 0), (0, 2)), constant_values=0.5)})
    big = R.rand(4, 5).astype("float32")
    check_output("crop", {"X": ("x", big)},
                 {"offsets": [1, 2], "shape": [2, 3]},
                 {"Out": big[1:3, 2:5]})


def test_gather_scatter():
    x = R.rand(5, 3).astype("float32")
    idx = np.array([0, 2, 4])
    check_output("gather", {"X": ("x", x), "Index": ("i", idx)}, {},
                 {"Out": x[idx]})
    upd = R.rand(3, 3).astype("float32")
    exp = x.copy()
    exp[idx] = upd
    check_output("scatter",
                 {"X": ("x", x), "Ids": ("i", idx), "Updates": ("u", upd)},
                 {"overwrite": True}, {"Out": exp})


def test_cast_sign_logical():
    x = R.uniform(-2, 2, (3, 4)).astype("float32")
    check_output("sign", {"X": ("x", x)}, {}, {"Out": np.sign(x)})
    a = (R.rand(3, 4) > 0.5)
    b = (R.rand(3, 4) > 0.5)
    check_output("logical_and", {"X": ("x", a), "Y": ("y", b)}, {},
                 {"Out": a & b})
    check_output("logical_not", {"X": ("x", a)}, {}, {"Out": ~a})


def test_compare_ops():
    x = R.rand(3, 4).astype("float32")
    y = R.rand(3, 4).astype("float32")
    check_output("less_than", {"X": ("x", x), "Y": ("y", y)}, {},
                 {"Out": x < y})
    check_output("equal", {"X": ("x", x), "Y": ("x2", x.copy())}, {},
                 {"Out": np.ones_like(x, bool)})


def test_top_k():
    x = R.rand(3, 6).astype("float32")
    k = 2
    idx = np.argsort(-x, axis=1)[:, :k]
    val = np.take_along_axis(x, idx, 1)
    got = check_output("top_k", {"X": ("x", x)}, {"k": k}, {"Out": val})


def test_one_hot_and_multiplex():
    ids = np.array([[1], [0], [3]])
    exp = np.zeros((3, 4), "float32")
    exp[np.arange(3), ids[:, 0]] = 1
    check_output("one_hot", {"X": ("x", ids)}, {"depth": 4}, {"Out": exp})


def test_cumsum_and_norm():
    x = R.rand(3, 4).astype("float32")
    check_output("cumsum", {"X": ("x", x)}, {"axis": 1},
                 {"Out": np.cumsum(x, 1)})
    check_output("norm", {"X": ("x", x)}, {"axis": 1, "epsilon": 1e-10},
                 {"Out": x / np.sqrt((x**2).sum(1, keepdims=True) + 1e-10)},
                 atol=1e-4)


def test_fill_and_random_shapes():
    from op_test import run_op
    got = run_op("fill_constant", {}, {"shape": [2, 3], "value": 7.0,
                                       "dtype": "float32"}, ["Out"])
    np.testing.assert_allclose(got["out__out0"], np.full((2, 3), 7.0))
    got = run_op("gaussian_random", {}, {"shape": [64, 64], "mean": 0.0,
                                         "std": 1.0}, ["Out"])
    assert abs(float(np.mean(got["out__out0"]))) < 0.1
    got = run_op("uniform_random", {}, {"shape": [64, 64], "min": -1.0,
                                        "max": 1.0}, ["Out"])
    a = got["out__out0"]
    assert a.min() >= -1 and a.max() <= 1 and abs(a.mean()) < 0.1
