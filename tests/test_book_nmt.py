"""Book-style machine-translation test on wmt14 data (reference:
fluid/tests/book/test_machine_translation.py + v2/dataset/wmt14.py): train
seq2seq+attention on wmt14 reader samples, assert the cost improves, then
beam-decode and score against the corpus.  Offline the wmt14 module
serves its deterministic synthetic parallel corpus (target = reversed
source, shifted ids) — a real translation function, so decode accuracy is
measurable; with the archive cached, the same code parses the real tgz."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.dataset import wmt14

DICT = 30
EMB = 32
HID = 32


def _fixed_len_batches(reader, body_len=6, batch=32):
    """Batch samples whose source body length is exactly ``body_len``
    (static shapes; the real pipeline would bucket instead)."""
    srcs, tins, tnexts = [], [], []
    for s, ti, tn in reader():
        if len(s) != body_len + 2:
            continue
        srcs.append(s)
        tins.append(ti)
        tnexts.append(tn)
        if len(srcs) == batch:
            yield (np.asarray(srcs), np.asarray(tins), np.asarray(tnexts))
            srcs, tins, tnexts = [], [], []


def test_wmt14_reader_protocol():
    """Sample structure matches the reference reader contract: framed
    source, <s>-prefixed target input, <e>-suffixed target label."""
    n = 0
    for src, trg, trg_next in wmt14.train(DICT)():
        assert src[0] == 0 and src[-1] == 1          # <s> ... <e>
        assert trg[0] == 0                           # <s> prefix
        assert trg_next[-1] == 1                     # <e> suffix
        assert trg[1:] == trg_next[:-1]              # shifted by one
        assert max(src + trg + trg_next) < DICT
        n += 1
        if n >= 50:
            break
    assert n == 50
    src_d, trg_d = wmt14.build_dict(DICT)
    assert len(src_d) == DICT and src_d["<s>"] == 0 and src_d["<e>"] == 1
    rid, _ = wmt14.get_dict(DICT)
    assert rid[0] == "<s>"


def test_wmt14_nmt_train_and_beam_decode(rng):
    """The machine-translation book test: cost must improve on wmt14
    training data and the beam decode must beat chance on the known
    synthetic translation function."""
    src = layers.data("src", shape=[], dtype="int64", lod_level=1)
    tgt = layers.data("tgt", shape=[], dtype="int64", lod_level=1)
    lbl = layers.data("lbl", shape=[], dtype="int64", lod_level=1)
    probs = models.seq2seq_attention(src, tgt, DICT, DICT,
                                     emb_dim=EMB, hidden_dim=HID)
    flat = layers.reshape(probs, [-1, DICT])
    loss = layers.mean(layers.cross_entropy(
        flat, layers.reshape(lbl, [-1, 1])))
    pt.optimizer.Adam(0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])

    batches = list(_fixed_len_batches(wmt14.train(DICT)))
    assert len(batches) >= 5
    losses = []
    for epoch in range(12):
        for s, ti, tn in batches[:5]:
            B, Ts, Tt = s.shape[0], s.shape[1], ti.shape[1]
            feeds = {"src": s, "src@LEN": np.full(B, Ts),
                     "tgt": ti, "tgt@LEN": np.full(B, Tt),
                     "lbl": tn, "lbl@LEN": np.full(B, Tt)}
            losses.append(float(exe.run(feed=feeds, fetch_list=[loss])[0]))
    assert losses[-1] < losses[0] * 0.5, \
        f"NMT cost did not improve: {losses[0]:.3f} -> {losses[-1]:.3f}"

    # beam decode the first test batch and score token accuracy against
    # the corpus target (the known synthetic translation function)
    s, _, tn = next(_fixed_len_batches(wmt14.test(DICT)))
    Tt = tn.shape[1]
    infer_prog = pt.Program()
    with pt.program_guard(infer_prog, pt.Program()):
        src_i = layers.data("src", shape=[], dtype="int64", lod_level=1)
        ids_v, scores_v, lens_v = models.seq2seq_infer(
            src_i, DICT, DICT, emb_dim=EMB, hidden_dim=HID,
            beam_size=3, bos_id=0, eos_id=1, max_len=Tt)
    ids, scores = exe.run(
        infer_prog,
        feed={"src": s, "src@LEN": np.full(s.shape[0], s.shape[1])},
        fetch_list=[ids_v, scores_v], is_test=True)
    assert ids.shape == (s.shape[0], 3, Tt)
    assert (scores[:, 0] + 1e-6 >= scores[:, 1]).all()
    top = ids[:, 0, :]
    acc = float((top == tn).mean())
    assert acc > 0.3, f"beam decode accuracy {acc:.2f} not above chance"
