"""Control fixture: idiomatic concurrency that every PT05x rule must
stay silent on — consistent guard discipline, one global lock order,
timeouts on blocking waits, predicate-loop condition waits, a registered
thread-name prefix, and no signal-handler lock work.
"""
import queue
import threading


class Worker:
    def __init__(self):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.q = queue.Queue()
        self.items = []
        self.stopping = False

    def put(self, item):
        with self.cond:
            self.items.append(item)
            self.cond.notify()

    def take(self):
        with self.cond:
            while not self.items and not self.stopping:
                self.cond.wait(timeout=0.5)
            return self.items.pop() if self.items else None

    def drain_queue(self):
        try:
            return self.q.get(timeout=0.1)
        except queue.Empty:
            return None

    def start(self):
        t = threading.Thread(target=self.take, name="pt-fx-worker",
                             daemon=True)
        t.start()
        return t

    def stop(self, t):
        with self.cond:
            self.stopping = True
            self.cond.notify_all()
        t.join(timeout=2.0)
