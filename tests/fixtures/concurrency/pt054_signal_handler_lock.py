"""Seeded defect: PT054 — lock acquisition reachable from a signal
handler.  The handler runs on the main thread at an arbitrary bytecode
boundary; if the interrupted frame already holds ``self.lock`` the
process self-deadlocks.
"""
import signal
import threading


class Daemon:
    def __init__(self):
        self.lock = threading.Lock()
        self.stopping = False
        signal.signal(signal.SIGTERM, self.on_term)

    def on_term(self, signum, frame):
        # the defect: blocking acquire inside a signal handler
        with self.lock:
            self.stopping = True

    def step(self):
        with self.lock:
            return self.stopping
