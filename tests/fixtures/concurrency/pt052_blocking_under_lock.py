"""Seeded defect: PT052 — blocking call while holding a lock.
``pop`` calls ``self.q.get()`` (no timeout) inside ``with self.lock``.
The queue drain stalls every other holder of the lock.
"""
import queue
import threading


class Mailbox:
    def __init__(self):
        self.lock = threading.Lock()
        self.q = queue.Queue()

    def push(self, item):
        self.q.put_nowait(item)

    def pop(self):
        with self.lock:
            # the defect: unbounded blocking get under the lock
            return self.q.get()
