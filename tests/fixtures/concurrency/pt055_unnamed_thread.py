"""Seeded defect: PT055 — framework thread without a registered ``pt-``
prefix name.  The leak-check fixture (and any operator reading a thread
dump) cannot attribute "helper-1" to a subsystem.
"""
import threading


class Runner:
    def __init__(self):
        self.done = False

    def _work(self):
        self.done = True

    def start(self):
        # the defect: ad-hoc name outside the frozen prefix table
        t = threading.Thread(target=self._work, name="helper-1",
                             daemon=True)
        t.start()
        return t
