"""Seeded defect: PT051 — static lock-order cycle.  ``transfer`` nests
``self.a`` then ``self.b``; ``audit`` nests them in the opposite order.
Writes stay consistently guarded so PT050 stays silent.
"""
import threading


class Ledger:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.debits = 0
        self.credits = 0

    def transfer(self):
        with self.a:
            with self.b:
                self.debits = self.debits + 1

    def audit(self):
        # the defect: b -> a reverses transfer()'s a -> b order
        with self.b:
            with self.a:
                self.credits = self.credits + 1
