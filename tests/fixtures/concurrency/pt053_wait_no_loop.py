"""Seeded defect: PT053 — ``Condition.wait`` outside a ``while`` loop.
A spurious wakeup (or a stolen notify) leaves ``take`` running with the
predicate still false.
"""
import threading


class Box:
    def __init__(self):
        self.cond = threading.Condition()
        self.item = None

    def put(self, item):
        with self.cond:
            self.item = item
            self.cond.notify()

    def take(self):
        with self.cond:
            if self.item is None:
                # the defect: `if` + bare wait — needs `while not pred`
                self.cond.wait()
            item, self.item = self.item, None
            return item
