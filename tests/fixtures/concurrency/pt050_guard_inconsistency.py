"""Seeded defect: PT050 — shared attribute written both under and
outside a lock.  ``bump`` guards ``self.count``; ``sneak`` writes it
bare.  Exactly ONE defect: nothing blocks, no ordering, threads named.
"""
import threading


class Counter:
    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self.lock:
            self.count = self.count + 1

    def sneak(self):
        # the defect: no lock around a write bump() guards
        self.count = 0

    def read_locked(self):
        with self.lock:
            return self.count
