"""Lockwatch runtime half of the PT05x concurrency pass
(paddle_tpu/testing/lockwatch.py).

Contract under test, both directions of the PR 5 opt-in convention:

  * OFF (the default): the factories return the PLAIN threading
    primitives — type identity, not a wrapper with a fast path — and a
    steady-state executor step loop performs zero lockwatch work
    (concurrency/* metric deltas all zero) and zero retraces.
  * ON: every acquisition through a watched primitive feeds a
    process-wide acquisition-order graph; an inversion raises a typed
    ``LockOrderViolation`` BEFORE blocking — naming both lock classes
    and carrying both hold stacks — so a latent deadlock becomes a
    deterministic report.  The @slow chaos round proves the conversion
    on a REAL two-thread two-lock inversion in a subprocess.
"""
import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import compile_cache
from paddle_tpu.core.compile_cache import retrace_guard
from paddle_tpu.observability import metrics as obs
from paddle_tpu.testing import lockwatch as lw
from paddle_tpu.testing.lockwatch import LockOrderViolation


@pytest.fixture
def watch():
    """Lockwatch ON for one test; graph/violations isolated + restored."""
    prior = lw.ENABLED
    lw.ENABLED = True
    lw.reset()
    yield lw
    lw.ENABLED = prior
    lw.reset()


def _concurrency_snapshot():
    snap = obs.registry().snapshot()
    return {k: v for k, v in snap.items() if k.startswith("concurrency/")}


def _counter(name):
    return obs.registry().snapshot()[name]["value"]


# ---------------------------------------------------------------------------
# OFF: zero overhead, zero instrumentation
# ---------------------------------------------------------------------------
def test_off_factories_return_plain_primitives():
    assert not lw.ENABLED    # suite must run with the watch off
    assert type(lw.make_lock("t")) is type(threading.Lock())
    assert type(lw.make_rlock("t")) is type(threading.RLock())
    assert type(lw.make_condition("t")) is threading.Condition
    # and a caller-supplied raw lock passes straight through
    raw = threading.Lock()
    cond = lw.make_condition("t", raw)
    assert type(cond) is threading.Condition


def test_off_zero_per_step_work(rng):
    """Steady-state executor loop: no concurrency metric moves, no
    retrace — the watch costs nothing unless somebody opts in."""
    pt.default_main_program().random_seed = 0
    x = layers.data("x", shape=[4], dtype="float32")
    pred = layers.fc(x, size=3, act="softmax")
    loss = layers.mean(pred)
    feed = {"x": rng.rand(8, 4).astype("float32")}
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.run(feed=feed, fetch_list=[loss])          # warm the cache

    # stats are process-global; earlier suites' legitimate retraces
    # (program-mutation tests) must not trip THIS guard
    compile_cache.stats().reset()
    before = _concurrency_snapshot()
    with retrace_guard():
        for _ in range(5):
            exe.run(feed=feed, fetch_list=[loss])
    compile_cache.stats().assert_no_retrace()
    assert _concurrency_snapshot() == before, (
        "lockwatch is off but concurrency/* metrics moved during a "
        "steady-state step loop")
    assert lw.graph() == {} and lw.violations() == []


# ---------------------------------------------------------------------------
# ON: graph recording + deterministic inversion report
# ---------------------------------------------------------------------------
def test_on_records_acquisition_order_edges(watch):
    a, b = lw.make_lock("fx.a"), lw.make_lock("fx.b")
    with a:
        with b:
            pass
    assert lw.graph() == {"fx.a": ("fx.b",)}
    # repeating the same order adds nothing
    with a:
        with b:
            pass
    assert lw.graph() == {"fx.a": ("fx.b",)}


def test_on_inversion_raises_before_blocking(watch):
    a, b = lw.make_lock("fx.a"), lw.make_lock("fx.b")
    with a:
        with b:
            pass
    violations_before = _counter("concurrency/order_violations")
    with b:
        with pytest.raises(LockOrderViolation) as ei:
            a.acquire()      # b -> a inverts the recorded a -> b
    v = ei.value
    assert v.acquiring == "fx.a" and v.holding == "fx.b"
    report = v.report()
    # the report stands alone: both lock classes, the cycle path, and
    # BOTH stacks (current acquire + first-seen reverse edge)
    assert "fx.a" in report and "fx.b" in report
    assert "fx.a" in " -> ".join(v.path) and "fx.b" in " -> ".join(v.path)
    assert v.current_stack.strip() and v.reverse_stack.strip()
    assert [x.path for x in lw.violations()] == [v.path]
    assert _counter("concurrency/order_violations") == violations_before + 1


def test_on_inversion_is_deterministic(watch):
    # no timing, no second thread: the cycle check runs at the acquire
    # call, so the SAME program raises at the SAME site every run
    for _ in range(3):
        lw.reset()
        a, b = lw.make_lock("fx.a"), lw.make_lock("fx.b")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderViolation):
                with a:
                    pass


def test_on_rlock_reentry_is_not_a_violation(watch):
    r = lw.make_rlock("fx.r")
    with r:
        with r:                       # re-entry: no self-edge, no raise
            pass
    assert lw.graph() == {}
    assert lw.violations() == []


def test_on_nonreentrant_self_deadlock_raises(watch):
    m = lw.make_lock("fx.m")
    m.acquire()
    try:
        with pytest.raises(LockOrderViolation):
            m.acquire()               # would self-deadlock; report instead
    finally:
        m.release()


def test_on_condition_roundtrip(watch):
    """Producer/consumer through a watched Condition: wait releases the
    lock (producer can get in), wakeup re-acquires, no violations."""
    lock = lw.make_lock("fx.box")
    cond = lw.make_condition("fx.box", lock)
    state = {"item": None}

    def produce():
        with cond:
            state["item"] = 42
            cond.notify()

    t = threading.Thread(target=produce, name="pt-fx-producer",
                         daemon=True)
    with cond:
        t.start()
        ok = cond.wait_for(lambda: state["item"] is not None, timeout=5.0)
        assert ok and state["item"] == 42
        assert lock.locked()          # wait re-acquired before returning
    t.join(timeout=5.0)
    assert lw.violations() == []


def test_on_hold_metrics(watch, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LOCKWATCH_HOLD_MS", "1")
    held_before = obs.registry().snapshot()["concurrency/lock_held_ms"]
    long_before = _counter("concurrency/long_holds")
    m = lw.make_lock("fx.slowpoke")
    with m:
        time.sleep(0.01)              # >> the 1 ms threshold above
    held_after = obs.registry().snapshot()["concurrency/lock_held_ms"]
    assert held_after["count"] == held_before["count"] + 1
    assert held_after["max"] >= 1.0   # milliseconds
    assert _counter("concurrency/long_holds") == long_before + 1


def test_stats_summary_lockwatch_section(watch, tmp_path):
    """A watched run's metrics surface in the stats CLI summary; a run
    with the watch off omits the section entirely."""
    from paddle_tpu.observability import export

    a, b = lw.make_lock("fx.a"), lw.make_lock("fx.b")
    with a:
        with b:
            pass
    snap = export.metrics_snapshot()
    p = tmp_path / "run.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "kind": "snapshot", **snap})
                 + "\n")
    summary = export.summarize_log(str(p))
    lk = summary["lockwatch"]
    # the metrics registry is process-global, so earlier tests in this
    # module contribute — assert at-least, not exactly
    assert lk["holds"] >= 2 and lk["order_edges"] >= 1
    rendered = export.render_summary(summary)
    assert "lockwatch:" in rendered
    assert "watched hold(s)" in rendered and "order edge(s)" in rendered

    # off-run log: no concurrency holds recorded -> section omitted
    empty = dict(snap)
    empty["metrics"] = {k: v for k, v in snap["metrics"].items()
                        if not k.startswith("concurrency/")}
    p2 = tmp_path / "off.jsonl"
    p2.write_text(json.dumps({"ts": 1.0, "kind": "snapshot", **empty})
                  + "\n")
    s2 = export.summarize_log(str(p2))
    assert "lockwatch" not in s2
    assert "lockwatch:" not in export.render_summary(s2)


# ---------------------------------------------------------------------------
# @slow chaos round: a REAL inversion in a subprocess becomes a report
# ---------------------------------------------------------------------------
_DEADLOCK_CHILD = r"""
import os, sys, threading
os.environ["PADDLE_TPU_LOCKWATCH"] = "1"
from paddle_tpu.testing import lockwatch as lw

a, b = lw.make_lock("chaos.a"), lw.make_lock("chaos.b")
g1, g2 = threading.Event(), threading.Event()
reports = []

def t1():                        # a -> b
    with a:
        g1.set()
        g2.wait(10)              # guarantee both threads hold one lock
        try:
            with b:
                pass
        except lw.LockOrderViolation as v:
            reports.append(v.report())

def t2():                        # b -> a: the inversion
    with b:
        g2.set()
        g1.wait(10)
        try:
            with a:
                pass
        except lw.LockOrderViolation as v:
            reports.append(v.report())

ts = [threading.Thread(target=t1, name="pt-fx-t1", daemon=True),
      threading.Thread(target=t2, name="pt-fx-t2", daemon=True)]
for t in ts: t.start()
for t in ts: t.join(timeout=20)
assert not any(t.is_alive() for t in ts), "HUNG: lockwatch failed to break the deadlock"
assert len(reports) == 1, f"expected exactly one violation, got {len(reports)}"
assert "chaos.a" in reports[0] and "chaos.b" in reports[0]
assert "lock-order violation" in reports[0] or "LockOrderViolation" in reports[0] or "chaos" in reports[0]
print("REPORT-OK")
print(reports[0])
"""


@pytest.mark.slow
@pytest.mark.timeout(120)
def test_deadlock_chaos_round_becomes_typed_report():
    """Two threads, two locks, opposite orders, both first-acquisitions
    synchronized — the classic AB/BA deadlock.  Without the watch this
    child HANGS; with it, exactly one thread gets a LockOrderViolation
    before blocking (the cycle check runs pre-acquire), both threads
    exit, and the report names both lock classes.  The subprocess call
    carries a hard timeout so a regression fails instead of wedging the
    suite."""
    out = subprocess.run(
        [sys.executable, "-c", _DEADLOCK_CHILD],
        capture_output=True, text=True, timeout=90)
    assert out.returncode == 0, (
        f"chaos child failed\nstdout:\n{out.stdout}\nstderr:\n{out.stderr}")
    assert "REPORT-OK" in out.stdout
    assert "chaos.a" in out.stdout and "chaos.b" in out.stdout
