"""Incremental checkpointing: dirty-row sparse deltas, chunked dense
diffs, the delta-chain manifest, and the async double-buffered commit
pipeline (distributed/checkpoint.py + sparse table token protocol).

Covers the PR 18 acceptance surface:
  - base + delta chains restore bit-identical to the live state at the
    last acked commit (rows, optimizer slots, export bytes);
  - the manifest records kind/parent/chain_len/content_hash and restore
    verifies the whole chain, falling back to the last durable prefix
    when a link is torn (ckpt.delta truncate) or half-written (SIGKILL
    mid-chain, the @slow subprocess round);
  - dense vars chunk-diff (unchanged vars cost zero delta bytes);
  - a row pushed between the dirty-set snapshot and the durable ack is
    never marked clean (the concurrent-push regression);
  - writer failure retracts the snapshot so those rows ride the next
    commit, and the Checkpointer's policy rebases full on chain caps.
"""
import hashlib
import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.checkpoint import (
    CheckpointManager, DeltaChainError)
from paddle_tpu.sparse import SparseSession, SparseTable
from paddle_tpu.testing import faultinject as fi
from paddle_tpu.testing.faultinject import InjectedFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB, DIM = 800, 6


def _mk_table(seed=11, num_shards=3, impl="vectorized", name="emb"):
    return SparseTable(name, VOCAB, DIM, optimizer="adagrad",
                       learning_rate=0.1, num_shards=num_shards,
                       seed=seed, impl=impl)


def _touch(t, rng, n=40):
    ids = np.unique(rng.randint(0, VOCAB, n).astype(np.int64))
    t.push(ids, rng.randn(len(ids), t.dim).astype(np.float32))
    return ids


def _scope_of(state, **dense):
    sc = pt.Scope()
    for k, v in state.items():
        sc.set(k, v)
    for k, v in dense.items():
        sc.set(k, v)
    return sc


def _commit(cm, t, step, kind, rng=None, **dense):
    """One blocking commit under the token protocol; returns the meta."""
    tok, st = t.export_full() if kind == "full" else t.export_delta()
    cm.save(step, _scope_of(st, **dense), blocking=True, kind=kind,
            on_commit=lambda info, tk=tok: t.commit_delta(tk),
            on_fail=lambda exc, tk=tok: t.retract_delta(tk))
    with open(os.path.join(str(cm.root), f"ckpt-{step}",
                           "meta.json")) as f:
        return json.load(f)


def _state_sha(state, w=None):
    h = hashlib.sha256()
    for k in sorted(state):
        a = np.ascontiguousarray(np.asarray(state[k]))
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    if w is not None:
        h.update(np.asarray(w, np.float32).tobytes())
    return h.hexdigest()


def _restore_table(cm, seed=11, num_shards=3, impl="vectorized",
                   name="emb", step=None):
    sc = pt.Scope()
    restored = cm.restore(step=step, scope=sc)
    state = {k: np.asarray(sc.get(k)) for k in sc.keys()
             if k.startswith("__sparse__/")}
    t = _mk_table(seed=seed, num_shards=num_shards, impl=impl, name=name)
    t.restore_state_vars(state)
    return restored, t, sc


# ---------------------------------------------------------------------------
# Delta-chain round trip + manifest
# ---------------------------------------------------------------------------
def test_delta_chain_restores_bit_identical(tmp_path, rng):
    """base + 2 deltas replay to EXACTLY the live state at the last
    commit: rows, Adagrad moment, and the canonical export bytes."""
    t = _mk_table()
    cm = CheckpointManager(str(tmp_path), async_save=False)
    w = np.zeros(2048, np.float32)

    _touch(t, rng)
    m1 = _commit(cm, t, 1, "full", w=w.copy())
    _touch(t, rng)
    w[100:200] += 1.0
    m2 = _commit(cm, t, 2, "delta", w=w.copy())
    _touch(t, rng)
    w[1500] = -3.0
    m3 = _commit(cm, t, 3, "delta", w=w.copy())

    # manifest chain: kind/parent/chain_len/content_hash
    assert (m1["kind"], m2["kind"], m3["kind"]) == ("full", "delta",
                                                    "delta")
    assert m1["chain_len"] == 0 and m2["chain_len"] == 1 \
        and m3["chain_len"] == 2
    assert m2["parent"] == m1["content_hash"]
    assert m3["parent"] == m2["content_hash"]

    cm2 = CheckpointManager(str(tmp_path), async_save=False)
    restored, t2, sc = _restore_table(cm2)
    assert restored == 3
    assert np.array_equal(np.asarray(sc.get("w"), np.float32), w)
    # export bytes are the strictest equality: ids, rows, AND slots
    assert _state_sha(t.export_state_vars()) == \
        _state_sha(t2.export_state_vars())
    allids = np.arange(VOCAB, dtype=np.int64)
    assert np.array_equal(t.pull(allids), t2.pull(allids))
    assert np.array_equal(t.pull_slot("moment", allids),
                          t2.pull_slot("moment", allids))


def test_delta_bytes_scale_with_touched_rows(tmp_path, rng):
    """A delta touching ~2% of rows is far smaller than the full base
    (the reason this PR exists) and records its size in the manifest."""
    t = _mk_table()
    t.push(np.arange(VOCAB, dtype=np.int64),
           rng.randn(VOCAB, DIM).astype(np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    m1 = _commit(cm, t, 1, "full")
    _touch(t, rng, n=16)
    m2 = _commit(cm, t, 2, "delta")
    assert m2["delta_bytes"] > 0
    assert m2["delta_bytes"] * 10 < m1["base_bytes"]
    assert m2["chain_bytes"] == m2["delta_bytes"]
    assert m2["base_bytes"] == m1["base_bytes"]


def test_unchanged_dense_var_costs_zero_delta_bytes(tmp_path):
    """Chunk diff: a dense var identical to the parent writes NO patch
    file; a single-chunk change patches just that chunk."""
    chunk = 4096
    w = np.zeros(16 * chunk // 4, np.float32)        # 16 chunks
    cm = CheckpointManager(str(tmp_path), async_save=False,
                           chunk_bytes=chunk)
    cm.save(1, _scope_of({}, w=w.copy()), blocking=True)
    # no change at all -> zero-byte delta
    cm.save(2, _scope_of({}, w=w.copy()), blocking=True, kind="delta")
    with open(tmp_path / "ckpt-2" / "meta.json") as f:
        m2 = json.load(f)
    assert m2["delta_bytes"] == 0
    ent = m2["vars"]["w"]
    assert ent["mode"] == "chunks"
    assert all(sh["patch"] is None for sh in ent["shards"])
    # one element -> exactly one changed chunk in the patch
    w[5 * chunk // 4] = 7.0
    cm.save(3, _scope_of({}, w=w.copy()), blocking=True, kind="delta")
    with open(tmp_path / "ckpt-3" / "meta.json") as f:
        m3 = json.load(f)
    sh = m3["vars"]["w"]["shards"][0]
    assert sh["patch"] is not None and sh["patch"]["changed"] == [5]
    assert 0 < m3["delta_bytes"] <= 2 * chunk
    sc = pt.Scope()
    assert CheckpointManager(str(tmp_path)).restore(scope=sc) == 3
    assert np.array_equal(np.asarray(sc.get("w"), np.float32), w)


def test_delta_requires_live_matching_chain(tmp_path, rng):
    """Fail fast BEFORE bytes land: no committed parent, or a sparse
    group layout that differs from the parent, raises DeltaChainError
    (the caller's cue to re-export a full rebase)."""
    t = _mk_table()
    _touch(t, rng)
    cm = CheckpointManager(str(tmp_path), async_save=False)
    with pytest.raises(DeltaChainError):
        cm.save(1, _scope_of(t.export_full()[1]), blocking=True,
                kind="delta")
    _commit(cm, t, 1, "full")
    _touch(t, rng)
    tok, st = t.export_delta()
    dropped = {k: v for k, v in st.items()
               if not k.startswith("__sparse__/emb/shard2/")}
    with pytest.raises(DeltaChainError):
        cm.save(2, _scope_of(dropped), blocking=True, kind="delta")
    t.retract_delta(tok)
    assert not os.path.isdir(tmp_path / "ckpt-2")
    # a failed delta attempt conservatively kills the planned chain —
    # the next delta refuses up front and a full rebase revives it
    assert not cm.chain_stats()["alive"]
    with pytest.raises(DeltaChainError):
        cm.save(2, _scope_of({}), blocking=True, kind="delta")
    _commit(cm, t, 2, "full")
    _touch(t, rng)
    _commit(cm, t, 3, "delta")


def test_restore_adopts_tip_and_next_delta_chains_onto_it(tmp_path, rng):
    t = _mk_table()
    _touch(t, rng)
    cm = CheckpointManager(str(tmp_path), async_save=False)
    _commit(cm, t, 1, "full")
    _touch(t, rng)
    m2 = _commit(cm, t, 2, "delta")

    cm2 = CheckpointManager(str(tmp_path), async_save=False)
    assert not cm2.chain_stats()["alive"]      # nothing adopted yet
    restored, t2, _ = _restore_table(cm2)
    assert restored == 2 and cm2.chain_stats()["alive"]
    _touch(t2, rng)
    m3 = _commit(cm2, t2, 3, "delta")
    assert m3["parent"] == m2["content_hash"] and m3["chain_len"] == 2


# ---------------------------------------------------------------------------
# Dirty-set token protocol (satellite a: concurrent push mid-commit)
# ---------------------------------------------------------------------------
def test_push_between_snapshot_and_ack_stays_dirty(rng):
    """A row pushed while the writer is serializing the snapshot must
    ride the NEXT delta — the ack can only clean rows it actually
    captured."""
    t = _mk_table()
    a = _touch(t, rng)
    tok, st = t.export_delta()
    assert t.dirty_rows == 0                    # snapshot moved them
    b = np.array([VOCAB - 1], np.int64)
    assert b[0] not in a
    t.push(b, np.ones((1, DIM), np.float32))    # "mid-serialization"
    t.commit_delta(tok)                         # durable ack
    assert t.dirty_rows == 1                    # b survived the ack
    _, st2 = t.export_delta()
    nxt = np.concatenate([v for k, v in st2.items() if k.endswith("/ids")])
    assert list(nxt) == [VOCAB - 1]


def test_retract_re_dirties_and_is_idempotent(rng):
    t = _mk_table()
    ids = _touch(t, rng)
    tok, _ = t.export_delta()
    assert t.dirty_rows == 0
    t.retract_delta(tok)
    assert t.dirty_rows == len(ids)             # back on the next commit
    t.retract_delta(tok)                        # double-fire: no-op
    assert t.dirty_rows == len(ids)
    tok2, _ = t.export_delta()
    t.commit_delta(tok2)
    t.retract_delta(tok2)                       # retract after ack: no-op
    assert t.dirty_rows == 0


def test_writer_failure_retracts_so_rows_ride_next_commit(tmp_path, rng):
    """End-to-end: an injected delta-file write failure fires on_fail,
    the dirty set comes back, and a fresh manager commits those rows in
    the full rebase."""
    t = _mk_table()
    _touch(t, rng)
    cm = CheckpointManager(str(tmp_path), async_save=False)
    _commit(cm, t, 1, "full")
    ids = _touch(t, rng)
    fi.configure("ckpt.delta@1=error")
    try:
        tok, st = t.export_delta()
        with pytest.raises(InjectedFault):
            cm.save(2, _scope_of(st), blocking=True, kind="delta",
                    on_commit=lambda info, tk=tok: t.commit_delta(tk),
                    on_fail=lambda exc, tk=tok: t.retract_delta(tk))
    finally:
        fi.clear()
    assert t.dirty_rows == len(ids)
    # failed write killed the chain: the policy's next commit is full
    assert not cm.chain_stats()["alive"]
    _commit(cm, t, 2, "full")
    assert t.dirty_rows == 0
    _, t2, _ = _restore_table(CheckpointManager(str(tmp_path)))
    assert _state_sha(t.export_state_vars()) == \
        _state_sha(t2.export_state_vars())


# ---------------------------------------------------------------------------
# Checkpointer policy (rebase caps) + async pipeline
# ---------------------------------------------------------------------------
def _mk_checkpointer(tmp_path, sess, **kw):
    from paddle_tpu.train_state import Checkpointer

    class _Exe:
        _step = 0
    return Checkpointer(str(tmp_path), _Exe(), handle_signals=False,
                        delta_source=sess, **kw)


def test_checkpointer_policy_full_then_deltas_then_rebase(tmp_path, rng):
    from paddle_tpu.train_state import DeltaPolicy
    t = _mk_table()
    # a big base keeps each ~10-row delta far under rebase_fraction, so
    # the ONLY rebase trigger in this run is the max_chain cap
    t.push(np.arange(VOCAB, dtype=np.int64),
           rng.randn(VOCAB, DIM).astype(np.float32))
    sess = SparseSession(t)
    ck = _mk_checkpointer(tmp_path, sess,
                          delta=DeltaPolicy(max_chain=2), max_to_keep=10)
    scope = pt.Scope()
    scope.set("w", np.zeros(256, np.float32))
    ck.begin(scope, None, 0, {})
    kinds = []
    for step in range(1, 6):
        _touch(t, rng, n=10)
        ck.emitted = step
        ck._save(0, 0, blocking=True)
        with open(tmp_path / f"ckpt-{step}" / "meta.json") as f:
            kinds.append(json.load(f)["kind"])
    # chain caps at max_chain=2 deltas, then a full rebase starts anew
    assert kinds == ["full", "delta", "delta", "full", "delta"]
    assert t.dirty_rows == 0
    snap = pt.observability.registry().snapshot()
    assert snap["checkpoint/rebase_total"]["value"] >= 1
    assert snap["checkpoint/delta_rows"]["value"] > 0
    _, t2, _ = _restore_table(CheckpointManager(str(tmp_path)))
    assert _state_sha(t.export_state_vars()) == \
        _state_sha(t2.export_state_vars())


def test_async_pipeline_commits_in_order_and_acks_late(tmp_path, rng):
    """Async double-buffered commits: several queued deltas land in
    order, wait() drains, and every token acks (dirty set empty)."""
    t = _mk_table()
    cm = CheckpointManager(str(tmp_path), async_save=True)
    _touch(t, rng)
    tok, st = t.export_full()
    cm.save(1, _scope_of(st), kind="full",
            on_commit=lambda info, tk=tok: t.commit_delta(tk),
            on_fail=lambda exc, tk=tok: t.retract_delta(tk))
    for step in (2, 3, 4):
        _touch(t, rng)
        tok, st = t.export_delta()
        cm.save(step, _scope_of(st), kind="delta",
                on_commit=lambda info, tk=tok: t.commit_delta(tk),
                on_fail=lambda exc, tk=tok: t.retract_delta(tk))
    cm.wait()
    assert t.dirty_rows == 0
    metas = []
    for step in (1, 2, 3, 4):
        with open(tmp_path / f"ckpt-{step}" / "meta.json") as f:
            metas.append(json.load(f))
    assert [m["kind"] for m in metas] == ["full"] + ["delta"] * 3
    for child, parent in zip(metas[1:], metas[:-1]):
        assert child["parent"] == parent["content_hash"]
    _, t2, _ = _restore_table(CheckpointManager(str(tmp_path)))
    assert _state_sha(t.export_state_vars()) == \
        _state_sha(t2.export_state_vars())


def test_gc_pins_delta_ancestors_until_rebase(tmp_path, rng):
    """max_to_keep counts steps, but a kept delta tip pins its whole
    ancestor chain; the chain frees once no kept tip references it."""
    t = _mk_table()
    cm = CheckpointManager(str(tmp_path), async_save=False, max_to_keep=2)
    _touch(t, rng)
    _commit(cm, t, 1, "full")
    for step in (2, 3, 4):
        _touch(t, rng)
        _commit(cm, t, step, "delta")
    assert cm.all_steps() == [1, 2, 3, 4]       # tip 4 pins 1-3
    _touch(t, rng)
    _commit(cm, t, 5, "full")
    assert cm.all_steps() == [1, 2, 3, 4, 5]    # kept tip 4 still pins
    _touch(t, rng)
    _commit(cm, t, 6, "full")
    assert cm.all_steps() == [5, 6]             # chain finally freed


# ---------------------------------------------------------------------------
# Torn-delta durability (satellite b, fast half)
# ---------------------------------------------------------------------------
def test_truncated_delta_falls_back_to_durable_prefix(tmp_path, rng):
    """ckpt.delta truncate tears a delta file AFTER its md5 is recorded:
    chain verification must reject the whole tip and restore the previous
    durable commit exactly."""
    t = _mk_table()
    cm = CheckpointManager(str(tmp_path), async_save=False)
    _touch(t, rng)
    _commit(cm, t, 1, "full")
    _touch(t, rng)
    _commit(cm, t, 2, "delta")
    oracle = _state_sha(t.export_state_vars())  # durable prefix = step 2
    _touch(t, rng)
    fi.configure("ckpt.delta@1=truncate")
    try:
        _commit(cm, t, 3, "delta")
        assert fi.fired("ckpt.delta") == 1
    finally:
        fi.clear()
    before = pt.observability.registry().snapshot()[
        "fault/checkpoint_fallbacks"]["value"]
    restored, t2, _ = _restore_table(CheckpointManager(str(tmp_path)))
    assert restored == 2
    assert _state_sha(t2.export_state_vars()) == oracle
    after = pt.observability.registry().snapshot()[
        "fault/checkpoint_fallbacks"]["value"]
    assert after - before == 1


# ---------------------------------------------------------------------------
# Kill mid-chain (satellite b, @slow chaos round)
# ---------------------------------------------------------------------------
_CHILD = textwrap.dedent("""
    import hashlib, json, os, sys
    import numpy as np
    os.environ["JAX_PLATFORMS"] = "cpu"
    import paddle_tpu as pt
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.sparse import SparseTable
    from paddle_tpu.testing import faultinject

    root, acked, spec = sys.argv[1], sys.argv[2], sys.argv[3]
    if spec:
        faultinject.configure(spec)
    rng = np.random.RandomState(7)
    t = SparseTable("emb", 2000, 6, optimizer="adagrad",
                    learning_rate=0.1, num_shards=3, seed=11)
    cm = CheckpointManager(root, async_save=False, max_to_keep=10)
    w = np.zeros(4096, np.float32)

    def sha(state, w):
        h = hashlib.sha256()
        for k in sorted(state):
            a = np.ascontiguousarray(np.asarray(state[k]))
            h.update(k.encode()); h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode()); h.update(a.tobytes())
        h.update(np.asarray(w, np.float32).tobytes())
        return h.hexdigest()

    for step in range(1, 9):
        ids = np.unique(rng.randint(0, 2000, 40).astype(np.int64))
        t.push(ids, rng.randn(len(ids), 6).astype(np.float32))
        w[(step * 13) % 4096] += 1.0
        kind = "full" if step == 1 else "delta"
        tok, st = t.export_full() if kind == "full" else t.export_delta()
        sc = pt.Scope()
        for k, v in st.items():
            sc.set(k, v)
        sc.set("w", w.copy())
        cm.save(step, sc, blocking=True, kind=kind,
                on_commit=lambda info, tk=tok: t.commit_delta(tk),
                on_fail=lambda exc, tk=tok: t.retract_delta(tk))
        # the save returned -> this commit is DURABLE: record the acked
        # oracle atomically so the on-disk acked file enumerates exactly
        # the commits restore may land on
        tmp = acked + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": step,
                       "sha": sha(t.export_state_vars(), w)}, f)
            f.flush(); os.fsync(f.fileno())
        os.replace(tmp, acked)
        dfd = os.open(os.path.dirname(acked), os.O_RDONLY)
        os.fsync(dfd); os.close(dfd)
    print("DONE", flush=True)
""")


def _run_child(tmp_path, spec):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.pop("PADDLE_TPU_METRICS_LOG", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    root = str(tmp_path / "ckpt")
    acked = str(tmp_path / "acked.json")
    child = tmp_path / "child.py"
    child.write_text(_CHILD)
    proc = subprocess.run(
        [sys.executable, str(child), root, acked, spec],
        env=env, capture_output=True, text=True, timeout=300)
    return proc, root, acked


@pytest.mark.slow
@pytest.mark.timeout(360)
def test_kill_mid_chain_restores_last_acked_commit(tmp_path):
    """SIGKILL while a delta's files are being written: the survivor
    restores EXACTLY the newest commit whose save() had returned in the
    child — sha256 over rows+slots+dense vs the acked oracle."""
    # each delta commit writes ~10 delta files (3 shards x ids/rows/
    # moment + the dense patch) — index 25 kills inside the 3rd delta
    proc, root, acked = _run_child(tmp_path, "ckpt.delta@25=kill")
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    assert "DONE" not in proc.stdout            # it really died mid-run
    with open(acked) as f:
        oracle = json.load(f)
    assert oracle["step"] >= 2                  # died with deltas on disk
    cm = CheckpointManager(root, async_save=False)
    sc = pt.Scope()
    restored = cm.restore(scope=sc)
    assert restored == oracle["step"]
    t2 = SparseTable("emb", 2000, 6, optimizer="adagrad",
                     learning_rate=0.1, num_shards=3, seed=11)
    state = {k: np.asarray(sc.get(k)) for k in sc.keys()
             if k.startswith("__sparse__/")}
    t2.restore_state_vars(state)
    h = hashlib.sha256()
    st = t2.export_state_vars()
    for k in sorted(st):
        a = np.ascontiguousarray(np.asarray(st[k]))
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(np.asarray(sc.get("w"), np.float32).tobytes())
    assert h.hexdigest() == oracle["sha"]
    # and the survivor can keep chaining from the adopted tip
    _touch(t2, np.random.RandomState(0))
    _commit(cm, t2, restored + 1, "delta")
