"""Local SGD (async-SGD successor) tests: K=1 equals synchronous data
parallelism; K>1 drifts locally between syncs but converges; replicas agree
after every sync (reference capability: ParameterServer2.h:468 asyncSGD)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.parallel.local_sgd import make_local_sgd_step

R = np.random.RandomState(11)
D = 8
N = 4


def _loss(params, x, y):
    pred = jnp.tanh(x @ params["w"]) @ params["v"]
    return jnp.mean((pred - y) ** 2)


def _init():
    return {"w": jnp.asarray(R.randn(D, D).astype("float32") * 0.4),
            "v": jnp.asarray(R.randn(D, 1).astype("float32") * 0.4)}


def test_k1_matches_synchronous_dp():
    """sync_every=1 == classic synchronous data parallelism (grad pmean):
    for plain SGD, averaging post-update params equals averaging grads."""
    mesh = make_mesh(MeshConfig(dp=N), devices=jax.devices()[:N])
    params = _init()
    x = R.randn(16, D).astype("float32")
    y = R.randn(16, 1).astype("float32")
    lr = 0.05

    step = make_local_sgd_step(_loss, mesh, sync_every=1, learning_rate=lr)
    p_local = jax.tree.map(jnp.copy, params)
    for _ in range(4):
        p_local, lv = step(p_local, x, y)

    # reference: synchronous dp == full-batch gradient on the mean loss
    p_ref = jax.tree.map(jnp.copy, params)
    for _ in range(4):
        shard_losses = []
        grads = []
        for i in range(N):
            xs, ys = x[i*4:(i+1)*4], y[i*4:(i+1)*4]
            l, g = jax.value_and_grad(_loss)(p_ref, xs, ys)
            grads.append(g)
        gmean = jax.tree.map(lambda *gs: sum(gs) / N, *grads)
        p_ref = jax.tree.map(lambda p, g: p - 0.05 * g, p_ref, gmean)

    for k in params:
        np.testing.assert_allclose(np.asarray(p_local[k]),
                                   np.asarray(p_ref[k]),
                                   rtol=1e-5, atol=1e-6)


def test_local_sgd_k4_trains_and_synchronizes():
    mesh = make_mesh(MeshConfig(dp=N), devices=jax.devices()[:N])
    params = _init()
    # learnable task: y = x @ w* (one shared linear target)
    w_star = R.randn(D, 1).astype("float32")
    x = R.randn(64, D).astype("float32")
    y = (x @ w_star).astype("float32")

    step = make_local_sgd_step(_loss, mesh, sync_every=4,
                               learning_rate=0.05)
    losses = []
    for _ in range(12):
        params, lv = step(params, x, y)
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.6
    # post-sync params are replicated: every device shard identical
    for leaf in jax.tree.leaves(params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)
