"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh;
the same kernel compiles for the MXU on TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_kernels import (_reference_attention,
                                           flash_attention)

R = np.random.RandomState(4)


def _ref(q, k, v, causal, scale):
    return np.asarray(_reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal, scale))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    BH, T, D = 2, 128, 32
    q = R.randn(BH, T, D).astype("float32")
    k = R.randn(BH, T, D).astype("float32")
    v = R.randn(BH, T, D).astype("float32")
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=causal, block_q=64, block_k=64,
                          use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _ref(q, k, v, causal, D ** -0.5),
                               atol=2e-5, rtol=2e-5)


def test_flash_bhtd_layout():
    B, T, H, D = 2, 64, 4, 16
    q = R.randn(B, T, H, D).astype("float32")
    k = R.randn(B, T, H, D).astype("float32")
    v = R.randn(B, T, H, D).astype("float32")
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          block_q=64, block_k=64, use_pallas=True,
                          interpret=True)
    assert out.shape == (B, T, H, D)
    # per-head equivalence
    for h in range(H):
        np.testing.assert_allclose(
            np.asarray(out[:, :, h]),
            _ref(q[:, :, h].transpose(0, 1, 2), k[:, :, h], v[:, :, h],
                 False, D ** -0.5), atol=2e-5, rtol=2e-5)


def test_flash_gradient_matches_reference():
    BH, T, D = 1, 64, 16
    q = jnp.asarray(R.randn(BH, T, D).astype("float32"))
    k = jnp.asarray(R.randn(BH, T, D).astype("float32"))
    v = jnp.asarray(R.randn(BH, T, D).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32,
                                       use_pallas=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, False, D ** -0.5) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_cross_attention_kernel():
    """Tq != Tk and Dv != Dq run through the kernel itself (encoder-decoder
    attention): the key-block count must come from K's length and the output
    feature dim from V's."""
    BH, Tq, Tk, D, Dv = 2, 64, 128, 16, 32
    q = R.randn(BH, Tq, D).astype("float32")
    k = R.randn(BH, Tk, D).astype("float32")
    v = R.randn(BH, Tk, Dv).astype("float32")
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          block_q=64, block_k=64, use_pallas=True,
                          interpret=True)
    assert out.shape == (BH, Tq, Dv)
    np.testing.assert_allclose(np.asarray(out),
                               _ref(q, k, v, False, D ** -0.5),
                               atol=2e-5, rtol=2e-5)


def test_flash_causal_cross_falls_back():
    BH, Tq, Tk, D = 1, 64, 128, 16
    q = R.randn(BH, Tq, D).astype("float32")
    k = R.randn(BH, Tk, D).astype("float32")
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(k),
                          causal=True, block_q=64, block_k=64,
                          use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _ref(q, k, k, True, D ** -0.5),
                               atol=2e-5, rtol=2e-5)


def test_flash_ragged_tail_falls_back():
    BH, T, D = 1, 100, 16     # not a block multiple
    q = R.randn(BH, T, D).astype("float32")
    out = flash_attention(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
                          block_q=64, block_k=64, use_pallas=True,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               _ref(q, q, q, False, D ** -0.5),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradient_kernel_paths(causal):
    """Gradients flow through the fused Pallas dq and dk/dv kernels (not a
    jnp recompute): multi-block grids in both q and k so block accumulation,
    lse residuals, and causal block-skipping are all exercised."""
    BH, T, D = 2, 128, 16
    q = jnp.asarray(R.randn(BH, T, D).astype("float32"))
    k = jnp.asarray(R.randn(BH, T, D).astype("float32"))
    v = jnp.asarray(R.randn(BH, T, D).astype("float32"))
    w = jnp.asarray(R.randn(BH, T, D).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(w * flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32,
            use_pallas=True, interpret=True))

    def loss_ref(q, k, v):
        return jnp.sum(w * _reference_attention(q, k, v, causal, D ** -0.5))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_gradient_cross_attention():
    """Tq != Tk and Dv != D through the backward kernels."""
    BH, Tq, Tk, D, Dv = 1, 64, 128, 16, 32
    q = jnp.asarray(R.randn(BH, Tq, D).astype("float32"))
    k = jnp.asarray(R.randn(BH, Tk, D).astype("float32"))
    v = jnp.asarray(R.randn(BH, Tk, Dv).astype("float32"))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32,
                                       use_pallas=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, False, D ** -0.5) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)
