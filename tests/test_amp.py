"""bf16 mixed-precision (AMP) tests — a NEW TPU-first capability beyond the
reference (its nearest analog is float16.h storage, math/float16.h, never
wired into training)."""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _mlp(rng):
    x = layers.data("x", shape=[16], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    pred = layers.fc(h, size=10, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    feeds = {"x": rng.rand(16, 16).astype("float32"),
             "y": rng.randint(0, 10, (16, 1))}
    return loss, feeds


def test_amp_training_converges_and_keeps_fp32_master(rng):
    loss, feeds = _mlp(rng)
    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    vals = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
            for _ in range(20)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0] * 0.7, vals
    # master weights and optimizer moments stay fp32
    scope = pt.global_scope()
    for p in pt.default_main_program().all_parameters():
        assert scope.get(p.name).dtype == jnp.float32, p.name


def test_amp_tracks_fp32_loss(rng):
    loss, feeds = _mlp(rng)
    prog = pt.default_main_program()
    exe32 = pt.Executor()
    exe32.run(pt.default_startup_program(), feed={}, fetch_list=[])
    ref = [float(exe32.run(prog, feed=feeds, fetch_list=[loss])[0])
           for _ in range(5)]

    pt.core.reset_global_scope()
    exe16 = pt.Executor(amp=True)
    exe16.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe16._step = 0
    got = [float(exe16.run(prog, feed=feeds, fetch_list=[loss])[0])
           for _ in range(5)]
    # bf16 has ~3 decimal digits; trajectories must agree loosely
    np.testing.assert_allclose(ref, got, rtol=0.05)


def test_amp_inference(rng):
    x = layers.data("x", shape=[8], dtype="float32")
    pred = layers.fc(x, size=4, act="softmax")
    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (out,) = exe.run(feed={"x": rng.rand(4, 8).astype("float32")},
                     fetch_list=[pred], is_test=True)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    np.testing.assert_allclose(np.asarray(out, np.float32).sum(-1), 1.0,
                               atol=2e-2)
