"""MoE layer tests (CPU mesh): top-k capacity routing semantics, and the
expert-parallel all-to-all path (ep=4) matching the single-device MoE
bit-for-bit."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import MeshConfig, make_mesh
from paddle_tpu.parallel.moe import (load_balancing_loss, moe_dispatch,
                                     moe_ffn)

R = np.random.RandomState(3)


def test_dispatch_topk_and_capacity():
    """Hand-checkable routing: 3 tokens, 2 experts, capacity 1, top-1 —
    the second token routed to a full expert is dropped."""
    gates = jnp.asarray([[0.9, 0.1],
                         [0.8, 0.2],
                         [0.3, 0.7]], jnp.float32)
    dispatch, combine = moe_dispatch(gates, capacity=1, top_k=1)
    d = np.asarray(dispatch)
    # token0 -> expert0 slot0; token1 dropped (expert0 full); token2 ->
    # expert1 slot0
    assert d[0, 0, 0] == 1 and d[2, 1, 0] == 1
    assert d.sum() == 2 and d[1].sum() == 0
    c = np.asarray(combine)
    np.testing.assert_allclose(c[0, 0, 0], 0.9, rtol=1e-6)
    np.testing.assert_allclose(c[2, 1, 0], 0.7, rtol=1e-6)


def test_dispatch_top2_uses_two_experts():
    gates = jnp.asarray([[0.6, 0.3, 0.1]], jnp.float32)
    dispatch, combine = moe_dispatch(gates, capacity=2, top_k=2)
    d = np.asarray(dispatch)
    assert d[0, 0].sum() == 1 and d[0, 1].sum() == 1 and d[0, 2].sum() == 0
    c = np.asarray(combine).sum(axis=2)[0]
    np.testing.assert_allclose(c, [0.6, 0.3, 0.0], rtol=1e-6)


def _params(E, D, H):
    gate_w = jnp.asarray(R.randn(D, E).astype("float32") * 0.5)
    w1 = jnp.asarray(R.randn(E, D, H).astype("float32") * 0.3)
    w2 = jnp.asarray(R.randn(E, H, D).astype("float32") * 0.3)
    return gate_w, w1, w2


def test_expert_parallel_matches_single_device():
    """ep=4 all-to-all MoE == single-device MoE on the same tokens: the
    dispatch/FFN/combine pipeline survives the two device hops exactly."""
    from jax.experimental.shard_map import shard_map

    EP, E, D, H, T = 4, 4, 8, 16, 16
    mesh = make_mesh(MeshConfig(ep=EP), devices=jax.devices()[:EP])
    gate_w, w1, w2 = _params(E, D, H)
    x = jnp.asarray(R.randn(T, D).astype("float32"))

    ref_out, ref_aux = moe_ffn(x, gate_w, w1, w2, axis_name=None,
                               top_k=2, capacity_factor=8.0)

    def per_device(x, gate_w, w1, w2):
        out, aux = moe_ffn(x, gate_w, w1, w2, axis_name="ep", top_k=2,
                           capacity_factor=8.0)
        return out, jax.lax.pmean(aux, "ep")

    f = shard_map(per_device, mesh=mesh,
                  in_specs=(P(), P(), P("ep"), P("ep")),
                  out_specs=(P(), P()), check_rep=False)
    out, aux = jax.jit(f)(x, gate_w, w1, w2)
    # every device routed the SAME tokens (x replicated), so per-device
    # output equals the single-device result
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)


def test_moe_trains_and_balances():
    """Gradients flow through dispatch/all_to_all/combine; training with the
    aux loss reduces total loss on a learnable mixture task."""
    from jax.experimental.shard_map import shard_map

    EP, E, D, H, T = 4, 4, 8, 16, 32
    mesh = make_mesh(MeshConfig(ep=EP), devices=jax.devices()[:EP])
    gate_w, w1, w2 = _params(E, D, H)
    x = jnp.asarray(R.randn(T, D).astype("float32"))
    y = jnp.asarray(R.randn(T, D).astype("float32") * 0.1)

    def loss_fn(params, x, y):
        gate_w, w1, w2 = params

        def per_device(x, y, gate_w, w1, w2):
            out, aux = moe_ffn(x, gate_w, w1, w2, axis_name="ep",
                               top_k=2, capacity_factor=4.0)
            return (jnp.mean((out - y) ** 2) +
                    0.01 * jax.lax.pmean(aux, "ep"))

        f = shard_map(per_device, mesh=mesh,
                      in_specs=(P(), P(), P(), P("ep"), P("ep")),
                      out_specs=P(), check_rep=False)
        return f(x, y, gate_w, w1, w2)

    params = (gate_w, w1, w2)
    step = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(25):
        lv, g = step(params, x, y)
        params = jax.tree.map(lambda p, gr: p - 0.1 * gr, params, g)
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9
    # expert weights received non-zero gradients (all-to-all is in the
    # gradient path)
    _, gw1, _ = g
    assert float(jnp.abs(gw1).sum()) > 0
