"""Pipeline parallelism tests (CPU mesh): per-stage parameters sharded on
'pp', GPipe microbatched training matching a single-device reference
(reference capability: ParallelNeuralNetwork.cpp per-layer device placement
with queue-pipelined activations)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel import MeshConfig, make_mesh, pipeline

S = 4          # stages
D = 16
M = 4          # microbatches
B = 8          # global batch

R = np.random.RandomState(7)


def _stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss(out, y):
    return jnp.mean((out - y) ** 2)


def _init_stages():
    return [{"w": jnp.asarray(R.randn(D, D).astype("float32") * 0.3),
             "b": jnp.asarray(R.randn(D).astype("float32") * 0.1)}
            for _ in range(S)]


def _reference_train(stages, x, y, lr, mom, steps):
    """Single-device reference: sequential 4-layer net, same SGD."""
    params = jax.tree.map(jnp.asarray, stages)
    vel = jax.tree.map(jnp.zeros_like, params)

    def loss_fn(params, x, y):
        h = x
        for p in params:
            h = _stage(p, h)
        return _loss(h, y)

    losses = []
    for _ in range(steps):
        lv, g = jax.value_and_grad(loss_fn)(params, x, y)
        vel = jax.tree.map(lambda v, gr: mom * v + gr, vel, g)
        params = jax.tree.map(lambda p, v: p - lr * v, params, vel)
        losses.append(float(lv))
    return params, losses


@pytest.mark.parametrize("remat", [False, True])
def test_pipeline_training_matches_single_device(remat):
    """pp=4 GPipe training == the sequential single-device run: same per-step
    losses and (bitwise-close) final per-stage weights."""
    mesh = make_mesh(MeshConfig(pp=S), devices=jax.devices()[:S])
    stages = _init_stages()
    x = R.randn(B, D).astype("float32")
    y = R.randn(B, D).astype("float32")
    lr, mom, steps = 0.1, 0.9, 5

    ref_params, ref_losses = _reference_train(stages, x, y, lr, mom, steps)

    params = pipeline.place_stage_params(
        pipeline.stack_stage_params(*stages), mesh)
    vel = jax.tree.map(jnp.zeros_like, params)
    step = pipeline.make_pipeline_train_step(
        _stage, _loss, mesh, num_microbatches=M, learning_rate=lr,
        momentum=mom, remat=remat)
    losses = []
    for _ in range(steps):
        params, vel, lv = step(params, vel, x, y)
        losses.append(float(lv))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    for i in range(S):
        np.testing.assert_allclose(
            np.asarray(params["w"][i]), np.asarray(ref_params[i]["w"]),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(params["b"][i]), np.asarray(ref_params[i]["b"]),
            rtol=1e-5, atol=1e-6)


def test_stage_params_actually_sharded():
    """Each device holds exactly its own stage slice of the stacked params
    (addressable shard shape [1, D, D]) — the memory-scaling property the
    round-2 scaffold lacked."""
    mesh = make_mesh(MeshConfig(pp=S), devices=jax.devices()[:S])
    params = pipeline.place_stage_params(
        pipeline.stack_stage_params(*_init_stages()), mesh)
    w = params["w"]
    assert w.shape == (S, D, D)
    shards = w.addressable_shards
    assert len(shards) == S
    for sh in shards:
        assert sh.data.shape == (1, D, D)


def test_pipeline_forward_heterogeneous_switch():
    """lax.switch adapter: heterogeneous per-stage callables (different
    param pytrees) still pipeline; output matches sequential composition."""
    from jax.experimental.shard_map import shard_map

    mesh = make_mesh(MeshConfig(pp=S), devices=jax.devices()[:S])
    fns = [lambda p, x: jnp.tanh(x @ p["w"]),
           lambda p, x: x * p["scale"] + p["shift"],
           lambda p, x: jnp.tanh(x @ p["w"]),
           lambda p, x: x + p["bias"]]
    ps = [{"w": jnp.asarray(R.randn(D, D).astype("float32") * 0.3)},
          {"scale": jnp.float32(1.5), "shift": jnp.float32(0.1)},
          {"w": jnp.asarray(R.randn(D, D).astype("float32") * 0.3)},
          {"bias": jnp.asarray(R.randn(D).astype("float32"))}]
    sfn = pipeline.switch_stage_fn(fns, ps)
    xs = R.randn(M, 2, D).astype("float32")
    dummy = jnp.zeros((S, 1))

    pipe = shard_map(
        lambda w, x: pipeline.pipeline_forward(sfn, w, x, axis_name="pp"),
        mesh=mesh, in_specs=(P("pp"), P()), out_specs=P())
    outs = np.asarray(jax.jit(pipe)(dummy, xs))

    h = xs
    for f, p in zip(fns, ps):
        h = jax.vmap(lambda xx: f(p, xx))(h)
    np.testing.assert_allclose(outs, np.asarray(h), rtol=1e-5, atol=1e-5)
