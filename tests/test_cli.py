"""CLI tests (TrainerMain.cpp analog): train/test/time/checkgrad jobs run a
REFERENCE v1 config end to end through ``python -m paddle_tpu``."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

CONF = "/root/reference/paddle/gserver/tests/sequence_rnn.conf"
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")


def _run(*argv, timeout=240):
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", *argv],
                       capture_output=True, text=True, timeout=timeout,
                       env=ENV, cwd="/root/repo")
    return r


def _json_lines(out):
    lines = []
    for ln in out.splitlines():
        try:
            lines.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    return lines


@pytest.mark.slow
def test_cli_train_saves_and_test_loads(tmp_path):
    """@slow: two full `python -m paddle_tpu` subprocesses against the
    REFERENCE v1 config (~10-15s of jax import per round on this
    container); the train/test job wiring stays tier-1-covered
    in-process by tests/test_graft_entry.py's config-build round and
    tests/test_trainer.py."""
    save = str(tmp_path / "model")
    r = _run("--config", CONF, "--job", "train", "--num_passes", "2",
             "--steps_per_pass", "5", "--save_dir", save)
    assert r.returncode == 0, r.stderr
    recs = _json_lines(r.stdout)
    assert len(recs) == 2
    assert recs[1]["mean_loss"] < recs[0]["mean_loss"]
    assert os.path.exists(os.path.join(save, "pass-00001"))

    r2 = _run("--config", CONF, "--job", "test",
              "--init_model_path", os.path.join(save, "pass-00001"))
    assert r2.returncode == 0, r2.stderr
    outs = _json_lines(r2.stdout)
    assert outs and np.isfinite(outs[0]["mean"])


@pytest.mark.slow
def test_cli_time(tmp_path):
    """@slow: one jax-importing subprocess round (REFERENCE v1 config)."""
    r = _run("--config", CONF, "--job", "time", "--iters", "8",
             )
    assert r.returncode == 0, r.stderr
    rec = _json_lines(r.stdout)[-1]
    assert rec["ms_per_batch"] > 0 and rec["batches_per_sec"] > 0


@pytest.mark.slow
def test_cli_checkgrad():
    """@slow: one jax-importing subprocess round (REFERENCE v1 config)."""
    r = _run("--config", CONF, "--job", "checkgrad")
    assert r.returncode == 0, r.stderr + r.stdout
    recs = _json_lines(r.stdout)
    final = recs[-1]
    assert final["checkgrad"] == "PASS"
    # the probe loop actually ran, at the f64-instrument tolerance: per-
    # parameter comparison lines with tight numeric/autodiff agreement
    probes = [x for x in recs if "autodiff" in x]
    assert len(probes) >= 3, recs
    for p in probes:
        assert p["ok"]
        assert abs(p["numeric"] - p["autodiff"]) <= 1e-3 * max(
            1.0, abs(p["numeric"]), abs(p["autodiff"]))


@pytest.mark.slow
def test_cli_start_pass_resume(tmp_path):
    """--save_dir + --init_model_path + --start_pass: train 1 pass, resume
    from its checkpoint at pass 1 (Flags.cpp:81 resume semantics).

    @slow: two full `python -m paddle_tpu` subprocesses (~11 s of jax
    import on this container) against a tier-1 budget that is ~98% full;
    resume semantics stay tier-1-covered in-process by
    tests/test_fault_tolerance.py's kill-and-resume bit-identity matrix
    (the same save/restore machinery, deeper assertions)."""
    cfg = tmp_path / "conf.py"
    cfg.write_text(
        "from paddle_tpu.trainer_config_helpers import *\n"
        "settings(batch_size=8, learning_rate=0.1)\n"
        "x = data_layer('x', 4)\n"
        "y = data_layer('label', 2)\n"
        "h = fc_layer(input=x, size=8, act=ReluActivation())\n"
        "out = fc_layer(input=h, size=2, act=SoftmaxActivation())\n"
        "outputs(classification_cost(input=out, label=y))\n")
    def run(*extra):
        out = _run(f"--config={cfg}", "--job=train", "--steps_per_pass=3",
                   "--batch=8", *extra)
        assert out.returncode == 0, out.stderr[-800:]
        return _json_lines(out.stdout)

    d = tmp_path / "saves"
    first = run("--num_passes=1", f"--save_dir={d}")
    assert first[0]["pass"] == 0 and (d / "pass-00000").is_dir()
    second = run("--num_passes=3", "--start_pass=1",
                 f"--init_model_path={d / 'pass-00000'}",
                 f"--save_dir={d}")
    assert [r["pass"] for r in second] == [1, 2]
    assert (d / "pass-00002").is_dir()
    # resumed training continues from the saved weights: loss keeps falling
    assert second[-1]["mean_loss"] < first[0]["mean_loss"]
    # start_pass past num_passes is a usage error, not a silent no-op
    bad = _run(f"--config={cfg}", "--job=train", "--start_pass=1",
               "--batch=8")
    assert bad.returncode != 0 and "total" in bad.stderr
