"""CLI tests (TrainerMain.cpp analog): train/test/time/checkgrad jobs run a
REFERENCE v1 config end to end through ``python -m paddle_tpu``."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

CONF = "/root/reference/paddle/gserver/tests/sequence_rnn.conf"
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")


def _run(*argv, timeout=240):
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", *argv],
                       capture_output=True, text=True, timeout=timeout,
                       env=ENV, cwd="/root/repo")
    return r


def _json_lines(out):
    lines = []
    for ln in out.splitlines():
        try:
            lines.append(json.loads(ln))
        except json.JSONDecodeError:
            continue
    return lines


def test_cli_train_saves_and_test_loads(tmp_path):
    save = str(tmp_path / "model")
    r = _run("--config", CONF, "--job", "train", "--num_passes", "2",
             "--steps_per_pass", "5", "--save_dir", save)
    assert r.returncode == 0, r.stderr
    recs = _json_lines(r.stdout)
    assert len(recs) == 2
    assert recs[1]["mean_loss"] < recs[0]["mean_loss"]
    assert os.path.exists(os.path.join(save, "pass-00001"))

    r2 = _run("--config", CONF, "--job", "test",
              "--init_model_path", os.path.join(save, "pass-00001"))
    assert r2.returncode == 0, r2.stderr
    outs = _json_lines(r2.stdout)
    assert outs and np.isfinite(outs[0]["mean"])


def test_cli_time(tmp_path):
    r = _run("--config", CONF, "--job", "time", "--iters", "8",
             )
    assert r.returncode == 0, r.stderr
    rec = _json_lines(r.stdout)[-1]
    assert rec["ms_per_batch"] > 0 and rec["batches_per_sec"] > 0


def test_cli_checkgrad():
    r = _run("--config", CONF, "--job", "checkgrad")
    assert r.returncode == 0, r.stderr + r.stdout
    final = _json_lines(r.stdout)[-1]
    assert final["checkgrad"] == "PASS"
