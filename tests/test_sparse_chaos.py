"""Chaos rounds for the sparse overlap legs (ISSUE 15 satellite): REAL
training processes with pull-ahead prefetch + bounded async push killed
under load, then relaunched.

The claim pinned here: the flush barrier in the checkpoint-export path
means a COMMITTED checkpoint contains every acknowledged push — so a
SIGKILL'd or SIGTERM'd run, relaunched with the identical command,
converges to a final table state byte-identical to the uninterrupted
run (batches touch disjoint ids, so the replayed overlap after resume
is deterministic; a single lost acked push would surface as a stale
row in the final-state hash).

Subprocess-driven (fresh jax import apiece) => ``@pytest.mark.slow``
per the PR 6 convention; every subprocess call carries a hard
``timeout=``.  The fast in-process subset (flush-barrier visibility,
export atomicity, error propagation) is tier-1 in
tests/test_sparse_vectorized.py.
"""
import hashlib
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.faults import EXIT_PREEMPTED

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUN_TIMEOUT = 180

# 24 disjoint-id batches over a 96-row vocab; Adagrad slots + the per-id
# Philox lazy init both ride the checkpoint.  After training, the table
# is saved standalone and DONE printed — the save directory's bytes are
# the comparison artifact.
TRAIN_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.sparse import SparseSession, SparseTable

ckpt_dir, table_out = sys.argv[1], sys.argv[2]
pt.default_main_program().random_seed = 42
pt.default_startup_program().random_seed = 42
ids = layers.data("ids", shape=[1], dtype="int64")
label = layers.data("label", shape=[1], dtype="float32")
emb = layers.embedding(ids, size=[96, 6], sparse=True, name="tbl")
fc = layers.fc(emb, size=1)
loss = layers.mean(layers.square(fc - label))
opt = pt.optimizer.Adagrad(learning_rate=0.1)
tr = pt.trainer.SGD(cost=loss, update_equation=opt)

table = SparseTable("tbl", 96, 6, optimizer="adagrad",
                    learning_rate=0.1, num_shards=3, seed=5)
sess = SparseSession(table, prefetch_depth=2, async_push=2,
                     push_flush_batch=2)

def reader():
    rng = np.random.RandomState(7)
    for b in range(24):
        lo = (b * 4) % 96
        yield [(np.array([lo + j], np.int64),
                rng.rand(1).astype("float32")) for j in range(4)]

tr.train(reader, num_passes=1, sparse_tables=sess,
         checkpoint_dir=ckpt_dir, resume=True, save_every_n_steps=4)
table.save(table_out)
print("DONE", flush=True)
"""


def _dir_digest(dirname):
    h = hashlib.sha256()
    for name in sorted(os.listdir(dirname)):
        with open(os.path.join(dirname, name), "rb") as fh:
            h.update(name.encode())
            h.update(fh.read())
    return h.hexdigest()


def _run(ckpt, out, env_extra=None, timeout=RUN_TIMEOUT):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-c", TRAIN_SCRIPT.format(repo=REPO),
         str(ckpt), str(out)],
        capture_output=True, text=True, timeout=timeout, env=env)


@pytest.mark.timeout(600)
def test_sigkill_under_async_push_resumes_bit_identical(tmp_path):
    # uninterrupted baseline
    p = _run(tmp_path / "ck_ref", tmp_path / "tbl_ref")
    assert p.returncode == 0 and "DONE" in p.stdout, p.stderr[-2000:]
    want = _dir_digest(tmp_path / "tbl_ref")

    # SIGKILL at global batch 14 (mid-pass, after periodic saves, with
    # pushes possibly still queued on the async worker)
    p = _run(tmp_path / "ck", tmp_path / "tbl",
             env_extra={"PADDLE_TPU_FAULT_SPEC": "trainer.step@14=kill"})
    assert p.returncode == -signal.SIGKILL, (p.returncode,
                                             p.stderr[-2000:])
    # identical relaunch resumes from the committed state and finishes
    p = _run(tmp_path / "ck", tmp_path / "tbl")
    assert p.returncode == 0 and "DONE" in p.stdout, p.stderr[-2000:]
    assert _dir_digest(tmp_path / "tbl") == want


@pytest.mark.timeout(600)
def test_sigterm_under_async_push_emergency_commit_then_resume(tmp_path):
    p = _run(tmp_path / "ck_ref", tmp_path / "tbl_ref")
    assert p.returncode == 0, p.stderr[-2000:]
    want = _dir_digest(tmp_path / "tbl_ref")

    # parent-timed SIGTERM mid-run: graceful drain -> emergency
    # checkpoint (export flush barrier inside) -> exit 75
    env = dict(os.environ)
    proc = subprocess.Popen(
        [sys.executable, "-c", TRAIN_SCRIPT.format(repo=REPO),
         str(tmp_path / "ck"), str(tmp_path / "tbl")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)
    try:
        deadline = time.monotonic() + RUN_TIMEOUT
        # wait until at least one periodic checkpoint exists, then kill
        ck = tmp_path / "ck"
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            if ck.is_dir() and any(ck.iterdir()):
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=RUN_TIMEOUT)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    if proc.returncode == 0:
        # the run beat the signal: still a valid (if weaker) round —
        # the artifact must already match
        assert _dir_digest(tmp_path / "tbl") == want
        return
    assert proc.returncode == EXIT_PREEMPTED, (proc.returncode,
                                               err[-2000:])
    p = _run(tmp_path / "ck", tmp_path / "tbl")
    assert p.returncode == 0 and "DONE" in p.stdout, p.stderr[-2000:]
    assert _dir_digest(tmp_path / "tbl") == want
