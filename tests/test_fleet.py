"""Serving fleet (ISSUE 11 tentpole b+c), in-process half: the
queue-depth-aware router, health-state eviction/re-add, breaker
eviction, death failover (the zero-drop path), and the autoscaling
policy matrix + an end-to-end scale-out/in round — all over
:class:`LocalReplica` fleet members (threaded, single process), so
tier-1 stays lean.  Real multi-process rounds (SIGKILL chaos, the fleet
CLI) live in tests/test_fleet_chaos.py under @pytest.mark.slow.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import faults
from paddle_tpu.serving import Server
from paddle_tpu.serving.fleet import (AutoscalePolicy, FleetRouter,
                                      LocalReplica)

from test_serving import FakeModel, _mk_server, _req


def _counter(name):
    return pt.observability.registry().snapshot()[name]["value"]


class _FleetFixture:
    """N FakeModel-backed LocalReplicas behind a router; keeps handles
    to every fake and server for gating/poisoning."""

    def __init__(self, n=2, policy=None, server_kw=None, **router_kw):
        self.fakes = []
        self.servers = []
        self.server_kw = dict(server_kw or {})

        def factory(i):
            fake = FakeModel()
            srv = _mk_server(fake, **self.server_kw)
            self.fakes.append(fake)
            self.servers.append(srv)
            return LocalReplica(srv, name=f"rep{i}")

        router_kw.setdefault("poll_interval_s", 0.02)
        self.router = FleetRouter(factory, replicas=n, **router_kw)
        self.router.start()

    def replica(self, i) -> LocalReplica:
        return self.router.replicas[i]

    def shutdown(self):
        self.router.shutdown(timeout_s=20)


@pytest.fixture
def fleet2():
    f = _FleetFixture(n=2)
    yield f
    f.shutdown()


def test_router_serves_and_health_aggregates(fleet2):
    out = fleet2.router.submit(_req(1)).result(timeout=10)
    np.testing.assert_array_equal(out[0], np.full(2, 2.0, "float32"))
    h = fleet2.router.health()
    assert h["ready"] is True and h["state"] == "ready"
    assert sorted(h["replicas"]) == ["rep0", "rep1"]
    assert all(v["routable"] for v in h["replicas"].values())


def test_routes_to_least_loaded_replica():
    f = _FleetFixture(n=2, server_kw={"deadline_ms": None})
    try:
        # build real queue depth on rep0 by gating its model
        f.fakes[0].gate = threading.Event()
        held = [f.servers[0].submit(_req(100 + i)) for i in range(4)]
        f.router._poll_all()              # refresh the routing signal
        assert f.replica(0).queue_depth() > 0
        out = f.router.submit(_req(5)).result(timeout=10)
        assert out is not None
        assert 5.0 in f.fakes[1].rows     # routed around the deep queue
        assert 5.0 not in f.fakes[0].rows
        f.fakes[0].open_gate_forever()
        for r in held:
            assert r.result(timeout=10) is not None
    finally:
        f.fakes[0].open_gate_forever()
        f.shutdown()


def test_draining_replica_is_evicted(fleet2):
    rep0 = fleet2.replica(0)              # before the reaper drops it
    before = _counter("fleet/evictions")
    rep0.server.begin_drain()
    fleet2.router._poll_all()
    assert not fleet2.router._is_routable(rep0)
    assert _counter("fleet/evictions") >= before + 1
    for i in range(3):
        fleet2.router.submit(_req(i)).result(timeout=10)
    assert not fleet2.fakes[0].rows       # all routed to the survivor
    assert len(fleet2.fakes[1].rows) == 3


def test_breaker_open_is_an_eviction_signal_and_readds():
    f = _FleetFixture(n=2, server_kw={"breaker_threshold": 1,
                                      "breaker_cooldown_s": 0.05,
                                      "retry_policy": None})
    try:
        f.fakes[0].fail = [RuntimeError("poison")]
        with pytest.raises(Exception):
            f.servers[0].infer(_req(1), timeout=10)
        assert f.servers[0].health()["models"]["fake"]["breaker"] == "open"
        f.router._poll_all()
        assert not f.router._is_routable(f.replica(0))   # evicted
        f.router.submit(_req(2)).result(timeout=10)
        assert 2.0 in f.fakes[1].rows
        # cooldown passes; a successful probe recloses -> re-added
        time.sleep(0.1)
        f.servers[0].infer(_req(3), timeout=10)
        f.router._poll_all()
        assert f.router._is_routable(f.replica(0))
    finally:
        f.shutdown()


def test_replica_death_fails_over_admitted_requests_zero_drop():
    """A replica aborting admitted work (the in-process analog of
    SIGKILL) must not surface to the client: the router resubmits to a
    survivor and the ONE client handle completes with real outputs."""
    f = _FleetFixture(n=2, server_kw={"deadline_ms": None,
                                      "max_batch": 1,
                                      "staging_depth": 1},
                      poll_interval_s=30.0)   # manual polls only
    try:
        rep0, rep1 = f.replica(0), f.replica(1)
        f.fakes[0].gate = threading.Event()
        # park rep1 (stale health = unroutable) so every submit lands on
        # rep0: fp1 dispatching (gated), fp2 staged, fp3 in the blocked
        # batcher's hands, fp4 in the ADMISSION QUEUE
        rep1.last_health_ts = 0.0
        fps = [f.router.submit(_req(10 + i)) for i in range(4)]
        time.sleep(0.2)
        rep1.poll_health()                # survivor back in the pool
        before = _counter("fleet/failovers")
        killer = threading.Thread(target=rep0.kill, daemon=True)
        killer.start()                    # aborts fp4 (queued) first
        time.sleep(0.1)
        f.fakes[0].open_gate_forever()    # free the wedged dispatches
        killer.join(timeout=15)
        for fp in fps:
            out = fp.result(timeout=15)   # all complete despite the kill
            assert out is not None
        assert _counter("fleet/failovers") >= before + 1
        served = sorted(set(f.fakes[0].rows + f.fakes[1].rows))
        assert served == [10.0, 11.0, 12.0, 13.0]     # none lost
        assert 13.0 in f.fakes[1].rows    # the aborted one failed over
    finally:
        f.fakes[0].open_gate_forever()
        f.shutdown()


def test_router_backlog_limit_sheds_at_the_fleet_rim():
    """With every ready replica at the backlog limit, the router
    rejects Overloaded WITHOUT paying the replica's wire+parse — but a
    failover resubmission (already admitted fleet-wide) is exempt."""
    f = _FleetFixture(n=1, server_kw={"deadline_ms": None},
                      backlog_limit=2, poll_interval_s=30.0)
    try:
        before = _counter("fleet/router_shed")
        f.fakes[0].gate = threading.Event()
        fp1 = f.router.submit(_req(1))   # dispatching (gated)
        fp2 = f.router.submit(_req(2))   # backlog 2 = at the limit
        with pytest.raises(faults.Overloaded, match="fleet saturated"):
            f.router.submit(_req(3))
        assert _counter("fleet/router_shed") == before + 1
        assert 3.0 not in f.fakes[0].rows      # never hit the replica
        f.fakes[0].open_gate_forever()
        assert fp1.result(timeout=10) is not None
        assert fp2.result(timeout=10) is not None
    finally:
        f.fakes[0].open_gate_forever()
        f.shutdown()


def test_cordon_removes_and_readds_without_touching_the_process(fleet2):
    """Administrative cordon: unroutable immediately, process and
    admitted work untouched; uncordon restores routing."""
    fleet2.router.cordon("rep0")
    for i in range(3):
        fleet2.router.submit(_req(i)).result(timeout=10)
    assert not fleet2.fakes[0].rows       # all routed around the cordon
    assert fleet2.replica(0).alive        # process untouched
    fleet2.router.cordon("rep0", cordoned=False)
    fleet2.replica(1).cordoned = True     # force the other way
    fleet2.router.submit(_req(9)).result(timeout=10)
    assert 9.0 in fleet2.fakes[0].rows
    with pytest.raises(ValueError):
        fleet2.router.cordon("ghost")


def test_fleet_draining_rejects_typed(fleet2):
    fleet2.router.begin_drain()
    with pytest.raises(faults.ServerClosed):
        fleet2.router.submit(_req(1))
    assert fleet2.router.health()["ready"] is False


def test_no_routable_replica_raises_model_unavailable():
    f = _FleetFixture(n=1)
    try:
        f.replica(0).server.begin_drain()
        f.router._poll_all()
        with pytest.raises(faults.ModelUnavailable):
            f.router.submit(_req(1))
    finally:
        f.shutdown()


# ---------------------------------------------------------------------------
# autoscaling policy (pure decision matrix) + e2e apply
# ---------------------------------------------------------------------------
def _snap(**kw):
    base = {"replicas": 2, "p99_ms": 100.0, "wait_share_p99": 0.8,
            "queue_depth": 4, "served_per_s": 50.0, "idle_s": 0.0,
            "since_last_decision_s": 1e9}
    base.update(kw)
    return base


def test_autoscale_policy_matrix():
    pol = AutoscalePolicy(wait_share_threshold=0.5, p99_floor_ms=20.0,
                          idle_rate_per_replica=1.0, idle_for_s=5.0,
                          min_replicas=1, max_replicas=4, cooldown_s=2.0)
    # scale-out: wait-dominated p99
    d = pol.decide(_snap())
    assert d and d["action"] == "scale_out"
    assert "queue-wait share" in d["reason"]
    # dispatch-dominated: more replicas won't help
    assert pol.decide(_snap(wait_share_p99=0.2)) is None
    # below the p99 floor: idle jitter never scales
    assert pol.decide(_snap(p99_ms=5.0)) is None
    # no window yet: no decision
    assert pol.decide(_snap(p99_ms=None, wait_share_p99=None)) is None
    # at max replicas: bounded
    assert pol.decide(_snap(replicas=4)) is None
    # cooldown: bounded rate of change
    assert pol.decide(_snap(since_last_decision_s=0.5)) is None
    # scale-in: sustained idle
    d = pol.decide(_snap(wait_share_p99=0.0, p99_ms=1.0, queue_depth=0,
                         served_per_s=0.0, idle_s=10.0))
    assert d and d["action"] == "scale_in"
    # ...but never below min_replicas
    assert pol.decide(_snap(replicas=1, wait_share_p99=0.0, p99_ms=1.0,
                            queue_depth=0, served_per_s=0.0,
                            idle_s=10.0)) is None
    # ...and not while the queue is non-empty
    assert pol.decide(_snap(wait_share_p99=0.0, p99_ms=1.0,
                            queue_depth=3, served_per_s=0.0,
                            idle_s=10.0)) is None
    # bad bounds rejected
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


def test_autoscale_apply_scales_out_then_in():
    # policy held OUTSIDE the router (no timer thread): the test drives
    # snapshot -> decide -> apply deterministically
    pol = AutoscalePolicy(wait_share_threshold=0.5, p99_floor_ms=1.0,
                          idle_rate_per_replica=1.0, idle_for_s=0.0,
                          min_replicas=1, max_replicas=3, cooldown_s=0.0)
    f = _FleetFixture(n=1)
    try:
        # seed a wait-dominated window (total 100 ms, dispatch 5 ms)
        with f.router._lock:
            for _ in range(32):
                f.router._window.append((100.0, 5.0))
        outs_before = _counter("fleet/scale_outs")
        snap = f.router.autoscale_snapshot()
        decision = pol.decide(snap)
        assert decision and decision["action"] == "scale_out"
        f.router.apply_decision(decision, snap)
        assert len(f.router.replicas) == 2
        assert _counter("fleet/scale_outs") == outs_before + 1
        f.router._poll_all()
        assert len(f.router._routable()) == 2
        # the new replica serves
        for i in range(4):
            f.router.submit(_req(i)).result(timeout=10)
        # now idle: scale back in through graceful drain
        with f.router._lock:
            f.router._window.clear()
        f.router.autoscale_snapshot()     # reset the served-rate window
        time.sleep(0.05)
        f.router._idle_since = time.monotonic() - 60.0
        ins_before = _counter("fleet/scale_ins")
        snap = f.router.autoscale_snapshot()
        snap["idle_s"] = 60.0
        decision = pol.decide(snap)
        assert decision and decision["action"] == "scale_in"
        f.router.apply_decision(decision, snap)
        assert _counter("fleet/scale_ins") == ins_before + 1
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            f.router._poll_all()
            f.router._reap_stopped()
            if len(f.router.replicas) == 1:
                break
            time.sleep(0.05)
        assert len(f.router.replicas) == 1   # drained + reaped
        # the survivor still serves
        f.router.submit(_req(9)).result(timeout=10)
    finally:
        f.shutdown()


def test_fleet_behind_http_front(fleet2):
    """The router exposes the server surface, so the HTTP front fronts
    a fleet unchanged — including drain -> 503 + Connection: close."""
    import http.client
    import json

    from paddle_tpu.serving.http import HttpFront

    front = HttpFront(fleet2.router, port=0).start()
    try:
        host, port = front.address
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("POST", "/v1/infer",
                     body=json.dumps({"id": 1, "feeds": {"x": [1.0, 2.0]}}))
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["outputs"] == [[2.0, 4.0]]
        conn.close()
        fleet2.router.begin_drain()
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("POST", "/v1/infer",
                     body=json.dumps({"id": 2, "feeds": {"x": [1.0, 2.0]}}))
        resp = conn.getresponse()
        assert resp.status == 503
        assert resp.getheader("Connection", "").lower() == "close"
        conn.close()
    finally:
        front.stop()
