"""Fleet-wide distributed tracing + metrics aggregation (ISSUE 20).

In-process loopback, real sockets (the test_pserver.py discipline —
shard servers on daemon threads in THIS interpreter).  What these pin:

* **one trace across processes**: a training step against a 2-shard
  pserver fleet produces — after merging the trainer's and the shards'
  JSONL logs — a single trace per step in which every shard's
  server-side ``pserver/rpc`` span parents under the trainer's client
  span, which parents under ``sparse/pull``/``sparse/push``;
* **remote attribution**: the doctor splits remote sparse wall into
  client-wire / server-queue / server-kernel from the reply-piggybacked
  server timings, summing to the measured wall within tolerance;
* **malformed context is ignored-and-counted, never fatal** — fuzzed at
  every rim (W3C traceparent, sparse wire header, master RPC envelope):
  the request still serves, ``trace/context_rejected`` increments;
* **zero overhead when off**: with ``observe`` off no wire frame grows
  a byte (no ``ctx`` in any request header, no ``srv`` in any reply),
  no span events are written even with a metrics_log sink set, and the
  reject counter stays at zero;
* **fleet metrics**: ``merge_snapshots`` semantics (counters sum,
  gauges stay per-source, skewed histograms are skipped-and-named) and
  the ``fleet-stats`` CLI end to end over logs + live shard endpoints
  + a master heartbeat piggyback.
"""
import json
import socket
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, layers
from paddle_tpu import observability as obs
from paddle_tpu.observability import attribution
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import tracing
from paddle_tpu.sparse import SparseSession
from paddle_tpu.sparse import wire
from paddle_tpu.sparse.client import RemoteSparseTable
from paddle_tpu.sparse.pserver import PServer

HOST = "127.0.0.1"
IO_TO = 10.0


@pytest.fixture(autouse=True)
def clean_observability():
    obs.registry().reset()
    prev = {n: flags.get_flag(n) for n in ("observe", "metrics_log")}
    yield
    for n, v in prev.items():
        flags.set_flag(n, v if v is not None else "")
    obs_export._reset_writer()
    obs_export.set_process_identity(None)
    obs.registry().reset()


@pytest.fixture
def fleet2():
    """A 2-shard in-thread fleet (test_pserver.py pattern)."""
    servers, threads = [], []
    for k in range(2):
        s = PServer(k, 2, host=HOST, io_timeout_s=IO_TO)
        s.start()
        servers.append(s)
    for s in servers:
        t = threading.Thread(target=s.serve_forever, daemon=True)
        t.start()
        threads.append(t)
    try:
        yield servers
    finally:
        for s in servers:
            s.stop()
        for t in threads:
            t.join(timeout=5.0)


def _addrs(servers):
    return [(HOST, s.port) for s in servers]


def _sparse_program(vocab=32, dim=4, name="tbl"):
    ids = layers.data("ids", shape=[1], dtype="int64")
    label = layers.data("label", shape=[1], dtype="float32")
    emb = layers.embedding(ids, size=[vocab, dim], sparse=True, name=name)
    fc = layers.fc(emb, size=1)
    loss = layers.mean(layers.square(fc - label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _rejected():
    m = obs.registry().snapshot().get("trace/context_rejected") or {}
    return m.get("value", 0.0)


def _spans(events):
    return [e for e in events if e.get("kind") == "span"]


def _write_split_log(path, role, index, events):
    """Re-write a slice of span events as the JSONL log that process
    WOULD have produced: identity header first, then the events."""
    ident = {"ts": min(e.get("ts", 0.0) for e in events) - 1e-6,
             "kind": "identity", "role": role, "pid": 1000 + (index or 0)}
    if index is not None:
        ident["index"] = index
    with open(path, "w") as fh:
        fh.write(json.dumps(ident) + "\n")
        for e in events:
            fh.write(json.dumps(
                {k: v for k, v in e.items()
                 if not k.startswith("_")}) + "\n")


# ---------------------------------------------------------------------------
# the e2e acceptance: one trace across trainer + 2 shards, doctor split
# ---------------------------------------------------------------------------
def test_e2e_fleet_trace_single_trace_and_doctor_split(
        fleet2, tmp_path, capsys):
    flags.set_flag("observe", True)
    log = tmp_path / "all.jsonl"
    flags.set_flag("metrics_log", str(log))
    _sparse_program(vocab=32, dim=4)
    kw = dict(vocab_size=32, dim=4, learning_rate=1.0, seed=13)
    with RemoteSparseTable("tbl", addrs=_addrs(fleet2), io_timeout_s=IO_TO,
                           **kw) as rt:
        sess = SparseSession(rt)
        sess.bind(pt.default_main_program())
        # ids land on BOTH shards (id % 2): one step = one trace
        ids = np.array([[5], [9], [6], [30]], np.int64)
        feed = {"ids": ids, "label": np.zeros((4, 1), np.float32)}
        for _step in range(2):
            with tracing.span("executor/step", path="fleet-e2e"):
                fr = sess.prepare_feed(dict(feed))
                sess.complete([np.ones_like(fr["tbl@ROWS"])])

    events, _files = obs_export.iter_log_events([str(log)])
    spans = _spans(events)
    server = [e for e in spans
              if (e.get("labels") or {}).get("side") == "server"]
    trainer = [e for e in spans if e not in server]
    assert server, "no server-side pserver/rpc spans were emitted"

    # split by emitting process + merge back — the multi-file path the
    # real fleet (one JSONL per process) exercises
    tlog = tmp_path / "trainer.jsonl"
    slogs = []
    _write_split_log(tlog, "trainer", None, trainer)
    for k in range(2):
        mine = [e for e in server if e["labels"].get("shard") == k]
        assert mine, f"shard {k} emitted no server spans"
        p = tmp_path / f"pserver{k}.jsonl"
        _write_split_log(p, "pserver", k, mine)
        slogs.append(p)
    merged, files = obs_export.iter_log_events(
        [str(tlog)] + [str(p) for p in slogs])
    mspans = _spans(merged)
    assert len(mspans) == len(spans)
    assert [f["role"] for f in sorted(files, key=lambda f: f["index"])] \
        == ["trainer", "pserver", "pserver"]

    # ONE trace per step; every parent exists, trace ids agree, no cycles
    by_trace = {}
    for e in mspans:
        by_trace.setdefault(e["trace"], []).append(e)
    assert len(by_trace) == 2
    for tid, tspans in by_trace.items():
        by_id = {e["span"]: e for e in tspans}
        for e in tspans:
            p = e.get("parent")
            if p is None:
                assert e["name"] == "executor/step"
                continue
            assert p in by_id, \
                f"span {e['span']} ({e['name']}) has unknown parent {p}"
            assert by_id[p]["trace"] == tid
            seen, cur = set(), e
            while cur.get("parent"):
                assert cur["span"] not in seen
                seen.add(cur["span"])
                cur = by_id[cur["parent"]]
        # each shard's server span parents under the trainer's client
        # pserver/rpc span, which parents under sparse/pull or push
        srv = [e for e in tspans
               if (e.get("labels") or {}).get("side") == "server"]
        assert {e["labels"]["shard"]
                for e in srv if e["labels"].get("op") == "pull"} == {0, 1}
        for e in srv:
            parent = by_id[e["parent"]]
            assert parent["name"] == "pserver/rpc"
            assert (parent.get("labels") or {}).get("side") != "server"
            assert by_id[parent["parent"]]["name"] in ("sparse/pull",
                                                       "sparse/push")
            assert e["labels"].get("queue_ms") is not None
            assert e["labels"].get("kernel_ms") is not None
        pulls = [e for e in tspans if e["name"] == "sparse/pull"]
        assert pulls and all(by_id[e["parent"]]["name"] == "executor/step"
                             for e in pulls)

    # the doctor's remote split: components sum to the measured client
    # wall within the pinned tolerance, every round attributed
    rb = attribution.remote_budget(merged)
    assert rb is not None
    assert rb["rounds"] == 4 and rb["attributed_rounds"] == 4
    assert rb["by_op"] == {"pull": 2, "push": 2}
    assert set(rb["budget"]) == {"client_wire_ms", "server_queue_ms",
                                 "server_kernel_ms"}
    assert rb["within_tolerance"] and rb["budget_gap_frac"] <= 0.15
    assert abs(rb["budget_sum_ms"] - rb["measured_wall_ms"]) \
        <= 0.15 * rb["measured_wall_ms"]
    assert attribution.doctor_report(
        [str(tlog)] + [str(p) for p in slogs])["remote"] == rb

    # CLI form over the merged files: sources labeled by role, the
    # remote budget rendered
    from paddle_tpu.cli import main as cli_main
    assert cli_main(["doctor", str(tlog)] + [str(p) for p in slogs]) == 0
    out = capsys.readouterr().out
    assert "source [trainer]" in out
    assert "source [pserver:0]" in out and "source [pserver:1]" in out
    assert "remote sparse:" in out and "client_wire" in out


# ---------------------------------------------------------------------------
# malformed context: ignored-and-counted at every rim, never fatal
# ---------------------------------------------------------------------------
def test_traceparent_rim_fuzz_rejected_and_counted():
    base = _rejected()
    assert tracing.extract_traceparent(None) is None    # absent: silent
    assert _rejected() == base
    bad = ["nonsense", "00-abc-def-01",
           "zz-" + "a" * 32 + "-" + "b" * 16 + "-01",   # non-hex version
           "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",   # forbidden version
           "00-" + "0" * 32 + "-" + "b" * 16 + "-01",   # all-zero trace
           "00-" + "a" * 32 + "-" + "0" * 16 + "-01",   # all-zero parent
           "00-" + "a" * 31 + "-" + "b" * 16 + "-01",   # short trace
           123]                                          # not a string
    for tp in bad:
        assert tracing.extract_traceparent(tp) is None
    assert _rejected() == base + len(bad)
    good = tracing.extract_traceparent(
        "00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    assert good.trace_id == "t" + "a" * 32 and good.span_id == "b" * 16
    assert _rejected() == base + len(bad)


def test_wire_ctx_rim_fuzz_rejected_and_counted():
    base = _rejected()
    assert tracing.extract(None) is None                # absent: silent
    bad = [123, ["1", "t", "s"], "2:tabc-1:abc-2",      # unknown version
           "1:only-two", "1::abc-2", "1:tabc-1:", "::", ""]
    for ctx in bad:
        assert tracing.extract(ctx) is None
    assert _rejected() == base + len(bad)
    rp = tracing.extract("1:tdead-1:beef-2")
    assert rp.trace_id == "tdead-1" and rp.span_id == "beef-2"


def test_wire_header_malformed_ctx_still_serves(fleet2):
    base = _rejected()
    for garbage in ({"v": 1}, "not-a-ctx"):
        with socket.create_connection((HOST, fleet2[0].port),
                                      timeout=IO_TO) as s:
            s.settimeout(IO_TO)
            wire.write_frame(s, {"op": "hello"})
            wire.read_frame(s)
            wire.write_frame(s, {"op": "stats", "ctx": garbage})
            reply, _ = wire.read_frame(s)
        assert reply["ok"] is True          # the request still served
        assert "srv" not in reply           # and attributed nothing
    assert _rejected() == base + 2
    # a WELL-formed ctx on the same op gets the server piggyback
    with socket.create_connection((HOST, fleet2[0].port),
                                  timeout=IO_TO) as s:
        s.settimeout(IO_TO)
        wire.write_frame(s, {"op": "hello"})
        wire.read_frame(s)
        wire.write_frame(s, {"op": "stats", "ctx": "1:tdead-1:beef-2"})
        reply, _ = wire.read_frame(s)
    assert reply["ok"] is True
    assert set(reply["srv"]) == {"queue_ms", "kernel_ms"}
    assert _rejected() == base + 2


def test_master_envelope_malformed_ctx_still_serves(tmp_path):
    from paddle_tpu.distributed.master import Master, MasterServer
    srv = MasterServer(Master()).start()
    try:
        base = _rejected()
        with socket.create_connection((srv.host, srv.port),
                                      timeout=IO_TO) as s:
            f = s.makefile("rw")
            f.write(json.dumps({"method": "ping",
                                "ctx": {"bogus": 1}}) + "\n")
            f.flush()
            assert json.loads(f.readline()) == {"result": "pong"}
            assert _rejected() == base + 1
            # well-formed ctx -> a master/rpc span parented on the
            # remote caller lands in the JSONL log
            log = tmp_path / "master.jsonl"
            flags.set_flag("metrics_log", str(log))
            f.write(json.dumps({"method": "ping",
                                "ctx": "1:tdead-1:beef-2"}) + "\n")
            f.flush()
            assert json.loads(f.readline()) == {"result": "pong"}
        spans = _spans(obs_export.iter_log_events([str(log)])[0])
        assert [e["name"] for e in spans] == ["master/rpc"]
        assert spans[0]["trace"] == "tdead-1"
        assert spans[0]["parent"] == "beef-2"
        assert spans[0]["labels"]["method"] == "ping"
        assert _rejected() == base + 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# zero overhead when off: no wire frame grows a byte
# ---------------------------------------------------------------------------
def test_observe_off_adds_zero_wire_bytes_and_zero_events(
        fleet2, tmp_path, monkeypatch):
    captured = []
    real = wire.write_frame

    def spy(sock, header, arrays=()):
        captured.append(dict(header))
        return real(sock, header, arrays)

    # both the client and the in-thread servers resolve wire.write_frame
    # at call time, so every request AND reply header is captured
    monkeypatch.setattr(wire, "write_frame", spy)

    flags.set_flag("observe", False)
    log = tmp_path / "off.jsonl"
    flags.set_flag("metrics_log", str(log))
    base = _rejected()
    kw = dict(vocab_size=32, dim=4, seed=3)
    ids = np.arange(8, dtype=np.int64)
    g = np.ones((8, 4), np.float32)
    with RemoteSparseTable("t", addrs=_addrs(fleet2), io_timeout_s=IO_TO,
                           **kw) as rt:
        rt.pull(ids)
        rt.push(ids, g)
    off = list(captured)
    off_req = [h for h in off if "op" in h]
    off_pull = [h for h in off_req if h.get("op") == "pull"]
    off_rep = [h for h in off if "ok" in h]
    assert off_pull and off_rep
    assert all("ctx" not in h for h in off_req)     # no request grew
    assert all("srv" not in h for h in off_rep)     # no reply grew
    assert not log.exists() or log.read_text() == ""  # zero span events
    assert _rejected() == base

    # the SAME rounds with observe on differ by exactly the ctx field
    captured.clear()
    flags.set_flag("observe", True)
    with RemoteSparseTable("t", addrs=_addrs(fleet2), io_timeout_s=IO_TO,
                           **kw) as rt:
        rt.pull(ids)
        rt.push(ids, g)
    on_pull = [h for h in captured if h.get("op") == "pull"]
    assert len(on_pull) == len(off_pull)
    for on_h, off_h in zip(on_pull, off_pull):
        assert "ctx" in on_h
        assert {k: v for k, v in on_h.items() if k != "ctx"} == off_h
    on_rep = [h for h in captured if "ok" in h and "srv" in h]
    assert on_rep                                    # piggyback is back
    assert _spans(obs_export.iter_log_events([str(log)])[0])


# ---------------------------------------------------------------------------
# fleet metrics: merge semantics + the fleet-stats CLI
# ---------------------------------------------------------------------------
def test_merge_snapshots_semantics():
    from paddle_tpu.observability import collector
    h = {"kind": "histogram", "count": 2, "sum": 3.0, "min": 1.0,
         "max": 2.0, "boundaries": [1.0, 10.0], "counts": [1, 1]}
    h_skew = dict(h, boundaries=[5.0], counts=[2])
    src = {
        "a": {"metrics": {"metrics": {
                  "fault/retries": {"kind": "counter", "value": 2.0},
                  "device/bytes_in_use": {"kind": "gauge",
                                          "values": {"cpu:0": 5.0}},
                  "pserver/frame_ms": h},
              "compile": {"compile/traces": 1},
              "device_memory": {"cpu:0": {"bytes_in_use": 5}}},
              "identity": {"role": "trainer", "pid": 1}},
        "b": {"metrics": {"metrics": {
                  "fault/retries": {"kind": "counter", "value": 3.0},
                  "device/bytes_in_use": {"kind": "gauge",
                                          "values": {"cpu:0": 7.0}},
                  "pserver/frame_ms": h_skew},
              "compile": {"compile/traces": 2}},
              "identity": {"role": "pserver", "index": 1, "pid": 2}},
    }
    merged = collector.merge_snapshots(src)
    m = merged["metrics"]
    assert m["fault/retries"] == {"kind": "counter", "value": 5.0}
    # gauges are per-process levels: one sample per source, never summed
    assert m["device/bytes_in_use"]["values"] == {"a:cpu:0": 5.0,
                                                  "b:cpu:0": 7.0}
    # the bucket-skewed source is skipped AND named, not averaged in
    assert m["pserver/frame_ms"]["count"] == 2
    assert m["pserver/frame_ms"]["counts"] == [1, 1]
    assert merged["skipped"] == ["b:pserver/frame_ms (bucket mismatch)"]
    assert merged["compile"]["compile/traces"] == 3.0
    assert merged["device_memory"] == {"a:cpu:0": {"bytes_in_use": 5}}
    assert set(merged["sources"]) == {"a", "b"}
    text = collector.render_fleet(merged)
    assert "fleet snapshot: 2 source(s)" in text
    assert "fault/retries: 5" in text
    assert "skipped: b:pserver/frame_ms (bucket mismatch)" in text
    # merging is itself observable
    snap = obs.registry().snapshot()
    assert snap["collector/merges"]["value"] >= 1.0
    assert snap["collector/sources"]["values"][""] == 2.0


def test_fleet_stats_cli_merges_logs_endpoints_and_master(
        fleet2, tmp_path, capsys):
    # two per-process logs, each stamped with its writer's identity
    logs = tmp_path / "logs"
    logs.mkdir()
    obs_export.set_process_identity("trainer")
    flags.set_flag("metrics_log", str(logs / "trainer.jsonl"))
    obs_export.periodic_report(1)
    obs_export._reset_writer()
    obs_export.set_process_identity("serve", 0)
    flags.set_flag("metrics_log", str(logs / "serve.jsonl"))
    obs_export.periodic_report(2)
    obs_export._reset_writer()
    obs_export.set_process_identity(None)
    flags.set_flag("metrics_log", "")

    from paddle_tpu.distributed.master import Master, MasterServer
    msrv = MasterServer(Master()).start()
    try:
        from paddle_tpu.cli import main as cli_main
        argv = ["fleet-stats", str(logs)] \
            + [f"{HOST}:{s.port}" for s in fleet2] \
            + ["--master", f"{msrv.host}:{msrv.port}"]
        assert cli_main(argv + ["--json"]) == 0
        merged = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert set(merged["sources"]) == {"trainer", "serve:0",
                                          "pserver:0", "pserver:1",
                                          "master"}
        assert merged["sources"]["pserver:1"]["role"] == "pserver"
        assert merged["metrics"]["pserver/requests"]["kind"] == "counter"
        assert merged["metrics"]["pserver/requests"]["value"] > 0
        # Prometheus exposition of the SAME merge
        assert cli_main(argv + ["--prom"]) == 0
        prom = capsys.readouterr().out
        assert "# TYPE" in prom and "pserver" in prom
    finally:
        msrv.stop()


def test_fleet_stats_cli_refuses_nonsense_source(tmp_path):
    from paddle_tpu.cli import main as cli_main
    with pytest.raises(SystemExit):
        cli_main(["fleet-stats", "definitely/not/a/thing"])
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit):
        cli_main(["fleet-stats", str(empty)])
