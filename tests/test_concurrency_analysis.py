"""PT05x concurrency pass: seeded-defect corpus + rule-grounding checks.

Layer map:
  * seeded corpus — ``tests/fixtures/concurrency/`` holds one MINIMAL
    defect per PT05x code plus a clean control; each fixture must fire
    EXACTLY its code exactly once (a rule that stops firing on its own
    minimal reproducer is broken, a rule that co-fires is too eager)
  * zoo silence — the model-zoo host sources carry no concurrency at
    all, so every PT05x rule must stay silent there (false-positive
    regression canary over real non-threaded code)
  * grounding — the analyzer's frozen pattern tables name REAL stdlib
    attributes, and every global the analyzer loads resolves (dis
    agreement: the pass can never die with NameError mid-scan)
  * baseline mechanics — apply_baseline's new/suppressed/stale split
    on a synthetic ledger (the ratchet the tier-1 gate relies on)
"""
import ast
import builtins
import dis
import inspect
import os
import pathlib

import pytest

from paddle_tpu.analysis import concurrency as cc
from paddle_tpu.analysis.diagnostics import CODES

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "concurrency"

# hermetic prefix table: fixtures never depend on the live registry
FX_PREFIXES = ("pt-fx",)


def _analyze_fixture(name):
    path = FIXTURES / name
    return cc.analyze_source(path.read_text(), f"tests/{name}",
                             thread_prefixes=FX_PREFIXES)


# ---------------------------------------------------------------------------
# seeded corpus: exact-fire matrix


SEEDED = [
    ("pt050_guard_inconsistency.py", "PT050"),
    ("pt051_order_cycle.py", "PT051"),
    ("pt052_blocking_under_lock.py", "PT052"),
    ("pt053_wait_no_loop.py", "PT053"),
    ("pt054_signal_handler_lock.py", "PT054"),
    ("pt055_unnamed_thread.py", "PT055"),
]


@pytest.mark.parametrize("fixture,code", SEEDED,
                         ids=[c for _f, c in SEEDED])
def test_seeded_defect_fires_exactly_once(fixture, code):
    findings = _analyze_fixture(fixture)
    assert [f.code for f in findings] == [code], (
        f"{fixture} must fire {code} exactly once, got "
        f"{[(f.code, f.line, f.message) for f in findings]}")
    f = findings[0]
    # findings are located and self-describing: real line, a symbol,
    # and a renderable diagnostic that round-trips through the frozen
    # code registry
    assert f.line > 0 and f.symbol
    assert f.code in CODES
    assert f.code in f.render() and f.path in f.render()
    d = f.to_diagnostic()
    assert d.code == code


def test_seeded_corpus_covers_every_pt05x_code():
    # adding PT056 without a minimal reproducer fixture fails here
    assert {c for _f, c in SEEDED} == {
        c for c in CODES if c.startswith("PT05")}


def test_clean_fixture_is_silent():
    assert _analyze_fixture("clean.py") == []


def test_pt051_cycle_names_both_locks():
    (f,) = _analyze_fixture("pt051_order_cycle.py")
    # the report must let a reader act without re-running the pass:
    # both lock classes in the cycle appear in the message
    assert "a" in f.symbol or "a" in f.message
    assert "b" in f.message or "b" in f.symbol


# ---------------------------------------------------------------------------
# zoo silence: no spurious findings over real non-threaded host code


def _model_sources():
    root = pathlib.Path(cc.package_root()) / "models"
    files = sorted(p for p in root.rglob("*.py"))
    assert len(files) >= 8, f"model zoo moved? found {files}"
    return files


@pytest.mark.parametrize(
    "path", _model_sources(),
    ids=lambda p: str(p.relative_to(pathlib.Path(cc.package_root()) /
                                    "models")))
def test_zoo_host_sources_have_zero_findings(path):
    rel = os.path.relpath(path, os.path.dirname(cc.package_root()))
    findings = cc.analyze_source(path.read_text(), rel.replace(os.sep, "/"),
                                 thread_prefixes=FX_PREFIXES)
    assert findings == [], (
        f"spurious PT05x finding(s) in zoo model source: "
        f"{[f.render() for f in findings]}")


def test_package_scan_covers_the_zoo():
    # the whole-tree scan (the thing the tier-1 ratchet gate runs) walks
    # every model file — silence above is meaningful only if scanned
    scanned = set()
    root = cc.package_root()
    for dirpath, dirs, files in os.walk(os.path.join(root, "models")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        scanned.update(os.path.join(dirpath, f) for f in files
                       if f.endswith(".py"))
    assert {str(p) for p in _model_sources()} == scanned


# ---------------------------------------------------------------------------
# grounding: pattern tables name real attributes; globals resolve


def test_pattern_tables_name_real_stdlib_attributes():
    import queue
    import socket
    import threading
    # Popen alone: this test only checks ATTRIBUTES exist, it never
    # spawns (the subprocess-tests-are-slow lint keys on the module name)
    from subprocess import Popen

    from paddle_tpu.testing import lockwatch

    # lock/cond factories: each name is either a threading callable or a
    # lockwatch factory — the analyzer treats both as the same class
    for name in cc.LOCK_FACTORIES + cc.RLOCK_FACTORIES + cc.COND_FACTORIES:
        assert (callable(getattr(threading, name, None))
                or callable(getattr(lockwatch, name, None))), name
    for name in cc.QUEUE_FACTORIES:
        assert callable(getattr(queue, name)), name
    for name in cc.EVENT_FACTORIES:
        assert callable(getattr(threading, name)), name
    for name in cc.THREAD_FACTORY_NAMES:
        assert callable(getattr(threading, name)), name
    # blocking-method tables: the methods the rule flags must exist on
    # the real objects, else the table is matching dead names
    for name in cc.BLOCKING_SOCKET_METHODS:
        assert hasattr(socket.socket, name), name
    for name in cc.BLOCKING_PROC_METHODS:
        assert hasattr(Popen, name), name
    # the condition / queue / thread methods the rules hardcode
    assert hasattr(threading.Condition, "wait")
    assert hasattr(threading.Condition, "wait_for")
    assert hasattr(threading.Thread, "join")
    for m in ("get", "put"):
        assert hasattr(queue.Queue, m)


def test_analyzer_globals_all_resolve():
    # dis agreement (convention of test_shape_rules_resolve_all_globals):
    # every LOAD_GLOBAL in the pass and its nested code objects resolves
    # in module globals or builtins — a scan can never NameError
    def walk(code):
        yield code
        for const in code.co_consts:
            if hasattr(const, "co_code"):
                yield from walk(const)

    bad = []
    for name, obj in vars(cc).items():
        fns = []
        if inspect.isfunction(obj) and obj.__module__ == cc.__name__:
            fns.append(obj)
        elif inspect.isclass(obj) and obj.__module__ == cc.__name__:
            fns.extend(f for f in vars(obj).values()
                       if inspect.isfunction(f))
        for fn in fns:
            for code in walk(fn.__code__):
                for ins in dis.get_instructions(code):
                    if (ins.opname == "LOAD_GLOBAL"
                            and ins.argval not in fn.__globals__
                            and not hasattr(builtins, ins.argval)):
                        bad.append((name, fn.__qualname__, ins.argval))
    assert not bad, f"analyzer functions with unresolvable globals: {bad}"


def test_thread_name_prefixes_parse_matches_live_registry():
    # the analyzer reads the frozen literal without importing; the two
    # views must agree or the static and runtime PT055 twins diverge
    from paddle_tpu.observability.metrics import THREAD_NAME_PREFIXES
    assert cc.thread_name_prefixes() == tuple(
        p for p, _help in THREAD_NAME_PREFIXES)


# ---------------------------------------------------------------------------
# baseline mechanics: the ratchet's three-way split


def _finding(code, path, line=10):
    return cc.Finding(code=code, path=path, line=line,
                      symbol="x", message="seeded")


def test_apply_baseline_three_way_split():
    findings = [
        _finding("PT050", "paddle_tpu/a.py", 1),   # new (not budgeted)
        _finding("PT052", "paddle_tpu/b.py", 2),   # suppressed (1 of 1)
        _finding("PT052", "paddle_tpu/b.py", 9),   # new (beyond budget)
    ]
    baseline = {
        ("paddle_tpu/b.py", "PT052"): (1, "legacy wire path"),
        ("paddle_tpu/gone.py", "PT051"): (1, "stale: code was fixed"),
    }
    new, suppressed, stale = cc.apply_baseline(findings, baseline)
    assert [(f.path, f.code, f.line) for f in new] == [
        ("paddle_tpu/a.py", "PT050", 1),
        ("paddle_tpu/b.py", "PT052", 9)]
    assert suppressed == {("paddle_tpu/b.py", "PT052"): 1}
    assert stale == [("paddle_tpu/gone.py", "PT051")]
    # and the rendered report names all three buckets
    report = cc.render_report(findings, baseline)
    assert "2 new" in report
    assert "baselined PT052 x1" in report
    assert "STALE baseline entry" in report


def test_apply_baseline_empty_is_clean():
    new, suppressed, stale = cc.apply_baseline([], {})
    assert (new, suppressed, stale) == ([], {}, [])


def test_shipped_baseline_is_well_formed_and_justified():
    # shrink-only ledger: every entry names a real in-tree file, a PT05x
    # code, a positive budget and a non-empty justification
    root = os.path.dirname(cc.package_root())
    for (path, code), (count, why) in cc.BASELINE.items():
        assert code in CODES and code.startswith("PT05"), (path, code)
        assert os.path.isfile(os.path.join(root, path)), path
        assert count >= 1
        assert isinstance(why, str) and len(why.strip()) >= 10, (path, code)


def test_fixture_docstrings_name_their_code():
    # each seeded fixture documents WHICH defect it plants, so a reader
    # landing in the corpus needs no cross-reference
    for fixture, code in SEEDED:
        mod = ast.parse((FIXTURES / fixture).read_text())
        doc = ast.get_docstring(mod) or ""
        assert code in doc, f"{fixture} docstring must name {code}"
