"""Repo-wide custom lint gate (tier-1).

Three AST lints over every ``paddle_tpu/`` source file, no imports needed:

1. **Broad except swallows** — an ``except``/``except Exception``/
   ``except BaseException`` handler whose body does nothing (only
   ``pass``/``continue``/a bare constant) hides real failures; ADVICE
   rounds repeatedly flagged these (e.g. the `_in_manual_mesh_context`
   swallow that masked the jax-0.4.37 drift until PR 1 narrowed it).
   Existing sites are enumerated in a FROZEN per-file allowlist: the
   count can only shrink.  Adding a new swallow fails this test — narrow
   the exception type or handle/log it; removing one fails until the
   allowlist is ratcheted down to match.
2. **Duplicate register_op names** — the runtime registry raises on a
   duplicate at import time, but only for modules the package actually
   imports; the AST scan also covers flag-gated or lazily imported files,
   and duplicate ``register_shape_fn`` names identically.
3. **Metric-name gate** — every metric name passed to the observability
   registry helpers (``inc_counter``/``set_gauge``/``observe_hist``) must
   be a string LITERAL registered in the frozen
   ``observability.metrics.METRIC_NAMES`` table (duplicates rejected): a
   typo'd or free-form name would otherwise create a silently empty time
   series.  Mirrors the duplicate-op-registration gate.
"""
import ast
import collections
import os

ROOT = os.path.join(os.path.dirname(__file__), os.pardir, "paddle_tpu")

# ---------------------------------------------------------------------------
# Frozen allowlist: relpath (from repo root) -> number of PERMITTED broad
# except-swallow sites.  Never add entries or raise counts — narrow the
# exception instead.  When you remove a swallow, ratchet its count down.
# ---------------------------------------------------------------------------
EXCEPT_SWALLOW_ALLOWLIST = {
    # last-resort CLI/config probing fallbacks, each commented in-source
    "paddle_tpu/cli.py": 1,
    "paddle_tpu/data_feeder.py": 1,
    # cache corruption recovery: a bad persistent entry must never take
    # down a training run (tests/test_compile_cache.py pins the behavior)
    "paddle_tpu/core/compile_cache.py": 2,
    # distributed best-effort cleanup paths (peer already gone)
    # (checkpoint.py's restore-fallback swallow was converted to a
    # logged + counted fallback in the fault-tolerance PR — ratcheted out)
    "paddle_tpu/distributed/master.py": 1,
}


def _iter_sources():
    for dirpath, dirs, files in os.walk(ROOT):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                path = os.path.join(dirpath, f)
                rel = os.path.relpath(
                    path, os.path.join(ROOT, os.pardir)).replace(os.sep, "/")
                with open(path) as fh:
                    yield rel, ast.parse(fh.read(), filename=rel)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:                                   # bare except:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(e, ast.Name) and
               e.id in ("Exception", "BaseException") for e in elts)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body does nothing: only pass/continue/bare constants (docstrings)."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue
        return False
    return True


def test_no_new_broad_except_swallows():
    found = collections.defaultdict(list)
    for rel, tree in _iter_sources():
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and _swallows(node):
                found[rel].append(node.lineno)

    problems = []
    for rel, lines in sorted(found.items()):
        allowed = EXCEPT_SWALLOW_ALLOWLIST.get(rel, 0)
        if len(lines) > allowed:
            problems.append(
                f"{rel}: {len(lines)} broad except-swallow(s) at lines "
                f"{lines}, allowlist permits {allowed} — narrow the "
                f"exception type or handle the error instead of adding "
                f"a swallow")
    for rel, allowed in sorted(EXCEPT_SWALLOW_ALLOWLIST.items()):
        actual = len(found.get(rel, []))
        if actual < allowed:
            problems.append(
                f"{rel}: allowlist permits {allowed} swallow(s) but only "
                f"{actual} remain — ratchet EXCEPT_SWALLOW_ALLOWLIST down "
                f"so the count can only shrink")
    assert not problems, "\n".join(problems)


def _registered_names(call_name: str):
    """(name, file, lineno) for every string literal passed to
    register_op(...) / register_shape_fn(...) decorator calls."""
    out = []
    for rel, tree in _iter_sources():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            target = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if target != call_name:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str):
                    out.append((arg.value, rel, node.lineno))
    return out


def test_no_duplicate_register_op_names():
    for call in ("register_op", "register_shape_fn", "register_shard_fn",
                 "register_tunable"):
        by_name = collections.defaultdict(list)
        for name, rel, lineno in _registered_names(call):
            by_name[name].append(f"{rel}:{lineno}")
        dupes = {n: sites for n, sites in by_name.items()
                 if len(sites) > 1}
        assert not dupes, (
            f"duplicate {call} names (the second registration would "
            f"raise at import time, or silently never load if the module "
            f"is flag-gated): {dupes}")
        assert by_name, f"AST scan found no {call} calls — lint is broken"


# ---------------------------------------------------------------------------
# Metric-name gate (paddle_tpu.observability.metrics.METRIC_NAMES)
# ---------------------------------------------------------------------------
_METRIC_HELPERS = ("inc_counter", "set_gauge", "observe_hist")
# the registry module itself delegates name -> self._registry.<helper>(name)
# with a variable, by construction — it is the ONE place free-form names
# are allowed (its own METRIC_NAMES table is what the gate checks against)
_METRIC_DEFINING_FILE = "paddle_tpu/observability/metrics.py"


def _metric_names_table():
    """(name, kind) rows parsed from the METRIC_NAMES literal — no import,
    so the gate also covers a syntactically valid but unimportable state."""
    path = os.path.join(ROOT, "observability", "metrics.py")
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "METRIC_NAMES"
                for t in node.targets):
            rows = ast.literal_eval(node.value)
            return [(name, kind) for name, kind, _help in rows]
    raise AssertionError("METRIC_NAMES literal not found in metrics.py")


def _iter_lint_sources():
    """Everything the metric gate covers: the package plus the driver."""
    yield from _iter_sources()
    bench = os.path.join(ROOT, os.pardir, "bench.py")
    with open(bench) as fh:
        yield "bench.py", ast.parse(fh.read(), filename="bench.py")


def test_metric_names_table_well_formed():
    rows = _metric_names_table()
    names = [n for n, _ in rows]
    dupes = {n for n in names if names.count(n) > 1}
    assert not dupes, f"duplicate METRIC_NAMES entries: {sorted(dupes)}"
    assert names, "METRIC_NAMES is empty — the gate has nothing to check"
    for name, kind in rows:
        assert "/" in name, f"metric {name!r} is not namespaced (sub/name)"
        assert kind in ("counter", "gauge", "histogram"), \
            f"metric {name!r}: unknown kind {kind!r}"


def test_metric_helper_names_are_registered_literals():
    registered = {n for n, _ in _metric_names_table()}
    problems, used = [], set()
    for rel, tree in _iter_lint_sources():
        if rel == _METRIC_DEFINING_FILE:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            target = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if target not in _METRIC_HELPERS:
                continue
            if not node.args:
                problems.append(f"{rel}:{node.lineno}: {target} without a "
                                f"positional metric name")
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                problems.append(
                    f"{rel}:{node.lineno}: {target} metric name must be a "
                    f"string literal (free-form names defeat the typo "
                    f"gate)")
                continue
            used.add(arg.value)
            if arg.value not in registered:
                problems.append(
                    f"{rel}:{node.lineno}: metric {arg.value!r} is not in "
                    f"observability.metrics.METRIC_NAMES — register it "
                    f"there (typo?)")
    assert not problems, "\n".join(problems)
    assert used, "AST scan found no metric-helper calls — lint is broken"


def test_metric_gate_matches_live_registry():
    """The parsed table and the imported module agree (guards against the
    literal-eval scan drifting from what the registry actually builds)."""
    from paddle_tpu.observability.metrics import METRIC_NAMES
    assert [(n, k) for n, k, _ in METRIC_NAMES] == _metric_names_table()


def test_lint_gate_covers_testing_package():
    """The fault-injection harness (paddle_tpu/testing/) is inside every
    lint's scan set — its metric writes and exception handling are held
    to the same gates as the rest of the package."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/testing/faultinject.py" in rels
    assert "paddle_tpu/testing/__init__.py" in rels
    # and the fault/* names it writes are registered in the frozen table
    registered = {n for n, _ in _metric_names_table()}
    assert "fault/injected" in registered
    assert {n for n in registered if n.startswith("fault/")} >= {
        "fault/injected", "fault/retries", "fault/preemptions",
        "fault/restarts", "fault/checkpoint_saves",
        "fault/checkpoint_restores", "fault/checkpoint_fallbacks",
        "fault/tasks_returned"}


def _top_level_package_imports(pkg: str):
    """(rel, lineno) of every TOP-LEVEL import of ``pkg`` from outside
    its own directory — the static half of a package's zero-cost-when-
    unused contract (lazy imports inside function bodies are fine)."""

    def _is_pkg_import(node):
        if isinstance(node, ast.Import):
            return any(a.name.startswith(f"paddle_tpu.{pkg}")
                       for a in node.names)
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if (mod.startswith(f"paddle_tpu.{pkg}")
                    or mod == pkg or mod.startswith(f"{pkg}.")):
                return True
            # `from paddle_tpu import <pkg>` / `from . import <pkg>`
            # / `from .. import <pkg>` — the package arrives as a NAME,
            # module says nothing about it
            if mod in ("paddle_tpu", "") or node.level > 0:
                return any(a.name == pkg or a.name.startswith(f"{pkg}.")
                           for a in node.names)
        return False

    found = []
    for rel, tree in _iter_sources():
        if rel.startswith(f"paddle_tpu/{pkg}/"):
            continue
        # walk with function-nesting context
        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if _is_pkg_import(child) and not in_func:
                    found.append((rel, child.lineno))
                visit(child, nested)
        visit(tree, False)
    return found


def test_serving_package_only_imported_lazily():
    """Zero-cost-when-unused, statically enforced: no module outside
    paddle_tpu/serving/ may import the serving package at TOP LEVEL —
    only inside a function body (lazy, like the CLI's serve branch).
    This is what guarantees ``import paddle_tpu`` never pulls the
    server; tests/test_serving_chaos.py proves the same fact at runtime
    in a fresh interpreter (under -m slow — a full subprocess import
    costs ~12 s of tier-1 budget)."""
    problems = [
        f"{rel}:{lineno}: top-level import of the serving package — "
        f"must be lazy (inside a function) so `import paddle_tpu` "
        f"stays serving-free"
        for rel, lineno in _top_level_package_imports("serving")]
    assert not problems, "\n".join(problems)
    # and the one sanctioned lazy site exists (the CLI serve branch)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.serving.cli import serve_main" in fh.read()


def test_tuning_package_only_imported_lazily():
    """Same contract for the autotuner: declaring a tunable
    (core.registry.register_tunable) costs nothing, and only an explicit
    autotune opt-in may load paddle_tpu/tuning/ — every call site
    (executor dispatch chunking, reader prefetch defaults, serving
    batcher, flash-attention layer blocks, the CLI tune branch) imports
    it inside a function body.  `import paddle_tpu` stays tuning-free
    (tests/test_tuning.py proves the runtime half)."""
    problems = [
        f"{rel}:{lineno}: top-level import of the tuning package — "
        f"must be lazy (inside a function) so training paths that "
        f"never opt in never load the autotuner"
        for rel, lineno in _top_level_package_imports("tuning")]
    assert not problems, "\n".join(problems)
    # and the ONE sanctioned lazy replay site exists: the shared
    # core.registry.resolve_tuned helper every call site (executor,
    # reader, serving, flash-attention layer, sparse session) now
    # routes through (round-15 dedup of the per-module copies)
    with open(os.path.join(ROOT, "core", "registry.py")) as fh:
        assert "from ..tuning.store import tuned" in fh.read()


def test_lint_gate_covers_serving_package():
    """The serving runtime (paddle_tpu/serving/) is inside every lint's
    scan set — its metric writes and exception handling are held to the
    same gates — and the serving/* names it writes are frozen in the
    METRIC_NAMES table."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/serving/__init__.py" in rels
    assert "paddle_tpu/serving/server.py" in rels
    assert "paddle_tpu/serving/model.py" in rels
    assert "paddle_tpu/serving/cli.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("serving/")} >= {
        "serving/requests", "serving/batches", "serving/shed",
        "serving/deadline_expired", "serving/breaker_open",
        "serving/queue_depth", "serving/batch_size",
        "serving/request_ms"}


def test_registry_matches_ast_scan():
    """The AST scan and the live registry agree — guards against the scan
    silently missing a registration idiom (e.g. names built dynamically)."""
    from paddle_tpu.core.registry import registered_ops

    ast_names = {n for n, _, _ in _registered_names("register_op")}
    live = set(registered_ops())
    # live ⊆ ast: every imported op was visible to the scan.  (ast - live
    # is legitimate: flag-gated modules may not be imported here.)
    missing = live - ast_names
    assert not missing, (
        f"ops registered at runtime but invisible to the AST lint "
        f"(dynamic name construction defeats the duplicate gate): "
        f"{sorted(missing)}")


def test_lint_gate_covers_tuning_package():
    """The autotuner (paddle_tpu/tuning/) is inside every lint's scan
    set — its metric writes and exception handling are held to the same
    gates — and the tuning/* names it writes are frozen in the
    METRIC_NAMES table."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/tuning/__init__.py" in rels
    assert "paddle_tpu/tuning/tunables.py" in rels
    assert "paddle_tpu/tuning/search.py" in rels
    assert "paddle_tpu/tuning/store.py" in rels
    assert "paddle_tpu/tuning/targets.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("tuning/")} >= {
        "tuning/trials", "tuning/trial_ms", "tuning/failures",
        "tuning/winners", "tuning/refusals", "tuning/replays"}


def test_tunable_registry_matches_ast_scan():
    """Agreement gate for the autotuner knob declarations: every live
    register_tunable name is a string literal the duplicate lint can
    see.  (ast - live is legitimate: serving and the flag-gated Pallas
    conv module register lazily.)  Every declared entry must also pass
    the registry's own validation — importing the declaring modules here
    IS that check, since register_tunable validates at call time."""
    import importlib

    from paddle_tpu.core.registry import registered_tunables

    # surface the lazily-imported declarations so live is maximal
    importlib.import_module("paddle_tpu.serving.server")
    importlib.import_module("paddle_tpu.serving.decode")
    importlib.import_module("paddle_tpu.ops.pallas_conv")
    importlib.import_module("paddle_tpu.sparse.session")

    ast_names = {n for n, _, _ in _registered_names("register_tunable")}
    live = set(registered_tunables())
    missing = live - ast_names
    assert not missing, (
        f"tunables registered at runtime but invisible to the AST lint "
        f"(dynamic name construction defeats the duplicate gate): "
        f"{sorted(missing)}")
    assert live >= {"executor/run_pipelined", "reader/prefetch",
                    "serving/batcher", "serving/decode_slots",
                    "pallas/paged_kv_gather", "sparse/hot_rows",
                    "sparse/prefetch", "sparse/push_flush",
                    "pallas/flash_attention",
                    "pallas/conv1x1_blocks", "xla/scoped_vmem_limit_kib",
                    "pallas/fused_optimizer_update",
                    "pallas/lod_gather_scatter"}, \
        f"expected initial tunable coverage missing: {sorted(live)}"
    # the sparse session knobs are HOST-side (measurable in-container,
    # ISSUE 15): they must never ship as pending-hardware stubs
    from paddle_tpu.core.registry import get_tunable as _gt
    for n in ("sparse/hot_rows", "sparse/prefetch", "sparse/push_flush"):
        assert _gt(n)["side"] == "host" and not _gt(n)["pending_hardware"]
    # device-side entries must carry their pre-registered decision rule
    from paddle_tpu.core.registry import get_tunable
    for n in live:
        e = get_tunable(n)
        if e["pending_hardware"]:
            assert e["decision_rule"], \
                f"pending-hardware tunable {n!r} without a decision rule"


# ---------------------------------------------------------------------------
# Span-name gate (paddle_tpu.observability.tracing.SPAN_NAMES) — the
# tracing mirror of the metric gate: every span name passed to span()/
# start_span() must be a string literal frozen in SPAN_NAMES.
# ---------------------------------------------------------------------------
_SPAN_HELPERS = ("span", "start_span")
# the tracing module itself passes names through variables by
# construction (its SPAN_NAMES table is what the gate checks against)
_SPAN_DEFINING_FILE = "paddle_tpu/observability/tracing.py"


def _span_names_table():
    """Names parsed from the SPAN_NAMES literal — no import, so the gate
    also covers a syntactically valid but unimportable state."""
    path = os.path.join(ROOT, "observability", "tracing.py")
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SPAN_NAMES"
                for t in node.targets):
            rows = ast.literal_eval(node.value)
            return [name for name, _help in rows]
    raise AssertionError("SPAN_NAMES literal not found in tracing.py")


def test_span_names_table_well_formed():
    names = _span_names_table()
    dupes = {n for n in names if names.count(n) > 1}
    assert not dupes, f"duplicate SPAN_NAMES entries: {sorted(dupes)}"
    assert names, "SPAN_NAMES is empty — the gate has nothing to check"
    for name in names:
        assert "/" in name, f"span {name!r} is not namespaced (sub/name)"


def test_span_helper_names_are_registered_literals():
    registered = set(_span_names_table())
    problems, used = [], set()
    for rel, tree in _iter_lint_sources():
        if rel == _SPAN_DEFINING_FILE:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            target = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if target not in _SPAN_HELPERS:
                continue
            if not node.args:
                problems.append(f"{rel}:{node.lineno}: {target} without a "
                                f"positional span name")
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                problems.append(
                    f"{rel}:{node.lineno}: {target} span name must be a "
                    f"string literal (free-form names defeat the typo "
                    f"gate)")
                continue
            used.add(arg.value)
            if arg.value not in registered:
                problems.append(
                    f"{rel}:{node.lineno}: span {arg.value!r} is not in "
                    f"observability.tracing.SPAN_NAMES — register it "
                    f"there (typo?)")
    assert not problems, "\n".join(problems)
    assert used, "AST scan found no span-helper calls — lint is broken"
    # the full causal chain is instrumented: every frozen name is LIVE
    # at some call site (a dead table row is a removed instrumentation
    # point, which deserves a conscious table edit)
    assert used == registered, (
        f"SPAN_NAMES and call sites disagree: "
        f"unused={sorted(registered - used)} "
        f"unregistered={sorted(used - registered)}")


def test_span_gate_matches_live_registry():
    from paddle_tpu.observability.tracing import SPAN_NAMES
    assert [n for n, _ in SPAN_NAMES] == _span_names_table()


def test_attribution_module_only_imported_lazily():
    """The doctor engine (observability/attribution.py) pulls
    analysis.cost_model; like serving and tuning, only the opted-in
    surfaces (doctor CLI, bench drivers) may import it — no top-level
    import outside paddle_tpu/observability/, and the observability
    package __init__ itself must not import it either (the `observe`
    hot path stays attribution-free)."""
    problems = []
    for rel, tree in _iter_sources():
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            mod = getattr(node, "module", "") or ""
            names = [a.name for a in node.names]
            hit = (
                ("observability.attribution" in mod)
                or (mod.endswith("observability") and
                    "attribution" in names)
                or (isinstance(node, ast.ImportFrom) and node.level > 0
                    and mod == "" and "attribution" in names)
                or (isinstance(node, ast.ImportFrom) and node.level > 0
                    and mod == "attribution")
                or (isinstance(node, ast.Import) and any(
                    "observability.attribution" in n for n in names)))
            if not hit:
                continue
            if rel == "paddle_tpu/observability/attribution.py":
                continue
            # lazy (inside a function body) is the sanctioned form —
            # detect top-level by column 0 of module/class scope walk
            problems.append((rel, node.lineno))
    # re-scan with function context to keep only TOP-LEVEL hits
    toplevel = []
    for rel, lineno in problems:
        path = os.path.join(ROOT, os.pardir, rel)
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=rel)

        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if getattr(child, "lineno", None) == lineno \
                        and not in_func \
                        and isinstance(child,
                                       (ast.Import, ast.ImportFrom)):
                    toplevel.append(f"{rel}:{lineno}")
                visit(child, nested)
        visit(tree, False)
    assert not toplevel, (
        "top-level import of observability.attribution — must be lazy "
        "(inside a function) so the observe hot path never pays for "
        "the cost model: " + ", ".join(toplevel))
    # and the sanctioned lazy site exists (the doctor CLI branch)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.observability import attribution" \
            in fh.read()


def _top_level_obs_submodule_imports(submod: str):
    """(rel, lineno) of every TOP-LEVEL import of
    ``paddle_tpu/observability/<submod>.py`` from any OTHER module —
    the static half of a lazy-only observability submodule's zero-cost
    contract (attribution and opprof both pull analysis.cost_model;
    opprof additionally pulls tuning.search)."""
    target = f"observability.{submod}"
    own = f"paddle_tpu/observability/{submod}.py"

    def _is_hit(node):
        mod = getattr(node, "module", "") or ""
        names = [a.name for a in node.names]
        return (
            (target in mod)
            or (mod.endswith("observability") and submod in names)
            or (isinstance(node, ast.ImportFrom) and node.level > 0
                and mod == "" and submod in names)
            or (isinstance(node, ast.ImportFrom) and node.level > 0
                and mod == submod)
            or (isinstance(node, ast.Import) and any(
                target in n for n in names)))

    found = []
    for rel, tree in _iter_sources():
        if rel == own:
            continue

        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if isinstance(child, (ast.Import, ast.ImportFrom)) \
                        and not in_func and _is_hit(child):
                    found.append(f"{rel}:{child.lineno}")
                visit(child, nested)
        visit(tree, False)
    return found


def test_opprof_module_only_imported_lazily():
    """The per-op profiler (observability/opprof.py) pulls
    analysis.cost_model AND tuning.search; like attribution, only the
    opted-in surfaces (profile/doctor CLI branches, benchmark driver)
    may import it — no top-level import anywhere else, and the
    observability package __init__ must not import it (the `observe`
    hot path stays profiler-free)."""
    toplevel = _top_level_obs_submodule_imports("opprof")
    assert not toplevel, (
        "top-level import of observability.opprof — must be lazy "
        "(inside a function) so training paths never pay for the "
        "cost-model/tuning import chain: " + ", ".join(toplevel))
    # and the sanctioned lazy sites exist (profile + doctor --per-op)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        src = fh.read()
    assert "from paddle_tpu.observability import opprof" in src


def test_lint_gate_covers_opprof_module():
    """observability/opprof.py is inside every lint's scan set, its
    opprof/* metric names are frozen in METRIC_NAMES, and its span name
    is frozen in SPAN_NAMES (the used==registered span check then keeps
    the walk instrumented)."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/observability/opprof.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("opprof/")} >= {
        "opprof/runs", "opprof/ops", "opprof/op_ms"}
    assert "opprof/op" in set(_span_names_table())


def test_collector_module_only_imported_lazily():
    """The fleet metrics collector (observability/collector.py) can dial
    sockets and pull the sparse wire stack — only the opted-in surfaces
    (the fleet-stats CLI branch, library callers inside a function) may
    import it.  No top-level import anywhere else, and the observability
    package __init__ must not import it (importing
    paddle_tpu.observability stays cheap and socket-free)."""
    toplevel = _top_level_obs_submodule_imports("collector")
    assert not toplevel, (
        "top-level import of observability.collector — must be lazy "
        "(inside a function) so importing the observability package "
        "never pays for the collector's socket/wire stack: "
        + ", ".join(toplevel))
    # and the sanctioned lazy site exists (the fleet-stats CLI branch)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.observability import collector" \
            in fh.read()


def test_lint_gate_covers_collector_module():
    """observability/collector.py is inside every lint's scan set and
    its collector/* metric names are frozen in METRIC_NAMES, so its
    helper calls ride the literal-name typo gate."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/observability/collector.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("collector/")} >= {
        "collector/merges", "collector/sources"}
    assert {n for n in registered if n.startswith("trace/")} >= {
        "trace/context_rejected"}


# ---------------------------------------------------------------------------
# Tier-1 time-budget guard: subprocess rounds must be @slow.  Each
# jax-importing subprocess costs ~10-30s of the 870s tier-1 cap (the
# suite runs at ~95% of it on this container); the PR 6/8/9/11
# convention pushes them to `-m slow`.  Frozen allowlist below: the few
# CHEAP subprocess tests deliberately kept tier-1 — never add entries,
# only remove them (the ratchet direction mirrors the except-swallow
# gate).
# ---------------------------------------------------------------------------
SUBPROCESS_FAST_ALLOWLIST = {
    # ~4s: the only cross-process coverage of the master's lease-lapse
    # re-serve (a dead trainer's task re-queues for a healthy one)
    "tests/test_master_service.py": {
        "test_elastic_trainer_death_cross_process"},
    # pre-existing CPU-backend collectives round (known-failing where
    # multiprocess CPU collectives are unimplemented; kept tier-1 so a
    # chip/GPU session surfaces it immediately)
    "tests/test_multiprocess_launch.py": {
        "test_two_process_distributed_train_and_checkpoint"},
}


def _iter_test_sources():
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    for f in sorted(os.listdir(tests_dir)):
        if f.startswith("test_") and f.endswith(".py"):
            path = os.path.join(tests_dir, f)
            with open(path) as fh:
                yield f"tests/{f}", ast.parse(fh.read(), filename=path)


def _mentions_slow(node) -> bool:
    return "slow" in ast.dump(node)


def test_subprocess_test_rounds_are_slow_marked():
    problems = []
    for rel, tree in _iter_test_sources():
        module_slow = any(
            isinstance(node, ast.Assign)
            and any(getattr(t, "id", None) == "pytestmark"
                    for t in node.targets)
            and _mentions_slow(node.value)
            for node in tree.body)
        if module_slow:
            continue
        # module-level helpers whose body touches subprocess: a test
        # calling one is a subprocess test (the _run(...) idiom)
        def touches_subprocess(fn):
            return any(isinstance(n, ast.Name) and n.id == "subprocess"
                       for n in ast.walk(fn))
        helpers = {node.name for node in tree.body
                   if isinstance(node, ast.FunctionDef)
                   and not node.name.startswith("test_")
                   and touches_subprocess(node)}

        def is_subprocess_test(fn):
            if touches_subprocess(fn):
                return True
            for n in ast.walk(fn):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id in helpers:
                    return True
            return False

        allowed = SUBPROCESS_FAST_ALLOWLIST.get(rel, set())
        for node in tree.body:
            if not (isinstance(node, ast.FunctionDef)
                    and node.name.startswith("test_")):
                continue
            if not is_subprocess_test(node):
                continue
            if any(_mentions_slow(d) for d in node.decorator_list):
                continue
            if node.name in allowed:
                continue
            problems.append(
                f"{rel}:{node.lineno}: {node.name} spawns a subprocess "
                f"but is not @pytest.mark.slow — each jax-importing "
                f"round costs ~10-30s of the 870s tier-1 cap; mark it "
                f"slow (PR 6/8/9/11 convention) or argue it into the "
                f"frozen SUBPROCESS_FAST_ALLOWLIST")
    assert not problems, "\n".join(problems)
    # the allowlist itself stays honest: every entry still exists
    by_file = {rel: {node.name for node in tree.body
                     if isinstance(node, ast.FunctionDef)}
               for rel, tree in _iter_test_sources()}
    for rel, names in SUBPROCESS_FAST_ALLOWLIST.items():
        missing = names - by_file.get(rel, set())
        assert not missing, (
            f"{rel}: allowlisted subprocess test(s) no longer exist — "
            f"ratchet SUBPROCESS_FAST_ALLOWLIST down: {sorted(missing)}")


def _top_level_serving_submodule_imports(submods=("http", "fleet")):
    """(rel, lineno) of every TOP-LEVEL import of
    paddle_tpu/serving/{http,fleet}.py from any OTHER module — including
    serving/__init__.py and serving/cli.py: importing paddle_tpu.serving
    (the Server surface) must not load the network front or the fleet
    router.  Lazy imports inside function bodies are the sanctioned
    form.  Careful with stdlib collisions: absolute ``import
    http.client`` is NOT a hit."""
    own = {f"paddle_tpu/serving/{m}.py" for m in submods}

    def _is_hit(node, rel):
        in_serving = rel.startswith("paddle_tpu/serving/")
        full = tuple(f"paddle_tpu.serving.{m}" for m in submods)
        if isinstance(node, ast.Import):
            return any(a.name.startswith(full) for a in node.names)
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith(full):
                return True
            if mod in ("paddle_tpu.serving", "serving"):
                return any(a.name in submods for a in node.names)
            if node.level > 0 and in_serving:
                # from .http import X / from . import http
                if mod in submods:
                    return True
                if mod == "" and any(a.name in submods
                                     for a in node.names):
                    return True
        return False

    found = []
    for rel, tree in _iter_sources():
        if rel in own:
            continue

        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if _is_hit(child, rel) and not in_func:
                    found.append((rel, child.lineno))
                visit(child, nested)
        visit(tree, False)
    return found


def test_http_and_fleet_modules_only_imported_lazily():
    """Zero-cost-when-unused for the NEW serving-fleet modules (ISSUE
    11): importing paddle_tpu — or paddle_tpu.serving itself, i.e.
    running a plain Server — loads neither serving/http.py nor
    serving/fleet.py.  Only the opted-in surfaces (`serve --http`, the
    `fleet` CLI branch) may import them, lazily.
    tests/test_fleet_chaos.py proves the runtime half in a fresh
    interpreter (@slow)."""
    problems = [
        f"{rel}:{lineno}: top-level import of serving.http/serving.fleet "
        f"— must be lazy (inside a function) so `import paddle_tpu"
        f".serving` stays front/fleet-free"
        for rel, lineno in _top_level_serving_submodule_imports()]
    assert not problems, "\n".join(problems)
    # and the sanctioned lazy sites exist
    with open(os.path.join(ROOT, "serving", "cli.py")) as fh:
        assert "from .http import HttpFront" in fh.read()   # serve --http
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.serving.fleet import fleet_main" \
            in fh.read()                                    # fleet branch
    with open(os.path.join(ROOT, "serving", "fleet.py")) as fh:
        assert "from .http import HttpFront" in fh.read()   # fleet_main


def test_lint_gate_covers_http_and_fleet_modules():
    """serving/http.py + serving/fleet.py are inside every lint's scan
    set, their http/* + fleet/* metric names are frozen in METRIC_NAMES,
    and their span names are frozen in SPAN_NAMES (the used==registered
    span check then keeps both instrumented)."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/serving/http.py" in rels
    assert "paddle_tpu/serving/fleet.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("http/")} >= {
        "http/requests", "http/rejected", "http/auth_failures",
        "http/request_ms"}
    assert {n for n in registered if n.startswith("fleet/")} >= {
        "fleet/requests", "fleet/failovers", "fleet/evictions",
        "fleet/relaunches", "fleet/router_shed", "fleet/scale_outs",
        "fleet/scale_ins", "fleet/replicas"}
    spans = set(_span_names_table())
    assert {"http/request", "fleet/autoscale"} <= spans


def _top_level_distributed_submodule_imports(submod: str):
    """(rel, lineno) of every TOP-LEVEL import of
    ``paddle_tpu/distributed/<submod>.py`` from any OTHER module —
    including distributed/__init__.py: importing paddle_tpu (or the
    distributed package for its Master/Supervisor surface) must not
    load the elastic service."""
    target = f"distributed.{submod}"
    own = f"paddle_tpu/distributed/{submod}.py"

    def _is_hit(node, rel):
        in_pkg = rel.startswith("paddle_tpu/distributed/")
        mod = getattr(node, "module", "") or ""
        names = [a.name for a in node.names]
        if isinstance(node, ast.Import):
            return any(f"paddle_tpu.{target}" in n for n in names)
        if target in mod:
            return True
        if mod.endswith("distributed") and submod in names:
            return True
        if node.level > 0 and in_pkg:
            # from .elastic import X / from . import elastic
            if mod == submod:
                return True
            if mod == "" and submod in names:
                return True
        return False

    found = []
    for rel, tree in _iter_sources():
        if rel == own:
            continue

        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if isinstance(child, (ast.Import, ast.ImportFrom)) \
                        and not in_func and _is_hit(child, rel):
                    found.append(f"{rel}:{child.lineno}")
                visit(child, nested)
        visit(tree, False)
    return found


def test_elastic_module_only_imported_lazily():
    """Zero-cost-when-unused for the elastic training service (ISSUE
    13): importing paddle_tpu — or paddle_tpu.distributed itself, i.e.
    using Master/Supervisor/CheckpointManager — loads neither the
    elastic coordinator nor its analysis/planner import chain.  Only
    the opted-in surfaces (the `elastic` CLI branch, an explicit
    `from paddle_tpu.distributed.elastic import ...`) may load it,
    lazily."""
    toplevel = _top_level_distributed_submodule_imports("elastic")
    assert not toplevel, (
        "top-level import of distributed.elastic — must be lazy "
        "(inside a function) so `import paddle_tpu` stays "
        "elastic-free: " + ", ".join(toplevel))
    # and the sanctioned lazy site exists (the CLI elastic branch)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.distributed.elastic import elastic_main" \
            in fh.read()
    # the distributed package __init__ must not re-export it either
    with open(os.path.join(ROOT, "distributed", "__init__.py")) as fh:
        assert "elastic" not in fh.read()


def test_lint_gate_covers_elastic_module():
    """distributed/elastic.py is inside every lint's scan set, its
    elastic/* metric names are frozen in METRIC_NAMES, its span name is
    frozen in SPAN_NAMES (the used==registered check then keeps the
    resize boundary instrumented), and the new injection sites are
    registered in the faultinject harness."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/distributed/elastic.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("elastic/")} >= {
        "elastic/workers", "elastic/heartbeats", "elastic/drains",
        "elastic/resizes", "elastic/resize_ms"}
    assert "elastic/resize" in set(_span_names_table())
    from paddle_tpu.testing.faultinject import KNOWN_SITES
    assert {"elastic.worker", "master.heartbeat"} <= set(KNOWN_SITES)


def test_sparse_package_only_imported_lazily():
    """Zero-cost-when-unused for the sparse parameter server (ISSUE 14):
    importing paddle_tpu — or running an Executor/Trainer without
    sparse_tables — never loads paddle_tpu/sparse/.  The trainer wiring
    is DUCK-TYPED (train(sparse_tables=session) calls methods on the
    session object), so no module outside the package needs even a lazy
    import; the one sanctioned lazy site is the reverse direction —
    sparse/session.py pulling serving.Model for the serve attachment —
    which lives inside the package and stays lazy for serving's own
    gate."""
    problems = [
        f"{rel}:{lineno}: top-level import of the sparse package — "
        f"must be lazy (inside a function) so `import paddle_tpu` and "
        f"every non-sparse training path stay sparse-free"
        for rel, lineno in _top_level_package_imports("sparse")]
    assert not problems, "\n".join(problems)
    # the serving attachment inside the package is itself lazy (the
    # serving gate would reject a top-level form; assert the sanctioned
    # lazy site exists so the attachment cannot silently disappear)
    with open(os.path.join(ROOT, "sparse", "session.py")) as fh:
        assert "from ..serving.model import Model" in fh.read()


def test_lint_gate_covers_sparse_package():
    """paddle_tpu/sparse/ is inside every lint's scan set, its sparse/*
    metric names are frozen in METRIC_NAMES, its pull/push span pair is
    frozen in SPAN_NAMES (the used==registered check then keeps the rim
    instrumented), and the sparse.push injection site is registered in
    the faultinject harness."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/sparse/__init__.py" in rels
    assert "paddle_tpu/sparse/table.py" in rels
    assert "paddle_tpu/sparse/session.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("sparse/")} >= {
        "sparse/pulls", "sparse/pulled_rows", "sparse/pushes",
        "sparse/pushed_rows", "sparse/pull_ms", "sparse/push_ms",
        "sparse/cache_hits", "sparse/cache_misses", "sparse/live_rows"}
    spans = set(_span_names_table())
    assert {"sparse/pull", "sparse/push"} <= spans
    from paddle_tpu.testing.faultinject import KNOWN_SITES
    assert "sparse.push" in KNOWN_SITES


def _top_level_sparse_submodule_imports(
        submods=("wire", "pserver", "client")):
    """(rel, lineno) of every TOP-LEVEL import of the sparse WIRE TIER
    (paddle_tpu/sparse/{wire,pserver,client}.py) from any module outside
    the tier itself — including sparse/__init__.py, table.py and
    session.py: importing paddle_tpu.sparse (the in-process
    SparseTable/SparseSession surface) must not load a socket stack.
    Lazy imports inside function bodies are the sanctioned form."""
    own = {f"paddle_tpu/sparse/{m}.py" for m in submods}

    def _is_hit(node, rel):
        in_sparse = rel.startswith("paddle_tpu/sparse/")
        full = tuple(f"paddle_tpu.sparse.{m}" for m in submods)
        if isinstance(node, ast.Import):
            return any(a.name.startswith(full) for a in node.names)
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith(full):
                return True
            if mod in ("paddle_tpu.sparse", "sparse"):
                return any(a.name in submods for a in node.names)
            if node.level > 0 and in_sparse:
                # from .wire import X / from . import wire
                if mod in submods:
                    return True
                if mod == "" and any(a.name in submods
                                     for a in node.names):
                    return True
        return False

    found = []
    for rel, tree in _iter_sources():
        if rel in own:
            continue

        def visit(node, in_func):
            for child in ast.iter_child_nodes(node):
                nested = in_func or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef))
                if _is_hit(child, rel) and not in_func:
                    found.append((rel, child.lineno))
                visit(child, nested)
        visit(tree, False)
    return found


def test_pserver_wire_tier_only_imported_lazily():
    """Zero-cost-when-unused for the sparse parameter-server WIRE tier
    (ISSUE 17): importing paddle_tpu — or paddle_tpu.sparse itself,
    i.e. running the in-process table — loads none of sparse/wire.py,
    sparse/pserver.py, sparse/client.py.  Only the opted-in surfaces
    (the `pserver` CLI branch, an explicit `from
    paddle_tpu.sparse.client import RemoteSparseTable`) may load them,
    lazily."""
    problems = [
        f"{rel}:{lineno}: top-level import of the sparse wire tier — "
        f"must be lazy (inside a function) so `import "
        f"paddle_tpu.sparse` stays socket-free"
        for rel, lineno in _top_level_sparse_submodule_imports()]
    assert not problems, "\n".join(problems)
    # and the sanctioned lazy site exists (the CLI pserver branch)
    with open(os.path.join(ROOT, "cli.py")) as fh:
        assert "from paddle_tpu.sparse.pserver import pserver_main" \
            in fh.read()
    # the sparse package __init__ must not re-export the tier either
    with open(os.path.join(ROOT, "sparse", "__init__.py")) as fh:
        body = fh.read().split('"""', 2)[2]      # docstring MAY name it
        for mod in ("wire", "pserver", "client"):
            assert f"import {mod}" not in body


def test_lint_gate_covers_pserver_tier():
    """sparse/{wire,pserver,client}.py are inside every lint's scan
    set, the pserver/* metric names are frozen in METRIC_NAMES, the
    pserver/rpc span is frozen in SPAN_NAMES (the used==registered
    check then keeps the client round instrumented), and the chaos
    sites are registered in the faultinject harness."""
    rels = {rel for rel, _ in _iter_sources()}
    assert "paddle_tpu/sparse/wire.py" in rels
    assert "paddle_tpu/sparse/pserver.py" in rels
    assert "paddle_tpu/sparse/client.py" in rels
    registered = {n for n, _ in _metric_names_table()}
    assert {n for n in registered if n.startswith("pserver/")} >= {
        "pserver/requests", "pserver/pull_rows", "pserver/push_rows",
        "pserver/wire_bytes_in", "pserver/wire_bytes_out",
        "pserver/frame_ms", "pserver/reconnects",
        "pserver/replication_lag_ms", "pserver/backup_pushes",
        "pserver/checkpoints"}
    assert "pserver/rpc" in set(_span_names_table())
    from paddle_tpu.testing.faultinject import KNOWN_SITES
    assert {"pserver.rpc", "pserver.shard"} <= set(KNOWN_SITES)


def test_shard_fn_registry_matches_ast_scan():
    """Same agreement gate for the sharding-propagation rules: every
    live register_shard_fn name is a string literal the duplicate lint
    can see, and every rule targets a registered op (a rule for a
    nonexistent op would never fire — a silent planner blind spot)."""
    from paddle_tpu.core.registry import (registered_ops,
                                          registered_shard_fns)

    ast_names = {n for n, _, _ in _registered_names("register_shard_fn")}
    live = set(registered_shard_fns())
    missing = live - ast_names
    assert not missing, (
        f"shard fns registered at runtime but invisible to the AST lint: "
        f"{sorted(missing)}")
    stale = live - set(registered_ops())
    assert not stale, (
        f"shard fns for unregistered ops (dead rules): {sorted(stale)}")
    assert live, "no shard fns registered — the planner has no rules"

# ---------------------------------------------------------------------------
# Thread-name-prefix gate (observability.metrics.THREAD_NAME_PREFIXES)
# ---------------------------------------------------------------------------
def _thread_prefix_table():
    """(prefix, help) rows parsed from the THREAD_NAME_PREFIXES literal —
    no import, same contract as the metric-name gate."""
    path = os.path.join(ROOT, "observability", "metrics.py")
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "THREAD_NAME_PREFIXES"
                for t in node.targets):
            return list(ast.literal_eval(node.value))
    raise AssertionError(
        "THREAD_NAME_PREFIXES literal not found in metrics.py")


def test_thread_prefix_table_well_formed():
    rows = _thread_prefix_table()
    assert rows, "THREAD_NAME_PREFIXES is empty — PT055 has no registry"
    prefixes = [p for p, _help in rows]
    dupes = {p for p in prefixes if prefixes.count(p) > 1}
    assert not dupes, f"duplicate thread prefixes: {sorted(dupes)}"
    for p, help_ in rows:
        assert p.startswith("pt-"), (
            f"thread prefix {p!r} must claim the framework's pt- "
            f"namespace")
        assert len(p) > len("pt-"), f"thread prefix {p!r} is bare"
        assert help_.strip(), f"thread prefix {p!r} has no help text"
    # no prefix may shadow another (pt-a and pt-a-b would make the
    # runtime attribution of a pt-a-b-* thread ambiguous)
    for a in prefixes:
        for b in prefixes:
            assert a == b or not b.startswith(a + "-"), (
                f"thread prefix {b!r} is shadowed by {a!r}")


def test_thread_prefix_gate_matches_live_registry():
    from paddle_tpu.observability.metrics import THREAD_NAME_PREFIXES
    assert list(THREAD_NAME_PREFIXES) == _thread_prefix_table()


# ---------------------------------------------------------------------------
# Concurrency verifier gate (analysis.concurrency, PT05x):
# the current tree must be clean modulo the FROZEN baseline, and the
# baseline can only shrink (the except-swallow ratchet convention).
# ---------------------------------------------------------------------------
def test_concurrency_baseline_well_formed():
    from paddle_tpu.analysis.concurrency import BASELINE
    for (rel, code), (count, why) in BASELINE.items():
        assert rel.startswith("paddle_tpu/"), (rel, code)
        assert code.startswith("PT05"), (
            f"baseline key {code!r} is not a PT05x concurrency code")
        assert count >= 1, (
            f"baseline entry {(rel, code)} permits {count} findings — "
            f"zero-count entries are dead weight; delete them")
        assert why.strip(), (
            f"baseline entry {(rel, code)} has no justification — every "
            f"accepted finding carries a one-line why")


def test_concurrency_tree_clean_vs_baseline():
    """Tier-1 ratchet: the PT05x pass over today's tree yields NO findings
    beyond the frozen baseline, and no baseline entry budgets MORE
    findings than remain (fix-or-justify, count-can-only-shrink)."""
    from paddle_tpu.analysis import concurrency as cc

    findings = cc.analyze_package()
    new, _suppressed, stale = cc.apply_baseline(findings)
    assert not new, (
        "new concurrency findings (fix them or — only for accepted-by-"
        "design sites — add a justified BASELINE entry):\n"
        + "\n".join(f.render() for f in new))
    assert not stale, (
        f"stale BASELINE entries budget more findings than remain — "
        f"ratchet them down so the count can only shrink: {stale}")


def test_concurrency_pass_covers_threaded_modules():
    """The analyzer's scan set is the same walk as every other lint —
    pin that the threaded modules it exists for are actually inside it,
    and that the pass sees their locks (a lock-model regression that
    finds NO locks would pass the ratchet vacuously)."""
    from paddle_tpu.analysis import concurrency as cc

    rels = {rel for rel, _ in _iter_sources()}
    for mod in ("paddle_tpu/serving/server.py",
                "paddle_tpu/serving/decode.py",
                "paddle_tpu/serving/fleet.py",
                "paddle_tpu/sparse/session.py",
                "paddle_tpu/distributed/master.py",
                "paddle_tpu/distributed/checkpoint.py",
                "paddle_tpu/reader/pipeline.py",
                "paddle_tpu/observability/export.py"):
        assert mod in rels, f"{mod} missing from the lint scan set"
    # the model sees the watched-factory idiom as locks: server.py's
    # runtime condition + state locks must resolve, else PT050-053
    # silently cover nothing
    path = os.path.join(ROOT, "serving", "server.py")
    with open(path) as fh:
        src = fh.read()
    import paddle_tpu.analysis.concurrency as ccmod
    tree = ast.parse(src)
    mm = ccmod._ModuleModel(tree, "paddle_tpu/serving/server.py")
    kinds = set(mm.attr_kind_index.values())
    assert {"lock", "cond"} <= kinds, (
        f"concurrency model no longer resolves server.py's locks/"
        f"conditions (saw kinds {sorted(kinds)}) — the PT05x rules "
        f"would run vacuously")


def test_lockwatch_factories_adopted_in_threaded_modules():
    """The serving/sparse/distributed lock creation sites route through
    testing.lockwatch factories (make_lock/make_rlock/make_condition),
    so enabling PADDLE_TPU_LOCKWATCH actually watches them; raw
    threading.Lock() in these modules would silently escape the
    watchdog.  Infrastructure locks are exempt BY DESIGN: the metrics
    registry's own lock (lockwatch writes metrics — recursion), the
    compile cache and profiler (leaf locks on paths the watchdog
    traverses), and lockwatch itself."""
    exempt = {
        "paddle_tpu/observability/metrics.py",
        "paddle_tpu/core/compile_cache.py",
        "paddle_tpu/profiler.py",
        "paddle_tpu/testing/lockwatch.py",
        "paddle_tpu/testing/faultinject.py",
    }
    offenders = []
    for rel, tree in _iter_sources():
        if rel in exempt:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "threading" \
                    and fn.attr in ("Lock", "RLock", "Condition"):
                offenders.append(f"{rel}:{node.lineno}: threading."
                                 f"{fn.attr}()")
    assert not offenders, (
        "raw threading primitives outside the exempt infrastructure "
        "set — route them through testing.lockwatch factories so the "
        "order watchdog can see them:\n" + "\n".join(offenders))
