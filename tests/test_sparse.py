"""Unit tests for the sparse parameter server (paddle_tpu/sparse/):
table store (lazy init, shard invariance, optimizer slot math,
checkpoint round-trip across shard-count changes, mmap storage),
session rim (dedup/inverse/bucketing, hot-cache invalidation-on-push,
fault injection at sparse.push), and the DataFeeder id-hardening
satellite."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.faults import InjectedFault, RetryPolicy
from paddle_tpu.sparse import (PAD_ID, SparseSession, SparseTable,
                               table_specs, tables_for_program)
from paddle_tpu.testing import faultinject


# ---------------------------------------------------------------------------
# SparseTable
# ---------------------------------------------------------------------------
def test_lazy_init_deterministic_across_shard_counts():
    ids = np.array([3, 99, 7, 42, 3], np.int64)
    t1 = SparseTable("t", 100, 4, num_shards=1, seed=11)
    t4 = SparseTable("t", 100, 4, num_shards=4, seed=11)
    r1, r4 = t1.pull(ids), t4.pull(ids)
    assert np.array_equal(r1, r4)
    # duplicate id pulls identical rows; re-pull is stable
    assert np.array_equal(r1[0], r1[4])
    assert np.array_equal(t1.pull(ids), r1)
    # only unique ids materialized
    assert t1.live_rows == 4
    assert t1.rows_initialized == 4
    # a different seed draws different rows
    t_other = SparseTable("t", 100, 4, seed=12)
    assert not np.array_equal(t_other.pull(ids), r1)


def test_pad_id_pulls_zero_and_push_skips():
    t = SparseTable("t", 10, 3, learning_rate=1.0)
    ids = np.array([1, PAD_ID, 2], np.int64)
    rows = t.pull(ids)
    assert np.array_equal(rows[1], np.zeros(3, np.float32))
    before = t.pull(np.array([1, 2], np.int64))
    n = t.push(ids, np.ones((3, 3), np.float32))
    assert n == 2                      # pad slot skipped
    after = t.pull(np.array([1, 2], np.int64))
    assert np.allclose(after, before - 1.0)


def test_sgd_and_adagrad_slot_math():
    g = np.array([[0.5, -2.0]], np.float32)
    t = SparseTable("t", 4, 2, optimizer="sgd", learning_rate=0.1,
                    initializer=("constant", 1.0))
    t.push(np.array([2], np.int64), g)
    want = (np.float64(1.0) - np.float64(0.1) * g.astype(np.float64)
            ).astype(np.float32)
    assert np.array_equal(t.pull(np.array([2], np.int64)), want)

    ta = SparseTable("t", 4, 2, optimizer="adagrad", learning_rate=0.1,
                     epsilon=1e-6, initializer=("constant", 1.0))
    ta.push(np.array([2], np.int64), g)
    m = (g.astype(np.float64) ** 2).astype(np.float32)
    assert np.array_equal(ta.pull_slot("moment", np.array([2], np.int64)),
                          m)
    want = np.float32(1.0) - np.float32(0.1) * g / \
        (np.sqrt(m) + np.float32(1e-6))
    assert np.array_equal(ta.pull(np.array([2], np.int64)), want)
    # untouched row has zero slot state
    assert np.array_equal(ta.pull_slot("moment", np.array([1], np.int64)),
                          np.zeros((1, 2), np.float32))


def test_push_validation():
    t = SparseTable("t", 10, 2)
    with pytest.raises(ValueError, match="duplicates"):
        t.push(np.array([1, 1], np.int64), np.zeros((2, 2), np.float32))
    with pytest.raises(ValueError, match="shape"):
        t.push(np.array([1], np.int64), np.zeros((2, 2), np.float32))
    with pytest.raises(ValueError, match="out-of-vocab"):
        t.push(np.array([10], np.int64), np.zeros((1, 2), np.float32))
    with pytest.raises(ValueError, match="negative"):
        t.pull(np.array([-2], np.int64))
    with pytest.raises(ValueError, match="integral"):
        t.pull(np.array([1.5]))


def test_export_restore_across_shard_count_change():
    t = SparseTable("t", 50, 4, optimizer="adagrad", learning_rate=0.1,
                    num_shards=4, seed=3)
    ids = np.array([0, 7, 13, 49], np.int64)
    t.pull(ids)
    t.push(ids, np.random.RandomState(0).randn(4, 4).astype(np.float32))
    state = t.export_state_vars()
    # restore under a DIFFERENT shard count: same rows, same slots
    t2 = SparseTable("t", 50, 4, optimizer="adagrad", learning_rate=0.1,
                     num_shards=2, seed=3)
    t2.restore_state_vars(state)
    assert np.array_equal(t.pull(ids), t2.pull(ids))
    assert np.array_equal(t.pull_slot("moment", ids),
                          t2.pull_slot("moment", ids))
    assert t2.live_rows == t.live_rows
    # lazy init of a NEW id continues identically after restore
    new = np.array([21], np.int64)
    assert np.array_equal(t.pull(new), t2.pull(new))
    # export is deterministic (sorted ids): byte-identical re-export
    s1, s2 = t.export_state_vars(), t.export_state_vars()
    assert sorted(s1) == sorted(s2)
    for k in s1:
        assert np.array_equal(s1[k], s2[k])


def test_restore_mismatch_rejected():
    t = SparseTable("t", 50, 4)
    state = t.export_state_vars()
    with pytest.raises(ValueError, match="dim"):
        SparseTable("t", 50, 8).restore_state_vars(state)
    with pytest.raises(ValueError, match="optimizer"):
        SparseTable("t", 50, 4,
                    optimizer="adagrad").restore_state_vars(state)
    with pytest.raises(ValueError, match="no.*state|carries no"):
        SparseTable("other", 50, 4).restore_state_vars(state)


def test_standalone_save_load(tmp_path):
    t = SparseTable("t", 30, 4, optimizer="adagrad", num_shards=3, seed=9)
    ids = np.array([1, 2, 28], np.int64)
    t.push(ids, np.ones((3, 4), np.float32))
    d = str(tmp_path / "table")
    t.save(d)
    t2 = SparseTable.load(d, num_shards=2)
    assert t2.optimizer == "adagrad" and t2.num_shards == 2
    assert np.array_equal(t.pull(ids), t2.pull(ids))
    assert np.array_equal(t.pull_slot("moment", ids),
                          t2.pull_slot("moment", ids))


def test_mmap_storage_parity(tmp_path):
    mem = SparseTable("t", 40, 4, optimizer="adagrad", seed=2)
    mm = SparseTable("t", 40, 4, optimizer="adagrad", seed=2,
                     num_shards=2, storage="mmap",
                     storage_dir=str(tmp_path))
    rng = np.random.RandomState(1)
    for _ in range(5):
        ids = np.unique(rng.randint(0, 40, 12).astype(np.int64))
        g = rng.randn(len(ids), 4).astype(np.float32)
        mem.push(ids, g)
        mm.push(ids, g)
    allids = np.arange(40, dtype=np.int64)
    assert np.array_equal(mem.pull(allids), mm.pull(allids))
    assert np.array_equal(mem.pull_slot("moment", allids),
                          mm.pull_slot("moment", allids))
    assert mm.host_bytes() == mem.host_bytes()
    assert os.listdir(str(tmp_path / "t"))   # spool files exist


def test_dense_initializer_and_budget_accounting():
    w = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    t = SparseTable("t", 20, 4, initializer=("dense", w))
    ids = np.array([0, 19, 5], np.int64)
    assert np.array_equal(t.pull(ids), w[ids])
    assert t.dense_bytes() == 20 * 4 * 4
    assert t.host_bytes() == 3 * 4 * 4     # rows only (sgd: no slots)


# ---------------------------------------------------------------------------
# SparseSession rim
# ---------------------------------------------------------------------------
def _sparse_program(vocab=32, dim=4, name="tbl"):
    ids = layers.data("ids", shape=[1], dtype="int64")
    label = layers.data("label", shape=[1], dtype="float32")
    emb = layers.embedding(ids, size=[vocab, dim], sparse=True, name=name)
    fc = layers.fc(emb, size=1)
    loss = layers.mean(layers.square(fc - label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def test_table_specs_and_builder():
    _sparse_program(vocab=64, dim=8)
    specs = table_specs(pt.default_main_program())
    assert specs == [{"name": "tbl", "vocab_size": 64, "dim": 8,
                      "dtype": "float32"}]
    tables = tables_for_program(pt.default_main_program(),
                                optimizer="adagrad", num_shards=2)
    assert set(tables) == {"tbl"}
    assert tables["tbl"].optimizer == "adagrad"


def test_prepare_feed_dedup_inverse_and_bucket():
    _sparse_program(vocab=32, dim=4)
    t = SparseTable("tbl", 32, 4, seed=1)
    sess = SparseSession(t, bucket_floor=8)
    sess.bind(pt.default_main_program())
    ids = np.array([[5], [9], [5], [30], [9], [9]], np.int64)
    feed = sess.prepare_feed({"ids": ids, "label": np.zeros((6, 1),
                                                           np.float32)})
    rows, inv = feed["tbl@ROWS"], feed["tbl@RIDX"]
    assert rows.shape == (8, 4)            # 3 unique -> bucket 8
    assert inv.shape == (6,) and inv.dtype == np.int32
    # the device gather reconstructs the per-position rows exactly
    gathered = rows[inv]
    direct = t.pull(ids.reshape(-1))
    assert np.array_equal(gathered, direct)
    assert sess.pending_batches == 1
    # bucketing keeps the compiled signature stable across batches with
    # different unique counts (up to the bucket)
    feed2 = sess.prepare_feed(
        {"ids": np.array([[1]] * 6, np.int64),
         "label": np.zeros((6, 1), np.float32)})
    assert feed2["tbl@ROWS"].shape == (8, 4)
    # inference mode enqueues nothing
    sess.prepare_feed({"ids": ids, "label": np.zeros((6, 1), np.float32)},
                      is_test=True)
    assert sess.pending_batches == 2


def test_session_actionable_errors():
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4)
    sess = SparseSession(t)
    with pytest.raises(RuntimeError, match="bind"):
        sess.prepare_feed({"ids": np.zeros((1, 1), np.int64)})
    sess.bind(pt.default_main_program())
    with pytest.raises(KeyError, match="ids"):
        sess.prepare_feed({"label": np.zeros((1, 1), np.float32)})
    with pytest.raises(ValueError, match="outside the declared vocab"):
        sess.prepare_feed({"ids": np.array([[16]], np.int64)})
    with pytest.raises(ValueError, match="outside the declared vocab"):
        sess.prepare_feed({"ids": np.array([[-1]], np.int64)})
    with pytest.raises(ValueError, match="integral"):
        sess.prepare_feed({"ids": np.array([[1.5]])})
    with pytest.raises(ValueError, match="object array"):
        sess.prepare_feed({"ids": np.array([[1], [2, 3]], dtype=object)})
    # int32 coerces fine (canonical int64)
    feed = sess.prepare_feed({"ids": np.array([[3]], np.int32)})
    assert feed["tbl@ROWS"].shape[1] == 4
    # mismatched table declaration
    bad = SparseSession(SparseTable("tbl", 16, 8))
    with pytest.raises(ValueError, match="dim"):
        bad.bind(pt.default_main_program())
    with pytest.raises(KeyError, match="sparse table"):
        SparseSession(SparseTable("other", 16, 4)).bind(
            pt.default_main_program())


def test_unknown_table_and_no_sparse_ops():
    ids = layers.data("ids", shape=[1], dtype="int64")
    layers.embedding(ids, size=[8, 2])     # dense only
    with pytest.raises(ValueError, match="no lookup_table_sparse"):
        SparseSession(SparseTable("x", 8, 2)).bind(
            pt.default_main_program())


def test_hot_cache_invalidation_on_push():
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4, learning_rate=1.0, seed=4)
    sess = SparseSession(t, cache_rows=32)
    sess.bind(pt.default_main_program())
    ids = np.array([[2], [3]], np.int64)
    f1 = sess.prepare_feed({"ids": ids})            # cold: misses
    assert sess.cache_stats()["misses"] >= 2
    f2 = sess.prepare_feed({"ids": ids})            # warm: hits
    assert sess.cache_stats()["hits"] >= 2
    assert np.array_equal(f1["tbl@ROWS"], f2["tbl@ROWS"])
    # push invalidates -> next pull returns UPDATED rows, not stale cache
    g = np.zeros_like(f1["tbl@ROWS"])
    g[:2] = 1.0
    sess.complete([g])                              # batch 1's pending
    f3 = sess.prepare_feed({"ids": ids}, is_test=True)
    fresh = t.pull(np.array([2, 3], np.int64))
    assert np.array_equal(f3["tbl@ROWS"][:2], fresh)
    assert not np.array_equal(f3["tbl@ROWS"], f2["tbl@ROWS"])
    # drain the remaining pending batch (f2)
    sess.complete([np.zeros_like(g)])
    assert sess.pending_batches == 0


def test_complete_fifo_contract():
    _sparse_program(vocab=16, dim=4)
    sess = SparseSession(SparseTable("tbl", 16, 4))
    sess.bind(pt.default_main_program())
    with pytest.raises(RuntimeError, match="no pending"):
        sess.complete([np.zeros((8, 4), np.float32)])
    sess.prepare_feed({"ids": np.array([[1]], np.int64)})
    with pytest.raises(ValueError, match="grad arrays"):
        sess.complete([])


def test_faultinject_push_drop_without_policy_raises():
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4, learning_rate=1.0,
                    initializer=("constant", 0.0))
    sess = SparseSession(t)
    sess.bind(pt.default_main_program())
    sess.prepare_feed({"ids": np.array([[1]], np.int64)})
    faultinject.configure("sparse.push@*=drop")
    try:
        with pytest.raises(ConnectionError):
            sess.complete([np.ones((8, 4), np.float32)])
    finally:
        faultinject.clear()
    # the drop was NOT silent and NOT applied: row still at init
    assert np.array_equal(t.pull(np.array([1], np.int64)),
                          np.zeros((1, 4), np.float32))
    assert sess.stats["pushes"] == 0


def test_faultinject_push_drop_with_policy_retries_exactly_once():
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4, learning_rate=1.0,
                    initializer=("constant", 0.0))
    sess = SparseSession(t, retry_policy=RetryPolicy(
        max_attempts=3, backoff_base_s=0.0, backoff_max_s=0.0))
    sess.bind(pt.default_main_program())
    sess.prepare_feed({"ids": np.array([[1], [2]], np.int64)})
    faultinject.configure("sparse.push@1=drop")     # first attempt only
    try:
        g = np.zeros((8, 4), np.float32)
        g[:2] = 1.0
        n = sess.complete([g])
        fired = faultinject.fired("sparse.push")
    finally:
        faultinject.clear()
    assert n == 2
    assert fired == 1
    # applied EXACTLY once (the site fires before any mutation)
    assert np.array_equal(
        t.pull(np.array([1, 2], np.int64)),
        np.full((2, 4), -1.0, np.float32))


def test_faultinject_push_fatal_action_raises():
    _sparse_program(vocab=16, dim=4)
    sess = SparseSession(SparseTable("tbl", 16, 4),
                         retry_policy=RetryPolicy(max_attempts=2,
                                                  backoff_base_s=0.0))
    sess.bind(pt.default_main_program())
    sess.prepare_feed({"ids": np.array([[1]], np.int64)})
    faultinject.configure("sparse.push@*=error")    # fatal: no retry
    try:
        with pytest.raises(InjectedFault):
            sess.complete([np.zeros((8, 4), np.float32)])
    finally:
        faultinject.clear()


def test_program_json_roundtrip_keeps_sparse_wiring():
    _sparse_program(vocab=32, dim=4)
    prog = pt.core.program.Program.from_dict(
        pt.default_main_program().to_dict())
    assert table_specs(prog) == table_specs(pt.default_main_program())
    gb = prog.global_block()
    assert gb.var("tbl@ROWS").session_feed
    assert gb.var("tbl@RIDX").session_feed
    sess = SparseSession(SparseTable("tbl", 32, 4))
    sess.bind(prog)
    assert sess.grad_fetch_list == ["tbl@ROWS@GRAD"]


def test_session_metrics_written_when_observing():
    from paddle_tpu.observability import registry
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4, learning_rate=1.0)
    reg = registry()

    def val(name):
        return reg.snapshot()[name]["value"]

    # observe=False: zero registry writes (python stats still counted)
    off = SparseSession(t, observe=False)
    off.bind(pt.default_main_program())
    before = val("sparse/pulls")
    off.prepare_feed({"ids": np.array([[1]], np.int64)})
    off.complete([np.zeros((8, 4), np.float32)])
    assert val("sparse/pulls") == before
    assert off.stats["pulls"] == 1
    # observe=True: counters move
    on = SparseSession(t, observe=True, cache_rows=8)
    on.bind(pt.default_main_program())
    p0, u0 = val("sparse/pulls"), val("sparse/pushes")
    on.prepare_feed({"ids": np.array([[1]], np.int64)})
    on.complete([np.zeros((8, 4), np.float32)])
    assert val("sparse/pulls") == p0 + 1
    assert val("sparse/pushes") == u0 + 1


# ---------------------------------------------------------------------------
# DataFeeder id hardening (satellite)
# ---------------------------------------------------------------------------
def test_feeder_id_bounds_actionable():
    from paddle_tpu.data_feeder import DataFeeder
    ids = layers.data("ids", shape=[1], dtype="int64")
    feeder = DataFeeder([ids], id_bounds={"ids": 100})
    # in-range int32 rows coerce to the declared int64
    out = feeder.feed([(np.array([5], np.int32),),
                       (np.array([99], np.int32),)])
    assert out["ids"].dtype == np.int64
    with pytest.raises(ValueError, match=r"outside.*\[0, 100\)"):
        feeder.feed([(np.array([100], np.int64),)])
    with pytest.raises(ValueError, match="outside"):
        feeder.feed([(np.array([-3], np.int64),)])
    with pytest.raises(ValueError, match="float"):
        feeder.feed([(np.array([1.5]),)])
    with pytest.raises(ValueError, match="ragged"):
        feeder.feed([([1, 2],), ([1],)])


def test_infer_id_bounds_covers_both_lookup_paths():
    from paddle_tpu.data_feeder import infer_id_bounds
    ids_d = layers.data("ids_dense", shape=[1], dtype="int64")
    ids_s = layers.data("ids_sparse", shape=[1], dtype="int64")
    layers.embedding(ids_d, size=[123, 4])
    layers.embedding(ids_s, size=[77, 4], sparse=True, name="tb2")
    bounds = infer_id_bounds(pt.default_main_program())
    assert bounds == {"ids_dense": 123, "ids_sparse": 77}


# ---------------------------------------------------------------------------
# Review-fix regressions
# ---------------------------------------------------------------------------
def test_cache_fill_fenced_against_concurrent_push():
    """A row pulled from the table BEFORE a concurrent push must not be
    inserted into the cache AFTER that push's invalidate (it would pin a
    pre-update row forever).  Deterministic interleaving: the push lands
    while the cache-miss pull is in flight."""
    _sparse_program(vocab=16, dim=4)
    t = SparseTable("tbl", 16, 4, learning_rate=1.0,
                    initializer=("constant", 0.0))
    sess = SparseSession(t, cache_rows=32)
    sess.bind(pt.default_main_program())

    real_pull = t.pull
    fired = []

    def racing_pull(ids):
        rows = real_pull(ids)
        if not fired:
            fired.append(True)
            # concurrent trainer push lands mid-pull (after the table
            # read, before the session's cache insert)
            sess._pending.append([(sess.bindings[0],
                                   np.array([2], np.int64))])
            sess.complete([np.ones((1, 4), np.float32)])
        return rows

    t.pull = racing_pull
    try:
        sess.prepare_feed({"ids": np.array([[2]], np.int64)},
                          is_test=True)
    finally:
        t.pull = real_pull
    # the stale pre-push row must NOT be cached: the next pull sees the
    # pushed update
    f = sess.prepare_feed({"ids": np.array([[2]], np.int64)},
                          is_test=True)
    assert np.array_equal(f["tbl@ROWS"][0],
                          np.full(4, -1.0, np.float32))


def test_bind_memo_does_not_survive_dead_program():
    import gc
    _sparse_program(vocab=16, dim=4)
    sess = SparseSession(SparseTable("tbl", 16, 4))
    sess.bind(pt.default_main_program())
    pt.core.reset_default_programs()
    gc.collect()
    _sparse_program(vocab=16, dim=4)     # fresh program, fresh id()
    sess.bind(pt.default_main_program())
    assert sess._bound_ref() is pt.default_main_program()
    assert sess.grad_fetch_list == ["tbl@ROWS@GRAD"]


def test_explicit_parameter_list_controls_wrt_exactly():
    """calc_gradient/append_backward with an explicit parameter_list
    must return exactly one grad per named input — sparse rows join
    only when named (and carry the optimizer-skip tag when they do)."""
    from paddle_tpu.backward import append_backward
    ids = layers.data("ids", shape=[1], dtype="int64")
    x = layers.data("x", shape=[4], dtype="float32")
    emb = layers.embedding(ids, size=[16, 4], sparse=True, name="tbl")
    fc = layers.fc(layers.concat([emb, x], axis=1), size=1,
                   param_attr=pt.ParamAttr(name="w"))
    loss = layers.mean(layers.square(fc))
    pairs = append_backward(loss, parameter_list=["w"])
    assert [p.name for p, _ in pairs] == ["w"]
    pairs = append_backward(loss, parameter_list=["w", "tbl@ROWS"])
    assert [p.name for p, _ in pairs] == ["w", "tbl@ROWS"]
    by_name = {p.name: p for p, _ in pairs}
    assert getattr(by_name["tbl@ROWS"], "is_sparse_rows", False)
    assert not getattr(by_name["w"], "is_sparse_rows", False)


def test_feeder_id_bounds_covers_sequence_feeds():
    from paddle_tpu.data_feeder import DataFeeder
    words = layers.data("words", shape=[], dtype="int64", lod_level=1)
    feeder = DataFeeder([words], id_bounds={"words": 50})
    out = feeder.feed([([1, 2, 3],), ([49],)])     # in-range: fine
    assert out["words"].dtype == np.int64
    with pytest.raises(ValueError, match=r"outside.*\[0, 50\)"):
        feeder.feed([([1, 50],), ([2],)])
    with pytest.raises(ValueError, match="outside"):
        feeder.feed([([-1],), ([2],)])
