"""Sharded embedding (CTR machinery) tests — replaces the reference's
SparseRemoteParameterUpdater / SelectedRows integration tests
(test_CompareSparse.cpp strategy: sparse vs dense must agree)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import layers, models, parallel
from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh


def test_manual_sharded_lookup_matches_dense(rng):
    V, D = 32, 8
    table = rng.randn(V, D).astype("float32")
    ids = rng.randint(0, V, (10,))
    mesh = make_mesh(MeshConfig(tp=8))
    f = pt.compat.shard_map(
        lambda t, i: parallel.sharded_lookup(t, i, axis_name="tp"),
        mesh=mesh, in_specs=(P("tp", None), P()), out_specs=P())
    out = np.asarray(jax.jit(f)(table, ids))
    np.testing.assert_allclose(out, table[ids], rtol=1e-6)


def test_sharded_lookup_grad_rows(rng):
    V, D = 16, 4
    ids = rng.randint(0, V, (6,))
    g = rng.randn(6, D).astype("float32")
    mesh = make_mesh(MeshConfig(tp=8))
    f = pt.compat.shard_map(
        lambda i, go: parallel.embedding.sharded_lookup_grad_rows(
            i, go, V, axis_name="tp"),
        mesh=mesh, in_specs=(P(), P()), out_specs=P("tp", None))
    shard_grads = np.asarray(jax.jit(f)(ids, g))
    dense = np.zeros((V, D), "float32")
    np.add.at(dense, ids, g)
    np.testing.assert_allclose(shard_grads, dense, rtol=1e-5, atol=1e-6)


def test_wide_deep_trains_with_vocab_sharded_tables(rng):
    """CTR model with tp-sharded embeddings via GSPMD: loss must track the
    unsharded run (test_CompareSparse equivalence strategy)."""
    def build():
        ids1 = layers.data("f1", shape=[1], dtype="int64")
        ids2 = layers.data("f2", shape=[1], dtype="int64")
        dense = layers.data("dense", shape=[4], dtype="float32")
        label = layers.data("ctr", shape=[1], dtype="float32")
        pred = models.wide_deep([ids1, ids2], dense, vocab_sizes=[32, 64],
                                emb_dim=8, deep_hidden=(16,))
        loss = layers.mean(layers.log_loss(pred, label))
        pt.optimizer.Adagrad(learning_rate=0.1).minimize(loss)
        return loss

    feeds = {"f1": rng.randint(0, 32, (16, 1)),
             "f2": rng.randint(0, 64, (16, 1)),
             "dense": rng.rand(16, 4).astype("float32"),
             "ctr": rng.randint(0, 2, (16, 1)).astype("float32")}

    loss = build()
    exe1 = pt.Executor()
    exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
    ref = [float(exe1.run(feed=feeds, fetch_list=[loss])[0])
           for _ in range(4)]

    pt.core.reset_global_scope()
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    # shard every embedding table by vocab rows
    prog = pt.default_main_program()
    specs = {p.name: P("tp", None) for p in prog.all_parameters()
             if "embedding" in p.name}
    assert len(specs) == 4
    exe8 = ShardedExecutor(mesh=mesh, param_specs=specs)
    exe8.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe8.place_state(prog)
    exe8._step = 0
    got = [float(exe8.run(prog, feed=feeds, fetch_list=[loss])[0])
           for _ in range(4)]
    np.testing.assert_allclose(ref, got, rtol=2e-4)
    w = pt.global_scope().get(next(iter(specs)))
    assert not w.sharding.is_fully_replicated
