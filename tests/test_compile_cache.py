"""Compile-cache subsystem (core/compile_cache.py): fingerprint-keyed
executor caching, retrace detection, LRU/weakref eviction, the persistent
on-disk executable cache, AOT ``Executor.compile`` and
``Trainer.train(warmup=...)``.

The retrace contract under test: ONE jit trace per (program content, feed
signature, executor config) — repeated ``run``/``run_steps``/
``run_pipelined`` calls must never re-pay trace/lower/compile, while any
fingerprint ingredient changing (program mutation, feed dtype, mesh, amp,
compiler options) must cost exactly one new trace.
"""
import gc

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import compile_cache
from paddle_tpu.core.compile_cache import (ExecCache, RetraceError,
                                           retrace_guard)
from paddle_tpu.core.program import Program, program_guard


@pytest.fixture(autouse=True)
def _fresh_stats():
    """Per-test telemetry isolation + persistent-cache knob restore."""
    compile_cache.stats().reset()
    yield
    pt.flags.set_flag("cache_dir", "")
    compile_cache.stats().reset()


@pytest.fixture
def cache_dir(tmp_path):
    """Point the persistent layer at a tmp dir for one test."""
    d = tmp_path / "ptcache"
    pt.flags.set_flag("cache_dir", str(d))
    return d


def _build_net(rng, seed=0):
    """Small classifier; returns (loss, feed)."""
    pt.default_main_program().random_seed = seed
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    pred = layers.fc(x, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    feed = {"x": rng.rand(8, 4).astype("float32"),
            "y": rng.randint(0, 3, (8, 1))}
    return loss, feed


def _traces():
    return compile_cache.stats().snapshot().get("traces", 0)


# ---------------------------------------------------------------------------
# retrace detector
# ---------------------------------------------------------------------------
def test_exactly_one_trace_per_signature(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    with retrace_guard():
        exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
        for _ in range(4):
            exe.run(feed=feed, fetch_list=[loss])
        for _ in range(2):
            exe.run_steps(3, feed=feed, fetch_list=[loss])
    # startup + run variant + run_steps variant
    assert _traces() == 3
    compile_cache.stats().assert_no_retrace()


def test_exactly_one_trace_run_pipelined(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    t0 = _traces()

    def feed_iter():
        for _ in range(10):
            yield dict(feed)

    with retrace_guard():
        outs = list(exe.run_pipelined(feed_iter(), fetch_list=[loss],
                                      steps_per_dispatch=4))
        outs += list(exe.run_pipelined(feed_iter(), fetch_list=[loss],
                                       steps_per_dispatch=4))
    assert len(outs) == 20
    # one scan variant + one per-step tail variant, traced once EACH
    # across BOTH pipelined sweeps
    assert _traces() - t0 == 2
    compile_cache.stats().assert_no_retrace()


def test_one_new_trace_on_program_mutation(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.run(feed=feed, fetch_list=[loss])
    t0 = _traces()
    layers.mean(loss)                       # version bump, content change
    exe.run(feed=feed, fetch_list=[loss])
    exe.run(feed=feed, fetch_list=[loss])
    assert _traces() - t0 == 1


def test_one_new_trace_on_feed_dtype_change(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.run(feed=feed, fetch_list=[loss])
    t0 = _traces()
    # "y" declared int64 is dtype-coerced by run(); vary the UNDECLARED
    # feed precision instead: shape change on x is a new signature
    feed2 = dict(feed, x=feed["x"][:4])
    feed2["y"] = feed["y"][:4]
    exe.run(feed=feed2, fetch_list=[loss])
    exe.run(feed=feed2, fetch_list=[loss])
    assert _traces() - t0 == 1


def test_retrace_guard_fires_on_cache_clear(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with pytest.raises(RetraceError):
        with retrace_guard():
            exe.run(feed=feed, fetch_list=[loss])
            exe._cache.clear()              # force the pathology
            exe.run(feed=feed, fetch_list=[loss])


# ---------------------------------------------------------------------------
# fingerprint ingredients
# ---------------------------------------------------------------------------
def test_fingerprint_invalidation_matrix(rng):
    """Program mutation, feed dtype, amp, compiler options and mesh each
    change the signature; a no-op rebuild does not."""
    from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh

    loss, feed = _build_net(rng)
    prog = pt.default_main_program()
    exe = pt.Executor()

    def sig(e, feeds=feed, p=None):
        import jax
        # mirror run()'s feed normalization: declared dtypes are coerced
        # BEFORE the signature is computed
        p = p or prog
        gb = p.global_block()
        fa = {}
        for k, v in feeds.items():
            arr = np.asarray(v)
            if gb.has_var(k):
                want = jax.dtypes.canonicalize_dtype(gb.var(k).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            fa[k] = arr
        return e._entry_sig(p, fa, [loss.name], [], False)

    base = sig(exe)
    assert sig(exe) == base                               # stable
    assert sig(pt.Executor()) == base                     # executor-independent
    assert sig(pt.Executor(amp=True)) != base
    assert sig(pt.Executor(check_nan_inf=True)) != base
    assert sig(pt.Executor(compute_dtype="float64")) != base
    assert sig(pt.Executor(
        compiler_options={"xla_cpu_enable_fast_math": True})) != base

    f32 = dict(feed, x=feed["x"].astype("float64"))
    # x declared float32: coerced, same signature; an UNdeclared feed
    # keeps its dtype and must differ
    assert sig(exe, feeds=f32) == base
    extra = dict(feed, z=np.zeros(3, "int32"))
    assert sig(exe, feeds=extra) != base
    assert sig(exe, feeds=dict(
        feed, z=np.zeros(3, "int64"))) != sig(exe, feeds=extra)

    layers.mean(loss)                                     # content change
    assert sig(exe) != base

    m8 = make_mesh(MeshConfig(dp=8))
    m4 = make_mesh(MeshConfig(dp=4), devices=__import__("jax").devices()[:4])
    s8, s4 = ShardedExecutor(mesh=m8), ShardedExecutor(mesh=m4)
    assert sig(s8) != sig(exe)                            # mesh folded in
    assert sig(s8) != sig(s4)                             # mesh shape/devices
    assert sig(ShardedExecutor(
        mesh=m8, param_specs={"w": ("dp",)})) != sig(s8)  # specs folded in


def test_content_identical_programs_share_entry(rng):
    """prune().clone(for_test=True) inference slices built per call (the
    trainer.test pattern) hit ONE cache entry instead of recompiling."""
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    main = pt.default_main_program()
    t0 = _traces()
    with retrace_guard():
        for _ in range(3):
            test_prog = main.prune([loss]).clone(for_test=True)
            exe.run(test_prog, feed=feed, fetch_list=[loss], is_test=True)
    assert _traces() - t0 == 1


def test_shared_entry_retargets_to_live_client(rng):
    """A shared entry's step fn must not depend on its CREATOR program
    staying alive: when a content-identical client hits the entry, the
    fn's program weakref cell retargets to the client, so a later
    re-trace (lazy-jit fallback, auto_layout re-jit) uses the live
    program instead of raising."""
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    main = pt.default_main_program()
    first = main.prune([loss]).clone(for_test=True)
    exe.run(first, feed=feed, fetch_list=[loss], is_test=True)
    second = main.prune([loss]).clone(for_test=True)
    exe.run(second, feed=feed, fetch_list=[loss], is_test=True)
    del first
    gc.collect()
    (entry,) = [e for e in exe._cache._od.values()
                if any(r() is second for r in e.prog_refs)]
    assert not entry.dead()
    cell = entry._prog_cell()
    assert cell is not None and cell[0]() is second


def test_clone_and_prune_bump_version(rng):
    loss, _ = _build_net(rng)
    main = pt.default_main_program()
    d0 = main.content_digest()
    pruned = main.prune([loss])
    assert pruned.content_digest() != d0       # ops changed, digest follows
    cloned = main.clone(for_test=True)
    assert cloned.version > main.version
    assert main.content_digest() == d0         # original untouched
    main.random_seed += 1                      # mutates without a bump
    assert main.content_digest() != d0         # digest cache keyed on seed


# ---------------------------------------------------------------------------
# eviction: LRU bound + dead-program sweeping
# ---------------------------------------------------------------------------
def test_lru_bound_and_eviction_counter(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe._cache = ExecCache(max_entries=2)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    for n in (8, 6, 4, 2):                     # distinct feed signatures
        exe.run(feed={k: v[:n] for k, v in feed.items()},
                fetch_list=[loss])
    assert len(exe._cache) == 2
    assert exe._cache.evictions >= 3           # startup + older variants
    assert compile_cache.stats().snapshot()["evictions"] >= 3


def test_dead_program_entries_swept(rng):
    exe = pt.Executor()

    def one_shot(i):
        with program_guard(Program(), Program()):
            x = layers.data("x", shape=[4], dtype="float32")
            out = layers.fc(x, size=2 + i)
            prog = pt.default_main_program()
            exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
            exe.run(prog, feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[out], is_test=True)

    one_shot(0)
    n_live = len(exe._cache)
    assert n_live >= 1
    gc.collect()                               # programs now unreachable
    exe._cache.sweep()
    assert len(exe._cache) == 0
    assert exe._cache.evictions >= n_live
    # sweeping also happens implicitly on the next put
    one_shot(1)
    assert len(exe._cache) <= 4


def test_state_keys_cache_swept(rng):
    """Dead (scope, keys_version) pairs no longer accumulate unboundedly."""
    from paddle_tpu.core.executor import _STATE_KEYS_CACHE_MAX
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    prog = pt.default_main_program()
    for _ in range(_STATE_KEYS_CACHE_MAX + 10):
        sc = pt.core.Scope()
        with pt.core.scope_guard(sc):
            exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
            exe.run(prog, feed=feed, fetch_list=[loss], scope=sc)
        del sc
        gc.collect()
    entries = prog._state_keys_cache["entries"]
    assert len(entries) <= _STATE_KEYS_CACHE_MAX + 1
    assert compile_cache.stats().snapshot().get(
        "state_keys_evictions", 0) > 0


# ---------------------------------------------------------------------------
# AOT: Executor.compile / CompiledProgram / Trainer warmup
# ---------------------------------------------------------------------------
def test_executor_compile_then_run_no_retrace(rng):
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    cp = exe.compile(feed=feed, fetch_list=[loss])
    assert cp.compile_times.get("compile_s", 0) > 0
    t0 = _traces()
    with retrace_guard():
        (v1,) = exe.run(feed=feed, fetch_list=[loss])
        (v2,) = cp.run(feed=feed)
    assert _traces() == t0                     # AOT paid the trace already
    assert np.isfinite(v1) and np.isfinite(v2)


def test_executor_compile_spec_feed_and_steps(rng):
    """(shape, dtype) specs compile the same variant concrete feeds hit;
    num_steps compiles the scan variant."""
    loss, feed = _build_net(rng)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.compile(feed={"x": ((8, 4), "float32"), "y": ((8, 1), "int64")},
                fetch_list=[loss])
    cp = exe.compile(
        feed={"x": ((4, 8, 4), "float32"), "y": ((4, 8, 1), "int64")},
        fetch_list=[loss], num_steps=4, feeds_stacked=True)
    t0 = _traces()
    with retrace_guard():
        exe.run(feed=feed, fetch_list=[loss])
        from paddle_tpu.core.executor import stack_feeds
        exe.run_steps(4, feed=stack_feeds([feed] * 4), fetch_list=[loss],
                      feeds_stacked=True)
    assert _traces() == t0
    assert cp.num_steps == 4


def test_trainer_warmup(rng):
    from paddle_tpu import trainer
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    pred = layers.fc(x, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    rows = [(rng.rand(4).astype("float32"), int(rng.randint(3)))
            for _ in range(32)]

    def reader():
        for i in range(0, 32, 8):
            yield rows[i:i + 8]

    t = trainer.SGD(loss, update_equation=pt.optimizer.SGD(0.1))
    t.train(reader, num_passes=1, feed_list=[x, y], warmup=True,
            steps_per_dispatch=2)
    t_after_warm_pass = _traces()
    with retrace_guard():                      # second pass: all cached
        t.train(reader, num_passes=1, feed_list=[x, y],
                steps_per_dispatch=2)
    assert _traces() == t_after_warm_pass


def test_sharded_compile_aot(rng):
    from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh
    loss, feed = _build_net(rng)
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(dp=8)))
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.compile(feed=feed, fetch_list=[loss])
    t0 = _traces()
    with retrace_guard():
        (v,) = exe.run(feed=feed, fetch_list=[loss])
    assert _traces() == t0
    assert np.isfinite(v)


# ---------------------------------------------------------------------------
# persistent on-disk layer
# ---------------------------------------------------------------------------
def test_persistent_cache_roundtrip(rng, cache_dir):
    """A second Executor (fresh in-process cache, same persistent dir)
    loads the serialized executable instead of tracing, and its fetches
    are bit-identical."""
    loss, feed = _build_net(rng)
    exe1 = pt.Executor()
    exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (v1,) = exe1.run(feed=feed, fetch_list=[loss])
    snap = compile_cache.stats().snapshot()
    assert snap["disk_stores"] >= 2            # startup + step executables
    assert any(p.name.startswith("ptxc-") for p in cache_dir.iterdir())

    # fresh executor, params reset to the same init by re-running startup
    pt.core.reset_global_scope()
    exe2 = pt.Executor()
    t0 = _traces()
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (v2,) = exe2.run(feed=feed, fetch_list=[loss])
    snap2 = compile_cache.stats().snapshot()
    assert _traces() == t0                     # zero traces: disk served both
    assert snap2["disk_hits"] - snap.get("disk_hits", 0) >= 2
    assert v1.tobytes() == v2.tobytes()


def test_persistent_cache_corrupt_entry_recompiles(rng, cache_dir):
    loss, feed = _build_net(rng)
    exe1 = pt.Executor()
    exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe1.run(feed=feed, fetch_list=[loss])
    for p in cache_dir.iterdir():
        if p.name.startswith("ptxc-"):
            p.write_bytes(b"corrupt")
    pt.core.reset_global_scope()
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (v,) = exe2.run(feed=feed, fetch_list=[loss])   # recompiles, no crash
    assert np.isfinite(v)


# ---------------------------------------------------------------------------
# benchmark wiring (satellite: tier-1 smoke; full A/B is slow)
# ---------------------------------------------------------------------------
def test_benchmark_smoke_cold_warm_subprocesses():
    """benchmark/run.py --model compile_cache --smoke: two fresh
    subprocesses share a tmp cache; asserts the warm arm loads executables
    (zero traces) and produces bit-identical fetches."""
    from benchmark.compile_cache import run_smoke
    row = run_smoke()
    assert row["bit_identical"]
    assert row["warm_traces"] == 0


@pytest.mark.slow
def test_benchmark_full_ab_models():
    """Full cold-vs-warm A/B on the three real models (minutes)."""
    from benchmark.compile_cache import MODELS, run_model
    rows = [run_model(m, quiet=True) for m in MODELS]
    assert all(r["bit_identical"] for r in rows)
    fast = [r for r in rows if r["speedup_engine"] >= 1.5]
    assert len(fast) >= 2, [
        (r["model"], r["speedup_engine"]) for r in rows]
