"""distributed/launch.py hardening: single-process no-op, env-var
resolution, retry-with-backoff around jax.distributed.initialize, and
the typed coordinator-timeout error (PADDLE_TPU_COORDINATOR_TIMEOUT_S).

The multi-host paths monkeypatch ``jax.distributed.initialize`` — no
real coordinator is reachable in this container (and the CPU backend's
real multi-process collectives are a known pre-existing gap covered by
tests/test_multiprocess_launch.py)."""
import pytest

import jax

from paddle_tpu.distributed import launch
from paddle_tpu.faults import RetryPolicy


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    launch.reset_distributed_state()
    monkeypatch.delenv("PADDLE_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("PADDLE_TPU_COORDINATOR_TIMEOUT_S", raising=False)
    yield
    launch.reset_distributed_state()


def test_single_process_noop(monkeypatch):
    """No coordinator anywhere: init is a no-op that still marks the
    process initialized (idempotent), and never touches jax.distributed."""
    def boom(**kw):
        raise AssertionError("initialize must not be called")
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    assert not launch.is_initialized()
    launch.init_distributed()
    assert launch.is_initialized()
    launch.init_distributed()          # second call: still a no-op
    assert launch.is_initialized()


def test_env_var_coordinator_path(monkeypatch):
    """PADDLE_TPU_COORDINATOR alone routes into the multi-host path with
    the env-provided address."""
    seen = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        seen.update(address=coordinator_address, n=num_processes,
                    pid=process_id)
    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("PADDLE_TPU_COORDINATOR", "10.0.0.1:1234")
    launch.init_distributed(num_processes=2, process_id=1)
    assert seen == {"address": "10.0.0.1:1234", "n": 2, "pid": 1}
    assert launch.is_initialized()


def test_transient_failures_retry_then_succeed(monkeypatch):
    """Connection-flavored failures retry with the seeded backoff; a
    later success initializes normally."""
    calls = {"n": 0}

    def flaky(**kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionRefusedError("coordinator not up yet")
    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    sleeps = []
    monkeypatch.setattr(launch, "retry_call",
                        lambda fn, policy, **kw: _drive_retry(
                            fn, policy, sleeps, kw))
    launch.init_distributed(coordinator_address="h:1", num_processes=2,
                            process_id=0, timeout_s=30.0)
    assert calls["n"] == 3
    assert launch.is_initialized()
    assert len(sleeps) == 2 and all(s > 0 for s in sleeps)


def _drive_retry(fn, policy, sleeps, kw):
    """Run the real retry_call with an instrumented no-op sleep."""
    from paddle_tpu.faults import retry_call
    kw = dict(kw)
    kw["sleep"] = sleeps.append
    return retry_call(fn, policy, **kw)


def test_timeout_budget_raises_typed_error(monkeypatch):
    """A coordinator that never answers exhausts the budget and raises
    CoordinatorTimeoutError carrying address + budget (not the raw
    transport error)."""
    def dead(**kw):
        raise ConnectionRefusedError("nobody home")
    monkeypatch.setattr(jax.distributed, "initialize", dead)
    # a tiny budget via the env knob; zero real sleeping (policy still
    # schedules delays, so neutralize time.sleep inside retry_call)
    monkeypatch.setenv("PADDLE_TPU_COORDINATOR_TIMEOUT_S", "3")
    import paddle_tpu.faults as faults
    monkeypatch.setattr(faults.time, "sleep", lambda s: None)
    with pytest.raises(launch.CoordinatorTimeoutError) as ei:
        launch.init_distributed(coordinator_address="h:9", num_processes=2,
                                process_id=0)
    err = ei.value
    assert err.address == "h:9"
    assert err.timeout_s == 3.0
    assert isinstance(err.last, ConnectionRefusedError)
    assert isinstance(err, TimeoutError)
    assert not launch.is_initialized()


def test_fatal_failures_do_not_retry(monkeypatch):
    """A deterministic setup error (bad arguments) propagates on the
    first attempt — retrying a ValueError would just stall the pod."""
    calls = {"n": 0}

    def bad(**kw):
        calls["n"] += 1
        raise ValueError("num_processes mismatch")
    monkeypatch.setattr(jax.distributed, "initialize", bad)
    with pytest.raises(ValueError):
        launch.init_distributed(coordinator_address="h:1",
                                num_processes=2, process_id=0)
    assert calls["n"] == 1
    assert not launch.is_initialized()


def test_retry_policy_fits_budget():
    """The derived schedule's total sleep stays within the budget and is
    deterministic (seeded)."""
    for budget in (1.0, 10.0, 60.0, 300.0):
        policy = launch._retry_policy(budget)
        assert isinstance(policy, RetryPolicy)
        total = sum(policy.delay(i)
                    for i in range(policy.max_attempts - 1))
        assert total <= budget * (1.0 + policy.jitter) + 1e-6, budget
    # same args -> same schedule (the chaos-determinism convention)
    a, b = launch._retry_policy(60.0), launch._retry_policy(60.0)
    assert [a.delay(i) for i in range(a.max_attempts - 1)] == \
        [b.delay(i) for i in range(b.max_attempts - 1)]
