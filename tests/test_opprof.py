"""Per-op runtime profiler (paddle_tpu.observability.opprof).

Pins the ISSUE 12 acceptance contract:

* the measured walk covers EVERY op in execution order — forward slice,
  the ``backward`` pseudo-op, optimizer updates — and the per-op table
  sums to the eager-replay total within the pinned tolerance
  (deterministic fake-timer matrix: the join/bookkeeping is what the
  tier-1 test pins; the real-timer acceptance rows live in
  benchmark/opprof_results.json);
* dtype-coercion + RNG parity with the COMPILED step: the eager replay
  reproduces a dropout-bearing training step's loss bit-identically;
* the per-op-class calibration table merges into the PR 10 format and
  ``analysis.planner`` demonstrably consumes it — a seeded table that
  inflates one op class flips the candidate ranking;
* zero overhead when off: with opprof merely loaded, ``Executor.run``
  hot paths write no metrics and never retrace;
* CLI rounds: ``profile`` in-process (tier-1) + subprocess (@slow),
  ``doctor --per-op`` joins the profile under the step budget.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, layers
from paddle_tpu import observability as obs
from paddle_tpu.core.compile_cache import retrace_guard
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import opprof


@pytest.fixture(autouse=True)
def clean_state():
    obs.registry().reset()
    prev = {n: flags.get_flag(n) for n in ("observe", "metrics_log")}
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    yield
    for n, v in prev.items():
        flags.set_flag(n, v)
    obs_export._reset_writer()
    obs.registry().reset()


def _build_net(dropout=True):
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=16, act="relu")
    if dropout:
        h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _feed(batch=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(batch, 8).astype("float32"),
            "y": rng.randint(0, 3, (batch, 1))}


def _fake_measure(op_ms=1.0):
    """Deterministic fake-timer: executes the call once (the walk's env
    state must advance for the join to see real shapes) and returns a
    scripted window.  Call order is frozen by profile_program: one call
    per op in execution order, then ONE full-replay total."""
    calls = []

    def measure(call, *, reps, warmup):
        call()
        calls.append(reps)
        return {"seconds": op_ms / 1e3, "windows": [op_ms / 1e3],
                "spread_pct": 0.0}

    measure.calls = calls
    return measure


# ---------------------------------------------------------------------------
# measured walk: coverage, phases, fake-timer sum
# ---------------------------------------------------------------------------
def test_fake_timer_matrix_rows_cover_ops_and_sum_to_total():
    loss = _build_net()
    prog = pt.default_main_program()
    n_ops = len(prog.global_block().ops)
    calls = {"n": 0}

    def measure(call, *, reps, warmup):
        call()
        calls["n"] += 1
        if calls["n"] <= n_ops:                 # per-op windows
            return {"seconds": 1e-3, "windows": [1e-3],
                    "spread_pct": 0.0}
        # the final call is the full-replay total: exactly the sum of
        # the per-op windows -> gap must be 0 and within tolerance
        return {"seconds": n_ops * 1e-3, "windows": [n_ops * 1e-3],
                "spread_pct": 0.0}

    rep = opprof.profile_program(prog, batch=8, measure=measure,
                                 fetch_list=[loss.name])
    assert calls["n"] == n_ops + 1
    assert rep["ops"] == n_ops
    assert [r["index"] for r in rep["rows"]] == list(range(n_ops))
    assert rep["per_op_sum_ms"] == pytest.approx(n_ops * 1.0)
    assert rep["eager_total_ms"] == pytest.approx(n_ops * 1.0)
    assert rep["sum_gap_frac"] == 0.0
    assert rep["within_tolerance"] is True
    assert rep["tolerance"] == opprof.TOLERANCE
    # every row joined against the static model carries a roofline
    joined = [r for r in rep["rows"] if r.get("modeled")]
    assert joined, "no rows joined against the static cost model"
    for r in joined:
        assert r["modeled"]["roofline"] in ("compute-bound",
                                            "memory-bound")
    # loss value materialized through the fetch hook
    assert np.isfinite(rep["fetches"][loss.name]).all()


def test_backward_and_update_ops_attributed_in_execution_order():
    _build_net()
    prog = pt.default_main_program()
    rep = opprof.profile_program(prog, batch=8,
                                 measure=_fake_measure())
    phases = [r["phase"] for r in rep["rows"]]
    types = [r["op_type"] for r in rep["rows"]]
    bw = types.index("backward")
    assert phases[bw] == "backward"
    assert set(phases[:bw]) == {"forward"}
    assert phases[bw + 1:] and set(phases[bw + 1:]) == {"update"}
    assert "sgd" in types[bw + 1:]
    # the backward row accounts the @GRAD outputs it produced
    bw_row = rep["rows"][bw]
    assert bw_row["bytes"] > 0 and bw_row["out_shapes"]


def test_tolerance_pinned_to_budget_tolerance():
    from paddle_tpu.observability import attribution
    assert opprof.TOLERANCE == attribution.BUDGET_TOLERANCE


def test_over_tolerance_is_reported_not_hidden():
    _build_net()
    prog = pt.default_main_program()
    n_ops = len(prog.global_block().ops)
    calls = {"n": 0}

    def measure(call, *, reps, warmup):
        call()
        calls["n"] += 1
        # total reads HALF the per-op sum -> gap 100%, over tolerance
        s = 1e-3 if calls["n"] <= n_ops else n_ops * 0.5e-3
        return {"seconds": s, "windows": [s], "spread_pct": 0.0}

    rep = opprof.profile_program(prog, batch=8, measure=measure)
    assert rep["within_tolerance"] is False
    assert "OVER TOLERANCE" in opprof.render_profile(rep)


# ---------------------------------------------------------------------------
# dtype-coercion + RNG parity with the compiled step (seeded program)
# ---------------------------------------------------------------------------
def test_eager_replay_parity_with_compiled_training_step():
    loss = _build_net(dropout=True)
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    scope = pt.global_scope()
    state0 = {k: np.array(scope.get(k))
              for k in exe._state_keys(prog, scope)}
    feed = _feed()
    step = exe._step          # the step counter the next run will use
    (compiled_loss,) = exe.run(feed=feed, fetch_list=[loss])
    rep = opprof.profile_program(prog, executor=exe, feed=feed,
                                 state=state0, step=step, batch=16,
                                 reps=1, warmup=0,
                                 fetch_list=[loss.name])
    # bit-identical INCLUDING the dropout mask: the walk reproduces the
    # compiled trace's per-op RNG uid sequence (backward replays the
    # forward from uid 0, exactly as value_and_grad traces it)
    assert np.asarray(rep["fetches"][loss.name]) == pytest.approx(
        np.asarray(compiled_loss), abs=0.0)


def test_amp_inference_replay_matches_compiled_dtype():
    x = layers.data("x", shape=[8], dtype="float32")
    pred = layers.fc(x, size=4, act="softmax")
    prog = pt.default_main_program()
    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feed = {"x": _feed()["x"]}
    (compiled_out,) = exe.run(feed=feed, fetch_list=[pred],
                              is_test=True, return_numpy=False)
    rep = opprof.profile_program(prog, executor=exe, feed=feed,
                                 is_test=True, batch=16, reps=1,
                                 warmup=0, fetch_list=[pred.name])
    # pure-inference AMP coerces to bf16 — the replay must time (and
    # produce) the SAME precision the compiled step computed at.  Values
    # agree to one bf16 ulp, not bitwise: jit fuses matmul+softmax into
    # one HLO computation while the per-op replay rounds to bf16 at each
    # op boundary (a replay that secretly ran at f32 would drift by far
    # more than one ulp after the f32-vs-bf16 softmax).
    assert str(compiled_out.dtype) == "bfloat16"
    assert rep["rows"][-1]["out_dtypes"][-1] == "bfloat16"
    np.testing.assert_allclose(
        np.asarray(rep["fetches"][pred.name], dtype="float32"),
        np.asarray(compiled_out, dtype="float32"),
        rtol=2 ** -7, atol=0.0)


def test_amp_training_forwards_time_at_bf16_grads_stay_fp32():
    """AMP TRAINING parity: the compiled step runs forward ops in bf16
    inside value_and_grad while grads/updates stay fp32 (master
    weights) — the walk must measure each phase at that phase's
    compiled precision."""
    _build_net(dropout=False)
    prog = pt.default_main_program()
    exe = pt.Executor(amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    rep = opprof.profile_program(prog, executor=exe, feed=_feed(),
                                 batch=16, measure=_fake_measure())
    fwd = [r for r in rep["rows"] if r["phase"] == "forward"]
    assert fwd
    assert all(dt == "bfloat16" for r in fwd for dt in r["out_dtypes"])
    bw = next(r for r in rep["rows"] if r["phase"] == "backward")
    assert all(dt == "float32" for dt in bw["out_dtypes"])
    upd = [r for r in rep["rows"] if r["phase"] == "update"]
    assert upd
    assert all(dt == "float32" for r in upd for dt in r["out_dtypes"])


# ---------------------------------------------------------------------------
# real-timer smoke (tiny, reps=1): sums reconcile on a real walk too
# ---------------------------------------------------------------------------
def test_real_timer_smoke_reconciles():
    _build_net(dropout=False)
    prog = pt.default_main_program()
    rep = opprof.profile_program(prog, batch=4, reps=1, warmup=1)
    assert rep["eager_total_ms"] > 0 and rep["per_op_sum_ms"] > 0
    # no tolerance assert: this box's contention swings small windows;
    # the committed acceptance rows live in benchmark/opprof_results.json
    assert rep["ops"] == len(prog.global_block().ops)
    snap = obs.registry().snapshot()
    assert snap["opprof/runs"]["value"] == 1
    assert snap["opprof/ops"]["value"] == rep["ops"]
    assert snap["opprof/op_ms"]["count"] == rep["ops"]


# ---------------------------------------------------------------------------
# XLA-loses-here: pallas candidates referenced with their rule ids
# ---------------------------------------------------------------------------
def test_xla_loses_here_names_pallas_candidate_rules():
    _build_net()
    prog = pt.default_main_program()
    ops = prog.global_block().ops
    sgd_idx = {i for i, op in enumerate(ops) if op.type == "sgd"}
    calls = {"n": 0}

    def measure(call, *, reps, warmup):
        call()
        i = calls["n"]
        calls["n"] += 1
        # make the optimizer updates dominate the measured profile
        s = 50e-3 if i in sgd_idx else 0.1e-3
        return {"seconds": s, "windows": [s], "spread_pct": 0.0}

    rep = opprof.profile_program(prog, batch=8, measure=measure)
    top = rep["xla_loses_here"][0]
    assert top["op_type"] == "sgd"
    assert top["share"] > 0.5
    assert top["pallas_candidate"] == "pallas/fused_optimizer_update"
    assert top["pending_hardware"] is True
    assert "1.10x" in top["decision_rule"]
    rendered = opprof.render_profile(rep)
    assert "pallas/fused_optimizer_update" in rendered
    assert "rule:" in rendered


def test_pallas_candidate_tunables_preregistered():
    from paddle_tpu.core.registry import get_tunable
    for name in ("pallas/fused_optimizer_update",
                 "pallas/lod_gather_scatter"):
        e = get_tunable(name)
        assert e["side"] == "device"
        assert e["pending_hardware"] is True
        assert e["decision_rule"], name
    # and the profiler's candidate map points at exactly these ids
    assert set(opprof.PALLAS_CANDIDATES.values()) == {
        "pallas/fused_optimizer_update", "pallas/lod_gather_scatter"}
    assert opprof.PALLAS_CANDIDATES["sgd"] == \
        "pallas/fused_optimizer_update"
    assert opprof.PALLAS_CANDIDATES["sequence_expand"] == \
        "pallas/lod_gather_scatter"


# ---------------------------------------------------------------------------
# memory timeline
# ---------------------------------------------------------------------------
def test_memory_timeline_curve_and_modeled_peak():
    _build_net()
    prog = pt.default_main_program()
    rep = opprof.profile_program(prog, batch=8,
                                 measure=_fake_measure())
    mem = rep["memory"]
    n_ops = len(prog.global_block().ops)
    assert len(mem["timeline"]) == n_ops
    assert mem["peak_bytes"] >= mem["state_bytes"] > 0
    assert mem["peak_bytes"] == max(p["live_bytes"]
                                    for p in mem["timeline"])
    assert mem["timeline"][mem["peak_index"]]["live_bytes"] == \
        mem["peak_bytes"]
    # forward activations pin to the backward: the peak sits at (or
    # after) the backward op, never mid-forward
    bw = next(i for i, op in enumerate(prog.global_block().ops)
              if op.type == "backward")
    assert mem["peak_index"] >= bw
    assert mem["modeled_peak_bytes"] and mem["peak_ratio"] > 0


# ---------------------------------------------------------------------------
# calibration table -> planner (the acceptance wiring)
# ---------------------------------------------------------------------------
def _two_layer_mlp():
    x = layers.data("x", shape=[128], dtype="float32")
    h = layers.fc(x, size=128, act="relu")
    h2 = layers.fc(h, size=128, act="relu")
    layers.mean(h2)
    return pt.default_main_program()


def test_op_class_table_merges_into_pr10_format(tmp_path):
    from paddle_tpu.observability import attribution
    path = str(tmp_path / "cal.json")
    # a PR 10 per-program row already in the table must survive
    attribution.save_calibration([{"program": "aaaa", "predicted_ms": 1.0,
                                   "measured_ms": 2.0, "ratio": 2.0}],
                                 path)
    rows = [{"program": "bbbb", "op_type": "mul", "predicted_ms": 1.0,
             "measured_ms": 200.0, "ratio": 200.0, "count": 2},
            {"program": "bbbb", "op_type": "relu", "predicted_ms": 1.0,
             "measured_ms": 1.0, "ratio": 1.0, "count": 1}]
    doc = attribution.save_op_class_calibration(rows, path)
    assert doc["programs"]["aaaa"]["ratio"] == 2.0
    assert doc["op_classes"]["bbbb:mul"]["ratio"] == 200.0
    # re-profiling the same program overwrites, never duplicates
    rows[0]["ratio"] = 150.0
    doc = attribution.save_op_class_calibration([rows[0]], path)
    assert doc["op_classes"]["bbbb:mul"]["ratio"] == 150.0
    assert len(doc["op_classes"]) == 2
    # and the per-program row STILL survives a save_calibration pass
    doc = attribution.save_calibration(
        [{"program": "cccc", "ratio": 3.0}], path)
    assert "bbbb:mul" in doc["op_classes"]
    # the planner-facing loader: median ratio per op type
    ratios = attribution.load_op_class_ratios(path)
    assert ratios == {"mul": 150.0, "relu": 1.0}


def test_planner_ranking_flips_under_seeded_op_class_inflation(tmp_path):
    from paddle_tpu.analysis import planner
    from paddle_tpu.observability import attribution
    prog = _two_layer_mlp()
    nominal = planner.rank_candidates(prog, {"tp": 2}, assume_batch=512)
    assert nominal[0][0] == "dp"
    assert {n for n, _ in nominal} >= {"dp", "megatron"}
    # seed a table through the real save/load path (the planner
    # "demonstrably loads" the committed format, not a hand dict)
    path = str(tmp_path / "cal.json")
    attribution.save_op_class_calibration(
        [{"program": "feed", "op_type": "mul", "predicted_ms": 1.0,
          "measured_ms": 200.0, "ratio": 200.0, "count": 2}], path)
    ratios = attribution.load_op_class_ratios(path)
    calibrated = planner.rank_candidates(prog, {"tp": 2},
                                         assume_batch=512,
                                         op_class_ratios=ratios)
    assert calibrated[0][0] == "megatron"
    # plan() itself follows the same ranking and records the fact
    p = planner.plan(prog, {"tp": 2}, assume_batch=512,
                     op_class_ratios=ratios)
    assert p.candidate == "megatron"
    assert any("op-class calibration" in d for d in p.diagnostics)
    p0 = planner.plan(prog, {"tp": 2}, assume_batch=512)
    assert p0.candidate == "dp"


def test_profile_report_op_classes_feed_the_loader(tmp_path):
    from paddle_tpu.observability import attribution
    _build_net()
    prog = pt.default_main_program()
    rep = opprof.profile_program(prog, batch=8,
                                 measure=_fake_measure())
    assert rep["op_classes"], "no op-class calibration rows produced"
    for row in rep["op_classes"]:
        assert row["program"] == rep["program"]
        assert row["model"] == "static-per-op"
    path = str(tmp_path / "cal.json")
    attribution.save_op_class_calibration(rep["op_classes"], path)
    ratios = attribution.load_op_class_ratios(path)
    assert set(ratios) == {r["op_type"] for r in rep["op_classes"]
                           if r["ratio"]}


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------
def test_executor_hot_path_untouched_with_opprof_loaded():
    # opprof IS imported (module top); the executor hot path must stay
    # registry-silent and retrace-free regardless
    flags.set_flag("observe", False)
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    before = obs.registry().snapshot()
    exe.run(feed=_feed(), fetch_list=[loss])       # pays the one trace
    with retrace_guard():
        for i in range(3):
            exe.run(feed=_feed(seed=i), fetch_list=[loss])
    after = obs.registry().snapshot()
    deltas = [(n, s) for n, s in after.items()
              if s != before.get(n)]
    assert not deltas, f"hot path wrote metrics: {deltas}"


def test_profiling_does_not_retrace_the_compiled_cache():
    loss = _build_net()
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feed = _feed()
    exe.run(feed=feed, fetch_list=[loss])          # compile once
    opprof.profile_program(prog, executor=exe, feed=feed, batch=16,
                           reps=1, warmup=0)
    with retrace_guard():                          # eager walk left the
        exe.run(feed=feed, fetch_list=[loss])      # cache untouched


# ---------------------------------------------------------------------------
# synthesis helpers
# ---------------------------------------------------------------------------
def test_synth_feeds_bound_by_consumers_and_lod_companions():
    words = layers.data("words", shape=[], dtype="int64", lod_level=1)
    emb = layers.embedding(words, size=(37, 8))
    layers.mean(emb)
    prog = pt.default_main_program()
    feeds = opprof.synth_feeds(prog, batch=6, seq_len=5)
    assert feeds["words"].shape == (6, 5)
    assert feeds["words"].max() < 37        # bounded by the table rows
    assert feeds["words@LEN"].shape == (6,)
    assert (feeds["words@LEN"] == 5).all()


def test_synth_state_prefers_live_scope_values():
    _build_net()
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    scope = pt.global_scope()
    state = opprof.synth_state(prog, scope=scope, batch=8)
    keys = set(exe._state_keys(prog, scope))
    assert keys <= set(state)
    k = next(iter(keys))
    assert np.asarray(state[k]) == pytest.approx(np.asarray(scope.get(k)))


# ---------------------------------------------------------------------------
# CLI rounds
# ---------------------------------------------------------------------------
def _save_program(tmp_path):
    _build_net()
    prog = pt.default_main_program()
    path = tmp_path / "prog.json"
    path.write_text(prog.to_json())
    return str(path)


def test_cli_profile_in_process(tmp_path, capsys):
    from paddle_tpu import cli
    path = _save_program(tmp_path)
    cal = str(tmp_path / "cal.json")
    rc = cli.main(["profile", path, "--batch", "4", "--reps", "1",
                   "--warmup", "0", "--json", "--calibration-out", cal])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["ops"] > 0 and rep["rows"]
    assert rep["xla_loses_here"]
    # the committed table round-trips into the planner loader
    from paddle_tpu.observability import attribution
    ratios = attribution.load_op_class_ratios(cal)
    assert ratios
    # `plan --calibration` accepts the same file (tp-splittable or not,
    # the load path is what this pins)
    doc = json.load(open(cal))
    assert doc["format"] == 2 and doc["op_classes"]


def test_cli_doctor_per_op_joins_profile(tmp_path, capsys):
    from paddle_tpu import cli
    log = tmp_path / "run.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    for i in range(3):
        exe.run(feed=_feed(seed=i), fetch_list=[loss])
    flags.set_flag("metrics_log", "")
    obs_export._reset_writer()
    path = _save_program(tmp_path) if False else None
    prog_path = tmp_path / "prog.json"
    prog_path.write_text(pt.default_main_program().to_json())
    rc = cli.main(["doctor", str(log), "--program", str(prog_path),
                   "--per-op", "--batch", "4", "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "training" in rep             # the PR 10 step budget
    assert rep["per_op"]["ops"] > 0      # joined under it
    assert rep["per_op"]["rows"]


def test_cli_doctor_per_op_requires_program(capsys, tmp_path):
    from paddle_tpu import cli
    log = tmp_path / "x.jsonl"
    log.write_text("")
    with pytest.raises(SystemExit):
        cli.main(["doctor", str(log), "--per-op"])


@pytest.mark.slow
@pytest.mark.timeout(180)
def test_cli_profile_subprocess_round(tmp_path):
    path = _save_program(tmp_path)
    cal = str(tmp_path / "cal.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "profile", path,
         "--batch", "4", "--reps", "1", "--warmup", "0", "--json",
         "--calibration-out", cal],
        capture_output=True, text=True, timeout=170, env=env,
        cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-4000:]
    rep = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rep["ops"] > 0
    assert os.path.exists(cal)
