"""Persistent autotuner (paddle_tpu.tuning): registry contracts, search
engine discipline + fault containment, store invalidation matrix, and
the replay acceptance criteria — zero search cost / zero added retraces
on warm replay, byte-identical defaults when untuned.
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import registry as core_registry
from paddle_tpu.core.registry import register_tunable
from paddle_tpu.testing import faultinject


@pytest.fixture
def tuning():
    """Import the package (lazily, like a call site) with clean memo and
    injection state on both sides."""
    from paddle_tpu import tuning as t
    t.clear_memo()
    faultinject.clear()
    yield t
    t.clear_memo()
    faultinject.clear()


@pytest.fixture
def knob(tuning):
    """A throwaway registered tunable, removed afterwards so the global
    registry (and the repo-lint live-vs-AST agreement gate) stays
    pristine."""
    name = "test/knob"
    core_registry._TUNABLES.pop(name, None)
    entry = register_tunable(
        name, side="host",
        space={"a": (1, 2), "b": (10, 20)},
        default={"a": 1, "b": 10},
        description="test knob")
    yield name, entry
    core_registry._TUNABLES.pop(name, None)


@pytest.fixture
def autotune_env(tmp_path, tuning):
    """cache_dir + autotune flags pointed at a throwaway store, restored
    afterwards."""
    from paddle_tpu import flags
    prev_cache = flags.get_flag("cache_dir")
    prev_auto = flags.get_flag("autotune")
    flags.set_flag("cache_dir", str(tmp_path))
    flags.set_flag("autotune", True)
    yield str(tmp_path)
    flags.set_flag("cache_dir", prev_cache)
    flags.set_flag("autotune", prev_auto)
    tuning.clear_memo()


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------
def test_register_tunable_validates_declarations(knob):
    name, _ = knob
    with pytest.raises(ValueError, match="registered twice"):
        register_tunable(name, side="host", space={"a": (1,)},
                         default={"a": 1})
    for kwargs, match in [
        (dict(side="gpu", space={"a": (1,)}, default={"a": 1}),
         "side"),
        (dict(side="host", space={}, default={}), "empty"),
        (dict(side="host", space={"a": (1,)}, default={"a": 1, "b": 2}),
         "default keys"),
        (dict(side="host", space={"a": (1, 2)}, default={"a": 3}),
         "not in its axis"),
        (dict(side="host", space={"a": (1, 1)}, default={"a": 1}),
         "duplicate values"),
        (dict(side="device", space={"a": (1,)}, default={"a": 1},
              pending_hardware=True), "decision_rule"),
    ]:
        with pytest.raises(ValueError, match=match):
            register_tunable("test/bad", **kwargs)
    with pytest.raises(ValueError, match="not namespaced"):
        register_tunable("flatname", side="host", space={"a": (1,)},
                         default={"a": 1})


def test_grid_configs_default_first_and_complete(tuning, knob):
    name, entry = knob
    configs = list(tuning.grid_configs(entry))
    assert configs[0] == {"a": 1, "b": 10}          # default first
    assert len(configs) == 4
    assert len({repr(sorted(c.items())) for c in configs}) == 4


def test_validate_config_reports_schema_drift(tuning, knob):
    _, entry = knob
    assert tuning.validate_config(entry, {"a": 2, "b": 20}) == []
    assert tuning.validate_config(entry, {"a": 2}) \
        == ["missing param 'b'"]
    assert any("not in declared axis" in p for p in
               tuning.validate_config(entry, {"a": 7, "b": 10}))
    assert any("unknown param" in p for p in
               tuning.validate_config(entry, {"a": 1, "b": 10, "z": 0}))


# ---------------------------------------------------------------------------
# Store: roundtrip + the invalidation matrix (every failure mode is a
# silent fall-back to defaults, like the checkpoint corruption tests)
# ---------------------------------------------------------------------------
def test_store_roundtrip_and_merge_subset(tuning, knob, tmp_path):
    name, _ = knob
    base = str(tmp_path)
    path = tuning.save_record(name, {"a": 2, "b": 20}, base=base,
                              speedup=1.5)
    assert os.path.exists(path)
    rec = tuning.load_record(name, base=base)
    assert rec["config"] == {"a": 2, "b": 20}
    assert rec["speedup"] == 1.5
    # tuned merges over the caller's default and only known keys
    assert tuning.tuned(name, {"a": 1, "b": 10}, base=base) \
        == {"a": 2, "b": 20}
    tuning.clear_memo()
    assert tuning.tuned(name, {"a": 1}, base=base) == {"a": 2}


def test_store_save_rejects_foreign_config(tuning, knob, tmp_path):
    name, _ = knob
    with pytest.raises(ValueError, match="declared space"):
        tuning.save_record(name, {"a": 7, "b": 10}, base=str(tmp_path))


def test_tuned_without_record_returns_default_object(tuning, knob,
                                                     tmp_path):
    name, _ = knob
    default = {"a": 1, "b": 10}
    out = tuning.tuned(name, default, base=str(tmp_path))
    assert out is default            # the SAME object, untouched
    # and the negative lookup memoizes: delete the dir, still default
    out2 = tuning.tuned(name, default, base=str(tmp_path))
    assert out2 is default


def test_store_invalidation_matrix(tuning, knob, tmp_path, monkeypatch):
    """jax/framework version bump, topology change, schema-version bump,
    tunable-space edit, and corrupt/truncated/drifted records each fall
    back to defaults WITHOUT error."""
    from paddle_tpu.core import compile_cache
    from paddle_tpu.tuning import store

    name, entry = knob
    base = str(tmp_path)
    default = {"a": 1, "b": 10}
    winner = {"a": 2, "b": 20}
    tuning.save_record(name, winner, base=base)

    def fresh_tuned():
        tuning.clear_memo()
        return tuning.tuned(name, default, base=base)

    assert fresh_tuned() == winner                 # baseline: replays

    # 1. framework/jax version bump -> different environment key
    monkeypatch.setattr(compile_cache, "environment_key",
                        lambda: ("jax-99.0", "9.9.9", "cpu", 8))
    assert fresh_tuned() is default
    monkeypatch.undo()

    # 2. topology change (device kind / count)
    monkeypatch.setattr(store, "topology_key", lambda: ("TPU v5", 256))
    assert fresh_tuned() is default
    monkeypatch.undo()

    # 3. tuning schema-version bump
    monkeypatch.setattr(store, "TUNING_FORMAT", store.TUNING_FORMAT + 1)
    assert fresh_tuned() is default
    monkeypatch.undo()

    # 4. tunable declaration edit (space digest changes)
    old_space = dict(entry["space"])
    entry["space"]["a"] = (1, 2, 3)
    assert fresh_tuned() is default
    entry["space"].update(old_space)
    assert fresh_tuned() == winner                 # restored: replays again

    path = store.record_path(name, base=base)

    # 5. truncated record
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])
    assert fresh_tuned() is default

    # 6. binary garbage
    with open(path, "wb") as f:
        f.write(b"\x00\xff\x13garbage")
    assert fresh_tuned() is default

    # 7. valid JSON, drifted config (value outside the declared space)
    payload = json.loads(blob.decode())
    payload["config"] = {"a": 7, "b": 10}
    with open(path, "w") as f:
        json.dump(payload, f)
    assert fresh_tuned() is default

    # 8. valid JSON, foreign tunable name
    payload = json.loads(blob.decode())
    payload["tunable"] = "other/knob"
    with open(path, "w") as f:
        json.dump(payload, f)
    assert fresh_tuned() is default

    # intact record replays after all that probing
    with open(path, "wb") as f:
        f.write(blob)
    assert fresh_tuned() == winner


# ---------------------------------------------------------------------------
# Search engine
# ---------------------------------------------------------------------------
def _sleep_measure(costs):
    """Deterministic synthetic workload: per-config sleep."""
    def measure(cfg):
        time.sleep(costs[(cfg["a"], cfg["b"])])
    return measure


def test_grid_search_finds_fastest_and_contains_failures(tuning, knob):
    name, _ = knob
    costs = {(1, 10): 0.015, (1, 20): 0.004, (2, 10): 0.015,
             (2, 20): 0.015}

    def measure(cfg):
        if (cfg["a"], cfg["b"]) == (2, 10):
            raise RuntimeError("this config cannot run")
        time.sleep(costs[(cfg["a"], cfg["b"])])

    result = tuning.grid_search(name, measure, reps=2, warmup=0)
    assert result.best == {"a": 1, "b": 20}
    by_status = {}
    for t in result.trials:
        by_status[t.status] = by_status.get(t.status, 0) + 1
    assert by_status == {"ok": 3, "failed": 1}
    failed = [t for t in result.trials if t.status == "failed"][0]
    assert "cannot run" in failed.error


def test_run_trial_soft_timeout_is_contained(tuning, knob):
    name, _ = knob

    def measure(cfg):
        time.sleep(0.05)

    from paddle_tpu.tuning.search import run_trial
    t = run_trial(measure, {"a": 1, "b": 10}, reps=3, warmup=0,
                  trial_timeout_s=0.01)
    assert t.status == "timeout"
    assert t.seconds is None


def test_faultinject_site_fail_and_timeout(tuning, knob):
    """tuning.trial[fail/timeout]: deterministic containment — the search
    records the injected trial and keeps going."""
    name, _ = knob
    faultinject.configure("tuning.trial@1=fail;tuning.trial@2=timeout")
    calls = []

    def measure(cfg):
        calls.append(dict(cfg))

    result = tuning.grid_search(name, measure, reps=1, warmup=0)
    statuses = [t.status for t in result.trials]
    assert statuses[0] == "failed"
    assert statuses[1] == "timeout"
    assert statuses[2:] == ["ok", "ok"]
    assert faultinject.fired("tuning.trial") == 2
    assert result.best is not None                 # search survived


def test_successive_halving_converges(tuning, knob):
    name, _ = knob
    costs = {(1, 10): 0.012, (1, 20): 0.012, (2, 10): 0.003,
             (2, 20): 0.012}
    result = tuning.successive_halving(name, _sleep_measure(costs),
                                       reps=3, warmup=0)
    assert result.best == {"a": 2, "b": 10}
    assert result.algo == "halving"


def test_paired_ab_noise_gate_refuses_flat_and_accepts_real(tuning, knob):
    name, _ = knob

    def flat(cfg):
        time.sleep(0.004)

    v = tuning.paired_ab(flat, {"a": 1, "b": 10}, {"a": 2, "b": 20},
                         pairs=4, warmup=0)
    assert not v["accepted"]
    assert "noise band" in v["refusal_reason"]
    assert len(v["default_windows"]) == len(v["candidate_windows"]) == 4

    def real(cfg):
        time.sleep(0.012 if cfg == {"a": 1, "b": 10} else 0.004)

    v = tuning.paired_ab(real, {"a": 1, "b": 10}, {"a": 2, "b": 20},
                         pairs=4, warmup=0)
    assert v["accepted"]
    assert v["speedup"] > 1.5


def test_tune_persists_winner_and_replays(tuning, knob, tmp_path):
    name, _ = knob
    base = str(tmp_path)
    costs = {(1, 10): 0.015, (1, 20): 0.003, (2, 10): 0.015,
             (2, 20): 0.015}
    doc = tuning.tune(name, _sleep_measure(costs), reps=2, pairs=3,
                      warmup=0, base=base)
    assert doc["status"] == "winner"
    assert doc["winner"] == {"a": 1, "b": 20}
    assert os.path.exists(doc["record_path"])
    assert tuning.tuned(name, {"a": 1, "b": 10}, base=base) \
        == {"a": 1, "b": 20}


def test_tune_refusal_persists_nothing(tuning, knob, tmp_path):
    name, _ = knob
    base = str(tmp_path)
    # distinct configs, identical cost: any "winner" is jitter
    doc = tuning.tune(name, lambda cfg: time.sleep(0.004), reps=2,
                      pairs=3, warmup=0, base=base)
    assert doc["status"] in ("noise_gate_refusal", "default_is_best")
    assert doc.get("winner") is None
    assert tuning.list_records(base=base) == []
    if doc["status"] == "noise_gate_refusal":
        # the refusal carries its evidence: raw windows + pair ratios
        assert doc["ab"]["pair_ratios"]
        assert doc["ab"]["default_windows"]


def test_tune_device_side_pending_stub_on_cpu(tuning):
    doc = tuning.tune("pallas/flash_attention", None)
    assert doc["status"] == "pending_hardware"
    assert doc["backend"] == "cpu"
    assert "1.10x" in doc["decision_rule"]


# ---------------------------------------------------------------------------
# Replay acceptance: zero search cost, zero added retraces, byte-identical
# defaults when untuned
# ---------------------------------------------------------------------------
def _tiny_net():
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _feeds(n, batch=8):
    rng = np.random.RandomState(5)
    return [{"x": rng.rand(batch, 8).astype(np.float32),
             "y": rng.randint(0, 3, (batch, 1))} for _ in range(n)]


def test_untuned_call_sites_resolve_todays_defaults(tuning):
    """With autotune off — and with it on but no record — every tuned
    call site resolves byte-identical to the hand-picked defaults."""
    exe = pt.Executor()                      # autotune defers to the flag
    d = {"steps_per_dispatch": 4, "prefetch_depth": 2}
    assert exe._tuned("executor/run_pipelined", d) is d
    exe_on = pt.Executor(autotune=True)      # on, but no record
    assert exe_on._tuned("executor/run_pipelined", d) == d
    assert exe_on._effective_compiler_options() == {}

    from paddle_tpu.reader.pipeline import _tuned_defaults
    assert _tuned_defaults(None, None) == (8, 1)
    assert _tuned_defaults(3, 2) == (3, 2)   # explicit always wins


def test_run_pipelined_default_resolution_matches_explicit(tuning):
    """run_pipelined() with omitted knobs (autotune off) is bit-identical
    to the explicit (4, 2) call — the defaults went through the tuned()
    seam without changing."""
    feeds = _feeds(6)
    loss = _tiny_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    outs_default = [o[0] for o in exe.run_pipelined(
        iter(feeds), pt.default_main_program(), fetch_list=[loss])]

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    loss2 = _tiny_net()
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    outs_explicit = [o[0] for o in exe2.run_pipelined(
        iter(feeds), pt.default_main_program(), fetch_list=[loss2],
        steps_per_dispatch=4, prefetch_depth=2)]
    assert len(outs_default) == len(outs_explicit) == 6
    for a, b in zip(outs_default, outs_explicit):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_warm_replay_zero_search_trials_zero_retraces(tuning, knob,
                                                      autotune_env):
    """THE acceptance test: a persisted executor/run_pipelined winner
    replays into the call site with ZERO search trials and ZERO added
    retraces — counter-delta + retrace_guard."""
    from paddle_tpu.core import compile_cache
    from paddle_tpu.observability import registry

    base = autotune_env
    tuning.save_record("executor/run_pipelined",
                       {"steps_per_dispatch": 2, "prefetch_depth": 1},
                       base=base)
    tuning.clear_memo()

    loss = _tiny_net()
    exe = pt.Executor(autotune=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = _feeds(4)

    trials_before = registry().snapshot()["tuning/trials"]["value"]
    outs = list(exe.run_pipelined(iter(feeds), pt.default_main_program(),
                                  fetch_list=[loss]))
    assert len(outs) == 4
    # the replayed K=2 really drove the dispatch: 4 feeds -> 2 scans
    # (per-dispatch evidence: the K=2 scan variant exists in the cache)
    assert len(exe._cache) >= 1

    # warm pass: same variants, zero new traces, zero search trials
    traces_before = compile_cache.stats().snapshot().get("traces", 0)
    with compile_cache.retrace_guard():
        outs2 = list(exe.run_pipelined(iter(feeds),
                                       pt.default_main_program(),
                                       fetch_list=[loss]))
    assert len(outs2) == 4
    assert compile_cache.stats().snapshot().get("traces", 0) \
        == traces_before
    trials_after = registry().snapshot()["tuning/trials"]["value"]
    assert trials_after == trials_before, \
        "replay must never run search trials"


def test_replay_reaches_every_host_call_site(tuning, knob, autotune_env):
    """Persisted winners are picked up by the serving batcher, the
    reader prefetch defaults, the flash-attention layer attrs, and the
    trainer's pipeline-opt fill."""
    base = autotune_env
    tuning.save_record("executor/run_pipelined",
                       {"steps_per_dispatch": 16, "prefetch_depth": 1},
                       base=base)
    tuning.clear_memo()

    # trainer fills omitted knobs from the winner; explicit keys win
    loss = _tiny_net()
    sgd = pt.trainer.SGD.__new__(pt.trainer.SGD)   # no re-minimize
    sgd.exe = pt.Executor(autotune=True)
    opts = {"buffer_size": 99}
    sgd._fill_tuned_pipeline_opts(opts, steps_per_dispatch=1)
    assert opts["steps_per_dispatch"] == 16
    assert opts["prefetch_depth"] == 1
    assert opts["buffer_size"] == 99               # explicit survived
    assert opts["num_workers"] == 1                # no record: default
    del loss

    # reader prefetch defaults
    from paddle_tpu.core.registry import has_tunable
    assert has_tunable("reader/prefetch")
    tuning.save_record("reader/prefetch",
                       {"num_workers": 2, "buffer_size": 16}, base=base)
    tuning.clear_memo()
    from paddle_tpu.reader.pipeline import _tuned_defaults
    assert _tuned_defaults(None, None) == (16, 2)

    # serving batcher (no server started; constructor-time resolution)
    import paddle_tpu.serving.server as srv_mod
    tuning.save_record("serving/batcher",
                       {"max_batch": 8, "max_wait_ms": 2.0}, base=base)
    tuning.clear_memo()
    s = srv_mod.Server(autotune=True)
    assert (s.max_batch, s.max_wait_s) == (8, 0.002)
    s_off = srv_mod.Server(autotune=False)
    assert (s_off.max_batch, s_off.max_wait_s) == (32, 0.005)
    s_explicit = srv_mod.Server(max_batch=64, autotune=True)
    assert s_explicit.max_batch == 64              # explicit wins

    # flash-attention layer: the winner lands in the OP ATTRS (the
    # fingerprint-coherent replay point)
    tuning.save_record("pallas/flash_attention",
                       {"block_q": 2048, "block_k": 2048}, base=base)
    tuning.clear_memo()
    q = layers.data("q", shape=[16, 64], dtype="float32")
    out = layers.flash_attention(q, q, q)
    op = [o for o in pt.default_main_program().global_block().ops
          if o.type == "flash_attention"][-1]
    assert op.attrs["block_q"] == 2048
    assert op.attrs["block_k"] == 2048
    # explicit blocks win over the record
    out2 = layers.flash_attention(q, q, q, block_q=512)
    op2 = [o for o in pt.default_main_program().global_block().ops
           if o.type == "flash_attention"][-1]
    assert op2.attrs["block_q"] == 512
    assert op2.attrs["block_k"] == 2048
    del out, out2


def test_scoped_vmem_winner_reaches_compiler_options_and_fingerprint(
        tuning, knob, autotune_env):
    """A persisted xla/scoped_vmem winner lands in the effective
    compiler options AND the compile fingerprint; the default-valued
    record injects nothing (absence == XLA default)."""
    base = autotune_env
    exe = pt.Executor(autotune=True)
    assert exe._effective_compiler_options() == {}

    tuning.save_record("xla/scoped_vmem_limit_kib",
                       {"scoped_vmem_limit_kib": 16 * 1024}, base=base)
    tuning.clear_memo()
    assert exe._effective_compiler_options() == {}   # default value: no-op

    tuning.save_record("xla/scoped_vmem_limit_kib",
                       {"scoped_vmem_limit_kib": 64 * 1024}, base=base)
    tuning.clear_memo()
    assert exe._effective_compiler_options() \
        == {"xla_tpu_scoped_vmem_limit_kib": "65536"}
    # and the fingerprint sees it (vs an autotune-off executor)
    assert exe._config_sig() != pt.Executor(autotune=False)._config_sig()
    # explicit user option wins over the record
    exe_user = pt.Executor(
        autotune=True,
        compiler_options={"xla_tpu_scoped_vmem_limit_kib": "32768"})
    assert exe_user._effective_compiler_options() \
        == {"xla_tpu_scoped_vmem_limit_kib": "32768"}


def test_import_paddle_tpu_does_not_load_tuning():
    """Runtime half of the lazy-import contract (static half in
    test_repo_lint): the core import path and an untuned executor run
    never pull paddle_tpu.tuning into sys.modules.  In-process proxy:
    this suite imports tuning in its own fixtures, so assert on the
    DECLARATION side — registering tunables needed no tuning import,
    and core.registry (which owns the declarations AND the shared
    ``resolve_tuned`` replay helper since round 15) only names the
    package inside the opted-in branch of that helper: every
    ``from ..tuning`` in its source is function-local, so importing
    the registry can never load the package."""
    import ast
    import importlib
    reg = importlib.import_module("paddle_tpu.core.registry")
    tree = ast.parse(open(reg.__file__).read())
    for node in tree.body:                   # MODULE level only
        assert not (isinstance(node, ast.ImportFrom)
                    and node.module and "tuning" in node.module)
        assert not (isinstance(node, ast.Import) and any(
            "tuning" in a.name for a in node.names))
    # and an untuned dispatch resolves without the package: the off path
    # short-circuits before any tuning import (`is` pins the
    # byte-identical-when-untuned contract)
    exe = pt.Executor(autotune=False)
    d = {"steps_per_dispatch": 4, "prefetch_depth": 2}
    assert exe._tuned("executor/run_pipelined", d) is d
    from paddle_tpu.core.registry import resolve_tuned
    assert resolve_tuned("executor/run_pipelined", d, False) is d


def test_warmup_aot_compiles_the_tuned_scan_variant(tuning, knob,
                                                    autotune_env):
    """train(pipeline=True, warmup=True, autotune=True) with a persisted
    winner must AOT-compile the WINNER's K — the training loop then
    dispatches with zero traces (warmup compiling the untuned K and the
    loop paying a first-dispatch compile stall was the bug)."""
    from paddle_tpu.core import compile_cache

    base = autotune_env
    tuning.save_record("executor/run_pipelined",
                       {"steps_per_dispatch": 2, "prefetch_depth": 1},
                       base=base)
    tuning.clear_memo()

    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    pred = layers.fc(x, size=3, act="softmax")
    cost = layers.mean(layers.cross_entropy(pred, y))
    sgd = pt.trainer.SGD(cost, update_equation=pt.optimizer.SGD(
        learning_rate=0.1))

    rng = np.random.RandomState(2)
    rows = [list(zip(rng.rand(8, 8).astype(np.float32),
                     rng.randint(0, 3, (8, 1)))) for _ in range(4)]

    def reader():
        return iter(rows)

    # warmup compiles startup + single-step + the K=2 scan variant; the
    # 4-batch loop (two K=2 scans) must then trace NOTHING new
    sgd.train(reader, num_passes=1, feed_list=[x, y],
              pipeline=True, warmup=True, autotune=True,
              event_handler=lambda e: None)
    # exactly 3 variants exist: startup, single-step, the K=2 scan — a
    # warmup that ignored the winner would have AOT-compiled a FOURTH
    # (the untuned K=8 scan) and the loop would have traced K=2 cold
    assert len(sgd.exe._cache) == 3, \
        f"expected startup+single+K=2 variants, got {len(sgd.exe._cache)}"
    traces_after_first = compile_cache.stats().snapshot().get("traces", 0)
    with compile_cache.retrace_guard():
        sgd.train(reader, num_passes=1, feed_list=[x, y],
                  pipeline=True, autotune=True,
                  event_handler=lambda e: None)
    assert compile_cache.stats().snapshot().get("traces", 0) \
        == traces_after_first


# ---------------------------------------------------------------------------
# CLI + observability surfacing
# ---------------------------------------------------------------------------
def test_tune_cli_refuses_search_without_a_store(tuning, capsys):
    """A save-requested search with no store configured must fail BEFORE
    searching (an accepted winner with nowhere to persist silently
    no-ops the documented search-then-replay workflow)."""
    from paddle_tpu import cli, flags
    prev = flags.get_flag("cache_dir")
    flags.set_flag("cache_dir", "")
    try:
        with pytest.raises(SystemExit, match="no winner store"):
            cli.main(["tune", "reader/prefetch", "--smoke"])
    finally:
        flags.set_flag("cache_dir", prev)


def test_tune_cli_smoke_in_process(tuning, capsys):
    from paddle_tpu import cli
    rc = cli.main(["tune", "reader/prefetch", "--smoke", "--budget", "2",
                   "--reps", "1", "--pairs", "2", "--no-save"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["tunable"] == "reader/prefetch"
    assert summary["status"] in ("winner", "default_is_best",
                                 "noise_gate_refusal", "no_viable_config")


def test_tune_cli_lists_registry(tuning, capsys):
    from paddle_tpu import cli
    assert cli.main(["tune", "--list"]) == 0
    out = capsys.readouterr().out
    assert "executor/run_pipelined" in out
    assert "decision rule" in out


def test_tuning_events_reach_stats_summary(tuning, knob, tmp_path):
    """Search/winner/replay events land in the JSONL log and the stats
    summarizer renders a tuning section."""
    from paddle_tpu import flags
    from paddle_tpu.observability import export

    name, _ = knob
    log = str(tmp_path / "run.jsonl")
    prev = flags.get_flag("metrics_log")
    flags.set_flag("metrics_log", log)
    try:
        costs = {(1, 10): 0.012, (1, 20): 0.003, (2, 10): 0.012,
                 (2, 20): 0.012}
        tuning.tune(name, _sleep_measure(costs), reps=2, pairs=3,
                    warmup=0, base=str(tmp_path))
        tuning.clear_memo()
        tuning.tuned(name, {"a": 1, "b": 10}, base=str(tmp_path))
    finally:
        flags.set_flag("metrics_log", prev)
        export._reset_writer()
    summary = export.summarize_log(log)
    tu = summary["tuning"]
    assert tu["trials"] == 4
    assert tu["winners"] and tu["winners"][0]["config"] \
        == {"a": 1, "b": 20}
    assert tu["replays"] and tu["replays"][0]["tunable"] == name
    rendered = export.render_summary(summary)
    assert "tuning:" in rendered and "winner:" in rendered
