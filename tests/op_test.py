"""Generic single-op test harness.

The analog of the reference's python/paddle/v2/fluid/tests/op_test.py
(SURVEY §4): build a program containing ONE op, run it, compare the forward
against a numpy reference, and check analytic gradients (jax.value_and_grad
through the lowering) against central finite differences computed by
re-running the forward — exactly the reference's get_numeric_gradient
strategy (op_test.py:120-180), with XLA autodiff standing in for the
hand-written grad kernels under test there.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _normalize_inputs(inputs) -> Dict[str, List[Tuple[str, np.ndarray]]]:
    """inputs: {slot: array | (name, array) | [(name, array), ...]}"""
    norm = {}
    for slot, v in inputs.items():
        if isinstance(v, np.ndarray):
            norm[slot] = [(f"{slot.lower()}__in", v)]
        elif isinstance(v, tuple):
            norm[slot] = [v]
        else:
            norm[slot] = list(v)
    return norm


def _build(op_type, inputs, attrs, out_slots, lens=None,
           n_outs_per_slot=None):
    """Returns (main, startup, feed_dict, out_names {slot: [names]})."""
    main, startup = pt.Program(), pt.Program()
    inputs = _normalize_inputs(inputs)
    lens = lens or {}
    n_outs_per_slot = n_outs_per_slot or {}
    feed = {}
    with pt.program_guard(main, startup):
        in_vars = {}
        for slot, pairs in inputs.items():
            vs = []
            for name, arr in pairs:
                lod = 1 if name in lens else 0
                v = layers.data(name, shape=list(arr.shape),
                                dtype=str(arr.dtype),
                                append_batch_size=False, lod_level=lod)
                feed[name] = arr
                if name in lens:
                    feed[name + "@LEN"] = np.asarray(lens[name])
                vs.append(v)
            in_vars[slot] = vs
        gb = main.global_block()
        out_names = {}
        for slot in out_slots:
            n = n_outs_per_slot.get(slot, 1)
            out_names[slot] = []
            for i in range(n):
                ov = gb.create_var(name=f"{slot.lower()}__out{i}",
                                   dtype="float32")
                out_names[slot].append(ov.name)
        gb.append_op(op_type,
                     inputs={s: [v.name for v in vs]
                             for s, vs in in_vars.items()},
                     outputs={s: list(ns) for s, ns in out_names.items()},
                     attrs=dict(attrs or {}))
    return main, startup, feed, out_names


def run_op(op_type, inputs, attrs, out_slots, lens=None, is_test=False,
           n_outs_per_slot=None, fetch_lens=False):
    main, startup, feed, out_names = _build(
        op_type, inputs, attrs, out_slots, lens, n_outs_per_slot)
    exe = pt.Executor(use_jit=False)
    scope = pt.Scope()
    exe.run(startup, feed={}, fetch_list=[], scope=scope)
    fetch = [n for slot in out_slots for n in out_names[slot]]
    if fetch_lens:
        fetch += [n + "@LEN" for slot in out_slots for n in out_names[slot]]
    vals = exe.run(main, feed=feed, fetch_list=fetch, scope=scope,
                   is_test=is_test)
    return dict(zip(fetch, vals))


def check_output(op_type, inputs, attrs, expected: Dict[str, np.ndarray],
                 lens=None, atol=1e-5, rtol=1e-4, is_test=True):
    """expected: {slot: array} (or {slot~i} for multi-output slots)."""
    slots = sorted({k.split("~")[0] for k in expected})
    n_per = {}
    for k in expected:
        s = k.split("~")[0]
        n_per[s] = max(n_per.get(s, 1),
                       int(k.split("~")[1]) + 1 if "~" in k else 1)
    got = run_op(op_type, inputs, attrs, slots, lens=lens, is_test=is_test,
                 n_outs_per_slot=n_per)
    for key, exp in expected.items():
        slot, idx = (key.split("~") + ["0"])[:2] if "~" in key \
            else (key, "0")
        name = f"{slot.lower()}__out{idx}"
        np.testing.assert_allclose(
            got[name], exp, atol=atol, rtol=rtol,
            err_msg=f"{op_type} output {key} mismatch")
    return got


def check_grad(op_type, inputs, attrs, wrt: Sequence[str],
               out_slots: Sequence[str] = ("Out",), lens=None,
               eps=2e-3, max_relative_error=5e-3, no_jit=True):
    """Compare analytic grads (value_and_grad through the lowering) against
    central differences of the scalar loss sum(out * W) with fixed random W
    (the reference uses uniform output grads; random W catches sign errors).
    """
    main, startup, feed, out_names = _build(op_type, inputs, attrs,
                                            list(out_slots), lens)
    rng = np.random.RandomState(7)
    with pt.program_guard(main, startup):
        gb = main.global_block()
        weighted = []
        for slot in out_slots:
            for n in out_names[slot]:
                ov = gb.var(n)
                # fixed random weight per output element, fed as data
                wname = n + "__w"
                # shape unknown until run; weight built lazily below
                weighted.append((ov, wname))
        # run once to get output shapes
        exe0 = pt.Executor(use_jit=False)
        s0 = pt.Scope()
        exe0.run(startup, feed={}, fetch_list=[], scope=s0)
        shapes = exe0.run(main, feed=feed,
                          fetch_list=[ov for ov, _ in weighted], scope=s0)
        terms = []
        for (ov, wname), arr in zip(weighted, shapes):
            w = rng.uniform(0.5, 1.5, np.shape(arr)).astype(arr.dtype)
            wv = layers.data(wname, shape=list(np.shape(arr)),
                             dtype=str(np.asarray(arr).dtype),
                             append_batch_size=False)
            feed[wname] = w
            terms.append(layers.reduce_sum(layers.elementwise_mul(ov, wv)))
        loss = terms[0] if len(terms) == 1 else layers.sums(terms)
        pairs = pt.append_backward(loss, parameter_list=list(wrt))

    exe = pt.Executor(use_jit=not no_jit)
    scope = pt.Scope()
    exe.run(startup, feed={}, fetch_list=[], scope=scope)
    fetches = exe.run(main, feed=feed,
                      fetch_list=[loss] + [g for _, g in pairs], scope=scope)
    analytic = dict(zip(wrt, fetches[1:]))

    def forward_loss(f):
        out = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
        return float(out[0])

    for name in wrt:
        base = feed[name]
        num = np.zeros_like(base, dtype=np.float64)
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            lp = forward_loss(feed)
            flat[i] = orig - eps
            lm = forward_loss(feed)
            flat[i] = orig
            num.reshape(-1)[i] = (lp - lm) / (2 * eps)
        a = np.asarray(analytic[name], np.float64)
        denom = max(np.abs(num).max(), np.abs(a).max(), 1e-3)
        rel = np.abs(a - num).max() / denom
        assert rel <= max_relative_error, (
            f"{op_type} grad wrt {name}: max rel error {rel:.4g} > "
            f"{max_relative_error} (analytic {a.ravel()[:5]} vs numeric "
            f"{num.ravel()[:5]})")
