"""v1 config DSL compat tests: configs written in the reference's
trainer_config_helpers DSL build and train on the TPU-native runtime
(reference: config_parser_test.py + trainer tests with sample configs)."""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.trainer_config_helpers import load_v1_config

REF_IMG = "/root/reference/benchmark/paddle/image"


def _write_cfg(tmp_path, body):
    p = tmp_path / "cfg.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_own_v1_mlp_config_trains(tmp_path, rng):
    path = _write_cfg(tmp_path, """
        from paddle.trainer_config_helpers import *
        settings(batch_size=8, learning_rate=0.1,
                 learning_method=MomentumOptimizer(0.9))
        img = data_layer(name='pixel', size=64)
        lab = data_layer(name='label', size=10)
        h = fc_layer(input=img, size=32, act=ReluActivation())
        net = fc_layer(input=h, size=10, act=SoftmaxActivation())
        loss = classification_cost(input=net, label=lab)
        outputs(loss)
    """)
    cfg = load_v1_config(path)
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    feeds = {"pixel": rng.rand(8, 64).astype("float32"),
             "label": rng.randint(0, 10, (8, 1))}
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(5)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


def test_own_v1_conv_config_builds(tmp_path):
    path = _write_cfg(tmp_path, """
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.01,
                 regularization=L2Regularization(5e-4))
        img = data_layer(name='image', size=3 * 16 * 16)
        lab = data_layer(name='label', size=10)
        conv = img_conv_layer(input=img, filter_size=3, num_channels=3,
                              num_filters=8, padding=1,
                              act=ReluActivation())
        pool = img_pool_layer(input=conv, pool_size=2, stride=2,
                              pool_type=MaxPooling())
        bn = batch_norm_layer(input=pool, act=ReluActivation())
        out = fc_layer(input=bn, size=10, act=SoftmaxActivation(),
                       layer_attr=ExtraAttr(drop_rate=0.5))
        loss = classification_cost(input=out, label=lab)
        outputs(loss)
    """)
    cfg = load_v1_config(path)
    assert len(cfg.outputs) == 1
    ops = [op.type for op in cfg.main_program.global_block().ops]
    for t in ("conv2d", "pool2d", "batch_norm", "dropout", "cross_entropy"):
        assert t in ops, (t, ops)


@pytest.mark.skipif(not os.path.exists(REF_IMG),
                    reason="reference tree not mounted")
@pytest.mark.parametrize("name,args", [
    ("alexnet.py", {"batch_size": 2}),
    ("smallnet_mnist_cifar.py", {"batch_size": 2}),
    ("vgg.py", {"batch_size": 2, "layer_num": 16}),
    ("resnet.py", {"batch_size": 2, "layer_num": 50}),
    ("googlenet.py", {"batch_size": 2, "use_gpu": False}),
])
def test_reference_benchmark_configs_train(name, args, rng):
    """The reference's own benchmark/paddle/image configs evaluate
    UNCHANGED against the compat DSL (BASELINE.json north star: 'benchmark
    scripts launch unchanged') AND TRAIN: two optimizer steps on a tiny
    batch at the config's full input resolution, loss decreasing — the
    `run.sh job=time` semantics, not just a parse check."""
    cfg = load_v1_config(os.path.join(REF_IMG, name), **args)
    assert cfg.outputs, name
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    names = list(cfg.data_layers)
    img_size = cfg.data_layers[names[0]].shape[-1]
    B = args["batch_size"]
    feeds = {names[0]: rng.rand(B, img_size).astype("float32") * 0.1,
             names[1]: rng.randint(0, 10, (B, 1))}
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(2)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0], (name, vals)


@pytest.mark.skipif(not os.path.exists(REF_IMG),
                    reason="reference tree not mounted")
def test_reference_smallnet_config_trains(rng):
    cfg = load_v1_config(os.path.join(REF_IMG, "smallnet_mnist_cifar.py"),
                         batch_size=4)
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    feeds = {"image": rng.rand(4, 3 * 32 * 32).astype("float32"),
             "label": rng.randint(0, 10, (4, 1))}
    data_names = list(cfg.data_layers)
    # the config's own data layer names drive the feed
    feeds = {data_names[0]: feeds["image"], data_names[1]: feeds["label"]}
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(4)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


GSERVER = "/root/reference/paddle/gserver/tests"


@pytest.mark.parametrize("conf,feed_shape", [
    ("concat_dotmul_a.conf", (4, 1000)),
    ("concat_dotmul_b.conf", (4, 1000)),
    ("concat_fullmatrix_a.conf", (4, 100)),
    ("concat_table_a.conf", None),              # int ids
    ("concat_slice_a.conf", (4, 8 * 16 * 16)),
    ("img_conv_a.conf", (4, 8 * 16 * 16)),
    ("img_conv_b.conf", (4, 8 * 16 * 16)),
    ("img_pool_a.conf", (4, 8 * 16 * 16)),
    ("img_pool_b.conf", (4, 8 * 16 * 16)),
])
@pytest.mark.needs_reference
def test_gserver_layer_configs_forward(conf, feed_shape, rng):
    """gserver layer-equivalence test configs evaluated VERBATIM: mixed
    projections (dotmul/fullmatrix/table/slice), conv/pool layer and
    projection forms — forward produces finite outputs."""
    from paddle_tpu.trainer_config_helpers import load_v1_config

    cfg = load_v1_config(os.path.join(GSERVER, conf))
    if feed_shape is None:
        feed = {"input": rng.randint(0, 10000, (4, 1)).astype("int64")}
    else:
        feed = {"input": rng.rand(*feed_shape).astype("float32")}
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    outs = exe.run(cfg.main_program, feed=feed, fetch_list=cfg.outputs,
                   is_test=True)
    assert outs and all(np.isfinite(np.asarray(o)).all() for o in outs)
