"""Per-op tests for optimizer ops vs numpy references (reference:
fluid/tests/test_sgd_op.py, test_momentum_op.py, test_adam_op.py, ...)."""
import numpy as np

from op_test import run_op

R = np.random.RandomState(9)
N = (4, 3)
LR = np.array([0.1], "float32")


def _pg():
    return (R.uniform(-1, 1, N).astype("float32"),
            R.uniform(-1, 1, N).astype("float32"))


def test_sgd_op():
    p, g = _pg()
    got = run_op("sgd", {"Param": ("p", p), "Grad": ("g", g),
                         "LearningRate": ("lr", LR)}, {}, ["ParamOut"])
    np.testing.assert_allclose(got["paramout__out0"], p - 0.1 * g,
                               rtol=1e-6)


def test_momentum_op():
    p, g = _pg()
    v = R.uniform(-1, 1, N).astype("float32")
    got = run_op("momentum",
                 {"Param": ("p", p), "Grad": ("g", g), "Velocity": ("v", v),
                  "LearningRate": ("lr", LR)},
                 {"mu": 0.9}, ["ParamOut", "VelocityOut"])
    v_out = 0.9 * v + g
    np.testing.assert_allclose(got["velocityout__out0"], v_out, rtol=1e-6)
    np.testing.assert_allclose(got["paramout__out0"], p - 0.1 * v_out,
                               rtol=1e-5)
    # nesterov variant
    got = run_op("momentum",
                 {"Param": ("p", p), "Grad": ("g", g), "Velocity": ("v", v),
                  "LearningRate": ("lr", LR)},
                 {"mu": 0.9, "use_nesterov": True}, ["ParamOut"])
    np.testing.assert_allclose(got["paramout__out0"],
                               p - (g + 0.9 * v_out) * 0.1, rtol=1e-5)


def test_adam_op():
    p, g = _pg()
    m = R.uniform(-1, 1, N).astype("float32")
    v = R.uniform(0, 1, N).astype("float32")
    b1p = np.array([0.9 ** 3], "float32")
    b2p = np.array([0.999 ** 3], "float32")
    got = run_op("adam",
                 {"Param": ("p", p), "Grad": ("g", g), "Moment1": ("m", m),
                  "Moment2": ("v", v), "Beta1Pow": ("b1", b1p),
                  "Beta2Pow": ("b2", b2p), "LearningRate": ("lr", LR)},
                 {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
                 ["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
                  "Beta2PowOut"])
    m_out = 0.9 * m + 0.1 * g
    v_out = 0.999 * v + 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m_out / (np.sqrt(v_out) + 1e-8)
    np.testing.assert_allclose(got["paramout__out0"], p_out, rtol=1e-5)
    np.testing.assert_allclose(got["beta1powout__out0"], b1p * 0.9,
                               rtol=1e-6)


def test_adagrad_op():
    p, g = _pg()
    mom = R.uniform(0, 1, N).astype("float32")
    got = run_op("adagrad",
                 {"Param": ("p", p), "Grad": ("g", g), "Moment": ("m", mom),
                  "LearningRate": ("lr", LR)},
                 {"epsilon": 1e-6}, ["ParamOut", "MomentOut"])
    m_out = mom + g * g
    np.testing.assert_allclose(got["momentout__out0"], m_out, rtol=1e-6)
    np.testing.assert_allclose(
        got["paramout__out0"], p - 0.1 * g / (np.sqrt(m_out) + 1e-6),
        rtol=1e-5)


def test_rmsprop_op():
    p, g = _pg()
    ms = R.uniform(0, 1, N).astype("float32")
    mom = R.uniform(-1, 1, N).astype("float32")
    got = run_op("rmsprop",
                 {"Param": ("p", p), "Grad": ("g", g),
                  "MeanSquare": ("ms", ms), "Moment": ("m", mom),
                  "LearningRate": ("lr", LR)},
                 {"decay": 0.95, "momentum": 0.8, "epsilon": 1e-6},
                 ["ParamOut", "MomentOut", "MeanSquareOut"])
    ms_out = 0.95 * ms + 0.05 * g * g
    mom_out = 0.8 * mom + 0.1 * g / np.sqrt(ms_out + 1e-6)
    np.testing.assert_allclose(got["meansquareout__out0"], ms_out, rtol=1e-5)
    np.testing.assert_allclose(got["paramout__out0"], p - mom_out, rtol=1e-4)


def test_adadelta_op():
    p, g = _pg()
    asg = R.uniform(0, 1, N).astype("float32")
    asu = R.uniform(0, 1, N).astype("float32")
    got = run_op("adadelta",
                 {"Param": ("p", p), "Grad": ("g", g),
                  "AvgSquaredGrad": ("a", asg),
                  "AvgSquaredUpdate": ("u", asu)},
                 {"rho": 0.95, "epsilon": 1e-6},
                 ["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"])
    g2 = 0.95 * asg + 0.05 * g * g
    upd = -np.sqrt((asu + 1e-6) / (g2 + 1e-6)) * g
    np.testing.assert_allclose(got["avgsquaredgradout__out0"], g2, rtol=1e-5)
    np.testing.assert_allclose(got["paramout__out0"], p + upd, rtol=1e-4)


def test_full_optimizer_builders_train():
    """Every host-side optimizer builder must assemble a runnable program
    (fluid/optimizer.py:213-513 parity)."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    for name, ctor in [
            ("sgd", lambda: pt.optimizer.SGD(0.1)),
            ("momentum", lambda: pt.optimizer.Momentum(0.1, momentum=0.9)),
            ("adam", lambda: pt.optimizer.Adam(0.01)),
            ("adamax", lambda: pt.optimizer.Adamax(0.01)),
            ("adagrad", lambda: pt.optimizer.Adagrad(0.1)),
            ("adadelta", lambda: pt.optimizer.Adadelta(0.1)),
            ("decayed_adagrad", lambda: pt.optimizer.DecayedAdagrad(0.1)),
            ("rmsprop", lambda: pt.optimizer.RMSProp(0.1)),
            ("ftrl", lambda: pt.optimizer.Ftrl(0.1))]:
        pt.core.reset_default_programs()
        pt.core.reset_global_scope()
        pt.unique_name.reset()
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        ctor().minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
        feeds = {"x": R.rand(8, 4).astype("float32"),
                 "y": R.rand(8, 1).astype("float32")}
        vals = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
                for _ in range(4)]
        assert np.isfinite(vals).all(), name
        assert vals[-1] < vals[0], f"{name} did not reduce loss: {vals}"


def test_model_average_apply_restore(rng):
    """ModelAverage swaps averaged weights in for evaluation and restores
    the live ones after (fluid optimizer.ModelAverage / v1 settings
    model_average)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.optimizer import ModelAverage

    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.fc(x, size=1, bias_attr=False,
                  param_attr=pt.ParamAttr(name="w"))
    loss = layers.mean(layers.square_error_cost(
        y, layers.data("t", shape=[1], dtype="float32")))
    pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    ma = ModelAverage(average_window_rate=1.0, var_names=["w"])
    ws = []
    feeds = {"x": rng.rand(8, 4).astype("float32"),
             "t": rng.rand(8, 1).astype("float32")}
    for _ in range(5):
        exe.run(pt.default_main_program(), feed=feeds, fetch_list=[loss])
        ws.append(np.asarray(pt.global_scope().get("w")).copy())
        ma.update()
    live = np.asarray(pt.global_scope().get("w")).copy()
    with ma.apply():
        inside = np.asarray(pt.global_scope().get("w")).copy()
        assert not np.allclose(inside, live)      # averaged, not live
        # running mean with growing window tracks the weight trajectory
        assert np.isfinite(inside).all()
    after = np.asarray(pt.global_scope().get("w"))
    np.testing.assert_array_equal(after, live)    # restored


def test_static_pruning_hook(rng):
    """StaticPruningHook (ParameterUpdaterHook.cpp:39): the smallest 80%
    of |w| are pinned to zero through training — the mask re-applies
    in-graph after every optimizer update."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.optimizer import StaticPruningHook

    x = layers.data("x", shape=[16], dtype="float32")
    t = layers.data("t", shape=[1], dtype="float32")
    y = layers.fc(x, size=1, bias_attr=False,
                  param_attr=pt.ParamAttr(name="w"))
    loss = layers.mean(layers.square_error_cost(y, t))
    pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    hook = StaticPruningHook(sparsity_ratio=0.75).attach(["w"])
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    hook.initialize()
    feeds = {"x": rng.rand(8, 16).astype("float32"),
             "t": rng.rand(8, 1).astype("float32")}
    mask = np.asarray(pt.global_scope().get("w@PRUNE_MASK"))
    assert mask.sum() == 4                      # 12 of 16 pruned
    for _ in range(5):
        exe.run(pt.default_main_program(), feed=feeds, fetch_list=[loss])
        w = np.asarray(pt.global_scope().get("w"))
        assert (w[mask == 0] == 0).all()        # pruned entries stay zero
    assert (w[mask == 1] != 0).any()            # survivors keep training


def test_adam_lazy_mode_rows():
    """adam_op.cc lazy_mode analog: only looked-up rows update; untouched
    rows keep param AND stale moments (no decay)."""
    V, D = 8, 3
    p = R.uniform(-1, 1, (V, D)).astype("float32")
    g = np.zeros((V, D), "float32")
    ids = np.array([[1, 5, 1]], "int64")       # row 1 duplicated
    for i in (1, 5):
        g[i] = R.uniform(-1, 1, D)
    g[1] *= 2.0                                 # summed duplicate grad
    m = R.uniform(-1, 1, (V, D)).astype("float32")
    v = R.uniform(0, 1, (V, D)).astype("float32")
    b1p = np.array([0.9], "float32")
    b2p = np.array([0.999], "float32")
    got = run_op("adam",
                 {"Param": ("p", p), "Grad": ("g", g), "Moment1": ("m", m),
                  "Moment2": ("v", v), "Beta1Pow": ("b1", b1p),
                  "Beta2Pow": ("b2", b2p), "LearningRate": ("lr", LR),
                  "Rows": ("ids", ids)},
                 {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                  "lazy_mode": True},
                 ["ParamOut", "Moment1Out", "Moment2Out"])
    p_o, m_o, v_o = (got["paramout__out0"], got["moment1out__out0"],
                     got["moment2out__out0"])
    # touched rows match the dense formula
    for i in (1, 5):
        m_ref = 0.9 * m[i] + 0.1 * g[i]
        v_ref = 0.999 * v[i] + 0.001 * g[i] * g[i]
        lr_t = 0.1 * np.sqrt(1 - b2p) / (1 - b1p)
        np.testing.assert_allclose(m_o[i], m_ref, rtol=1e-5)
        np.testing.assert_allclose(v_o[i], v_ref, rtol=1e-5)
        np.testing.assert_allclose(
            p_o[i], p[i] - lr_t * m_ref / (np.sqrt(v_ref) + 1e-8),
            rtol=1e-5)
    # untouched rows: bitwise frozen (param and moments)
    untouched = [i for i in range(V) if i not in (1, 5)]
    np.testing.assert_array_equal(p_o[untouched], p[untouched])
    np.testing.assert_array_equal(m_o[untouched], m[untouched])
    np.testing.assert_array_equal(v_o[untouched], v[untouched])


def test_adam_lazy_mode_end_to_end():
    """Adam(lazy_mode=True) routes embedding tables through the sparse
    path (Rows wired from lookup_table Ids) and still learns; a param
    used outside lookup_table stays dense."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    V, D = 50, 8
    x = layers.data("x", shape=[4], dtype="int64")
    y = layers.data("y", shape=[1], dtype="int64")
    emb = layers.embedding(x, size=[V, D], param_attr=pt.ParamAttr(
        name="lazy_emb"))
    pred = layers.fc(layers.reduce_mean(emb, dim=1), size=5, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    opt = pt.optimizer.Adam(1e-1, lazy_mode=True)
    opt.minimize(loss)
    prog = pt.default_main_program()
    adam_ops = [op for op in prog.global_block().ops if op.type == "adam"]
    by_param = {op.inputs["Param"][0]: op for op in adam_ops}
    assert "Rows" in by_param["lazy_emb"].inputs
    assert by_param["lazy_emb"].attrs.get("lazy_mode") is True
    dense = [n for n in by_param if n != "lazy_emb"]
    assert dense and all("Rows" not in by_param[n].inputs for n in dense)

    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    rng = np.random.RandomState(0)
    xs = rng.randint(0, V, (16, 4))
    ys = (xs[:, 0] % 5)[:, None]
    emb0 = np.asarray(pt.global_scope().get("lazy_emb")).copy()
    vals = [float(exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])[0])
            for _ in range(30)]
    assert vals[-1] < vals[0] * 0.7
    emb1 = np.asarray(pt.global_scope().get("lazy_emb"))
    touched = np.unique(xs)
    untouched = np.setdiff1d(np.arange(V), touched)
    if len(untouched):
        np.testing.assert_array_equal(emb1[untouched], emb0[untouched])
    assert not np.allclose(emb1[touched], emb0[touched])
