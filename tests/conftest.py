"""Test environment: force an 8-virtual-device CPU platform BEFORE jax
imports, so mesh/sharding tests run without TPU hardware (the driver's
dryrun uses the same trick)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter startup with the TPU
# platform pinned, so the env vars above can come too late; force the
# platform through the live config (backends are not initialized yet).
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


REFERENCE_ROOT = "/root/reference"


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: full benchmark A/Bs (minutes); deselect with -m 'not slow'")
    config.addinivalue_line(
        "markers",
        "timeout(seconds): per-test budget (advisory when pytest-timeout "
        "is absent; chaos subprocess tests ALSO pass hard timeouts to "
        "every subprocess call)")
    config.addinivalue_line(
        "markers",
        "needs_reference: reads config/data files from the reference "
        "checkout at /root/reference; SKIPPED (not failed) when that "
        "mount is absent so pre-existing environment gaps cannot mask "
        "real regressions")
    config.addinivalue_line(
        "markers",
        "needs_multiprocess_collectives: real multi-process collectives "
        "round; SKIPPED on the CPU backend (jax CPU cannot run "
        "cross-process psum) so it runs — and fails loudly if broken — "
        "the first session with a chip/GPU")


def pytest_collection_modifyitems(config, items):
    """Convert known environment gaps into EXPLICIT skips with reasons.

    Before this hook the reference-unmounted v1/cli suites and the
    CPU-collectives round were permanent tier-1 FAILURES (27 at the
    PR 13 seed), which meant every session had to eyeball the failure
    list to tell 'pre-existing' from 'new regression'.  Skips keep the
    signal: a mounted /root/reference (or a chip backend) re-enables
    them automatically."""
    ref_missing = not os.path.isdir(REFERENCE_ROOT)
    # NOTE: this conftest pins JAX_PLATFORMS=cpu unconditionally (line
    # 6), so the env var says nothing about the MACHINE — probe for
    # accelerator device files instead, so a chip/GPU host still runs
    # the collectives round (and surfaces a regression) while
    # CPU-only containers skip it with a reason.
    has_accelerator = any(
        os.path.exists(p) for p in
        ("/dev/accel0", "/dev/accel1", "/dev/vfio/0",
         "/dev/nvidia0", "/dev/nvidiactl"))
    skip_ref = pytest.mark.skip(
        reason=f"{REFERENCE_ROOT} not mounted (reference-dependent "
               f"v1/cli suite)")
    skip_coll = pytest.mark.skip(
        reason="no accelerator on this host and the CPU backend has no "
               "multi-process collectives (runs on chip/GPU sessions)")
    for item in items:
        if ref_missing and item.get_closest_marker("needs_reference"):
            item.add_marker(skip_ref)
        if not has_accelerator and item.get_closest_marker(
                "needs_multiprocess_collectives"):
            item.add_marker(skip_coll)


@pytest.fixture(autouse=True)
def fresh_state():
    """Fresh default programs/scope/name-counters per test."""
    import paddle_tpu as pt
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    yield


@pytest.fixture(autouse=True)
def no_leaked_pipeline_threads():
    """Fail any test that leaks a live input-pipeline worker thread, and
    assert every live framework (``pt-*``) thread carries a prefix
    registered in the frozen ``THREAD_NAME_PREFIXES`` table — the
    runtime twin of the static PT055 rule.

    The reader/executor pipeline engine guarantees its workers die with
    their consumer (paddle_tpu/reader/pipeline.py); this enforces the
    guarantee for every test, with a short grace period for the workers'
    stop-event poll to fire after generator close/GC."""
    yield
    import gc
    import sys
    import threading
    import time

    from paddle_tpu.observability.metrics import THREAD_NAME_PREFIXES
    from paddle_tpu.reader.pipeline import THREAD_NAME_PREFIX

    # PT055's runtime twin: any live thread claiming the framework's
    # pt- namespace must carry a REGISTERED prefix (a new subsystem
    # must add its prefix to the frozen table, not invent one ad hoc)
    registered = tuple(p for p, _help in THREAD_NAME_PREFIXES)
    rogue = [t.name for t in threading.enumerate()
             if t.is_alive() and t.name.startswith("pt-")
             and not any(t.name == p or t.name.startswith(p + "-")
                         for p in registered)]
    assert not rogue, (
        f"live framework thread(s) with unregistered pt- name prefix "
        f"{rogue}; register the prefix in observability.metrics."
        f"THREAD_NAME_PREFIXES")

    # the sparse session's workers (prefetch join-on-close, async-push
    # bounded idle linger) carry their own prefix; only enforce it when
    # the test actually loaded the lazily-imported sparse package
    prefixes = [THREAD_NAME_PREFIX]
    sparse_mod = sys.modules.get("paddle_tpu.sparse.session")
    if sparse_mod is not None:
        prefixes.append(sparse_mod.THREAD_NAME_PREFIX)
    # the checkpoint commit writer has the same bounded-idle-linger
    # contract (distributed/checkpoint.py)
    ckpt_mod = sys.modules.get("paddle_tpu.distributed.checkpoint")
    if ckpt_mod is not None:
        prefixes.append(ckpt_mod.THREAD_NAME_PREFIX)

    def leaked():
        return [t for t in threading.enumerate()
                if t.is_alive()
                and any(t.name.startswith(p) for p in prefixes)]

    if leaked():
        gc.collect()           # close abandoned pipeline generators
        deadline = time.monotonic() + 2.0
        while leaked() and time.monotonic() < deadline:
            time.sleep(0.05)
    threads = leaked()
    assert not threads, (
        f"test leaked live input-pipeline worker threads: "
        f"{[t.name for t in threads]}")


# Threaded suites run with the lockwatch order watchdog ON: locks these
# tests create through the lockwatch factories record the process-wide
# acquisition-order graph, an inversion raises deterministically at the
# acquire site, and any violation swallowed by a broad except still
# fails the test here.  ENABLED is flipped directly (not via env):
# the factories consult it per call, so objects built inside the test
# get watched primitives while other suites keep plain ones.
_LOCKWATCH_SUITES = frozenset({
    "test_serving", "test_serving_chaos", "test_decode",
    "test_http_front", "test_fleet", "test_fleet_chaos",
    "test_input_pipeline", "test_master_service", "test_sparse_trainer",
    "test_checkpoint_delta", "test_checkpoint_sharded", "test_pserver",
    "test_elastic",
})


@pytest.fixture(autouse=True)
def lockwatch_for_threaded_suites(request):
    mod = getattr(request, "module", None)
    name = getattr(mod, "__name__", "").rsplit(".", 1)[-1]
    if name not in _LOCKWATCH_SUITES:
        yield
        return
    from paddle_tpu.testing import lockwatch as lw
    prior = lw.ENABLED
    lw.ENABLED = True
    lw.reset()
    try:
        yield
    finally:
        vs = lw.violations()
        lw.ENABLED = prior
        lw.reset()
    assert not vs, (
        "lockwatch recorded lock-order violation(s) during this test:\n"
        + "\n\n".join(v.report() for v in vs))


@pytest.fixture
def rng():
    return np.random.RandomState(42)
