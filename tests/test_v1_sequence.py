"""v1 sequence/generation DSL tests: REFERENCE config files evaluated
verbatim (recurrent_group/memory, mixed_layer+projections, lstmemory_group,
recurrent_layer+CRF, beam_search generation) and trained/decoded on the
TPU-native runtime.

Reference configs under test:
- paddle/gserver/tests/sequence_rnn.conf (recurrent_group + memory)
- paddle/gserver/tests/sequence_layer_group.conf (mixed_layer `+=` form +
  lstmemory_group)
- v1_api_demo/sequence_tagging/rnn_crf.py (mixed projections,
  recurrent_layer reverse, CRF train + decode, chunk evaluator)
- paddle/trainer/tests/sample_trainer_rnn_gen.conf (beam_search generation)
"""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.trainer_config_helpers import load_v1_config

REF = "/root/reference"
PADDLE = os.path.join(REF, "paddle")


def _train_steps(cfg, feeds, n=3):
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(n)]
    return vals


@pytest.mark.needs_reference
def test_reference_sequence_rnn_conf_trains(rng):
    """gserver/tests/sequence_rnn.conf verbatim: recurrent_group with a
    name-linked memory trains and the loss falls."""
    cfg = load_v1_config(os.path.join(PADDLE,
                                      "gserver/tests/sequence_rnn.conf"))
    assert cfg.settings["batch_size"] == 2
    B, T = 4, 6
    feeds = {"word": rng.randint(0, 10, (B, T)).astype("int64"),
             "word@LEN": np.array([6, 4, 5, 6]),
             "label": rng.randint(0, 3, (B, 1)).astype("int64")}
    vals = _train_steps(cfg, feeds, n=8)
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]


@pytest.mark.needs_reference
def test_reference_sequence_layer_group_conf_trains(rng):
    """gserver/tests/sequence_layer_group.conf verbatim: the `with
    mixed_layer(...) as x: x += full_matrix_projection(...)` form plus
    lstmemory_group (the conf reads its dict relative to paddle/)."""
    cwd = os.getcwd()
    os.chdir(PADDLE)
    try:
        cfg = load_v1_config(os.path.join(
            PADDLE, "gserver/tests/sequence_layer_group.conf"))
    finally:
        os.chdir(cwd)
    B, T = 3, 5
    feeds = {"word": rng.randint(0, 100, (B, T)).astype("int64"),
             "word@LEN": np.array([5, 3, 4]),
             "label": rng.randint(0, 3, (B, 1)).astype("int64")}
    vals = _train_steps(cfg, feeds, n=8)
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]


@pytest.mark.needs_reference
def test_reference_rnn_crf_config_trains_and_decodes(rng):
    """v1_api_demo/sequence_tagging/rnn_crf.py verbatim: mixed_layer with
    full_matrix/table projections, reversed recurrent_layer, CRF loglik
    cost, viterbi decode, chunk evaluator."""
    cfg = load_v1_config(os.path.join(
        REF, "v1_api_demo/sequence_tagging/rnn_crf.py"))
    assert cfg.input_order == ["word", "pos", "chunk", "features"]
    B, T = 2, 4
    ntags = 23  # rnn_crf.py num_label_types (no SIMD align in this config)
    feeds = {"word": rng.randint(0, 6778, (B, T)).astype("int64"),
             "word@LEN": np.array([4, 3]),
             "pos": rng.randint(0, 44, (B, T)).astype("int64"),
             "pos@LEN": np.array([4, 3]),
             "chunk": rng.randint(0, ntags, (B, T)).astype("int64"),
             "chunk@LEN": np.array([4, 3])}
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(8)]
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]
    # decode path: the crf_decoding layer is in the program; fetch it
    blk = cfg.main_program.global_block()
    decode_op = next(op for op in blk.ops if op.type == "crf_decoding")
    path = exe.run(cfg.main_program, feed=feeds,
                   fetch_list=[decode_op.outputs["ViterbiPath"][0]])[0]
    assert path.shape[:2] == (B, T)
    assert ((path >= 0) & (path < ntags)).all()
    # chunk evaluator was recorded and wired
    kinds = [e["kind"] for e in cfg.evaluators]
    assert "chunk" in kinds and "sum" in kinds


@pytest.mark.needs_reference
def test_reference_rnn_gen_conf_generates(rng):
    """trainer/tests/sample_trainer_rnn_gen.conf verbatim: beam_search DSL
    (StaticInput + GeneratedInput, trans_full_matrix_projection weight
    tying) decodes on the static-shape beam scan."""
    cfg = load_v1_config(
        os.path.join(PADDLE, "trainer/tests/sample_trainer_rnn_gen.conf"),
        beam_search=True)
    ids_var = cfg.outputs[0]
    assert not isinstance(ids_var, str), "Outputs() must resolve by name"
    B = 3
    feeds = {"sent_id": np.arange(B, dtype="int64").reshape(B, 1),
             "dummy_data_input": rng.rand(B, 2).astype("float32")}
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    ids = exe.run(cfg.main_program, feed=feeds, fetch_list=[ids_var],
                  is_test=True)[0]
    K = 2  # beam_flag=True -> beam_size 2
    assert ids.shape[0] == B and ids.shape[1] == K and ids.shape[2] == 10
    assert ((ids >= -1) & (ids < 5)).all()


def test_mixed_layer_projection_math(rng):
    """mixed_layer == sum of its projections (checked against numpy)."""
    from paddle_tpu.trainer_config_helpers import (
        mixed_layer, full_matrix_projection, identity_projection)
    import paddle_tpu.layers as L

    x = L.data("x", shape=[8], dtype="float32")
    with mixed_layer(size=8) as m:
        m += full_matrix_projection(input=x)
        m += identity_projection(x)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    xv = rng.rand(4, 8).astype("float32")
    out, = exe.run(pt.default_main_program(), feed={"x": xv},
                   fetch_list=[m])
    w = np.asarray(pt.global_scope().get("fc_0.w_0"))
    np.testing.assert_allclose(out, xv @ w + xv, rtol=1e-5, atol=1e-5)


def test_recurrent_group_matches_manual_scan(rng):
    """recurrent_group semantics: out_t = tanh([x_t, h_{t-1}] W + b)
    cross-checked against a numpy recurrence."""
    from paddle_tpu.trainer_config_helpers import (
        recurrent_group, memory, fc_layer, TanhActivation)
    import paddle_tpu.layers as L

    H = 4
    x = L.data("x", shape=[3], dtype="float32", lod_level=1)

    def step(y):
        mem = memory(name="h", size=H)
        return fc_layer(input=[y, mem], size=H, act=TanhActivation(),
                        bias_attr=True, name="h")

    out = recurrent_group(step=step, input=x, name="g")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    B, T = 2, 5
    xv = rng.rand(B, T, 3).astype("float32")
    ov, = exe.run(pt.default_main_program(),
                  feed={"x": xv, "x@LEN": np.array([T, T])},
                  fetch_list=[out])
    # v1 deterministic parameter names for a named layer (round 5:
    # _<layer>.w<i>/.wbias, the reference config_parser convention)
    w1 = np.asarray(pt.global_scope().get("_h.w0"))
    w2 = np.asarray(pt.global_scope().get("_h.w1"))
    b = np.asarray(pt.global_scope().get("_h.wbias"))
    h = np.zeros((B, H), "float32")
    for t in range(T):
        h = np.tanh(xv[:, t] @ w1 + h @ w2 + b)
        np.testing.assert_allclose(ov[:, t], h, rtol=2e-5, atol=2e-5)


def test_v1_lr_decay_schedule(rng):
    """settings(learning_rate_decay_a/b) applies the v1 poly schedule:
    lr_t = lr * (1 + a*batch*t)^-b (LearningRateScheduler.cpp:56)."""
    from paddle_tpu import lr_decay
    import paddle_tpu.layers as L

    lr_var = lr_decay.v1_poly_decay(0.1, decay_a=0.5, decay_b=0.75,
                                    batch_size=4)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    got = [float(exe.run(pt.default_main_program(), feed={},
                         fetch_list=[lr_var])[0]) for _ in range(4)]
    want = [0.1 * (1 + 0.5 * 4 * t) ** -0.75 for t in range(4)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.needs_reference
def test_reference_nested_rnn_conf_trains(rng):
    """gserver/tests/sequence_nest_rnn.conf verbatim: recurrent_group over
    SubsequenceInput with the inner group's memory booted from the outer
    memory (nested LoD; RecurrentGradientMachine's sub-network mode)."""
    cfg = load_v1_config(os.path.join(
        PADDLE, "gserver/tests/sequence_nest_rnn.conf"))
    B, S, T = 2, 3, 4
    feeds = {"word": rng.randint(0, 10, (B, S, T)).astype("int64"),
             "word@LEN": np.array([3, 2]),
             "word@LEN2": np.array([[4, 3, 2], [4, 4, 1]]),
             "label": rng.randint(0, 3, (B, 1)).astype("int64")}
    vals = _train_steps(cfg, feeds, n=8)
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]


@pytest.mark.needs_reference
def test_nested_rnn_equals_flat_rnn(rng):
    """The reference's RecurrentGradientMachine equivalence check
    (test_RecurrentGradientMachine.cpp): sequence_nest_rnn.conf on
    subsequence-split data == sequence_rnn.conf on the concatenated flat
    data, because the inner memory boots from the outer memory (the
    recurrence is continuous across subsequence boundaries)."""
    B, S, T = 2, 3, 4
    tokens = rng.randint(0, 10, (B, S * T)).astype("int64")

    flat = load_v1_config(os.path.join(PADDLE,
                                       "gserver/tests/sequence_rnn.conf"))
    flat_loss = flat.outputs[0]
    exe = pt.Executor()
    exe.run(flat.startup_program, feed={}, fetch_list=[])
    label = rng.randint(0, 3, (B, 1)).astype("int64")
    lf, = exe.run(flat.main_program,
                  feed={"word": tokens, "word@LEN": np.full(B, S * T),
                        "label": label},
                  fetch_list=[flat_loss], is_test=True)
    flat_params = [np.asarray(pt.global_scope().get(p.name))
                   for p in flat.main_program.global_block()
                   .all_parameters()]

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    nest = load_v1_config(os.path.join(
        PADDLE, "gserver/tests/sequence_nest_rnn.conf"))
    nest_loss = nest.outputs[0]
    exe2 = pt.Executor()
    exe2.run(nest.startup_program, feed={}, fetch_list=[])
    nest_ps = nest.main_program.global_block().all_parameters()
    assert len(nest_ps) == len(flat_params)
    for p, val in zip(nest_ps, flat_params):
        assert tuple(np.shape(val)) == tuple(p.shape), (p.name, p.shape)
        pt.global_scope().set(p.name, __import__("jax").numpy.asarray(val))
    ln, = exe2.run(nest.main_program,
                   feed={"word": tokens.reshape(B, S, T),
                         "word@LEN": np.full(B, S),
                         "word@LEN2": np.full((B, S), T),
                         "label": label},
                   fetch_list=[nest_loss], is_test=True)
    np.testing.assert_allclose(float(ln), float(lf), rtol=1e-5)


def test_simple_attention_matches_numpy(rng):
    """networks.py simple_attention cross-checked against a numpy
    re-derivation (Bahdanau score + masked softmax + weighted sum)."""
    from paddle_tpu.trainer_config_helpers import simple_attention
    import paddle_tpu.layers as L

    B, T, D, P = 2, 5, 6, 4
    enc = L.data("enc", shape=[D], dtype="float32", lod_level=1)
    proj = L.data("proj", shape=[P], dtype="float32", lod_level=1)
    state = L.data("state", shape=[P], dtype="float32")
    ctxv = simple_attention(enc, proj, state, name="att")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    lens = np.array([5, 3])
    feeds = {"enc": rng.randn(B, T, D).astype("float32"),
             "enc@LEN": lens,
             "proj": rng.randn(B, T, P).astype("float32"),
             "proj@LEN": lens,
             "state": rng.randn(B, P).astype("float32")}
    got, = exe.run(pt.default_main_program(), feed=feeds, fetch_list=[ctxv])
    wt = np.asarray(pt.global_scope().get("fc_0.w_0"))     # [P, P]
    ws = np.asarray(pt.global_scope().get("fc_1.w_0"))     # [P, 1]
    m = feeds["state"] @ wt                                # [B, P]
    comb = feeds["proj"] + m[:, None, :]
    score = (comb @ ws)[..., 0]                            # [B, T]
    score[0, lens[0]:] = -np.inf
    score[1, lens[1]:] = -np.inf
    w = np.exp(score - score.max(1, keepdims=True))
    w /= w.sum(1, keepdims=True)
    want = (feeds["enc"] * w[..., None]).sum(1)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


S2S_ATTENTION_CONF = '''
from paddle.trainer_config_helpers import *
settings(batch_size=4, learning_rate=5e-3, learning_method=AdamOptimizer())
src_dict, tgt_dict, word_dim, hidden = 20, 20, 8, 8

src = data_layer(name='source', size=src_dict)
src_emb = embedding_layer(input=src, size=word_dim)
enc = bidirectional_lstm(input=src_emb, size=hidden, return_seq=True)
with mixed_layer(size=hidden) as enc_proj:
    enc_proj += full_matrix_projection(enc)

tgt = data_layer(name='target', size=tgt_dict)
tgt_emb = embedding_layer(input=tgt, size=word_dim)

def gru_decoder_with_attention(enc_vec, enc_pr, cur_word):
    dec_mem = memory(name='gru_decoder', size=hidden)
    context = simple_attention(encoded_sequence=enc_vec,
                               encoded_proj=enc_pr,
                               decoder_state=dec_mem)
    with mixed_layer(size=hidden * 3) as dec_inputs:
        dec_inputs += full_matrix_projection(context)
        dec_inputs += full_matrix_projection(cur_word)
    return gru_step_layer(input=dec_inputs, output_mem=dec_mem,
                          size=hidden, name='gru_decoder')

dec = recurrent_group(name='decoder',
                      step=gru_decoder_with_attention,
                      input=[StaticInput(enc), StaticInput(enc_proj),
                             tgt_emb])
prob = fc_layer(input=dec, size=tgt_dict, act=SoftmaxActivation(),
                bias_attr=True)
lbl = data_layer(name='label', size=tgt_dict)
outputs(classification_cost(input=prob, label=lbl))
'''


def test_seq2seq_attention_decoder_config(tmp_path, rng):
    """The canonical v1 seqToseq architecture (demo/seqToseq/seqToseq_net.py
    gru_decoder_with_attention): bidirectional encoder, simple_attention
    over StaticInput encoder states INSIDE the decoder recurrent_group,
    gru_step_layer cell — written as a v1 config, evaluated by the DSL,
    trained end to end."""
    path = tmp_path / "s2s_attn.py"
    path.write_text(S2S_ATTENTION_CONF)
    cfg = load_v1_config(str(path))
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    B, TS, TT = 4, 6, 5
    feeds = {"source": rng.randint(0, 20, (B, TS)).astype("int64"),
             "source@LEN": np.array([6, 5, 4, 6]),
             "target": rng.randint(0, 20, (B, TT)).astype("int64"),
             "target@LEN": np.array([5, 5, 3, 4]),
             "label": rng.randint(0, 20, (B, TT)).astype("int64"),
             "label@LEN": np.array([5, 5, 3, 4])}
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(12)]
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0] * 0.95


@pytest.mark.needs_reference
def test_data_feeder_nested_sequences(rng):
    """DataFeeder pads nested rows (list of subsequences) to [B,S,T] with
    @LEN/@LEN2 companions, and the nested reference config trains from
    feeder-produced feeds (the process_subseq provider path)."""
    import paddle_tpu.layers as L

    cfg = load_v1_config(os.path.join(
        PADDLE, "gserver/tests/sequence_nest_rnn.conf"))
    word = cfg.data_layers["word"]
    label = cfg.data_layers["label"]
    feeder = pt.DataFeeder([word, label], seq_bucket_multiple=1)
    rows = [([[1, 2, 3], [4, 5]], 0),
            ([[6], [7, 8], [9, 1, 2]], 2)]
    feeds = feeder.feed(rows)
    assert feeds["word"].shape == (2, 3, 3)
    np.testing.assert_array_equal(feeds["word@LEN"], [2, 3])
    np.testing.assert_array_equal(feeds["word@LEN2"],
                                  [[3, 2, 0], [1, 2, 3]])
    assert feeds["word"][0, 1, 1] == 5 and feeds["word"][1, 2, 2] == 2

    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(6)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


def test_thin_v1_layer_wrappers(rng):
    """Smoke + numeric checks for the thin v1 wrappers (power,
    slope_intercept, sum_to_one_norm, cos_sim, trans, repeat)."""
    from paddle_tpu import trainer_config_helpers as dsl
    import paddle_tpu.layers as L

    a = L.data("a", shape=[4], dtype="float32")
    b = L.data("b", shape=[4], dtype="float32")
    si = dsl.slope_intercept_layer(a, slope=2.0, intercept=1.0)
    norm = dsl.sum_to_one_norm_layer(a)
    cs = dsl.cos_sim(a, b, scale=3)
    tr = dsl.trans_layer(a)
    rep = dsl.repeat_layer(a, 3)
    exe = pt.Executor()
    av = rng.rand(2, 4).astype("float32") + 0.1
    bv = rng.rand(2, 4).astype("float32") + 0.1
    si_v, n_v, c_v, t_v, r_v = exe.run(
        pt.default_main_program(), feed={"a": av, "b": bv},
        fetch_list=[si, norm, cs, tr, rep])
    np.testing.assert_allclose(si_v, 2 * av + 1, rtol=1e-6)
    np.testing.assert_allclose(n_v, av / av.sum(1, keepdims=True),
                               rtol=1e-5)
    want_cs = 3 * (av * bv).sum(1) / (np.linalg.norm(av, axis=1) *
                                      np.linalg.norm(bv, axis=1))
    np.testing.assert_allclose(np.ravel(c_v), want_cs, rtol=1e-5)
    assert t_v.shape == (4, 2)
    assert r_v.shape == (2, 12)


@pytest.mark.parametrize("conf", ["sequence_lstm.conf",
                                  "sequence_recurrent.py",
                                  "sequence_recurrent_group.py",
                                  "sequence_rnn_multi_input.conf"])
@pytest.mark.needs_reference
def test_more_gserver_sequence_configs_train(conf, rng):
    """Additional gserver sequence configs VERBATIM: lstmemory forms, the
    recurrent layer vs group equivalence pair, and a multi-input
    recurrent_group whose step embeds the raw ids (step vars keep their
    vocab metadata)."""
    cwd = os.getcwd()
    os.chdir(PADDLE)   # configs read dict files relative to paddle/
    try:
        cfg = load_v1_config(os.path.join(PADDLE, "gserver/tests", conf))
    finally:
        os.chdir(cwd)
    B, T = 3, 5
    feeds = {}
    for nm, v in cfg.data_layers.items():
        if v.dtype == np.dtype("int64"):
            if v.lod_level:
                vocab = getattr(v, "v1_size", 10) or 10
                feeds[nm] = rng.randint(0, min(vocab, 100),
                                        (B, T)).astype("int64")
                feeds[nm + "@LEN"] = np.full(B, T)
            else:
                feeds[nm] = rng.randint(0, 3, (B, 1)).astype("int64")
        else:
            dims = [int(d) for d in (v.shape or (1,))[1:] if d and d > 0]
            feeds[nm] = rng.rand(B, *dims).astype("float32")
    vals = _train_steps(cfg, feeds, n=6)
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]
