"""Serving chaos suite: REAL server processes killed under load.

Subprocess rounds (fresh jax import apiece, ~15 s on this CPU container)
run under ``@pytest.mark.slow`` like the training chaos suite; the fast
deterministic degradation matrix lives in tests/test_serving.py.

The acceptance round (ISSUE 8): SIGTERM under live load → admission
stops (late requests get typed ``ServerClosed`` rejections), in-flight
batches complete, readiness flips to ``draining``, the process exits 0
with ZERO admitted requests dropped — and a supervised relaunch of the
identical command returns to ``ready`` and serves again.

Every subprocess call carries a hard ``timeout=``.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def artifact_dir(tmp_path_factory):
    """One tiny exported MLP artifact shared by every round."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()
    x = layers.data("x", shape=[8], dtype="float32")
    h = layers.fc(x, size=32, act="relu")
    pred = layers.fc(h, size=4, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    d = str(tmp_path_factory.mktemp("serve_artifact") / "mlp")
    pt.export_compiled_model(d, {"x": ((-1, 8), "float32")}, [pred])
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    return d


def _spawn_server(artifact_dir, *extra):
    cmd = [sys.executable, "-m", "paddle_tpu", "serve",
           "--model", f"m={artifact_dir}",
           "--max-batch", "4", "--max-wait-ms", "5",
           "--deadline-ms", "2000", "--queue", "64", *extra]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    return subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE, text=True, env=env,
                            cwd=REPO)


def _wait_ready(proc, timeout_s=180):
    """Read events until the ready state line; returns all seen events."""
    deadline = time.monotonic() + timeout_s
    events = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"server exited before ready (rc={proc.poll()})")
        ev = json.loads(line)
        events.append(ev)
        if ev.get("event") == "state" and ev.get("state") == "ready":
            return events
    raise AssertionError("server never became ready")


def _request_line(i, rng):
    return json.dumps({"id": i,
                       "feeds": {"x": rng.rand(8).tolist()}}) + "\n"


def test_import_paddle_tpu_does_not_import_serving():
    """Runtime half of the zero-cost guard (the static half is the
    repo-lint lazy-import gate, tier-1): a fresh ``import paddle_tpu``
    pulls nothing from paddle_tpu.serving."""
    code = (
        "import sys\n"
        "import paddle_tpu\n"
        "bad = [m for m in sys.modules if m.startswith("
        "'paddle_tpu.serving')]\n"
        "assert not bad, f'import paddle_tpu pulled {bad}'\n"
        "print('SERVING-NOT-IMPORTED')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "SERVING-NOT-IMPORTED" in r.stdout


def test_sigterm_under_load_drains_admitted_requests(artifact_dir):
    """THE kill-under-load acceptance round."""
    proc = _spawn_server(artifact_dir)
    try:
        _wait_ready(proc)
        rng = np.random.RandomState(0)
        # stream requests; SIGTERM strikes mid-stream
        total, kill_after = 60, 25
        for i in range(kill_after):
            proc.stdin.write(_request_line(i, rng))
            if i % 5 == 4:
                proc.stdin.flush()
                time.sleep(0.005)
        proc.stdin.flush()
        proc.send_signal(signal.SIGTERM)
        # keep writing AFTER the kill: these must get typed rejections
        # (or responses, if they raced admission-close), never silence
        late_ids = []
        try:
            for i in range(kill_after, total):
                proc.stdin.write(_request_line(i, rng))
                late_ids.append(i)
            proc.stdin.flush()
            proc.stdin.close()
        except (BrokenPipeError, OSError, ValueError):
            late_ids = late_ids[:0]     # pipe already torn down: fine
        out = proc.stdout.read()        # until EOF at process exit
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert proc.returncode == 0, f"drain must exit 0, got {proc.returncode}"
    responses, states = {}, []
    stopped_summary = None
    for line in out.splitlines():
        ev = json.loads(line)
        if ev.get("event") == "state":
            states.append(ev["state"])
        elif ev.get("event") == "stopped":
            stopped_summary = ev
        elif "id" in ev and ev.get("id") is not None:
            assert ev["id"] not in responses, f"duplicate response {ev}"
            responses[ev["id"]] = ev
    # readiness flipped: draining seen, then stopped, in order
    assert "draining" in states and "stopped" in states
    assert states.index("draining") < states.index("stopped")
    # ZERO silent drops: every pre-kill request has exactly one terminal
    # response, and every admitted one has OUTPUTS (drained, not aborted)
    for i in range(kill_after):
        assert i in responses, f"request {i} got no response (dropped)"
        ev = responses[i]
        assert "outputs" in ev or ev.get("error") in (
            "ServerClosed", "Overloaded", "DeadlineExceeded"), ev
    admitted_served = sum(1 for i in range(kill_after)
                          if "outputs" in responses[i])
    assert admitted_served > 0
    # post-SIGTERM writes that the server read got TYPED rejections
    for i in late_ids:
        if i in responses:
            assert responses[i].get("error") == "ServerClosed" \
                or "outputs" in responses[i], responses[i]
    assert stopped_summary is not None
    assert stopped_summary["models"]["m"]["queue_depth"] == 0


def test_supervised_relaunch_returns_to_ready_and_serves(artifact_dir):
    """Round 2 of the acceptance: after a drain, relaunching the SAME
    command (the Supervisor.run_command contract — exit 0 is 'done', so
    the relaunch is the supervisor restarting the serving job, exactly
    what a k8s-style controller does) returns to ready and serves."""
    rng = np.random.RandomState(7)
    # leg 1: serve one request, SIGTERM, clean exit
    proc = _spawn_server(artifact_dir)
    try:
        _wait_ready(proc)
        proc.stdin.write(_request_line(0, rng))
        proc.stdin.flush()
        while True:
            ev = json.loads(proc.stdout.readline())
            if ev.get("id") == 0:
                assert "outputs" in ev
                break
        proc.send_signal(signal.SIGTERM)
        proc.stdin.close()
        out = proc.stdout.read()
        proc.wait(timeout=120)
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # leg 2: identical command relaunched -> ready again, serves again
    proc = _spawn_server(artifact_dir)
    try:
        events = _wait_ready(proc)
        assert any(ev.get("state") == "ready" for ev in events)
        proc.stdin.write(_request_line(1, rng))
        proc.stdin.flush()
        while True:
            ev = json.loads(proc.stdout.readline())
            if ev.get("id") == 1:
                assert "outputs" in ev and len(ev["outputs"][0]) == 4
                break
        proc.stdin.close()
        out = proc.stdout.read()
        proc.wait(timeout=120)
        assert proc.returncode == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def test_sigterm_during_startup_still_drains_to_exit_0(artifact_dir):
    """Handlers are installed before model load/warmup: a supervisor's
    SIGTERM that lands in the startup window must still end in the
    drain path and exit 0, not a default-disposition kill (143)."""
    proc = _spawn_server(artifact_dir)
    try:
        # first line = the 'loading' event: handlers are already in
        # place by then; strike during load/warmup
        ev = json.loads(proc.stdout.readline())
        assert ev.get("event") == "loading"
        proc.send_signal(signal.SIGTERM)
        proc.stdin.close()
        out = proc.stdout.read()
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert proc.returncode == 0, proc.returncode
    states = [json.loads(line)["state"] for line in out.splitlines()
              if json.loads(line).get("event") == "state"]
    assert states[-2:] == ["draining", "stopped"] or \
        states[-1] == "stopped", states


def test_injected_dispatch_fault_opens_breaker_in_subprocess(artifact_dir):
    """PADDLE_TPU_FAULT_SPEC drives the serving.dispatch site end to end
    in the process form: every dispatch fails fatally, the breaker opens
    after the threshold, late requests get fast ModelUnavailable."""
    cmd = [sys.executable, "-m", "paddle_tpu", "serve",
           "--model", f"m={artifact_dir}",
           "--max-batch", "1", "--max-wait-ms", "1",
           "--deadline-ms", "0", "--queue", "16",
           "--breaker-threshold", "2", "--breaker-cooldown-s", "3600"]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_FAULT_SPEC="serving.dispatch@*=fatal",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE, text=True, env=env,
                            cwd=REPO)
    try:
        _wait_ready(proc)
        rng = np.random.RandomState(0)
        errors = {}
        for i in range(6):
            proc.stdin.write(_request_line(i, rng))
            proc.stdin.flush()
            while True:
                ev = json.loads(proc.stdout.readline())
                if ev.get("id") == i:
                    errors[i] = ev.get("error")
                    break
        proc.stdin.close()
        out = proc.stdout.read()
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert proc.returncode == 0
    # first failures are ModelError (dispatched, injected-fatal); once
    # the breaker opens the rest fail fast at admission
    assert errors[0] == "ModelError"
    assert "ModelUnavailable" in errors.values(), errors
