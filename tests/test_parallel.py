"""Multi-chip sharding tests on the 8-virtual-device CPU mesh (the pattern
the driver's dryrun_multichip validates).  Replaces the reference's NCCL and
pserver integration tests (nccl_op_test.cu.cc, test_ParameterServer2.cpp)
with in-process mesh runs — no cluster needed, same as the reference tested
send/recv over localhost (SURVEY §4)."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import layers, parallel
from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh, mesh_guard


def _mlp_program(rng, tp_shard=False):
    img = layers.data("img", shape=[16], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    hidden = layers.fc(img, size=32, act="relu",
                       param_attr=pt.ParamAttr(name="w_col",
                                               sharding=(None, "tp"))
                       if tp_shard else None)
    pred = layers.fc(hidden, size=10, act="softmax",
                     param_attr=pt.ParamAttr(name="w_row",
                                             sharding=("tp", None))
                     if tp_shard else None)
    loss = layers.mean(layers.cross_entropy(pred, label))
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    feeds = {"img": rng.rand(16, 16).astype("float32"),
             "label": rng.randint(0, 10, (16, 1))}
    return loss, feeds


def test_dp_training_matches_single_device(rng):
    """Same seeds, same data: an 8-way dp run must track the 1-device run
    (the reference's test_CompareTwoNets/test_CompareSparse strategy)."""
    loss, feeds = _mlp_program(rng)
    prog = pt.default_main_program()

    exe1 = pt.Executor()
    exe1.run(pt.default_startup_program(), feed={}, fetch_list=[])
    single = [float(exe1.run(prog, feed=feeds, fetch_list=[loss])[0])
              for _ in range(3)]

    pt.core.reset_global_scope()
    mesh = make_mesh(MeshConfig(dp=8))
    exe8 = ShardedExecutor(mesh=mesh)
    exe8.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe8._step = 0
    multi = [float(exe8.run(prog, feed=feeds, fetch_list=[loss])[0])
             for _ in range(3)]
    np.testing.assert_allclose(single, multi, rtol=2e-4)


def test_tp_sharded_params_train(rng):
    loss, feeds = _mlp_program(rng, tp_shard=True)
    prog = pt.default_main_program()
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    exe = ShardedExecutor(mesh=mesh)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.place_state(prog)
    vals = [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
            for _ in range(3)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]
    # the column-parallel weight really is sharded over tp
    w = pt.global_scope().get("w_col")
    assert not w.sharding.is_fully_replicated


def test_ring_attention_matches_full_attention(rng):
    from jax.experimental.shard_map import shard_map
    mesh = make_mesh(MeshConfig(sp=8))
    B, T, H, D = 2, 32, 4, 8
    q = rng.randn(B, T, H, D).astype("float32")
    k = rng.randn(B, T, H, D).astype("float32")
    v = rng.randn(B, T, H, D).astype("float32")

    def ref_attn(q, k, v):
        s = np.einsum("bthd,bshd->bhts", q * (D ** -0.5), k)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhts,bshd->bthd", p, v)

    ring = shard_map(
        lambda q, k, v: parallel.ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(out, ref_attn(q, k, v), atol=2e-5)


def test_ring_attention_causal(rng):
    from jax.experimental.shard_map import shard_map
    mesh = make_mesh(MeshConfig(sp=8))
    B, T, H, D = 1, 16, 2, 4
    q = rng.randn(B, T, H, D).astype("float32")
    k = rng.randn(B, T, H, D).astype("float32")
    v = rng.randn(B, T, H, D).astype("float32")

    def ref_attn(q, k, v):
        s = np.einsum("bthd,bshd->bhts", q * (D ** -0.5), k)
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask, s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhts,bshd->bthd", p, v)

    ring = shard_map(
        lambda q, k, v: parallel.ring_attention(q, k, v, axis_name="sp",
                                                causal=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    out = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(out, ref_attn(q, k, v), atol=2e-5)


def test_collectives_outside_spmd_are_noops():
    x = np.ones((4,), "float32")
    assert np.allclose(parallel.psum(x), x)
    assert np.allclose(parallel.all_gather(x), x)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_path(rng, causal):
    # ~17s per arm on this container (PR 13 budget audit): the ring
    # attention parity itself stays tier-1 via the non-flash path test;
    # the flash-kernel composition arms ride -m slow beside the other
    # kernel matrices.
    """Flash-kernel ring attention (per-hop fused (out,lse) + streaming
    merge) == dense attention, forward and gradient (sp=4, kernels in
    interpret mode on CPU)."""
    from jax.experimental.shard_map import shard_map
    import jax.numpy as jnp

    mesh = make_mesh(MeshConfig(sp=4), devices=jax.devices()[:4])
    B, T, H, D = 1, 32, 2, 8
    q = rng.randn(B, T, H, D).astype("float32")
    k = rng.randn(B, T, H, D).astype("float32")
    v = rng.randn(B, T, H, D).astype("float32")

    def dense(q, k, v):
        s = jnp.einsum("bthd,bshd->bhts", q * (D ** -0.5), k)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhts,bshd->bthd", p, v)

    ring = shard_map(
        lambda q, k, v: parallel.ring_attention(
            q, k, v, axis_name="sp", causal=causal, use_flash=True,
            block_q=8, block_k=8, interpret=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_rep=False)
    out = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(out, np.asarray(dense(q, k, v)), atol=2e-5,
                               rtol=2e-5)

    # gradients flow through the per-hop kernels and the lse merges
    w = jnp.asarray(rng.randn(B, T, H, D).astype("float32"))
    gf = jax.grad(lambda a, b, c: jnp.sum(w * ring(a, b, c)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(w * dense(a, b, c)),
                  argnums=(0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v))
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_ring_attention_flash_bf16(rng):
    """The auto-selected TPU path must survive bf16 inputs (the merge runs
    f32 internally, output returns in the input dtype)."""
    from jax.experimental.shard_map import shard_map
    import jax.numpy as jnp

    mesh = make_mesh(MeshConfig(sp=2), devices=jax.devices()[:2])
    B, T, H, D = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.bfloat16)
    ring = shard_map(
        lambda q, k, v: parallel.ring_attention(
            q, k, v, axis_name="sp", use_flash=True, block_q=8,
            block_k=8, interpret=True),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_rep=False)
    out = jax.jit(ring)(q, q, q)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


def test_sharded_run_steps_matches_per_step(rng):
    """ShardedExecutor.run_steps: K steps in one sharded scan dispatch
    track K run() calls on the same dp x tp mesh; stacked feeds shard the
    per-step batch dim (leading steps axis scanned, not distributed)."""
    loss, feeds = _mlp_program(rng, tp_shard=True)
    prog = pt.default_main_program()
    mesh = make_mesh(MeshConfig(dp=2, tp=4))

    exe = ShardedExecutor(mesh=mesh)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.place_state(prog)
    seq = [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
           for _ in range(4)]
    w_seq = np.asarray(pt.global_scope().get("w_col")).copy()

    pt.core.reset_global_scope()
    exe2 = ShardedExecutor(mesh=mesh)
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe2.place_state(prog)
    exe2._step = exe._step - 4
    (stacked,) = exe2.run_steps(4, prog, feed=feeds, fetch_list=[loss])
    np.testing.assert_allclose(stacked.reshape(-1), seq, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(pt.global_scope().get("w_col")),
                               w_seq, rtol=2e-2, atol=1e-5)
    # the tp-annotated parameter is actually sharded after the scan
    w = pt.global_scope().get("w_col")
    assert not w.is_fully_replicated

    # stacked feeds: per-step batches
    k_feeds = {"img": np.stack([feeds["img"]] * 3),
               "label": np.stack([feeds["label"]] * 3)}
    (st2,) = exe2.run_steps(3, prog, feed=k_feeds, fetch_list=[loss],
                            feeds_stacked=True)
    assert st2.shape[0] == 3 and np.isfinite(st2).all()
