"""paddle_tpu.utils tests (reference: python/paddle/utils/ —
dump_config, make_model_diagram, merge_model, plotcurve)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, utils

REF_CFG = "/root/reference/v1_api_demo/quick_start/trainer_config.lr.py"


def _build(rng):
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1, name="mw")
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    xb = rng.rand(8, 4).astype("float32")
    exe.run(feed={"x": xb, "y": xb.sum(1, keepdims=True)},
            fetch_list=[loss])
    return loss, exe


@pytest.mark.skipif(not os.path.exists(REF_CFG),
                    reason="reference not mounted")
def test_dump_config_and_diagram(tmp_path, monkeypatch):
    # the config reads ./data/dict.txt at evaluation time
    (tmp_path / "data").mkdir()
    with open(tmp_path / "data" / "dict.txt", "w") as f:
        for i in range(30):
            f.write(f"word{i}\t{i}\n")
    monkeypatch.chdir(tmp_path)
    args = {"dict_file": str(tmp_path / "data" / "dict.txt")}
    s = utils.dump_config(REF_CFG, config_args=args)
    d = json.loads(s)
    assert d["blocks"][0]["ops"], "dump contains ops"
    dot = utils.make_model_diagram(REF_CFG, config_args=args,
                                   dot_path=str(tmp_path / "m.dot"))
    assert "digraph" in dot and (tmp_path / "m.dot").exists()


def test_merge_and_load_model_roundtrip(tmp_path, rng):
    loss, exe = _build(rng)
    w_before = np.asarray(pt.global_scope().get("mw.w_0")).copy()
    out = utils.merge_model(str(tmp_path / "model.tar.gz"))
    assert os.path.exists(out)

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    prog = utils.load_merged_model(out)
    w_after = np.asarray(pt.global_scope().get("mw.w_0"))
    np.testing.assert_array_equal(w_before, w_after)
    # the restored program is runnable: same loss vs rebuilt feeds
    loss_vars = [v for b in prog.blocks
                 for v in b.vars.values() if "mean" in v.name]
    assert loss_vars and prog.global_block().ops


def test_plotcurve_parses_log(tmp_path):
    log = ["Pass 0, Batch 10, Cost=2.5",
           "noise line",
           "Pass=1 avg cost=1.25",
           "Pass 2, Cost 0.7 acc=0.9"]
    ids, costs = utils.plotcurve(log)
    assert ids.tolist() == [0, 1, 2]
    assert costs.tolist() == [2.5, 1.25, 0.7]
    p = tmp_path / "train.log"
    p.write_text("\n".join(log) + "\n")
    ids2, costs2 = utils.plotcurve(str(p))
    assert ids2.tolist() == ids.tolist()
    # key selects the metric; no plot file unless output_path given
    ids3, accs = utils.plotcurve(["Pass 0 Cost=2.0 acc=0.5"], key="acc")
    assert accs.tolist() == [0.5]
    assert not (tmp_path / "plot.png").exists()
    out = tmp_path / "curve.png"
    try:
        utils.plotcurve(log, output_path=str(out))
        assert out.exists()
    except ImportError:
        pass


def test_image_dataset_creater_end_to_end(tmp_path, rng):
    """v1 preprocess_img role: a train/test label-directory tree becomes
    batch part files + meta (mean image, labels); the parts feed
    reader.creator.recordio into a training-ready pipeline."""
    from PIL import Image

    import paddle_tpu as pt
    from paddle_tpu import reader
    from paddle_tpu.image import ImageClassificationDatasetCreater

    for split, n in (("train", 6), ("test", 2)):
        for label in ("cat", "dog"):
            d = tmp_path / split / label
            d.mkdir(parents=True)
            for i in range(n):
                arr = (rng.rand(20, 24, 3) * 255).astype("uint8")
                Image.fromarray(arr).save(d / f"im{i}.jpg")

    c = ImageClassificationDatasetCreater(str(tmp_path), target_size=16,
                                          num_per_batch=5)
    out = c.create_batches()
    import pickle
    meta = pickle.load(open(os.path.join(out, "batches.meta"), "rb"))
    assert meta["num_labels"] == 2 and meta["image_size"] == 16
    assert meta["mean_image"].shape == (3 * 16 * 16,)
    labels = pickle.load(open(os.path.join(out, "labels.pkl"), "rb"))
    assert set(labels.values()) == {"cat", "dog"}

    rows = list(reader.creator.recordio(
        os.path.join(out, "train_batches", "batch-*.pickle"))())
    assert len(rows) == 12
    im, lid = rows[0]
    assert im.shape == (3 * 16 * 16,) and lid in (0, 1)
    test_rows = list(reader.creator.recordio(
        os.path.join(out, "test_batches", "batch-*.pickle"))())
    assert len(test_rows) == 4
    # idempotent without overwrite
    assert c.create_batches() == out
