"""DataFeeder padding: the vectorized fast path must be byte-identical to
the original per-row reference implementation on randomized inputs, across
lod 0/1/2, the [B] -> [B,1] label reshape, and seq_bucket_multiple
rounding; plus staging-buffer reuse semantics."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.data_feeder import DataFeeder, _round_up


def _var(name, dtype, lod_level=0, shape=None):
    return layers.data(name, shape=shape if shape is not None else [1],
                       dtype=dtype, lod_level=lod_level)


def _assert_same(a, b):
    assert a.dtype == b.dtype, (a.dtype, b.dtype)
    assert a.shape == b.shape, (a.shape, b.shape)
    assert (np.asarray(a) == np.asarray(b)).all()


# ---------------------------------------------------------------------------
# lod 1
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", ["int64", "float32", "int32", "float64"])
@pytest.mark.parametrize("mult", [1, 4, 8])
def test_pad_rows_vectorized_matches_reference_randomized(rng, dtype, mult):
    v = _var("w", dtype, lod_level=1)
    fd = DataFeeder([v], seq_bucket_multiple=mult)
    for trial in range(30):
        B = rng.randint(1, 10)
        col = [list(rng.randint(0, 100, rng.randint(1, 13)).astype(dtype))
               for _ in range(B)]
        a_vec, l_vec = fd._pad_rows_vectorized(col, v)
        a_ref, l_ref = fd._pad_rows_reference(col, v)
        _assert_same(a_vec, a_ref)
        _assert_same(l_vec, l_ref)
        assert a_vec.shape[1] % mult == 0   # bucket rounding


def test_pad_rows_vector_features_match_reference(rng):
    v = _var("f", "float32", lod_level=1)
    fd = DataFeeder([v], seq_bucket_multiple=8)
    for _ in range(20):
        B = rng.randint(1, 8)
        col = [[list(rng.rand(4)) for _ in range(rng.randint(1, 7))]
               for _ in range(B)]
        a_vec, l_vec = fd._pad_rows_vectorized(col, v)
        a_ref, l_ref = fd._pad_rows_reference(col, v)
        _assert_same(a_vec, a_ref)
        _assert_same(l_vec, l_ref)


def test_pad_rows_zero_length_row():
    v = _var("w", "int64", lod_level=1)
    fd = DataFeeder([v], seq_bucket_multiple=4)
    col = [[1, 2, 3], [], [7]]
    a_vec, l_vec = fd._pad_rows_vectorized(col, v)
    a_ref, l_ref = fd._pad_rows_reference(col, v)
    _assert_same(a_vec, a_ref)
    assert list(l_vec) == [3, 0, 1]


def test_native_path_agrees_with_vectorized(rng):
    from paddle_tpu.native import get_native
    if get_native() is None:
        pytest.skip("native toolchain unavailable")
    v = _var("w", "int64", lod_level=1)
    fd = DataFeeder([v], seq_bucket_multiple=8)
    col = [list(rng.randint(0, 1000, rng.randint(1, 40)))
           for _ in range(16)]
    a_nat, l_nat = fd._pad_rows(col, v)          # native first
    a_vec, l_vec = fd._pad_rows_vectorized(col, v)
    _assert_same(np.asarray(a_nat), a_vec)
    _assert_same(np.asarray(l_nat, np.int32), l_vec)


# ---------------------------------------------------------------------------
# lod 2
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mult", [1, 8])
def test_pad_nested_matches_reference_randomized(rng, mult):
    v = _var("n", "int64", lod_level=2)
    fd = DataFeeder([v], seq_bucket_multiple=mult)
    for _ in range(30):
        B = rng.randint(1, 7)
        col = [[list(rng.randint(0, 50, rng.randint(0, 6)))
                for _ in range(rng.randint(1, 5))] for _ in range(B)]
        a, l1, l2 = fd._pad_nested(col, v)
        ra, rl1, rl2 = fd._pad_nested_reference(col, v)
        _assert_same(a, ra)
        _assert_same(l1, rl1)
        _assert_same(l2, rl2)


def test_pad_nested_empty_row_matches_reference():
    # a row with NO subsequences counts as length 1 (reference rule) —
    # the vectorized path must not collapse T to 0
    v = _var("n", "int64", lod_level=2)
    fd = DataFeeder([v], seq_bucket_multiple=8)
    col = [[], [[]]]
    a, l1, l2 = fd._pad_nested(col, v)
    ra, rl1, rl2 = fd._pad_nested_reference(col, v)
    _assert_same(a, ra)
    _assert_same(l1, rl1)
    _assert_same(l2, rl2)
    assert a.shape == (2, 1, 8)


def test_feed_lod2_emits_len_companions(rng):
    v = _var("n", "int64", lod_level=2)
    fd = DataFeeder([v], seq_bucket_multiple=4)
    col = [[[1, 2], [3]], [[4, 5, 6]]]
    out = fd.feed([(row,) for row in col])
    assert set(out) == {"n", "n@LEN", "n@LEN2"}
    assert out["n"].shape == (2, 2, 4)
    assert list(out["n@LEN"]) == [2, 1]
    assert out["n@LEN2"].tolist() == [[2, 1], [3, 0]]


# ---------------------------------------------------------------------------
# lod 0 + label reshape
# ---------------------------------------------------------------------------
def test_feed_label_reshape_and_dtype(rng):
    x = _var("x", "float32", shape=[5])
    y = _var("y", "int64", shape=[1])
    fd = DataFeeder([x, y])
    rows = [(rng.rand(5).astype("float32"), int(i % 3)) for i in range(6)]
    out = fd.feed(rows)
    assert out["x"].shape == (6, 5) and out["x"].dtype == np.float32
    assert out["y"].shape == (6, 1) and out["y"].dtype == np.int64
    assert list(out["y"][:, 0]) == [0, 1, 2, 0, 1, 2]


# ---------------------------------------------------------------------------
# staging-buffer cache
# ---------------------------------------------------------------------------
def test_staging_buffers_rotate_and_stay_correct(rng):
    x = _var("x", "float32", shape=[4])
    fd = DataFeeder([x], staging_slots=2)
    rows = [[(rng.rand(4).astype("float32"),) for _ in range(3)]
            for _ in range(4)]
    outs = [fd.feed(r)["x"] for r in rows]
    # slots=2: call 3 reuses call 1's buffer, call 4 reuses call 2's
    assert outs[2] is outs[0] or outs[2].base is (outs[0].base or outs[0])
    expected3 = np.stack([r[0] for r in rows[3]])
    assert np.array_equal(outs[3], expected3)
    # the two live slots hold the two most recent feeds
    expected2 = np.stack([r[0] for r in rows[2]])
    assert np.array_equal(outs[2], expected2)


def test_staging_padded_buffers_are_rezeroed(rng):
    # float64: numpy path, no native; shape=[] avoids the [...,1] reshape
    v = _var("w", "float64", lod_level=1, shape=[])
    fd = DataFeeder([v], seq_bucket_multiple=8, staging_slots=1)
    long_row = [(list(rng.rand(8)),)]
    short_row = [([0.5],)]
    fd.feed(long_row)
    out = fd.feed(short_row)["w"]             # same buffer, reused
    assert out.shape == (1, 8)
    assert out[0, 0] == 0.5 and (out[0, 1:] == 0).all()


def test_round_up():
    assert _round_up(5, 8) == 8
    assert _round_up(8, 8) == 8
    assert _round_up(0, 8) == 0
    assert _round_up(7, 1) == 7
