"""ShardedExecutor(amp=True) — THE production config (bf16 compute,
fp32 master weights, sharded mesh) — equivalence vs the unsharded AMP
path on the 8-virtual-device CPU mesh.  Every other sharding test runs
fp32; AMP under a mesh exercises a distinct path (bf16 cast inside the
traced forward + GSPMD sharding + donated fp32 state) that was
previously untested."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh


def _program(rng, tp_shard=False, batch=16):
    img = layers.data("img", shape=[16], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    hidden = layers.fc(img, size=32, act="relu",
                       param_attr=pt.ParamAttr(name="w_col",
                                               sharding=(None, "tp"))
                       if tp_shard else None)
    pred = layers.fc(hidden, size=10, act="softmax",
                     param_attr=pt.ParamAttr(name="w_row",
                                             sharding=("tp", None))
                     if tp_shard else None)
    loss = layers.mean(layers.cross_entropy(pred, label))
    pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    feeds = {"img": rng.rand(batch, 16).astype("float32"),
             "label": rng.randint(0, 10, (batch, 1))}
    return loss, feeds


def _train(exe, prog, loss, feeds, steps=4):
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe._step = 0
    return [float(exe.run(prog, feed=feeds, fetch_list=[loss])[0])
            for _ in range(steps)]


@pytest.mark.parametrize("mesh_cfg", [dict(dp=8), dict(dp=2, tp=4)])
def test_sharded_amp_matches_unsharded_amp(rng, mesh_cfg):
    """Same seeds, same data: dp8 / dp2xtp4 AMP training must track the
    1-device AMP run step for step (the test_CompareTwoNets strategy,
    bf16 tolerance)."""
    loss, feeds = _program(rng, tp_shard="tp" in mesh_cfg)
    prog = pt.default_main_program()

    single = _train(pt.Executor(amp=True), prog, loss, feeds)

    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(**mesh_cfg)), amp=True)
    if "tp" in mesh_cfg:
        exe.place_state(prog)
    multi = _train(exe, prog, loss, feeds)

    assert np.isfinite(multi).all()
    # bf16 forward: per-step values match within bf16 resolution; the
    # training trajectory must actually descend
    np.testing.assert_allclose(single, multi, rtol=3e-2, atol=1e-3)
    assert multi[-1] < multi[0]


def test_sharded_amp_master_weights_stay_fp32(rng):
    """AMP invariant under the mesh: persistable params remain fp32 in
    scope (bf16 is compute-only), exactly as unsharded AMP keeps them."""
    loss, feeds = _program(rng)
    prog = pt.default_main_program()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(dp=8)), amp=True)
    _train(exe, prog, loss, feeds, steps=2)
    scope = pt.global_scope()
    for name in scope.keys():
        v = scope.get(name)
        if hasattr(v, "dtype") and "float" in str(v.dtype):
            assert str(v.dtype) == "float32", (name, v.dtype)


def test_sharded_amp_run_steps_window(rng):
    """The compiled K-step scan (run_steps) — the benchmark/driver shape —
    under ShardedExecutor(amp=True): one dispatch, finite stacked losses,
    state advanced, and the final loss consistent with per-step runs."""
    loss, feeds = _program(rng)
    prog = pt.default_main_program()

    single = _train(pt.Executor(amp=True), prog, loss, feeds, steps=5)

    pt.core.reset_global_scope()
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(dp=8)), amp=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe._step = 0
    (lv,) = exe.run_steps(5, prog, feed=feeds, fetch_list=[loss],
                          return_numpy=False)
    lv = np.asarray(lv)
    assert lv.shape[0] == 5 and np.isfinite(lv).all()
    np.testing.assert_allclose(lv, single, rtol=3e-2, atol=1e-3)
