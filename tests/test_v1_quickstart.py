"""quick_start demo configs (v1_api_demo/quick_start/trainer_config.*.py)
evaluated VERBATIM and trained: logistic regression, embedding+pooling,
sequence-conv text CNN, LSTM, bidirectional LSTM — the sentiment pipeline
the v1 tutorial shipped."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.trainer_config_helpers import load_v1_config

QS = "/root/reference/v1_api_demo/quick_start"
VOCAB = 200


@pytest.fixture()
def qs_cwd(tmp_path, monkeypatch):
    """The configs hardcode ./data/dict.txt at evaluation time."""
    (tmp_path / "data").mkdir()
    with open(tmp_path / "data" / "dict.txt", "w") as f:
        for i in range(VOCAB):
            f.write(f"word{i}\t{i}\n")
    monkeypatch.chdir(tmp_path)
    return str(tmp_path / "data" / "dict.txt")


def _train(cfg, feeds, n=6):
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    vals = [float(exe.run(cfg.main_program, feed=feeds,
                          fetch_list=[loss])[0]) for _ in range(n)]
    assert np.isfinite(vals).all()
    assert vals[-1] < vals[0]
    return vals


def _seq_feeds(rng):
    return {"word": rng.randint(0, VOCAB, (8, 12)),
            "word@LEN": np.full(8, 12),
            "label": rng.randint(0, 2, (8, 1))}


@pytest.mark.needs_reference
def test_quickstart_lr(qs_cwd, rng):
    cfg = load_v1_config(os.path.join(QS, "trainer_config.lr.py"),
                         dict_file=qs_cwd)
    _train(cfg, {"word": rng.rand(8, VOCAB).astype("float32"),
                 "label": rng.randint(0, 2, (8, 1))})


@pytest.mark.parametrize("conf", ["trainer_config.emb.py",
                                  "trainer_config.cnn.py",
                                  "trainer_config.lstm.py",
                                  "trainer_config.bidi-lstm.py",
                                  "trainer_config.db-lstm.py"])
@pytest.mark.needs_reference
def test_quickstart_sequence_configs(qs_cwd, rng, conf):
    cfg = load_v1_config(os.path.join(QS, conf), dict_file=qs_cwd)
    _train(cfg, _seq_feeds(rng))
