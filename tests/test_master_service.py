"""Master-as-a-service tests: TCP JSON-RPC master (go/master/service.go
analog) consumed from OTHER processes, including a trainer that dies
mid-task and a survivor that finishes its work (elastic recovery via task
timeout re-queue, service.go:368-472; SURVEY §4 in-process-over-localhost
test pattern)."""
import json
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed.master import (Master, MasterClient,
                                           MasterServer, TaskQueueClient)


def _start(master):
    srv = MasterServer(master).start()
    return srv


def test_client_server_roundtrip():
    m = Master(chunks_per_task=2, timeout_s=30.0)
    m.set_dataset(list(range(10)))
    srv = _start(m)
    try:
        c = MasterClient(srv.address)
        assert c.ping() == "pong"
        got = []
        while True:
            t = c.get_task()
            if t is None:
                break
            got.extend(t.chunks)
            c.task_finished(t.task_id)
        assert sorted(got) == list(range(10))
        st = c.stats()
        assert st["done"] == 5 and st["todo"] == 0 and st["pending"] == 0
        c.close()
    finally:
        srv.stop()


def test_task_queue_client_over_rpc():
    """TaskQueueClient (the reader integration) duck-types over the RPC
    stub unchanged."""
    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([[1, 2], [3, 4], [5, 6]])
    srv = _start(m)
    try:
        c = MasterClient(srv.address)
        r = TaskQueueClient(c, chunk_reader=lambda ch: iter(ch))
        assert sorted(r.reader()()) == [1, 2, 3, 4, 5, 6]
    finally:
        srv.stop()


WORKER = textwrap.dedent("""
    import json, sys, os, time
    sys.path.insert(0, {repo!r})
    from paddle_tpu.distributed.master import MasterClient
    addr, mode = sys.argv[1], sys.argv[2]
    c = MasterClient(addr)
    if mode == "die":
        t = c.get_task()
        assert t is not None
        print(json.dumps({{"got": t.task_id}}), flush=True)
        os._exit(1)          # hard death mid-task: no finish, no cleanup
    got = []
    while True:
        t = c.get_task()
        if t is None:
            st = c.stats()
            if st["pending"] == 0 and st["todo"] == 0:
                break
            time.sleep(0.2)   # a dead trainer's lease must lapse first
            continue
        got.extend(t.chunks)
        c.task_finished(t.task_id)
    print(json.dumps({{"chunks": got}}), flush=True)
""")


@pytest.mark.timeout(60)
def test_elastic_trainer_death_cross_process(tmp_path):
    """Two trainer PROCESSES against one master service: trainer A takes a
    task and dies; after the lease times out the task re-queues and trainer
    B finishes the full dataset."""
    m = Master(chunks_per_task=1, timeout_s=1.0, failure_max=3)
    m.set_dataset(list(range(6)))
    srv = _start(m)
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo="/root/repo"))
    try:
        a = subprocess.run([sys.executable, str(script), srv.address,
                            "die"], capture_output=True, text=True,
                           timeout=30)
        died_with = json.loads(a.stdout.strip().splitlines()[-1])
        assert "got" in died_with          # A held a task when it died
        assert a.returncode == 1

        b = subprocess.run([sys.executable, str(script), srv.address,
                            "work"], capture_output=True, text=True,
                           timeout=45)
        assert b.returncode == 0, b.stderr
        out = json.loads(b.stdout.strip().splitlines()[-1])
        # B processed every chunk, including the one A died holding
        assert sorted(out["chunks"]) == list(range(6))
    finally:
        srv.stop()


def test_generator_close_prompt_when_master_dead():
    """Closing a task-loop reader generator whose master has DIED must
    return promptly: the GeneratorExit finalizer takes the single-attempt
    <=2 s ``task_returned_nowait`` path instead of the full retry loop
    (3 x 30 s connect timeout ~= 90 s stall)."""
    from paddle_tpu.distributed.master import task_loop_reader

    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([[1, 2], [3, 4], [5, 6]])
    srv = _start(m)
    c = MasterClient(srv.address)
    gen = task_loop_reader(c, chunk_reader=lambda ch: iter(ch))()
    assert next(gen) in (1, 3, 5)      # a task is now in flight
    srv.stop()                         # master dies mid-task
    t0 = time.time()
    gen.close()                        # GeneratorExit -> best-effort return
    elapsed = time.time() - t0
    assert elapsed < 10.0, f"generator close stalled {elapsed:.1f}s"
    c.close()


def test_task_failed_max_failure_drop():
    """A task that keeps failing is dropped after failure_max failures
    (service.go:455-472): re-queued failure_max-1 times, then moved to
    done and NEVER re-served this pass."""
    m = Master(chunks_per_task=1, timeout_s=30.0, failure_max=2)
    m.set_dataset([["poison"]])
    t1 = m.get_task()
    assert t1 is not None
    m.task_failed(t1.task_id)            # failure 1: re-queued
    assert m.stats() == {"todo": 1, "pending": 0, "done": 0, "epoch": 0}
    t2 = m.get_task()
    assert t2 is not None and t2.task_id == t1.task_id
    assert t2.num_failures == 1
    m.task_failed(t2.task_id)            # failure 2 == failure_max: drop
    st = m.stats()
    assert st == {"todo": 0, "pending": 0, "done": 1, "epoch": 0}
    assert m.get_task() is None          # dropped, not re-served
    # failing an unknown/already-dropped id is a no-op, not an error
    m.task_failed(t2.task_id)
    assert m.stats()["done"] == 1


def test_requeue_timeouts_redispatch_exactly_once():
    """A task whose holder dies (lease lapses) is re-served to another
    client EXACTLY once: one timeout -> one budget tick -> one re-serve,
    and the re-served copy is not duplicated in any queue."""
    m = Master(chunks_per_task=1, timeout_s=0.15, failure_max=3)
    m.set_dataset([["c0"], ["c1"]])
    dead = m.get_task()                  # "holder" that will never finish
    assert dead is not None
    time.sleep(0.25)                     # lease lapses
    # survivor pulls twice: gets the fresh task and the timed-out one,
    # each exactly once
    got = [m.get_task(), m.get_task()]
    ids = sorted(t.task_id for t in got)
    assert ids == sorted({dead.task_id} |
                         {t.task_id for t in got})
    assert len(ids) == 2                 # no duplicate serve
    redispatched = next(t for t in got if t.task_id == dead.task_id)
    assert redispatched.num_failures == 1    # exactly one budget tick
    assert m.get_task() is None          # nothing left to serve
    st = m.stats()
    assert st["pending"] == 2 and st["todo"] == 0
    for t in got:
        m.task_finished(t.task_id)
    st = m.stats()
    assert st["done"] == 2 and st["pending"] == 0


def test_membership_register_heartbeat_members_over_rpc():
    """The etcd-membership analog end to end over the wire: register,
    heartbeat refresh, lease-style staleness, command delivery on the
    heartbeat reply, deregister."""
    m = Master(chunks_per_task=1, timeout_s=30.0, world=2,
               heartbeat_lease_s=0.2)
    m.set_dataset([["a"], ["b"]])
    srv = _start(m)
    try:
        c = MasterClient(srv.address)
        resp = c.register_worker(0, cursor=None, pid=123)
        assert resp["ok"] and resp["world"] == 2
        assert resp["shard_done"] == 0
        hb = c.heartbeat(0)
        assert hb["ok"] and hb["cmd"] is None
        mem = c.members()
        assert mem[0]["stale"] is False and mem[0]["pid"] == 123
        time.sleep(0.3)                      # lease lapses
        assert c.members()[0]["stale"] is True
        c.heartbeat(0)                       # refresh recovers the lease
        assert c.members()[0]["stale"] is False
        # command channel: the coordinator's drain rides the reply
        m.set_command("drain", slot=0)
        assert c.heartbeat(0)["cmd"] == "drain"
        m.set_command(None, slot=0)
        assert c.heartbeat(0)["cmd"] is None
        c.deregister_worker(0)
        assert c.members() == {}
        c.close()
    finally:
        srv.stop()


def test_heartbeat_from_unregistered_slot_auto_registers():
    m = Master(heartbeat_lease_s=5.0)
    assert m.heartbeat(3)["ok"]
    assert 3 in m.members() and not m.members()[3]["stale"]


def test_membership_survives_state_dict_round_trip():
    """Membership + world serialize in state_dict, so a coordinator
    restart (job-record restore) still knows its fleet; a long outage
    reads as every member stale — which is correct."""
    m = Master(world=4, heartbeat_lease_s=0.05)
    m.set_dataset([[i] for i in range(4)])
    m.register_worker(0, cursor=1, pid=11)
    m.register_worker(2, cursor=0, pid=22)
    state = m.state_dict()
    # JSON round-trip (the job record is a JSON file)
    state = json.loads(json.dumps(state))
    fresh = Master(heartbeat_lease_s=0.05)   # lease is config, not state
    fresh.load_state_dict(state)
    assert fresh.world == 4
    mem = fresh.members()
    assert set(mem) == {0, 2} and mem[0]["pid"] == 11
    time.sleep(0.06)
    assert all(v["stale"] for v in fresh.members().values())
    # the queue state round-tripped too (task 0 reconciled done)
    assert fresh.stats()["done"] == 1


def test_snapshot_path_preserves_sharded_mode(tmp_path):
    """The per-task_finished snapshot file carries the same payload as
    state_dict (world + membership included) — a snapshot restore of a
    sharded master must not silently fall back to the racy pull queue."""
    p = str(tmp_path / "snap.json")
    m = Master(world=2, snapshot_path=p)
    m.set_dataset([["a"], ["b"], ["c"], ["d"]])
    m.register_worker(0, pid=7)
    t = m.get_task(slot=0)
    m.task_finished(t.task_id)             # writes the snapshot
    m2 = Master(snapshot_path=p)
    m2.restore_snapshot()
    assert m2.world == 2
    assert m2.members()[0]["pid"] == 7
    with pytest.raises(ValueError):
        m2.get_task()                      # still slot-sharded
    assert m2.get_task(slot=0).task_id == 2


def test_state_dict_rpc_duck_types_for_checkpoint_embedding():
    """MasterClient.state_dict(): train(master=client) can embed a
    REMOTE master's queue position in its checkpoint's TrainState."""
    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([["a"], ["b"]])
    srv = _start(m)
    try:
        c = MasterClient(srv.address)
        t = c.get_task()
        c.task_finished(t.task_id)
        state = c.state_dict()
        assert len(state["done"]) == 1 and len(state["todo"]) == 1
        c.close()
    finally:
        srv.stop()


def test_task_returned_nowait_succeeds_against_live_master():
    """The fast path is not only for dead masters: against a live one it
    really returns the task (re-queued immediately, no budget burn)."""
    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([[1, 2]])
    srv = _start(m)
    try:
        c = MasterClient(srv.address)
        t = c.get_task()
        assert t is not None
        c.task_returned_nowait(t.task_id)
        t2 = c.get_task()              # the returned task comes back
        assert t2 is not None and t2.chunks == t.chunks
        c.task_finished(t2.task_id)
        assert c.stats()["done"] == 1
        c.close()
    finally:
        srv.stop()
