"""Asynchronous input pipeline: reader engine lifecycle, run_pipelined
parity with the sequential executor, and the Trainer pipeline= path."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.executor import stack_feeds
from paddle_tpu.reader import buffered, interleave, native_buffered, prefetch
from paddle_tpu.reader.pipeline import THREAD_NAME_PREFIX


# ---------------------------------------------------------------------------
# reader.pipeline engine
# ---------------------------------------------------------------------------
def _range_reader(n):
    return lambda: iter(range(n))


def test_prefetch_single_worker_preserves_order():
    assert list(prefetch(_range_reader(200), buffer_size=4)()) == \
        list(range(200))


def test_prefetch_multi_worker_yields_every_item():
    out = list(prefetch(_range_reader(500), buffer_size=8, num_workers=4)())
    assert sorted(out) == list(range(500))


def test_prefetch_mapper_runs_in_parallel_workers():
    seen_threads = set()

    def mapper(x):
        seen_threads.add(threading.current_thread().name)
        return x * 3

    out = list(prefetch(_range_reader(300), buffer_size=8, num_workers=3,
                        mapper=mapper)())
    assert sorted(out) == [3 * i for i in range(300)]
    assert all(n.startswith(THREAD_NAME_PREFIX) for n in seen_threads)


def test_prefetch_propagates_reader_exception():
    def bad():
        yield from range(5)
        raise RuntimeError("decode failed")

    with pytest.raises(RuntimeError, match="decode failed"):
        list(prefetch(lambda: bad(), buffer_size=2, num_workers=2)())


def test_prefetch_early_abandon_stops_workers():
    g = prefetch(_range_reader(10 ** 9), buffer_size=4, num_workers=3)()
    assert next(g) is not None
    g.close()        # conftest's leak fixture asserts the workers died
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and any(
            t.name.startswith(THREAD_NAME_PREFIX)
            for t in threading.enumerate()):
        time.sleep(0.02)
    assert not [t for t in threading.enumerate()
                if t.name.startswith(THREAD_NAME_PREFIX)]


def test_prefetch_rejects_bad_worker_count():
    with pytest.raises(ValueError):
        prefetch(_range_reader(3), num_workers=0)


def test_interleave_covers_all_shards():
    shards = [lambda i=i: iter(range(i * 100, i * 100 + 10))
              for i in range(5)]
    expect = sorted(sum((list(range(i * 100, i * 100 + 10))
                         for i in range(5)), []))
    assert sorted(interleave(shards, buffer_size=8)()) == expect
    assert sorted(interleave(shards, buffer_size=8, num_workers=2)()) == \
        expect


def test_interleave_worker_mixes_its_shards():
    # one worker owning every shard must still cycle them round-robin
    shards = [lambda i=i: iter([(i, j) for j in range(3)]) for i in range(3)]
    out = list(interleave(shards, buffer_size=16, num_workers=1)())
    assert [s for s, _ in out[:3]] == [0, 1, 2]  # first round touches all


def test_interleave_propagates_shard_exception():
    def bad():
        yield 1
        raise ValueError("shard 1 corrupt")

    shards = [_range_reader(50), lambda: bad()]
    with pytest.raises(ValueError, match="shard 1 corrupt"):
        list(interleave(shards, buffer_size=4)())


def test_buffered_reraises_and_preserves_order():
    assert list(buffered(_range_reader(100), 4)()) == list(range(100))

    def bad():
        yield 1
        raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        list(buffered(lambda: bad(), 4)())


def test_native_buffered_propagates_exception():
    def bad():
        yield from range(3)
        raise RuntimeError("reader broke")

    r = native_buffered(lambda: bad(), size=2)
    got = []
    with pytest.raises(RuntimeError, match="reader broke"):
        for x in r():
            got.append(x)
    assert got == [0, 1, 2]


# ---------------------------------------------------------------------------
# stack_feeds
# ---------------------------------------------------------------------------
def test_stack_feeds_shapes_and_validation():
    feeds = [{"x": np.full((2, 3), i, np.float32), "y": np.array([i])}
             for i in range(4)]
    st = stack_feeds(feeds)
    assert st["x"].shape == (4, 2, 3) and st["y"].shape == (4, 1)
    assert (st["x"][2] == 2).all()
    with pytest.raises(ValueError):
        stack_feeds([])
    with pytest.raises(ValueError, match="keys differ"):
        stack_feeds([{"x": np.zeros(2)}, {"z": np.zeros(2)}])


# ---------------------------------------------------------------------------
# Executor.run_pipelined
# ---------------------------------------------------------------------------
def _build_cls_net(seed_layers=True):
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=16, act="relu")
    if seed_layers:
        h = layers.dropout(h, dropout_prob=0.3)  # step-keyed RNG must match
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _fresh():
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()


def _batches(rng, n, batch=16, feat=8):
    return [{"x": rng.rand(batch, feat).astype("float32"),
             "y": rng.randint(0, 3, (batch, 1))} for _ in range(n)]


def test_run_pipelined_matches_sequential_run_bitwise():
    batches = _batches(np.random.RandomState(7), 11)

    _fresh()
    loss = _build_cls_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    seq = [exe.run(pt.default_main_program(), feed=f, fetch_list=[loss])[0]
           for f in batches]

    _fresh()
    loss2 = _build_cls_net()
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    pip = [o[0] for o in exe2.run_pipelined(
        iter(batches), pt.default_main_program(), fetch_list=[loss2],
        steps_per_dispatch=4)]

    assert len(pip) == len(seq)
    for i, (a, b) in enumerate(zip(seq, pip)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"step {i}: sequential {a} != pipelined {b}"


def test_run_pipelined_handles_signature_changes():
    # two padding buckets alternating: scans must split at the boundary
    rng = np.random.RandomState(3)
    batches = []
    for width in (8, 16, 8, 8, 8, 16, 16):
        batches.append({"x": rng.rand(4, width).astype("float32")})

    _fresh()
    x = layers.data("x", shape=[-1], dtype="float32")
    out = layers.reduce_mean(x)
    exe = pt.Executor()
    outs = list(exe.run_pipelined(iter(batches), pt.default_main_program(),
                                  fetch_list=[out], steps_per_dispatch=3,
                                  is_test=True))
    assert len(outs) == len(batches)
    for f, o in zip(batches, outs):
        np.testing.assert_allclose(o[0], f["x"].mean(), rtol=1e-6)


def test_run_pipelined_flushes_partial_stack_on_signature_change():
    """A signature change mid-K must flush the partially-filled stack
    through the per-step path — every feed trains, in order, with
    fetches BIT-IDENTICAL to the sequential loop (training state + the
    step-keyed RNG cross the flush boundary intact) — and the flushed
    steps are counted in pipeline/fallback_steps so a bucketing mistake
    that degrades every dispatch to singles is visible in telemetry."""
    rng = np.random.RandomState(11)
    # K=4: one full scan of A, a 2-deep partial stack of A flushed by the
    # B signature change, then a 3-step B tail — 5 fallback steps total
    batches = _batches(rng, 6, batch=16) + _batches(rng, 3, batch=10)

    _fresh()
    loss = _build_cls_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    seq = [exe.run(pt.default_main_program(), feed=f, fetch_list=[loss])[0]
           for f in batches]

    _fresh()
    loss2 = _build_cls_net()
    exe2 = pt.Executor(observe=True)
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    from paddle_tpu.observability import registry
    before = registry().snapshot()["pipeline/fallback_steps"]["value"]
    pip = [o[0] for o in exe2.run_pipelined(
        iter(batches), pt.default_main_program(), fetch_list=[loss2],
        steps_per_dispatch=4)]
    after = registry().snapshot()["pipeline/fallback_steps"]["value"]

    assert len(pip) == len(seq) == 9
    for i, (a, b) in enumerate(zip(seq, pip)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            f"step {i}: sequential {a} != pipelined {b}"
    assert after - before == 5, \
        f"expected 5 per-step fallback dispatches (2 flushed + 3 tail), " \
        f"metric counted {after - before}"


def test_run_pipelined_propagates_feed_iter_exception():
    _fresh()
    x = layers.data("x", shape=[4], dtype="float32")
    out = layers.reduce_mean(x)
    exe = pt.Executor()

    def feeds():
        yield {"x": np.zeros((2, 4), np.float32)}
        raise RuntimeError("source died")

    with pytest.raises(RuntimeError, match="source died"):
        list(exe.run_pipelined(feeds(), pt.default_main_program(),
                               fetch_list=[out], steps_per_dispatch=2,
                               is_test=True))


def test_run_pipelined_rejects_check_nan_inf():
    _fresh()
    layers.data("x", shape=[4], dtype="float32")
    exe = pt.Executor(check_nan_inf=True)
    with pytest.raises(ValueError, match="check_nan_inf"):
        next(iter(exe.run_pipelined(iter([]), pt.default_main_program())))


# ---------------------------------------------------------------------------
# Trainer pipeline= option
# ---------------------------------------------------------------------------
def test_trainer_pipeline_trains_and_fires_events():
    from paddle_tpu import trainer as trainer_mod

    rng = np.random.RandomState(0)
    w_true = rng.rand(5, 1).astype("float32")

    def reader():
        r = np.random.RandomState(1)
        for _ in range(30):
            xb = r.rand(8, 5).astype("float32")
            yb = xb @ w_true + 0.01 * r.randn(8, 1).astype("float32")
            yield [(xb[i], yb[i]) for i in range(8)]

    x = layers.data("x", shape=[5], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    cost = layers.mean(layers.square_error_cost(pred, y))
    sgd = trainer_mod.SGD(cost, update_equation=pt.optimizer.SGD(
        learning_rate=0.05))

    seen = {"begin": 0, "end": 0, "passes": 0, "losses": []}

    def handler(e):
        if isinstance(e, trainer_mod.events.BeginIteration):
            seen["begin"] += 1
        elif isinstance(e, trainer_mod.events.EndIteration):
            seen["end"] += 1
            seen["losses"].append(e.cost)
        elif isinstance(e, trainer_mod.events.EndPass):
            seen["passes"] += 1

    sgd.train(reader, num_passes=2, event_handler=handler,
              feed_list=[x, y], pipeline={"steps_per_dispatch": 4})
    assert seen["begin"] == seen["end"] == 60
    assert seen["passes"] == 2
    assert np.isfinite(seen["losses"]).all()
    # training signal: second pass clearly below the first's start
    assert np.mean(seen["losses"][-10:]) < seen["losses"][0]
