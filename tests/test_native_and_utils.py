"""Native feeder runtime + utility-subsystem tests (reference analogs:
PyDataProvider2 provider tests, utils/tests Stat tests, gflags usage,
fluid net_drawer)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.native import get_native


def _native():
    n = get_native()
    if n is None:
        pytest.skip("native toolchain unavailable")
    return n


def test_native_pad_batch_matches_python():
    n = _native()
    rows = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10]]
    padded, lens = n.pad_batch(rows, 4, "int64")
    assert padded.shape == (3, 8) and padded.dtype == np.int64
    np.testing.assert_array_equal(lens, [3, 2, 5])
    np.testing.assert_array_equal(padded[2, :5], [6, 7, 8, 9, 10])
    assert padded[1, 2:].sum() == 0
    # float32 + 2-D numpy rows (time x feature)
    rows = [np.arange(6, dtype="float32").reshape(3, 2),
            np.ones((1, 2), "float32")]
    padded, lens = n.pad_batch(rows, 1, "float32")
    assert padded.shape == (2, 3, 2)
    np.testing.assert_array_equal(padded[0], np.arange(6).reshape(3, 2))
    assert padded[1, 1:].sum() == 0


def test_native_pad_dtype_casting():
    n = _native()
    padded, _ = n.pad_batch([np.array([1, 2], np.int32)], 1, "int64")
    assert padded.dtype == np.int64


def test_data_feeder_uses_native_consistently():
    main = pt.Program()
    with pt.program_guard(main, pt.Program()):
        w = layers.data("w", shape=[], dtype="int64", lod_level=1)
    feeder = pt.DataFeeder([w], seq_bucket_multiple=4)
    feed = feeder.feed([([1, 2, 3],), ([9],)])
    assert feed["w"].shape == (2, 4)
    np.testing.assert_array_equal(feed["w@LEN"], [3, 1])


def test_async_batcher_order_and_end():
    n = _native()
    items = iter(range(100))

    def nxt():
        try:
            return (next(items),)
        except StopIteration:
            return None
    b = n.AsyncBatcher(nxt, capacity=8)
    got = []
    while True:
        item = b.next_batch()
        if item is None:
            break
        got.append(item[0])
    b.close()
    assert got == list(range(100))


def test_async_batcher_propagates_reader_errors():
    """A bug in the user's reader must surface, not silently end the epoch
    (reference contrast: PyDataProvider2 forwards provider exceptions)."""
    n = _native()
    state = {"i": 0}

    def nxt():
        state["i"] += 1
        if state["i"] == 3:
            raise RuntimeError("reader exploded")
        return (state["i"],)

    b = n.AsyncBatcher(nxt, capacity=2)
    got = []
    with pytest.raises(RuntimeError, match="reader exploded"):
        while True:
            item = b.next_batch()
            if item is None:
                break
            got.append(item[0])
    b.close()
    assert got == [1, 2]


def test_pad_batch_rejects_inconsistent_dims():
    n = _native()
    with pytest.raises(ValueError, match="inconsistent feature dims"):
        n.pad_batch([np.ones((2, 3), "float32"),
                     np.ones((2, 4), "float32")], 1, "float32")
    with pytest.raises(ValueError, match="inconsistent feature dims"):
        n.pad_batch([np.ones((2, 3), "float32"), [1.0, 2.0]], 1, "float32")
    with pytest.raises(ValueError, match="ndim"):
        n.pad_batch([np.ones((2, 3, 4), "float32")], 1, "float32")


def test_native_buffered_reader():
    r = pt.reader.native_buffered(lambda: iter(range(50)), size=4)
    assert list(r()) == list(range(50))
    # reusable
    assert list(r()) == list(range(50))


def test_flags_env_and_parse(monkeypatch):
    from paddle_tpu import flags
    assert flags.get_flag("log_period") == 100
    flags.set_flag("log_period", 5)
    assert flags.get_flag("log_period") == 5
    rest = flags.parse_args(["--beam_size=7", "positional", "--unknown=1"])
    assert flags.get_flag("beam_size") == 7
    assert rest == ["positional", "--unknown=1"]
    with pytest.raises(KeyError):
        flags.set_flag("nonexistent", 1)
    flags.set_flag("log_period", 100)


def test_net_drawer_dot():
    from paddle_tpu import net_drawer
    x = layers.data("x", shape=[4], dtype="float32")
    h = layers.fc(x, size=2, act="softmax")
    dot = net_drawer.draw_graph(pt.default_main_program())
    assert dot.startswith("digraph") and "mul" in dot and "softmax" in dot
    assert "fc_0_w_0" in dot.replace(".", "_")


def test_stat_timers():
    from paddle_tpu import profiler
    st = profiler.Stat()
    with st.timer("fwd"):
        pass
    with st.timer("fwd"):
        pass
    with st.timer("bwd"):
        pass
    rep = st.report()
    assert "fwd" in rep and "count=2" in rep
    st.reset()
    assert st.report() == "======= StatSet ======="


def test_executor_error_mentions_op(rng):
    """CustomStackTrace analog: failures carry the op context."""
    x = layers.data("x", shape=[4], dtype="float32")
    layers.fc(x, size=2)
    exe = pt.Executor(use_jit=False)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with pytest.raises(Exception) as ei:
        exe.run(feed={}, fetch_list=[])   # missing feed
    assert "x" in str(ei.value)


def test_reader_creators(tmp_path, rng):
    """reader.creator parity (v2 creator.py): np_array rows, text_file
    lines, recordio over dataset.common.split part files."""
    from paddle_tpu import reader
    from paddle_tpu.dataset import common

    arr = rng.rand(5, 3).astype("float32")
    rows = list(reader.creator.np_array(arr)())
    assert len(rows) == 5 and np.allclose(rows[2], arr[2])

    p = tmp_path / "t.txt"
    p.write_text("alpha\nbeta\n")
    assert list(reader.creator.text_file(str(p))()) == ["alpha", "beta"]

    common.split(lambda: iter(range(10)), 3,
                 suffix=str(tmp_path / "part-%05d.pickle"))
    got = list(reader.creator.recordio(str(tmp_path / "part-*.pickle"))())
    assert sorted(got) == list(range(10))


def test_cloud_reader_exactly_once_and_failover(tmp_path):
    """creator.cloud_reader: two readers share one master; chunks are
    consumed exactly once, and a reader that dies mid-task requeues its
    chunk for the survivor (the reference's etcd+Go-master cloud_reader
    semantics, creator.py:91)."""

    from paddle_tpu import reader
    from paddle_tpu.dataset import common
    from paddle_tpu.distributed.master import Master, MasterServer

    common.split(lambda: iter(range(12)), 3,
                 suffix=str(tmp_path / "part-%05d.pickle"))
    pattern = str(tmp_path / "part-*.pickle")

    srv = MasterServer(Master(chunks_per_task=1, timeout_s=0.5)).start()
    try:
        r1 = reader.creator.cloud_reader(pattern, srv.address)()
        r2 = reader.creator.cloud_reader(pattern, srv.address)()
        # r1 completes its first task (chunk [0,1,2])...
        first = [next(r1) for _ in range(3)]
        assert first == [0, 1, 2]
        # ...pulls one record of its second task (chunk [3,4,5]), dies.
        # Generator finalization RETURNS the task synchronously
        # (task_returned — no failure-budget burn, no timeout wait)
        assert next(r1) == 3
        r1.close()
        got2 = sorted(r2)
        # survivor saw everything except r1's FINISHED chunk — including
        # the re-served abandoned one; nothing lost, no double-serve of
        # completed work
        assert got2 == list(range(3, 12))
    finally:
        srv.stop()


def test_load_torch_state_dict_matches_torch_forward(rng):
    """torch2paddle's role (utils/torch2paddle.py): a torch MLP's weights
    import into the equivalent paddle_tpu network and the forward outputs
    match torch exactly (linear weights auto-transposed)."""
    torch = pytest.importorskip("torch")

    import paddle_tpu as pt
    from paddle_tpu import layers

    tnet = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4))
    x = layers.data("x", shape=[8], dtype="float32")
    h = layers.fc(x, size=16, act="relu",
                  param_attr=pt.ParamAttr(name="w1"), bias_attr="b1")
    out = layers.fc(h, size=4, param_attr=pt.ParamAttr(name="w2"),
                    bias_attr="b2")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])

    imported = pt.utils.load_torch_state_dict(
        tnet.state_dict(),
        {"0.weight": "w1", "0.bias": "b1",
         "2.weight": "w2", "2.bias": "b2"})
    assert sorted(imported) == ["b1", "b2", "w1", "w2"]

    xv = rng.randn(5, 8).astype("float32")
    (got,) = exe.run(feed={"x": xv}, fetch_list=[out], is_test=True)
    with torch.no_grad():
        want = tnet(torch.from_numpy(xv)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)

    # shape mismatches fail loudly
    with pytest.raises(ValueError, match="shape"):
        pt.utils.load_torch_state_dict(tnet.state_dict(),
                                       {"0.weight": "w2"})

    # square linear weights are transpose-ambiguous: refused without an
    # explicit flag, exact with one
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    sq = torch.nn.Linear(8, 8)
    x2 = layers.data("x2", shape=[8], dtype="float32")
    out2 = layers.fc(x2, size=8, param_attr=pt.ParamAttr(name="wsq"),
                     bias_attr="bsq")
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with pytest.raises(ValueError, match="ambiguous"):
        pt.utils.load_torch_state_dict(sq.state_dict(),
                                       {"weight": "wsq"})
    pt.utils.load_torch_state_dict(
        sq.state_dict(), {"weight": ("wsq", True), "bias": "bsq"})
    xv2 = rng.randn(3, 8).astype("float32")
    (got2,) = exe2.run(feed={"x2": xv2}, fetch_list=[out2], is_test=True)
    with torch.no_grad():
        want2 = sq(torch.from_numpy(xv2)).numpy()
    np.testing.assert_allclose(got2, want2, rtol=2e-5, atol=1e-5)
