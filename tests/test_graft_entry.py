"""Driver-path regression tests for ``__graft_entry__``.

Round-1 failure (MULTICHIP_r01.json ok=false): the driver's process had
already initialized the JAX backend (single real TPU) before calling
``dryrun_multichip``, so env/config mutation inside the function was dead
and the device-count assert fired.  These tests run the entry module the
way the driver does — in a bare subprocess whose backend is initialized
*before* the call, with only ONE visible device — and require success.
"""
import os
import subprocess
import sys

import pytest

# @slow (ISSUE 12 tier-1 budget audit): two bare-subprocess rounds at
# ~31s + ~14s of pure jax-import/compile wall — the driver exercises the
# graft entry for real on every bench run, and the suite sits at ~95% of
# the 870s cap.  Run with `-m slow` (the PR 6/8/9/11 convention).
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, extra_env=None, timeout=1800):
    env = dict(os.environ)
    # Simulate the driver's bare environment: single-device platform, no
    # virtual-mesh flags inherited from the test conftest.
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env.update(extra_env or {})
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_dryrun_multichip_after_backend_init():
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('DRYRUN_OK')\n" % REPO
    )
    proc = _run(code)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "DRYRUN_OK" in proc.stdout


def test_entry_compiles_single_chip():
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "jax.jit(fn).lower(*args).compile()\n"
        "print('ENTRY_OK')\n" % REPO
    )
    proc = _run(code)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "ENTRY_OK" in proc.stdout
