"""HTTP serving front (ISSUE 11 tentpole a): hermetic round-trips over
an in-process server on an ephemeral port.

The deadline-propagation contract is the satellite's acceptance: a
client timeout header becomes the per-request deadline and expires at
the SAME two rims PR 8 pins — batch formation and dispatch — mapping to
504; the typed-rejection -> status-code matrix covers the rest
(Overloaded 429, ModelUnavailable 503, ServerClosed 503 + Connection:
close, BadRequest 400, auth 401/403).

Deterministic like tests/test_serving.py: a gated FakeModel makes "the
dispatcher is busy" a fact, not a race.  Subprocess/CLI rounds live in
tests/test_fleet_chaos.py under @pytest.mark.slow.
"""
import http.client
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import Model, Server
from paddle_tpu.serving.http import (DEADLINE_HEADER, TOKEN_HEADER,
                                     HttpFront, status_for)

from test_serving import FakeModel, _mk_server, _req


@pytest.fixture
def front_of():
    """Factory fixture: front_of(server, **kw) -> (host, port); every
    front and backend is stopped at teardown."""
    cleanup = []

    def make(srv, **kw):
        front = HttpFront(srv, port=0, **kw).start()
        cleanup.append((front, srv))
        return front.address

    yield make
    for front, srv in cleanup:
        front.stop()
        try:
            srv.shutdown(timeout=10)
        except TypeError:
            srv.shutdown()


def _http(host, port, method, path, body=None, headers=None, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read().decode("utf-8")
        return resp.status, dict(resp.getheaders()), data
    finally:
        conn.close()


def _lines(data):
    return [json.loads(ln) for ln in data.splitlines() if ln.strip()]


def _post_line(host, port, obj, headers=None, timeout=30):
    status, hdrs, data = _http(host, port, "POST", "/v1/infer",
                               body=json.dumps(obj), headers=headers,
                               timeout=timeout)
    return status, hdrs, (_lines(data)[0] if data.strip() else None)


# ---------------------------------------------------------------------------
# basic round trips
# ---------------------------------------------------------------------------
def test_healthz_infer_metrics_and_404(front_of):
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)

    status, _, data = _http(host, port, "GET", "/healthz")
    assert status == 200 and json.loads(data)["ready"] is True

    status, _, obj = _post_line(
        host, port, {"id": 7, "feeds": {"x": [1.0, 2.0]}})
    assert status == 200
    assert obj["id"] == 7 and obj["outputs"] == [[2.0, 4.0]]
    assert obj["ms"] >= 0 and obj["dispatch_ms"] is not None

    status, _, data = _http(host, port, "GET", "/metrics")
    assert status == 200 and "http_requests_total" in data

    status, _, _ = _http(host, port, "GET", "/nope")
    assert status == 404
    status, _, _ = _http(host, port, "POST", "/nope", body="{}")
    assert status == 404


def test_multi_line_body_streams_per_request_lines(front_of):
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)
    body = "\n".join(
        [json.dumps({"id": i, "feeds": {"x": [float(i), 0.0]}})
         for i in range(4)] + ["not json at all"])
    status, hdrs, data = _http(host, port, "POST", "/v1/infer", body=body)
    assert status == 200
    assert hdrs.get("Content-Type") == "application/x-ndjson"
    lines = _lines(data)
    assert len(lines) == 5                      # 4 results + 1 error line
    by_id = {ln.get("id"): ln for ln in lines if "outputs" in ln}
    assert sorted(by_id) == [0, 1, 2, 3]
    for i in range(4):
        assert by_id[i]["outputs"] == [[2.0 * i, 0.0]]
    errs = [ln for ln in lines if "error" in ln]
    assert len(errs) == 1 and errs[0]["error"] in ("ValueError",
                                                   "BadRequest")


# ---------------------------------------------------------------------------
# deadline propagation (satellite acceptance)
# ---------------------------------------------------------------------------
def test_deadline_header_expires_at_batch_formation_504(front_of,
                                                        monkeypatch):
    """The client timeout header becomes the request deadline; a request
    that expires while QUEUED (the batch-formation rim) maps to 504 and
    is never computed."""
    expiries = []
    real_emit = pt.observability.emit_event

    def spy(kind, **fields):
        if kind == "serving" and fields.get("event") == "deadline_expired":
            expiries.append(fields.get("where"))
        return real_emit(kind, **fields)

    monkeypatch.setattr(pt.observability, "emit_event", spy)
    from test_serving import _soak_pipeline

    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, deadline_ms=None,
                     staging_depth=1)
    host, port = front_of(srv)
    # dispatcher gated, staging full, batcher blocked on staging.put:
    # the next request stays in the ADMISSION QUEUE until released
    held = _soak_pipeline(srv)
    t2_result = {}

    def queued():
        t2_result["r2"] = _post_line(
            host, port, {"id": 2, "feeds": {"x": [2.0, 2.0]}},
            headers={DEADLINE_HEADER: "40"})

    t2 = threading.Thread(target=queued, daemon=True)
    t2.start()
    time.sleep(0.3)                      # r2's 40 ms deadline lapses
    fake.open_gate_forever()
    t2.join(timeout=15)
    for r in held:
        assert r.result(timeout=10) is not None
    status, _, obj = t2_result["r2"]
    assert status == 504
    assert obj["error"] == "DeadlineExceeded" and obj["id"] == 2
    assert 2.0 not in fake.rows          # expired = never computed
    assert "batching" in expiries


def test_deadline_header_expires_at_dispatch_rim_504(front_of,
                                                     monkeypatch):
    """A request that forms a batch in time but expires while STAGED
    (the dispatch rim) also maps to 504 — the second rim PR 8 pins."""
    expiries = []
    real_emit = pt.observability.emit_event

    def spy(kind, **fields):
        if kind == "serving" and fields.get("event") == "deadline_expired":
            expiries.append(fields.get("where"))
        return real_emit(kind, **fields)

    monkeypatch.setattr(pt.observability, "emit_event", spy)
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, max_wait_ms=1.0,
                     deadline_ms=None, staging_depth=1)
    host, port = front_of(srv)

    res = {}

    def post(key, obj, headers=None):
        res[key] = _post_line(host, port, obj, headers=headers)

    t1 = threading.Thread(
        target=post, args=("r1", {"id": 1, "feeds": {"x": [1.0, 1.0]}}),
        daemon=True)
    t1.start()
    time.sleep(0.15)                     # r1 dispatching (gated)
    # r2: batches immediately (max_wait 1 ms), then sits in staging
    # behind the gated r1 until its 120 ms deadline lapses
    t2 = threading.Thread(
        target=post, args=("r2", {"id": 2, "feeds": {"x": [2.0, 2.0]}}),
        kwargs={"headers": {DEADLINE_HEADER: "120"}}, daemon=True)
    t2.start()
    time.sleep(0.4)                      # past r2's deadline
    fake.open_gate_forever()
    t1.join(timeout=15)
    t2.join(timeout=15)
    assert res["r1"][0] == 200
    status, _, obj = res["r2"]
    assert status == 504 and obj["error"] == "DeadlineExceeded"
    assert 2.0 not in fake.rows
    assert "dispatch" in expiries


def test_body_deadline_field_overrides_header(front_of):
    """A per-line deadline_ms beats the header default — the header is
    the default for lines that don't choose their own."""
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)
    # header would expire instantly; the body opts out of deadlines
    status, _, obj = _post_line(
        host, port,
        {"id": 1, "feeds": {"x": [1.0, 2.0]}, "deadline_ms": None},
        headers={DEADLINE_HEADER: "0.001"})
    assert status == 200 and obj["outputs"] == [[2.0, 4.0]]


# ---------------------------------------------------------------------------
# typed-rejection -> status-code matrix
# ---------------------------------------------------------------------------
def test_overloaded_maps_to_429_with_retry_after(front_of):
    from test_serving import _soak_pipeline

    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, queue_capacity=1,
                     deadline_ms=None, staging_depth=1)
    host, port = front_of(srv)
    held = _soak_pipeline(srv)
    rq = srv.submit(_req(2), deadline_ms=9000.0)   # fills the queue
    # incoming via HTTP with the soonest deadline -> shed -> 429
    status, hdrs, obj = _post_line(
        host, port, {"id": 3, "feeds": {"x": [3.0, 3.0]}},
        headers={DEADLINE_HEADER: "10"})
    assert status == 429
    assert obj["error"] == "Overloaded"
    assert hdrs.get("Retry-After") == "1"
    fake.open_gate_forever()
    for r in held + [rq]:
        assert r.result(timeout=10) is not None


def test_model_unavailable_maps_to_503(front_of):
    fake = FakeModel(fail=[RuntimeError("poison")])
    srv = _mk_server(fake, max_batch=1, breaker_threshold=1,
                     retry_policy=None)
    host, port = front_of(srv)
    with pytest.raises(Exception):
        srv.infer(_req(1), timeout=10)             # opens the breaker
    assert srv.health()["models"]["fake"]["breaker"] == "open"
    status, hdrs, obj = _post_line(
        host, port, {"id": 2, "feeds": {"x": [2.0, 2.0]}})
    assert status == 503
    assert obj["error"] == "ModelUnavailable"
    assert hdrs.get("Retry-After") is not None


def test_server_closed_maps_to_503_connection_close(front_of):
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)
    srv.begin_drain()
    status, hdrs, obj = _post_line(
        host, port, {"id": 1, "feeds": {"x": [1.0, 1.0]}})
    assert status == 503
    assert obj["error"] == "ServerClosed"
    assert hdrs.get("Connection", "").lower() == "close"
    # the readiness surface flips with it
    status, _, data = _http(host, port, "GET", "/healthz")
    assert status == 503 and json.loads(data)["ready"] is False


def test_bad_requests_map_to_400(front_of):
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)
    status, _, obj = _post_line(host, port, {"nope": 1})
    assert status == 400
    status, _, _ = _http(host, port, "POST", "/v1/infer",
                         body="not json")
    assert status == 400
    # unknown model name is a 400-class admission error too
    status, _, obj = _post_line(
        host, port, {"id": 1, "model": "ghost",
                     "feeds": {"x": [1.0, 1.0]}})
    assert status == 400


def test_status_for_covers_the_frozen_matrix():
    from paddle_tpu import faults
    from paddle_tpu.serving.server import ModelError
    assert status_for(faults.Overloaded("x")) == 429
    assert status_for(faults.DeadlineExceeded("x")) == 504
    assert status_for(faults.ModelUnavailable("x")) == 503
    assert status_for(faults.ServerClosed("x")) == 503
    assert status_for(ValueError("x")) == 400
    assert status_for(ModelError("x")) == 500


# ---------------------------------------------------------------------------
# auth-token -> model routing
# ---------------------------------------------------------------------------
def test_token_auth_and_model_routing(front_of):
    a, b = FakeModel("a"), FakeModel("b")
    srv = _mk_server([a, b])
    host, port = front_of(srv, tokens={"tok-a": "a", "open": None})

    # no token -> 401 (and counted as an auth failure)
    status, hdrs, _ = _post_line(
        host, port, {"id": 1, "model": "a", "feeds": {"x": [1.0, 1.0]}})
    assert status == 401 and "WWW-Authenticate" in hdrs
    # unknown token -> 401
    status, _, _ = _post_line(
        host, port, {"id": 2, "model": "a", "feeds": {"x": [1.0, 1.0]}},
        headers={TOKEN_HEADER: "wrong"})
    assert status == 401
    # bound token routes WITHOUT a model field (tenant inferred)
    status, _, obj = _post_line(
        host, port, {"id": 3, "feeds": {"x": [3.0, 3.0]}},
        headers={TOKEN_HEADER: "tok-a"})
    assert status == 200 and obj["model"] == "a"
    assert 3.0 in a.rows and 3.0 not in b.rows
    # bound token + mismatched explicit model -> 403
    status, _, obj = _post_line(
        host, port, {"id": 4, "model": "b", "feeds": {"x": [4.0, 4.0]}},
        headers={TOKEN_HEADER: "tok-a"})
    assert status == 403 and 4.0 not in b.rows
    # unbound token may pick any tenant; Bearer form accepted
    status, _, obj = _post_line(
        host, port, {"id": 5, "model": "b", "feeds": {"x": [5.0, 5.0]}},
        headers={"Authorization": "Bearer open"})
    assert status == 200 and obj["model"] == "b" and 5.0 in b.rows


def test_open_front_needs_no_token(front_of):
    fake = FakeModel()
    srv = _mk_server(fake)
    host, port = front_of(srv)                    # tokens=None
    status, _, obj = _post_line(
        host, port, {"id": 1, "feeds": {"x": [1.0, 1.0]}})
    assert status == 200
