"""Beam-search generation tests (reference: fluid test_beam_search_op.py,
test_beam_search_decode_op.py; RecurrentGradientMachine generation golden
tests trainer/tests/test_recurrent_machine_generation.cpp)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models


def _markov_program(P, beam_size, max_len, bos, eos):
    """Decoder whose next-token distribution depends only on the current
    token: probs = P[token] — exactly computable in numpy."""
    V = P.shape[0]
    Pvar = layers.data("P", shape=[V, V], dtype="float32",
                       append_batch_size=False)
    init = layers.data("init", shape=[1], dtype="float32")
    bs = layers.BeamSearchDecoder(beam_size=beam_size, bos_id=bos,
                                  eos_id=eos, max_len=max_len, vocab_size=V)
    with bs.step():
        tok = bs.token()
        mem = bs.memory(init=init)
        probs = layers.gather(Pvar, tok)
        bs.update_memory(mem, mem)
        bs.set_probs(probs)
    return bs()


def test_beam_k1_matches_greedy_chain():
    rng = np.random.RandomState(0)
    V, T, bos, eos = 5, 4, 0, 4
    P = rng.dirichlet(np.ones(V), size=V).astype("float32")
    P[:, eos] = 1e-6           # never stop
    P /= P.sum(1, keepdims=True)
    ids_v, scores_v, lens_v = _markov_program(P, 1, T, bos, eos)
    exe = pt.Executor()
    ids, scores = exe.run(feed={"P": P, "init": np.zeros((2, 1), "float32")},
                          fetch_list=[ids_v, scores_v])
    tok, exp_ids, exp_score = bos, [], 0.0
    for _ in range(T):
        nxt = int(np.argmax(P[tok]))
        exp_score += np.log(P[tok, nxt])
        exp_ids.append(nxt)
        tok = nxt
    for b in range(2):
        np.testing.assert_array_equal(ids[b, 0], exp_ids)
        np.testing.assert_allclose(scores[b, 0], exp_score, rtol=1e-4)


def test_beam_finds_better_than_greedy():
    """Classic beam > greedy setup: a low-prob first step leads to a
    near-deterministic tail."""
    V, bos, eos = 4, 0, 3
    P = np.full((V, V), 1e-9, "float32")
    # from bos: token1 p=0.6, token2 p=0.4
    P[0, 1], P[0, 2] = 0.6, 0.4
    # token1 -> uniform-ish continuations (greedy path gets stuck cheap)
    P[1, 1], P[1, 2] = 0.5, 0.5
    # token2 -> token2 with p ~1 (the good tail)
    P[2, 2] = 1.0
    P /= P.sum(1, keepdims=True)
    T = 3
    ids_v, scores_v, _ = _markov_program(P, 2, T, bos, eos)
    exe = pt.Executor()
    ids, scores = exe.run(feed={"P": P, "init": np.zeros((1, 1), "float32")},
                          fetch_list=[ids_v, scores_v])
    # best: 2,2,2 with logp log(.4)  vs greedy 1,... log(.6)+2*log(.5)
    np.testing.assert_array_equal(ids[0, 0], [2, 2, 2])
    assert scores[0, 0] >= scores[0, 1] - 1e-6
    np.testing.assert_allclose(scores[0, 0], np.log(0.4), rtol=1e-4)


def test_beam_eos_freezes_score():
    V, bos, eos = 3, 0, 2
    P = np.full((V, V), 1e-9, "float32")
    P[0, 2] = 0.9            # bos -> eos
    P[0, 1] = 0.1
    P[1, 1] = 1.0
    P /= P.sum(1, keepdims=True)
    ids_v, scores_v, lens_v = _markov_program(P, 2, 5, bos, eos)
    exe = pt.Executor()
    ids, scores, lens = exe.run(
        feed={"P": P, "init": np.zeros((1, 1), "float32")},
        fetch_list=[ids_v, scores_v, lens_v])
    np.testing.assert_array_equal(ids[0, 0], [2] * 5)      # eos then frozen
    np.testing.assert_allclose(scores[0, 0], np.log(P[0, 2]), rtol=1e-4)
    assert int(lens[0, 0]) == 1


def test_seq2seq_train_then_beam_decode(rng):
    """Micro machine-translation book test: learn 'always emit token 3'
    then check the decoder's top beam starts with it."""
    V, H = 8, 16
    src = layers.data("src", shape=[], dtype="int64", lod_level=1)
    tgt = layers.data("tgt", shape=[], dtype="int64", lod_level=1)
    lbl = layers.data("lbl", shape=[], dtype="int64", lod_level=1)
    probs = models.seq2seq_attention(src, tgt, V, V, emb_dim=8, hidden_dim=H)
    flat = layers.reshape(probs, [-1, V])
    loss = layers.mean(layers.cross_entropy(
        flat, layers.reshape(lbl, [-1, 1])))
    opt = pt.optimizer.Adam(0.05)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    B, Ts, Tt = 8, 5, 4
    feeds = {"src": rng.randint(2, V, (B, Ts)),
             "src@LEN": np.full(B, Ts),
             "tgt": np.full((B, Tt), 3),
             "tgt@LEN": np.full(B, Tt),
             "lbl": np.full((B, Tt), 3),
             "lbl@LEN": np.full(B, Tt)}
    losses = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5

    infer_prog = pt.Program()
    with pt.program_guard(infer_prog, pt.Program()):
        src_i = layers.data("src", shape=[], dtype="int64", lod_level=1)
        ids_v, scores_v, lens_v = models.seq2seq_infer(
            src_i, V, V, emb_dim=8, hidden_dim=H, beam_size=3, bos_id=0,
            eos_id=1, max_len=4)
    ids, scores = exe.run(infer_prog,
                          feed={"src": rng.randint(2, V, (2, Ts)),
                                "src@LEN": np.full(2, Ts)},
                          fetch_list=[ids_v, scores_v], is_test=True)
    assert ids.shape == (2, 3, 4)
    assert (scores[:, 0] + 1e-6 >= scores[:, 1]).all()
    assert (ids[:, 0, 0] == 3).all()


def test_beam_step_hook_forces_early_eos():
    """Per-step drill-down hook (RecurrentGradientMachine.h:71-130 beam
    inspection/pruning analog): a hook that prunes everything but EOS
    from step 2 on truncates generation, changing ids and lens."""
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    V, T, bos, eos = 5, 6, 0, 4
    P = rng.dirichlet(np.ones(V), size=V).astype("float32")
    P[:, eos] = 1e-9                      # never stops on its own
    P /= P.sum(1, keepdims=True)

    # baseline: full-length generation
    ids_v, _, lens_v = _markov_program(P, 2, T, bos, eos)
    exe = pt.Executor()
    feed = {"P": P, "init": np.zeros((2, 1), "float32")}
    base_ids, base_lens = exe.run(feed=feed, fetch_list=[ids_v, lens_v])
    assert (np.asarray(base_lens) == T).all()

    def force_eos(t, info):
        # from step 2 on, -inf every candidate except the EOS column
        bias = jnp.where(jnp.arange(info["scores"].shape[-1]) == eos,
                         0.0, -1e30)[None, None, :]
        return jnp.where(t >= 2, bias, jnp.zeros_like(bias))

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    V2 = P.shape[0]
    Pvar = layers.data("P", shape=[V2, V2], dtype="float32",
                       append_batch_size=False)
    init = layers.data("init", shape=[1], dtype="float32")
    bs = layers.BeamSearchDecoder(beam_size=2, bos_id=bos, eos_id=eos,
                                  max_len=T, vocab_size=V2,
                                  step_hook=force_eos)
    with bs.step():
        tok = bs.token()
        mem = bs.memory(init=init)
        probs = layers.gather(Pvar, tok)
        bs.update_memory(mem, mem)
        bs.set_probs(probs)
    h_ids_v, _, h_lens_v = bs()
    exe2 = pt.Executor()
    h_ids, h_lens = exe2.run(feed=feed, fetch_list=[h_ids_v, h_lens_v])
    # generation stopped at the forced EOS: 2 real tokens + eos padding
    assert (np.asarray(h_lens) == 3).all(), h_lens
    assert (np.asarray(h_ids)[:, :, 2:] == eos).all()
    assert not np.array_equal(np.asarray(h_ids), np.asarray(base_ids))


def test_beam_hook_registry_roundtrip():
    """register_beam_hook/get_beam_hook: explicit names round-trip, the
    decoder accepts a registry NAME (not just a callable), and unknown
    names fail with the actionable KeyError."""
    import pytest

    calls = []

    def noop_hook(t, info):
        calls.append("traced")
        return None

    name = layers.register_beam_hook("unit_noop_hook", noop_hook)
    assert name == "unit_noop_hook"
    assert layers.get_beam_hook("unit_noop_hook") is noop_hook
    with pytest.raises(KeyError, match="not registered"):
        layers.get_beam_hook("no_such_hook")

    # a name-referenced no-op hook leaves generation unchanged
    rng = np.random.RandomState(0)
    V, T, bos, eos = 5, 3, 0, 4
    P = rng.dirichlet(np.ones(V), size=V).astype("float32")
    P[:, eos] = 1e-6
    P /= P.sum(1, keepdims=True)
    ids_v, _, _ = _markov_program(P, 2, T, bos, eos)
    exe = pt.Executor()
    feed = {"P": P, "init": np.zeros((1, 1), "float32")}
    (base_ids,) = exe.run(feed=feed, fetch_list=[ids_v])

    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    Pvar = layers.data("P", shape=[V, V], dtype="float32",
                       append_batch_size=False)
    init = layers.data("init", shape=[1], dtype="float32")
    bs = layers.BeamSearchDecoder(beam_size=2, bos_id=bos, eos_id=eos,
                                  max_len=T, vocab_size=V,
                                  step_hook="unit_noop_hook")
    with bs.step():
        tok = bs.token()
        mem = bs.memory(init=init)
        bs.update_memory(mem, mem)
        bs.set_probs(layers.gather(Pvar, tok))
    h_ids_v, _, _ = bs()
    (h_ids,) = pt.Executor().run(feed=feed, fetch_list=[h_ids_v])
    np.testing.assert_array_equal(np.asarray(h_ids), np.asarray(base_ids))
    assert calls        # the hook really ran inside the compiled scan


def test_greedy_kv_decode_agrees_with_beam_k1():
    """Bridge between the two generation paths (ISSUE 16): the KV-cache
    incremental greedy chain (serving.decode.DecodeEngine) and the
    compiled BeamSearchDecoder at beam_size=1 must pick the SAME token
    sequence when fed the same per-step distributions.  The engine's
    trajectory is replayed as a Markov table P[state] = softmax(logits
    emitted from that state), which is exactly the decoder's input
    contract — valid because the greedy chain visits distinct states."""
    from paddle_tpu.serving.decode import DecodeEngine

    eng = DecodeEngine(11, hidden_dim=10, n_layers=1, slots=2,
                       max_len=16, len_buckets=(16,), eos_id=None,
                       seed=9, name="g2b")
    V, n = eng.vocab_size, 4

    def chain(prompt):
        """Greedy tokens + the [n, V] logit rows that chose them."""
        eng.reset()
        tok, row = eng.prefill(0, prompt)
        rows, toks = [row], [tok]
        cur = np.zeros(2, np.int64)
        lens = np.zeros(2, np.int32)
        act = np.zeros(2, np.float32)
        cur[0], lens[0], act[0] = tok, len(prompt), 1.0
        for _ in range(n - 1):
            r = np.asarray(eng.decode_step(cur, lens, act)[0, 0],
                           "float32")
            toks.append(int(r.argmax()))
            rows.append(r)
            cur[0] = toks[-1]
            lens[0] += 1
        return toks, rows

    # find a prompt whose chain visits distinct states (so the Markov
    # replay is a well-defined function state -> next distribution)
    for pick in range(20):
        prompt = [3, 7, 1 + pick % (V - 1)]
        toks, rows = chain(prompt)
        states = toks[:-1]
        if len(set(states)) == len(states) and \
                len(set(states) | set(toks)) < V - 1:
            break
    else:
        raise AssertionError("no prompt produced a distinct-state chain")
    bos = next(i for i in range(V) if i not in states)
    eos = next(i for i in range(V) if i not in states + toks + [bos])

    P = np.full((V, V), 1.0 / V, "float32")
    for state, row in zip([bos] + states, rows):
        e = np.exp(row - row.max())
        P[state] = e / e.sum()
    P[:, eos] = 1e-9               # eos never argmax -> never emitted
    P /= P.sum(1, keepdims=True)

    ids_v, _, _ = _markov_program(P, 1, n, bos, eos)
    ids, = pt.Executor().run(
        feed={"P": P, "init": np.zeros((1, 1), "float32")},
        fetch_list=[ids_v])
    assert list(np.asarray(ids)[0, 0]) == toks


def test_dsl_exports_layer_meta():
    """LayerOutput/LayerType/BeamInput/convex_comb_layer exist in the DSL
    surface (reference layers.py __all__), and behave: layer outputs ARE
    LayerOutput instances, LayerType derives uncommon members."""
    import paddle_tpu.trainer_config_helpers as tch

    for n in ("LayerOutput", "LayerType", "BeamInput", "convex_comb_layer"):
        assert n in tch.__all__ and hasattr(tch, n)
    x = layers.data("meta_x", shape=[8], dtype="float32")
    assert isinstance(x, tch.LayerOutput)
    assert tch.LayerType.FC_LAYER == "fc"
    # non-lowercased protocol values reproduced exactly
    assert tch.LayerType.RANK_COST == "rank-cost"
    assert tch.LayerType.CROSS_ENTROPY == "multi-class-cross-entropy"
    assert tch.LayerType.POOL_LAYER == "pool"
    assert tch.convex_comb_layer is tch.linear_comb_layer
    bi = tch.BeamInput(x, x, x)
    assert bi.gold is x


def test_cross_entropy_over_beam_trains():
    """Beam-level training end to end (VERDICT r4 missing #3): a scorer
    trained with cross_entropy_over_beam learns to rank the gold candidate
    first; the off-beam case stays finite and pushes beam scores down."""
    from paddle_tpu.trainer_config_helpers import (BeamInput,
                                                   cross_entropy_over_beam)

    rng = np.random.RandomState(3)
    B, K, D = 8, 4, 6
    x = layers.data("x", shape=[D], dtype="float32")
    cand = layers.data("cand", shape=[K], dtype="int64")
    gold = layers.data("gold", shape=[1], dtype="int64")
    scores = layers.fc(x, size=K)
    cost = cross_entropy_over_beam([BeamInput(scores, cand, gold)])
    pt.optimizer.Adam(learning_rate=0.1).minimize(cost)

    xv = rng.randn(B, D).astype("float32")
    cv = np.tile(np.arange(K, dtype="int64")[None], (B, 1))
    # gold id: a fixed position per sample derived from x (learnable)
    gpos = (np.abs(xv[:, 0] * 10).astype("int64") % K)
    gv = cv[np.arange(B), gpos][:, None]
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feed = {"x": xv, "cand": cv, "gold": gv}
    vals = [float(exe.run(feed=feed, fetch_list=[cost])[0])
            for _ in range(40)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0] * 0.3
    (sc,) = exe.run(feed=feed, fetch_list=[scores], is_test=True)
    assert (np.argmax(sc, axis=1) == gpos).mean() >= 0.9

    # off-beam gold: finite loss through the virtual extra-path slot
    gv_off = np.full((B, 1), K + 7, "int64")
    (lv,) = exe.run(feed={"x": xv, "cand": cv, "gold": gv_off},
                    fetch_list=[cost], is_test=True)
    assert np.isfinite(float(lv)) and float(lv) > 0
