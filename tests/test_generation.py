"""Beam-search generation tests (reference: fluid test_beam_search_op.py,
test_beam_search_decode_op.py; RecurrentGradientMachine generation golden
tests trainer/tests/test_recurrent_machine_generation.cpp)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers, models


def _markov_program(P, beam_size, max_len, bos, eos):
    """Decoder whose next-token distribution depends only on the current
    token: probs = P[token] — exactly computable in numpy."""
    V = P.shape[0]
    Pvar = layers.data("P", shape=[V, V], dtype="float32",
                       append_batch_size=False)
    init = layers.data("init", shape=[1], dtype="float32")
    bs = layers.BeamSearchDecoder(beam_size=beam_size, bos_id=bos,
                                  eos_id=eos, max_len=max_len, vocab_size=V)
    with bs.step():
        tok = bs.token()
        mem = bs.memory(init=init)
        probs = layers.gather(Pvar, tok)
        bs.update_memory(mem, mem)
        bs.set_probs(probs)
    return bs()


def test_beam_k1_matches_greedy_chain():
    rng = np.random.RandomState(0)
    V, T, bos, eos = 5, 4, 0, 4
    P = rng.dirichlet(np.ones(V), size=V).astype("float32")
    P[:, eos] = 1e-6           # never stop
    P /= P.sum(1, keepdims=True)
    ids_v, scores_v, lens_v = _markov_program(P, 1, T, bos, eos)
    exe = pt.Executor()
    ids, scores = exe.run(feed={"P": P, "init": np.zeros((2, 1), "float32")},
                          fetch_list=[ids_v, scores_v])
    tok, exp_ids, exp_score = bos, [], 0.0
    for _ in range(T):
        nxt = int(np.argmax(P[tok]))
        exp_score += np.log(P[tok, nxt])
        exp_ids.append(nxt)
        tok = nxt
    for b in range(2):
        np.testing.assert_array_equal(ids[b, 0], exp_ids)
        np.testing.assert_allclose(scores[b, 0], exp_score, rtol=1e-4)


def test_beam_finds_better_than_greedy():
    """Classic beam > greedy setup: a low-prob first step leads to a
    near-deterministic tail."""
    V, bos, eos = 4, 0, 3
    P = np.full((V, V), 1e-9, "float32")
    # from bos: token1 p=0.6, token2 p=0.4
    P[0, 1], P[0, 2] = 0.6, 0.4
    # token1 -> uniform-ish continuations (greedy path gets stuck cheap)
    P[1, 1], P[1, 2] = 0.5, 0.5
    # token2 -> token2 with p ~1 (the good tail)
    P[2, 2] = 1.0
    P /= P.sum(1, keepdims=True)
    T = 3
    ids_v, scores_v, _ = _markov_program(P, 2, T, bos, eos)
    exe = pt.Executor()
    ids, scores = exe.run(feed={"P": P, "init": np.zeros((1, 1), "float32")},
                          fetch_list=[ids_v, scores_v])
    # best: 2,2,2 with logp log(.4)  vs greedy 1,... log(.6)+2*log(.5)
    np.testing.assert_array_equal(ids[0, 0], [2, 2, 2])
    assert scores[0, 0] >= scores[0, 1] - 1e-6
    np.testing.assert_allclose(scores[0, 0], np.log(0.4), rtol=1e-4)


def test_beam_eos_freezes_score():
    V, bos, eos = 3, 0, 2
    P = np.full((V, V), 1e-9, "float32")
    P[0, 2] = 0.9            # bos -> eos
    P[0, 1] = 0.1
    P[1, 1] = 1.0
    P /= P.sum(1, keepdims=True)
    ids_v, scores_v, lens_v = _markov_program(P, 2, 5, bos, eos)
    exe = pt.Executor()
    ids, scores, lens = exe.run(
        feed={"P": P, "init": np.zeros((1, 1), "float32")},
        fetch_list=[ids_v, scores_v, lens_v])
    np.testing.assert_array_equal(ids[0, 0], [2] * 5)      # eos then frozen
    np.testing.assert_allclose(scores[0, 0], np.log(P[0, 2]), rtol=1e-4)
    assert int(lens[0, 0]) == 1


def test_seq2seq_train_then_beam_decode(rng):
    """Micro machine-translation book test: learn 'always emit token 3'
    then check the decoder's top beam starts with it."""
    V, H = 8, 16
    src = layers.data("src", shape=[], dtype="int64", lod_level=1)
    tgt = layers.data("tgt", shape=[], dtype="int64", lod_level=1)
    lbl = layers.data("lbl", shape=[], dtype="int64", lod_level=1)
    probs = models.seq2seq_attention(src, tgt, V, V, emb_dim=8, hidden_dim=H)
    flat = layers.reshape(probs, [-1, V])
    loss = layers.mean(layers.cross_entropy(
        flat, layers.reshape(lbl, [-1, 1])))
    opt = pt.optimizer.Adam(0.05)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    B, Ts, Tt = 8, 5, 4
    feeds = {"src": rng.randint(2, V, (B, Ts)),
             "src@LEN": np.full(B, Ts),
             "tgt": np.full((B, Tt), 3),
             "tgt@LEN": np.full(B, Tt),
             "lbl": np.full((B, Tt), 3),
             "lbl@LEN": np.full(B, Tt)}
    losses = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
              for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5

    infer_prog = pt.Program()
    with pt.program_guard(infer_prog, pt.Program()):
        src_i = layers.data("src", shape=[], dtype="int64", lod_level=1)
        ids_v, scores_v, lens_v = models.seq2seq_infer(
            src_i, V, V, emb_dim=8, hidden_dim=H, beam_size=3, bos_id=0,
            eos_id=1, max_len=4)
    ids, scores = exe.run(infer_prog,
                          feed={"src": rng.randint(2, V, (2, Ts)),
                                "src@LEN": np.full(2, Ts)},
                          fetch_list=[ids_v, scores_v], is_test=True)
    assert ids.shape == (2, 3, 4)
    assert (scores[:, 0] + 1e-6 >= scores[:, 1]).all()
    assert (ids[:, 0, 0] == 3).all()
