"""Unified runtime observability (paddle_tpu.observability).

Pins the PR's acceptance contract:

* metrics registry semantics (typed, frozen names, thread-safe);
* ZERO overhead when off — no registry writes and no retraces in the
  stepped hot path with ``observe=False``;
* with ``observe=True`` a run_pipelined training loop produces step-time
  histograms, queue-depth/stall metrics, staging times, and a parseable
  JSONL log that ``python -m paddle_tpu stats`` summarizes;
* XProf annotations wrap dispatches with program-attributable names;
* NaN provenance: a poisoned op is named by the eager bisect;
* the trainer's periodic reports fire on the ``log_period`` cadence.
"""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, layers
from paddle_tpu import observability as obs
from paddle_tpu.core.compile_cache import retrace_guard
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import metrics as obs_metrics


@pytest.fixture(autouse=True)
def clean_observability():
    """Fresh registry + restored flags + closed JSONL writer per test."""
    obs.registry().reset()
    prev = {n: flags.get_flag(n)
            for n in ("observe", "metrics_log", "log_period")}
    yield
    for n, v in prev.items():
        flags.set_flag(n, v)
    obs_export._reset_writer()
    obs.registry().reset()


def _counters_total(snap):
    return sum(s["value"] for s in snap.values() if s["kind"] == "counter")


def _hist_total(snap):
    return sum(s["count"] for s in snap.values()
               if s["kind"] == "histogram")


def _gauges_total(snap):
    return sum(len(s["values"]) for s in snap.values()
               if s["kind"] == "gauge")


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_registry_counter_gauge_histogram_roundtrip():
    obs.inc_counter("executor/steps", 3)
    obs.inc_counter("executor/steps")
    obs.set_gauge("executor/examples_per_sec", 123.5)
    obs.set_gauge("device/bytes_in_use", 10, label="tpu:0")
    obs.set_gauge("device/bytes_in_use", 20, label="tpu:1")
    for v in (0.3, 4.0, 4.0, 900.0):
        obs.observe_hist("executor/step_time_ms", v)
    snap = obs.registry().snapshot()
    assert snap["executor/steps"]["value"] == 4
    assert snap["executor/examples_per_sec"]["values"][""] == 123.5
    assert snap["device/bytes_in_use"]["values"] == {"tpu:0": 10.0,
                                                     "tpu:1": 20.0}
    h = snap["executor/step_time_ms"]
    assert h["count"] == 4 and h["min"] == 0.3 and h["max"] == 900.0
    assert h["sum"] == pytest.approx(908.3)
    assert sum(h["counts"]) == 4
    assert len(h["counts"]) == len(h["boundaries"]) + 1
    # fixed boundaries: 4.0 falls in the bucket with edge 5.0
    assert h["counts"][h["boundaries"].index(5.0)] == 2


def test_registry_rejects_unknown_names_and_kind_mismatch():
    with pytest.raises(KeyError, match="frozen"):
        obs.inc_counter("executor/step_tmie_ms")      # typo'd
    with pytest.raises(TypeError, match="histogram"):
        obs.inc_counter("executor/step_time_ms")      # wrong kind
    with pytest.raises(TypeError, match="counter"):
        obs.observe_hist("executor/steps", 1.0)


def test_registry_thread_safety_exact_counts():
    n_threads, n_iters = 8, 1000

    def work():
        for _ in range(n_iters):
            obs.inc_counter("executor/steps")
            obs.observe_hist("pipeline/queue_depth", 1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = obs.registry().snapshot()
    assert snap["executor/steps"]["value"] == n_threads * n_iters
    assert snap["pipeline/queue_depth"]["count"] == n_threads * n_iters


def test_histogram_quantile_walks_buckets():
    for v in [1.0] * 50 + [30.0] * 50:
        obs.observe_hist("pipeline/queue_depth", v)
    snap = obs.registry().snapshot()["pipeline/queue_depth"]
    assert obs_metrics.histogram_quantile(snap, 0.25) == 1.0
    assert obs_metrics.histogram_quantile(snap, 0.9) == 32.0


def test_report_renders_nonempty_metrics():
    obs.inc_counter("executor/steps", 2)
    obs.observe_hist("executor/step_time_ms", 5.0)
    rep = obs.report()
    assert "executor/steps: 2" in rep
    assert "executor/step_time_ms" in rep and "p50=" in rep


# ---------------------------------------------------------------------------
# zero overhead when off (acceptance-pinned)
# ---------------------------------------------------------------------------
def _build_net():
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=16, act="relu")
    h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _batches(n, batch=16):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 8).astype("float32"),
             "y": rng.randint(0, 3, (batch, 1))} for _ in range(n)]


def test_observe_off_zero_registry_writes_and_zero_retrace():
    flags.set_flag("observe", False)
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = _batches(5)
    before = obs.registry().snapshot()
    exe.run(feed=feeds[0], fetch_list=[loss])       # pays the one trace
    with retrace_guard():                           # then: NO retraces
        for f in feeds[1:]:
            exe.run(feed=f, fetch_list=[loss])
        outs = list(exe.run_pipelined(
            iter(_batches(8)), pt.default_main_program(),
            fetch_list=[loss], steps_per_dispatch=4))
    assert len(outs) == 8
    after = obs.registry().snapshot()
    # the hot path never touched the registry: counter/histogram/gauge
    # deltas are all EXACTLY zero
    assert _counters_total(after) == _counters_total(before) == 0
    assert _hist_total(after) == _hist_total(before) == 0
    assert _gauges_total(after) == _gauges_total(before) == 0


def test_observe_flip_does_not_retrace_or_change_math():
    """observe=True must be host-side only: same fingerprints (no new
    trace when flipped mid-run), bit-identical fetches."""
    loss = _build_net()
    exe = pt.Executor(observe=False)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = _batches(4)
    off = [exe.run(feed=f, fetch_list=[loss])[0] for f in feeds[:2]]
    with retrace_guard():        # flipping observe may not re-trace
        exe.observe = True
        on = [exe.run(feed=f, fetch_list=[loss])[0] for f in feeds[2:]]
    assert np.isfinite(off).all() and np.isfinite(on).all()
    snap = obs.registry().snapshot()
    assert snap["executor/steps"]["value"] == 2   # only observed steps
    assert snap["executor/step_time_ms"]["count"] == 2


# ---------------------------------------------------------------------------
# observe=True: pipelined loop -> histograms + JSONL + stats CLI
# ---------------------------------------------------------------------------
def test_pipelined_loop_metrics_jsonl_and_stats_cli(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with retrace_guard():       # instrumentation must not retrace either
        outs = list(exe.run_pipelined(
            iter(_batches(10)), pt.default_main_program(),
            fetch_list=[loss], steps_per_dispatch=4))
        # second chunked run hits the cached variants
        list(exe.run_pipelined(
            iter(_batches(10)), pt.default_main_program(),
            fetch_list=[loss], steps_per_dispatch=4))
    assert len(outs) == 10
    snap = obs.registry().snapshot()
    # step-time histograms from the scan dispatches + tail singles
    assert snap["executor/step_time_ms"]["count"] >= 4
    assert snap["executor/dispatch_steps"]["max"] == 4
    assert snap["executor/steps"]["value"] == 21  # startup + 2x10
    assert snap["executor/feed_bytes"]["value"] > 0
    assert snap["executor/stage_put_ms"]["count"] >= 4
    # pipeline engine signals: sampled depth + consumer stalls + busy split
    assert snap["pipeline/queue_depth"]["count"] > 0
    assert snap["pipeline/consumer_stall_ms"]["count"] > 0
    assert snap["pipeline/worker_busy_s"]["value"] > 0
    assert snap["executor/examples_per_sec"]["values"][""] > 0
    obs.periodic_report(step=20)           # snapshot event for the CLI

    lines = [json.loads(ln) for ln in log.read_text().splitlines()]
    kinds = {ln["kind"] for ln in lines}
    assert "step" in kinds and "snapshot" in kinds
    step_events = [ln for ln in lines if ln["kind"] == "step"]
    # cold dispatches (compile inside the call) are tagged and excluded
    # from step timing; warm ones carry real per-step times
    assert any(ln["cold_compile"] for ln in step_events)
    warm = [ln for ln in step_events if not ln["cold_compile"]]
    assert warm and all(ln["step_ms"] > 0 for ln in warm)
    assert all(ln["step_ms"] is None for ln in step_events
               if ln["cold_compile"])
    assert any(ln["steps"] == 4 and ln["path"] == "run_steps"
               for ln in step_events)

    from paddle_tpu.cli import main as cli_main
    assert cli_main(["stats", str(log)]) == 0
    out = capsys.readouterr().out
    assert "dispatches" in out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["steps"]["steps"] == 21
    assert summary["snapshots"] == 1
    assert summary["last_snapshot"]["histograms"][
        "executor/step_time_ms"]["count"] >= 4
    assert summary["last_snapshot"]["worker_busy_fraction"] is not None


def test_run_steps_metrics_report_per_step_time():
    flags.set_flag("observe", True)
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    stacked = pt.stack_feeds(_batches(6))
    exe.run_steps(6, feed=stacked, fetch_list=[loss], feeds_stacked=True)
    snap = obs.registry().snapshot()
    # both dispatches so far were COLD (first trace of each variant):
    # their wall time is compile-dominated and stays out of the histogram
    assert snap["executor/steps"]["value"] == 7        # startup + 6
    assert snap["executor/dispatch_steps"]["max"] == 6
    assert snap["executor/step_time_ms"]["count"] == 0
    exe.run_steps(6, feed=stacked, fetch_list=[loss], feeds_stacked=True)
    snap = obs.registry().snapshot()
    # the warm re-dispatch records real step time + throughput
    assert snap["executor/step_time_ms"]["count"] == 1
    # examples/sec uses the PER-STEP batch dim of stacked feeds (16), not
    # the leading K axis: 16*6 examples over a sub-second dispatch
    assert snap["executor/examples_per_sec"]["values"][""] > 0


def test_xprof_annotations_wrap_dispatch(monkeypatch):
    import jax
    names = []

    class FakeAnn:
        def __init__(self, name, **kw):
            names.append((name, kw))

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(jax.profiler, "TraceAnnotation", FakeAnn)
    monkeypatch.setattr(jax.profiler, "StepTraceAnnotation", FakeAnn)
    flags.set_flag("observe", True)
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    exe.run(feed=_batches(1)[0], fetch_list=[loss])
    ann = [n for n, _ in names if n.startswith("pt:run:")]
    assert ann, f"no pt:run annotation in {names}"
    # program-attributable: carries a fingerprint prefix
    assert len(ann[-1].split(":")[2]) == 12
    assert any(n == "paddle_tpu/step" and "step_num" in kw
               for n, kw in names)


def test_sharded_observe_label_names_mesh():
    from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh
    mesh = make_mesh(MeshConfig(dp=8))
    exe = ShardedExecutor(mesh=mesh)
    assert exe._observe_label() == "mesh=dp8"
    assert exe._trace_name("run", "abcdef0123456789").endswith(":mesh=dp8")


# ---------------------------------------------------------------------------
# NaN provenance
# ---------------------------------------------------------------------------
def test_nan_provenance_names_poisoned_forward_op(tmp_path):
    flags.set_flag("metrics_log", str(tmp_path / "nan.jsonl"))
    x = layers.data("x", shape=[4], dtype="float32")
    h = layers.scale(x, scale=0.0)
    bad = layers.log(h)                     # log(0) -> -inf
    loss = layers.mean(bad)
    exe = pt.Executor(check_nan_inf=True)
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
    msg = str(ei.value)
    assert "NaN provenance" in msg
    assert "'log'" in msg and bad.name in msg
    assert "8 Inf" in msg
    events = [json.loads(ln)
              for ln in (tmp_path / "nan.jsonl").read_text().splitlines()]
    nan_ev = [e for e in events if e["kind"] == "nan"]
    assert nan_ev and nan_ev[0]["op_type"] == "log"
    assert nan_ev[0]["var"] == bad.name
    assert nan_ev[0]["phase"] == "forward"


def test_nan_provenance_bisects_training_program():
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, size=4, act="relu")
    z = layers.log(layers.scale(h, scale=0.0))   # poisoned forward slice
    pred = layers.reduce_sum(z, dim=1, keep_dim=True)
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor(check_nan_inf=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": np.ones((2, 4), np.float32),
                      "y": np.ones((2, 1), np.float32)},
                fetch_list=[loss])
    msg = str(ei.value)
    assert "NaN provenance" in msg and "'log'" in msg
    assert "phase forward" in msg


def test_nan_provenance_reports_poisoned_feed():
    x = layers.data("x", shape=[4], dtype="float32")
    out = layers.scale(x, scale=2.0)
    exe = pt.Executor(check_nan_inf=True)
    feed = np.ones((2, 4), np.float32)
    feed[0, 0] = np.nan
    with pytest.raises(FloatingPointError) as ei:
        exe.run(feed={"x": feed}, fetch_list=[out])
    assert "phase feed" in str(ei.value)
    assert "'x'" in str(ei.value)


def test_nan_event_counter_gated_by_observe():
    flags.set_flag("observe", True)
    x = layers.data("x", shape=[2], dtype="float32")
    bad = layers.log(layers.scale(x, scale=0.0))
    exe = pt.Executor(check_nan_inf=True)
    with pytest.raises(FloatingPointError):
        exe.run(feed={"x": np.ones((1, 2), np.float32)}, fetch_list=[bad])
    assert obs.registry().snapshot()["executor/nan_events"]["value"] == 1


# ---------------------------------------------------------------------------
# trainer log_period wiring
# ---------------------------------------------------------------------------
def test_trainer_periodic_reports_fire_on_log_period(tmp_path):
    from paddle_tpu import trainer as trainer_mod
    log = tmp_path / "train.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    flags.set_flag("log_period", 5)

    def reader():
        rng = np.random.RandomState(1)
        for _ in range(12):
            xb = rng.rand(8, 5).astype("float32")
            yb = (xb.sum(axis=1, keepdims=True)
                  + 0.01 * rng.randn(8, 1)).astype("float32")
            yield [(xb[i], yb[i]) for i in range(8)]

    x = layers.data("x", shape=[5], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    cost = layers.mean(layers.square_error_cost(pred, y))
    sgd = trainer_mod.SGD(cost, update_equation=pt.optimizer.SGD(
        learning_rate=0.01))
    sgd.train(reader, num_passes=1, feed_list=[x, y])
    # 12 iterations at log_period=5 -> reports after #5 and #10
    assert obs.registry().snapshot()["trainer/reports"]["value"] == 2
    events = [json.loads(ln) for ln in log.read_text().splitlines()]
    snaps = [e for e in events if e["kind"] == "snapshot"]
    assert [s["step"] for s in snaps] == [5, 10]


def test_periodic_report_noop_when_not_observing(tmp_path):
    flags.set_flag("observe", False)
    flags.set_flag("log_period", 1)
    flags.set_flag("metrics_log", str(tmp_path / "off.jsonl"))
    assert obs.maybe_periodic_report(5) is False
    assert not (tmp_path / "off.jsonl").exists()
    # explicit observing=True overrides the off flag (Executor(observe=..))
    assert obs.maybe_periodic_report(5, observing=True) is True
    assert (tmp_path / "off.jsonl").exists()


# ---------------------------------------------------------------------------
# snapshot / export plumbing
# ---------------------------------------------------------------------------
def test_metrics_snapshot_merges_compile_counters():
    snap = obs.metrics_snapshot()
    assert set(snap) == {"metrics", "compile", "device_memory"}
    assert all(k.startswith("compile/") for k in snap["compile"])
    assert set(snap["metrics"]) == {n for n, _, _ in obs.METRIC_NAMES}
    json.dumps(snap)                     # JSON-serializable end to end


def test_stats_cli_rejects_missing_file(capsys):
    from paddle_tpu.cli import main as cli_main
    with pytest.raises(SystemExit, match="cannot read"):
        cli_main(["stats", "/nonexistent/run.jsonl"])


def test_metrics_log_unwritable_path_disables_quietly():
    """An unwritable log path must disable export, not crash the observed
    hot path on the SECOND event (regression: the disabled writer used to
    raise AttributeError on every emit after the first failure)."""
    flags.set_flag("metrics_log", "/nonexistent_dir/obs/x.jsonl")
    obs.emit_event("step", steps=1)      # open fails -> disables
    obs.emit_event("step", steps=2)      # must be a silent no-op
    obs.emit_event("nan", op_type="log")


def test_worker_busy_counters_visible_mid_run(monkeypatch):
    """Busy/wait counters flush periodically, not only at worker exit —
    a live pipeline's snapshot must carry them."""
    from paddle_tpu.reader import pipeline as pl
    from paddle_tpu.reader.pipeline import prefetch
    monkeypatch.setattr(pl, "_FLUSH_EVERY", 1)
    g = prefetch(lambda: iter(range(10 ** 6)), buffer_size=2,
                 num_workers=1, instrument=True)()
    try:
        for _ in range(8):
            next(g)
        snap = obs.registry().snapshot()
        assert snap["pipeline/worker_busy_s"]["value"] > 0
    finally:
        g.close()


def test_check_nan_inf_steps_do_not_donate_state():
    """check_nan_inf variants keep state buffers alive (donate=False), so
    the provenance bisect sees true pre-step values with no per-step host
    snapshot — and healthy steps keep training normally."""
    loss = _build_net()
    exe = pt.Executor(check_nan_inf=True)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    scope = pt.global_scope()
    key = next(k for k in scope.keys() if k.endswith("w_0"))
    for f in _batches(3):
        before = scope.get(key)
        exe.run(feed=f, fetch_list=[loss])
        assert not (hasattr(before, "is_deleted") and before.is_deleted())
        assert not np.array_equal(np.asarray(before),
                                  np.asarray(scope.get(key)))


def test_summarize_log_tolerates_corrupt_lines(tmp_path):
    p = tmp_path / "x.jsonl"
    p.write_text('{"ts": 1.0, "kind": "step", "steps": 2, "step_ms": 3.0,'
                 ' "wall_ms": 6.0}\nnot json\n')
    s = obs.summarize_log(str(p))
    assert s["corrupt_lines"] == 1
    assert s["steps"]["steps"] == 2
