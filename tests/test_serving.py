"""Serving runtime: batching correctness + the degradation matrix
(ISSUE 8): deadline expiry never dispatches, overload sheds oldest
deadline first, a poisoned model's breaker opens while the healthy
tenant keeps serving, graceful drain completes every admitted request,
and the zero-cost-when-unused guard (training paths byte-identical with
serving loaded).

Deterministic by construction: the degradation tests drive a FAKE model
(a plain callable) gated on threading.Events, so "the server is busy
dispatching" and "the queue is full" are facts, not race outcomes.
Subprocess rounds (SIGTERM drain, supervised relaunch) live in
tests/test_serving_chaos.py under @pytest.mark.slow.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import faults, layers
from paddle_tpu.core.executor import pad_batch, stack_feeds
from paddle_tpu.serving import (DeadlineExceeded, Model, ModelError,
                                ModelUnavailable, Overloaded, Server,
                                ServerClosed)
from paddle_tpu.testing import faultinject


@pytest.fixture(autouse=True)
def _clear_injection():
    yield
    faultinject.clear()


# ---------------------------------------------------------------------------
# Fakes: deterministic models with per-dispatch gating
# ---------------------------------------------------------------------------
class FakeModel:
    """Row-wise fake tenant: output = feeds['x'] * 2.  ``gate`` (when
    set) blocks each dispatch until released; ``fail`` is a list of
    exceptions to raise, one per dispatch, None = succeed."""

    def __init__(self, name="fake", gate=False, fail=None):
        self.calls = []                  # list of batch sizes dispatched
        self.rows = []                   # all rows ever computed
        self.gate = threading.Event() if gate else None
        self.release_all = False
        self.fail = list(fail or [])
        self.model = Model(name, self._fn,
                           example={"x": np.zeros(2, "float32")})

    def _fn(self, feeds):
        if self.gate is not None and not self.release_all:
            if not self.gate.wait(timeout=10):
                raise RuntimeError("FakeModel gate never released")
            self.gate.clear()
        if self.fail:
            err = self.fail.pop(0)
            if err is not None:
                self.calls.append(int(feeds["x"].shape[0]))
                raise err
        x = np.asarray(feeds["x"])
        self.calls.append(int(x.shape[0]))
        self.rows.extend(x[:, 0].tolist())
        return [x * 2.0]

    def release(self):
        self.gate.set()

    def open_gate_forever(self):
        self.release_all = True
        self.gate.set()


def _mk_server(fake, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("deadline_ms", 5000.0)
    kw.setdefault("queue_capacity", 16)
    kw.setdefault("warmup", False)
    srv = Server(**kw)
    models = fake if isinstance(fake, (list, tuple)) else [fake]
    for m in models:
        srv.add_model(m.model if isinstance(m, FakeModel) else m)
    srv.start()
    return srv


def _req(i, dim=2):
    return {"x": np.full(dim, float(i), "float32")}


# ---------------------------------------------------------------------------
# pad_batch / bucketing
# ---------------------------------------------------------------------------
def test_pad_batch_repeats_first_row():
    stacked = stack_feeds([{"x": np.array([1.0, 2.0])},
                           {"x": np.array([3.0, 4.0])}])
    padded = pad_batch(stacked, 4)
    assert padded["x"].shape == (4, 2)
    np.testing.assert_array_equal(padded["x"][2], padded["x"][0])
    np.testing.assert_array_equal(padded["x"][3], padded["x"][0])
    # no-op at target, rejects shrink
    assert pad_batch(stacked, 2)["x"].shape == (2, 2)
    with pytest.raises(ValueError, match="rows"):
        pad_batch(stacked, 1)


def test_buckets_are_powers_of_two_up_to_max():
    from paddle_tpu.serving.server import _bucket_for, _buckets
    assert _buckets(8) == [1, 2, 4, 8]
    assert _buckets(12) == [1, 2, 4, 8, 12]
    assert _bucket_for(3, [1, 2, 4, 8]) == 4
    assert _bucket_for(9, [1, 2, 4, 8]) == 8


# ---------------------------------------------------------------------------
# Batching correctness on a REAL program-backed model
# ---------------------------------------------------------------------------
def test_batched_responses_match_direct_execution():
    x = layers.data("x", shape=[8], dtype="float32")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=4, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    m = Model.from_program(exe, pt.default_main_program(), [pred],
                           name="mlp",
                           example={"x": np.zeros(8, "float32")})
    srv = Server(max_batch=4, max_wait_ms=20.0, deadline_ms=None,
                 queue_capacity=64)
    srv.add_model(m)
    srv.start()
    assert srv.state == "ready" and srv.ready()
    try:
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.rand(8).astype("float32")} for _ in range(6)]
        pendings = [srv.submit(f) for f in feeds]
        outs = np.stack([p.result(timeout=30)[0] for p in pendings])
        ref = exe.run(pt.default_main_program(),
                      feed={"x": np.stack([f["x"] for f in feeds])},
                      fetch_list=[pred], is_test=True)
        # coalesced + padded batching must not change the math
        np.testing.assert_allclose(outs, ref[0], rtol=0, atol=0)
        h = srv.health()
        assert h["models"]["mlp"]["served"] == 6
        assert h["models"]["mlp"]["batches"] >= 2   # 6 reqs, max_batch 4
    finally:
        srv.shutdown(drain=True)
    assert srv.state == "stopped"


def test_padded_rows_are_sliced_out():
    fake = FakeModel()
    srv = _mk_server(fake, max_batch=4, max_wait_ms=50.0)
    try:
        ps = [srv.submit(_req(i)) for i in range(3)]   # 3 -> bucket 4
        outs = [p.result(timeout=10) for p in ps]
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o[0], np.full(2, 2.0 * i))
        assert fake.calls == [4]                       # padded dispatch
    finally:
        srv.shutdown()


def test_mixed_signatures_never_stack():
    fake = FakeModel()
    srv = _mk_server(fake, max_batch=8, max_wait_ms=100.0)
    try:
        a = srv.submit({"x": np.zeros(2, "float32")})
        b = srv.submit({"x": np.zeros(3, "float32")})   # different shape
        a.result(timeout=10)
        b.result(timeout=10)
        assert sorted(fake.calls) == [1, 1]             # two dispatches
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------
def test_expired_request_never_dispatches():
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1)
    try:
        r1 = srv.submit(_req(1))                  # occupies the dispatcher
        time.sleep(0.02)                          # r1 reaches the gate
        r2 = srv.submit(_req(2), deadline_ms=1.0)
        time.sleep(0.05)                          # r2's deadline passes
        fake.open_gate_forever()
        r1.result(timeout=10)
        with pytest.raises(DeadlineExceeded):
            r2.result(timeout=10)
        # THE contract: the expired request's row was never computed
        assert 2.0 not in fake.rows
    finally:
        srv.shutdown()


def test_deadline_none_disables_expiry():
    fake = FakeModel()
    srv = _mk_server(fake, deadline_ms=None)
    try:
        out = srv.infer(_req(7), timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 14.0))
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Admission control + shedding
# ---------------------------------------------------------------------------
def _soak_pipeline(srv, n=3, deadline_ms=60000.0):
    """Fill the dispatcher (gated model), the staging queue and the
    batcher's hands, so subsequent submits ACCUMULATE in the admission
    queue — makes queue-full a deterministic fact, not a race.  With
    max_batch=1 and staging_depth=1 that is 3 requests: one dispatching,
    one staged, one held by the blocked batcher."""
    held = []
    for i in range(n):
        held.append(srv.submit(_req(1000 + i), deadline_ms=deadline_ms))
        time.sleep(0.05)
    return held


def test_overload_sheds_oldest_deadline_first():
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, queue_capacity=2,
                     deadline_ms=None, staging_depth=1)
    try:
        held = _soak_pipeline(srv)
        r2 = srv.submit(_req(2), deadline_ms=1000.0)  # queued, soonest
        r3 = srv.submit(_req(3), deadline_ms=5000.0)  # queued -> full
        r4 = srv.submit(_req(4), deadline_ms=9000.0)  # -> shed r2
        with pytest.raises(Overloaded):
            r2.result(timeout=10)
        fake.open_gate_forever()
        for r in held + [r3, r4]:
            assert r.result(timeout=10) is not None
        assert 2.0 not in fake.rows                   # shed = never computed
        snap = pt.observability.registry().snapshot()
        assert snap["serving/shed"]["value"] >= 1
    finally:
        fake.open_gate_forever()
        srv.shutdown()


def test_incoming_with_soonest_deadline_is_rejected():
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, queue_capacity=1,
                     deadline_ms=None, staging_depth=1)
    try:
        held = _soak_pipeline(srv)
        rq = srv.submit(_req(2), deadline_ms=9000.0)  # fills the queue
        with pytest.raises(Overloaded):
            srv.submit(_req(3), deadline_ms=10.0)     # soonest -> rejected
        fake.open_gate_forever()
        for r in held + [rq]:
            assert r.result(timeout=10) is not None
    finally:
        fake.open_gate_forever()
        srv.shutdown()


def test_backpressure_without_shedding_rejects_newcomer():
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, queue_capacity=1, shed=False,
                     deadline_ms=None, staging_depth=1)
    try:
        held = _soak_pipeline(srv, deadline_ms=10000.0)
        rq = srv.submit(_req(2), deadline_ms=10000.0)
        with pytest.raises(Overloaded):
            srv.submit(_req(3), deadline_ms=90000.0)  # latest, still shed
        fake.open_gate_forever()
        for r in held + [rq]:
            assert r.result(timeout=10) is not None
    finally:
        fake.open_gate_forever()
        srv.shutdown()


# ---------------------------------------------------------------------------
# Circuit breaker: poisoned tenant vs healthy tenant
# ---------------------------------------------------------------------------
def test_breaker_opens_on_poisoned_model_healthy_tenant_serves():
    poisoned = FakeModel(name="bad",
                         fail=[ValueError("shape mismatch (poisoned)"),
                               ValueError("shape mismatch (poisoned)")])
    healthy = FakeModel(name="good")
    srv = _mk_server([poisoned, healthy], max_batch=1,
                     breaker_threshold=2, breaker_cooldown_s=3600.0)
    try:
        for _ in range(2):
            with pytest.raises(ModelError, match="poisoned"):
                srv.infer(_req(1), model="bad", timeout=10)
        # breaker is now open: fail fast at admission, no dispatch
        with pytest.raises(ModelUnavailable):
            srv.submit(_req(2), model="bad")
        assert srv.health()["models"]["bad"]["breaker"] == "open"
        # the healthy co-tenant is untouched
        out = srv.infer(_req(5), model="good", timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 10.0))
        assert srv.health()["models"]["good"]["breaker"] == "closed"
        snap = pt.observability.registry().snapshot()
        assert snap["serving/breaker_open"]["value"] >= 1
    finally:
        srv.shutdown()


def test_breaker_half_open_probe_recovers():
    flaky = FakeModel(name="flaky", fail=[ValueError("boom"),
                                          ValueError("boom")])
    srv = _mk_server(flaky, max_batch=1, breaker_threshold=2,
                     breaker_cooldown_s=0.05)
    try:
        for _ in range(2):
            with pytest.raises(ModelError):
                srv.infer(_req(1), timeout=10)
        assert srv.health()["models"]["flaky"]["breaker"] == "open"
        time.sleep(0.08)                       # cooldown -> half_open
        assert srv.health()["models"]["flaky"]["breaker"] == "half_open"
        out = srv.infer(_req(3), timeout=10)   # probe succeeds
        np.testing.assert_array_equal(out[0], np.full(2, 6.0))
        assert srv.health()["models"]["flaky"]["breaker"] == "closed"
    finally:
        srv.shutdown()


def test_non_row_wise_model_fails_typed_without_killing_dispatcher():
    """A model whose outputs cannot be row-sliced (scalar fetch) is a
    MODEL failure: its requests complete with ModelError, the breaker
    counts it, and the dispatcher thread survives to serve the next
    batch (a dead dispatcher would wedge staging and hang drain)."""
    class ScalarModel(FakeModel):
        def _fn(self, feeds):
            self.calls.append(int(np.asarray(feeds["x"]).shape[0]))
            if len(self.calls) == 1:
                return [np.float32(1.0)]       # not [B, ...]-indexable
            return [np.asarray(feeds["x"]) * 2.0]

    m = ScalarModel(name="scalar")
    srv = _mk_server(m, max_batch=1, breaker_threshold=10)
    try:
        with pytest.raises(ModelError):
            srv.infer(_req(1), timeout=10)
        # dispatcher alive: the next (well-formed) dispatch serves
        out = srv.infer(_req(3), timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 6.0))
        srv.shutdown(drain=True, timeout=30)   # and drain does not hang
        assert srv.state == "stopped"
    finally:
        if srv.state != "stopped":
            srv.shutdown(drain=False)


def test_malformed_feeds_rejected_at_admission_not_breaker():
    """Missing/mis-shaped inputs on a spec-carrying model reject at
    submit (per-request), never reach dispatch, never feed the shared
    circuit breaker — one bad client cannot open the tenant's breaker."""
    specs = {"x": {"shape": [None, 2], "dtype": "float32"},
             "y": {"shape": [None, 3], "dtype": "float32"}}
    m = Model("specced", lambda feeds: [np.asarray(feeds["x"]) * 2.0],
              input_specs=specs)
    srv = _mk_server(m, max_batch=1, breaker_threshold=1)
    try:
        with pytest.raises(ValueError, match="missing inputs"):
            srv.submit({"x": np.zeros(2, "float32")})     # no 'y'
        with pytest.raises(ValueError, match="does not match declared"):
            srv.submit({"x": np.zeros(5, "float32"),      # wrong shape
                        "y": np.zeros(3, "float32")})
        with pytest.raises(ValueError, match="has no input"):
            srv.submit({"x": np.zeros(2, "float32"),
                        "y": np.zeros(3, "float32"),
                        "typo": np.zeros(1)})
        # breaker (threshold 1!) untouched: nothing reached dispatch
        assert srv.health()["models"]["specced"]["breaker"] == "closed"
        out = srv.infer({"x": np.full(2, 3.0, "float32"),
                         "y": np.zeros(3, "float32")}, timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 6.0))
    finally:
        srv.shutdown()


def test_transient_dispatch_error_retries_once():
    flaky = FakeModel(name="flaky",
                      fail=[faults.TransientDispatchError("hiccup"), None])
    srv = _mk_server(flaky, max_batch=1)
    try:
        out = srv.infer(_req(3), timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 6.0))
        assert len(flaky.calls) == 2           # failed + retried
        assert srv.health()["models"]["flaky"]["breaker"] == "closed"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Fault-injection sites
# ---------------------------------------------------------------------------
def test_injected_dispatch_transient_is_retried():
    fake = FakeModel()
    srv = _mk_server(fake, max_batch=1)
    try:
        faultinject.configure("serving.dispatch@1=transient")
        out = srv.infer(_req(2), timeout=10)
        np.testing.assert_array_equal(out[0], np.full(2, 4.0))
        assert faultinject.fired("serving.dispatch") == 1
    finally:
        srv.shutdown()


def test_injected_dispatch_fatal_feeds_the_breaker():
    fake = FakeModel()
    srv = _mk_server(fake, max_batch=1, breaker_threshold=1)
    try:
        faultinject.configure("serving.dispatch@*=fatal")
        with pytest.raises(ModelError):
            srv.infer(_req(1), timeout=10)
        assert srv.health()["models"]["fake"]["breaker"] == "open"
        assert fake.calls == []                # never reached the model
    finally:
        srv.shutdown()


def test_injected_request_drop_and_delay():
    fake = FakeModel()
    srv = _mk_server(fake)
    try:
        faultinject.configure("serving.request@1=drop")
        with pytest.raises(ConnectionError):
            srv.submit(_req(1))
        faultinject.configure("serving.request@1=delay:30")
        t0 = time.monotonic()
        out = srv.infer(_req(2), timeout=10)
        assert time.monotonic() - t0 >= 0.03
        np.testing.assert_array_equal(out[0], np.full(2, 4.0))
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Drain / lifecycle
# ---------------------------------------------------------------------------
def test_graceful_drain_completes_every_admitted_request():
    fake = FakeModel()
    srv = _mk_server(fake, max_batch=4, max_wait_ms=2.0,
                     queue_capacity=None, deadline_ms=None)
    admitted, stop = [], threading.Event()

    def pump():
        i = 0
        while not stop.is_set():
            try:
                admitted.append(srv.submit(_req(i)))
            except ServerClosed:
                break
            i += 1
            time.sleep(0.001)

    t = threading.Thread(target=pump)
    t.start()
    try:
        time.sleep(0.08)                       # requests in flight
        srv.begin_drain()
        assert srv.state == "draining"
        with pytest.raises(ServerClosed):
            srv.submit(_req(99999))
        srv.shutdown(drain=True, timeout=30)
    finally:
        stop.set()
        t.join(timeout=10)
    assert srv.state == "stopped"
    assert len(admitted) > 0
    # ZERO dropped admitted requests: every one reached a terminal result
    for p in admitted:
        out = p.result(timeout=0.5)            # must already be done
        assert out is not None


def test_shutdown_without_drain_aborts_queued_typed():
    fake = FakeModel(gate=True)
    srv = _mk_server(fake, max_batch=1, queue_capacity=16,
                     deadline_ms=None)
    r1 = srv.submit(_req(1))
    time.sleep(0.02)
    queued = [srv.submit(_req(i)) for i in range(2, 6)]
    fake.open_gate_forever()
    srv.shutdown(drain=False, timeout=30)
    assert srv.state == "stopped"
    r1.result(timeout=5)                       # in-flight one completed
    aborted = 0
    for p in queued:
        assert p.done()
        try:
            p.result(timeout=0)
        except ServerClosed:
            aborted += 1
    assert aborted >= 1                        # tail was aborted, typed


def test_submit_validation_errors():
    fake = FakeModel()
    srv = _mk_server(fake)
    try:
        with pytest.raises(ValueError, match="unknown model"):
            srv.submit(_req(0), model="nope")
        with pytest.raises(RuntimeError, match="already started"):
            srv.add_model(FakeModel(name="late").model)
    finally:
        srv.shutdown()
    with pytest.raises(ServerClosed):
        srv.submit(_req(1))                  # stopped: admission closed
    srv2 = Server(warmup=False)
    with pytest.raises(ValueError, match="no models"):
        srv2.start()
    srv3 = Server(warmup=False)
    srv3.add_model(FakeModel(name="dup").model)
    with pytest.raises(ValueError, match="duplicate"):
        srv3.add_model(FakeModel(name="dup").model)
    with pytest.raises(ValueError):
        Server(max_batch=0)
    with pytest.raises(ValueError):
        Server(queue_capacity=0)


# ---------------------------------------------------------------------------
# Overload p99 bound (the in-process shedding acceptance)
# ---------------------------------------------------------------------------
def _overload_arm(*, shed, queue, duration_s, service_s=0.004,
                  max_batch=4, factor=2.0):
    """Offer ``factor``x a fixed-service-time fake's capacity; return
    (sorted served latencies s, rejected/errored count, offered)."""
    class SlowModel(FakeModel):
        def _fn(self, feeds):
            time.sleep(service_s)              # fixed batch service time
            x = np.asarray(feeds["x"])
            self.calls.append(int(x.shape[0]))
            return [x * 2.0]

    slow = SlowModel(name="slow")
    srv = _mk_server(slow, max_batch=max_batch, max_wait_ms=1.0,
                     queue_capacity=queue, shed=shed, deadline_ms=None)
    lat, errs = [], []
    lock = threading.Lock()

    def cb(p):
        with lock:
            (errs if p.error is not None else lat).append(
                (time.monotonic() - p.t_admit))

    rate = factor * max_batch / service_s
    t0 = time.monotonic()
    offered = 0
    try:
        while time.monotonic() - t0 < duration_s:
            due = int((time.monotonic() - t0) * rate) - offered
            for _ in range(due):
                offered += 1
                try:
                    srv.submit(_req(offered)).add_done_callback(cb)
                except Overloaded:
                    with lock:
                        errs.append(None)
            time.sleep(0.002)
        # control arm: don't serve the unbounded backlog out, abort it
        srv.shutdown(drain=shed, timeout=30)
    finally:
        if srv.state != "stopped":
            srv.shutdown(drain=False)
    with lock:
        return sorted(lat), len(errs), offered


def test_shedding_bounds_admitted_p99_under_2x_overload():
    """2x offered overload on a fixed-service-time fake: with shedding,
    admitted-request p99 stays bounded (~queue/throughput); the no-shed
    unbounded-queue control arm under the SAME load degrades with queue
    depth — its p99 must be decisively worse."""
    from benchmark.serving_common import percentile
    shed_lat, shed_errs, shed_offered = _overload_arm(
        shed=True, queue=8, duration_s=1.0)
    ctrl_lat, _, _ = _overload_arm(
        shed=False, queue=None, duration_s=1.5)
    assert len(shed_lat) >= 20                 # actually served plenty
    assert shed_errs >= 10                     # and actually overloaded
    assert len(ctrl_lat) >= 20
    shed_p99 = percentile(shed_lat, 0.99)
    ctrl_p99 = percentile(ctrl_lat, 0.99)
    # absolute SANITY bound only: the shed arm's queue holds ~2 batches,
    # so a p99 on the order of the whole 1 s run means the bound did
    # nothing; the tight claim is the relative one below (a wall-clock
    # threshold tuned to this ~1-core box would flake on slower CI)
    assert shed_p99 <= 1.0, (
        f"admitted p99 {shed_p99 * 1e3:.1f} ms with shedding is not "
        f"bounded")
    # ... and the comparative claim: without shedding the same overload
    # collapses (latency grows with the unbounded queue for the whole
    # run)
    assert ctrl_p99 >= 1.5 * shed_p99, (
        f"control p99 {ctrl_p99 * 1e3:.1f} ms vs shed p99 "
        f"{shed_p99 * 1e3:.1f} ms — control arm did not degrade")


# ---------------------------------------------------------------------------
# Zero cost when unused
# ---------------------------------------------------------------------------
def test_training_paths_byte_identical_with_serving_loaded():
    """Counter-delta + retrace + bit-identity guard: loading and using
    the serving package must not perturb Executor.run/run_steps."""
    from paddle_tpu.core.compile_cache import retrace_guard

    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=8, act="relu")
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    opt = pt.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(4, 4).astype("float32"),
            "y": rng.randint(0, 3, (4, 1))}

    def run_block(e):
        outs = []
        outs.append(e.run(pt.default_main_program(), feed=feed,
                          fetch_list=[loss])[0])
        outs.append(e.run_steps(2, pt.default_main_program(), feed=feed,
                                fetch_list=[loss])[0])
        return outs

    # arm A: plain training run (serving package IS imported by this
    # test module — the guard is that using it changes nothing)
    state0 = {k: np.array(pt.global_scope().get(k))
              for k in pt.global_scope().keys()}
    a = run_block(exe)

    # restore state, spin up AND use a serving server, run again
    for k, v in state0.items():
        pt.global_scope().set(k, v)
    fake = FakeModel()
    srv = _mk_server(fake)
    srv.infer(_req(1), timeout=10)
    srv.shutdown()

    exe2 = pt.Executor()
    before = pt.observability.registry().snapshot()
    with retrace_guard():
        b = run_block(exe2)
    after = pt.observability.registry().snapshot()
    for av, bv in zip(a, b):
        np.testing.assert_array_equal(np.asarray(av), np.asarray(bv))
    # the TRAINING dispatches wrote no executor metrics (observe off)
    for name in ("executor/steps", "executor/dispatches"):
        assert after[name]["value"] == before[name]["value"]


# ---------------------------------------------------------------------------
# Artifact round trip + stats CLI section
# ---------------------------------------------------------------------------
def test_artifact_model_serves_and_matches_direct_call(tmp_path):
    x = layers.data("x", shape=[6], dtype="float32")
    pred = layers.fc(x, size=3, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    d = str(tmp_path / "m")
    pt.export_compiled_model(d, {"x": ((-1, 6), "float32")}, [pred])
    run, _ = pt.load_compiled_model(d)

    m = Model.from_artifact(d)
    assert m.name == "m" and m.example is not None
    srv = Server(max_batch=2, max_wait_ms=5.0, deadline_ms=None,
                 queue_capacity=8)
    srv.add_model(m)
    srv.start()
    try:
        xs = np.random.RandomState(0).rand(6).astype("float32")
        out = srv.infer({"x": xs}, timeout=60)
        ref = run({"x": xs[None]})
        np.testing.assert_allclose(out[0], np.asarray(ref[0])[0],
                                   rtol=0, atol=0)
    finally:
        srv.shutdown()


def test_from_compiled_serves_through_the_aot_variant():
    x = layers.data("x", shape=[5], dtype="float32")
    pred = layers.fc(x, size=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    cp = exe.compile(pt.default_main_program(),
                     feed={"x": ((1, 5), "float32")}, fetch_list=[pred],
                     is_test=True)
    assert cp.executor is exe
    m = Model.from_compiled(cp, name="aot",
                            example={"x": np.zeros(5, "float32")})
    srv = Server(max_batch=1, max_wait_ms=1.0, deadline_ms=None,
                 queue_capacity=4)
    srv.add_model(m)
    srv.start()
    try:
        xs = np.random.RandomState(1).rand(5).astype("float32")
        out = srv.infer({"x": xs}, timeout=30)
        ref = exe.run(pt.default_main_program(), feed={"x": xs[None]},
                      fetch_list=[pred], is_test=True)
        np.testing.assert_allclose(out[0], ref[0][0], rtol=0, atol=0)
    finally:
        srv.shutdown()


def test_stats_cli_serving_section(tmp_path, capsys):
    from paddle_tpu.observability.export import (render_summary,
                                                 summarize_log)
    log = tmp_path / "serve.jsonl"
    pt.flags.set_flag("metrics_log", str(log))
    try:
        fake = FakeModel()
        srv = _mk_server(fake, max_batch=2, queue_capacity=2,
                         deadline_ms=None)
        ps = [srv.submit(_req(i)) for i in range(2)]
        for p in ps:
            p.result(timeout=10)
        srv.shutdown(drain=True)
    finally:
        pt.flags.set_flag("metrics_log", "")
        from paddle_tpu.observability.export import _reset_writer
        _reset_writer()
    summary = summarize_log(str(log))
    sv = summary["serving"]
    assert sv["requests_served"] == 2
    assert sv["batches"] >= 1
    assert sv["states"][-1] == "stopped"
    assert "ready" in sv["states"] and "draining" in sv["states"]
    text = render_summary(summary)
    assert "serving:" in text and "shed=0" in text
