"""Dataset-tail coverage (reference: v2/dataset/{sentiment,flowers,voc2012,
mq2007}.py): official-format parsers against locally synthesized archives,
synthetic-fallback contracts, and demo wiring — flowers feeds an image
classifier, voc2012 feeds the SSD loss, mq2007 feeds a pairwise ranker,
sentiment feeds a bag-of-embedding classifier (each trains with
decreasing loss, matching the reference demo semantics)."""
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.dataset import flowers, mq2007, sentiment, voc2012


# ---------------------------------------------------------------------------
# parsers against official-layout local data
# ---------------------------------------------------------------------------
def test_sentiment_zip_parser(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    monkeypatch.setattr(sentiment, "DATA_HOME", str(tmp_path))
    sentiment._CACHE.clear()
    os.makedirs(tmp_path / "corpora")
    arch = tmp_path / "corpora" / "movie_reviews.zip"
    with zipfile.ZipFile(arch, "w") as z:
        z.writestr("movie_reviews/pos/cv000_1.txt", "great great fun movie")
        z.writestr("movie_reviews/pos/cv001_2.txt", "a great film")
        z.writestr("movie_reviews/neg/cv000_3.txt", "awful terrible movie")
        z.writestr("movie_reviews/neg/cv001_4.txt", "bad bad film")
    wd = sentiment.get_word_dict()
    assert wd[0][0] in ("great", "bad")      # most frequent words first
    ids = dict(wd)
    data = sentiment.load_sentiment_data()
    assert len(data) == 4
    # interleaved neg/pos like the reference's sort_files()
    assert [lab for _, lab in data] == [0, 1, 0, 1]
    words, lab = data[0]
    assert lab == 0 and words == [ids["awful"], ids["terrible"],
                                  ids["movie"]]


def test_flowers_tar_parser(tmp_path, monkeypatch):
    import scipy.io as scio
    from PIL import Image
    from paddle_tpu.dataset import common
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    d = tmp_path / "flowers"
    os.makedirs(d)
    # 4 images, ids 1..4; labels 1-based in the .mat like the official file
    tar_p = d / "102flowers.tgz"
    with tarfile.open(tar_p, "w:gz") as tf:
        for i in range(1, 5):
            img = Image.fromarray(
                (np.full((300, 260, 3), i * 30)).astype("uint8"))
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            blob = buf.getvalue()
            info = tarfile.TarInfo("jpg/image_%05d.jpg" % i)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    scio.savemat(d / "imagelabels.mat",
                 {"labels": np.array([[5, 6, 7, 8]])})
    scio.savemat(d / "setid.mat", {"tstid": np.array([[1, 2, 3]]),
                                   "trnid": np.array([[4]]),
                                   "valid": np.array([[4]])})
    reader = flowers._tar_reader(
        str(tar_p), str(d / "imagelabels.mat"), str(d / "setid.mat"),
        "tstid", lambda s: flowers.default_mapper(False, s))
    samples = list(reader())
    assert len(samples) == 3
    x, y = samples[0]
    assert x.shape == (3 * 224 * 224,) and x.dtype == np.float32
    assert y == 4                                  # 1-based 5 → 0-based 4


def test_voc2012_tar_parser(tmp_path, monkeypatch):
    from PIL import Image
    from paddle_tpu.dataset import common
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    tar_p = tmp_path / "VOCtrainval_11-May-2012.tar"
    with tarfile.open(tar_p, "w") as tf:
        ids = ["2007_000001", "2007_000002"]
        listing = ("\n".join(ids) + "\n").encode()
        info = tarfile.TarInfo(voc2012.SET_FILE.format("val"))
        info.size = len(listing)
        tf.addfile(info, io.BytesIO(listing))
        for i, key in enumerate(ids):
            img = Image.fromarray(
                (np.full((40, 50, 3), 100 + i)).astype("uint8"))
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            blob = buf.getvalue()
            info = tarfile.TarInfo(voc2012.DATA_FILE.format(key))
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
            mask = np.zeros((40, 50), dtype="uint8")
            mask[10:20, 5:15] = i + 1
            m = Image.fromarray(mask, mode="L")
            buf = io.BytesIO()
            m.save(buf, format="PNG")
            blob = buf.getvalue()
            info = tarfile.TarInfo(voc2012.LABEL_FILE.format(key))
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    samples = list(voc2012._tar_reader(str(tar_p), "val")())
    assert len(samples) == 2
    img, mask = samples[1]
    assert img.shape == (40, 50, 3) and mask.shape == (40, 50)
    assert voc2012.boxes_from_mask(mask) == [(2, 10, 5, 20, 15)]


def test_mq2007_letor_parser(tmp_path, monkeypatch):
    monkeypatch.setattr(mq2007, "DATA_HOME", str(tmp_path))
    fold = tmp_path / "MQ2007" / "Fold1"
    os.makedirs(fold)
    lines = []
    for qid, rels in [(10, [2, 0, 1]), (11, [0, 0, 0]), (12, [1, 2])]:
        for di, rel in enumerate(rels):
            feats = " ".join(f"{k}:{0.01 * (di + k):.6f}"
                             for k in range(1, 47))
            lines.append(f"{rel} qid:{qid} {feats} # doc{qid}-{di}")
    (fold / "train.txt").write_text("\n".join(lines) + "\n")
    qls = mq2007.load_from_text(str(fold / "train.txt"), shuffle=False)
    assert [ql.query_id for ql in qls] == [10, 11, 12]
    assert len(qls[0]) == 3
    # qid 11 has all-zero relevance → filtered
    kept = mq2007.query_filter(qls)
    assert [ql.query_id for ql in kept] == [10, 12]
    # pairwise: hi always first
    pairs = list(mq2007.gen_pair(qls[0]))
    assert len(pairs) == 3                # (2,0) (2,1) (1,0)
    for lab, hi, lo in pairs:
        assert lab == [1] and hi.shape == (46,) and lo.shape == (46,)
    # listwise is sorted descending
    labels, feats = next(mq2007.gen_list(qls[0]))
    assert labels[:, 0].tolist() == [2, 1, 0] and feats.shape == (3, 46)
    # reader end-to-end through the resolver; pointwise yields the top
    # doc of each kept query (mq2007.py:313 next(gen_point(...)))
    got = list(mq2007.train(format="pointwise")())
    assert len(got) == 2
    assert got[0][0] == 2 and got[1][0] == 2     # ranked best-first


# ---------------------------------------------------------------------------
# synthetic fallbacks keep the documented contracts
# ---------------------------------------------------------------------------
def test_synthetic_contracts():
    w, lab = next(sentiment.train()())
    assert isinstance(w, list) and lab in (0, 1)
    x, y = next(flowers.train()())
    assert x.shape == (3 * 224 * 224,) and 0 <= y < 102
    img, mask = next(voc2012.train()())
    assert img.ndim == 3 and mask.shape == img.shape[:2]
    assert voc2012.boxes_from_mask(mask)
    lab, hi, lo = next(mq2007.train()())
    assert hi.shape == (46,) and lo.shape == (46,)


# ---------------------------------------------------------------------------
# demo wiring: each dataset trains its reference demo model
# ---------------------------------------------------------------------------
def _train_steps(loss, feeds, steps, lr=0.1):
    opt = pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    vals = [float(exe.run(feed=feeds(i), fetch_list=[loss])[0])
            for i in range(steps)]
    return vals


def test_flowers_image_classification_demo():
    """demo/image_classification on flowers: small convnet, loss falls."""
    samples = list(flowers._synthetic(64, seed=5, is_train=True)())
    xs = np.stack([s[0].reshape(3, 224, 224)[:, ::28, ::28]
                   for s in samples])          # 3x8x8 downsample for CI
    ys = np.array([s[1] for s in samples])[:, None]
    img = layers.data("img", shape=[3, 8, 8], dtype="float32")
    lab = layers.data("lab", shape=[1], dtype="int64")
    conv = layers.conv2d(img, num_filters=8, filter_size=3, act="relu")
    pool = layers.pool2d(conv, pool_size=2, pool_type="max")
    pred = layers.fc(pool, size=102, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, lab))

    def feeds(i):
        idx = np.arange(32) % 64 if i % 2 == 0 else (np.arange(32) + 32) % 64
        return {"img": xs[idx] / 60.0, "lab": ys[idx]}

    vals = _train_steps(loss, feeds, steps=12, lr=0.5)
    assert vals[-1] < vals[0]


def test_voc2012_ssd_demo():
    """voc2012 masks → boxes feed ssd_loss; a localizer head trains."""
    P, C = 8, voc2012.NUM_CLASSES
    prior = np.tile(np.array([[0.2, 0.2, 0.6, 0.6]], "float32"), (P, 1))
    prior += np.linspace(0, 0.3, P)[:, None].astype("float32")
    samples = list(voc2012._synthetic(8, seed=9)())
    gtbs, gtls = [], []
    for img, mask in samples:
        boxes = voc2012.boxes_from_mask(mask)[:2] or [(1, 0, 0, 8, 8)]
        size = float(mask.shape[0])
        gtb = np.zeros((2, 4), "float32")
        gtl = np.zeros((2, 1), "int64")
        for bi, (cls, y0, x0, y1, x1) in enumerate(boxes):
            gtb[bi] = [x0 / size, y0 / size, x1 / size, y1 / size]
            gtl[bi] = cls
        gtbs.append(gtb)
        gtls.append(gtl)
    gtb = np.stack(gtbs)
    gtl = np.stack(gtls)

    feat = layers.data("feat", shape=[P, 8], dtype="float32")
    gtbv = layers.data("gtb", shape=[2, 4], dtype="float32")
    gtlv = layers.data("gtl", shape=[2, 1], dtype="int64")
    priorv = layers.data("prior", shape=[P, 4], dtype="float32",
                         append_batch_size=False)
    loc = layers.fc(feat, size=4, num_flatten_dims=2)
    conf = layers.fc(feat, size=C, num_flatten_dims=2)
    loss = layers.mean(layers.ssd_loss(loc, conf, gtbv, gtlv, priorv))

    rng = np.random.RandomState(3)
    featv = rng.rand(8, P, 8).astype("float32")

    def feeds(_):
        return {"feat": featv, "gtb": gtb, "gtl": gtl, "prior": prior}

    vals = _train_steps(loss, feeds, steps=10, lr=0.05)
    assert vals[-1] < vals[0]


def test_mq2007_rank_demo():
    """demo/rank: pairwise rank_loss on mq2007 features learns to order."""
    # param init lives in the STARTUP program; pin its seed so the learned
    # scorer (and the held-out frac below) is one deterministic number per
    # jax PRNG implementation, not a draw
    pt.default_startup_program().random_seed = 0
    pairs = list(mq2007.train()())          # 2237 synthetic pairs
    hi = np.stack([p[1] for p in pairs]).astype("float32")
    lo = np.stack([p[2] for p in pairs]).astype("float32")
    left = layers.data("left", shape=[46], dtype="float32")
    right = layers.data("right", shape=[46], dtype="float32")
    lab = layers.data("lab", shape=[1], dtype="float32")
    w = pt.ParamAttr(name="rank_w")
    sl = layers.fc(left, size=1, param_attr=w)
    sr = layers.fc(right, size=1, param_attr=w)
    loss = layers.mean(layers.rank_loss(lab, sl, sr))

    def feeds(i):
        s = (i * 64) % 384
        return {"left": hi[s:s + 64], "right": lo[s:s + 64],
                "lab": np.ones((64, 1), "float32")}

    vals = _train_steps(loss, feeds, steps=30, lr=0.5)
    assert vals[-1] < vals[0]
    # the learned scorer ranks held-out hi above lo most of the time.
    # Threshold: with n=1853 held-out pairs the random-ranking null is
    # frac ~ N(0.5, 0.5/sqrt(1853) ≈ 0.012), so 0.7 is >17σ above chance;
    # the seeded run measures 0.820 here and every nearby init seed lands
    # ≥ 0.74, so 0.7 flags real ranking regressions without sitting on the
    # measured value (the old 64-pair eval read 0.594 against a 0.6 bar —
    # chance-level noise of ±0.0625 with the bound inside it).
    wv = np.asarray(pt.global_scope().get("rank_w"))
    frac = float(np.mean((hi[384:] @ wv) > (lo[384:] @ wv)))
    assert frac > 0.7


def test_sentiment_classifier_demo():
    """demo/sentiment: bag-of-embedding classifier on the corpus."""
    data = sentiment.load_sentiment_data()[:128]
    T = 64
    toks = np.zeros((128, T), "int64")
    for i, (ws, _) in enumerate(data):
        ws = [w % 512 for w in ws[:T]]       # fold vocab so tokens repeat
        toks[i, :len(ws)] = ws
    labs = np.array([lab for _, lab in data])[:, None]
    x = layers.data("x", shape=[T], dtype="int64")
    y = layers.data("y", shape=[1], dtype="int64")
    emb = layers.embedding(x, size=[512, 16])
    avg = layers.reduce_mean(emb, dim=1)
    pred = layers.fc(avg, size=2, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))

    def feeds(i):
        s = (i * 64) % 128
        return {"x": toks[s:s + 64], "y": labs[s:s + 64]}

    vals = _train_steps(loss, feeds, steps=40, lr=2.0)
    assert vals[-1] < vals[0] * 0.9


# ---------------------------------------------------------------------------
# paddle_tpu.image (reference v2/image.py)
# ---------------------------------------------------------------------------
def test_image_module_transforms(tmp_path):
    from PIL import Image
    from paddle_tpu import image

    # BGR convention: a pure-red RGB image loads with red in channel 2
    rgb = np.zeros((40, 60, 3), "uint8")
    rgb[..., 0] = 200
    buf = io.BytesIO()
    Image.fromarray(rgb).save(buf, format="PNG")
    im = image.load_image_bytes(buf.getvalue())
    assert im.shape == (40, 60, 3)
    assert im[..., 2].mean() == 200 and im[..., 0].mean() == 0

    r = image.resize_short(im, 20)
    assert min(r.shape[:2]) == 20 and r.shape[:2] == (20, 30)
    c = image.center_crop(r, 16)
    assert c.shape[:2] == (16, 16)
    rc = image.random_crop(r, 16)
    assert rc.shape[:2] == (16, 16)
    assert image.left_right_flip(r).shape == r.shape
    assert np.array_equal(image.left_right_flip(r), r[:, ::-1])
    chw = image.to_chw(c)
    assert chw.shape == (3, 16, 16)

    t = image.simple_transform(im, 24, 16, is_train=False,
                               mean=[10.0, 20.0, 30.0])
    assert t.shape == (3, 16, 16) and t.dtype == np.float32
    assert abs(float(t[2].mean()) - (200 - 30.0)) < 1e-5   # red - mean[2]
    assert abs(float(t[0].mean()) - (0 - 10.0)) < 1e-5

    # file round-trip + load_and_transform
    p = tmp_path / "img.png"
    Image.fromarray(rgb).save(p)
    lt = image.load_and_transform(str(p), 24, 16, is_train=True)
    assert lt.shape == (3, 16, 16)


def test_batch_images_from_tar(tmp_path):
    from PIL import Image
    from paddle_tpu import image

    tar_p = str(tmp_path / "imgs.tar")
    with tarfile.open(tar_p, "w") as tf:
        for i in range(5):
            buf = io.BytesIO()
            Image.fromarray(np.full((8, 8, 3), i * 40, "uint8")).save(
                buf, format="JPEG")
            blob = buf.getvalue()
            info = tarfile.TarInfo(f"jpg/im_{i}.jpg")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    img2label = {f"jpg/im_{i}.jpg": i for i in range(5)}
    meta = image.batch_images_from_tar(tar_p, "train", img2label,
                                       num_per_batch=2)
    files = [ln.strip() for ln in open(meta)]
    assert len(files) == 3                       # 2+2+1
    import pickle as pkl
    total = []
    for f in files:
        with open(f, "rb") as fh:
            b = pkl.load(fh)
        assert len(b["data"]) == len(b["label"])
        total.extend(b["label"])
    assert sorted(total) == [0, 1, 2, 3, 4]
    # idempotent: existing batch dir returns the same meta
    assert image.batch_images_from_tar(tar_p, "train", img2label) == meta


def test_wmt14_tgz_parser(tmp_path, monkeypatch):
    """Official-layout wmt14.tgz (src.dict/trg.dict + tab-separated
    parallel files) parses with <s>/<e> framing, UNK mapping, and the
    >80-token drop (reference wmt14.py:45,71)."""
    from paddle_tpu.dataset import wmt14

    d = tmp_path / "wmt14"
    os.makedirs(d)
    src_dict = "\n".join(["<s>", "<e>", "<unk>", "hello", "world"])
    trg_dict = "\n".join(["<s>", "<e>", "<unk>", "bonjour", "monde"])
    train = ("hello world\tbonjour monde\n"
             "hello oov\tbonjour oov\n"
             + " ".join(["hello"] * 90) + "\tbonjour\n")   # dropped: >80
    tar_p = d / "wmt14.tgz"
    with tarfile.open(tar_p, "w:gz") as tf:
        for name, text in [("wmt14/train/src.dict", src_dict),
                           ("wmt14/train/trg.dict", trg_dict),
                           ("wmt14/train/train", train)]:
            blob = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    wmt14._DICT_MEMO.clear()
    samples = list(wmt14._tar_reader(str(tar_p), "train/train", 5)())
    assert len(samples) == 2                    # long pair dropped
    src, trg, nxt = samples[0]
    assert src == [0, 3, 4, 1]                  # <s> hello world <e>
    assert trg == [0, 3, 4] and nxt == [3, 4, 1]
    # oov maps to UNK_IDX
    assert samples[1][0] == [0, 3, 2, 1]


def test_mnist_idx_gz_parser(tmp_path, rng):
    """Official MNIST idx3/idx1 gzip format (mnist.py reader_from_files):
    big-endian magic+dims headers, raw u8 payload."""
    import gzip
    import struct

    from paddle_tpu.dataset import mnist

    imgs = (rng.rand(5, 28, 28) * 255).astype("uint8")
    labs = rng.randint(0, 10, 5).astype("uint8")
    ip = tmp_path / "train-images-idx3-ubyte.gz"
    lp = tmp_path / "train-labels-idx1-ubyte.gz"
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 5, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 5))
        f.write(labs.tobytes())
    rows = list(mnist.reader_from_files(str(ip), str(lp))())
    assert len(rows) == 5
    x, y = rows[3]
    assert x.shape == (784,) and x.dtype == np.float32
    # v2 mnist normalization: pixel / 255 * 2 - 1 in [-1, 1]
    np.testing.assert_allclose(
        x, imgs[3].reshape(-1).astype("f4") / 255.0 * 2.0 - 1.0, atol=1e-6)
    assert y == int(labs[3])


def test_conll05_props_parser(tmp_path):
    """Official conll05st layout: parallel words.gz/props.gz streams,
    bracket columns -> BIO, one item per predicate, 9-slot SRL tuples
    (reference conll05.py:53-178 semantics)."""
    import gzip
    import io
    import tarfile

    from paddle_tpu.dataset import conll05

    words = "The\ncat\nchased\na\nmouse\n.\n\n"
    # two predicate columns: 'chased' (col 1) and a fake second 'saw'
    props_rows = [
        "-    *        (A0*",
        "-    (A0*)    *)",
        "chased (V*)   *",
        "saw  (A1*     (V*)",
        "-    *)       (A1*)",
        "-    *        *",
        "",
    ]
    props = "\n".join(" ".join(r.split()) for r in props_rows) + "\n"
    arch = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(arch, "w:gz") as tf:
        for name, text in ((conll05.WORDS_NAME, words),
                           (conll05.PROPS_NAME, props)):
            blob = io.BytesIO()
            with gzip.GzipFile(fileobj=blob, mode="wb") as gz:
                gz.write(text.encode())
            info = tarfile.TarInfo(name)
            info.size = len(blob.getvalue())
            tf.addfile(info, io.BytesIO(blob.getvalue()))

    items = list(conll05.corpus_reader(str(arch))())
    assert len(items) == 2                       # one per predicate column
    # tail flush: the same archive WITHOUT the trailing blank line must
    # still yield the final sentence
    arch2 = tmp_path / "no-trailing-newline.tar.gz"
    with tarfile.open(arch2, "w:gz") as tf:
        for name, text in ((conll05.WORDS_NAME, words.rstrip("\n") + "\n"),
                           (conll05.PROPS_NAME,
                            props.rstrip("\n").rsplit("\n", 1)[0] + "\n")):
            blob = io.BytesIO()
            with gzip.GzipFile(fileobj=blob, mode="wb") as gz:
                gz.write(text.encode())
            info = tarfile.TarInfo(name)
            info.size = len(blob.getvalue())
            tf.addfile(info, io.BytesIO(blob.getvalue()))
    assert len(list(conll05.corpus_reader(str(arch2))())) == 2
    sent, pred, labels = items[0]
    assert sent == ["The", "cat", "chased", "a", "mouse", "."]
    assert pred == "chased"
    assert labels == ["O", "B-A0", "B-V", "B-A1", "I-A1", "O"]
    sent2, pred2, labels2 = items[1]
    assert labels2 == ["B-A0", "I-A0", "O", "B-V", "B-A1", "O"]

    wd = {w: i + 1 for i, w in enumerate(sorted(set(sent)))}
    vd = {"chased": 0}
    ld = {t: i for i, t in enumerate(
        sorted({t for it in items for t in it[2]}))}
    rows = list(conll05.reader_creator(
        conll05.corpus_reader(str(arch)), wd, vd, ld)())
    assert len(rows) == 2
    w_idx, n2, n1, c0, p1, p2, pidx, mark, lab = rows[0]
    assert len(w_idx) == 6 and len(lab) == 6
    # predicate window around 'chased' (index 2): marks on 0..4
    assert mark == [1, 1, 1, 1, 1, 0]
    assert c0 == [wd["chased"]] * 6 and pidx == [0] * 6


def test_cifar_imikolov_uci_parsers_hermetic(tmp_path, rng):
    """HTTP-free duplicates of the core format-parser checks that
    otherwise live only in test_dataset_real.py (which some CI setups
    deselect wholesale over its localhost download tests): cifar pickle
    tar, imikolov ngram tgz, uci_housing whitespace table."""
    import pickle
    import tarfile as tar_mod

    from paddle_tpu.dataset import cifar, imikolov, uci_housing

    # cifar
    arch = tmp_path / "cifar-10-python.tar.gz"
    with tar_mod.open(arch, "w:gz") as tf:
        batch = {"data": (rng.rand(4, 3072) * 255).astype("uint8"),
                 "labels": [int(x) for x in rng.randint(0, 10, 4)]}
        blob = pickle.dumps(batch)
        info = tar_mod.TarInfo("cifar-10-batches-py/data_batch_1")
        info.size = len(blob)
        tf.addfile(info, io.BytesIO(blob))
    samples = list(cifar._tar_reader(str(arch), "data_batch", "labels")())
    assert len(samples) == 4 and samples[0][0].shape == (3, 32, 32)

    # imikolov
    arch2 = tmp_path / "simple-examples.tgz"
    txt = b"the cat sat\n"
    with tar_mod.open(arch2, "w:gz") as tf:
        info = tar_mod.TarInfo(imikolov.TRAIN_FILE)
        info.size = len(txt)
        tf.addfile(info, io.BytesIO(txt))
    with tar_mod.open(arch2) as tf:
        freq = imikolov.word_count(tf.extractfile(imikolov.TRAIN_FILE))
    word_idx = {w: i for i, w in enumerate(sorted(freq))}
    word_idx["<unk>"] = len(word_idx)
    grams = list(imikolov._real_reader(
        imikolov.TRAIN_FILE, word_idx, 3, imikolov.DataType.NGRAM,
        str(arch2))())
    assert len(grams) == 3 and all(len(g) == 3 for g in grams)

    # uci_housing
    raw = rng.rand(10, 14).astype("float32") * 10
    f = tmp_path / "housing.data"
    with open(f, "w") as fh:
        for row in raw:
            fh.write(" ".join(f"{v:.4f}" for v in row) + "\n")
    train_rows, test_rows = uci_housing.load_data(str(f))
    assert train_rows.shape[0] == 8 and test_rows.shape[0] == 2


def test_conll05_get_dict_prefers_published(tmp_path, monkeypatch):
    """get_dict loads the reference's published wordDict/verbDict/
    targetDict (line index == id) when cached, and falls back to the
    synthetic vocabulary when nothing is available (ADVICE round 5:
    corpus-derived ids are incompatible with the published embedding)."""
    from paddle_tpu.dataset import common, conll05

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    # hermetic "published" files: content drives the md5 the probe checks
    contents = {
        conll05.WORDDICT_URL: "the\ncat\nsat\n",
        conll05.VERBDICT_URL: "sit\nrun\n",
        conll05.TRGDICT_URL: "O\nB-V\nB-A0\n",
    }
    d = tmp_path / "conll05st"
    d.mkdir()
    for url, text in contents.items():
        fname = d / url.split("/")[-1]
        fname.write_text(text)
        md5 = common.md5file(str(fname))
        for const in ("WORDDICT", "VERBDICT", "TRGDICT"):
            if url == getattr(conll05, const + "_URL"):
                monkeypatch.setattr(conll05, const + "_MD5", md5)

    wd, vd, ld = conll05.get_dict(download=True)
    assert wd == {"the": 0, "cat": 1, "sat": 2}
    assert vd == {"sit": 0, "run": 1}
    assert ld == {"O": 0, "B-V": 1, "B-A0": 2}

    # nothing cached, no download permission -> synthetic vocabulary
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "empty"))
    wd, vd, ld = conll05.get_dict()
    assert len(wd) == conll05.WORD_VOCAB and "w0" in wd
