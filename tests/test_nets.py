"""Composite net helper tests (reference: fluid/nets.py users, e.g.
fluid/tests/book image/sentiment configs and test_machine_translation's
attention block)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, nets


def np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis, keepdims=True))
    return e / e.sum(axis, keepdims=True)


def np_attention(q, k, v):
    d = q.shape[-1]
    logits = (q * d ** -0.5) @ np.swapaxes(k, -1, -2)
    return np_softmax(logits) @ v


@pytest.mark.parametrize("shape", [(2, 16, 8), (2, 4, 16, 8)])
def test_scaled_dot_product_attention(rng, shape):
    """3-D inputs route through the fused flash-attention kernel, 4-D
    through the matmul fallback; both must match the numpy reference."""
    q = rng.randn(*shape).astype(np.float32)
    k = rng.randn(*shape).astype(np.float32)
    v = rng.randn(*shape).astype(np.float32)
    qv = layers.data("q", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    kv = layers.data("k", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    vv = layers.data("v", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    out = nets.scaled_dot_product_attention(qv, kv, vv)
    exe = pt.Executor()
    (o,) = exe.run(feed={"q": q, "k": k, "v": v}, fetch_list=[out])
    np.testing.assert_allclose(o, np_attention(q, k, v), rtol=2e-4,
                               atol=2e-4)


def test_scaled_dot_product_attention_dropout_path(rng):
    """dropout_rate > 0 uses the unfused path; at test time (is_test) the
    default downgrade_in_infer dropout scales the attention weights by
    (1 - rate), so the output is (1 - rate) * reference."""
    shape = (2, 6, 8)
    q = rng.randn(*shape).astype(np.float32)
    k = rng.randn(*shape).astype(np.float32)
    v = rng.randn(*shape).astype(np.float32)
    qv = layers.data("q", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    kv = layers.data("k", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    vv = layers.data("v", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    out = nets.scaled_dot_product_attention(qv, kv, vv, dropout_rate=0.3)
    # the dropout op must be present on this path...
    assert any(op.type == "dropout"
               for op in pt.default_main_program().current_block().ops)
    exe = pt.Executor()
    (o,) = exe.run(feed={"q": q, "k": k, "v": v}, fetch_list=[out],
                   is_test=True)
    np.testing.assert_allclose(o, 0.7 * np_attention(q, k, v), rtol=2e-4,
                               atol=2e-4)


def test_scaled_dot_product_attention_no_dropout_op_at_rate_zero(rng):
    """rate 0.0 must not append a dropout op (it would burn an RNG key and
    perturb the stream for downstream ops)."""
    shape = (2, 4, 6, 8)  # 4-D: matmul path, where the guard lives
    qv = layers.data("q", shape=list(shape), dtype="float32",
                     append_batch_size=False)
    nets.scaled_dot_product_attention(qv, qv, qv, dropout_rate=0.0)
    assert not any(op.type == "dropout"
                   for op in pt.default_main_program().current_block().ops)


def test_glu(rng):
    x = rng.randn(3, 8).astype(np.float32)
    xv = layers.data("x", shape=[8], dtype="float32")
    out = nets.glu(xv)
    exe = pt.Executor()
    (o,) = exe.run(feed={"x": x}, fetch_list=[out])
    a, b = np.split(x, 2, axis=-1)
    np.testing.assert_allclose(o, a / (1 + np.exp(-b)), rtol=1e-5,
                               atol=1e-6)


def test_simple_img_conv_pool_shapes(rng):
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    xv = layers.data("img", shape=[1, 28, 28], dtype="float32")
    out = nets.simple_img_conv_pool(xv, num_filters=4, filter_size=5,
                                    pool_size=2, pool_stride=2, act="relu")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (o,) = exe.run(feed={"img": x}, fetch_list=[out])
    assert o.shape == (2, 4, 12, 12)
    assert (o >= 0).all()


def test_img_conv_group_with_batchnorm(rng):
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    xv = layers.data("img", shape=[3, 16, 16], dtype="float32")
    out = nets.img_conv_group(xv, conv_num_filter=[4, 4], pool_size=2,
                              conv_act="relu", conv_with_batchnorm=True,
                              pool_stride=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (o,) = exe.run(feed={"img": x}, fetch_list=[out])
    assert o.shape == (2, 4, 8, 8)
