"""End-to-end tracing + perf attribution (ISSUE 10 acceptance).

Pins:

* span API semantics: nesting, cross-thread parents, one-trace-per-
  request ROOT sentinel, events, frozen names;
* tracing OFF path: zero JSONL events, zero registry writes, zero
  retraces with ``observe`` off even when a metrics_log is set;
* span parent/child invariants on a REAL pipelined run: every parent
  exists, no cycles, the whole chain joins one trace, step events carry
  their span join keys;
* the doctor: budget components sum to the measured wall within the
  pinned tolerance, calibration rows, trace/doctor/stats CLIs including
  multi-file merge with restart boundaries;
* serving: request spans nest inside their batch's dispatch window,
  batch spans link member traces, retry/breaker span events survive a
  drain;
* robustness: torn/truncated final JSONL line (chaos-kill artifact) is
  counted, never fatal;
* Prometheus exposition: name-mangling round trip against METRIC_NAMES.
"""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, layers
from paddle_tpu import observability as obs
from paddle_tpu.core.compile_cache import retrace_guard
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import tracing


@pytest.fixture(autouse=True)
def clean_observability():
    obs.registry().reset()
    prev = {n: flags.get_flag(n) for n in ("observe", "metrics_log")}
    yield
    for n, v in prev.items():
        flags.set_flag(n, v if v is not None else "")
    obs_export._reset_writer()
    obs.registry().reset()


def _read_events(path):
    events, _files = obs_export.iter_log_events([str(path)])
    return events


def _spans(events):
    return [e for e in events if e.get("kind") == "span"]


def _assert_tree_invariants(spans):
    """Every span's parent exists inside its trace; parent chains
    terminate (no cycles); trace ids agree along edges."""
    by_id = {e["span"]: e for e in spans}
    for e in spans:
        p = e.get("parent")
        if p is None:
            continue
        assert p in by_id, f"span {e['span']} has unknown parent {p}"
        assert by_id[p]["trace"] == e["trace"], \
            f"parent {p} in different trace"
        seen, cur = set(), e
        while cur.get("parent"):
            assert cur["span"] not in seen, f"cycle through {cur['span']}"
            seen.add(cur["span"])
            cur = by_id[cur["parent"]]


# ---------------------------------------------------------------------------
# span API (no jax)
# ---------------------------------------------------------------------------
def test_span_api_nesting_events_and_cross_thread_parent(tmp_path):
    flags.set_flag("metrics_log", str(tmp_path / "api.jsonl"))
    with tracing.span("executor/run_pipelined", steps_per_dispatch=4) as root:
        assert tracing.current_span() is root
        with tracing.span("executor/step", path="run") as child:
            assert child.parent_id == root.span_id
            assert child.trace_id == root.trace_id
            tracing.add_event("retry", attempt=1)
        # cross-thread: explicit parent, ended on the other thread
        done = threading.Event()

        def worker():
            sp = tracing.start_span("pipeline/stage", parent=root,
                                    kind="scan")
            sp.end(steps=4)
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
        # ROOT forces a fresh trace even under an active span
        iso = tracing.start_span("serving/request", parent=tracing.ROOT,
                                 id=1)
        assert iso.parent_id is None and iso.trace_id != root.trace_id
        iso.cancel()                       # cancelled spans never emit
    assert tracing.current_span() is None
    spans = _spans(_read_events(tmp_path / "api.jsonl"))
    names = [e["name"] for e in spans]
    assert sorted(names) == ["executor/run_pipelined", "executor/step",
                             "pipeline/stage"]
    _assert_tree_invariants(spans)
    step = next(e for e in spans if e["name"] == "executor/step")
    assert step["events"][0]["name"] == "retry"
    stage = next(e for e in spans if e["name"] == "pipeline/stage")
    assert stage["labels"] == {"kind": "scan", "steps": 4}  # end() merges


def test_span_names_frozen():
    with pytest.raises(KeyError, match="frozen"):
        tracing.start_span("executor/step_tmie")          # typo'd
    # idempotent end: second end() emits nothing
    flags.set_flag("metrics_log", "")
    sp = tracing.start_span("reader/item", parent=tracing.ROOT)
    sp.end()
    sp.end()


# ---------------------------------------------------------------------------
# zero overhead when off (acceptance-pinned)
# ---------------------------------------------------------------------------
def _build_net():
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=16, act="relu")
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _batches(n, batch=16):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 8).astype("float32"),
             "y": rng.randint(0, 3, (batch, 1))} for _ in range(n)]


def test_tracing_off_zero_events_zero_writes_zero_retrace(tmp_path):
    """observe off + metrics_log SET: the training path emits NO JSONL
    events (spans included), touches NO metrics, and cannot retrace."""
    log = tmp_path / "off.jsonl"
    flags.set_flag("observe", False)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = _batches(9)
    before = obs.registry().snapshot()
    exe.run(feed=feeds[0], fetch_list=[loss])       # pays the one trace
    with retrace_guard():
        outs = list(exe.run_pipelined(
            iter(feeds[1:]), pt.default_main_program(),
            fetch_list=[loss], steps_per_dispatch=4))
    assert len(outs) == 8
    after = obs.registry().snapshot()
    assert after == before
    assert not log.exists() or log.read_text() == ""


# ---------------------------------------------------------------------------
# pipelined run: invariants + doctor + CLIs (one run, many assertions)
# ---------------------------------------------------------------------------
def test_pipelined_trace_invariants_doctor_and_clis(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    prog = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    with retrace_guard():       # spans may not retrace either
        list(exe.run_pipelined(iter(_batches(10)), prog,
                               fetch_list=[loss], steps_per_dispatch=4))
        list(exe.run_pipelined(iter(_batches(10)), prog,
                               fetch_list=[loss], steps_per_dispatch=4))
    flags.set_flag("metrics_log", "")

    events = _read_events(log)
    spans = _spans(events)
    _assert_tree_invariants(spans)
    names = {e["name"] for e in spans}
    assert {"executor/run_pipelined", "reader/pipeline", "reader/item",
            "pipeline/stage", "executor/step", "executor/dispatch",
            "executor/fetch_block"} <= names
    # the whole causal chain joins ONE trace per run_pipelined call
    roots = [e for e in spans if e["name"] == "executor/run_pipelined"]
    assert len(roots) == 2
    for root in roots:
        members = [e for e in spans if e["trace"] == root["trace"]]
        mnames = {e["name"] for e in members}
        assert {"pipeline/stage", "executor/step", "reader/item",
                "executor/dispatch"} <= mnames
    # step events carry their span join keys
    step_events = [e for e in events if e.get("kind") == "step"]
    ids = {e["span"] for e in spans}
    for se in step_events:
        assert se["span"] in ids and se["trace"]

    # ---- doctor: budget sums to measured wall within tolerance ----
    from paddle_tpu.observability import attribution
    budget = attribution.step_budget(events)
    assert budget is not None and budget["within_tolerance"]
    total = sum(budget["budget"].values())
    wall = budget["measured_wall_ms"]
    assert abs(total - wall) <= attribution.BUDGET_TOLERANCE * wall
    assert budget["steps"] == 21       # startup-program run + 2x10
    assert budget["top"] in budget["budget"]
    assert budget["hints"]

    # ---- build_traces / span_stats / critical path ----
    traces = tracing.build_traces(events)
    big = max(traces, key=lambda t: len(t["spans"]))
    assert tracing.critical_path(big)[0]["name"] == \
        "executor/run_pipelined"
    stats = tracing.span_stats(events)
    assert stats["executor/step"]["count"] >= 4

    # ---- multi-file merge: split the log, feed both halves ----
    lines = log.read_text().splitlines()
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    a.write_text("\n".join(lines[: len(lines) // 2]) + "\n")
    b.write_text("\n".join(lines[len(lines) // 2:]) + "\n")
    merged = obs_export.summarize_logs([str(a), str(b)])
    single = obs_export.summarize_logs([str(log)])
    assert merged["events"] == single["events"]
    assert merged["steps"]["steps"] == single["steps"]["steps"]
    assert len(merged["restarts"]) == 2
    assert "restart boundary" in obs_export.render_summary(merged)

    # ---- CLIs: stats (multi-file), trace, doctor (+ --program) ----
    from paddle_tpu.cli import main as cli_main
    assert cli_main(["stats", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "restart boundary" in out

    assert cli_main(["trace", str(a), str(b), "--limit", "1"]) == 0
    out = capsys.readouterr().out
    assert "executor/run_pipelined" in out and "critical path" in out

    prog_json = tmp_path / "prog.json"
    prog_json.write_text(prog.to_json())
    cal_out = tmp_path / "calibration.json"
    assert cli_main(["doctor", str(a), str(b),
                     "--program", str(prog_json), "--batch", "16",
                     "--calibration-out", str(cal_out)]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["training"]["within_tolerance"]
    assert doc["calibration"]["ratio"] > 0
    table = json.loads(cal_out.read_text())
    assert doc["calibration"]["program"] in table["programs"]


def test_calibration_table_merges_by_program(tmp_path):
    from paddle_tpu.observability import attribution
    path = str(tmp_path / "cal.json")
    r1 = {"program": "aaa", "predicted_ms": 1.0, "measured_ms": 2.0,
          "ratio": 2.0}
    r2 = {"program": "bbb", "predicted_ms": 1.0, "measured_ms": 3.0,
          "ratio": 3.0}
    attribution.save_calibration([r1], path)
    doc = attribution.save_calibration([r2, {**r1, "ratio": 4.0}], path)
    assert set(doc["programs"]) == {"aaa", "bbb"}
    assert doc["programs"]["aaa"]["ratio"] == 4.0   # re-doctor overwrites


def test_executable_facts_via_compat():
    """cost_analysis()/memory_analysis() guarded through compat: on this
    jax a compiled step exposes flops; the wrapper never raises."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import compat
    from paddle_tpu.observability import attribution
    comp = jax.jit(lambda x: jnp.dot(x, x)).lower(
        jnp.ones((32, 32), jnp.float32)).compile()
    facts = attribution.executable_facts(comp)
    assert facts is not None and facts["flops"] > 0
    assert compat.executable_cost_analysis(object()) is None
    assert compat.executable_memory_analysis(object()) is None


# ---------------------------------------------------------------------------
# serving: spans, budget, fault events under drain
# ---------------------------------------------------------------------------
def _fake_model(name="toy", fn=None):
    from paddle_tpu.serving import Model
    return Model(name, fn or (lambda feeds: [np.asarray(feeds["x"]) * 2.0]),
                 example={"x": np.zeros(2, "float32")})


def test_serving_request_batch_spans_and_budget(tmp_path):
    from paddle_tpu.serving import Server
    log = tmp_path / "serve.jsonl"
    flags.set_flag("metrics_log", str(log))
    srv = Server(max_batch=4, max_wait_ms=2, deadline_ms=None,
                 warmup=False)
    srv.add_model(_fake_model())
    srv.start()
    try:
        for i in range(6):
            srv.infer({"x": np.ones(2, "float32") * i}, timeout=10)
    finally:
        srv.shutdown()
    flags.set_flag("metrics_log", "")
    events = _read_events(log)
    spans = _spans(events)
    reqs = [e for e in spans if e["name"] == "serving/request"]
    batches = [e for e in spans if e["name"] == "serving/batch"]
    assert len(reqs) == 6 and batches
    # one trace per request; batch spans link member request traces and
    # every member request's completion lands inside its batch window
    assert len({e["trace"] for e in reqs}) == 6
    by_id = {(e.get("labels") or {}).get("id"): e for e in reqs}
    linked = set()
    for b in batches:
        labels = b["labels"]
        assert labels["traces"]
        b_end = b["t0"] + b["dur_ms"] / 1e3
        for rid in labels["requests"]:
            r = by_id[rid]
            r_end = r["t0"] + r["dur_ms"] / 1e3
            assert b["t0"] - 1e-6 <= r_end <= b_end + 1e-6
            linked.add(rid)
    assert linked == set(by_id)
    assert all((e.get("labels") or {}).get("status") == "ok"
               for e in reqs)

    from paddle_tpu.observability import attribution
    sb = attribution.serving_budget(events)
    assert sb["served"] == 6 and sb["within_tolerance"]
    assert sb["budget"]["dispatch_ms_mean"] is not None


def test_retry_and_breaker_span_events_survive_drain(tmp_path):
    """Chaos round: a transient dispatch failure leaves a `retry` span
    event, repeated fatal batches leave a `breaker_open` span event, and
    both survive a drain-to-stopped shutdown (the SIGTERM handler path —
    serving/cli.py wires SIGTERM to exactly this drain; the subprocess
    round lives in the @slow chaos suite)."""
    from paddle_tpu import faults
    from paddle_tpu.serving import Server
    log = tmp_path / "chaos.jsonl"
    flags.set_flag("metrics_log", str(log))

    flaky_calls = {"n": 0}

    def flaky(feeds):
        flaky_calls["n"] += 1
        if flaky_calls["n"] == 1:
            raise faults.TransientDispatchError("injected transient")
        return [np.asarray(feeds["x"]) * 2.0]

    def poisoned(feeds):
        raise ValueError("poisoned tenant")

    srv = Server(max_batch=2, max_wait_ms=1, deadline_ms=None,
                 warmup=False, breaker_threshold=2)
    srv.add_model(_fake_model("flaky", flaky))
    srv.add_model(_fake_model("bad", poisoned))
    srv.start()
    try:
        # transient -> retried inside the SAME batch span
        out = srv.infer({"x": np.ones(2, "float32")}, model="flaky",
                        timeout=10)
        assert np.allclose(out[0], 2.0)
        # two fatal batches -> breaker opens on the second
        for _ in range(2):
            p = srv.submit({"x": np.ones(2, "float32")}, model="bad")
            with pytest.raises(Exception):
                p.result(timeout=10)
        # breaker now open: the rejection is traced too
        with pytest.raises(faults.ModelUnavailable):
            srv.submit({"x": np.ones(2, "float32")}, model="bad")
    finally:
        srv.begin_drain()
        srv.shutdown()           # drain: every admitted request answered
    flags.set_flag("metrics_log", "")

    events = _read_events(log)
    spans = _spans(events)
    batch_events = [ev for e in spans if e["name"] == "serving/batch"
                    for ev in e.get("events", [])]
    assert any(ev["name"] == "retry" for ev in batch_events)
    assert any(ev["name"] == "breaker_open" for ev in batch_events)
    # drain left no un-terminated request span: every submit (including
    # the breaker-open rejection) emitted a terminal span
    reqs = [e for e in spans if e["name"] == "serving/request"]
    assert len(reqs) == 4
    assert any((e.get("labels") or {}).get("status") == "ModelUnavailable"
               for e in reqs)
    states = [str(e.get("state")) for e in events
              if e.get("kind") == "serving" and e.get("event") == "state"]
    assert states[-2:] == ["draining", "stopped"]


def test_rejected_request_span_carries_typed_status(tmp_path):
    """Admission rejections (Overloaded backpressure) still emit the
    request span with the typed status — shed requests are exactly what
    an overload trace must show (regression: rejection paths used to
    raise without ever ending the span)."""
    from paddle_tpu import faults
    from paddle_tpu.serving import Server
    log = tmp_path / "reject.jsonl"
    flags.set_flag("metrics_log", str(log))
    gate = threading.Event()

    def slow(feeds):
        gate.wait(10)
        return [np.asarray(feeds["x"]) * 2.0]

    srv = Server(max_batch=1, max_wait_ms=1, deadline_ms=None,
                 queue_capacity=1, shed=False, warmup=False,
                 staging_depth=1)
    srv.add_model(_fake_model("slow", slow))
    srv.start()
    admitted, rejected = [], 0
    try:
        # soak dispatcher + staging + queue, then keep offering until
        # the bounded queue rejects (backpressure, shed=False)
        for _ in range(12):
            try:
                admitted.append(srv.submit({"x": np.ones(2, "float32")}))
            except faults.Overloaded:
                rejected += 1
        assert rejected >= 1 and admitted
    finally:
        gate.set()
        srv.shutdown()
    flags.set_flag("metrics_log", "")
    reqs = [e for e in _spans(_read_events(log))
            if e["name"] == "serving/request"]
    statuses = {(e.get("labels") or {}).get("status") for e in reqs}
    assert "Overloaded" in statuses and "ok" in statuses
    # every admitted-or-rejected request reached a terminal span
    assert len(reqs) == len(admitted) + rejected


def test_failed_dispatch_still_emits_step_span(tmp_path):
    """A fatally failing dispatch ends the executor/step root with the
    typed status instead of leaving its dispatch child orphaned."""
    from paddle_tpu.testing import faultinject
    log = tmp_path / "fail.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    faultinject.configure("executor.dispatch@*=error")
    try:
        with pytest.raises(Exception, match="injected"):
            exe.run(feed=_batches(1)[0], fetch_list=[loss])
    finally:
        faultinject.clear()
        flags.set_flag("metrics_log", "")
    spans = _spans(_read_events(log))
    _assert_tree_invariants(spans)
    failed = [e for e in spans if e["name"] == "executor/step"
              and (e.get("labels") or {}).get("status") == "InjectedFault"]
    assert failed, f"no failed step span in {[e['name'] for e in spans]}"


def test_executor_retry_span_event(tmp_path):
    """A transient dispatch failure at the executor rim records a retry
    span event on the dispatch span."""
    from paddle_tpu import faults
    from paddle_tpu.testing import faultinject
    log = tmp_path / "retry.jsonl"
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(log))
    loss = _build_net()
    exe = pt.Executor(retry_policy=faults.RetryPolicy(
        max_attempts=2, backoff_base_s=0.0, jitter=0.0))
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    faultinject.configure("executor.dispatch@1=transient")
    try:
        exe.run(feed=_batches(1)[0], fetch_list=[loss])
    finally:
        faultinject.clear()
        flags.set_flag("metrics_log", "")
    spans = _spans(_read_events(log))
    dispatch = [e for e in spans if e["name"] == "executor/dispatch"]
    assert any(ev["name"] == "retry"
               for e in dispatch for ev in e.get("events", []))


# ---------------------------------------------------------------------------
# robustness + prometheus
# ---------------------------------------------------------------------------
def test_truncated_final_line_counted_not_fatal(tmp_path):
    """A process killed mid-write tears the final line — possibly inside
    a multi-byte UTF-8 character.  The summary skips it with a counted
    warning instead of aborting (UnicodeDecodeError regression)."""
    p = tmp_path / "torn.jsonl"
    good = ('{"ts": 1.0, "kind": "step", "steps": 2, "step_ms": 3.0,'
            ' "wall_ms": 6.0}\n')
    torn = '{"ts": 2.0, "kind": "step", "label": "café'.encode()[:-1]
    p.write_bytes(good.encode() + torn)
    s = obs.summarize_log(str(p))
    assert s["corrupt_lines"] == 1
    assert s["steps"]["steps"] == 2
    # and a clean multi-file merge still reports the torn file's count
    q = tmp_path / "ok.jsonl"
    q.write_text(good)
    merged = obs_export.summarize_logs([str(p), str(q)])
    assert merged["corrupt_lines"] == 1 and merged["steps"]["steps"] == 4


def test_merged_fault_timeline_carries_source_index(tmp_path):
    """A relaunched job produces one log per attempt; the merged faults
    timeline interleaves them by coerced ts ONLY, so each rendered row
    must also carry the source-file index (argument position) — without
    it an event is not attributable to the right attempt."""
    a, b = tmp_path / "attempt0.jsonl", tmp_path / "attempt1.jsonl"
    def fault(ts, event, **kw):
        return json.dumps({"ts": ts, "kind": "fault",
                           "event": event, **kw}) + "\n"
    # attempt 1's first fault lands BETWEEN attempt 0's two faults on
    # the clock (overlapping supervisor/child shutdown) — exactly the
    # interleaving ts-order cannot disambiguate
    a.write_text(fault(1.0, "inject", site="dispatch", step=3)
                 + fault(3.0, "relaunch", attempt=1, delay_s=0.5))
    b.write_text(fault(2.0, "restore", step=3)
                 + fault(4.0, "inject", site="dispatch", step=7))
    merged = obs_export.summarize_logs([str(a), str(b)])
    tl = merged["faults"]["timeline"]
    assert [(e.get("source"), e["event"]) for e in tl] == \
        [(0, "inject"), (1, "restore"), (0, "relaunch"), (1, "inject")]
    # restart boundaries name the same index the rows carry
    assert [(r["source"], r["file"]) for r in merged["restarts"]] == \
        [(0, str(a)), (1, str(b))]
    text = obs_export.render_summary(merged)
    assert "source=1 event=restore" in text
    assert "[1] " + str(b) in text
    # single-file summaries stay unchanged: no source column
    single = obs_export.summarize_logs([str(a)])
    assert all("source" not in e for e in single["faults"]["timeline"])
    assert "source=" not in obs_export.render_summary(single)


def test_prometheus_name_mangling_round_trip():
    names = [n for n, _k, _h in obs.METRIC_NAMES]
    mangled = [obs_export.prom_name(n) for n in names]
    assert len(set(mangled)) == len(names)          # no collisions
    for n, m in zip(names, mangled):
        assert obs_export.metric_name_from_prom(m) == n
        # the reversibility invariant: subsystem part carries no "_"
        assert "_" not in n.split("/")[0]
    with pytest.raises(ValueError):
        obs_export.metric_name_from_prom("not_paddle")


def test_prometheus_exposition_and_stats_prom_cli(tmp_path, capsys):
    flags.set_flag("observe", True)
    flags.set_flag("metrics_log", str(tmp_path / "prom.jsonl"))
    obs.inc_counter("executor/steps", 3)
    obs.observe_hist("executor/step_time_ms", 4.0)
    obs.set_gauge("device/bytes_in_use", 10, label="cpu:0")
    text = obs_export.to_prometheus(obs.metrics_snapshot())
    assert "paddle_tpu_executor_steps_total 3" in text
    assert 'paddle_tpu_executor_step_time_ms_bucket{le="5"} 1' in text
    assert "paddle_tpu_executor_step_time_ms_count 1" in text
    assert 'paddle_tpu_device_bytes_in_use{label="cpu:0"} 10' in text
    obs.periodic_report(step=1)           # snapshot event for the CLI
    flags.set_flag("metrics_log", "")
    from paddle_tpu.cli import main as cli_main
    assert cli_main(["stats", str(tmp_path / "prom.jsonl"),
                     "--prom"]) == 0
    out = capsys.readouterr().out
    assert "paddle_tpu_executor_steps_total 3" in out
    # no snapshot in the log -> a one-line error, not a traceback
    (tmp_path / "empty.jsonl").write_text("")
    with pytest.raises(SystemExit, match="no snapshot"):
        cli_main(["stats", str(tmp_path / "empty.jsonl"), "--prom"])
