"""v1 DSL tail coverage: the round-4 layer/network additions
(trainer_config_helpers/extra_layers.py, networks_extra.py) — every
reference v1_api_demo and benchmark/paddle config evaluates verbatim, and
the new wrappers produce finite forwards/training steps.

Reference surface: trainer_config_helpers/layers.py (133 defs) +
networks.py (21 defs); after this round the repo exports every one
(two raise NotImplementedError by design with guidance:
cross_entropy_over_beam, lambda_cost)."""
import os
import re
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.trainer_config_helpers import load_v1_config

REF = "/root/reference"


def _eval(path, **args):
    return load_v1_config(os.path.join(REF, path), **args)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_dsl_surface_complete():
    """Every def in the reference layers.py + networks.py is exported."""
    import paddle_tpu.trainer_config_helpers as tch
    have = set(tch.__all__)
    for mod in ("layers", "networks"):
        src = open(f"{REF}/python/paddle/trainer_config_helpers/"
                   f"{mod}.py").read()
        defs = set(re.findall(r"^def ([a-z]\w+)\(", src, re.M))
        missing = defs - have
        assert not missing, f"{mod}.py missing: {sorted(missing)}"


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
@pytest.mark.parametrize("path,args,min_ops", [
    ("v1_api_demo/gan/gan_conf.py", {}, 5),        # TRAINS in test_api_gan
    ("v1_api_demo/gan/gan_conf_image.py", {}, 10),  # same machinery
    ("v1_api_demo/model_zoo/resnet/resnet.py", {}, 150),  # + grad test below
])
def test_v1_demo_configs_evaluate(path, args, min_ops):
    cfg = _eval(path, **args)
    n = len(cfg.main_program.global_block().ops)
    assert n >= min_ops, (path, n)


def _demo_feeds(rng, path, B=4, T=3):
    """Synthetic feeds for ONE demo config, matching its provider format."""
    def sparse_features():
        s = np.zeros((B, T, 76328), "float32")   # sparse_binary_vector seq
        for b in range(B):
            for t in range(T):
                s[b, t, rng.choice(76328, 30, replace=False)] = 1.0
        return s

    makers = {
        "v1_api_demo/mnist/vgg_16_mnist.py": lambda: dict(
            feeds={"pixel": rng.rand(B, 784).astype("f4"),
                   "label": rng.randint(0, 10, (B, 1))}),
        "v1_api_demo/mnist/light_mnist.py": lambda: dict(
            feeds={"pixel": rng.rand(B, 784).astype("f4"),
                   "label": rng.randint(0, 10, (B, 1))}),
        "v1_api_demo/vae/vae_conf.py": lambda: dict(
            feeds={"x_batch": rng.rand(B, 784).astype("f4")}),
        "v1_api_demo/traffic_prediction/trainer_config.py": lambda: dict(
            feeds=dict({"link_encode": rng.rand(B, 24).astype("f4")},
                       **{f"label_{m}min": rng.randint(0, 4, (B, 1))
                          for m in range(5, 125, 5)})),
        "v1_api_demo/sequence_tagging/rnn_crf.py": lambda: dict(
            feeds={"word": rng.randint(0, 6778, (B, T)),
                   "word@LEN": np.full(B, T),
                   "pos": rng.randint(0, 44, (B, T)),
                   "pos@LEN": np.full(B, T),
                   "chunk": rng.randint(0, 23, (B, T)),
                   "chunk@LEN": np.full(B, T)}),
        "v1_api_demo/sequence_tagging/linear_crf.py": lambda: dict(
            feeds={"features": sparse_features(),
                   "features@LEN": np.full(B, T),
                   "chunk": rng.randint(0, 23, (B, T)),
                   "chunk@LEN": np.full(B, T)},
            seq=("features", "word", "pos")),
    }
    return makers[path]()


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
@pytest.mark.parametrize("path,steps", [
    ("v1_api_demo/mnist/vgg_16_mnist.py", 6),
    ("v1_api_demo/mnist/light_mnist.py", 4),
    ("v1_api_demo/vae/vae_conf.py", 6),
    ("v1_api_demo/traffic_prediction/trainer_config.py", 4),
    ("v1_api_demo/sequence_tagging/linear_crf.py", 4),
    ("v1_api_demo/sequence_tagging/rnn_crf.py", 4),
])
def test_v1_demo_configs_train(path, steps, rng):
    """Round 5: demo configs TRAIN (optimizer steps, loss decreasing) —
    the test_v1_config.py:79 pattern applied to the demo tree.  Feeds
    mirror each demo's DataProvider format (sparse-binary tag features,
    multi-task traffic labels, raw mnist pixels); the GAN pair trains via
    the GradientMachine facade in test_api_gan.py."""
    spec = _demo_feeds(rng, path)
    cfg = load_v1_config(os.path.join(REF, path),
                         sequence_inputs=spec.get("seq", ()))
    loss = cfg.minimize_outputs()
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    vals = [float(exe.run(cfg.main_program, feed=spec["feeds"],
                          fetch_list=[loss])[0]) for _ in range(steps)]
    assert np.isfinite(vals).all(), (path, vals)
    assert min(vals[1:]) < vals[0], (path, vals)


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
def test_model_zoo_resnet_gradients_flow(rng):
    """model_zoo/resnet is an inference tower (Outputs names feature
    layers, no cost): assert gradients flow end to end by attaching a
    mean cost to the named output and taking one SGD step that moves the
    stem conv weights."""
    cfg = _eval("v1_api_demo/model_zoo/resnet/resnet.py")
    gb = cfg.main_program.global_block()
    out_name = cfg.outputs[0]
    assert isinstance(out_name, str) and out_name == "res5_3_branch2c_conv"
    var = gb.vars[out_name + ".tmp_0"]
    import paddle_tpu.core.program as _prog
    with _prog.program_guard(cfg.main_program, cfg.startup_program):
        from paddle_tpu import layers
        loss = layers.mean(var)
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    stem = next(n for n in pt.global_scope().keys() if n.endswith(".w0")
                and "conv1" in n)
    before = np.asarray(pt.global_scope().get(stem)).copy()
    feed = {"input": rng.rand(2, 3 * 224 * 224).astype("f4") * 0.1}
    if "label" in cfg.data_layers:      # the config's (unused) cost branch
        feed["label"] = rng.randint(0, 10, (2, 1))
    (lv,) = exe.run(cfg.main_program, feed=feed, fetch_list=[loss])
    assert np.isfinite(float(lv))
    assert not np.allclose(before, np.asarray(pt.global_scope().get(stem)))


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
def test_resnet_lstm_quickstart_evaluates(tmp_path, monkeypatch):
    """quick_start/trainer_config.resnet-lstm.py (GNMT-style residual
    LSTM stack) reads ./data/dict.txt at evaluation time."""
    (tmp_path / "data").mkdir()
    with open(tmp_path / "data" / "dict.txt", "w") as f:
        for i in range(100):
            f.write(f"word{i}\t{i}\n")
    monkeypatch.chdir(tmp_path)
    cfg = _eval("v1_api_demo/quick_start/trainer_config.resnet-lstm.py")
    assert len(cfg.main_program.global_block().ops) >= 30


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
def test_benchmark_rnn_config_evaluates(tmp_path, monkeypatch):
    """benchmark/paddle/rnn/rnn.py imports its sibling imdb module and
    prepares data at parse time; satisfy both with the stub protocol the
    reference itself uses (imdb.train.pkl presence check)."""
    import sys
    (tmp_path / "imdb.py").write_text(textwrap.dedent("""
        def create_data(path="imdb.pkl"):
            pass
    """))
    monkeypatch.chdir(tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    sys.modules.pop("imdb", None)
    try:
        cfg = _eval("benchmark/paddle/rnn/rnn.py", batch_size=4)
        ops = [op.type for op in cfg.main_program.global_block().ops]
        assert any("lstm" in t or "while" in t or "scan" in t or
                   "rnn" in t for t in ops) or len(ops) > 10
    finally:
        sys.modules.pop("imdb", None)


def _run_cfg(body, feeds, n_steps=0, fetch_all=True):
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(body))
        path = f.name
    cfg = load_v1_config(path)
    exe = pt.Executor()
    if n_steps:
        loss = cfg.minimize_outputs()     # creates optimizer state in startup
        exe.run(cfg.startup_program, feed={}, fetch_list=[])
        vals = [float(exe.run(cfg.main_program, feed=feeds,
                              fetch_list=[loss])[0])
                for _ in range(n_steps)]
        return vals
    exe.run(cfg.startup_program, feed={}, fetch_list=[])
    outs = exe.run(cfg.main_program, feed=feeds, fetch_list=cfg.outputs,
                   is_test=True)
    if fetch_all:
        for o in outs:
            assert np.isfinite(np.asarray(o, dtype=np.float64)).all()
    return outs


def test_image_tail_layers_forward(rng):
    """pad/crop/rotate/spp/maxout/prelu/resize/switch_order/block_expand
    in one config, forward finite."""
    outs = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.01)
        img = data_layer(name='pixel', size=3 * 8 * 8)
        conv = img_conv_layer(input=img, filter_size=3, num_channels=3,
                              num_filters=4, padding=1,
                              act=ReluActivation())
        padded = pad_layer(input=conv, pad_h=[1, 1], pad_w=[1, 1])
        cropped = crop_layer(input=padded, offset=[1, 1],
                             shape=[4, 4, 8, 8])
        rot = rotate_layer(input=cropped, height=8, width=8)
        sw = switch_order_layer(input=rot)
        pyramid = spp_layer(input=conv, pyramid_height=2)
        mx = maxout_layer(input=conv, groups=2)
        pr = prelu_layer(input=conv)
        rs = resize_layer(input=conv, size=4 * 8 * 8)
        be = block_expand_layer(input=conv, num_channels=4, block_x=4,
                                block_y=4, stride_x=4, stride_y=4)
        outputs(sum_cost(input=rs), sum_cost(input=pyramid),
                sum_cost(input=resize_layer(input=mx, size=2*8*8)),
                sum_cost(input=resize_layer(input=pr, size=4*8*8)),
                sum_cost(input=resize_layer(input=sw, size=4*8*8)))
    """, {"pixel": rng.rand(4, 3 * 8 * 8).astype("float32")})
    assert len(outs) == 5


def test_algebra_tail_layers_forward(rng):
    outs = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.01)
        a = data_layer(name='a', size=16)
        b = data_layer(name='b', size=16)
        dp = dot_prod_layer(input1=a, input2=b)
        l2 = l2_distance_layer(x=a, y=b)
        rn = row_l2_norm_layer(input=a)
        lc = linear_comb_layer(weights=data_layer(name='w', size=4),
                               vectors=a, size=4)
        gu = gated_unit_layer(input=a, size=8)
        ss = scale_shift_layer(input=a)
        cl = clip_layer(input=a, min=0.2, max=0.8)
        tl = tensor_layer(a=a, b=b, size=4)
        outputs(sum_cost(input=dp), sum_cost(input=l2),
                sum_cost(input=rn), sum_cost(input=lc),
                sum_cost(input=gu), sum_cost(input=ss),
                sum_cost(input=cl), sum_cost(input=tl))
    """, {"a": rng.rand(4, 16).astype("float32"),
          "b": rng.rand(4, 16).astype("float32"),
          "w": rng.rand(4, 4).astype("float32")})
    assert len(outs) == 8


def test_cost_tail_layers_train(rng):
    """huber/rank/smooth_l1/multi-binary/selfnorm costs all train."""
    vals = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=8, learning_rate=0.05,
                 learning_method=AdamOptimizer())
        x = data_layer(name='x', size=16)
        y = data_layer(name='y', size=4)
        h = fc_layer(input=x, size=4, act=SigmoidActivation())
        c1 = huber_regression_cost(input=h, label=y)
        c2 = smooth_l1_cost(input=h, label=y)
        c3 = multi_binary_label_cross_entropy(
            input=fc_layer(input=x, size=4, act=LinearActivation()),
            label=y)
        total = c1 + c2 + c3
        outputs(sum_cost(input=total))
    """, {"x": rng.rand(8, 16).astype("float32"),
          "y": (rng.rand(8, 4) > 0.5).astype("float32")},
        n_steps=6)
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


def test_conv3d_pool3d_layers(rng):
    outs = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=2, learning_rate=0.01)
        vol = data_layer(name='vol', size=1 * 4 * 8 * 8)
        # v1 3-D layers operate on an explicit NCDHW reshape
        r = resize_layer(input=vol, size=4 * 8 * 8)
        outputs(sum_cost(input=r))
    """, {"vol": rng.rand(2, 256).astype("float32")})
    # direct fluid-level 3-D path (the DSL wrappers call these)
    from paddle_tpu import layers
    x = layers.data("v3", shape=[1, 4, 8, 8], dtype="float32")
    c = layers.conv3d(x, num_filters=2, filter_size=3, padding=1)
    p = layers.pool3d(c, pool_size=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    (pv,) = exe.run(pt.default_main_program(),
                    feed={"v3": rng.rand(2, 1, 4, 8, 8).astype("float32")},
                    fetch_list=[p], is_test=True)
    assert pv.shape == (2, 2, 2, 4, 4) and np.isfinite(pv).all()


def test_sequence_tail_layers(rng):
    outs = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.01)
        ids = data_layer(name='ids', size=50)
        emb = embedding_layer(input=ids, size=8)
        with mixed_layer(size=24) as ctxp:
            ctxp += context_projection(input=emb, context_len=3)
        sc = seq_concat_layer(a=emb, b=emb)
        mh = multi_head_attention(
            query=last_seq(input=emb), key=emb, value=emb,
            key_proj_size=8, value_proj_size=8, head_num=2)
        bg = bidirectional_gru(input=emb, size=4)
        dpa = dot_product_attention(
            encoded_sequence=emb, attended_sequence=emb,
            transformed_state=fc_layer(input=last_seq(input=emb), size=8))
        outputs(sum_cost(input=last_seq(input=ctxp)),
                sum_cost(input=last_seq(input=sc)),
                sum_cost(input=mh), sum_cost(input=bg),
                sum_cost(input=dpa))
    """, {"ids": rng.randint(0, 50, (4, 6)),
          "ids@LEN": np.full(4, 6)})
    assert len(outs) == 5


def test_multiplex_eos_sampling(rng):
    from paddle_tpu import layers
    idx = layers.data("idx", shape=[1], dtype="int64")
    a = layers.data("a", shape=[8], dtype="float32")
    b = layers.data("b", shape=[8], dtype="float32")
    m = layers.multiplex([a, b], idx)
    probs = layers.softmax(a)
    sid = layers.sampling_id(probs)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    av = rng.rand(4, 8).astype("float32")
    bv = rng.rand(4, 8).astype("float32")
    mv, sv = exe.run(pt.default_main_program(),
                     feed={"idx": np.array([[0], [1], [0], [1]]),
                           "a": av, "b": bv},
                     fetch_list=[m, sid], is_test=True)
    np.testing.assert_allclose(mv[0], av[0], rtol=1e-6)
    np.testing.assert_allclose(mv[1], bv[1], rtol=1e-6)
    assert sv.shape == (4,) and (sv >= 0).all() and (sv < 8).all()


def test_no_unimplemented_costs_remain():
    """Round 5 closes the last two declared-unsupported DSL costs:
    lambda_cost (test_lambda_rank.py) and cross_entropy_over_beam
    (test_generation.py::test_cross_entropy_over_beam_trains) are real
    implementations now — the surface carries zero NotImplementedError
    cost layers."""
    import inspect

    import paddle_tpu.trainer_config_helpers as tch
    for n in ("lambda_cost", "cross_entropy_over_beam"):
        src = inspect.getsource(getattr(tch, n))
        assert "NotImplementedError" not in src, n


def test_default_decorators_feed_optimizer(tmp_path):
    """model_zoo ordering: default_momentum/decay_rate called around
    Settings() must reach the built optimizer (round-4 review fix)."""
    p = tmp_path / "cfg.py"
    p.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *
        momentum = 0.7
        default_momentum(momentum)
        default_decay_rate(0.013)
        Settings(algorithm='sgd', batch_size=4, learning_rate=0.1,
                 learning_method='momentum')
        x = data_layer(name='x', size=8)
        y = data_layer(name='y', size=1)
        outputs(regression_cost(input=fc_layer(
            input=x, size=1, act=LinearActivation()), label=y))
    """))
    cfg = load_v1_config(str(p))
    assert cfg.settings["learning_method"].momentum == 0.7
    import paddle_tpu.core.program as _prog
    with _prog.program_guard(cfg.main_program, cfg.startup_program):
        opt = cfg.make_optimizer()
    assert getattr(opt, "regularization", None) is not None


def test_prelu_element_mode(rng):
    from paddle_tpu import layers
    x = layers.data("x", shape=[3, 4, 5], dtype="float32")
    out = layers.prelu(x, mode="element")
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    xv = rng.randn(2, 3, 4, 5).astype("float32")
    (ov,) = exe.run(pt.default_main_program(), feed={"x": xv},
                    fetch_list=[out], is_test=True)
    np.testing.assert_allclose(ov, np.where(xv >= 0, xv, 0.25 * xv),
                               rtol=1e-5)


def test_conv_operator_per_sample_filters(rng):
    """conv_operator's filter layer yields one filter set per sample."""
    vals = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.01)
        img = data_layer(name='pixel', size=2 * 6 * 6)
        filt = data_layer(name='filt', size=3 * 2 * 3 * 3)
        with mixed_layer(size=3 * 4 * 4) as m:
            m += conv_operator(img=img, filter=filt, filter_size=3,
                               num_filters=3, num_channels=2)
        outputs(sum_cost(input=m))
    """, {"pixel": rng.rand(4, 72).astype("float32"),
          "filt": rng.rand(4, 54).astype("float32")})
    # cross-check sample 0 against numpy conv with ITS OWN filter
    import tempfile
    from paddle_tpu.trainer_config_helpers import load_v1_config as lc
    # (numeric check through the op directly)
    from paddle_tpu import layers
    pt.core.reset_default_programs(); pt.core.reset_global_scope()
    pt.unique_name.reset()
    x = layers.data("x", shape=[2, 6, 6], dtype="float32")
    f = layers.data("f", shape=[54], dtype="float32")
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("conv_operator")
    out = helper.create_variable_for_type_inference("float32", (-1, 3, 4, 4))
    helper.append_op(type="conv2d_dynamic_filter",
                     inputs={"Input": [x], "Filter": [f]},
                     outputs={"Output": [out]},
                     attrs={"filter_shape": [3, 2, 3, 3],
                            "strides": [1, 1], "paddings": [0, 0]})
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    xv = rng.rand(4, 2, 6, 6).astype("float32")
    fv = rng.rand(4, 54).astype("float32")
    (ov,) = exe.run(pt.default_main_program(), feed={"x": xv, "f": fv},
                    fetch_list=[out], is_test=True)
    w0 = fv[1].reshape(3, 2, 3, 3)
    ref = np.zeros((3, 4, 4), np.float32)
    for o in range(3):
        for i_ in range(4):
            for j_ in range(4):
                ref[o, i_, j_] = np.sum(
                    xv[1, :, i_:i_ + 3, j_:j_ + 3] * w0[o])
    np.testing.assert_allclose(ov[1], ref, rtol=2e-2, atol=1e-4)


def test_sub_nested_seq_invalid_indices(rng):
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu import layers
    x = layers.data("x", shape=[3, 4], dtype="float32")   # [B,S,T] no D
    x.lod_level = 2
    sel = layers.data("sel", shape=[2], dtype="int64")
    from paddle_tpu.trainer_config_helpers.extra_layers import \
        sub_nested_seq_layer
    out = sub_nested_seq_layer(x, sel)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    xv = rng.rand(2, 3, 4).astype("float32")
    sv = np.array([[1, -1], [2, 0]], np.int64)
    outs = exe.run(pt.default_main_program(),
                   feed={"x": xv, "sel": sv},
                   fetch_list=[out, out.name + "@LEN"], is_test=True)
    ov, lens = outs
    np.testing.assert_allclose(ov[0, 0], xv[0, 1], rtol=1e-6)
    assert np.allclose(ov[0, 1], 0)         # -1 pick masked out
    np.testing.assert_allclose(ov[1, 1], xv[1, 0], rtol=1e-6)
    assert list(lens) == [1, 2]


def test_context_projection_trainable_padding(rng):
    """padding_attr=ParamAttr trains boundary rows: gradients reach the
    padding parameter (review fix — it used to be silently dropped)."""
    vals = _run_cfg("""
        from paddle.trainer_config_helpers import *
        settings(batch_size=4, learning_rate=0.1,
                 learning_method=AdamOptimizer())
        ids = data_layer(name='ids', size=50)
        emb = embedding_layer(input=ids, size=8)
        with mixed_layer(size=24) as m:
            m += context_projection(input=emb, context_len=3,
                                    padding_attr=ParamAttr(name="ctx_pad"))
        outputs(sum_cost(input=last_seq(input=m)))
    """, {"ids": rng.randint(0, 50, (4, 5)), "ids@LEN": np.full(4, 5)},
        n_steps=4)
    assert np.isfinite(vals).all()
    pad = np.asarray(pt.global_scope().get("ctx_pad"))
    assert pad.shape == (2, 8) and not np.allclose(pad, 0)
