"""Timing utilities: Stat/global_stat thread safety, StepTimer warmup
semantics, compile_report, the reentrancy-guarded profiler() context
manager, and the merged report surface."""
import re
import threading
import time

import pytest

from paddle_tpu import profiler


# ---------------------------------------------------------------------------
# Stat
# ---------------------------------------------------------------------------
def test_stat_accumulates_and_reports():
    st = profiler.Stat()
    for _ in range(3):
        with st.timer("fwd"):
            pass
    with st.timer("bwd"):
        pass
    rep = st.report()
    assert "StatSet" in rep
    m = re.search(r"fwd: total=\S+ count=(\d+)", rep)
    assert m and int(m.group(1)) == 3
    assert "bwd" in rep
    st.reset()
    assert "fwd" not in st.report()


def test_stat_thread_safe_concurrent_timers():
    st = profiler.Stat()
    n_threads, n_iters = 8, 500

    def work():
        for _ in range(n_iters):
            with st.timer("x"):
                pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    m = re.search(r"x: total=\S+ count=(\d+)", st.report())
    assert m and int(m.group(1)) == n_threads * n_iters


def test_stat_report_survives_reset_race():
    """reset()/report() racing live timer() scopes must neither crash
    (dict-changed-size, ZeroDivisionError) nor deadlock."""
    st = profiler.Stat()
    stop = threading.Event()
    errors = []

    def churn():
        try:
            i = 0
            while not stop.is_set():
                with st.timer(f"op{i % 5}"):
                    pass
                i += 1
        except Exception as e:      # pragma: no cover - the failure mode
            errors.append(e)

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 0.5
    try:
        while time.monotonic() < deadline:
            st.report()
            st.reset()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors


def test_global_stat_and_timer_helper():
    profiler.global_stat().reset()
    with profiler.timer("step"):
        pass
    assert "step" in profiler.global_stat().report()
    profiler.global_stat().reset()


# ---------------------------------------------------------------------------
# StepTimer
# ---------------------------------------------------------------------------
def test_step_timer_warmup_discard():
    st = profiler.StepTimer(warmup=2)
    returned = []
    for _ in range(5):
        st.start()
        returned.append(st.stop())
    # every stop() returns its wall time, but only post-warmup steps record
    assert len(returned) == 5
    assert len(st.times) == 3
    assert st.mean == pytest.approx(sum(st.times) / 3)


def test_step_timer_mean_empty_is_zero():
    assert profiler.StepTimer(warmup=2).mean == 0


# ---------------------------------------------------------------------------
# compile_report / merged report
# ---------------------------------------------------------------------------
def test_compile_report_is_stat_style_text():
    rep = profiler.compile_report()
    assert isinstance(rep, str) and "CompileStats" in rep


def test_merged_report_has_all_three_sections():
    rep = profiler.report()
    assert "StatSet" in rep
    assert "CompileStats" in rep
    assert "Metrics" in rep


def test_metrics_snapshot_reexport_shape():
    snap = profiler.metrics_snapshot()
    assert set(snap) == {"metrics", "compile", "device_memory"}
    assert all(k.startswith("compile/") for k in snap["compile"])


# ---------------------------------------------------------------------------
# profiler() context manager
# ---------------------------------------------------------------------------
@pytest.fixture
def fake_trace(monkeypatch):
    import jax
    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda *a, **k: calls.__setitem__("start", calls["start"] + 1))
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    return calls


def test_profiler_ctx_nested_is_single_session(fake_trace):
    with profiler.profiler("/tmp/t1"):
        with profiler.profiler("/tmp/t2"):   # nested: no-op inner scope
            with profiler.profiler("/tmp/t3"):
                pass
        assert fake_trace == {"start": 1, "stop": 0}
    assert fake_trace == {"start": 1, "stop": 1}


def test_profiler_ctx_accepts_and_ignores_reference_args(fake_trace):
    with profiler.profiler("/tmp/t", state="GPU", sorted_key="total"):
        pass
    assert fake_trace == {"start": 1, "stop": 1}


def test_profiler_ctx_recovers_after_start_failure(fake_trace, monkeypatch):
    import jax
    fixture_fake = jax.profiler.start_trace   # the fake from fake_trace

    def boom(*a, **k):
        raise RuntimeError("collector busy")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.raises(RuntimeError, match="collector busy"):
        with profiler.profiler("/tmp/t"):
            pass                      # pragma: no cover - never reached
    # the failed enter must not leave a stuck depth: a later scope starts
    monkeypatch.setattr(jax.profiler, "start_trace", fixture_fake)
    with profiler.profiler("/tmp/t"):
        pass
    assert fake_trace == {"start": 1, "stop": 1}


def test_cuda_profiler_alias():
    assert profiler.cuda_profiler is profiler.profiler


def test_stat_timer_times_real_work():
    st = profiler.Stat()
    with st.timer("sleep"):
        time.sleep(0.01)
    m = re.search(r"sleep: total=(\S+)ms", st.report())
    assert m and float(m.group(1)) >= 8.0
