"""The GradientMachine manual-training-loop facade (paddle_tpu.api) drives
the reference GAN demo's alternating D/G idiom — three machines built from
the VERBATIM reference config (v1_api_demo/gan/gan_conf.py), parameter
sharing by name, script-owned training decisions
(v1_api_demo/gan/gan_trainer.py:156-298)."""
import os

import numpy as np
import pytest

from paddle_tpu import api

GAN_CONF = "/root/reference/v1_api_demo/gan/gan_conf.py"

pytestmark = pytest.mark.skipif(not os.path.exists(GAN_CONF),
                                reason="reference not mounted")


def _noise(rng, n, dim=10):
    return rng.normal(size=(n, dim)).astype("float32")


def test_machine_forward_and_param_access(rng):
    m = api.GradientMachine.createFromConfig(
        GAN_CONF, "mode=generator,data=uniform")
    names = m.getParameterNames()
    # deterministic v1 parameter names from the config's layer names
    assert "_gen_layer_hidden.w0" in names
    assert "_gen_layer_hidden.wbias" in names
    (sample,) = m.forward({"noise": _noise(rng, 16)})
    assert sample.shape == (16, 2) and np.isfinite(sample).all()
    # setParameter round trip
    w = m.getParameter("_gen_layer_hidden.w0")
    m.setParameter("_gen_layer_hidden.w0", w * 0.0)
    (zeroed,) = m.forward({"noise": _noise(rng, 16)})
    assert not np.allclose(sample, zeroed)


def test_gan_alternating_training(rng):
    """Both configs build machines, train alternately on synthetic data,
    D and G losses both move, and the shared-parameter copies keep the
    generator machine in sync (the gan_trainer.py:284-298 idiom)."""
    dis_m = api.GradientMachine.createFromConfig(
        GAN_CONF, "mode=discriminator_training,data=uniform")
    gen_m = api.GradientMachine.createFromConfig(
        GAN_CONF, "mode=generator_training,data=uniform")
    g_only = api.GradientMachine.createFromConfig(
        GAN_CONF, "mode=generator,data=uniform")

    # shared-name layout: the gen-training machine contains BOTH networks
    assert "_dis_hidden.w0" in gen_m.getParameterNames()
    assert "_dis_hidden.w0" in dis_m.getParameterNames()
    assert "_gen_layer_hidden.w0" in g_only.getParameterNames()

    api.copy_shared_parameters(gen_m, dis_m)
    api.copy_shared_parameters(gen_m, g_only)
    np.testing.assert_array_equal(gen_m.getParameter("_dis_hidden.w0"),
                                  dis_m.getParameter("_dis_hidden.w0"))

    dis_trainer = api.Trainer.create(dis_m)
    gen_trainer = api.Trainer.create(gen_m)
    dis_trainer.startTrain()
    gen_trainer.startTrain()

    B = 64
    data = rng.rand(100 * B, 2).astype("float32")  # "uniform" source
    ones = np.ones((B, 1), "int64")
    zeros = np.zeros((B, 1), "int64")

    d_w0 = dis_m.getParameter("_dis_hidden.w0").copy()
    g_w0 = gen_m.getParameter("_gen_layer_hidden.w0").copy()

    curr_train, curr_strike, MAX_strike = "dis", 0, 3
    d_losses, g_losses = [], []
    n_dis = n_gen = 0
    dis_trainer.startTrainPass()
    gen_trainer.startTrainPass()
    for i in range(40):
        noise = _noise(rng, B)
        real = data[rng.choice(len(data), B, replace=False)]
        (fake,) = g_only.forward({"noise": noise})
        batch_pos = {"sample": real, "label": ones}
        batch_neg = {"sample": fake, "label": zeros}
        d_loss = 0.5 * (dis_m.get_loss(batch_pos) +
                        dis_m.get_loss(batch_neg))
        batch_gen = {"noise": noise, "label": ones}
        g_loss = gen_m.get_loss(batch_gen)
        d_losses.append(d_loss)
        g_losses.append(g_loss)

        if (not (curr_train == "dis" and curr_strike == MAX_strike)) and \
           ((curr_train == "gen" and curr_strike == MAX_strike)
                or d_loss > g_loss):
            curr_strike = curr_strike + 1 if curr_train == "dis" else 1
            curr_train = "dis"
            dis_trainer.trainOneDataBatch(B, batch_neg)
            dis_trainer.trainOneDataBatch(B, batch_pos)
            api.copy_shared_parameters(dis_m, gen_m)
            n_dis += 1
        else:
            curr_strike = curr_strike + 1 if curr_train == "gen" else 1
            curr_train = "gen"
            gen_trainer.trainOneDataBatch(B, batch_gen)
            api.copy_shared_parameters(gen_m, dis_m)
            api.copy_shared_parameters(gen_m, g_only)
            n_gen += 1
    dis_trainer.finishTrainPass()
    gen_trainer.finishTrainPass()

    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    assert n_dis > 0 and n_gen > 0, (n_dis, n_gen)
    # both networks actually trained (losses moved, params moved)
    assert not np.allclose(dis_m.getParameter("_dis_hidden.w0"), d_w0)
    assert not np.allclose(gen_m.getParameter("_gen_layer_hidden.w0"), g_w0)
    # shared copies kept the sampling machine in sync with the trained gen
    np.testing.assert_array_equal(
        g_only.getParameter("_gen_layer_hidden.w0"),
        gen_m.getParameter("_gen_layer_hidden.w0"))
    # the static side stays frozen within each machine's own step:
    # gen-training must not have changed dis params EXCEPT via copies
    np.testing.assert_array_equal(gen_m.getParameter("_dis_hidden.w0"),
                                  dis_m.getParameter("_dis_hidden.w0"))


def test_gan_conf_image_trains(rng):
    """The conv/deconv GAN config (gan_conf_image.py, data=mnist) also
    trains through the facade: one D step + one G step, losses finite,
    both networks' weights move."""
    conf = "/root/reference/v1_api_demo/gan/gan_conf_image.py"
    dis_m = api.GradientMachine.createFromConfig(
        conf, "mode=discriminator_training,data=mnist")
    gen_m = api.GradientMachine.createFromConfig(
        conf, "mode=generator_training,data=mnist")
    api.copy_shared_parameters(gen_m, dis_m)

    B = 4
    sample = rng.rand(B, 28 * 28).astype("f4") * 2 - 1
    noise = rng.normal(size=(B, 100)).astype("f4")
    d_name = next(n for n in dis_m.getParameterNames()
                  if n.startswith("_dis_") and n.endswith(".w0"))
    g_name = next(n for n in gen_m.getParameterNames()
                  if n.startswith("_gen_") and n.endswith(".w0"))
    d_before = dis_m.getParameter(d_name).copy()
    g_before = gen_m.getParameter(g_name).copy()
    d_loss = dis_m.train_batch({"sample": sample,
                                "label": np.ones((B, 1), "int64")})
    g_loss = gen_m.train_batch({"noise": noise,
                                "label": np.ones((B, 1), "int64")})
    assert np.isfinite(d_loss) and np.isfinite(g_loss)
    assert not np.allclose(d_before, dis_m.getParameter(d_name))
    assert not np.allclose(g_before, gen_m.getParameter(g_name))


def test_trainer_pass_bookkeeping(rng):
    m = api.GradientMachine.createFromConfig(
        GAN_CONF, "mode=discriminator_training,data=uniform")
    t = api.Trainer.create(m)
    t.startTrainPass()
    loss = t.trainOneDataBatch(8, {"sample": rng.rand(8, 2).astype("f4"),
                                   "label": np.ones((8, 1), "int64")})
    t.finishTrainPass()
    assert np.isfinite(loss) and t.pass_id == 1
