"""Per-op tests for metrics, CTC, NCE, hsigmoid, detection, control flow,
LR schedules, evaluators — the remaining SURVEY §2.2 categories."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from op_test import check_grad, check_output, run_op

R = np.random.RandomState(17)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
def test_auc_matches_sklearn_style():
    n = 200
    label = R.randint(0, 2, (n, 1))
    # informative scores
    score = np.clip(label[:, 0] * 0.3 + R.rand(n) * 0.7, 0, 1)
    pred = np.stack([1 - score, score], 1).astype("float32")
    got = run_op("auc", {"Predict": ("p", pred), "Label": ("l", label)},
                 {"num_thresholds": 200}, ["AUC"])
    auc = float(got["auc__out0"][0])

    # brute-force pairwise AUC
    pos = score[label[:, 0] == 1]
    neg = score[label[:, 0] == 0]
    pairs = (pos[:, None] > neg[None, :]).mean() + \
        0.5 * (pos[:, None] == neg[None, :]).mean()
    assert abs(auc - pairs) < 0.02, (auc, pairs)


def test_precision_recall_op():
    idx = np.array([0, 1, 1, 2, 2, 2])
    lab = np.array([0, 1, 2, 2, 2, 0])
    got = run_op("precision_recall",
                 {"Indices": ("i", idx.reshape(-1, 1)),
                  "Labels": ("l", lab.reshape(-1, 1))},
                 {"class_number": 3}, ["BatchMetrics"])
    m = got["batchmetrics__out0"]
    # micro precision = accuracy here = 4/6
    np.testing.assert_allclose(m[3], 4 / 6, atol=1e-6)


def test_positive_negative_pair():
    qid = np.array([0, 0, 0, 1, 1])
    label = np.array([2, 1, 0, 1, 0]).astype("float32")
    score = np.array([0.9, 0.8, 0.85, 0.3, 0.6]).astype("float32")
    got = run_op("positive_negative_pair",
                 {"Score": ("s", score.reshape(-1, 1)),
                  "Label": ("l", label.reshape(-1, 1)),
                  "QueryID": ("q", qid.reshape(-1, 1))},
                 {}, ["PositivePair", "NegativePair"])
    # q0 pairs: (0>1 ok), (0>2 ok), (1>2 wrong: 0.8<0.85); q1: (3>4 wrong)
    assert float(got["positivepair__out0"][0]) == 2.0
    assert float(got["negativepair__out0"][0]) == 2.0


def test_chunk_eval_iob():
    """IOB chunking F1 (ChunkEvaluator/chunk_eval_op)."""
    # tags: 0=B, 1=I, 2=O  (single chunk type, IOB)
    label = np.array([[0, 1, 2, 0, 1, 1]])
    # prediction gets first chunk right, second wrong boundary
    pred = np.array([[0, 1, 2, 2, 0, 1]])
    got = run_op("chunk_eval",
                 {"Inference": ("p", pred), "Label": ("l", label)},
                 {"num_chunk_types": 1, "chunk_scheme": "IOB"},
                 ["Precision", "Recall", "F1-Score"])
    p = float(got["precision__out0"][0])
    r = float(got["recall__out0"][0])
    assert 0 < p <= 1 and 0 < r <= 1
    np.testing.assert_allclose(p, 0.5, atol=1e-6)   # 1 of 2 predicted right
    np.testing.assert_allclose(r, 0.5, atol=1e-6)   # 1 of 2 gold found


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------
def test_warpctc_simple_case():
    """T=1, single label: loss = -log softmax(logits)[label]."""
    logits = R.randn(1, 1, 4).astype("float32")
    label = np.array([[1]])
    got = run_op("warpctc",
                 {"Logits": ("x", logits), "Label": ("l", label)},
                 {"blank": 0}, ["Loss"],
                 lens={"x": np.array([1]), "l": np.array([1])})
    p = np.exp(logits[0, 0]) / np.exp(logits[0, 0]).sum()
    np.testing.assert_allclose(got["loss__out0"].reshape(-1),
                               [-np.log(p[1])], rtol=1e-4)


def test_warpctc_two_step_enumeration():
    """T=2, label [a]: paths = {blank,a}, {a,blank}, {a,a} -> sum probs."""
    logits = R.randn(1, 2, 3).astype("float32")
    a = 2
    label = np.array([[a]])
    got = run_op("warpctc",
                 {"Logits": ("x", logits), "Label": ("l", label)},
                 {"blank": 0}, ["Loss"],
                 lens={"x": np.array([2]), "l": np.array([1])})
    sm = np.exp(logits[0]) / np.exp(logits[0]).sum(-1, keepdims=True)
    prob = sm[0, 0] * sm[1, a] + sm[0, a] * sm[1, 0] + sm[0, a] * sm[1, a]
    np.testing.assert_allclose(got["loss__out0"].reshape(-1),
                               [-np.log(prob)], rtol=1e-4)


@pytest.mark.slow
def test_warpctc_grad_runs():
    # ~46s on this container (PR 13 budget audit): the ctc forward
    # value check above stays tier-1; the gradient smoke rides -m slow.
    logits = R.randn(2, 4, 5).astype("float32")
    label = np.array([[1, 2], [3, -1]])
    check_grad("warpctc",
               {"Logits": ("x", logits), "Label": ("l", label)},
               {"blank": 0}, wrt=["x"], out_slots=["Loss"],
               lens={"x": np.array([4, 3])}, max_relative_error=2e-2)


# ---------------------------------------------------------------------------
# nce / hsigmoid
# ---------------------------------------------------------------------------
def test_nce_cost_finite_and_trainable(rng):
    x = layers.data("x", shape=[8], dtype="float32")
    lbl = layers.data("lbl", shape=[1], dtype="int64")
    cost = layers.nce(x, lbl, num_total_classes=50, num_neg_samples=5)
    loss = layers.mean(cost)
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {"x": rng.rand(16, 8).astype("float32"),
             "lbl": rng.randint(0, 50, (16, 1))}
    vals = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
            for _ in range(10)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


def test_hsigmoid_trains(rng):
    x = layers.data("x", shape=[8], dtype="float32")
    lbl = layers.data("lbl", shape=[1], dtype="int64")
    cost = layers.hsigmoid(x, lbl, num_classes=16)
    loss = layers.mean(cost)
    pt.optimizer.SGD(0.5).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {"x": rng.rand(16, 8).astype("float32"),
             "lbl": rng.randint(0, 16, (16, 1))}
    vals = [float(exe.run(feed=feeds, fetch_list=[loss])[0])
            for _ in range(10)]
    assert np.isfinite(vals).all() and vals[-1] < vals[0]


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------
def test_roi_pool():
    x = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    rois = np.array([[0, 0, 0, 3, 3]], "float32")   # batch 0, 4x4 region
    got = run_op("roi_pool", {"X": ("x", x), "ROIs": ("r", rois)},
                 {"pooled_height": 2, "pooled_width": 2,
                  "spatial_scale": 1.0}, ["Out"])
    out = got["out__out0"]
    assert out.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(out[0, 0], [[9, 11], [25, 27]])


def test_iou_similarity():
    a = np.array([[0, 0, 10, 10]], "float32")
    b = np.array([[0, 0, 10, 10], [5, 5, 15, 15]], "float32")
    got = run_op("iou_similarity", {"X": ("x", a), "Y": ("y", b)}, {},
                 ["Out"])
    np.testing.assert_allclose(got["out__out0"][0],
                               [1.0, 25.0 / 175.0], rtol=1e-5)


# ---------------------------------------------------------------------------
# control flow constructs
# ---------------------------------------------------------------------------
def test_ifelse_construct(rng):
    x = layers.data("x", shape=[1], dtype="float32")
    limit = layers.fill_constant([1], "float32", 0.5)
    cond = layers.less_than(x, limit)
    ie = layers.control_flow.IfElse(cond)
    with ie.true_block():
        ie.output(layers.scale(ie.input(x), scale=10.0))
    with ie.false_block():
        ie.output(layers.scale(ie.input(x), scale=-1.0))
    out = ie()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    res = exe.run(feed={"x": np.array([[0.2], [0.8]], "float32")},
                  fetch_list=[out])
    np.testing.assert_allclose(res[0].reshape(-1), [2.0, -0.8], rtol=1e-5)


def test_static_rnn_cumsum(rng):
    seq = layers.data("seq", shape=[2], dtype="float32", lod_level=1)
    rnn = layers.control_flow.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(seq)
        acc = rnn.memory(shape=[2])
        new = layers.elementwise_add(acc, x_t)
        rnn.update_memory(acc, new)
        rnn.step_output(new)
    out = rnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    data = rng.rand(2, 4, 2).astype("float32")
    (res,) = exe.run(feed={"seq": data, "seq@LEN": np.array([4, 4])},
                     fetch_list=[out])
    np.testing.assert_allclose(res, np.cumsum(data, axis=1), rtol=1e-5)


# ---------------------------------------------------------------------------
# LR schedules
# ---------------------------------------------------------------------------
def test_lr_decay_schedules(rng):
    from paddle_tpu.optimizer import exponential_decay
    x = layers.data("x", shape=[2], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    lr = exponential_decay(learning_rate=0.1, decay_steps=2,
                           decay_rate=0.5, staircase=True)
    opt = pt.optimizer.SGD(learning_rate=lr)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    feeds = {"x": rng.rand(4, 2).astype("float32"),
             "y": rng.rand(4, 1).astype("float32")}
    lrs = [float(exe.run(feed=feeds, fetch_list=[lr])[0])
           for _ in range(5)]
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025],
                               rtol=1e-5)


def test_evaluator_accuracy(rng):
    x = layers.data("x", shape=[4], dtype="float32")
    lbl = layers.data("lbl", shape=[1], dtype="int64")
    pred = layers.fc(x, size=3, act="softmax")
    ev = pt.evaluator.Accuracy(input=pred, label=lbl)
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    ev.reset(exe)
    for _ in range(3):
        exe.run(feed={"x": rng.rand(8, 4).astype("float32"),
                      "lbl": rng.randint(0, 3, (8, 1))},
                fetch_list=[pred])
    acc = ev.eval(exe)
    assert 0.0 <= float(np.asarray(acc)) <= 1.0
