"""Fault-tolerant training runtime: checkpoint/resume bit-identity,
preemption handling, transient-error retry, corrupt-checkpoint fallback,
and the zero-overhead off path.

Everything here is the FAST deterministic subset — failures come from
the seed-driven injection harness (paddle_tpu.testing.faultinject), not
real process kills; the subprocess kill matrix lives in
tests/test_chaos_kill.py."""
import hashlib
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.distributed import CheckpointManager, CheckpointTimeoutError
from paddle_tpu.faults import (EXIT_PREEMPTED, InjectedFault, Preempted,
                               RetriesExhausted, RetryPolicy)
from paddle_tpu.testing import faultinject as fi
from paddle_tpu.train_state import TRAIN_STATE_VAR, TrainState


@pytest.fixture(autouse=True)
def _clean_spec():
    fi.clear()
    yield
    fi.clear()


def _build_trainer(lr=0.1):
    """Deterministic trainer with dropout (so resume must restore the
    step-keyed RNG stream, not just the params)."""
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="int64")
    h = layers.fc(x, size=16, act="relu")
    h = layers.dropout(h, dropout_prob=0.3)
    pred = layers.fc(h, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, y))
    return pt.trainer.SGD(cost=loss,
                          update_equation=pt.optimizer.Momentum(lr, 0.9))


def _fresh():
    """New default programs/scope (several sub-runs inside one test)."""
    pt.core.reset_default_programs()
    pt.core.reset_global_scope()
    pt.unique_name.reset()


def _reader(n_batches=10, batch=4):
    def r():
        rng = np.random.RandomState(7)
        for _ in range(n_batches):
            yield [(rng.rand(8).astype("float32"),
                    rng.randint(0, 3, (1,))) for _ in range(batch)]
    return r


def _collect(tr, reader, num_passes=2, **kw):
    out = []

    def handler(e):
        if isinstance(e, pt.trainer.events.EndIteration):
            out.append((e.pass_id, e.batch_id, float(e.cost).hex()))
    tr.train(reader, num_passes=num_passes, event_handler=handler, **kw)
    return out


def _sha(events):
    return hashlib.sha256(repr(events).encode()).hexdigest()


# The uninterrupted reference run for the standard config (2 passes x 10
# batches, per-batch dispatch) — several tests compare against it, and it
# is strictly deterministic, so compute it once per session.
_BASELINE = {}


def _baseline():
    if "ev" not in _BASELINE:
        _fresh()
        _BASELINE["ev"] = _collect(_build_trainer(), _reader())
        _fresh()
    return _BASELINE["ev"]


# ---------------------------------------------------------------------------
# Kill-and-resume bit-identity (injection-driven)
# ---------------------------------------------------------------------------
@pytest.mark.timeout(120)
def test_preempt_resume_bit_identity_per_batch(tmp_path):
    """Preempted at batch 7 of 20, resumed: the concatenated fetch stream
    is sha256-identical to the uninterrupted run — params, optimizer
    moments AND the dropout RNG stream all restored."""
    baseline = _baseline()
    assert len(baseline) == 20

    tr = _build_trainer()
    part1 = []

    def h1(e):
        if isinstance(e, pt.trainer.events.EndIteration):
            part1.append((e.pass_id, e.batch_id, float(e.cost).hex()))
    fi.configure("trainer.step@7=preempt")
    with pytest.raises(Preempted) as ei:
        tr.train(_reader(), num_passes=2, event_handler=h1,
                 checkpoint_dir=str(tmp_path), save_every_n_steps=3)
    assert ei.value.code == EXIT_PREEMPTED
    assert ei.value.step == 7
    fi.clear()
    # the emergency checkpoint covers everything emitted so far
    assert len(part1) == 7

    _fresh()
    tr2 = _build_trainer()
    part2 = _collect(tr2, _reader(), checkpoint_dir=str(tmp_path),
                     resume=True, save_every_n_steps=3)
    assert part1 + part2 == baseline
    assert _sha(part1 + part2) == _sha(baseline)


@pytest.mark.timeout(120)
def test_preempt_resume_bit_identity_pipelined(tmp_path):
    """Same invariant through the async pipelined path (order-preserving
    config: num_workers=0), preempting mid-stream."""
    pipe = {"steps_per_dispatch": 4, "num_workers": 0}
    baseline = _collect(_build_trainer(), _reader(), pipeline=pipe)

    _fresh()
    tr = _build_trainer()
    part1 = []

    def h1(e):
        if isinstance(e, pt.trainer.events.EndIteration):
            part1.append((e.pass_id, e.batch_id, float(e.cost).hex()))
    fi.configure("trainer.step@9=preempt")
    with pytest.raises(Preempted):
        tr.train(_reader(), num_passes=2, event_handler=h1, pipeline=pipe,
                 checkpoint_dir=str(tmp_path), save_every_n_steps=4)
    fi.clear()

    _fresh()
    part2 = _collect(_build_trainer(), _reader(), pipeline=pipe,
                     checkpoint_dir=str(tmp_path), resume=True,
                     save_every_n_steps=4)
    assert part1 + part2 == baseline


@pytest.mark.timeout(120)
def test_reader_crash_propagates_then_resumable(tmp_path):
    """Reader exception at item N: propagated to the caller (not
    swallowed), and the run resumes from the last periodic checkpoint
    with bit-identical continuation — the crash costs the tail batches
    after the last save, never correctness."""
    baseline = _baseline()

    tr = _build_trainer()
    part1 = []

    def h1(e):
        if isinstance(e, pt.trainer.events.EndIteration):
            part1.append((e.pass_id, e.batch_id, float(e.cost).hex()))
    fi.configure("reader.item@8=error")
    with pytest.raises(InjectedFault):
        tr.train(_reader(), num_passes=2, event_handler=h1,
                 checkpoint_dir=str(tmp_path), save_every_n_steps=3)
    fi.clear()
    assert len(part1) == 7          # batches 1..7 done; item 8 blew up

    _fresh()
    part2 = _collect(_build_trainer(), _reader(), checkpoint_dir=str(tmp_path),
                     resume=True, save_every_n_steps=3)
    # resume replays from the last periodic save (batch 6): the replayed
    # overlap must be bit-identical to what the crashed run produced
    merged = {(p, b): c for p, b, c in part1}
    merged.update({(p, b): c for p, b, c in part2})
    assert [(p, b, merged[(p, b)]) for p, b, _ in baseline] == baseline
    overlap = set((p, b) for p, b, _ in part1) & \
        set((p, b) for p, b, _ in part2)
    assert overlap, "expected replayed batches after the last checkpoint"
    d1 = dict(((p, b), c) for p, b, c in part1)
    d2 = dict(((p, b), c) for p, b, c in part2)
    for k in overlap:
        assert d1[k] == d2[k]


@pytest.mark.timeout(120)
def test_real_sigterm_mid_training(tmp_path):
    """A real SIGTERM delivered to this process mid-run: the installed
    handler defers to the next dispatch boundary, commits an emergency
    checkpoint, and raises Preempted; the previous handler is restored
    afterwards."""
    old = signal.getsignal(signal.SIGTERM)
    tr = _build_trainer()
    fi.configure("trainer.step@5=sigterm")   # os.kill(self, SIGTERM)
    with pytest.raises(Preempted) as ei:
        tr.train(_reader(), num_passes=2,
                 checkpoint_dir=str(tmp_path), save_every_n_steps=100)
    fi.clear()
    assert ei.value.code == EXIT_PREEMPTED
    assert signal.getsignal(signal.SIGTERM) is old
    cm = CheckpointManager(str(tmp_path))
    assert cm.all_steps(), "emergency checkpoint missing"

    _fresh()
    resumed = _collect(_build_trainer(), _reader(),
                       checkpoint_dir=str(tmp_path), resume=True)
    baseline = _baseline()
    assert resumed == baseline[len(baseline) - len(resumed):]


@pytest.mark.timeout(120)
def test_resume_with_empty_dir_starts_fresh_and_completion_idempotent(
        tmp_path):
    """resume=True on an empty directory trains from scratch (supervisor
    scripts can always pass it); after completion, a relaunch resumes
    into an empty pass range and exits immediately with no new events."""
    baseline = _baseline()
    got = _collect(_build_trainer(), _reader(), checkpoint_dir=str(tmp_path),
                   resume=True, save_every_n_steps=5)
    assert got == baseline
    _fresh()
    again = _collect(_build_trainer(), _reader(), checkpoint_dir=str(tmp_path),
                     resume=True, save_every_n_steps=5)
    assert again == []


def test_checkpoint_options_require_checkpoint_dir():
    """resume / save_every_n_steps / master without checkpoint_dir are
    loud errors — an operator who asked for checkpointing must never run
    silently unprotected."""
    from paddle_tpu.distributed import Master
    tr = _build_trainer()
    with pytest.raises(ValueError, match="checkpoint_dir"):
        tr.train(_reader(), resume=True)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        tr.train(_reader(), save_every_n_steps=5)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        tr.train(_reader(), master=Master())


def test_restore_rejects_checkpoint_without_train_state(tmp_path):
    """A plain CheckpointManager checkpoint (no TrainState) cannot be
    resumed as a training run — typed error, not a silent half-resume."""
    scope = pt.global_scope()
    scope.set("w", np.ones(3, np.float32))
    CheckpointManager(str(tmp_path), async_save=False).save(1, scope)
    tr = _build_trainer()
    with pytest.raises(ValueError, match="TrainState"):
        tr.train(_reader(), checkpoint_dir=str(tmp_path), resume=True)


@pytest.mark.timeout(120)
def test_step_advancing_event_handler_degrades_to_per_pass_saves(tmp_path):
    """An event handler that runs EXTRA executor work (trainer.test every
    batch) drifts the step counter past the loop's own dispatches;
    checkpoint cadence must degrade to at-least-once-per-pass (the
    BeginPass resync), never silently to zero."""
    tr = _build_trainer()
    test_reader = _reader(2)

    def handler(e):
        if isinstance(e, pt.trainer.events.EndIteration):
            tr.test(test_reader)          # advances exe._step mid-pass
    tr.train(_reader(4), num_passes=2, event_handler=handler,
             checkpoint_dir=str(tmp_path), save_every_n_steps=2)
    from paddle_tpu.distributed import CheckpointManager
    steps = CheckpointManager(str(tmp_path)).all_steps()
    # drift suppresses mid-pass boundaries, but every pass start resyncs:
    # at least one save in the later pass plus the final save
    assert len(steps) >= 2
    assert 8 in steps                     # final_save committed


# ---------------------------------------------------------------------------
# TrainState
# ---------------------------------------------------------------------------
def test_train_state_roundtrip_and_version_guard():
    ts = TrainState(exe_step=41, pass_id=1, batch_id=3, emitted=23,
                    iters_done=23, random_seed=9,
                    optimizer={"type": "Momentum", "learning_rate": 0.1},
                    emergency=True)
    back = TrainState.from_array(ts.to_array())
    assert back == ts
    # forward-compat: unknown fields are ignored, newer versions rejected
    import json
    d = json.loads(bytes(ts.to_array()).decode())
    d["version"] = TrainState().version + 1
    arr = np.frombuffer(json.dumps(d).encode(), dtype=np.uint8)
    with pytest.raises(ValueError, match="newer"):
        TrainState.from_array(arr)
    d["version"] = TrainState().version
    d["future_field"] = "ignored"
    arr = np.frombuffer(json.dumps(d).encode(), dtype=np.uint8)
    assert TrainState.from_array(arr).exe_step == 41


def test_train_state_never_leaks_into_scope(tmp_path):
    tr = _build_trainer()
    _collect(tr, _reader(4), num_passes=1, checkpoint_dir=str(tmp_path),
             save_every_n_steps=2)
    assert not pt.global_scope().has(TRAIN_STATE_VAR)


# ---------------------------------------------------------------------------
# Corrupt checkpoints
# ---------------------------------------------------------------------------
def _save_two_checkpoints(tmp_path):
    scope = pt.Scope()
    cm = CheckpointManager(str(tmp_path), async_save=False)
    scope.set("w", np.arange(6, dtype=np.float32))
    cm.save(1, scope)
    scope.set("w", np.arange(6, dtype=np.float32) * 10)
    cm.save(2, scope)
    return cm


def test_corrupt_latest_falls_back_to_newest_intact(tmp_path):
    cm = _save_two_checkpoints(tmp_path)
    # flip bytes in the newest checkpoint's shard file (bitrot)
    d = os.path.join(str(tmp_path), "ckpt-2")
    shard = [f for f in os.listdir(d) if f.endswith(".npy")][0]
    with open(os.path.join(d, shard), "r+b") as f:
        f.seek(-4, 2)
        f.write(b"\xde\xad\xbe\xef")
    before = pt.observability.registry().snapshot()[
        "fault/checkpoint_fallbacks"]["value"]
    fresh = pt.Scope()
    assert cm.restore(scope=fresh) == 1
    np.testing.assert_array_equal(np.asarray(fresh.get("w")),
                                  np.arange(6, dtype=np.float32))
    after = pt.observability.registry().snapshot()[
        "fault/checkpoint_fallbacks"]["value"]
    assert after - before == 1


def test_truncated_latest_falls_back(tmp_path):
    cm = _save_two_checkpoints(tmp_path)
    d = os.path.join(str(tmp_path), "ckpt-2")
    shard = [f for f in os.listdir(d) if f.endswith(".npy")][0]
    with open(os.path.join(d, shard), "r+b") as f:
        f.truncate(8)
    fresh = pt.Scope()
    assert cm.restore(scope=fresh) == 1


def test_injected_write_truncation_detected_on_restore(tmp_path):
    """The ckpt.write@N=truncate injection corrupts a shard AFTER its md5
    is recorded — restore's verify pass must reject that checkpoint."""
    scope = pt.Scope()
    scope.set("w", np.arange(64, dtype=np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    cm.save(1, scope)                       # intact
    fi.configure("ckpt.write@1=truncate")
    scope.set("w", np.arange(64, dtype=np.float32) + 1)
    cm.save(2, scope)                       # torn write
    fi.clear()
    fresh = pt.Scope()
    assert cm.restore(scope=fresh) == 1     # fell back past the torn one
    np.testing.assert_array_equal(np.asarray(fresh.get("w")),
                                  np.arange(64, dtype=np.float32))
    # with verification disabled the torn file is exposed (proves verify
    # is what saved us, not luck)
    assert cm.all_steps() == [1, 2]


def test_async_write_failure_surfaces_on_wait(tmp_path):
    """A failure in the async writer thread re-raises from the next
    wait()/save() — an uncommitted checkpoint is never silently recorded
    as saved."""
    scope = pt.Scope()
    scope.set("w", np.arange(4, dtype=np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=True)
    fi.configure("ckpt.write@1=error")
    cm.save(1, scope)                     # async: returns immediately
    with pytest.raises(InjectedFault):
        cm.wait()
    fi.clear()
    assert cm.all_steps() == []           # nothing committed
    cm.save(2, scope, blocking=True)      # manager still usable
    assert cm.all_steps() == [2]


def test_recommit_shelf_recovers_when_final_missing(tmp_path):
    """Crash between the same-step shelve renames: only ckpt-N.prev.tmp
    remains.  all_steps must still list N and restore must read the
    shelf instead of silently falling back to an older step."""
    scope = pt.Scope()
    scope.set("w", np.arange(4, dtype=np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    cm.save(3, scope)
    os.rename(os.path.join(str(tmp_path), "ckpt-3"),
              os.path.join(str(tmp_path), "ckpt-3.prev.tmp"))
    assert cm.all_steps() == [3]
    fresh = pt.Scope()
    assert cm.restore(scope=fresh) == 3
    np.testing.assert_array_equal(np.asarray(fresh.get("w")),
                                  np.arange(4, dtype=np.float32))
    # a later commit of the same step cleans the shelf up
    cm.save(3, scope)
    assert not os.path.exists(
        os.path.join(str(tmp_path), "ckpt-3.prev.tmp"))


def test_all_corrupt_raises_file_not_found(tmp_path):
    scope = pt.Scope()
    scope.set("w", np.arange(6, dtype=np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    cm.save(1, scope)
    d = os.path.join(str(tmp_path), "ckpt-1")
    shard = [f for f in os.listdir(d) if f.endswith(".npy")][0]
    with open(os.path.join(d, shard), "r+b") as f:
        f.truncate(4)
    with pytest.raises(FileNotFoundError, match="no valid checkpoint"):
        cm.restore(scope=pt.Scope())


# ---------------------------------------------------------------------------
# Checkpoint barrier timeout knob
# ---------------------------------------------------------------------------
def test_wait_for_timeout_typed_and_configurable(tmp_path):
    cm = CheckpointManager(str(tmp_path), barrier_timeout_s=0.05)
    with pytest.raises(CheckpointTimeoutError) as ei:
        cm._wait_for(lambda: False, "ckpt-9 shard manifests")
    assert isinstance(ei.value, TimeoutError)     # typed, still a Timeout
    assert ei.value.tag == "ckpt-9 shard manifests"
    assert ei.value.timeout_s == 0.05
    assert "ckpt-9 shard manifests" in str(ei.value)


def test_wait_for_timeout_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CKPT_TIMEOUT_S", "0.03")
    cm = CheckpointManager(str(tmp_path))
    assert cm.barrier_timeout_s == 0.03
    monkeypatch.delenv("PADDLE_TPU_CKPT_TIMEOUT_S")
    from paddle_tpu.distributed.checkpoint import DEFAULT_BARRIER_TIMEOUT_S
    assert CheckpointManager(str(tmp_path)).barrier_timeout_s == \
        DEFAULT_BARRIER_TIMEOUT_S


# ---------------------------------------------------------------------------
# Transient-error retry at the dispatch rim
# ---------------------------------------------------------------------------
def _tiny_exe(**kw):
    x = layers.data("x", shape=[4], dtype="float32")
    loss = layers.mean(layers.fc(x, size=2))
    exe = pt.Executor(**kw)
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    return exe, loss, {"x": np.ones((2, 4), np.float32)}


def test_dispatch_transient_retried():
    exe, loss, feed = _tiny_exe(retry_policy=RetryPolicy(
        max_attempts=3, backoff_base_s=0.0, jitter=0.0))
    ref = exe.run(feed=feed, fetch_list=[loss])
    before = pt.observability.registry().snapshot()[
        "fault/retries"]["value"]
    fi.configure("executor.dispatch@1=transient")
    out = exe.run(feed=feed, fetch_list=[loss])
    assert fi.fired("executor.dispatch") == 1
    fi.clear()
    after = pt.observability.registry().snapshot()[
        "fault/retries"]["value"]
    assert after - before == 1
    assert np.isfinite(out[0]).all() and np.isfinite(ref[0]).all()


def test_dispatch_retries_exhausted():
    exe, loss, feed = _tiny_exe(retry_policy=RetryPolicy(
        max_attempts=2, backoff_base_s=0.0, jitter=0.0))
    fi.configure("executor.dispatch@*=transient")
    with pytest.raises(RetriesExhausted):
        exe.run(feed=feed, fetch_list=[loss])
    fi.clear()


def test_dispatch_fatal_not_retried():
    exe, loss, feed = _tiny_exe(retry_policy=RetryPolicy(
        max_attempts=5, backoff_base_s=0.0, jitter=0.0))
    before = pt.observability.registry().snapshot()[
        "fault/retries"]["value"]
    fi.configure("executor.dispatch@*=error")     # InjectedFault: fatal
    with pytest.raises(InjectedFault):
        exe.run(feed=feed, fetch_list=[loss])
    # fatal raised on attempt 1: no backoff loop, no retry budget burned
    assert fi.fired("executor.dispatch") == 1
    fi.clear()
    after = pt.observability.registry().snapshot()[
        "fault/retries"]["value"]
    assert after == before


def test_retry_policy_deterministic_schedule():
    a = RetryPolicy(max_attempts=5, backoff_base_s=0.1, backoff_max_s=1.0,
                    jitter=0.2, seed=42)
    b = RetryPolicy(max_attempts=5, backoff_base_s=0.1, backoff_max_s=1.0,
                    jitter=0.2, seed=42)
    da = [a.delay(i) for i in range(6)]
    db = [b.delay(i) for i in range(6)]
    assert da == db
    assert all(d <= 1.0 * 1.2 + 1e-9 for d in da)     # cap + jitter bound
    assert da[1] > da[0] * 1.2 or da[1] > da[0]       # grows


@pytest.mark.timeout(120)
def test_retry_during_training_run_keeps_math_identical(tmp_path):
    """A transiently-failing dispatch mid-training, retried: the final
    event stream equals the failure-free run (the injection fires before
    the dispatch executes, so no step runs twice)."""
    baseline = _baseline()
    tr = _build_trainer()
    tr.exe.retry_policy = RetryPolicy(max_attempts=3, backoff_base_s=0.0,
                                      jitter=0.0)
    fi.configure("executor.dispatch@5=transient")
    got = _collect(tr, _reader())
    fi.clear()
    assert got == baseline


# ---------------------------------------------------------------------------
# Master rim
# ---------------------------------------------------------------------------
def test_master_client_drop_retries_with_backoff_task_returned_once():
    """Injected connection drop on a MasterClient RPC: the call retries
    with backoff and succeeds; an in-flight task handed back via the
    retried call lands in todo EXACTLY once."""
    from paddle_tpu.distributed.master import Master, MasterClient, \
        MasterServer

    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([[1], [2]])
    srv = MasterServer(m).start()
    try:
        c = MasterClient(srv.address, retries=3, retry_wait_s=0.01)
        t = c.get_task()
        assert t is not None
        before = pt.observability.registry().snapshot()[
            "fault/retries"]["value"]
        fi.configure("master.call@1=drop")
        c.task_returned(t.task_id)        # attempt 1 dropped, 2 succeeds
        fi.clear()
        after = pt.observability.registry().snapshot()[
            "fault/retries"]["value"]
        assert after - before == 1
        st = c.stats()
        assert st["todo"] == 2 and st["pending"] == 0   # returned ONCE
        c.close()
    finally:
        srv.stop()


def test_task_loop_transient_returns_task_exactly_once():
    """A retryable failure while consuming a chunk returns the task to
    the master budget-free (never silently retries non-idempotent reads)
    and re-raises; the task is re-served intact afterwards."""
    from paddle_tpu.distributed.master import Master, task_loop_reader
    from paddle_tpu.faults import TransientError

    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([[1, 2, 3]])
    calls = {"returned": 0}
    orig = m.task_returned

    def counting_returned(task_id):
        calls["returned"] += 1
        return orig(task_id)
    m.task_returned = counting_returned

    state = {"fail": True}

    def chunk_reader(chunk):
        yield chunk[0]
        if state["fail"]:
            state["fail"] = False
            raise TransientError("wire glitch mid-chunk")
        yield from chunk[1:]

    gen = task_loop_reader(m, chunk_reader)()
    with pytest.raises(TransientError):
        list(gen)
    assert calls["returned"] == 1
    t = m.get_task()                      # re-served, budget intact
    assert t is not None and t.num_failures == 0
    # second consumption (the "retry") succeeds end to end
    m.task_returned(t.task_id)
    assert calls["returned"] == 2         # the explicit return just above
    assert sorted(task_loop_reader(m, chunk_reader)()) == [1, 2, 3]
    assert calls["returned"] == 2         # success path never re-returns


def test_classify_oserror_wire_vs_host():
    """Plain OSError is retryable only for wire errnos; deterministic
    host failures (disk full, IO error) are fatal — a supervisor must
    not spin against a full disk."""
    import errno

    from paddle_tpu.faults import classify
    assert classify(OSError(errno.ECONNRESET, "reset")) == "retryable"
    assert classify(OSError(errno.ETIMEDOUT, "timeo")) == "retryable"
    assert classify(OSError("errno-less socket flavor")) == "retryable"
    assert classify(OSError(errno.ENOSPC, "disk full")) == "fatal"
    assert classify(OSError(errno.EIO, "io error")) == "fatal"
    assert classify(OSError(errno.EMFILE, "fd limit")) == "fatal"


def test_task_loop_swallow_no_livelock_on_persistent_transient(
        monkeypatch):
    """swallow_failures=True with a chunk that ALWAYS fails retryably:
    the budget-free return happens EXACTLY once per task, then real
    failure budget burns and the task is dropped at failure_max — the
    loop terminates instead of ping-ponging the task forever."""
    import time as _time

    from paddle_tpu.distributed.master import Master, task_loop_reader
    from paddle_tpu.faults import TransientError

    monkeypatch.setattr(_time, "sleep", lambda s: None)
    m = Master(chunks_per_task=1, timeout_s=30.0, failure_max=3)
    m.set_dataset(["poison", "good"])
    attempts = {"n": 0}

    def chunk_reader(chunk):
        if chunk == "poison":
            attempts["n"] += 1
            raise TransientError("always down")
        yield chunk

    got = list(task_loop_reader(m, chunk_reader, swallow_failures=True)())
    assert got == ["good"]
    # EXACTLY one budget-free return + failure_max budget-burning
    # attempts, then the task is dropped — bounded, not infinite
    assert attempts["n"] == 1 + 3
    assert m.stats()["done"] == 2 and m.stats()["todo"] == 0


@pytest.mark.timeout(120)
def test_master_state_rides_inside_checkpoint(tmp_path):
    """train(master=...): the task-queue position is embedded in the
    checkpoint's TrainState (atomic with the model) and restored into a
    FRESH Master on resume — pending leases re-serve, done stays done."""
    from paddle_tpu.distributed import Master

    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset(["a", "b", "c"])
    t = m.get_task()
    m.task_finished(t.task_id)
    leased = m.get_task()                 # held (pending) at save time
    assert leased is not None

    tr = _build_trainer()
    fi.configure("trainer.step@4=preempt")
    with pytest.raises(Preempted):
        tr.train(_reader(6), num_passes=1, master=m,
                 checkpoint_dir=str(tmp_path), save_every_n_steps=2)
    fi.clear()

    _fresh()
    fresh_master = Master(chunks_per_task=1, timeout_s=30.0)
    tr2 = _build_trainer()
    tr2.train(_reader(6), num_passes=1, master=fresh_master,
              checkpoint_dir=str(tmp_path), resume=True)
    st = fresh_master.stats()
    assert st["done"] == 1                # finished work stays finished
    # the lease held at checkpoint time re-serves (at-least-once)
    assert st["todo"] + st["pending"] == 2
    chunks = []
    while True:
        t2 = fresh_master.get_task()
        if t2 is None:
            break
        chunks.extend(t2.chunks)
    assert "b" in chunks or "c" in chunks
    assert len(chunks) == 2


def test_injected_preempt_without_checkpoint_dir_fails_loudly():
    tr = _build_trainer()
    fi.configure("trainer.step@2=preempt")
    with pytest.raises(InjectedFault, match="checkpoint_dir"):
        tr.train(_reader(4), num_passes=1)
    fi.clear()


def test_cross_signal_keeps_grace_window_same_signal_escalates(tmp_path):
    """SIGINT pending + the scheduler's routine SIGTERM must NOT kill the
    process during the grace window (the pending emergency save would be
    lost); only a REPEAT of the same signal escalates to the previous
    handler."""
    from paddle_tpu.train_state import Checkpointer

    class _Exe:
        _step = 0
    c = Checkpointer(str(tmp_path), _Exe())
    escalated = []
    c._old_handlers = {signal.SIGINT: lambda s, f: escalated.append(s),
                       signal.SIGTERM: lambda s, f: escalated.append(s)}
    c._on_signal(signal.SIGINT, None)
    assert c._preempt_sig == signal.SIGINT
    c._on_signal(signal.SIGTERM, None)        # cross-kind: absorbed
    assert c._preempt_sig == signal.SIGINT
    assert escalated == []
    c._on_signal(signal.SIGINT, None)         # same-kind repeat: escalate
    assert escalated == [signal.SIGINT]


def test_ckpt_write_generic_action_raises(tmp_path):
    """A consumed ckpt.write spec entry with a generic action must act
    (raise), never count as fired while doing nothing."""
    scope = pt.Scope()
    scope.set("w", np.arange(8, dtype=np.float32))
    cm = CheckpointManager(str(tmp_path), async_save=False)
    fi.configure("ckpt.write@1=error")
    with pytest.raises(InjectedFault):
        cm.save(1, scope)
    assert fi.fired("ckpt.write") == 1
    fi.clear()


# ---------------------------------------------------------------------------
# Supervisor (in-process)
# ---------------------------------------------------------------------------
def test_supervisor_restarts_preempted_fn_with_backoff():
    from paddle_tpu.distributed import Supervisor

    sleeps = []
    state = {"runs": 0}

    def fn():
        state["runs"] += 1
        if state["runs"] < 3:
            raise Preempted(step=state["runs"] * 5, checkpoint_dir="/x")
        return "done"

    sup = Supervisor(max_restarts=3, backoff_base_s=0.25, backoff_max_s=10,
                     jitter=0.0, sleep=sleeps.append)
    before = pt.observability.registry().snapshot()[
        "fault/restarts"]["value"]
    assert sup.run(fn) == "done"
    assert state["runs"] == 3 and sup.restarts == 2
    assert sleeps == [0.25, 0.5]          # exponential, deterministic
    after = pt.observability.registry().snapshot()[
        "fault/restarts"]["value"]
    assert after - before == 2


def test_supervisor_gives_up_and_fatal_propagates():
    from paddle_tpu.distributed import Supervisor, SupervisorGaveUp
    from paddle_tpu.faults import TransientError

    def flaky():
        raise TransientError("flaky")

    sup = Supervisor(max_restarts=2, backoff_base_s=0.0, jitter=0.0,
                     sleep=lambda s: None)
    # same give-up surface as run_command (uniform for callers)
    with pytest.raises(SupervisorGaveUp):
        sup.run(flaky)
    assert sup.restarts == 2

    def fatal():
        raise ValueError("shape mismatch")
    sup2 = Supervisor(max_restarts=5, sleep=lambda s: None)
    with pytest.raises(ValueError):
        sup2.run(fatal)
    assert sup2.restarts == 0             # fatal never relaunches


def test_supervisor_run_command_relaunches_on_preempt_exit(tmp_path):
    import sys

    from paddle_tpu.distributed import Supervisor, SupervisorGaveUp

    flag = tmp_path / "ran_once"
    script = tmp_path / "job.py"
    script.write_text(
        "import os, sys\n"
        f"flag = {str(flag)!r}\n"
        "if os.path.exists(flag):\n"
        "    sys.exit(0)\n"
        "open(flag, 'w').close()\n"
        f"sys.exit({EXIT_PREEMPTED})\n")
    sup = Supervisor(max_restarts=2, backoff_base_s=0.0, jitter=0.0,
                     sleep=lambda s: None)
    assert sup.run_command([sys.executable, str(script)]) == 0
    assert sup.restarts == 1

    bad = tmp_path / "bad.py"
    bad.write_text("import sys; sys.exit(3)\n")   # fatal status
    sup2 = Supervisor(max_restarts=5, backoff_base_s=0.0, jitter=0.0,
                      sleep=lambda s: None)
    with pytest.raises(SupervisorGaveUp):
        sup2.run_command([sys.executable, str(bad)])
    assert sup2.restarts == 0


def test_supervisor_relaunch_gate_is_bounded():
    """The fleet's composition surface: same restart accounting and
    backoff as the run loops, exhausted after max_restarts."""
    from paddle_tpu.distributed import Supervisor

    sleeps = []
    sup = Supervisor(max_restarts=2, backoff_base_s=0.25, jitter=0.0,
                     sleep=sleeps.append)
    assert sup.relaunch_gate("replica r0", "exit status -9") is True
    assert sup.relaunch_gate("replica r0", "exit status -9") is True
    assert sup.relaunch_gate("replica r0", "exit status -9") is False
    assert sup.restarts == 2
    assert sleeps == [0.25, 0.5]          # exponential, deterministic


def _run_command_in_thread(sup, argv):
    """Run sup.run_command(argv) in a thread; returns (thread, box)."""
    import threading

    box = {}

    def target():
        try:
            box["rc"] = sup.run_command(argv)
        except BaseException as e:   # noqa: BLE001 — surfaced via box
            box["error"] = e

    t = threading.Thread(target=target, daemon=True)
    t.start()
    # wait for the child to exist so terminate() has a target
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with sup._child_lock:
            if sup._child is not None:
                return t, box
        time.sleep(0.005)
    raise AssertionError("run_command never spawned its child")


def test_supervisor_terminate_forwards_signal_without_relaunch():
    """Killing the supervisor must kill the child, not orphan it — and a
    signal death *caused by* terminate() is a deliberate stop, never a
    relaunch trigger (signal deaths are otherwise retryable)."""
    import sys

    from paddle_tpu.distributed import Supervisor

    sup = Supervisor(max_restarts=5, backoff_base_s=0.0, jitter=0.0,
                     sleep=lambda s: None)
    t, box = _run_command_in_thread(
        sup, [sys.executable, "-c", "import time; time.sleep(60)"])
    child = sup._child
    sup.terminate()                       # forwards SIGTERM
    t.join(timeout=15)
    assert not t.is_alive()
    assert "error" not in box, f"unexpected: {box.get('error')}"
    assert box["rc"] == -signal.SIGTERM   # child died by the signal...
    assert sup.restarts == 0              # ...and was NOT relaunched
    assert child.poll() is not None       # and is reaped, not orphaned


def test_supervisor_terminate_escalates_to_sigkill(tmp_path):
    """A child that ignores SIGTERM is escalated to SIGKILL after the
    bounded wait instead of stalling the drain forever."""
    import sys

    from paddle_tpu.distributed import Supervisor

    flag = tmp_path / "ignoring"
    script = (
        "import signal, time, sys\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        f"open({str(flag)!r}, 'w').close()\n"
        "time.sleep(60)\n")
    sup = Supervisor(max_restarts=5, backoff_base_s=0.0, jitter=0.0,
                     sleep=lambda s: None)
    t, box = _run_command_in_thread(sup, [sys.executable, "-c", script])
    deadline = time.monotonic() + 10.0
    while not flag.exists():              # handler installed before TERM
        assert time.monotonic() < deadline, "child never started"
        time.sleep(0.005)
    t0 = time.monotonic()
    sup.terminate(kill_timeout_s=0.5)
    t.join(timeout=15)
    assert not t.is_alive()
    assert box["rc"] == -signal.SIGKILL
    assert time.monotonic() - t0 < 10.0   # bounded, not a hang
    assert sup.restarts == 0


# ---------------------------------------------------------------------------
# Zero-overhead off path
# ---------------------------------------------------------------------------
@pytest.mark.timeout(120)
def test_off_path_zero_new_work(monkeypatch):
    """With fault injection unset and no checkpoint_dir, Trainer.train
    and Executor.run never touch the injection harness, the retry rim,
    or the fault metrics — the PR 5 observe-off counter-delta guarantee
    extended to the fault layer."""
    from paddle_tpu import flags
    flags.set_flag("observe", False)

    def boom(*a, **kw):
        raise AssertionError("faultinject.check called on the off path")
    monkeypatch.setattr(fi, "check", boom)

    def snap_counters():
        return {k: v["value"] for k, v in
                pt.observability.registry().snapshot().items()
                if v["kind"] == "counter"}

    before = snap_counters()
    tr = _build_trainer()
    out = _collect(tr, _reader(6), num_passes=1)
    assert len(out) == 6
    _fresh()                    # separate program for the bare executor
    exe, loss, feed = _tiny_exe()
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])
    after = snap_counters()
    assert after == before, "off path wrote metrics"
