"""Static program verifier tests (paddle_tpu.analysis).

Three contracts from the desc-layer parity work:

1. **Seeded-defect matrix** — programmatically corrupt a known-clean
   program one defect at a time and assert each corruption yields exactly
   its stable ``PT0xx`` code (and the clean program yields nothing).  The
   codes are frozen API (analysis/diagnostics.py): a failing assert here
   means a code changed meaning, which downstream tooling must never see.
2. **Coverage gate** — every registered op has a ``register_shape_fn``
   rule or an explicit ``SHAPE_INFER_ALLOWLIST`` entry, never both; a new
   op without either fails tier-1 instead of silently degrading coverage.
3. **Zero steady-state overhead** — validation runs at most once per
   (program, version, fetches), pinned through the ``validations`` counter
   in ``profiler.compile_stats()``, and an invalid program is rejected
   BEFORE compile-cache fingerprinting (no trace, no cache entry).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models
from paddle_tpu.analysis import (CODES, ProgramVerificationError,
                                 SHAPE_INFER_ALLOWLIST, coverage)
from paddle_tpu.core.program import Program
from paddle_tpu.core.registry import registered_ops, registered_shape_fns


# ---------------------------------------------------------------------------
# Fixture: one small known-clean program (fc classifier)
# ---------------------------------------------------------------------------
def _build_clean():
    """(main, startup, loss) for x[4] -> fc(3, softmax) -> CE -> mean."""
    main, startup = Program(), Program()
    with pt.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        label = layers.data("label", shape=[1], dtype="int64")
        pred = layers.fc(x, size=3, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
    return main, startup, loss


def _find_param(program, ndim):
    for v in program.global_block().vars.values():
        if v.persistable and v.shape is not None and len(v.shape) == ndim:
            return v
    raise AssertionError(f"no persistable rank-{ndim} param found")


def _codes(report):
    return set(report.codes())


def test_clean_program_reports_nothing():
    main, startup, loss = _build_clean()
    assert len(main.validate(fetch_list=[loss])) == 0
    assert len(startup.validate()) == 0
    # a mesh without any specs is also clean
    assert len(main.validate(fetch_list=[loss], mesh={"dp": 2})) == 0


# ---------------------------------------------------------------------------
# The seeded-defect matrix: one corruption -> exactly one code
# ---------------------------------------------------------------------------
def test_pt001_dangling_input():
    main, _, _ = _build_clean()
    op = main.global_block().ops[-1]            # the mean op
    slot = next(iter(op.inputs))
    op.inputs[slot] = ["missing_var"]
    assert _codes(main.validate()) == {"PT001"}


def test_pt002_declared_never_produced():
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="phantom", shape=(-1, 4), dtype="float32")
    b.create_var(name="phantom_out", shape=(-1, 4), dtype="float32")
    b.append_op(type="scale", inputs={"X": ["phantom"]},
                outputs={"Out": ["phantom_out"]}, attrs={"scale": 2.0})
    assert _codes(main.validate()) == {"PT002"}


def test_pt003_undeclared_output():
    main, _, _ = _build_clean()
    main.global_block().append_op(
        type="scale", inputs={"X": ["x"]},
        outputs={"Out": ["never_declared"]}, attrs={"scale": 1.0})
    assert _codes(main.validate()) == {"PT003"}


def test_pt004_duplicate_writer():
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="t1", shape=(-1, 4), dtype="float32")
    b.append_op(type="scale", inputs={"X": ["x"]},
                outputs={"Out": ["t1"]}, attrs={"scale": 1.0})
    b.append_op(type="scale", inputs={"X": ["x"]},
                outputs={"Out": ["t1"]}, attrs={"scale": 3.0})
    assert _codes(main.validate()) == {"PT004"}


def test_pt005_unregistered_op():
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="bogus_out", shape=(-1, 4), dtype="float32")
    b.append_op(type="totally_bogus_op", inputs={"X": ["x"]},
                outputs={"Out": ["bogus_out"]})
    assert _codes(main.validate()) == {"PT005"}


def test_pt006_orphaned_len_companion():
    main, _, _ = _build_clean()
    main.global_block().create_var(name="seq@LEN", shape=(-1,),
                                   dtype="int64")
    assert _codes(main.validate()) == {"PT006"}


def test_pt006_len_base_not_a_sequence():
    main, _, _ = _build_clean()
    # base exists but is lod_level=0 — a length companion makes no sense
    main.global_block().create_var(name="x@LEN", shape=(-1,),
                                   dtype="int64")
    assert _codes(main.validate()) == {"PT006"}


def test_pt006_orphaned_grad():
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="g_out", shape=(-1, 4), dtype="float32")
    b.append_op(type="scale", inputs={"X": ["x@GRAD"]},
                outputs={"Out": ["g_out"]}, attrs={"scale": 1.0})
    assert _codes(main.validate()) == {"PT006"}


def test_pt007_def_after_use():
    main, _, _ = _build_clean()
    ops = main.global_block().ops
    ops.insert(0, ops.pop())                    # mean now precedes its producer
    assert _codes(main.validate()) == {"PT007"}


def test_pt010_shape_rule_rejects():
    main, _, _ = _build_clean()
    w = _find_param(main, ndim=2)
    w.shape = (5, 3)                            # mul contraction 4 vs 5
    assert _codes(main.validate()) == {"PT010"}


def test_pt011_dtype_flip():
    main, _, _ = _build_clean()
    pred = None
    for op in main.global_block().ops:
        if op.type == "softmax":
            pred = op.outputs["Out"][0]
    main.global_block().var(pred).dtype = np.dtype("int64")
    assert _codes(main.validate()) == {"PT011"}


def test_pt012_shape_contradiction():
    main, _, _ = _build_clean()
    pred = None
    for op in main.global_block().ops:
        if op.type == "softmax":
            pred = op.outputs["Out"][0]
    main.global_block().var(pred).shape = (7, 9)
    assert _codes(main.validate()) == {"PT012"}


def test_pt020_dead_op_tail():
    main, _, loss = _build_clean()
    b = main.global_block()
    b.create_var(name="deadvar", shape=(-1, 4), dtype="float32")
    b.append_op(type="scale", inputs={"X": ["x"]},
                outputs={"Out": ["deadvar"]}, attrs={"scale": 1.0})
    assert _codes(main.validate(fetch_list=[loss])) == {"PT020"}
    # without fetch targets deadness is undefined -> lint skipped
    assert len(main.validate()) == 0


def test_fetching_len_companion_alone_is_not_dead():
    # regression: the executor serves `name + "@LEN"` fetches, but the
    # dead-op lint once seeded reachability with the companion name only —
    # the producer's output_names hold the BASE name, so every op in a
    # lengths-only fetch was reported PT020
    main, startup = Program(), Program()
    with pt.program_guard(main, startup):
        words = layers.data("words", shape=[], dtype="int64", lod_level=1)
        emb = layers.embedding(words, size=[50, 8])
    assert len(main.validate(fetch_list=[emb.name + "@LEN"])) == 0


def test_pt021_unstable_feed_signature():
    main, _, _ = _build_clean()
    main.global_block().create_var(
        name="ragged", shape=(-1, -1), dtype="float32", is_data=True)
    assert _codes(main.validate()) == {"PT021"}


def test_pt022_persistable_rebound():
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="running_mean", shape=(4,), dtype="float32",
                 persistable=True)
    b.append_op(type="reduce_mean", inputs={"X": ["x"]},
                outputs={"Out": ["running_mean"]}, attrs={"dim": [0]})
    assert _codes(main.validate()) == {"PT022"}


def test_pt030_unknown_mesh_axis():
    main, _, loss = _build_clean()
    _find_param(main, ndim=2).sharding = ("bogus_axis", None)
    assert _codes(main.validate(fetch_list=[loss],
                                mesh={"dp": 2})) == {"PT030"}
    # no mesh context -> sharding lints skipped entirely
    assert len(main.validate(fetch_list=[loss])) == 0


def test_pt031_non_divisible_dim():
    main, _, loss = _build_clean()
    w = _find_param(main, ndim=2)
    assert w.shape == (4, 3)
    w.sharding = ("dp", None)                   # 4 % 3 != 0
    assert _codes(main.validate(fetch_list=[loss],
                                mesh={"dp": 3})) == {"PT031"}
    # divisible extent is clean
    assert len(main.validate(fetch_list=[loss], mesh={"dp": 2})) == 0


def test_pt030_via_param_specs_override():
    main, _, loss = _build_clean()
    w = _find_param(main, ndim=2)
    rep = main.validate(fetch_list=[loss], mesh={"dp": 2},
                        param_specs={w.name: ("nope",)})
    assert _codes(rep) == {"PT030"}


def test_raise_on_error_carries_report():
    main, _, _ = _build_clean()
    op = main.global_block().ops[-1]
    slot = next(iter(op.inputs))
    op.inputs[slot] = ["missing_var"]
    with pytest.raises(ProgramVerificationError) as ei:
        main.validate(raise_on_error=True)
    assert "PT001" in ei.value.report.codes()
    assert "PT001" in str(ei.value)


def test_serialization_roundtrip_still_detects():
    """Defects survive Program.to_json/from_json — the CLI path."""
    main, _, _ = _build_clean()
    op = main.global_block().ops[-1]
    slot = next(iter(op.inputs))
    op.inputs[slot] = ["missing_var"]
    clone = Program.from_json(main.to_json())
    assert _codes(clone.validate()) == {"PT001"}


# ---------------------------------------------------------------------------
# Shape-rule coverage gate (tier-1: a new op must pick a side)
# ---------------------------------------------------------------------------
def test_every_op_has_rule_or_allowlist_entry():
    ops = set(registered_ops())
    fns = set(registered_shape_fns())
    allow = set(SHAPE_INFER_ALLOWLIST)
    assert not (ops - fns - allow), (
        f"ops with neither a register_shape_fn rule nor a "
        f"SHAPE_INFER_ALLOWLIST entry: {sorted(ops - fns - allow)} — add a "
        f"shape rule next to the lowering (preferred) or allowlist it with "
        f"a reason")
    assert not (fns & allow), (
        f"ops BOTH ruled and allowlisted (drop the allowlist entry): "
        f"{sorted(fns & allow)}")
    assert not (allow - ops), (
        f"stale allowlist entries for unregistered ops: "
        f"{sorted(allow - ops)}")
    assert not (fns - ops), (
        f"shape rules for unregistered ops: {sorted(fns - ops)}")


def test_coverage_floor():
    n, total = coverage()
    assert n / total >= 0.80, f"shape-rule coverage {n}/{total} below 80%"


def test_stack_program_validates_clean():
    # regression: the stack rule once referenced a helper missing from its
    # module's import list, so validating ANY stack program raised
    # NameError (masked into a spurious PT010 by the rule-crash guard)
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="s1", shape=(-1, 4), dtype="float32")
    b.append_op(type="scale", inputs={"X": ["x"]},
                outputs={"Out": ["s1"]}, attrs={"scale": 2.0})
    b.create_var(name="stacked", shape=(2, -1, 4), dtype="float32")
    b.append_op(type="stack", inputs={"X": ["x", "s1"]},
                outputs={"Out": ["stacked"]}, attrs={"axis": 0})
    assert len(main.validate()) == 0


def test_stack_shape_mismatch_rejected():
    main, _, _ = _build_clean()
    b = main.global_block()
    w = _find_param(main, ndim=2)               # fc weight (4, 3) vs x (-1, 4)
    b.create_var(name="stacked_bad", shape=None, dtype="float32")
    b.append_op(type="stack", inputs={"X": ["x", w.name]},
                outputs={"Out": ["stacked_bad"]}, attrs={"axis": 0})
    assert _codes(main.validate()) == {"PT010"}


def test_crop_rule_matches_lowering_offsets():
    # regression: negative shape entries slice x[o:] in the lowering, so
    # the inferred dim is input minus offset — the rule once returned the
    # full input dim, spuriously PT012-ing correctly declared outputs
    from paddle_tpu.core.registry import get_shape_fn
    from paddle_tpu.analysis.shape_infer import VarInfo

    rule = get_shape_fn("crop")
    out = rule(None, {"X": [VarInfo((10, 8), "float32")]},
               {"offsets": [2, 0], "shape": [-1, 5]})
    assert out["Out"].shape == (8, 5)


def test_pool_with_index_rule_floors_like_lowering():
    # the patch-extraction lowering always floors; honoring ceil_mode
    # here once mispredicted the runtime dims (spurious PT012)
    from paddle_tpu.core.registry import get_shape_fn
    from paddle_tpu.analysis.shape_infer import VarInfo

    rule = get_shape_fn("max_pool2d_with_index")
    out = rule(None, {"X": [VarInfo((1, 2, 7, 7), "float32")]},
               {"ksize": [3, 3], "strides": [2, 2], "ceil_mode": True})
    assert out["Out"].shape == (1, 2, 3, 3)
    assert out["Mask"].shape == (1, 2, 3, 3)


def test_where_rule_broadcasts_operands():
    # jnp.where broadcasts Condition/X/Y; same_as("X") once inferred the
    # unbroadcast X shape (spurious PT012 on correctly declared outputs)
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="wc", shape=(-1, 4), dtype="bool", is_data=True)
    b.create_var(name="wy", shape=(1, 1), dtype="float32", is_data=True)
    b.create_var(name="wo", shape=(-1, 4), dtype="float32")
    b.append_op(type="where",
                inputs={"Condition": ["wc"], "X": ["x"], "Y": ["wy"]},
                outputs={"Out": ["wo"]})
    assert len(main.validate()) == 0


def test_elementwise_rule_equal_shapes_any_axis():
    # regression: _bcast short-circuits equal shapes before the axis
    # check; the rule once raised 'bad axis' and PT010'd a valid program
    main, _, _ = _build_clean()
    b = main.global_block()
    b.create_var(name="e1", shape=(-1, 4), dtype="float32")
    b.append_op(type="elementwise_add", inputs={"X": ["x"], "Y": ["x"]},
                outputs={"Out": ["e1"]}, attrs={"axis": 1})
    assert len(main.validate()) == 0


def test_shape_rules_resolve_all_globals():
    # the static companion of the regression above: every LOAD_GLOBAL in
    # every registered rule (and its nested code objects) must resolve in
    # the rule's module globals or builtins, so a rule can never die with
    # NameError at validation time
    import builtins
    import dis
    from paddle_tpu.core.registry import get_shape_fn

    def walk(code):
        yield code
        for const in code.co_consts:
            if hasattr(const, "co_code"):
                yield from walk(const)

    seen, bad = set(), []
    for name in registered_shape_fns():
        fn = get_shape_fn(name)
        if fn.__code__ in seen:
            continue
        seen.add(fn.__code__)
        for code in walk(fn.__code__):
            for ins in dis.get_instructions(code):
                if (ins.opname == "LOAD_GLOBAL"
                        and ins.argval not in fn.__globals__
                        and not hasattr(builtins, ins.argval)):
                    bad.append((name, fn.__qualname__, ins.argval))
    assert not bad, f"shape rules with unresolvable globals: {bad}"


def test_diagnostic_codes_are_frozen():
    # the documented registry: removing or re-purposing a code is a break
    assert set(CODES) == {
        "PT001", "PT002", "PT003", "PT004", "PT005", "PT006", "PT007",
        "PT010", "PT011", "PT012", "PT020", "PT021", "PT022",
        "PT030", "PT031", "PT040", "PT041", "PT042",
        "PT050", "PT051", "PT052", "PT053", "PT054", "PT055"}
    from paddle_tpu.analysis.diagnostics import ERROR, WARNING
    # the PT04x family's severities are part of the frozen contract:
    # double-booked axes are spec errors, propagation findings advise
    assert CODES["PT040"][0] == ERROR
    assert CODES["PT041"][0] == WARNING
    assert CODES["PT042"][0] == WARNING
    # PT05x (the host-tree concurrency pass, analysis.concurrency):
    # guard inconsistency, blocking-under-lock and unnamed threads
    # advise; order cycles, waits without a predicate loop and
    # signal-handler lock acquisition are outright errors — the three
    # shapes that END as deadlocks or lost wakeups, not slowdowns
    assert CODES["PT050"][0] == WARNING
    assert CODES["PT051"][0] == ERROR
    assert CODES["PT052"][0] == WARNING
    assert CODES["PT053"][0] == ERROR
    assert CODES["PT054"][0] == ERROR
    assert CODES["PT055"][0] == WARNING


# ---------------------------------------------------------------------------
# Clean bill of health for the model zoo
# ---------------------------------------------------------------------------
_MODEL_BUILDERS = {
    "mnist_mlp": lambda: [models.mnist_mlp(
        layers.data("img", shape=[784], dtype="float32"))],
    "mnist_lenet": lambda: [models.mnist_lenet(
        layers.data("img", shape=[1, 28, 28], dtype="float32"))],
    "resnet_cifar": lambda: [models.resnet_cifar(
        layers.data("img", shape=[3, 16, 16], dtype="float32"), depth=8)],
    "resnet_imagenet": lambda: [models.resnet_imagenet(
        layers.data("img", shape=[3, 64, 64], dtype="float32"), depth=18)],
    "vgg16": lambda: [models.vgg16(
        layers.data("img", shape=[3, 32, 32], dtype="float32"))],
    "alexnet": lambda: [models.alexnet(
        layers.data("img", shape=[3, 224, 224], dtype="float32"))],
    "googlenet": lambda: [models.googlenet(
        layers.data("img", shape=[3, 64, 64], dtype="float32"))],
    "lstm_textcls": lambda: [models.lstm_text_classification(
        layers.data("words", shape=[], dtype="int64", lod_level=1),
        vocab_size=50, emb_dim=8, hidden_size=8)],
    "seq2seq_attention": lambda: [models.seq2seq_attention(
        layers.data("src", shape=[], dtype="int64", lod_level=1),
        layers.data("tgt", shape=[], dtype="int64", lod_level=1),
        src_vocab_size=30, tgt_vocab_size=30, emb_dim=8, hidden_dim=8)],
    "wide_deep": lambda: [models.wide_deep(
        [layers.data("f1", shape=[1], dtype="int64"),
         layers.data("f2", shape=[1], dtype="int64")],
        layers.data("dense", shape=[4], dtype="float32"),
        vocab_sizes=[20, 30], emb_dim=4, deep_hidden=(8,))],
}


@pytest.mark.parametrize("name", sorted(_MODEL_BUILDERS))
def test_model_zoo_validates_clean(name):
    main, startup = Program(), Program()
    with pt.program_guard(main, startup):
        fetch = _MODEL_BUILDERS[name]()
    rep = main.validate(fetch_list=fetch)
    assert len(rep) == 0, f"{name}/main:\n{rep.render()}"
    rep = startup.validate()
    assert len(rep) == 0, f"{name}/startup:\n{rep.render()}"


# ---------------------------------------------------------------------------
# Executor wiring: memoization, flag deferral, reject-before-cache
# ---------------------------------------------------------------------------
def _feeds(rng):
    return {"x": rng.rand(8, 4).astype("float32"),
            "label": rng.randint(0, 3, (8, 1))}


def test_validation_runs_once_per_signature(rng):
    main, startup, loss = _build_clean()
    stats = pt.profiler.compile_stats()
    v0 = stats.counters["validations"]
    exe = pt.Executor(validate=True)
    exe.run(startup, feed={}, fetch_list=[])
    for _ in range(4):
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    # once for startup, once for (main, [loss]) — NOT once per step
    assert stats.counters["validations"] - v0 == 2
    # run_steps on the same (program, fetches) reuses the memo too
    exe.run_steps(3, main, feed=_feeds(rng), fetch_list=[loss])
    assert stats.counters["validations"] - v0 == 2
    # a different fetch signature is a fresh validation
    exe.run(main, feed=_feeds(rng), fetch_list=[])
    assert stats.counters["validations"] - v0 == 3
    # version churn does not accumulate memo entries: stale-version keys
    # are swept, so a long-lived mutated program stays bounded
    for _ in range(5):
        main._bump_version()
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    assert len(exe._validated[main]) == 1


def test_validation_off_by_default(rng):
    main, startup, loss = _build_clean()
    stats = pt.profiler.compile_stats()
    v0 = stats.counters["validations"]
    exe = pt.Executor()
    exe.run(startup, feed={}, fetch_list=[])
    exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    assert stats.counters["validations"] - v0 == 0


def test_validation_flag_deferral(rng):
    from paddle_tpu import flags
    main, startup, loss = _build_clean()
    stats = pt.profiler.compile_stats()
    v0 = stats.counters["validations"]
    flags.set_flag("validate", True)
    try:
        exe = pt.Executor()            # validate=None defers to the flag
        exe.run(startup, feed={}, fetch_list=[])
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    finally:
        flags.set_flag("validate", False)
    assert stats.counters["validations"] - v0 == 2


def test_invalid_program_rejected_before_cache(rng):
    """The reject-before-fingerprint contract: a broken program must not
    trace, must not enter the executor cache, and must keep failing on
    retry (error reports are never memoized as 'validated')."""
    main, startup, loss = _build_clean()
    op = main.global_block().ops[-1]
    slot = next(iter(op.inputs))
    op.inputs[slot] = ["missing_var"]

    stats = pt.profiler.compile_stats()
    t0 = stats.counters["traces"]
    exe = pt.Executor(validate=True)
    with pytest.raises(ProgramVerificationError) as ei:
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    assert "PT001" in ei.value.report.codes()
    assert len(exe._cache) == 0
    assert stats.counters["traces"] - t0 == 0
    # still raises on the second attempt (not memoized as valid)
    with pytest.raises(ProgramVerificationError):
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])


def test_trainer_validate_kwarg(rng):
    from paddle_tpu.trainer import SGD
    x = layers.data("x", shape=[4], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    pred = layers.fc(x, size=3, act="softmax")
    loss = layers.mean(layers.cross_entropy(pred, label))
    tr = SGD(loss)
    assert tr.exe.validate is None
    stats = pt.profiler.compile_stats()
    v0 = stats.counters["validations"]
    batch = [[rng.rand(4).astype("float32"),
              rng.randint(0, 3, (1,)).astype("int64")] for _ in range(4)]
    tr.train(lambda: iter([batch, batch]), num_passes=1,
             feed_list=[x, label], validate=True)
    # startup + train step validated exactly once despite two batches
    assert stats.counters["validations"] - v0 == 2
    # the override is per-call: a later train() with the default None
    # defers to the flag again instead of inheriting True
    assert tr.exe.validate is None
    tr.train(lambda: iter([batch]), num_passes=1, feed_list=[x, label])
    assert stats.counters["validations"] - v0 == 2


# ---------------------------------------------------------------------------
# CLI: python -m paddle_tpu check
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_cli_check(tmp_path):
    # @slow: two `python -m paddle_tpu check` subprocesses (~8 s of jax
    # import on this container) against a tier-1 budget that is ~98%
    # full; the check pipeline itself (validate_program, report
    # rendering, PT0xx codes) stays tier-1-covered in-process throughout
    # this file, and cli.job_check's argument handling by the in-process
    # CLI tests.
    main, _, loss = _build_clean()
    ok = tmp_path / "prog_ok.json"
    ok.write_text(main.to_json())
    op = main.global_block().ops[-1]
    slot = next(iter(op.inputs))
    op.inputs[slot] = ["missing_var"]
    bad = tmp_path / "prog_bad.json"
    bad.write_text(main.to_json())

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "check", str(ok)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd="/root/repo")
    assert r.returncode == 0, r.stderr
    assert '"check": "PASS"' in r.stdout

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "check", str(bad)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd="/root/repo")
    assert r.returncode == 1, r.stderr
    assert "PT001" in r.stdout
    assert '"check": "FAIL"' in r.stdout

    # a zero/negative mesh size would silently skip the divisibility
    # lints and PASS — reject it up front
    from paddle_tpu.cli import _parse_mesh
    assert _parse_mesh("dp=8,tp=2") == {"dp": 8, "tp": 2}
    with pytest.raises(SystemExit):
        _parse_mesh("dp=0")
    with pytest.raises(SystemExit):
        _parse_mesh("dp=eight")
    with pytest.raises(SystemExit):
        _parse_mesh("dp=8,dp=2")

    # bad inputs get a one-line message, never a traceback
    notjson = tmp_path / "notes.txt"
    notjson.write_text("not a program")
    for target in [str(tmp_path / "nope.json"), str(notjson),
                   str(tmp_path)]:              # dir without __model__
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu", "check", target],
            capture_output=True, text=True, timeout=240, env=env,
            cwd="/root/repo")
        assert r.returncode != 0, target
        assert "Traceback" not in r.stderr, (target, r.stderr)
        assert "check:" in r.stderr, (target, r.stderr)


def test_sharded_executor_validates_against_mesh(rng):
    """The ShardedExecutor wires its mesh + spec overrides into the
    verifier: a param spec naming a non-mesh axis fails PT030 before any
    trace, via the same validate-before-fingerprint path."""
    from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh

    main, startup, loss = _build_clean()
    w = _find_param(main, ndim=2)
    mesh = make_mesh(MeshConfig(dp=8))
    exe = ShardedExecutor(mesh=mesh, validate=True,
                          param_specs={w.name: ("ghost_axis",)})
    # the param is declared in the startup program too, so the bad spec
    # is caught on the very first program that touches it
    with pytest.raises(ProgramVerificationError) as ei:
        exe.run(startup, feed={}, fetch_list=[])
    assert "PT030" in ei.value.report.codes()

    # with a real axis the same program runs clean
    exe_ok = ShardedExecutor(mesh=mesh, validate=True)
    exe_ok.run(startup, feed={}, fetch_list=[])
    exe_ok.run(main, feed=_feeds(rng), fetch_list=[loss])


def test_spec_mutation_invalidates_validation_memo(rng):
    """The validation memo folds the sharding context into its key: a spec
    override mutated AFTER a successful validation must re-run the
    sharding lints, not ride the stale (version, fetches) memo into GSPMD."""
    from paddle_tpu.parallel import MeshConfig, ShardedExecutor, make_mesh

    main, startup, loss = _build_clean()
    w = _find_param(main, ndim=2)
    exe = ShardedExecutor(mesh=make_mesh(MeshConfig(dp=8)), validate=True)
    exe.run(startup, feed={}, fetch_list=[])
    exe.run(main, feed=_feeds(rng), fetch_list=[loss])      # memoized clean
    exe.param_specs[w.name] = ("ghost_axis",)
    with pytest.raises(ProgramVerificationError) as ei:
        exe.run(main, feed=_feeds(rng), fetch_list=[loss])
    assert "PT030" in ei.value.report.codes()


def test_rule_crash_degrades_to_pt010():
    """A shape rule blowing up on malformed inputs (wrong rank unpack,
    missing attr) must surface as a PT010 diagnostic — never escape
    Program.validate() as the opaque exception the verifier exists to
    replace."""
    main, _, _ = _build_clean()
    b = main.global_block()
    # rank-3 Input makes _conv2d_transpose_shape's `n, c, h, wd = x.shape`
    # unpack fail (and ShapeError subclasses ValueError, so a crash here
    # is otherwise indistinguishable from a diagnostic to callers)
    b.create_var(name="im3", shape=(2, 3, 8), dtype="float32")
    b.create_var(name="k", shape=(3, 4, 3, 3), dtype="float32",
                 persistable=True)
    b.create_var(name="convt_out", shape=(-1, 4, -1, -1), dtype="float32")
    b.append_op(type="conv2d_transpose",
                inputs={"Input": ["im3"], "Filter": ["k"]},
                outputs={"Output": ["convt_out"]}, attrs={})
    rep = main.validate()       # must not raise
    # the malformed conv reports PT010; its never-produced inputs PT002
    assert "PT010" in rep.codes()
    assert all(c in ("PT010", "PT002") for c in rep.codes()), rep.render()


def test_validation_memo_survives_id_reuse(rng):
    """The validated-memo is keyed by live Program objects (weakly): a
    new program allocated at a dead program's address with the same
    version/fetches must still be validated — and rejected if invalid."""
    exe = pt.Executor(validate=True)
    ok_main, ok_startup, ok_loss = _build_clean()
    exe.run(ok_startup, feed={}, fetch_list=[])
    exe.run(ok_main, feed=_feeds(rng), fetch_list=[ok_loss])
    loss_name = ok_loss.name
    del ok_main, ok_startup, ok_loss            # free -> id() reusable
    import gc
    gc.collect()
    for _ in range(20):                         # give id reuse many shots
        pt.unique_name.reset()                  # reproduce the var names
        bad_main, _, bad_loss = _build_clean()
        assert bad_loss.name == loss_name       # same fetch signature
        op = bad_main.global_block().ops[-1]
        slot = next(iter(op.inputs))
        op.inputs[slot] = ["missing_var"]
        with pytest.raises(ProgramVerificationError):
            exe.run(bad_main, feed=_feeds(rng), fetch_list=[bad_loss])
        del bad_main, bad_loss


def test_dead_op_lint_sees_nested_sub_blocks():
    """Liveness flows through DOUBLY-nested sub-blocks: a global-block
    producer consumed only inside block 2 (a body within a body) must not
    be flagged PT020."""
    main = Program()
    b0 = main.global_block()
    b0.create_var(name="x", shape=(-1, 4), dtype="float32", is_data=True)
    b0.create_var(name="emb", shape=(-1, 4), dtype="float32")
    b0.create_var(name="out", shape=(-1, 4), dtype="float32")
    b1 = main.create_block(parent_idx=0)
    b2 = main.create_block(parent_idx=b1.idx)
    main.current_block_idx = 0
    # produced in block 0, read ONLY in block 2
    b0.append_op(type="scale", inputs={"X": ["x"]},
                 outputs={"Out": ["emb"]}, attrs={"scale": 1.0})
    b2.append_op(type="scale", inputs={"X": ["emb"]},
                 outputs={"Out": ["inner"]}, attrs={"scale": 1.0})
    b1.append_op(type="while", inputs={}, outputs={},
                 attrs={"sub_block": b2.idx})
    b0.append_op(type="while", inputs={"X": ["x"]},
                 outputs={"Out": ["out"]}, attrs={"sub_block": b1.idx})
    rep = main.validate(fetch_list=["out"])
    assert "PT020" not in rep.codes(), rep.render()
