"""Chaos suite: REAL training processes interrupted and resumed.

Everything here is subprocess-driven (each run pays a fresh jax import +
trace, ~15s apiece on this CPU container) and runs under
``@pytest.mark.slow``: tier-1 keeps the fast deterministic subset —
injection-driven, no real processes — in tests/test_fault_tolerance.py.
The rounds: a fully deterministic injected-preemption + supervisor
relaunch (merged event stream sha256-identical to an uninterrupted run),
a parent-timed real SIGTERM (emergency checkpoint + exit 75), and a
randomized-but-seeded SIGKILL matrix (resume from the last periodic
checkpoint with bit-identical replayed overlap).

Every subprocess call carries a hard ``timeout=`` (the per-test marker
is advisory when pytest-timeout is absent)."""
import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.faults import EXIT_PREEMPTED

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One deterministic training job: 24 batches, dropout (RNG stream must
# resume), Momentum (optimizer moments must resume), periodic saves.
# Every EndIteration appends one JSON line {"p", "b", "c"} (c = float
# hex, bit-exact) to the events file; resume=True is ALWAYS passed, so
# relaunching the identical command is the whole recovery story.
TRAIN_SCRIPT = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import layers

ckpt_dir, out_path = sys.argv[1], sys.argv[2]
x = layers.data("x", shape=[8], dtype="float32")
y = layers.data("y", shape=[1], dtype="int64")
h = layers.fc(x, size=16, act="relu")
h = layers.dropout(h, dropout_prob=0.3)
pred = layers.fc(h, size=3, act="softmax")
loss = layers.mean(layers.cross_entropy(pred, y))
tr = pt.trainer.SGD(cost=loss,
                    update_equation=pt.optimizer.Momentum(0.05, 0.9))

def reader():
    rng = np.random.RandomState(7)
    for _ in range(24):
        yield [(rng.rand(8).astype("float32"),
                rng.randint(0, 3, (1,))) for _ in range(4)]

out = open(out_path, "a", buffering=1)

def handler(e):
    if isinstance(e, pt.trainer.events.EndIteration):
        out.write(json.dumps(
            {{"p": e.pass_id, "b": e.batch_id,
              "c": float(e.cost).hex()}}) + "\\n")
        out.flush()

kw = {{}}
if ckpt_dir != "-":
    kw = dict(checkpoint_dir=ckpt_dir, resume=True, save_every_n_steps=4)
tr.train(reader, num_passes=1, event_handler=handler, **kw)
print("DONE", flush=True)
"""

RUN_TIMEOUT = 180          # hard cap per training subprocess


def _write_script(tmp_path):
    script = tmp_path / "train_job.py"
    script.write_text(TRAIN_SCRIPT.format(repo=REPO))
    return str(script)


def _env(extra=None):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.pop("PADDLE_TPU_METRICS_LOG", None)
    env["JAX_PLATFORMS"] = "cpu"
    if extra:
        env.update(extra)
    return env


def _events(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass      # torn final line from a SIGKILLed writer
    return out


def _baseline(tmp_path):
    script = _write_script(tmp_path)
    out = str(tmp_path / "baseline.jsonl")
    r = subprocess.run([sys.executable, script, "-", out],
                       capture_output=True, text=True, env=_env(),
                       timeout=RUN_TIMEOUT)
    assert r.returncode == 0, r.stderr[-2000:]
    ev = _events(out)
    assert len(ev) == 24
    return script, ev


def _merge_check(parts, baseline):
    """Assemble per-(pass,batch) events from the run parts; every key
    present in two parts must be BIT-IDENTICAL (the replayed overlap
    after a hard kill), and the union must equal the baseline exactly."""
    merged = {}
    for part in parts:
        for e in part:
            k = (e["p"], e["b"])
            if k in merged:
                assert merged[k] == e["c"], (
                    f"replayed batch {k} diverged: {merged[k]} vs {e['c']}")
            merged[k] = e["c"]
    want = {(e["p"], e["b"]): e["c"] for e in baseline}
    assert merged == want
    sha = hashlib.sha256(repr(sorted(merged.items())).encode()).hexdigest()
    want_sha = hashlib.sha256(repr(sorted(want.items())).encode()).hexdigest()
    assert sha == want_sha


@pytest.mark.timeout(600)
def test_injected_preemption_supervisor_relaunch_bit_identity(tmp_path):
    """Acceptance path, fully deterministic: training preempted at global
    batch 9 (fault spec) exits EXIT_PREEMPTED with an emergency
    checkpoint; distributed.Supervisor relaunches the SAME command, which
    resumes and completes; merged events == uninterrupted run."""
    from paddle_tpu.distributed import Supervisor

    script, baseline = _baseline(tmp_path)
    ckpt = str(tmp_path / "ckpt")
    out = str(tmp_path / "events.jsonl")

    sup = Supervisor(max_restarts=2, backoff_base_s=0.0, jitter=0.0,
                     sleep=lambda s: None)
    rc = sup.run_command(
        [sys.executable, script, ckpt, out], timeout=RUN_TIMEOUT,
        env=_env({"PADDLE_TPU_FAULT_SPEC": "trainer.step@9=preempt"}))
    assert rc == 0
    # exactly one relaunch: the preempted first attempt + the resumed one
    # (the resumed run starts past batch 9, so the index-matched spec
    # entry cannot re-fire)
    assert sup.restarts == 1
    ev = _events(out)
    assert len(ev) == 24            # 9 before preemption + 15 after
    _merge_check([ev], baseline)
    # no batch ran twice: the emergency checkpoint at batch 9 was the
    # exact handoff point (max_to_keep GC has since rotated it away)
    assert [e["b"] for e in ev] == list(range(24))


@pytest.mark.timeout(600)
def test_parent_sigterm_emergency_checkpoint_and_resume(tmp_path):
    """A REAL SIGTERM from outside at an arbitrary moment: the child
    finishes its in-flight step, commits an emergency checkpoint, exits
    EXIT_PREEMPTED; relaunching resumes to a bit-identical stream."""
    script, baseline = _baseline(tmp_path)
    ckpt = str(tmp_path / "ckpt")
    out = str(tmp_path / "events.jsonl")

    proc = subprocess.Popen([sys.executable, script, ckpt, out],
                            env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    # wait until it has demonstrably made progress, then pull the plug
    deadline = time.time() + RUN_TIMEOUT
    while len(_events(out)) < 5 and time.time() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=RUN_TIMEOUT)
    stderr = proc.stderr.read()
    if rc == 0:
        # the run raced to completion before the signal landed — nothing
        # left to resume; the invariant below still must hold
        pass
    else:
        assert rc == EXIT_PREEMPTED, f"exit {rc}; stderr: {stderr[-2000:]}"
    part1 = _events(out)

    r2 = subprocess.run([sys.executable, script, ckpt, out],
                        capture_output=True, text=True, env=_env(),
                        timeout=RUN_TIMEOUT)
    assert r2.returncode == 0, r2.stderr[-2000:]
    _merge_check([part1, _events(out)[len(part1):]], baseline)


@pytest.mark.timeout(600)
def test_kill_matrix_sigkill_resumes_from_periodic_checkpoint(tmp_path):
    """SIGKILL (no handler, no emergency checkpoint — the hard-preemption
    case) at a randomized-but-seeded moment: resume replays from the last
    periodic checkpoint; replayed batches must be bit-identical and the
    merged stream must equal the baseline."""
    import random
    script, baseline = _baseline(tmp_path)
    rng = random.Random(1234)
    for round_i in range(2):
        ckpt = str(tmp_path / f"ckpt_k{round_i}")
        out = str(tmp_path / f"events_k{round_i}.jsonl")
        wait_batches = rng.randint(3, 12)
        proc = subprocess.Popen([sys.executable, script, ckpt, out],
                                env=_env(), stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        deadline = time.time() + RUN_TIMEOUT
        while len(_events(out)) < wait_batches and time.time() < deadline:
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if proc.poll() is None:
            proc.kill()                        # SIGKILL: no cleanup at all
        proc.wait(timeout=RUN_TIMEOUT)
        part1 = _events(out)

        # relaunch until done (a supervisor would; one resume suffices
        # here since nothing kills the second run)
        r2 = subprocess.run([sys.executable, script, ckpt, out],
                            capture_output=True, text=True, env=_env(),
                            timeout=RUN_TIMEOUT)
        assert r2.returncode == 0, r2.stderr[-2000:]
        _merge_check([part1, _events(out)[len(part1):]], baseline)
