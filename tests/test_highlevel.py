"""High-level API tests: v2-style trainer/events/infer, datasets, reader
decorators, DataFeeder, task-queue master, checkpoint manager
(reference: v2 trainer/event protocol, v2/reader tests, go master/pserver
service tests — all run in-process, SURVEY §4)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers


def test_trainer_sgd_events_and_infer(rng):
    img = layers.data("img", shape=[784], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    pred = pt.models.mnist_mlp(img, hidden_sizes=(32,))
    cost = layers.mean(layers.cross_entropy(pred, label))
    acc = layers.accuracy(pred, label)

    trainer = pt.trainer.SGD(cost=cost,
                             update_equation=pt.optimizer.Adam(0.01),
                             extra_layers=[acc])
    seen = {"begin_pass": 0, "end_pass": 0, "iters": 0, "costs": []}

    def handler(e):
        if isinstance(e, pt.trainer.events.BeginPass):
            seen["begin_pass"] += 1
        elif isinstance(e, pt.trainer.events.EndPass):
            seen["end_pass"] += 1
        elif isinstance(e, pt.trainer.events.EndIteration):
            seen["iters"] += 1
            seen["costs"].append(e.cost)
            assert e.metrics

    train_reader = pt.reader.batch(
        pt.reader.shuffle(pt.dataset.mnist.train(), buf_size=500),
        batch_size=32)
    trainer.train(train_reader, num_passes=2, event_handler=handler,
                  feed_list=[img, label])
    assert seen["begin_pass"] == 2 and seen["end_pass"] == 2
    assert seen["iters"] == 2 * (pt.dataset.mnist.TRAIN_N // 32)
    assert np.mean(seen["costs"][-20:]) < np.mean(seen["costs"][:20])

    # test() pass
    test_cost = trainer.test(pt.reader.batch(pt.dataset.mnist.test(), 50),
                             feed_list=[img, label])
    assert np.isfinite(test_cost[0])

    # v2-style infer
    batch = [row for _, row in zip(range(8), pt.dataset.mnist.test()())]
    probs = pt.infer(pred, input=[(x,) for x, _ in batch],
                     feed_list=[img], executor=trainer.exe)
    assert probs.shape == (8, 10)
    labels = np.array([y for _, y in batch])
    assert (np.argmax(probs, 1) == labels).mean() > 0.5


def test_reader_decorators():
    r = pt.reader.batch(lambda: iter(range(10)), batch_size=3,
                        drop_last=False)
    batches = list(r())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    r2 = pt.reader.firstn(lambda: iter(range(100)), 5)
    assert list(r2()) == [0, 1, 2, 3, 4]
    r3 = pt.reader.chain(lambda: iter([1]), lambda: iter([2]))
    assert list(r3()) == [1, 2]
    r4 = pt.reader.map_readers(lambda a, b: a + b,
                               lambda: iter([1, 2]), lambda: iter([10, 20]))
    assert list(r4()) == [11, 22]
    r5 = pt.reader.buffered(lambda: iter(range(5)), 2)
    assert list(r5()) == [0, 1, 2, 3, 4]
    shuffled = list(pt.reader.shuffle(lambda: iter(range(20)), 10)())
    assert sorted(shuffled) == list(range(20))


def test_data_feeder_sequences():
    main = pt.Program()
    with pt.program_guard(main, pt.Program()):
        words = layers.data("w", shape=[], dtype="int64", lod_level=1)
        label = layers.data("y", shape=[1], dtype="int64")
    feeder = pt.DataFeeder([words, label], seq_bucket_multiple=4)
    feed = feeder.feed([([1, 2, 3], 0), ([4, 5], 1), ([6, 7, 8, 9, 10], 1)])
    assert feed["w"].shape == (3, 8)            # bucketed to multiple of 4
    np.testing.assert_array_equal(feed["w@LEN"], [3, 2, 5])
    np.testing.assert_array_equal(feed["w"][1, :2], [4, 5])
    assert feed["w"][1, 2:].sum() == 0
    assert feed["y"].shape == (3, 1)


def test_master_task_queue_lifecycle():
    from paddle_tpu.distributed import Master
    m = Master(chunks_per_task=2, timeout_s=60, failure_max=2,
               num_epochs=2)
    m.set_dataset(list(range(10)))              # 5 tasks
    t1 = m.get_task()
    t2 = m.get_task()
    assert t1.task_id != t2.task_id
    m.task_finished(t1.task_id)
    m.task_failed(t2.task_id)                   # requeued (budget 2)
    ids = set()
    while True:
        t = m.get_task()
        if t is None or t.epoch > 0:
            break
        ids.add(t.task_id)
        m.task_finished(t.task_id)
    assert t2.task_id in ids                    # failed task came back
    # second pass recycled (num_epochs=2); a third is not handed out
    assert t is not None and t.epoch == 1


def test_master_timeout_requeue():
    from paddle_tpu.distributed import Master
    m = Master(chunks_per_task=1, timeout_s=0.0, failure_max=3)
    m.set_dataset([1, 2])
    t = m.get_task()
    # deadline is already past: the next get_task must hand it back
    seen = {m.get_task().task_id, m.get_task().task_id}
    assert t.task_id in seen


def test_master_client_reader():
    from paddle_tpu.distributed import Master, TaskQueueClient
    m = Master(chunks_per_task=2)
    m.set_dataset([0, 1, 2, 3, 4])
    cli = TaskQueueClient(m, lambda chunk: iter([chunk * 10]))
    got = sorted(list(cli.reader()()))
    assert got == [0, 10, 20, 30, 40]


def test_checkpoint_roundtrip_and_corruption(tmp_path):
    from paddle_tpu.distributed import CheckpointManager
    import jax.numpy as jnp
    scope = pt.Scope()
    scope.set("w", jnp.arange(6.0).reshape(2, 3))
    scope.set("m", jnp.ones((3,)))
    cm = CheckpointManager(str(tmp_path), max_to_keep=2, async_save=False)
    cm.save(1, scope)
    scope.set("w", jnp.zeros((2, 3)))
    cm.save(2, scope)
    cm.save(3, scope)
    assert cm.all_steps() == [2, 3]             # gc kept last 2

    # corrupt newest -> restore falls back to previous (pserver recovery)
    import glob
    (wfile,) = glob.glob(os.path.join(str(tmp_path), "ckpt-3", "w.*.npy"))
    with open(wfile, "wb") as f:
        f.write(b"garbage")
    fresh = pt.Scope()
    step = cm.restore(scope=fresh)
    assert step == 2
    np.testing.assert_allclose(np.asarray(fresh.get("w")),
                               np.zeros((2, 3)))


def test_checkpoint_async(tmp_path):
    from paddle_tpu.distributed import CheckpointManager
    import jax.numpy as jnp
    scope = pt.Scope()
    scope.set("w", jnp.ones((4,)))
    cm = CheckpointManager(str(tmp_path), async_save=True)
    cm.save(7, scope)
    cm.wait()
    assert cm.latest_step() == 7


def test_datasets_protocol():
    for mod, nfields in [(pt.dataset.uci_housing, 2),
                         (pt.dataset.movielens, 3),
                         (pt.dataset.imdb, 2),
                         (pt.dataset.conll05, 2)]:
        row = next(mod.train()())
        assert len(row) == nfields
    x, y = next(pt.dataset.cifar.train10()())
    assert x.shape == (3, 32, 32) and 0 <= y < 10
    gram = next(pt.dataset.imikolov.train()())
    assert len(gram) == 5


def test_trainer_with_uci_housing(rng):
    """The fit_a_line demo end-to-end through the v2 surface."""
    x = layers.data("x", shape=[13], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    cost = layers.mean(layers.square_error_cost(pred, y))
    trainer = pt.trainer.SGD(cost=cost,
                             update_equation=pt.optimizer.Adam(0.3))
    costs = []
    trainer.train(pt.reader.batch(pt.dataset.uci_housing.train(), 32),
                  num_passes=8,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, pt.trainer.events.EndIteration) else None,
                  feed_list=[x, y])
    assert costs[-1] < costs[0] * 0.1


def test_v2_master_client_and_topology(tmp_path):
    """v2 master.client consumes dataset chunks over the TCP master; v2
    Topology serializes the network (reference: v2/master/client.py,
    v2/topology.py)."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.distributed.master import Master, MasterServer

    m = Master(chunks_per_task=1, timeout_s=30.0)
    m.set_dataset([["r1", "r2"], ["r3"]])
    srv = MasterServer(m).start()
    try:
        c = paddle.master.client(srv.address)
        got = sorted(r for r in c.next_record()
                     if not isinstance(r, (bytes,)))
        assert got == ["r1", "r2", "r3"]
        c.close()
    finally:
        srv.stop()

    images = paddle.layer.data(name="px", size=16)
    out = paddle.layer.fc(input=images, size=4,
                          act=paddle.activation.Softmax())
    topo = paddle.topology.Topology(out)
    blob = topo.serialize()
    assert "px" in blob and topo.get_layer("px") is not None
    assert "px" in topo.data_layers()


def test_v2_ploter(tmp_path):
    import paddle_tpu.v2 as paddle
    p = paddle.plot.Ploter("train", "test")
    for i in range(5):
        p.append("train", i, 1.0 / (i + 1))
        p.append("test", i, 1.2 / (i + 1))
    out = tmp_path / "cost.png"
    p.plot(str(out))
    assert out.exists() and out.stat().st_size > 0
    p.reset()
    assert p.data["train"] == ([], [])


def test_trainer_steps_per_dispatch_matches_per_batch(rng):
    """steps_per_dispatch=4 (stacked run_steps chunks) reproduces the
    per-batch training trajectory and still fires per-batch events;
    shape-changing batches fall back cleanly."""
    def build():
        pt.core.reset_default_programs()
        pt.core.reset_global_scope()
        pt.unique_name.reset()
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1, name="tw")
        cost = layers.mean(layers.square_error_cost(pred, y))
        return x, y, cost

    w_true = rng.rand(8, 1).astype("float32")
    rows = []
    for _ in range(10):
        xb = rng.rand(16, 8).astype("float32")
        rows.append([(xb[i], xb[i] @ w_true) for i in range(16)])

    def reader():
        yield from rows

    def run(k):
        x, y, cost = build()
        tr = pt.trainer.SGD(cost=cost,
                            update_equation=pt.optimizer.SGD(0.2))
        costs = []
        tr.train(reader, num_passes=2, feed_list=[x, y],
                 steps_per_dispatch=k,
                 event_handler=lambda e: costs.append(e.cost)
                 if isinstance(e, pt.trainer.events.EndIteration) else None)
        return costs, np.asarray(pt.global_scope().get("tw.w_0")).copy()

    c1, w1 = run(1)
    c4, w4 = run(4)          # 10 batches/pass -> chunks of 4,4,2
    assert len(c1) == len(c4) == 20
    np.testing.assert_allclose(c4, c1, rtol=2e-2, atol=1e-6)
    np.testing.assert_allclose(w4, w1, rtol=2e-2, atol=1e-6)

    # bucketed shapes: alternate batch sizes force per-run chunking
    def bucketed():
        for i, r in enumerate(rows):
            yield r[:8] if i % 2 else r

    x, y, cost = build()
    tr = pt.trainer.SGD(cost=cost, update_equation=pt.optimizer.SGD(0.2))
    n = {"iters": 0}
    tr.train(bucketed, num_passes=1, feed_list=[x, y],
             steps_per_dispatch=4,
             event_handler=lambda e: n.__setitem__("iters", n["iters"] + 1)
             if isinstance(e, pt.trainer.events.EndIteration) else None)
    assert n["iters"] == 10


def test_v2_full_namespace_and_data_type_idiom(rng):
    """The auto-generated v2 facade: every DSL *_layer appears suffix-
    stripped in paddle.layer, data_type InputTypes retype data layers, and
    the classic v2 script shape (data_type + pooling_type + event loop)
    trains (reference: python/paddle/v2/layer.py auto-generation +
    data_type.py)."""
    import paddle_tpu.v2 as paddle

    # surface: the suffix-stripped names exist for the full DSL
    import paddle_tpu.trainer_config_helpers as tch
    for n in tch.__all__:
        if n.endswith("_layer"):
            assert hasattr(paddle.layer, n[:-6]), n
    for ns, names in [(paddle.activation, ["Relu", "Softmax", "Linear"]),
                      (paddle.pooling, ["Max", "Avg", "Sum"]),
                      (paddle.attr, ["Param", "Extra"]),
                      (paddle.evaluator, ["classification_error"]),
                      (paddle.networks, ["vgg_16_network",
                                         "bidirectional_gru"])]:
        for n in names:
            assert hasattr(ns, n), n

    # data_type idiom end-to-end
    words = paddle.layer.data(
        name="w2", type=paddle.data_type.integer_value_sequence(100))
    lab = paddle.layer.data(name="l2",
                            type=paddle.data_type.integer_value(2))
    assert words.dtype == np.dtype("int64") and words.lod_level == 1
    emb = paddle.layer.embedding(input=words, size=16)
    pooled = paddle.layer.pooling(input=emb,
                                  pooling_type=paddle.pooling.Max())
    out = paddle.layer.fc(input=pooled, size=2,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=lab)
    tr = paddle.trainer.SGD(
        cost=cost,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))

    def reader():
        for _ in range(48):
            toks = rng.randint(2, 100, rng.randint(3, 9)).tolist()
            yield toks, toks[0] % 2

    costs = []
    tr.train(paddle.batch(reader, 16), num_passes=3,
             event_handler=lambda e: costs.append(e.cost)
             if isinstance(e, paddle.event.EndIteration) else None,
             feed_list=[words, lab])
    assert costs[-1] < costs[0]


def test_v2_data_type_forms(rng):
    """layer.data accepts the v1 positional form, dense sequences get
    lod+shape, sparse types raise with guidance, wrong types raise
    TypeError (review findings)."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.data_feeder import DataFeeder

    v = paddle.layer.data("pixel9", 784)
    assert v.shape == (-1, 784)
    ds = paddle.layer.data(name="ds9",
                           type=paddle.data_type.dense_vector_sequence(4))
    assert ds.lod_level == 1 and ds.shape == (-1, -1, 4)
    rows = [([np.ones(4), np.zeros(4)],), ([np.ones(4)] * 3,)]
    feed = DataFeeder([ds]).feed(rows)
    a = np.asarray(feed["ds9"])
    assert a.shape[0] == 2 and a.shape[1] >= 3 and a.shape[2] == 4
    assert np.asarray(feed["ds9@LEN"]).tolist() == [2, 3]
    with pytest.raises(NotImplementedError):
        paddle.layer.data(name="sb9",
                          type=paddle.data_type.sparse_binary_vector(9))
    with pytest.raises(TypeError):
        paddle.layer.data("x9", 7, 3)


def test_v2_op_and_inference_namespaces(rng):
    """paddle.op unary math over layers and the Inference class
    (reference v2/op.py, v2/inference.py)."""
    import paddle_tpu.v2 as paddle

    x = paddle.layer.data(name="xi2", type=paddle.data_type.dense_vector(8))
    h = paddle.layer.fc(input=x, size=4, act=paddle.activation.Relu())
    y = paddle.op.sqrt(paddle.op.square(h))
    out = paddle.layer.fc(input=y, size=2, act=paddle.activation.Softmax())
    exe = pt.Executor()
    exe.run(pt.default_startup_program(), feed={}, fetch_list=[])
    # parse_network: the api_train.py idiom for the model config
    cfg_prog = paddle.layer.parse_network(out)
    assert cfg_prog.global_block().ops and cfg_prog.to_dict()["blocks"]
    inf = paddle.inference.Inference(output_layer=out)
    res = inf.infer(input=[(rng.rand(8).astype("float32"),)],
                    feed_list=[x])
    a = np.asarray(res)
    assert a.shape == (1, 2) and np.allclose(a.sum(), 1.0, atol=1e-5)
    # field='id' returns argmax ids (reference inference.py semantics)
    ids = inf.infer(input=[(rng.rand(8).astype("float32"),)],
                    feed_list=[x], field="id")
    assert ids.shape == (1,) and ids[0] in (0, 1)
    with pytest.raises(ValueError):
        inf.infer(input=[(rng.rand(8).astype("float32"),)],
                  feed_list=[x], field="prob")
