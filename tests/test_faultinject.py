"""Deterministic fault-injection harness (paddle_tpu.testing.faultinject):
spec grammar, index- vs hit-count matching, counters, and the
zero-overhead off state."""
import pytest

from paddle_tpu.faults import (InjectedFault, TransientDispatchError)
from paddle_tpu.testing import faultinject as fi


@pytest.fixture(autouse=True)
def _clean_spec():
    fi.clear()
    yield
    fi.clear()


def test_off_by_default():
    assert fi.ENABLED is False
    assert fi.active_spec() == ""


def test_configure_and_clear():
    fi.configure("executor.dispatch@3=transient")
    assert fi.ENABLED
    assert fi.active_spec() == "executor.dispatch@3=transient"
    fi.clear()
    assert not fi.ENABLED
    fi.configure("")          # empty spec == clear
    assert not fi.ENABLED


def test_spec_parse_errors():
    for bad in ("dispatch", "dispatch=x", "@3=x", "dispatch@x=boom",
                "dispatch@3"):
        with pytest.raises(ValueError):
            fi.configure(bad)


def test_hit_count_matching():
    """Sites without a natural index match on their 1-based hit count."""
    fi.configure("master.call@2=drop")
    assert fi.check("master.call") is None          # hit 1
    assert fi.check("master.call") == "drop"        # hit 2
    assert fi.check("master.call") is None          # hit 3
    assert fi.hits("master.call") == 3
    assert fi.fired("master.call") == 1


def test_index_matching_survives_restart_semantics():
    """Index-matched sites key on the caller's position, not process hit
    count — a resumed run starting past N must NOT re-fire N's entry."""
    fi.configure("trainer.step@5=preempt")
    # "resumed" process: first observed indexes are 6, 7, ...
    assert fi.check("trainer.step", index=6) is None
    assert fi.check("trainer.step", index=7) is None
    assert fi.fired("trainer.step") == 0
    # the original run would have fired exactly at 5
    assert fi.check("trainer.step", index=5) == "preempt"


def test_star_fires_every_hit():
    fi.configure("reader.item@*=error")
    for i in range(3):
        assert fi.check("reader.item", index=i + 1) == "error"
    assert fi.fired("reader.item") == 3


def test_multiple_entries_and_sites():
    fi.configure("reader.item@2=error;executor.dispatch@1=transient")
    assert fi.check("reader.item", index=1) is None
    assert fi.check("executor.dispatch") == "transient"
    assert fi.check("reader.item", index=2) == "error"


def test_raise_for_mapping():
    with pytest.raises(InjectedFault):
        fi.raise_for("error", "reader.item", 3)
    with pytest.raises(TransientDispatchError):
        fi.raise_for("transient", "executor.dispatch")
    with pytest.raises(ConnectionError):
        fi.raise_for("drop", "master.call")
    # call sites handle their own site-specific actions BEFORE routing
    # here; anything unrecognized (typo, wrong site) fails loudly rather
    # than counting as fired while doing nothing
    with pytest.raises(ValueError, match="not understood"):
        fi.raise_for("premept", "trainer.step")       # typo'd action
    with pytest.raises(ValueError, match="not understood"):
        fi.raise_for("preempt", "executor.dispatch")  # wrong site


def test_configure_resets_counters():
    fi.configure("master.call@1=drop")
    assert fi.check("master.call") == "drop"
    fi.configure("master.call@1=drop")
    assert fi.hits("master.call") == 0
    assert fi.fired("master.call") == 0
    assert fi.check("master.call") == "drop"   # counts restarted


def test_firing_counts_metric_and_emits_event(tmp_path):
    from paddle_tpu import flags
    from paddle_tpu.observability import registry, summarize_log
    from paddle_tpu.observability.export import _reset_writer

    log = tmp_path / "faults.jsonl"
    old = flags.get_flag("metrics_log")
    flags.set_flag("metrics_log", str(log))
    try:
        before = registry().snapshot()["fault/injected"]["value"]
        fi.configure("reader.item@1=error")
        assert fi.check("reader.item", index=1) == "error"
        after = registry().snapshot()["fault/injected"]["value"]
        assert after - before == 1
        _reset_writer()
        summary = summarize_log(str(log))
        assert summary["faults"]["events"] == 1
        assert summary["faults"]["by_event"] == {"injected": 1}
        assert summary["faults"]["timeline"][0]["site"] == "reader.item"
    finally:
        flags.set_flag("metrics_log", old)
        _reset_writer()


@pytest.mark.slow
def test_env_spec_activates_in_subprocess(tmp_path):
    # @slow: fresh-interpreter paddle_tpu import (~12 s on this
    # container, PR 6/8 convention); the spec parsing/arming logic is
    # tier-1-covered in-process (configure() tests above) — only the
    # PADDLE_TPU_FAULT_SPEC env activation needs the subprocess.
    import subprocess
    import sys
    code = ("from paddle_tpu.testing import faultinject as fi;"
            "assert fi.ENABLED;"
            "assert fi.check('reader.item', index=4) == 'error';"
            "print('armed')")
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, env={"PATH": "/usr/bin:/bin",
                         "PYTHONPATH": "/root/repo",
                         "PADDLE_TPU_FAULT_SPEC": "reader.item@4=error"})
    assert r.returncode == 0, r.stderr
    assert "armed" in r.stdout
